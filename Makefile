# Build orchestration (reference parity: `justfile` recipes).

.PHONY: all native test test-slow test-faults test-farm test-farm-proc test-gateway fixtures bench bench-fast bench-multichip bench-serve bench-quotient bench-quotient-multichip setup-committee setup-step lint lint-fast lint-deep tpu-evidence report-ci

all: native

native:
	$(MAKE) -C spectre_tpu/native

test: native lint lint-deep test-faults test-farm test-farm-proc test-gateway bench-fast
	python -m pytest tests/ -q

# fault-injection tier (PR 3, grown in PR 6): deterministic resilience
# suite — beacon retry/backoff + circuit breaker, device-prove -> CPU
# fallback byte-equality, job-journal crash replay, MSM table-budget
# degrade, admission-control shed/recover, stalled-worker replacement
# (injectable clock keeps it seconds-scale), artifact-store quarantine,
# SRS checksum refusal, overload RPC contract (429/-32001/Retry-After),
# and the observability tier (PR 7): /metrics exposition parity,
# getTrace span trees, peak-RSS attribution, broken-metrics-sink
# tolerance. PR 8 adds the provenance-manifest tier (test_manifest.py):
# end-to-end manifest pins, compile telemetry, queue-wait parity,
# manifest.write fault tolerance, crash-replay without a manifest.
# PR 9 adds the output-integrity tier (test_integrity.py): verify-
# before-serve SDC matrix, artifact scrubber, readiness self-check,
# diskfull fault kind. PR 10 adds the follower tier (test_follower.py):
# unbroken update chain across period boundaries, kill-mid-prove
# byte-identical replay, cache-hit-never-touches-prover, beacon-outage
# degrade/recover, corrupt-stored-update quarantine + re-prove.
# PR 14 adds the gateway tier (test_gateway.py): pack corruption
# quarantine -> rebuild, gateway.pack_write ioerror, torn pack-journal
# tail, and the fault-scheduled 10^4-client acceptance drill.
# Also part of the full pytest ladder above.
test-faults: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py tests/test_service.py tests/test_observability.py tests/test_manifest.py tests/test_integrity.py tests/test_follower.py tests/test_farm.py tests/test_gateway.py -q

# proof-farm failover matrix (PR 11, tests/test_farm.py): replica crash
# mid-prove -> lease takeover with a byte-identical proof, breaker-open
# replica receives no work, SDC re-prove on a DIFFERENT replica
# (cross-host verification), dispatcher restart replays leases without
# double-proving, beacon quorum ignores a lone dissenting head, and the
# UpdateStore 10k-period RSS bound.
test-farm: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_farm.py -q

# real-process failover drill (PR 18, tests/test_farm_proc.py): three
# actual serve() subprocesses announce themselves to an empty dispatcher
# head, one is SIGKILLed mid-prove -> exactly one lease takeover, a
# byte-identical final proof, and TTL deregistration of the corpse; plus
# lease-journal replay across a killed dispatcher PROCESS. Skips cleanly
# where fork+HTTP is unavailable; the `timeout` wrapper is the hard
# wall-clock budget (subprocesses each pay a jax import).
test-farm-proc: native
	timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/test_farm_proc.py -q

# light-client serving gateway (PR 14, tests/test_gateway.py): HTTP
# cache semantics (digest ETags stable across restarts, 304s, immutable
# only below tip), pack byte-identity vs direct UpdateStore reads, pack
# survival across restart + scrubber, and the follower -> loadgen
# end-to-end drill with the fault schedule armed.
test-gateway: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_gateway.py -q

test-slow: native
	RUN_SLOW=1 python -m pytest tests/ -q

fixtures:
	python -c "from spectre_tpu.test_utils import generate_fixtures; \
	from spectre_tpu import spec; generate_fixtures(spec.TINY); \
	generate_fixtures(spec.MINIMAL)"

setup-committee:
	python -m spectre_tpu.prover_service.cli --spec tiny circuit committee-update setup --k 17

setup-step:
	python -m spectre_tpu.prover_service.cli --spec tiny circuit sync-step setup --k 17

bench: native
	python bench.py

# CI perf tier: seconds-scale 2^12 MSM on pinned CPU (no device probing),
# gated against the checked-in floor in bench_floor.json — fails on a >20%
# throughput regression so `make test` surfaces perf rot without the 2^16 run
bench-fast: native
	python bench.py --fast

# multi-chip gate (PR 13): 8 simulated devices (XLA host-platform flag),
# sharded MSM + NTT micro-floors AND a complete byte-checked k=13 mesh
# prove, all under one hard wall-clock budget (BENCH_MULTICHIP_TIMEOUT,
# default 2700s) — the regression gate for the historical rc=124 where
# per-call shard_map re-jitting made the 8-device prove never finish.
# Knobs: SPECTRE_BENCH_DEVICES (8), SPECTRE_MESH_SHAPE, BENCH_MULTICHIP_K.
bench-multichip: native
	BENCH_METRIC=multichip python bench.py --fast

# quotient tier (ISSUE 19): the quotient phase timed with PRODUCTION
# inputs (a real prove runs with the host quotient hooked), every timed
# run byte-checked against the host h coefficients. bench-quotient gates
# k=11 single-device against bench_floor.json (and rides `make bench-fast`
# via BENCH_METRIC=all); the multichip variant runs the k=13 quotient
# SHARDED on 8 simulated devices — any quotient_sharded_degraded tick is
# a hard error. Knobs: BENCH_QUOTIENT_K(S), BENCH_QUOTIENT_TIMEOUT,
# SPECTRE_BENCH_DEVICES (8), SPECTRE_MESH_SHAPE.
bench-quotient: native
	BENCH_METRIC=quotient python bench.py --fast

bench-quotient-multichip: native
	BENCH_METRIC=quotient_multichip python bench.py --fast

# gateway read-plane tier (PR 14): 10^4-client in-process Zipf drill over
# a synthetic sealed store — requests/s gated against bench_floor.json,
# zero sealed-period store fallbacks asserted unconditionally. Knobs:
# BENCH_SERVE_CLIENTS (10000), BENCH_SERVE_REQUESTS, BENCH_SERVE_PERIODS.
bench-serve: native
	JAX_PLATFORMS=cpu BENCH_METRIC=serve python bench.py --fast

# manifest CI gate (PR 10): diff a candidate provenance manifest against
# a baseline and exit 3 on a prove_s regression (> 10% by default) or any
# new backend compile. Point the vars at manifest files or job ids:
#   make report-ci BASELINE_MANIFEST=base.manifest.json CANDIDATE_MANIFEST=cand.manifest.json
BASELINE_MANIFEST ?= baseline.manifest.json
CANDIDATE_MANIFEST ?= candidate.manifest.json
report-ci:
	JAX_PLATFORMS=cpu python -m spectre_tpu.observability report $(BASELINE_MANIFEST) --diff $(CANDIDATE_MANIFEST) --ci

# the full hardware-evidence suite, ordered cheap->expensive, every stage
# deadline-guarded; safe (and labeled) under CPU-JAX when the tunnel is
# wedged. Run the MOMENT a TPU probe succeeds.
tpu-evidence: native
	python scripts/tpu_evidence.py

# static analysis: compile check + the soundness auditor / kernel lint /
# trace-lint AST scan (spectre_tpu/analysis). Fails on any non-baselined
# error finding; accepted findings live in spectre_tpu/analysis/baseline.json
# (see README). --no-probes: the dynamic retrace probes are the lint-deep
# tier below, so `make test` (which runs both) compiles them only once.
lint:
	python -m compileall -q spectre_tpu tests bench.py __graft_entry__.py
	JAX_PLATFORMS=cpu python -m spectre_tpu.analysis --fail-on error --no-probes

# kernel-lint only (seconds; the full `lint` builds three tiny circuits)
lint-fast:
	JAX_PLATFORMS=cpu python -m spectre_tpu.analysis --engine kernel --fail-on error

# deep tier: trace-cache hygiene — static AST scan of jit/shard_map/
# pallas_call sites vs the declared runner registry (TC-FRESH-JIT,
# TC-CONST-CAPTURE, TC-UNSTABLE-STATIC, TC-UNCACHED-RUNNER) plus dynamic
# double-call probes over every runner family asserting zero recompiles on
# the second call (TC-RETRACE-DYN — the historical rc=124 class). Budgeted
# under 120s on a 1-core CPU host (tests/test_analysis.py pins it).
lint-deep:
	JAX_PLATFORMS=cpu python -m spectre_tpu.analysis --engine trace --fail-on error

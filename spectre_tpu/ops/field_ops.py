"""Vectorized Montgomery arithmetic on 16-bit limb tensors (JAX).

The device-side equivalent of the reference's `halo2curves` field arithmetic
(SURVEY.md §2b N1), designed for the TPU VPU: all values are [..., 16] uint32
tensors of 16-bit limbs; multiplication is 16 unrolled CIOS rounds, each a
fully vectorized multiply-accumulate over the batch; no 64-bit integers
anywhere. Montgomery radix R = 2^256 (matches the native C++ lib, so host <->
device form conversion is pure layout change).

Magnitude analysis (why uint32 never overflows): each CIOS round adds at most
~2^18 per accumulator column; over 16 rounds plus shifted carries the
accumulators stay < 2^24.

Works identically under `jit` on TPU and CPU backends; tests compare against
the C++/Python oracle on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import bn254
from . import limbs as L

NLIMBS = 16
MASK = np.uint32(0xFFFF)


class FieldCtx:
    """Per-modulus constant set, device-resident after first use."""

    def __init__(self, p: int, name: str):
        self.p = p
        self.name = name
        # constants kept as NUMPY so they lift to fresh embedded constants in
        # every trace (a cached jnp array created inside a jit trace is a
        # leaked tracer — learned the hard way)
        self.p_limbs = L.int_to_limbs16(p)
        self.n0inv16 = np.uint32((-pow(p, -1, 1 << 16)) % (1 << 16))
        r = (1 << 256) % p
        self.r_mod_p = r
        self.r2 = L.int_to_limbs16((r * r) % p)
        self.one_mont = L.int_to_limbs16(r)
        self.zero = np.zeros(NLIMBS, dtype=np.uint32)

    # -- host-side encode/decode (pure numpy/ints: safe to call anywhere,
    #    including from inside cached constant builders used under jit) --
    def encode_np(self, vals) -> np.ndarray:
        """Python ints -> Montgomery limb array [n, 16] (host computation)."""
        r = self.r_mod_p
        return L.ints_to_limbs16([(int(v) % self.p) * r % self.p for v in vals])

    def encode(self, vals) -> np.ndarray:
        """Alias of encode_np — numpy out, so results are safe to cache."""
        return self.encode_np(vals)

    def decode(self, arr) -> list[int]:
        """Montgomery limb tensor/array -> Python ints (host computation)."""
        rinv = pow(self.r_mod_p, -1, self.p)
        return [v * rinv % self.p for v in L.limbs16_to_ints(np.asarray(arr))]


@functools.cache
def fr_ctx() -> FieldCtx:
    return FieldCtx(bn254.R, "bn254_fr")


@functools.cache
def fq_ctx() -> FieldCtx:
    return FieldCtx(bn254.P, "bn254_fq")


# ---------------------------------------------------------------------------
# core arithmetic (all shapes [..., 16] uint32)
# ---------------------------------------------------------------------------

def _carry_propagate(t):
    """Full carry propagation of a [..., k] uint32 accumulator tensor, little-
    endian 16-bit limbs. Returns same-shape tensor with entries < 2^16 except
    possibly the top. lax.scan keeps the traced graph to O(1) ops regardless
    of limb count (unrolled carry chains dominate XLA compile time otherwise)."""
    tT = jnp.moveaxis(t, -1, 0)

    def step(carry, ti):
        cur = ti + carry
        return cur >> 16, cur & MASK

    carry, outs = jax.lax.scan(step, jnp.zeros_like(tT[0]), tT)
    return jnp.moveaxis(outs, 0, -1), carry


def _sub_limbs(a, b):
    """a - b with borrow chain; returns (diff limbs, final borrow 0/1)."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    aT = jnp.moveaxis(jnp.broadcast_to(a, shape), -1, 0)
    bT = jnp.moveaxis(jnp.broadcast_to(b, shape), -1, 0)

    def step(borrow, ab):
        ai, bi = ab
        cur = ai - bi - borrow  # uint32 wraps
        return (cur >> 16) & np.uint32(1), cur & MASK  # wrap iff borrow

    borrow, outs = jax.lax.scan(step, jnp.zeros_like(aT[0]), (aT, bT))
    return jnp.moveaxis(outs, 0, -1), borrow


def _cond_sub_p(ctx: FieldCtx, a):
    """a if a < p else a - p (a < 2p, limbs normalized)."""
    diff, borrow = _sub_limbs(a, jnp.broadcast_to(ctx.p_limbs, a.shape))
    return jnp.where((borrow == 0)[..., None], diff, a)


def add(ctx: FieldCtx, a, b):
    t = a + b
    t, _ = _carry_propagate(t)
    return _cond_sub_p(ctx, t)


def sub(ctx: FieldCtx, a, b):
    # a + (p - b): both < p so p - b has no borrow issues
    pb, _ = _sub_limbs(jnp.broadcast_to(ctx.p_limbs, b.shape), b)
    return add(ctx, a, pb)


def neg(ctx: FieldCtx, a):
    pb, _ = _sub_limbs(jnp.broadcast_to(ctx.p_limbs, a.shape), a)
    # p - 0 = p must normalize to 0
    is_zero = jnp.all(a == 0, axis=-1, keepdims=True)
    return jnp.where(is_zero, jnp.zeros_like(a), _cond_sub_p(ctx, pb))


def _mont_mul_cios(ctx: FieldCtx, a, b):
    """Montgomery product a*b*R^{-1} mod p: 16 CIOS rounds as a lax.scan.

    Each round is a fully vectorized multiply-accumulate over the batch; the
    scan keeps the traced graph small (an unrolled version is ~300 HLO ops per
    multiply, which made circuit-sized graphs take minutes to compile). Written
    scatter-free: shifted adds via concatenate."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    bT = jnp.moveaxis(jnp.broadcast_to(b, shape), -1, 0)  # [16, ...]
    p_limbs = ctx.p_limbs
    n0 = ctx.n0inv16
    z1 = jnp.zeros(shape[:-1] + (1,), dtype=jnp.uint32)

    def rnd(t, bi):
        prod = a * bi[..., None]          # [..., 16], each < 2^32
        t = (t
             + jnp.concatenate([prod & MASK, z1], axis=-1)
             + jnp.concatenate([z1, prod >> 16], axis=-1))
        m = (t[..., 0] * n0) & MASK
        q = p_limbs * m[..., None]
        t = (t
             + jnp.concatenate([q & MASK, z1], axis=-1)
             + jnp.concatenate([z1, q >> 16], axis=-1))
        # t[...,0] now ≡ 0 mod 2^16; shift down one limb
        carry = t[..., 0:1] >> 16
        t = jnp.concatenate([t[..., 1:2] + carry, t[..., 2:], z1], axis=-1)
        return t, None

    t0 = jnp.zeros(shape[:-1] + (NLIMBS + 1,), dtype=jnp.uint32)
    t, _ = jax.lax.scan(rnd, t0, bT)
    res, _top = _carry_propagate(t[..., :NLIMBS])
    # Montgomery guarantees result < 2p for p < R/4 (ours is), so top == 0
    return _cond_sub_p(ctx, res)


_USE_MXU = False


def mont_mul(ctx: FieldCtx, a, b):
    """Montgomery product dispatcher: CIOS scan by default, the MXU int8-limb
    matmul formulation (`field_mxu.mont_mul`, SURVEY.md §7 hard part 2) when
    `enable_mxu()` has been called. The flag is read at TRACE time, so a
    `from field_ops import mont_mul` binding still follows later swaps;
    executables compiled before the swap keep the implementation they traced
    (re-jit to pick up the new one)."""
    if _USE_MXU:
        from . import field_mxu
        return field_mxu.mont_mul(ctx, a, b)
    return _mont_mul_cios(ctx, a, b)


def enable_mxu(on: bool = True):
    """Route `mont_mul` through the MXU formulation (see dispatcher above).
    Auto-enabled when SPECTRE_FIELD_IMPL=mxu."""
    global _USE_MXU
    _USE_MXU = bool(on)


if __import__("os").environ.get("SPECTRE_FIELD_IMPL") == "mxu":
    enable_mxu()


def mont_sqr(ctx: FieldCtx, a):
    return mont_mul(ctx, a, a)


def to_mont(ctx: FieldCtx, a):
    return mont_mul(ctx, a, ctx.r2)


def from_mont(ctx: FieldCtx, a):
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(ctx, a, one)


def mont_pow(ctx: FieldCtx, a, e: int, max_unroll: int = 24):
    """a^e for a host-known integer exponent.

    Short exponents unroll (fast, fully fused); long ones (e.g. Fermat
    inversion, 254 bits) run as a lax.fori_loop over a constant bit array to
    keep the traced graph small — an unrolled 254-bit ladder is ~400 chained
    mont_muls and makes XLA compile times explode."""
    if e < 0:
        raise ValueError("negative exponent")
    if e == 0:
        return jnp.broadcast_to(ctx.one_mont, a.shape)
    nbits = e.bit_length()
    if nbits <= max_unroll:
        result = None
        base = a
        while e:
            if e & 1:
                result = base if result is None else mont_mul(ctx, result, base)
            e >>= 1
            if e:
                base = mont_sqr(ctx, base)
        return result
    bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], dtype=jnp.uint32)

    def body(i, carry):
        result, base = carry
        mult = mont_mul(ctx, result, base)
        result = jnp.where((bits[i] == 1)[..., None], mult, result)
        base = mont_sqr(ctx, base)
        return (result, base)

    result0 = jnp.broadcast_to(ctx.one_mont, a.shape)
    result, _ = jax.lax.fori_loop(0, nbits, body, (result0, a))
    return result


def inv(ctx: FieldCtx, a):
    """Batched inversion via Fermat (a^(p-2)); inv(0) = 0."""
    return mont_pow(ctx, a, ctx.p - 2)


def limb_digits(scalars, w, c: int):
    """Extract window-w c-bit digits from [n, L] 16-bit limb tensors.

    Width-generic (L = 16 full scalars, L = 8 GLV half-scalars): the limb
    count comes from the tensor, not a module constant. w may be a traced
    int32 (used inside lax loops). Branchless across limb boundaries: a
    digit spans at most 2 limbs for c <= 16. Windows past the top limb read
    as zero (the padded-window idiom in parallel.sharded_msm relies on it)."""
    nlimbs = scalars.shape[-1]
    off = w * c
    limb_idx = off // 16
    shift = off % 16
    in_range = limb_idx < nlimbs
    col = jnp.take(scalars, jnp.minimum(limb_idx, nlimbs - 1), axis=1)
    col = jnp.where(in_range, col, 0)
    nxt = jnp.take(scalars, jnp.minimum(limb_idx + 1, nlimbs - 1), axis=1)
    lo = col >> shift
    hi = jnp.where(shift > 0, nxt << (16 - shift), 0)
    hi = jnp.where(limb_idx + 1 < nlimbs, hi, 0)
    return ((lo | hi) & ((1 << c) - 1)).astype(jnp.int32)


def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def select(mask, a, b):
    """mask ? a : b, mask shaped [...] (no limb axis)."""
    return jnp.where(mask[..., None], a, b)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def mul_const(ctx: FieldCtx, a, c_mont):
    """Multiply by a broadcast constant already in Montgomery form."""
    return mont_mul(ctx, a, jnp.broadcast_to(c_mont, a.shape))

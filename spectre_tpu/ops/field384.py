"""Vectorized 384-bit Montgomery arithmetic: BLS12-381 Fq on device (N5).

Closes the `field_ops.py` deferral ("BLS12-381 device field uses 24 limbs;
later round"): the same 16-bit-limb CIOS design as `field_ops`, widened to
24 limbs / R = 2^384. Primary witness-side consumer: batched G1 pubkey
decompression (512 keys per committee, `preprocessor` + fixture generation —
reference does these on the host with `halo2curves`, SURVEY.md §2b N5).

sqrt uses the p ≡ 3 (mod 4) exponentiation (BLS12-381's base field
qualifies), so decompression is two batched pows (sqrt + legendre folded
into one: y = a^((p+1)/4), valid iff y^2 == a).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NLIMBS = 24
MASK = np.uint32(0xFFFF)


class Field384Ctx:
    def __init__(self, p: int, name: str):
        assert p.bit_length() <= 384 and p % 4 == 3
        self.p = p
        self.name = name
        self.p_limbs = _int_to_limbs(p)
        self.n0inv16 = np.uint32((-pow(p, -1, 1 << 16)) % (1 << 16))
        r = (1 << (16 * NLIMBS)) % p
        self.r_mod_p = r
        self.r2 = _int_to_limbs((r * r) % p)
        self.one_mont = _int_to_limbs(r)

    def encode_np(self, vals) -> np.ndarray:
        r = self.r_mod_p
        return _ints_to_limbs([(int(v) % self.p) * r % self.p for v in vals])

    def decode(self, arr) -> list[int]:
        rinv = pow(self.r_mod_p, -1, self.p)
        return [v * rinv % self.p for v in _limbs_to_ints(np.asarray(arr))]


def _int_to_limbs(v: int) -> np.ndarray:
    return np.array([(v >> (16 * i)) & 0xFFFF for i in range(NLIMBS)],
                    dtype=np.uint32)


def _ints_to_limbs(vals) -> np.ndarray:
    out = np.empty((len(vals), NLIMBS), dtype=np.uint32)
    for i, v in enumerate(vals):
        out[i] = _int_to_limbs(int(v))
    return out


def _limbs_to_ints(arr: np.ndarray) -> list[int]:
    arr = arr.reshape(-1, NLIMBS)
    return [sum(int(row[i]) << (16 * i) for i in range(NLIMBS)) for row in arr]


@functools.cache
def bls_fq_ctx() -> Field384Ctx:
    from ..fields import bls12_381 as bls
    return Field384Ctx(bls.P, "bls12_381_fq")


def _carry_propagate(t):
    tT = jnp.moveaxis(t, -1, 0)

    def step(carry, ti):
        cur = ti + carry
        return cur >> 16, cur & MASK

    carry, outs = jax.lax.scan(step, jnp.zeros_like(tT[0]), tT)
    return jnp.moveaxis(outs, 0, -1), carry


def _sub_limbs(a, b):
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    aT = jnp.moveaxis(jnp.broadcast_to(a, shape), -1, 0)
    bT = jnp.moveaxis(jnp.broadcast_to(b, shape), -1, 0)

    def step(borrow, ab):
        ai, bi = ab
        cur = ai - bi - borrow
        return (cur >> 16) & np.uint32(1), cur & MASK

    borrow, outs = jax.lax.scan(step, jnp.zeros_like(aT[0]), (aT, bT))
    return jnp.moveaxis(outs, 0, -1), borrow


def _cond_sub_p(ctx, a):
    diff, borrow = _sub_limbs(a, jnp.broadcast_to(ctx.p_limbs, a.shape))
    return jnp.where((borrow == 0)[..., None], diff, a)


def add(ctx, a, b):
    t, _ = _carry_propagate(a + b)
    return _cond_sub_p(ctx, t)


def mont_mul(ctx, a, b):
    """24-round CIOS; accumulators stay < 2^24 (same magnitude argument as
    field_ops.mont_mul, two extra limbs of headroom)."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    bT = jnp.moveaxis(jnp.broadcast_to(b, shape), -1, 0)
    p_limbs = ctx.p_limbs
    n0 = ctx.n0inv16
    z1 = jnp.zeros(shape[:-1] + (1,), dtype=jnp.uint32)

    def rnd(t, bi):
        prod = a * bi[..., None]
        t = (t
             + jnp.concatenate([prod & MASK, z1], axis=-1)
             + jnp.concatenate([z1, prod >> 16], axis=-1))
        m = (t[..., 0] * n0) & MASK
        q = p_limbs * m[..., None]
        t = (t
             + jnp.concatenate([q & MASK, z1], axis=-1)
             + jnp.concatenate([z1, q >> 16], axis=-1))
        carry = t[..., 0:1] >> 16
        t = jnp.concatenate([t[..., 1:2] + carry, t[..., 2:], z1], axis=-1)
        return t, None

    t0 = jnp.zeros(shape[:-1] + (NLIMBS + 1,), dtype=jnp.uint32)
    t, _ = jax.lax.scan(rnd, t0, bT)
    res, _top = _carry_propagate(t[..., :NLIMBS])
    return _cond_sub_p(ctx, res)


def mont_pow(ctx, a, e: int):
    """a^e via a fori_loop over the exponent bits (384-bit exponents)."""
    nbits = e.bit_length()
    bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], dtype=jnp.uint32)

    def body(i, carry):
        result, base = carry
        mult = mont_mul(ctx, result, base)
        result = jnp.where((bits[i] == 1)[..., None], mult, result)
        return (result, mont_mul(ctx, base, base))

    result0 = jnp.broadcast_to(jnp.asarray(ctx.one_mont), a.shape)
    result, _ = jax.lax.fori_loop(0, nbits, body, (result0, a))
    return result


@functools.cache
def _decompress_fn():
    """jitted: x (Montgomery [n,24]) -> (y_mont, ok) with y = sqrt(x^3+4)."""
    ctx = bls_fq_ctx()

    def fn(xm):
        b4 = jnp.broadcast_to(jnp.asarray(
            ctx.encode_np([4])[0]), xm.shape)
        x3 = mont_mul(ctx, mont_mul(ctx, xm, xm), xm)
        rhs = add(ctx, x3, b4)
        y = mont_pow(ctx, rhs, (ctx.p + 1) // 4)
        ok = jnp.all(mont_mul(ctx, y, y) == rhs, axis=-1)
        return y, ok

    return jax.jit(fn)


def g1_decompress_batch(compressed: list[bytes]) -> list[tuple[int, int]]:
    """Batched BLS12-381 G1 decompression on device (the 512-pubkey
    witness-side op). Bit-identical to `bls12_381.g1_decompress` per key —
    pinned by tests; flags/canonicality are validated on host, the sqrt
    rides the device."""
    from ..fields import bls12_381 as bls

    ctx = bls_fq_ctx()
    xs, signs = [], []
    for b in compressed:
        assert len(b) == 48 and b[0] & 0x80, "bad compressed G1"
        assert not b[0] & 0x40, "infinity not expected in committee keys"
        xi = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:], "big")
        assert xi < ctx.p, "x not canonical"
        xs.append(xi)
        signs.append(bool(b[0] & 0x20))
    xm = jnp.asarray(ctx.encode_np(xs))
    y_m, ok = _decompress_fn()(xm)
    assert bool(jnp.all(ok)), "point not on curve"
    ys = ctx.decode(np.asarray(y_m))
    out = []
    for xi, y, sgn in zip(xs, ys, signs):
        # sign normalization matches bls12_381._fq_sign (y > (p-1)/2)
        if (y > (ctx.p - 1) // 2) != sgn:
            y = ctx.p - y
        out.append((xi, y))
    return out

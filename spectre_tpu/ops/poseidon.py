"""Poseidon permutation over BN254 Fr: native (host int) + batched JAX (N7).

Reference parity: `pse-poseidon` (native) and halo2-base `PoseidonSponge`
(in-circuit), with the spectre sponge shape pinned in
`lightclient-circuits/src/poseidon.rs:22-30`: T=12, RATE=11, R_F=8, R_P=65,
x^5 S-box. Round constants and the MDS matrix follow the halo2-base /
zcash-halo2 Grain procedure the reference instantiates (`poseidon.rs:79`
`PoseidonSponge::new::<R_F, R_P, 0>` -> `OptimizedPoseidonSpec` ->
`generate_constants`/`generate_mds` with SECURE_MDS=0): rejection-sampled
MSB-first round constants; non-rejected LSB-first MDS xs/ys (batch-retried on
duplicates); Cauchy matrix 1/(x_i + y_j). The optimized-spec rewrite the Rust
side applies for sparse partial rounds is an equivalence transform, so the
naive schedule here produces identical permutation outputs. NOTE on external
parity: the reference snapshot contains NO reproducible (committee, poseidon)
pair — audited round 5: the only external Poseidon artifact anywhere in it is
`.env.example`'s INITIAL_COMMITTEE_POSEIDON (Sepolia period 10), whose
preimage committee lives behind a beacon API this offline environment cannot
reach; `poseidon.rs` has no unit vectors, and every fixture computes its
commitment at runtime. Offline evidence is therefore: (a) an independent
integer-register Grain re-derivation matching bit-for-bit
(tests/test_ops.py::TestGrainSecondSource), and (b) golden vectors of this
derivation pinned so any drift is loud. Cross-checkable the moment an oracle
appears.

The sponge construction (rate-11 "onion" absorb over committee pubkeys) lives
in gadgets/poseidon_commit.py; this module is the permutation itself.
"""

from __future__ import annotations

import functools


import jax
import jax.numpy as jnp

from ..fields import bn254
from . import field_ops as F

R = bn254.R

# spectre sponge shape (poseidon.rs:22-30)
T = 12
RATE = 11
R_F = 8
R_P = 65


class GrainLFSR:
    """80-bit Grain LFSR from the Poseidon reference parameter generator."""

    def __init__(self, field_bits: int, t: int, r_f: int, r_p: int,
                 field_type: int = 1, sbox: int = 0):
        bits = []
        bits += _to_bits(field_type, 2)
        bits += _to_bits(sbox, 4)
        bits += _to_bits(field_bits, 12)
        bits += _to_bits(t, 12)
        bits += _to_bits(r_f, 10)
        bits += _to_bits(r_p, 10)
        bits += [1] * 30
        assert len(bits) == 80
        self.state = bits
        for _ in range(160):
            self._next_bit()

    def _next_bit(self) -> int:
        s = self.state
        new = s[62] ^ s[51] ^ s[38] ^ s[23] ^ s[13] ^ s[0]
        self.state = s[1:] + [new]
        return new

    def next_filtered_bit(self) -> int:
        # von Neumann-style filtering: emit second bit of a pair iff first is 1
        while True:
            b1 = self._next_bit()
            b2 = self._next_bit()
            if b1:
                return b2

    def next_field_element(self, p: int, nbits: int) -> int:
        """Rejection-sampled element, bits MSB-first (used for round
        constants — matches the Poseidon reference generator and
        zcash-halo2/halo2-base `Grain::next_field_element`)."""
        while True:
            v = 0
            for _ in range(nbits):
                v = (v << 1) | self.next_filtered_bit()
            if v < p:
                return v

    def next_field_element_without_rejection(self, p: int, nbits: int) -> int:
        """Non-rejected element, bits packed LSB-first then wide-reduced
        (zcash-halo2/halo2-base `next_field_element_without_rejection`,
        used for the MDS xs/ys): bit i goes to byte i//8 bit i%8 of a
        64-byte little-endian buffer, interpreted mod p."""
        v = 0
        for i in range(nbits):
            v |= self.next_filtered_bit() << i
        return v % p


def _to_bits(v: int, n: int):
    return [(v >> (n - 1 - i)) & 1 for i in range(n)]


@functools.cache
def constants(t: int = T, r_f: int = R_F, r_p: int = R_P,
              secure_mds: int = 0):
    """(round_constants [(r_f + r_p) * t], mds [t][t]) over Fr.

    Generation follows halo2-base `OptimizedPoseidonSpec::new::<R_F,R_P,0>`
    (= zcash-halo2 `generate_constants` + `generate_mds`, the code path the
    reference instantiates in `poseidon.rs:79` via
    `PoseidonSponge::<F,T,RATE>::new::<R_F,R_P,0>`): round constants by
    MSB-first rejection sampling; MDS xs/ys by LSB-first non-rejected
    sampling, retried as a whole 2t batch until all 2t values are distinct,
    with `secure_mds` initial batches discarded (the reference uses 0);
    mds[i][j] = 1/(xs[i]+ys[j])."""
    nbits = R.bit_length()  # 254
    lfsr = GrainLFSR(nbits, t, r_f, r_p)
    rc = [lfsr.next_field_element(R, nbits) for _ in range((r_f + r_p) * t)]
    select = secure_mds
    while True:
        vals = [lfsr.next_field_element_without_rejection(R, nbits)
                for _ in range(2 * t)]
        if len(set(vals)) != 2 * t:
            continue
        if select != 0:
            select -= 1
            continue
        xs, ys = vals[:t], vals[t:]
        break
    mds = [[pow((xs[i] + ys[j]) % R, -1, R) for j in range(t)] for i in range(t)]
    return rc, mds


# ---------------------------------------------------------------------------
# native permutation (host ints) — used by witness gen / commitment mirror
# ---------------------------------------------------------------------------

def permute_native(state: list[int], t: int = T, r_f: int = R_F, r_p: int = R_P) -> list[int]:
    assert len(state) == t
    rc, mds = constants(t, r_f, r_p)
    s = [x % R for x in state]
    half = r_f // 2
    ri = 0

    def full_round(s, ri):
        s = [(x + rc[ri * t + i]) % R for i, x in enumerate(s)]
        s = [pow(x, 5, R) for x in s]
        return _mds_mul(s, mds), ri + 1

    def partial_round(s, ri):
        s = [(x + rc[ri * t + i]) % R for i, x in enumerate(s)]
        s[0] = pow(s[0], 5, R)
        return _mds_mul(s, mds), ri + 1

    for _ in range(half):
        s, ri = full_round(s, ri)
    for _ in range(r_p):
        s, ri = partial_round(s, ri)
    for _ in range(half):
        s, ri = full_round(s, ri)
    return s


def _mds_mul(s, mds):
    t = len(s)
    return [sum(mds[i][j] * s[j] for j in range(t)) % R for i in range(t)]


class PoseidonSponge:
    """Native sponge (absorb/squeeze), matching halo2-base's PoseidonSponge
    semantics: absorb buffers elements; squeeze pads with a single 1 then
    permutes chunks of RATE."""

    def __init__(self, t: int = T, rate: int = RATE, r_f: int = R_F, r_p: int = R_P):
        self.t, self.rate, self.r_f, self.r_p = t, rate, r_f, r_p
        self.state = [0] * t
        self.buf: list[int] = []

    def absorb(self, vals):
        self.buf.extend(int(v) % R for v in vals)

    def squeeze(self) -> int:
        chunks = self.buf + [1]
        self.buf = []
        for off in range(0, len(chunks), self.rate):
            chunk = chunks[off:off + self.rate]
            for i, v in enumerate(chunk):
                self.state[i + 1] = (self.state[i + 1] + v) % R
            self.state = permute_native(self.state, self.t, self.r_f, self.r_p)
        return self.state[1]


# ---------------------------------------------------------------------------
# batched device permutation
# ---------------------------------------------------------------------------

@functools.cache
def _device_constants(t: int = T, r_f: int = R_F, r_p: int = R_P):
    """Montgomery-encoded numpy constants (trace-safe to cache)."""
    ctx = F.fr_ctx()
    rc, mds = constants(t, r_f, r_p)
    rc_np = ctx.encode(rc).reshape(r_f + r_p, t, F.NLIMBS)
    mds_np = ctx.encode([mds[i][j] for i in range(t) for j in range(t)]) \
        .reshape(t, t, F.NLIMBS)
    return rc_np, mds_np


def permute(state: jax.Array, t: int = T, r_f: int = R_F, r_p: int = R_P) -> jax.Array:
    """Batched Poseidon permutation: state [m, t, 16] Montgomery -> same.

    Full rounds scan + partial rounds scan (same shapes per round); MDS as a
    stacked field multiply [m, t, t, 16] + add-tree over j."""
    ctx = F.fr_ctx()
    rc_np, mds_np = _device_constants(t, r_f, r_p)
    rc = jnp.asarray(rc_np)
    mds = jnp.asarray(mds_np)
    half = r_f // 2

    def sbox5(x):
        x2 = F.mont_sqr(ctx, x)
        x4 = F.mont_sqr(ctx, x2)
        return F.mont_mul(ctx, x4, x)

    def mds_mul(s):
        prod = F.mont_mul(ctx, mds[None], s[:, None, :, :])  # [m, t, t, 16]
        acc = prod
        while acc.shape[2] > 1:
            k = acc.shape[2]
            half_k = k // 2
            merged = F.add(ctx, acc[:, :, :half_k], acc[:, :, half_k:2 * half_k])
            acc = jnp.concatenate([merged, acc[:, :, 2 * half_k:]], axis=2) \
                if k % 2 else merged
        return acc[:, :, 0]

    def full_round(s, rci):
        s = F.add(ctx, s, rci[None])
        s = sbox5(s)
        return mds_mul(s), None

    def partial_round(s, rci):
        s = F.add(ctx, s, rci[None])
        first = sbox5(s[:, :1])
        s = jnp.concatenate([first, s[:, 1:]], axis=1)
        return mds_mul(s), None

    s, _ = jax.lax.scan(full_round, state, rc[:half])
    s, _ = jax.lax.scan(partial_round, s, rc[half:half + r_p])
    s, _ = jax.lax.scan(full_round, s, rc[half + r_p:])
    return s

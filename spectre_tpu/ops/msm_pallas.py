"""Pallas TPU kernel path for the Pippenger MSM (N2 north star).

Why this exists: the jnp MSM (`ops/msm.py`) lowers every field op to its own
XLA kernel with `lax.scan` carry chains — dozens of HBM round-trips per EC
add. This module fuses one COMPLETE projective add (14 Montgomery muls +
~20 field add/subs, RCB alg. 7) into a single Pallas kernel with all
intermediates in VMEM/registers, and lays data out structure-of-arrays so the
128-wide lanes run across POINTS (the batch) instead of across the 16 limbs
(which wasted 7/8 of every VPU issue in the AoS layout).

Layout: a point batch is [48, N] uint32 — rows = 3 projective coordinates x
16 Montgomery 16-bit limbs, lanes = points. Kernel math mirrors
`ops/field_ops.py` CIOS exactly (same magnitude analysis: accumulators stay
< 2^24, so uint32 never overflows).

Reference parity: halo2's `best_multiexp` (SURVEY.md §2b N2) — algorithmic
redesign, no code lineage.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import field_ops as F

NL = F.NLIMBS          # 16 limbs x 16 bits
ROWS = 3 * NL          # SoA rows per point batch
MASK16 = np.uint32(0xFFFF)
LANE = 128

_P_LIMBS = tuple(int(v) for v in F.fq_ctx().p_limbs)   # BN254 Fq
_ONE_LIMBS = tuple(int(v) for v in F.fq_ctx().one_mont)
_N0 = np.uint32(F.fq_ctx().n0inv16)


def _interpret() -> bool:
    """Run the Pallas kernel in interpret mode off-TPU: Mosaic only lowers
    for real TPU targets, so every other backend (the CPU CI box included)
    gets the exact-arithmetic interpreter — same kernel body, same bytes,
    pinned against the jnp path by tests. SPECTRE_PALLAS_INTERPRET=1 forces
    it on TPU too (kernel debugging)."""
    if os.environ.get("SPECTRE_PALLAS_INTERPRET") == "1":
        return True
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# layout converters
# ---------------------------------------------------------------------------

def to_soa(points):
    """[..., 3, 16] AoS -> [48, N] SoA (flattening leading dims)."""
    a = points.reshape(-1, 3, NL)
    return jnp.transpose(a, (1, 2, 0)).reshape(ROWS, a.shape[0])


def from_soa(arr):
    """[48, N] SoA -> [N, 3, 16] AoS."""
    n = arr.shape[1]
    return jnp.transpose(arr.reshape(3, NL, n), (2, 0, 1))


def inf_soa(n: int):
    """Projective infinity (0:1:0) as [48, n]."""
    one = F.fq_ctx().one_mont
    col = np.zeros((ROWS,), np.uint32)
    col[NL:2 * NL] = one
    return jnp.broadcast_to(jnp.asarray(col)[:, None], (ROWS, n))


# ---------------------------------------------------------------------------
# in-kernel field arithmetic over [16, T] limb-row arrays
#
# Written in lax.scan/fori_loop form, NOT unrolled: a fully unrolled CIOS is
# ~2k HLO ops per multiply and took XLA-CPU ~90s to compile ONE multiply;
# the scan form keeps every function to tens of ops (same lesson as
# ops/field_ops.py, transposed so lanes run across points).
# ---------------------------------------------------------------------------

def _p_col():
    """[16, 1] modulus column, built IN-TRACE from scalar literals: a
    pallas kernel body may not capture traced array constants (pallas_call
    rejects the jaxpr), so the column is materialized with 16 selects over
    an iota — free next to a CIOS scan, and the same code path serves the
    plain-jit uses of the _k_* helpers."""
    idx = jax.lax.broadcasted_iota(jnp.uint32, (NL, 1), 0)
    col = jnp.zeros((NL, 1), jnp.uint32)
    for i, v in enumerate(_P_LIMBS):
        col = jnp.where(idx == np.uint32(i), np.uint32(v), col)
    return col


def _k_mont_mul(a, b):
    """CIOS Montgomery product: a, b [16, T] uint32 -> [16, T]."""
    lane_shape = a.shape[1:]
    z1 = jnp.zeros((1,) + lane_shape, jnp.uint32)
    p_col = _p_col()

    def rnd(t, bj):
        prod = a * bj[None]
        t = (t + jnp.concatenate([prod & MASK16, z1], 0)
             + jnp.concatenate([z1, prod >> 16], 0))
        m = (t[0] * _N0) & MASK16
        q = p_col * m[None]
        t = (t + jnp.concatenate([q & MASK16, z1], 0)
             + jnp.concatenate([z1, q >> 16], 0))
        carry = t[0] >> 16
        t = jnp.concatenate([(t[1] + carry)[None], t[2:], z1], 0)
        return t, None

    t0 = jnp.zeros((NL + 1,) + lane_shape, jnp.uint32)
    t, _ = jax.lax.scan(rnd, t0, b)
    return _k_carry_sub(t[:NL])


def _carry_prop(t):
    """Carry-propagate a [16, T] accumulator (entries < 2^32)."""
    def step(c, ti):
        cur = ti + c
        return cur >> 16, cur & MASK16

    c, outs = jax.lax.scan(step, jnp.zeros_like(t[0]), t)
    return outs, c


def _k_carry_sub(t):
    """Full carry propagation then conditional subtract of p."""
    out, _top = _carry_prop(t)
    return _k_cond_sub_p(out)


def _k_cond_sub_p(a):
    """a if a < p else a - p (a < 2p, limbs normalized). a: [16, T]."""
    def step(borrow, api):
        ai, pi = api
        cur = ai - pi - borrow
        return (cur >> 16) & np.uint32(1), cur & MASK16

    p_col = jnp.broadcast_to(_p_col(), a.shape)
    borrow, diff = jax.lax.scan(step, jnp.zeros_like(a[0]), (a, p_col))
    return jnp.where(borrow != 0, a, diff)


def _k_add(a, b):
    out, _ = _carry_prop(a + b)
    return _k_cond_sub_p(out)


def _k_sub(a, b):
    """a - b mod p via a + (p - b); both inputs reduced (p - 0 = p is
    normalized by the add's conditional subtract)."""
    def step(borrow, pbi):
        pi, bi = pbi
        cur = pi - bi - borrow
        return (cur >> 16) & np.uint32(1), cur & MASK16

    p_col = jnp.broadcast_to(_p_col(), b.shape)
    _, pb = jax.lax.scan(step, jnp.zeros_like(b[0]), (p_col, b))
    return _k_add(a, pb)


def _k_padd(p_arr, q_arr):
    """Complete RCB (alg. 7, a=0, b3=9) add on [48, T] arrays."""
    x1, y1, z1 = p_arr[:NL], p_arr[NL:2 * NL], p_arr[2 * NL:]
    x2, y2, z2 = q_arr[:NL], q_arr[NL:2 * NL], q_arr[2 * NL:]

    t0 = _k_mont_mul(x1, x2)
    t1 = _k_mont_mul(y1, y2)
    t2 = _k_mont_mul(z1, z2)
    m3 = _k_mont_mul(_k_add(x1, y1), _k_add(x2, y2))
    m4 = _k_mont_mul(_k_add(y1, z1), _k_add(y2, z2))
    m5 = _k_mont_mul(_k_add(x1, z1), _k_add(x2, z2))
    t3 = _k_sub(_k_sub(m3, t0), t1)
    t4 = _k_sub(_k_sub(m4, t1), t2)
    ycross = _k_sub(_k_sub(m5, t0), t2)

    t0_3 = _k_add(_k_add(t0, t0), t0)
    t2_2 = _k_add(t2, t2)
    t2_4 = _k_add(t2_2, t2_2)
    b3t2 = _k_add(_k_add(t2_4, t2_4), t2)          # 9*t2
    y_2 = _k_add(ycross, ycross)
    y_4 = _k_add(y_2, y_2)
    b3y = _k_add(_k_add(y_4, y_4), ycross)         # 9*ycross

    z3p = _k_add(t1, b3t2)
    t1m = _k_sub(t1, b3t2)

    x3a = _k_mont_mul(t4, b3y)
    x3b = _k_mont_mul(t3, t1m)
    y3a = _k_mont_mul(b3y, t0_3)
    y3b = _k_mont_mul(t1m, z3p)
    z3a = _k_mont_mul(t0_3, t3)
    z3b = _k_mont_mul(z3p, t4)

    return jnp.concatenate(
        [_k_sub(x3b, x3a), _k_add(y3b, y3a), _k_add(z3b, z3a)], axis=0)


def _padd_kernel(p_ref, q_ref, o_ref):
    o_ref[:, :] = _k_padd(p_ref[:, :], q_ref[:, :])


# module-level jitted entry points (trace-cache hygiene lint roots):
# analysis/trace_lint verifies each name below is a stable module-level
# jit; the pallas_calls below live INSIDE jit-decorated functions, so
# the outer jit caches its trace (exempt from TC-FRESH-JIT by design).
TRACE_JIT_ROOTS = ("_padd_soa_call", "_bucket_sums",
                   "_bucket_windows_jit", "_bucket_fixed_jit")


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _padd_soa_call(p, q, block: int, interpret: bool):
    from jax.experimental import pallas as pl

    n = p.shape[1]
    grid = (n // block,)
    return pl.pallas_call(
        _padd_kernel,
        out_shape=jax.ShapeDtypeStruct((ROWS, n), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (0, i)),
            pl.BlockSpec((ROWS, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((ROWS, block), lambda i: (0, i)),
        interpret=interpret,
    )(p, q)


def _legal_block(n_pad: int, want: int) -> int:
    """Largest Mosaic-legal lane-block ≤ want: a multiple of LANE that
    divides n_pad (n_pad is lane-padded, so LANE itself always qualifies —
    the search can't fall below a legal shape). The sublane dim is the fixed
    ROWS=48 = 6 packed uint32 sublane tiles, legal by construction."""
    q = n_pad // LANE
    d = min(max(want // LANE, 1), q)
    while q % d:
        d -= 1
    return d * LANE


def padd_soa(p, q, block: int = 2048):
    """Complete projective add on SoA batches [48, N]; pads lanes to a
    multiple of 128 (padding lanes compute garbage and are sliced off)."""
    n = p.shape[1]
    n_pad = -(-n // LANE) * LANE
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n))
        p = jnp.pad(p, pad)
        q = jnp.pad(q, pad)
    out = _padd_soa_call(p, q, _legal_block(n_pad, block), _interpret())
    return out[:, :n] if n_pad != n else out


# ---------------------------------------------------------------------------
# MSM on SoA arrays: VMEM-resident bucket accumulation (Pippenger)
#
# The bucket phase runs INSIDE one Pallas kernel instead of the old XLA
# argsort + emission-slot reduction. Grid = (point block,): the FULL
# [nwin, 48, 2^(c-1)] bucket tensor stays resident in VMEM across the
# block axis (the out BlockSpec ignores the block index — the standard
# revisiting-accumulator pattern), the window axis is an in-kernel
# fori_loop (keeps the trace constant-size: interpret mode inlines the
# body once per GRID step, so windows must not be grid), and point blocks
# stream through the pallas pipeline — which on TPU is exactly the
# double-buffered DMA the bucket method wants. Digits are SIGNED
# (ops/msm.signed_digit_stream), so the bucket array is half of 2^c; the
# digit sign and the GLV half-scalar sign fold into ONE conditional-negate
# mask per point. Column j holds bucket j+1 (weight j+1); digit 0 matches
# no column and is a free skip.
#
# VMEM budget: nwin * 48 * 2^(c-1) * 4 bytes resident — 0.8 MB at the
# production GLV window (c=11, nwin=12), 7.9 MB at c=13; the vanilla path
# caps its default window at 11 (254-bit scalars triple nwin) to stay
# inside the ~16 MB arena next to the streamed point blocks.
# ---------------------------------------------------------------------------

def _inf_col():
    """[48, 1] projective infinity (0:1:0) built IN-TRACE from scalar
    literals — same TC-CONST-CAPTURE constraint as _p_col: a pallas kernel
    body may not capture traced array constants."""
    idx = jax.lax.broadcasted_iota(jnp.uint32, (ROWS, 1), 0)
    col = jnp.zeros((ROWS, 1), jnp.uint32)
    for i, v in enumerate(_ONE_LIMBS):
        if v:
            col = jnp.where(idx == np.uint32(NL + i), np.uint32(v), col)
    return col


def _k_cneg(mask, arr):
    """Conditional projective negation on [48, T]: y -> p - y where mask.
    Infinity is safe: _k_sub normalizes p - 0 back to 0, so (0:1:0)
    negates to itself bit-for-bit."""
    y = arr[NL:2 * NL]
    ny = _k_sub(jnp.zeros_like(y), y)
    return jnp.concatenate(
        [arr[:NL], jnp.where(mask, ny, y), arr[2 * NL:]], axis=0)


def _k_bucket_accumulate(pts, digs, negs, buckets):
    """One point block into the resident bucket tensor (pure jnp body — the
    kernel below is a ref-shim around it; kernel-lint traces it directly).

    pts [P, 48, B] SoA points (P = 1: one base shared by every window;
    P = nwin: fixed-base per-window tables); digs [nwin, B] int32 signed
    digits in [-2^(c-1)+1, 2^(c-1)]; negs [1, B] uint32 0/1 GLV sign mask;
    buckets [nwin, 48, NB] with column j = bucket j+1. Per (window, point):
    one conditional negate (digit sign XOR GLV sign), one full-width
    complete add against the window's bucket array (the [48, 1] point
    column broadcasts through _k_padd), and a one-hot column select — the
    serial chain is the bucket method's data dependence; the lane axis
    runs across the 2^(c-1) buckets."""
    nwin, _, nb = buckets.shape
    npts = pts.shape[-1]
    shared = pts.shape[0] == 1
    lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1) + 1

    def win(w, bks):
        acc = jax.lax.dynamic_slice(bks, (w, 0, 0), (1, ROWS, nb))[0]
        dw = jax.lax.dynamic_slice(digs, (w, 0), (1, npts))
        pw = pts[0] if shared else jax.lax.dynamic_slice(
            pts, (w, 0, 0), (1, ROWS, npts))[0]

        def body(i, a):
            d = jax.lax.dynamic_slice(dw, (0, i), (1, 1))
            g = jax.lax.dynamic_slice(negs, (0, i), (1, 1))
            col = jax.lax.dynamic_slice(pw, (0, i), (ROWS, 1))
            eff = _k_cneg(jnp.logical_xor(d < 0, g != 0), col)
            cand = _k_padd(a, eff)
            return jnp.where(lane1 == jnp.abs(d), cand, a)

        acc = jax.lax.fori_loop(0, npts, body, acc)
        return jax.lax.dynamic_update_slice(bks, acc[None], (w, 0, 0))

    return jax.lax.fori_loop(0, nwin, win, buckets)


def _bucket_kernel(d_ref, g_ref, p_ref, o_ref):
    from jax.experimental import pallas as pl

    nwin, _, nb = o_ref.shape

    @pl.when(pl.program_id(0) == 0)
    def _init():
        o_ref[...] = jnp.broadcast_to(_inf_col()[None], (nwin, ROWS, nb))

    o_ref[...] = _k_bucket_accumulate(
        p_ref[...], d_ref[...], g_ref[...], o_ref[...])


def _aggregate_buckets_soa(bucket_sums, c: int):
    """sum_b b * B_b: bucket_sums [48, nwin, nbuckets] -> [48, nwin].

    High-to-low over digit bits: acc = 2*acc + sum(buckets with bit j set)."""
    nwin, nbuckets = bucket_sums.shape[1], bucket_sums.shape[2]
    idx = jnp.arange(nbuckets)
    inf1 = inf_soa(1)[:, None, :]                      # [48, 1, 1]
    acc = inf_soa(nwin)
    for j in range(c - 1, -1, -1):
        acc = padd_soa(acc, acc)
        mask = ((idx >> j) & 1).astype(bool)
        cur = jnp.where(mask[None, None, :], bucket_sums, inf1)
        k = nbuckets
        while k > 1:
            half = k // 2
            merged = padd_soa(
                cur[:, :, :half].reshape(ROWS, nwin * half),
                cur[:, :, half:2 * half].reshape(ROWS, nwin * half),
            ).reshape(ROWS, nwin, half)
            cur = (jnp.concatenate([merged, cur[:, :, 2 * half:]], axis=2)
                   if k % 2 else merged)
            k = cur.shape[2]
        acc = padd_soa(acc, cur[:, :, 0])
    return acc


@functools.partial(jax.jit, static_argnames=("nb", "block", "interpret"))
def _bucket_sums(points, digs, negs, nb: int, block: int, interpret: bool):
    """pallas_call wrapper: digits [nwin, n], negs [1, n], points either
    [48, n] (shared base) or [nwin, 48, n] (fixed-base window tables) ->
    [nwin, 48, nb] bucket sums. Grid = point blocks only: the bucket
    tensor is initialized at block 0 and revisited — VMEM-resident — until
    the last block is folded in, while the input specs stream the next
    point/digit block through the pipeline DMA. Jitted at module level
    (trace-cache root) even though its callers are themselves jitted —
    inner jit caches compose for free and keep the pallas_call under a
    stable trace cache for any future direct caller."""
    from jax.experimental import pallas as pl

    nwin, n = digs.shape
    if points.ndim == 2:
        points = points[None]
    nper = points.shape[0]
    return pl.pallas_call(
        _bucket_kernel,
        out_shape=jax.ShapeDtypeStruct((nwin, ROWS, nb), jnp.uint32),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((nwin, block), lambda j: (0, j)),
            pl.BlockSpec((1, block), lambda j: (0, j)),
            pl.BlockSpec((nper, ROWS, block), lambda j: (0, 0, j)),
        ],
        out_specs=pl.BlockSpec((nwin, ROWS, nb), lambda j: (0, 0, 0)),
        interpret=interpret,
    )(digs, negs, points)


def _signed_inputs(points, scalars, neg, c: int, nwin: int, gran: int):
    """Shared digit/sign/padding prep for the bucket pipeline: returns
    (points, digs [nwin, n_pad] int32, negs [1, n_pad] uint32). Padding
    points are infinity with digit 0 — skipped inside the kernel."""
    from . import msm as MSM

    n = scalars.shape[0]
    digs = MSM.signed_digit_stream(scalars, c, nwin)
    negs = (jnp.zeros((n,), jnp.uint32) if neg is None
            else jnp.asarray(neg).astype(jnp.uint32))
    n_pad = -(-n // gran) * gran
    if n_pad != n:
        pad = n_pad - n
        if points.ndim == 3:
            points = jnp.concatenate(
                [points, jnp.broadcast_to(
                    inf_soa(pad)[None], (points.shape[0], ROWS, pad))],
                axis=2)
        else:
            points = jnp.concatenate([points, inf_soa(pad)], axis=1)
        digs = jnp.pad(digs, ((0, 0), (0, pad)))
        negs = jnp.pad(negs, (0, pad))
    return points, digs, negs[None]


@functools.partial(jax.jit, static_argnames=("c", "nbits", "interpret"))
def _bucket_windows_jit(points, scalars, neg, c: int, nbits: int,
                        interpret: bool):
    """Raw bucket sums via the kernel: points [48, n] SoA, scalars [n, L]
    limb magnitudes, neg [n] sign mask (or None) -> [nwin, 48, nb].

    Deliberately jits ONLY the digit prep + pallas bucket stage: the
    weighted aggregation runs eagerly through padd_soa's own per-shape jit.
    Inlining it here would splice every interpret-mode padd body of the
    reduction tree into one jaxpr, and XLA-CPU's LLVM compile time is
    superlinear in program size (~75s vs ~15s for the split pipeline at
    tiny shapes)."""
    nwin = (nbits + c) // c          # ceil((nbits + 1) / c): carry room
    nb = 1 << (c - 1)
    gran = 8 if interpret else LANE
    points, digs, negs = _signed_inputs(points, scalars, neg, c, nwin, gran)
    block = _legal_block(points.shape[-1], 1024) if not interpret \
        else points.shape[-1]
    return _bucket_sums(points, digs, negs, nb, block, interpret)


@functools.partial(jax.jit, static_argnames=("c", "nbits", "interpret"))
def _bucket_fixed_jit(table, scalars, neg, c: int, nbits: int,
                      interpret: bool):
    """Fixed-base variant: table [nwin, 48, N] SoA window tables
    (T[w] = 2^{cw} * base, endo-expanded), scalars [N, L], neg [N] ->
    [nwin, 48, nb] raw bucket sums (same jit-scope split as
    _bucket_windows_jit)."""
    nwin = (nbits + c) // c
    nb = 1 << (c - 1)
    gran = 8 if interpret else LANE
    table, digs, negs = _signed_inputs(table, scalars, neg, c, nwin, gran)
    block = _legal_block(table.shape[-1], 1024) if not interpret \
        else table.shape[-1]
    return _bucket_sums(table, digs, negs, nb, block, interpret)


def _with_zero_bucket(acc):
    """[48, nwin, nb] -> [48, nwin, nb+1]: column j holds bucket j+1, so
    prepend the weight-0 bucket and the shared weighted aggregation
    (weight = column index) applies unchanged."""
    nwin = acc.shape[1]
    return jnp.concatenate(
        [jnp.broadcast_to(_inf_col()[:, None], (ROWS, nwin, 1)), acc],
        axis=2)


def msm_bucket_windows(points, scalars, neg, c: int, nbits: int):
    """[48, nwin] per-window sums (interpret mode resolved per call)."""
    sums = _bucket_windows_jit(points, scalars, neg, c, nbits, _interpret())
    return _aggregate_buckets_soa(
        _with_zero_bucket(jnp.transpose(sums, (1, 0, 2))), c)


def msm_bucket_fixed(table, scalars, neg, c: int, nbits: int):
    """[3, 16] projective result for a fixed-base window table: bucket sums
    merge ACROSS windows before one aggregation and the combine chain
    disappears (same structure as msm.msm_fixed_run)."""
    sums = _bucket_fixed_jit(table, scalars, neg, c, nbits, _interpret())
    acc = jnp.transpose(sums, (1, 0, 2))                  # [48, nwin, nb]
    nb = acc.shape[2]
    k = acc.shape[1]
    while k > 1:
        half = k // 2
        merged = padd_soa(
            acc[:, :half].reshape(ROWS, half * nb),
            acc[:, half:2 * half].reshape(ROWS, half * nb),
        ).reshape(ROWS, half, nb)
        acc = (jnp.concatenate([merged, acc[:, 2 * half:]], axis=1)
               if k % 2 else merged)
        k = acc.shape[1]
    out = _aggregate_buckets_soa(_with_zero_bucket(acc), c)
    return from_soa(out)[0]


def to_soa_windows(table):
    """[nwin, N, 3, 16] AoS window tables -> [nwin, 48, N] SoA."""
    nwin, n = table.shape[0], table.shape[1]
    return jnp.transpose(table, (0, 2, 3, 1)).reshape(nwin, ROWS, n)


def combine_windows_soa(window_sums, c: int):
    """[48, nwin] -> affine host result via the AoS combine (tiny workload:
    c doublings + 1 add per window — not worth a kernel)."""
    from . import msm as MSM

    return MSM.combine_windows(from_soa(window_sums), c)


def msm_soa(points, scalars, c: int | None = None):
    """Full MSM: points [48, n] SoA Montgomery, scalars [n, 16] standard
    limbs. Returns [3, 16] projective Montgomery (AoS, as ops/msm.msm).
    Signed-digit recode of the full 254-bit scalars — same group element
    as the unsigned vanilla path, half the bucket columns."""
    n = points.shape[1]
    if c is None:
        from . import msm as MSM
        c = MSM.default_window(n)
    return combine_windows_soa(
        msm_bucket_windows(points, scalars, None, c, 254), c)

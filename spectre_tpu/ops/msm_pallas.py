"""Pallas TPU kernel path for the Pippenger MSM (N2 north star).

Why this exists: the jnp MSM (`ops/msm.py`) lowers every field op to its own
XLA kernel with `lax.scan` carry chains — dozens of HBM round-trips per EC
add. This module fuses one COMPLETE projective add (14 Montgomery muls +
~20 field add/subs, RCB alg. 7) into a single Pallas kernel with all
intermediates in VMEM/registers, and lays data out structure-of-arrays so the
128-wide lanes run across POINTS (the batch) instead of across the 16 limbs
(which wasted 7/8 of every VPU issue in the AoS layout).

Layout: a point batch is [48, N] uint32 — rows = 3 projective coordinates x
16 Montgomery 16-bit limbs, lanes = points. Kernel math mirrors
`ops/field_ops.py` CIOS exactly (same magnitude analysis: accumulators stay
< 2^24, so uint32 never overflows).

Reference parity: halo2's `best_multiexp` (SURVEY.md §2b N2) — algorithmic
redesign, no code lineage.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import field_ops as F

NL = F.NLIMBS          # 16 limbs x 16 bits
ROWS = 3 * NL          # SoA rows per point batch
MASK16 = np.uint32(0xFFFF)
LANE = 128

_P_LIMBS = tuple(int(v) for v in F.fq_ctx().p_limbs)   # BN254 Fq
_N0 = np.uint32(F.fq_ctx().n0inv16)


def _interpret() -> bool:
    """Run the Pallas kernel in interpret mode off-TPU: Mosaic only lowers
    for real TPU targets, so every other backend (the CPU CI box included)
    gets the exact-arithmetic interpreter — same kernel body, same bytes,
    pinned against the jnp path by tests. SPECTRE_PALLAS_INTERPRET=1 forces
    it on TPU too (kernel debugging)."""
    if os.environ.get("SPECTRE_PALLAS_INTERPRET") == "1":
        return True
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# layout converters
# ---------------------------------------------------------------------------

def to_soa(points):
    """[..., 3, 16] AoS -> [48, N] SoA (flattening leading dims)."""
    a = points.reshape(-1, 3, NL)
    return jnp.transpose(a, (1, 2, 0)).reshape(ROWS, a.shape[0])


def from_soa(arr):
    """[48, N] SoA -> [N, 3, 16] AoS."""
    n = arr.shape[1]
    return jnp.transpose(arr.reshape(3, NL, n), (2, 0, 1))


def inf_soa(n: int):
    """Projective infinity (0:1:0) as [48, n]."""
    one = F.fq_ctx().one_mont
    col = np.zeros((ROWS,), np.uint32)
    col[NL:2 * NL] = one
    return jnp.broadcast_to(jnp.asarray(col)[:, None], (ROWS, n))


# ---------------------------------------------------------------------------
# in-kernel field arithmetic over [16, T] limb-row arrays
#
# Written in lax.scan/fori_loop form, NOT unrolled: a fully unrolled CIOS is
# ~2k HLO ops per multiply and took XLA-CPU ~90s to compile ONE multiply;
# the scan form keeps every function to tens of ops (same lesson as
# ops/field_ops.py, transposed so lanes run across points).
# ---------------------------------------------------------------------------

def _p_col():
    """[16, 1] modulus column, built IN-TRACE from scalar literals: a
    pallas kernel body may not capture traced array constants (pallas_call
    rejects the jaxpr), so the column is materialized with 16 selects over
    an iota — free next to a CIOS scan, and the same code path serves the
    plain-jit uses of the _k_* helpers."""
    idx = jax.lax.broadcasted_iota(jnp.uint32, (NL, 1), 0)
    col = jnp.zeros((NL, 1), jnp.uint32)
    for i, v in enumerate(_P_LIMBS):
        col = jnp.where(idx == np.uint32(i), np.uint32(v), col)
    return col


def _k_mont_mul(a, b):
    """CIOS Montgomery product: a, b [16, T] uint32 -> [16, T]."""
    lane_shape = a.shape[1:]
    z1 = jnp.zeros((1,) + lane_shape, jnp.uint32)
    p_col = _p_col()

    def rnd(t, bj):
        prod = a * bj[None]
        t = (t + jnp.concatenate([prod & MASK16, z1], 0)
             + jnp.concatenate([z1, prod >> 16], 0))
        m = (t[0] * _N0) & MASK16
        q = p_col * m[None]
        t = (t + jnp.concatenate([q & MASK16, z1], 0)
             + jnp.concatenate([z1, q >> 16], 0))
        carry = t[0] >> 16
        t = jnp.concatenate([(t[1] + carry)[None], t[2:], z1], 0)
        return t, None

    t0 = jnp.zeros((NL + 1,) + lane_shape, jnp.uint32)
    t, _ = jax.lax.scan(rnd, t0, b)
    return _k_carry_sub(t[:NL])


def _carry_prop(t):
    """Carry-propagate a [16, T] accumulator (entries < 2^32)."""
    def step(c, ti):
        cur = ti + c
        return cur >> 16, cur & MASK16

    c, outs = jax.lax.scan(step, jnp.zeros_like(t[0]), t)
    return outs, c


def _k_carry_sub(t):
    """Full carry propagation then conditional subtract of p."""
    out, _top = _carry_prop(t)
    return _k_cond_sub_p(out)


def _k_cond_sub_p(a):
    """a if a < p else a - p (a < 2p, limbs normalized). a: [16, T]."""
    def step(borrow, api):
        ai, pi = api
        cur = ai - pi - borrow
        return (cur >> 16) & np.uint32(1), cur & MASK16

    p_col = jnp.broadcast_to(_p_col(), a.shape)
    borrow, diff = jax.lax.scan(step, jnp.zeros_like(a[0]), (a, p_col))
    return jnp.where(borrow != 0, a, diff)


def _k_add(a, b):
    out, _ = _carry_prop(a + b)
    return _k_cond_sub_p(out)


def _k_sub(a, b):
    """a - b mod p via a + (p - b); both inputs reduced (p - 0 = p is
    normalized by the add's conditional subtract)."""
    def step(borrow, pbi):
        pi, bi = pbi
        cur = pi - bi - borrow
        return (cur >> 16) & np.uint32(1), cur & MASK16

    p_col = jnp.broadcast_to(_p_col(), b.shape)
    _, pb = jax.lax.scan(step, jnp.zeros_like(b[0]), (p_col, b))
    return _k_add(a, pb)


def _k_padd(p_arr, q_arr):
    """Complete RCB (alg. 7, a=0, b3=9) add on [48, T] arrays."""
    x1, y1, z1 = p_arr[:NL], p_arr[NL:2 * NL], p_arr[2 * NL:]
    x2, y2, z2 = q_arr[:NL], q_arr[NL:2 * NL], q_arr[2 * NL:]

    t0 = _k_mont_mul(x1, x2)
    t1 = _k_mont_mul(y1, y2)
    t2 = _k_mont_mul(z1, z2)
    m3 = _k_mont_mul(_k_add(x1, y1), _k_add(x2, y2))
    m4 = _k_mont_mul(_k_add(y1, z1), _k_add(y2, z2))
    m5 = _k_mont_mul(_k_add(x1, z1), _k_add(x2, z2))
    t3 = _k_sub(_k_sub(m3, t0), t1)
    t4 = _k_sub(_k_sub(m4, t1), t2)
    ycross = _k_sub(_k_sub(m5, t0), t2)

    t0_3 = _k_add(_k_add(t0, t0), t0)
    t2_2 = _k_add(t2, t2)
    t2_4 = _k_add(t2_2, t2_2)
    b3t2 = _k_add(_k_add(t2_4, t2_4), t2)          # 9*t2
    y_2 = _k_add(ycross, ycross)
    y_4 = _k_add(y_2, y_2)
    b3y = _k_add(_k_add(y_4, y_4), ycross)         # 9*ycross

    z3p = _k_add(t1, b3t2)
    t1m = _k_sub(t1, b3t2)

    x3a = _k_mont_mul(t4, b3y)
    x3b = _k_mont_mul(t3, t1m)
    y3a = _k_mont_mul(b3y, t0_3)
    y3b = _k_mont_mul(t1m, z3p)
    z3a = _k_mont_mul(t0_3, t3)
    z3b = _k_mont_mul(z3p, t4)

    return jnp.concatenate(
        [_k_sub(x3b, x3a), _k_add(y3b, y3a), _k_add(z3b, z3a)], axis=0)


def _padd_kernel(p_ref, q_ref, o_ref):
    o_ref[:, :] = _k_padd(p_ref[:, :], q_ref[:, :])


# module-level jitted entry points (trace-cache hygiene lint roots):
# analysis/trace_lint verifies each name below is a stable module-level
# jit; the pallas_call below lives INSIDE a jit-decorated function, so
# the outer jit caches its trace (exempt from TC-FRESH-JIT by design).
TRACE_JIT_ROOTS = ("_padd_soa_call", "msm_windows_soa")


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _padd_soa_call(p, q, block: int, interpret: bool):
    from jax.experimental import pallas as pl

    n = p.shape[1]
    grid = (n // block,)
    return pl.pallas_call(
        _padd_kernel,
        out_shape=jax.ShapeDtypeStruct((ROWS, n), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (0, i)),
            pl.BlockSpec((ROWS, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((ROWS, block), lambda i: (0, i)),
        interpret=interpret,
    )(p, q)


def _legal_block(n_pad: int, want: int) -> int:
    """Largest Mosaic-legal lane-block ≤ want: a multiple of LANE that
    divides n_pad (n_pad is lane-padded, so LANE itself always qualifies —
    the search can't fall below a legal shape). The sublane dim is the fixed
    ROWS=48 = 6 packed uint32 sublane tiles, legal by construction."""
    q = n_pad // LANE
    d = min(max(want // LANE, 1), q)
    while q % d:
        d -= 1
    return d * LANE


def padd_soa(p, q, block: int = 2048):
    """Complete projective add on SoA batches [48, N]; pads lanes to a
    multiple of 128 (padding lanes compute garbage and are sliced off)."""
    n = p.shape[1]
    n_pad = -(-n // LANE) * LANE
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n))
        p = jnp.pad(p, pad)
        q = jnp.pad(q, pad)
    out = _padd_soa_call(p, q, _legal_block(n_pad, block), _interpret())
    return out[:, :n] if n_pad != n else out


# ---------------------------------------------------------------------------
# MSM on SoA arrays (segmented-reduction Pippenger, as ops/msm.py)
# ---------------------------------------------------------------------------

def _segmented_bucket_sums_soa(points, digits, nbuckets: int):
    """points [48, n] (n a power of two), digits [n] in [0, nbuckets]
    (nbuckets = sentinel/skip) -> [48, nbuckets] bucket sums.

    Emission slots are laid out with stride nbuckets+1 per level: the last
    slot of each level's block is the trash slot for non-emitting lanes
    (sentinel pairs), discarded before the tree reduction."""
    n = points.shape[1]
    order = jnp.argsort(digits, stable=True)
    buckets = digits[order]
    pts = points[:, order]
    levels = n.bit_length() - 1
    stride = nbuckets + 1

    emissions = inf_soa((levels + 1) * stride)
    for lvl in range(levels):
        left, right = pts[:, 0::2], pts[:, 1::2]
        bl, br = buckets[0::2], buckets[1::2]
        same = bl == br
        merged = padd_soa(left, right)
        pts = jnp.where(same[None, :], merged, right)
        emit_idx = lvl * stride + jnp.where(same, nbuckets, bl)
        emissions = emissions.at[:, emit_idx].set(left, mode="drop")
        buckets = br
    emissions = emissions.at[:, levels * stride + buckets[0]].set(
        pts[:, 0], mode="drop")

    # drop trash slots, tree-reduce over the level axis
    acc = emissions.reshape(ROWS, levels + 1, stride)[:, :, :nbuckets]
    k = levels + 1
    while k > 1:
        half = k // 2
        merged = padd_soa(
            acc[:, :half].reshape(ROWS, half * nbuckets),
            acc[:, half:2 * half].reshape(ROWS, half * nbuckets),
        ).reshape(ROWS, half, nbuckets)
        acc = (jnp.concatenate([merged, acc[:, 2 * half:]], axis=1)
               if k % 2 else merged)
        k = acc.shape[1]
    return acc[:, 0]


def _aggregate_buckets_soa(bucket_sums, c: int):
    """sum_b b * B_b: bucket_sums [48, nwin, nbuckets] -> [48, nwin].

    High-to-low over digit bits: acc = 2*acc + sum(buckets with bit j set)."""
    nwin, nbuckets = bucket_sums.shape[1], bucket_sums.shape[2]
    idx = jnp.arange(nbuckets)
    inf1 = inf_soa(1)[:, None, :]                      # [48, 1, 1]
    acc = inf_soa(nwin)
    for j in range(c - 1, -1, -1):
        acc = padd_soa(acc, acc)
        mask = ((idx >> j) & 1).astype(bool)
        cur = jnp.where(mask[None, None, :], bucket_sums, inf1)
        k = nbuckets
        while k > 1:
            half = k // 2
            merged = padd_soa(
                cur[:, :, :half].reshape(ROWS, nwin * half),
                cur[:, :, half:2 * half].reshape(ROWS, nwin * half),
            ).reshape(ROWS, nwin, half)
            cur = (jnp.concatenate([merged, cur[:, :, 2 * half:]], axis=2)
                   if k % 2 else merged)
            k = cur.shape[2]
        acc = padd_soa(acc, cur[:, :, 0])
    return acc


@functools.partial(jax.jit, static_argnums=(2,))
def msm_windows_soa(points, scalars, c: int):
    """Per-window partial MSM sums: points [48, n] SoA Montgomery, scalars
    [n, 16] standard-form 16-bit limbs -> [48, nwin]."""
    from . import msm as MSM

    nwin = (254 + c - 1) // c
    nbuckets = 1 << c
    n = points.shape[1]
    n_pad = max(1 << ((n - 1).bit_length() if n > 1 else 1), LANE)
    if n_pad != n:
        points = jnp.concatenate([points, inf_soa(n_pad - n)], axis=1)

    def one_window(w):
        d = MSM._digits_traced(scalars, w, c)
        if n_pad != n:
            d = jnp.concatenate(
                [d, jnp.full((n_pad - n,), nbuckets, dtype=d.dtype)])
        return _segmented_bucket_sums_soa(points, d, nbuckets)

    sums = jax.lax.map(one_window, jnp.arange(nwin))     # [nwin, 48, nb]
    return _aggregate_buckets_soa(jnp.transpose(sums, (1, 0, 2)), c)


def combine_windows_soa(window_sums, c: int):
    """[48, nwin] -> affine host result via the AoS combine (tiny workload:
    c doublings + 1 add per window — not worth a kernel)."""
    from . import msm as MSM

    return MSM.combine_windows(from_soa(window_sums), c)


def msm_soa(points, scalars, c: int | None = None):
    """Full MSM: points [48, n] SoA Montgomery, scalars [n, 16] standard
    limbs. Returns [3, 16] projective Montgomery (AoS, as ops/msm.msm)."""
    n = points.shape[1]
    if c is None:
        from . import msm as MSM
        c = MSM.default_window(n)
    return combine_windows_soa(msm_windows_soa(points, scalars, c), c)

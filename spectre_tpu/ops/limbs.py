"""Host-side conversion between Python ints and 16-bit limb tensors.

Device convention: a 256-bit value is [..., 16] uint32, little-endian 16-bit
limbs (each entry < 2^16). This is the wire format between the host (Python
ints / the native C++ lib's 4x64 limbs) and device kernels.
"""

from __future__ import annotations

import numpy as np

NLIMBS = 16
LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1


def ints_to_limbs16(vals) -> np.ndarray:
    """Iterable of ints -> [n, 16] uint32 (16-bit limbs, little-endian)."""
    vals = list(vals)
    out = np.zeros((len(vals), NLIMBS), dtype=np.uint32)
    for i, v in enumerate(vals):
        v = int(v)
        for j in range(NLIMBS):
            out[i, j] = (v >> (LIMB_BITS * j)) & LIMB_MASK
    return out


def limbs16_to_ints(arr: np.ndarray) -> list[int]:
    """[..., 16] limb array -> list of ints (leading axes flattened)."""
    arr = np.asarray(arr, dtype=np.uint64).reshape(-1, NLIMBS)
    return [sum(int(row[j]) << (LIMB_BITS * j) for j in range(NLIMBS)) for row in arr]


def int_to_limbs16(v: int) -> np.ndarray:
    return ints_to_limbs16([v])[0]


def u64limbs_to_u16limbs(arr: np.ndarray) -> np.ndarray:
    """[n, 4] uint64 (native lib format) -> [n, 16] uint32 16-bit limbs."""
    arr = np.asarray(arr, dtype=np.uint64)
    n = arr.shape[0]
    out = np.zeros((n, NLIMBS), dtype=np.uint32)
    for j in range(4):
        limb = arr[:, j]
        for k in range(4):
            out[:, 4 * j + k] = (limb >> np.uint64(16 * k)).astype(np.uint64) & np.uint64(0xFFFF)
    return out


def u16limbs_to_u64limbs(arr: np.ndarray) -> np.ndarray:
    """[n, 16] uint32 16-bit limbs -> [n, 4] uint64 (native lib format)."""
    arr = np.asarray(arr, dtype=np.uint64)
    n = arr.shape[0]
    out = np.zeros((n, 4), dtype=np.uint64)
    for j in range(4):
        acc = np.zeros(n, dtype=np.uint64)
        for k in range(4):
            acc |= (arr[:, 4 * j + k] & np.uint64(0xFFFF)) << np.uint64(16 * k)
        out[:, j] = acc
    return out

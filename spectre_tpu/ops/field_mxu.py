"""Montgomery multiplication as MXU matmuls (int8-limb formulation).

SURVEY.md §7 hard part 2: the systolic array, not the VPU, is where TPU
FLOPs live — but bignum multiply is a *convolution* of limb vectors, which
is bilinear, not linear. The mapping used here:

    conv(a, b)[k] = sum_{i+j=k} a_i * b_j
                  = reshape(outer(a, b), [L*L]) @ S        (one matmul)

where `S` is the constant one-hot [L*L, 2L] matrix with S[(i,j), i+j] = 1.
The outer product is an elementwise broadcast multiply (VPU, O(L^2) int32
MACs per element); the REDUCTION — the O(L^2) accumulate that dominates the
schoolbook/CIOS op count — becomes a [N, L*L] @ [L*L, 2L] matmul with a
large batch dimension N: exactly the shape XLA tiles onto the MXU
(contraction 1024, output 64, M = batch). Limbs are 8-bit so every partial
product fits comfortably: max a_i*b_j = 255^2 < 2^16, column sums < L * 2^16
+ carries < 2^22 « int32.

One Montgomery product a*b*R^-1 (R = 2^256) is three such multiplies
(separated operand scanning, Montgomery's original form):

    t  = a * b                      (full 512-bit product)
    m  = (t mod R) * p' mod R       (low half only, p' = -p^-1 mod R)
    out = (t + m * p) / R           (full product + shift)

~3L^2 = 3072 8-bit MACs vs CIOS's 512 16-bit VPU MACs — more raw MACs, but
on MXU lanes instead of VPU lanes (v5e: 394 Tops int8 MXU vs ~4 Tops VPU),
so the formulation wins whenever the matmul actually lands on the MXU.
On CPU (XLA:CPU) the same graph is exact but slower than CIOS — this module
is therefore opt-in: set SPECTRE_FIELD_IMPL=mxu or call `enable()`
(BASELINE.md records both paths; the tunnel-wedged fallback criterion is
CPU-validated exactness, which `tests/test_ops.py::TestMxuField` pins).

Layout compatibility: public entry points take and return the SAME
[..., 16]-limb uint32 tensors as `field_ops` — conversion to/from the
internal [..., 32] 8-bit layout is two cheap vectorized bit ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import field_ops as F

L8 = 32          # 8-bit limbs per 256-bit value
MASK8 = np.uint32(0xFF)


@functools.cache
def conv_matrix(la: int, lb: int, out_cols: int) -> np.ndarray:
    """One-hot [la*lb, out_cols] reduction matrix: (i,j) -> column i+j.
    Truncating out_cols below la+lb-1 drops high columns (mod-2^(8*out)
    products for the Montgomery m step). Shared with the matmul-NTT short
    transform in ops/ntt.py, which convolves 33-limb reduction constants
    against 32-limb data."""
    S = np.zeros((la * lb, out_cols), dtype=np.int32)
    for i in range(la):
        for j in range(lb):
            k = i + j
            if k < out_cols:
                S[i * lb + j, k] = 1
    return S


def _conv_matrix(full: bool) -> np.ndarray:
    return conv_matrix(L8, L8, 2 * L8 if full else L8)


class MxuCtx:
    """Per-modulus constants in the 8-bit-limb domain."""

    def __init__(self, ctx: F.FieldCtx):
        self.base = ctx
        p = ctx.p
        self.p8 = np.array([(p >> (8 * i)) & 0xFF for i in range(L8)],
                           dtype=np.int32)
        pinv = (-pow(p, -1, 1 << 256)) % (1 << 256)   # p' = -p^-1 mod R
        self.pinv8 = np.array([(pinv >> (8 * i)) & 0xFF for i in range(L8)],
                              dtype=np.int32)


@functools.cache
def _mxu_ctx(name: str) -> MxuCtx:
    base = {"bn254_fr": F.fr_ctx, "bn254_fq": F.fq_ctx}[name]()
    return MxuCtx(base)


def _to8(a):
    """[..., 16] uint32 16-bit limbs -> [..., 32] int32 8-bit limbs."""
    lo = (a & MASK8).astype(jnp.int32)
    hi = ((a >> 8) & MASK8).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(*a.shape[:-1], L8)

def _from8(a8):
    """[..., 32] int32 8-bit limbs (< 2^8) -> [..., 16] uint32 16-bit limbs."""
    pairs = a8.reshape(*a8.shape[:-1], 16, 2).astype(jnp.uint32)
    return pairs[..., 0] | (pairs[..., 1] << 8)


def _carry8(t, out_limbs: int):
    """Carry-propagate a [..., k] int32 column tensor into `out_limbs` 8-bit
    limbs (little-endian), dropping any final carry overflowing out_limbs
    (callers size out_limbs so it never does)."""
    tT = jnp.moveaxis(t, -1, 0)

    def step(carry, ti):
        cur = ti + carry
        return cur >> 8, cur & jnp.int32(0xFF)

    carry, outs = jax.lax.scan(step, jnp.zeros_like(tT[0]), tT)
    outs = jnp.moveaxis(outs, 0, -1)
    k = outs.shape[-1]
    if k < out_limbs:
        # remaining carry extends into higher limbs
        ext = []
        for _ in range(out_limbs - k):
            ext.append(carry & 0xFF)
            carry = carry >> 8
        outs = jnp.concatenate([outs] + [e[..., None] for e in ext], axis=-1)
    return outs[..., :out_limbs]


def mul_columns(a8, b8, out_cols: int):
    """Raw column products via the one-hot matmul; no carries yet.
    a8: [..., la], b8: [..., lb] int32 (entries < 2^8). Returns
    [..., out_cols] int32 convolution columns."""
    la, lb = a8.shape[-1], b8.shape[-1]
    outer = a8[..., :, None] * b8[..., None, :]           # [..., la, lb] VPU
    flat = outer.reshape(*outer.shape[:-2], la * lb)
    S = conv_matrix(la, lb, out_cols)
    # [N, la*lb] @ [la*lb, out]: the MXU-shaped reduction
    return jax.lax.dot_general(
        flat, S, (((flat.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def _mul_columns(a8, b8, full: bool):
    return mul_columns(a8, b8, 2 * L8 if full else L8)


def mont_mul(ctx: F.FieldCtx, a, b):
    """Drop-in replacement for `field_ops.mont_mul` (same layout, same
    Montgomery form): 3 matmul-multiplies + carries."""
    mc = _mxu_ctx(ctx.name)
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a8 = _to8(jnp.broadcast_to(a, shape))
    b8 = _to8(jnp.broadcast_to(b, shape))

    # t = a * b, 64 columns; carried to 64 8-bit limbs
    t_cols = _mul_columns(a8, b8, full=True)
    t8 = _carry8(t_cols, 2 * L8)
    t_lo, t_hi = t8[..., :L8], t8[..., L8:]

    # m = t_lo * p' mod 2^256 (low-half product)
    m_cols = _mul_columns(t_lo, jnp.broadcast_to(mc.pinv8, t_lo.shape),
                          full=False)
    m8 = _carry8(m_cols, L8)

    # u = (t + m*p) / 2^256. Low half of t + m*p is 0 by construction; the
    # carry out of the low half is what must flow into the high half. Add
    # the low columns t_lo + (m*p)_lo, propagate, keep ONLY the carry.
    mp_cols = _mul_columns(m8, jnp.broadcast_to(mc.p8, m8.shape), full=True)
    low_sum = mp_cols[..., :L8] + t_lo
    lowT = jnp.moveaxis(low_sum, -1, 0)

    def step(carry, ti):
        cur = ti + carry
        return cur >> 8, cur & jnp.int32(0xFF)

    carry_low, _ = jax.lax.scan(step, jnp.zeros_like(lowT[0]), lowT)

    hi_cols = mp_cols[..., L8:] + t_hi
    hi_cols = hi_cols.at[..., 0].add(carry_low)
    # u = (t + m*p)/R < 2p < 2^255: 32 8-bit limbs suffice
    u8 = _carry8(hi_cols, L8)
    res16 = _from8(u8.astype(jnp.uint32))
    return F._cond_sub_p(ctx, res16)


def enabled() -> bool:
    import os
    return os.environ.get("SPECTRE_FIELD_IMPL") == "mxu"

"""Device kernels (JAX/XLA/Pallas): the TPU compute path.

Field elements are limb tensors: [..., 16] uint32 arrays holding 16-bit limbs
(little-endian), Montgomery form with R = 2^256 — the same R as the native C++
library, so host<->device conversions are pure bit movement. 16-bit limbs are
the TPU-native choice: products of two limbs fit uint32 (no 64-bit multiply on
TPU), and column accumulations stay far below 2^32.

Modules:
  limbs      int <-> limb-tensor conversion (numpy, host side)
  field_ops  Montgomery arithmetic on limb tensors (vectorized, jit-able)
  ntt        radix-2 NTT/iNTT over BN254 Fr with per-stage twiddle tables
  ec         batched BN254 G1 jacobian arithmetic (branchless select form)
  msm        Pippenger MSM: sort + padded-gather + tree reduction
  sha256     batched SHA256 over u32 lanes (witness hashing, N6)
  poseidon   batched Poseidon permutation over Fr (N7) + native params
"""

"""Batched SHA256 over u32 lanes (witness hashing kernel, N6).

Reference parity: `zkevm-hashes` `generate_witnesses_sha256` + the sha2 crate
(SURVEY.md §2b N6) — the prover hashes ~1000+ 64-byte blocks per proof (SSZ
merkleization, signing roots, pubkey roots). Here one vectorized compression
processes every block in the batch simultaneously; the 64 rounds run as a
lax.scan with a rolling 16-word message-schedule window.

Host-side padding helpers mirror FIPS 180-4; oracle = hashlib.sha256.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

H0 = np.array([0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
               0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19], dtype=np.uint32)

K = np.array([
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2], dtype=np.uint32)


def _rotr(x, r):
    return (x >> r) | (x << (32 - r))


def compress(state: jax.Array, blocks: jax.Array) -> jax.Array:
    """One SHA256 compression: state [n, 8] u32, blocks [n, 16] u32 -> [n, 8]."""
    a, b, c, d, e, f, g, h = [state[:, i] for i in range(8)]
    win = jnp.moveaxis(blocks, 1, 0)  # [16, n] rolling schedule window

    def rnd(carry, kt):
        a, b, c, d, e, f, g, h, win = carry
        wt = win[0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        sig0 = _rotr(win[1], 7) ^ _rotr(win[1], 18) ^ (win[1] >> 3)
        sig1 = _rotr(win[14], 17) ^ _rotr(win[14], 19) ^ (win[14] >> 10)
        nxt = sig1 + win[9] + sig0 + win[0]
        win = jnp.concatenate([win[1:], nxt[None]], axis=0)
        return (t1 + t2, a, b, c, d + t1, e, f, g, win), None

    carry = (a, b, c, d, e, f, g, h, win)
    carry, _ = jax.lax.scan(rnd, carry, jnp.asarray(K))
    na, nb, nc, nd, ne, nf, ng, nh = carry[:8]
    return state + jnp.stack([na, nb, nc, nd, ne, nf, ng, nh], axis=1)


def sha256_blocks(blocks: jax.Array) -> jax.Array:
    """Hash [n, nblocks, 16] u32 pre-padded messages -> [n, 8] digests.

    nblocks is static; chaining over blocks is a host loop (small)."""
    n = blocks.shape[0]
    state = jnp.broadcast_to(jnp.asarray(H0), (n, 8))
    for i in range(blocks.shape[1]):
        state = compress(state, blocks[:, i])
    return state


def hash_pairs(left: jax.Array, right: jax.Array) -> jax.Array:
    """SSZ merkle node hash: sha256(left || right) of 32-byte nodes as [n, 8]
    u32 words -> [n, 8]. 64-byte message = 1 data block + 1 constant pad block."""
    n = left.shape[0]
    block1 = jnp.concatenate([left, right], axis=1)
    pad = np.zeros(16, dtype=np.uint32)
    pad[0] = 0x80000000
    pad[15] = 512  # message length in bits
    state = compress(jnp.broadcast_to(jnp.asarray(H0), (n, 8)), block1)
    return compress(state, jnp.broadcast_to(jnp.asarray(pad), (n, 16)))


# ---------------------------------------------------------------------------
# host-side packing helpers
# ---------------------------------------------------------------------------

def pad_message(msg: bytes) -> np.ndarray:
    """FIPS 180-4 padding -> [nblocks, 16] uint32 (big-endian words)."""
    ln = len(msg)
    msg = msg + b"\x80"
    while (len(msg) % 64) != 56:
        msg += b"\x00"
    msg += (8 * ln).to_bytes(8, "big")
    arr = np.frombuffer(msg, dtype=">u4").astype(np.uint32)
    return arr.reshape(-1, 16)


def bytes32_to_words(b: bytes) -> np.ndarray:
    assert len(b) == 32
    return np.frombuffer(b, dtype=">u4").astype(np.uint32)


def words_to_bytes32(w) -> bytes:
    return np.asarray(w, dtype=np.uint32).astype(">u4").tobytes()


def sha256_many(msgs: list[bytes]) -> list[bytes]:
    """Batched hash of equal-length byte messages (host convenience)."""
    assert msgs and all(len(m) == len(msgs[0]) for m in msgs)
    blocks = np.stack([pad_message(m) for m in msgs])  # [n, nb, 16]
    out = sha256_blocks(jnp.asarray(blocks))
    return [words_to_bytes32(row) for row in np.asarray(out)]

"""Batched, moded NTT / iNTT over BN254 Fr on limb tensors (device kernel N3).

Reference parity: halo2's FFT (`halo2_proofs` best_fft, SURVEY.md §2b N3),
rebuilt as a moded, batched pipeline mirroring the MSM-modes design
(`ops/msm.py`):

* **Batched many-polynomial transforms** — every entry point is shape-
  generic over leading batch axes (`[..., n, 16]`), and `ntt_many` /
  `intt_many` transform a whole `[B, n, 16]` column stack in ONE compiled
  kernel with shared per-stage twiddles. The prover's commit phase and the
  device quotient previously dispatched one kernel per column; per-op
  dispatch overhead (a 16-round CIOS scan per butterfly stage) amortizes
  over the batch instead.
* **`SPECTRE_NTT_MODE=radix2|fourstep`** — `radix2` is the iterative
  Cooley-Tukey kernel (log n fully-vectorized butterfly stages over the
  whole array); `fourstep` is the single-device Bailey split (row NTTs →
  twiddle mult → transpose → column NTTs) reusing the exact decomposition
  and twiddle matrix of `parallel/sharded_ntt.py` — the MXU-shaped layout
  of "Enabling AI ASICs for ZKP" (PAPERS.md, arXiv:2604.17808): two
  batches of short NTTs plus one elementwise/transpose step instead of
  log n sequential full-array gather stages. Both modes produce
  BYTE-IDENTICAL results (exact canonical field arithmetic; pinned by
  tests/test_ntt_modes.py), they differ only in work shape.
* **Fused coset-LDE** — the `mont_mul(coeffs, g^i)` coset pre-scale folds
  into stage 0 of the NTT (the stage-0 twiddle is 1, so the scale multiply
  REPLACES a previously wasted multiply-by-one), making
  `coset_ntt`/`coset_lde_std` one kernel instead of scale-then-NTT. The
  inverse path gets the same treatment: `coset_intt` multiplies once by a
  combined `g^{-i}·n^{-1}` table, and the `_std` variants additionally fold
  the Montgomery boundary conversion into the same table (std→mont+scale on
  the way in, mont→std+unscale+1/n on the way out) — zero extra elementwise
  passes for the quotient pipeline.
* **Budgeted twiddle/coset tables** — stage twiddles, four-step twiddle
  matrices and coset power tables live in a byte-budgeted LRU
  (`SPECTRE_NTT_TABLE_MB`, reusing `ops/msm.py:_TableLRU`) keyed on
  `(kind, k, omega/g)`. A long-running prover service touching many
  circuit sizes must not grow host memory per size it ever saw; eviction
  costs recompute time, never correctness.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import bn254
from . import field_ops as F
from .msm import _TableLRU, _record_event

R = bn254.R

NTT_MODES = ("radix2", "fourstep")

# fourstep needs at least one row stage and one column stage
_FOURSTEP_MIN_LOGN = 2


def ntt_mode() -> str:
    """Active NTT mode from SPECTRE_NTT_MODE (default: radix2). Read per
    call — the jitted kernels key on the mode as a static argument, so
    flipping the env between calls retraces correctly."""
    mode = os.environ.get("SPECTRE_NTT_MODE", "radix2")
    if mode not in NTT_MODES:
        raise ValueError(
            f"SPECTRE_NTT_MODE={mode!r}: expected one of {NTT_MODES}")
    return mode


def _resolve_mode(mode: str | None, logn: int) -> str:
    m = mode if mode is not None else ntt_mode()
    if m not in NTT_MODES:
        raise ValueError(f"unknown NTT mode {m!r}")
    if m == "fourstep" and logn < _FOURSTEP_MIN_LOGN:
        return "radix2"              # nothing to split
    return m


# ---------------------------------------------------------------------------
# budgeted twiddle / coset tables (host-side LRU, numpy entries)
# ---------------------------------------------------------------------------

def _table_budget_bytes() -> int:
    mb = os.environ.get("SPECTRE_NTT_TABLE_MB")
    if mb is not None:
        return int(mb) << 20
    try:
        with open("/proc/meminfo") as f:
            total = int(f.readline().split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return 1 << 30
    return min(1 << 30, int(total * 0.10))


_TABLES = _TableLRU(_table_budget_bytes(), label="ntt twiddle/coset table",
                    budget_var="SPECTRE_NTT_TABLE_MB",
                    on_event=_record_event)


def lru_stats() -> dict:
    """Twiddle/coset table cache stats for GET /metrics."""
    return _TABLES.stats()


@functools.cache
def _bitrev(logn: int) -> np.ndarray:
    n = 1 << logn
    idx = np.arange(n)
    rev = np.zeros(n, dtype=np.int32)
    for b in range(logn):
        rev |= ((idx >> b) & 1) << (logn - 1 - b)
    return rev


def _stage_twiddles(logn: int, omega: int):
    """Montgomery twiddle tables per stage: stage s has m=2^s butterflies per
    block, twiddle_j = omega^(n/(2m) * j), j < m. LRU-cached per (k, omega);
    entries are numpy so they lift to fresh embedded constants per trace."""
    key = ("stage", logn, omega)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    tables = []
    for s in range(logn):
        m = 1 << s
        w = pow(omega, n // (2 * m), R)
        powers = [1] * m
        for j in range(1, m):
            powers[j] = powers[j - 1] * w % R
        tables.append(ctx.encode(powers))
    return _TABLES.put(key, None, tuple(tables))


def _twiddle_matrix(logr: int, logc: int, omega: int) -> np.ndarray:
    """Montgomery [Rr, Cc, 16] table of omega^(jr*kc) — the four-step
    inter-pass twiddles, shared with `parallel/sharded_ntt.py`. The prover
    reuses one omega per domain, so this is a one-time cost per circuit
    size; LRU-budgeted so a service touching many sizes stays bounded."""
    key = ("mat", logr, logc, omega)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    from ..native import host

    rr, cc = 1 << logr, 1 << logc
    ctx = F.fr_ctx()
    rows = np.empty((rr, cc, 16), dtype=np.uint32)
    for jr in range(rr):
        w = pow(omega, jr, R)
        rows[jr] = ctx.encode_np(
            host.limbs_to_ints(host.fp_powers(host.FR, w, cc)))
    return _TABLES.put(key, None, rows)


def _power_table(logn: int, g: int) -> np.ndarray:
    """[n, 16] Montgomery table of g^i (host-computed once, LRU-cached)."""
    key = ("pow", logn, g)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    powers = [1] * n
    for i in range(1, n):
        powers[i] = powers[i - 1] * g % R
    return _TABLES.put(key, None, ctx.encode(powers))


def _fused_in_table(logn: int, g: int | None) -> np.ndarray:
    """Stage-0 pre-scale table for the coset-LDE entry fusions.

    g given: encode(g^i · R) — one mont_mul takes a STANDARD-form input to
    Montgomery form AND applies the coset scale (mont_mul(x_std, enc(g^i·R))
    = x·g^i·R²·R^{-1} = mont(g^i·x)); g None: encode(R) row, the plain
    std→mont conversion fused the same way."""
    key = ("fin", logn, g)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    r = ctx.r_mod_p
    if g is None:
        tab = ctx.encode([r])                # [1, 16], broadcasts
    else:
        n = 1 << logn
        vals = [0] * n
        acc = r
        for i in range(n):
            vals[i] = acc
            acc = acc * g % R
        tab = ctx.encode(vals)
    return _TABLES.put(key, None, tab)


def _fused_out_table(logn: int, g: int | None, std: bool) -> np.ndarray:
    """Post-NTT multiply table for the inverse path, folding up to three
    elementwise passes into one: the 1/n iNTT scale, the inverse coset
    unscale g^{-i} (when g is given), and — for std=True — the Montgomery →
    standard conversion (the table is left UN-encoded, so mont_mul(v_mont,
    t_std) = v·t in standard form directly)."""
    key = ("fout", logn, g, std)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    ninv = pow(n, -1, R)
    if g is None:
        vals = [ninv]                        # [1, 16], broadcasts
    else:
        ginv = pow(g, -1, R)
        vals = [0] * n
        acc = ninv
        for i in range(n):
            vals[i] = acc
            acc = acc * ginv % R
    if std:
        from . import limbs as L
        tab = L.ints_to_limbs16(vals)        # raw values: output is standard
    else:
        tab = ctx.encode(vals)
    return _TABLES.put(key, None, tab)


# ---------------------------------------------------------------------------
# core transforms (shape-generic over leading batch axes)
# ---------------------------------------------------------------------------

def _ntt_stages(a, logn: int, omega: int, scale=None):
    """Iterative radix-2 Cooley-Tukey over axis -2 of a [..., n, 16]
    Montgomery limb tensor; leading axes are batch.

    `scale` ([n, 16] or [1, 16] numpy) folds an elementwise pre-multiply
    into stage 0: the stage-0 twiddle is 1 (its multiply is skipped — exact
    for canonical inputs, mont_mul by one_mont is the identity), so the
    fused path costs the same butterfly work as the plain transform while
    replacing the separate scale-then-NTT dispatch."""
    ctx = F.fr_ctx()
    n = 1 << logn
    rev = jnp.asarray(_bitrev(logn))
    a = jnp.take(a, rev, axis=-2)
    if scale is not None:
        s = np.asarray(scale)
        if s.shape[0] == n:                  # permute alongside the data
            s = s[np.asarray(_bitrev(logn))]
        a = F.mont_mul(ctx, a, jnp.asarray(s))
    tables = _stage_twiddles(logn, omega)
    for s_i in range(logn):
        m = 1 << s_i
        blk = a.reshape(a.shape[:-2] + (n // (2 * m), 2, m, F.NLIMBS))
        u = blk[..., 0, :, :]
        v = blk[..., 1, :, :]
        if s_i:                              # stage-0 twiddle is 1: skip
            v = F.mont_mul(ctx, v, jnp.asarray(tables[s_i]))
        a = jnp.stack([F.add(ctx, u, v), F.sub(ctx, u, v)],
                      axis=-3).reshape(a.shape[:-2] + (n, F.NLIMBS))
    return a


def _ntt_fourstep(a, logn: int, omega: int, scale=None):
    """Single-device four-step (Bailey) NTT of [..., n, 16]: view x as an
    Rr x Cc matrix (A[jr, jc] = x[jc*Rr + jr]), length-Cc row NTTs, the
    omega^(jr*kc) twiddle multiply, a transpose, then length-Rr row NTTs —
    the exact decomposition `parallel/sharded_ntt.py` shards over a mesh,
    here kept on one device: log n sequential full-array gather stages
    become two batches of short NTTs plus one MXU-shaped elementwise +
    transpose step. Output is natural order, byte-identical to radix2."""
    ctx = F.fr_ctx()
    logr = logn // 2
    logc = logn - logr
    rr, cc = 1 << logr, 1 << logc
    omega_row = pow(omega, rr, R)            # length-Cc root (step 1)
    omega_col = pow(omega, cc, R)            # length-Rr root (step 4)
    tw = _twiddle_matrix(logr, logc, omega)

    lead = a.shape[:-2]
    # A[jr, jc] = x[jc*rr + jr]
    A = jnp.moveaxis(a.reshape(lead + (cc, rr, F.NLIMBS)), -2, -3)
    if scale is not None:
        s = np.asarray(scale)
        if s.shape[0] == (1 << logn):
            s = np.moveaxis(s.reshape(cc, rr, F.NLIMBS), -2, -3)
        A = F.mont_mul(ctx, A, jnp.asarray(s))
    y = _ntt_stages(A, logc, omega_row)      # step 1: row NTTs (rr batched)
    y = F.mont_mul(ctx, y, jnp.asarray(tw))  # step 2: twiddle
    y = jnp.moveaxis(y, -2, -3)              # step 3: transpose
    y = _ntt_stages(y, logr, omega_col)      # step 4: column NTTs
    # y[kc, kr] = X[kr*cc + kc] -> natural order
    return jnp.moveaxis(y, -2, -3).reshape(a.shape)


def _ntt_nd(a, logn: int, omega: int, scale=None, mode: str = "radix2"):
    if mode == "fourstep":
        return _ntt_fourstep(a, logn, omega, scale)
    return _ntt_stages(a, logn, omega, scale)


def _logn_of(a) -> int:
    n = a.shape[-2]
    logn = n.bit_length() - 1
    assert 1 << logn == n, "transform length must be a power of two"
    return logn


# jitted entry kernels: (g, kinds, mode) are static so env flips retrace;
# tables resolve host-side at trace time and embed as constants


def _batch_rows(a, body):
    """Apply `body` ([n, 16] -> [n, 16]) over the leading batch axes.

    On CPU the columns run SEQUENTIALLY inside the one compiled program
    (lax.map): a 2^14 column's stage working set is ~1 MB and stays
    cache-hot across its log n stages, where the fully vectorized [B, n]
    layout streams B x that per stage and falls out of cache (measured:
    vectorized batch = 0.89x of a jitted per-column loop on the 1-core
    reference box; map = one dispatch AND per-column locality). Real
    vector machines keep the vectorized layout — the batch axis is what
    fills the VPU. Trace-time host decision; both layouts are the same
    exact arithmetic, so results are byte-identical either way."""
    if a.ndim == 2:
        return body(a)
    if jax.default_backend() == "cpu":
        flat = a.reshape((-1,) + a.shape[-2:])
        return jax.lax.map(body, flat).reshape(a.shape)
    return body(a)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _fwd_kernel(a, omega: int, in_kind, mode: str):
    """in_kind: None (mont input, no scale), ("mont", g) fused coset
    pre-scale on a Montgomery input, ("std", g_or_None) standard-form input
    with the boundary conversion (+ optional coset scale) fused in."""
    logn = _logn_of(a)
    if in_kind is None:
        scale = None
    elif in_kind[0] == "mont":
        scale = _power_table(logn, in_kind[1])
    else:
        scale = _fused_in_table(logn, in_kind[1])
    return _batch_rows(a, lambda row: _ntt_nd(row, logn, omega, scale, mode))


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _inv_kernel(a, omega: int, g, std: bool, mode: str):
    """Inverse transform of [..., n, 16]: forward with omega^{-1}, then ONE
    fused multiply by the combined (1/n, g^{-i}, mont→std) table."""
    logn = _logn_of(a)
    omega_inv = pow(omega, -1, R)
    tab = _fused_out_table(logn, g, std)

    def body(row):
        res = _ntt_nd(row, logn, omega_inv, None, mode)
        return F.mont_mul(F.fr_ctx(), res, jnp.asarray(tab))

    return _batch_rows(a, body)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def ntt(a: jax.Array, omega: int, mode: str | None = None) -> jax.Array:
    """NTT of a [n, 16] Montgomery limb tensor; returns evaluations in
    natural order. omega must be a primitive n-th root of unity (host int).
    mode defaults to SPECTRE_NTT_MODE (see `ntt_mode`)."""
    return _fwd_kernel(a, omega, None, _resolve_mode(mode, _logn_of(a)))


def ntt_many(a: jax.Array, omega: int, mode: str | None = None) -> jax.Array:
    """Batched NTT of a [B, n, 16] stack in one compiled kernel: every
    butterfly stage processes all B polynomials with shared twiddles."""
    return _fwd_kernel(a, omega, None, _resolve_mode(mode, _logn_of(a)))


def intt(a: jax.Array, omega: int, mode: str | None = None) -> jax.Array:
    """Inverse NTT: forward with omega^{-1}, then scale by n^{-1}."""
    return _inv_kernel(a, omega, None, False,
                       _resolve_mode(mode, _logn_of(a)))


def intt_many(a: jax.Array, omega: int, mode: str | None = None) -> jax.Array:
    """Batched inverse NTT of a [B, n, 16] stack (see `ntt_many`)."""
    return _inv_kernel(a, omega, None, False,
                       _resolve_mode(mode, _logn_of(a)))


def coset_ntt(a: jax.Array, omega: int, g: int,
              mode: str | None = None) -> jax.Array:
    """Fused coset-LDE: evaluations of a on g*<omega> in ONE kernel — the
    g^i pre-scale rides stage 0 of the NTT instead of a separate pass."""
    return _fwd_kernel(a, omega, ("mont", g),
                       _resolve_mode(mode, _logn_of(a)))


def coset_intt(a: jax.Array, omega: int, g: int,
               mode: str | None = None) -> jax.Array:
    """Fused inverse coset-LDE: one combined g^{-i}·n^{-1} multiply after
    the inverse transform (two elementwise passes become one)."""
    return _inv_kernel(a, omega, g, False, _resolve_mode(mode, _logn_of(a)))


def coset_ntt_many(a: jax.Array, omega: int, g: int,
                   mode: str | None = None) -> jax.Array:
    """Batched fused coset-LDE over a [B, n, 16] stack."""
    return _fwd_kernel(a, omega, ("mont", g),
                       _resolve_mode(mode, _logn_of(a)))


def coset_intt_many(a: jax.Array, omega: int, g: int,
                    mode: str | None = None) -> jax.Array:
    return _inv_kernel(a, omega, g, False, _resolve_mode(mode, _logn_of(a)))


def coset_lde_std(a_std: jax.Array, omega: int, g: int | None,
                  mode: str | None = None) -> jax.Array:
    """Coset-LDE of STANDARD-form limb input ([..., n, 16]): the std→mont
    boundary conversion and the coset scale fold into one stage-0 table, so
    the whole quotient-phase `to_ext` is a single kernel. Returns Montgomery
    evaluations (the quotient keeps working in Montgomery form)."""
    return _fwd_kernel(a_std, omega, ("std", g),
                       _resolve_mode(mode, _logn_of(a_std)))


def coset_intt_std(a: jax.Array, omega: int, g: int | None,
                   mode: str | None = None) -> jax.Array:
    """Inverse coset-LDE emitting STANDARD-form limbs: 1/n, g^{-i} and the
    mont→std conversion are ONE multiply by a raw (un-encoded) table."""
    return _inv_kernel(a, omega, g, True, _resolve_mode(mode, _logn_of(a)))


def coset_scale(a: jax.Array, g: int, inverse: bool = False) -> jax.Array:
    """a_i *= g^i (or g^{-i}) — the unfused building block, kept for
    composition outside the NTT (and for oracle tests of the fusion)."""
    ctx = F.fr_ctx()
    logn = _logn_of(a)
    tab = _power_table(logn, pow(g, -1, R) if inverse else g)
    return F.mont_mul(ctx, a, jnp.asarray(tab))

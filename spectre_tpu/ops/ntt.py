"""Batched, moded NTT / iNTT over BN254 Fr on limb tensors (device kernel N3).

Reference parity: halo2's FFT (`halo2_proofs` best_fft, SURVEY.md §2b N3),
rebuilt as a moded, batched pipeline mirroring the MSM-modes design
(`ops/msm.py`):

* **Batched many-polynomial transforms** — every entry point is shape-
  generic over leading batch axes (`[..., n, 16]`), and `ntt_many` /
  `intt_many` transform a whole `[B, n, 16]` column stack in ONE compiled
  kernel with shared per-stage twiddles. The prover's commit phase and the
  device quotient previously dispatched one kernel per column; per-op
  dispatch overhead (a 16-round CIOS scan per butterfly stage) amortizes
  over the batch instead.
* **`SPECTRE_NTT_MODE=radix2|fourstep`** — `radix2` is the iterative
  Cooley-Tukey kernel (log n fully-vectorized butterfly stages over the
  whole array); `fourstep` is the single-device Bailey split (row NTTs →
  twiddle mult → transpose → column NTTs) reusing the exact decomposition
  and twiddle matrix of `parallel/sharded_ntt.py` — the MXU-shaped layout
  of "Enabling AI ASICs for ZKP" (PAPERS.md, arXiv:2604.17808): two
  batches of short NTTs plus one elementwise/transpose step instead of
  log n sequential full-array gather stages. Both modes produce
  BYTE-IDENTICAL results (exact canonical field arithmetic; pinned by
  tests/test_ntt_modes.py), they differ only in work shape.
* **Fused coset-LDE** — the `mont_mul(coeffs, g^i)` coset pre-scale folds
  into stage 0 of the NTT (the stage-0 twiddle is 1, so the scale multiply
  REPLACES a previously wasted multiply-by-one), making
  `coset_ntt`/`coset_lde_std` one kernel instead of scale-then-NTT. The
  inverse path gets the same treatment: `coset_intt` multiplies once by a
  combined `g^{-i}·n^{-1}` table, and the `_std` variants additionally fold
  the Montgomery boundary conversion into the same table (std→mont+scale on
  the way in, mont→std+unscale+1/n on the way out) — zero extra elementwise
  passes for the quotient pipeline.
* **Budgeted twiddle/coset tables** — stage twiddles, four-step twiddle
  matrices and coset power tables live in a byte-budgeted LRU
  (`SPECTRE_NTT_TABLE_MB`, reusing `ops/msm.py:_TableLRU`) keyed on
  `(kind, k, omega/g)`. A long-running prover service touching many
  circuit sizes must not grow host memory per size it ever saw; eviction
  costs recompute time, never correctness.
* **`SPECTRE_NTT_KERNEL=stages|matmul`** — selects the BODY of the
  fourstep short row/col transforms. `stages` is the butterfly kernel
  above; `matmul` computes each short transform as a direct DFT matrix
  product in the 8-bit-limb domain (arXiv:2604.17808's MXU mapping,
  reusing `field_mxu.py`'s one-hot-reduction formulation): the per-length
  twiddle matrix W[k,j] = omega^{jk} is precomputed in limb form
  (LRU-budgeted), `sum_j W[k,j]*x[j]` contracts over the point axis with
  one `dot_general(..., preferred_element_type=int32)`, and the columns
  are carry-propagated and Montgomery-reduced ONCE per matrix product —
  log n sequential gather stages become two batched matmuls plus the
  twiddle/transpose step. Exact-arithmetic reduction radix is 2^264 (one
  extra 8-bit limb of headroom), which fully reduces sums of up to 1024
  products in a single REDC; short transforms longer than 2^10 fall back
  to `stages` (the int32 column budget C·L·255² and the single-REDC bound
  n·p²/2^264 < p both cap out there). Byte-identical to `stages` (pinned
  by tests/test_ntt_kernels.py); CPU is slower — the MXU win is the
  point, see BASELINE.md.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import bn254
from . import field_ops as F
from .msm import _TableLRU, _record_event

R = bn254.R

NTT_MODES = ("radix2", "fourstep")
NTT_KERNELS = ("stages", "matmul")

# fourstep needs at least one row stage and one column stage
_FOURSTEP_MIN_LOGN = 2

# Longest short transform the matmul kernel accepts. Two independent budgets
# pin the cap (PROVED, not asserted, by analysis/kernel_lint.lint_matmul_cap
# — bump this and the lint fails until the budgets are re-widened):
#   * int32 columns in the one-hot collapse: the i1 axis splits into groups
#     of `_conv_group_width(logn)` limbs (two-level carry split), so each
#     convolution column sums at most W products of ≤ n·255² plus the carry
#     scan's running remainder — peak W·n·255·256 ≤ 2^15·255·256 =
#     2,139,095,040 < 2^31-1 for every logn ≤ 12 (W = 2^(15-logn), capped
#     at 32: n ≤ 1024 needs no split and keeps the PR 15 single-matmul
#     collapse).
#   * single-REDC full reduction with radix 2^272: u < n·p²/2^272 + p < 2p
#     needs n·p < 2^272, i.e. n < 2^18 — one conditional subtract
#     canonicalizes through n = 4096 with 2^14 to spare.
# Fourstep short legs are ~sqrt(n_ext), so cap 12 keeps every extended
# domain up to n_ext = 2^24 (k = 22) on the MXU matmul path.
_MATMUL_MAX_LOGN = 12


def _conv_group_width(logn: int) -> int:
    """i1-axis group width of the two-level carry split: largest W with
    W·n ≤ 2^15 (the int32 column + carry-scan budget above), capped at the
    full 32-limb axis — n ≤ 1024 stays on the unsplit single-matmul path."""
    return 1 << min(5, max(0, 15 - logn))


def ntt_mode() -> str:
    """Active NTT mode from SPECTRE_NTT_MODE (default: radix2). Read per
    call — the jitted kernels key on the mode as a static argument, so
    flipping the env between calls retraces correctly."""
    mode = os.environ.get("SPECTRE_NTT_MODE", "radix2")
    if mode not in NTT_MODES:
        raise ValueError(
            f"SPECTRE_NTT_MODE={mode!r}: expected one of {NTT_MODES}")
    return mode


def ntt_kernel() -> str:
    """Active short-transform kernel from SPECTRE_NTT_KERNEL (default:
    stages). Read per call, like `ntt_mode` — the jitted entries key on it
    as a static argument."""
    kern = os.environ.get("SPECTRE_NTT_KERNEL", "stages")
    if kern not in NTT_KERNELS:
        raise ValueError(
            f"SPECTRE_NTT_KERNEL={kern!r}: expected one of {NTT_KERNELS}")
    return kern


def _resolve_mode(mode: str | None, logn: int) -> str:
    m = mode if mode is not None else ntt_mode()
    if m not in NTT_MODES:
        raise ValueError(f"unknown NTT mode {m!r}")
    if m == "fourstep" and logn < _FOURSTEP_MIN_LOGN:
        return "radix2"              # nothing to split
    return m


def _resolve_kernel(kernel: str | None, mode: str) -> str:
    """The kernel knob selects the BODY of the fourstep short transforms;
    radix2 has no short transforms, so normalize to "stages" there to keep
    trace cache keys stable when the env flips."""
    k = kernel if kernel is not None else ntt_kernel()
    if k not in NTT_KERNELS:
        raise ValueError(f"unknown NTT kernel {k!r}")
    if mode != "fourstep":
        return "stages"
    return k


# ---------------------------------------------------------------------------
# budgeted twiddle / coset tables (host-side LRU, numpy entries)
# ---------------------------------------------------------------------------

def _table_budget_bytes() -> int:
    mb = os.environ.get("SPECTRE_NTT_TABLE_MB")
    if mb is not None:
        return int(mb) << 20
    try:
        with open("/proc/meminfo") as f:
            total = int(f.readline().split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return 1 << 30
    return min(1 << 30, int(total * 0.10))


_TABLES = _TableLRU(_table_budget_bytes(), label="ntt twiddle/coset table",
                    budget_var="SPECTRE_NTT_TABLE_MB",
                    on_event=_record_event)


def lru_stats() -> dict:
    """Twiddle/coset table cache stats for GET /metrics."""
    return _TABLES.stats()


@functools.cache
def _bitrev(logn: int) -> np.ndarray:
    n = 1 << logn
    idx = np.arange(n)
    rev = np.zeros(n, dtype=np.int32)
    for b in range(logn):
        rev |= ((idx >> b) & 1) << (logn - 1 - b)
    return rev


def _stage_twiddles(logn: int, omega: int):
    """Montgomery twiddle tables per stage: stage s has m=2^s butterflies per
    block, twiddle_j = omega^(n/(2m) * j), j < m. LRU-cached per (k, omega);
    entries are numpy so they lift to fresh embedded constants per trace."""
    key = ("stage", logn, omega)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    tables = []
    for s in range(logn):
        m = 1 << s
        w = pow(omega, n // (2 * m), R)
        powers = [1] * m
        for j in range(1, m):
            powers[j] = powers[j - 1] * w % R
        tables.append(ctx.encode(powers))
    return _TABLES.put(key, None, tuple(tables))


def _twiddle_matrix(logr: int, logc: int, omega: int) -> np.ndarray:
    """Montgomery [Rr, Cc, 16] table of omega^(jr*kc) — the four-step
    inter-pass twiddles, shared with `parallel/sharded_ntt.py`. The prover
    reuses one omega per domain, so this is a one-time cost per circuit
    size; LRU-budgeted so a service touching many sizes stays bounded."""
    key = ("mat", logr, logc, omega)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    from ..native import host

    rr, cc = 1 << logr, 1 << logc
    ctx = F.fr_ctx()
    rows = np.empty((rr, cc, 16), dtype=np.uint32)
    for jr in range(rr):
        w = pow(omega, jr, R)
        rows[jr] = ctx.encode_np(
            host.limbs_to_ints(host.fp_powers(host.FR, w, cc)))
    return _TABLES.put(key, None, rows)


def _power_table(logn: int, g: int) -> np.ndarray:
    """[n, 16] Montgomery table of g^i (host-computed once, LRU-cached)."""
    key = ("pow", logn, g)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    powers = [1] * n
    for i in range(1, n):
        powers[i] = powers[i - 1] * g % R
    return _TABLES.put(key, None, ctx.encode(powers))


def _fused_in_table(logn: int, g: int | None) -> np.ndarray:
    """Stage-0 pre-scale table for the coset-LDE entry fusions.

    g given: encode(g^i · R) — one mont_mul takes a STANDARD-form input to
    Montgomery form AND applies the coset scale (mont_mul(x_std, enc(g^i·R))
    = x·g^i·R²·R^{-1} = mont(g^i·x)); g None: encode(R) row, the plain
    std→mont conversion fused the same way."""
    key = ("fin", logn, g)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    r = ctx.r_mod_p
    if g is None:
        tab = ctx.encode([r])                # [1, 16], broadcasts
    else:
        n = 1 << logn
        vals = [0] * n
        acc = r
        for i in range(n):
            vals[i] = acc
            acc = acc * g % R
        tab = ctx.encode(vals)
    return _TABLES.put(key, None, tab)


def _fused_out_table(logn: int, g: int | None, std: bool) -> np.ndarray:
    """Post-NTT multiply table for the inverse path, folding up to three
    elementwise passes into one: the 1/n iNTT scale, the inverse coset
    unscale g^{-i} (when g is given), and — for std=True — the Montgomery →
    standard conversion (the table is left UN-encoded, so mont_mul(v_mont,
    t_std) = v·t in standard form directly)."""
    key = ("fout", logn, g, std)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    ninv = pow(n, -1, R)
    if g is None:
        vals = [ninv]                        # [1, 16], broadcasts
    else:
        ginv = pow(g, -1, R)
        vals = [0] * n
        acc = ninv
        for i in range(n):
            vals[i] = acc
            acc = acc * ginv % R
    if std:
        from . import limbs as L
        tab = L.ints_to_limbs16(vals)        # raw values: output is standard
    else:
        tab = ctx.encode(vals)
    return _TABLES.put(key, None, tab)


def _vinv_in_table(logn: int, vals: tuple) -> np.ndarray:
    """Stage-0 pre-scale table for the fused quotient vanishing-inverse:
    encode(vals[i % len(vals)]) tiled over the domain. The extended-domain
    vanishing polynomial has only EXTENSION distinct values, so the caller
    passes the short period tuple (hashable → usable as a static jit arg)
    and the full [n, 16] Montgomery table materializes here, LRU-budgeted
    like every other per-size table."""
    key = ("vinv", logn, vals)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    ctx = F.fr_ctx()
    n = 1 << logn
    per = len(vals)
    return _TABLES.put(key, None,
                       ctx.encode([vals[i % per] for i in range(n)]))


# ---------------------------------------------------------------------------
# matmul kernel: short transforms as DFT matrix products in the limb domain
# ---------------------------------------------------------------------------

# Reduction radix for the matmul kernel's single REDC: two extra 8-bit limbs
# over the 2^256 Montgomery radix. W entries carry the compensating 2^272
# factor, so after dividing by 2^272 the result is back in plain Montgomery
# form (factor R = 2^256) and byte-identical to the stages kernel. The radix
# sets the single-REDC length budget n < 2^272/p ≈ 2^18 (see the
# _MATMUL_MAX_LOGN note) — PR 15's 2^264 capped it at n = 1024.
_REDC_SHIFT = 272
_REDC_LIMBS = _REDC_SHIFT // 8               # 34
# t = Σ ω^{jk}·2^272·x_j < n·p² < 2^520 at the cap: 66 limbs hold both t and
# m·p < 2^272·p < 2^526, and the REDC high half (u < 2p) is the top 32
_T_LIMBS = _REDC_LIMBS + 32                  # 66


@functools.cache
def _matmul_consts():
    """(p' = -p^{-1} mod 2^272 as 34 limbs, p as 32 limbs), int32 8-bit."""
    p = F.fr_ctx().p
    r1 = 1 << _REDC_SHIFT
    pinv = (-pow(p, -1, r1)) % r1
    pinv8 = np.array([(pinv >> (8 * i)) & 0xFF for i in range(_REDC_LIMBS)],
                     dtype=np.int32)
    p8 = np.array([(p >> (8 * i)) & 0xFF for i in range(32)], dtype=np.int32)
    return pinv8, p8


def _dft_matrix8(logn: int, omega: int) -> np.ndarray:
    """8-bit-limb DFT matrix for the matmul kernel, contraction-ready:
    Wt[j, k*32 + i] = limb i of (omega^{jk} · 2^272 mod p), uint8 [n, n*32].
    One dot_general contracting the point axis j then yields every output
    point's raw limb-pair products in one MXU-shaped matmul. LRU-budgeted
    (uint8 keeps the n=1024 table at 32 MB host-side)."""
    key = ("dft8", logn, omega)
    hit = _TABLES.get(key, None)
    if hit is not None:
        return hit
    p = F.fr_ctx().p
    n = 1 << logn
    shift = (1 << _REDC_SHIFT) % p
    out = np.empty((n, n, 32), dtype=np.uint8)
    for j in range(n):
        w = pow(omega, j, p)
        acc = shift
        row = out[j]
        for k in range(n):
            row[k] = np.frombuffer(acc.to_bytes(32, "little"), np.uint8)
            acc = acc * w % p
    return _TABLES.put(key, None, out.reshape(n, n * 32))


def _ntt_dft_matmul(a, logn: int, omega: int, group_width: int | None = None):
    """Direct DFT of axis -2 of a [..., n, 16] Montgomery limb tensor as one
    limb-domain matrix product (the arXiv:2604.17808 MXU mapping):

        T[k] = sum_j (omega^{jk}·2^272) · x_j  <  n·p²     (exact, int32 cols)
        out[k] = REDC_272(T[k])                            (one reduction)

    The point-axis contraction is ONE dot_general against the precomputed
    [n, n*32] twiddle-limb matrix; the limb-pair products then collapse to
    convolution columns through `field_mxu.conv_matrix`'s one-hot matmul.
    Past n = 1024 a single collapse overflows int32 (C·L·255² at L = 32), so
    the i1 limb axis splits into groups of `_conv_group_width(logn)` — each
    group's columns carry-propagate to exact 8-bit limbs independently
    (level 1), then the per-group limb tensors sum (≤ 4·255 per lane) and
    one more carry pass renormalizes (level 2). Value-preserving, so the
    result is bit-exact for any group width; the width only bounds the int32
    partial sums (W·n·255·256 < 2^31, proved by kernel_lint's cap check).
    `group_width` overrides the split for tests/lint probes — small n can
    exercise the grouped path cheaply.

    A single 2^272-radix REDC then fully reduces: u < n·p²/2^272 + p < 2p,
    one conditional subtract canonicalizes. Canonical in, canonical out —
    byte-identical to `_ntt_stages`."""
    from . import field_mxu as MX

    ctx = F.fr_ctx()
    n = 1 << logn
    pinv8, p8 = _matmul_consts()
    wt = jnp.asarray(_dft_matrix8(logn, omega)).astype(jnp.int32)
    width = group_width if group_width is not None else _conv_group_width(logn)

    x8 = MX._to8(a)                           # [..., n, 32] int32, limbs i2
    # G[..., k, i1, i2] = sum_j Wt[j, (k,i1)] * x8[..., j, i2]: the one
    # point-axis dot_general (batch, then lhs free i2, then rhs free (k,i1))
    g = jax.lax.dot_general(
        x8, wt, (((x8.ndim - 2,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)     # [..., i2, n*32]
    g = g.reshape(g.shape[:-2] + (MX.L8, n, MX.L8))   # [..., i2, k, i1]
    g = jnp.moveaxis(g, -3, -1)               # [..., k, i1, i2]
    s = MX.conv_matrix(MX.L8, MX.L8, 63)      # columns of a 32x32 conv
    if width >= MX.L8:
        flat = g.reshape(g.shape[:-2] + (MX.L8 * MX.L8,))
        t_cols = jax.lax.dot_general(
            flat, s, (((flat.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)  # [..., k, 63] < C·L·255²
        t8 = MX._carry8(t_cols, _T_LIMBS)
    else:
        # two-level carry split (see docstring): group the i1 axis
        t8 = None
        for lo in range(0, MX.L8, width):
            part = g[..., lo:lo + width, :]
            flat = part.reshape(part.shape[:-2] + (width * MX.L8,))
            cols = jax.lax.dot_general(
                flat, s[lo * MX.L8:(lo + width) * MX.L8],
                preferred_element_type=jnp.int32,
                dimension_numbers=(((flat.ndim - 1,), (0,)), ((), ())))
            p8g = MX._carry8(cols, _T_LIMBS)  # exact per-group limbs
            t8 = p8g if t8 is None else t8 + p8g
        t8 = MX._carry8(t8, _T_LIMBS)         # lanes ≤ G·255: renormalize
    t_lo = t8[..., :_REDC_LIMBS]
    m_cols = MX.mul_columns(t_lo, jnp.asarray(pinv8), _REDC_LIMBS)
    m8 = MX._carry8(m_cols, _REDC_LIMBS)      # m = t·p' mod 2^272
    mp_cols = MX.mul_columns(m8, jnp.asarray(p8), _T_LIMBS)

    # low 34 limbs of t + m·p are 0 mod 2^272 by construction: propagate
    # them only for the carry into the high half (carry ≤ 1)
    low_sum = t_lo + mp_cols[..., :_REDC_LIMBS]
    low_t = jnp.moveaxis(low_sum, -1, 0)

    def step(carry, ti):
        cur = ti + carry
        return cur >> 8, cur & jnp.int32(0xFF)

    carry_low, _ = jax.lax.scan(step, jnp.zeros_like(low_t[0]), low_t)

    hi_cols = mp_cols[..., _REDC_LIMBS:] + t8[..., _REDC_LIMBS:]
    hi_cols = hi_cols.at[..., 0].add(carry_low)
    u8 = MX._carry8(hi_cols, 32)              # u < 2p < 2^255
    res16 = MX._from8(u8.astype(jnp.uint32))
    return F._cond_sub_p(ctx, res16)


def _short_transform(a, logn: int, omega: int, kernel: str):
    """Fourstep row/col transform body: butterfly stages or the DFT-matmul.
    Lengths past the matmul's exactness budget fall back to stages."""
    if kernel == "matmul" and 0 < logn <= _MATMUL_MAX_LOGN:
        return _ntt_dft_matmul(a, logn, omega)
    return _ntt_stages(a, logn, omega)


# ---------------------------------------------------------------------------
# core transforms (shape-generic over leading batch axes)
# ---------------------------------------------------------------------------

def _ntt_stages(a, logn: int, omega: int, scale=None):
    """Iterative radix-2 Cooley-Tukey over axis -2 of a [..., n, 16]
    Montgomery limb tensor; leading axes are batch.

    `scale` ([n, 16] or [1, 16] numpy) folds an elementwise pre-multiply
    into stage 0: the stage-0 twiddle is 1 (its multiply is skipped — exact
    for canonical inputs, mont_mul by one_mont is the identity), so the
    fused path costs the same butterfly work as the plain transform while
    replacing the separate scale-then-NTT dispatch."""
    ctx = F.fr_ctx()
    n = 1 << logn
    rev = jnp.asarray(_bitrev(logn))
    a = jnp.take(a, rev, axis=-2)
    if scale is not None:
        s = np.asarray(scale)
        if s.shape[0] == n:                  # permute alongside the data
            s = s[np.asarray(_bitrev(logn))]
        a = F.mont_mul(ctx, a, jnp.asarray(s))
    tables = _stage_twiddles(logn, omega)
    for s_i in range(logn):
        m = 1 << s_i
        blk = a.reshape(a.shape[:-2] + (n // (2 * m), 2, m, F.NLIMBS))
        u = blk[..., 0, :, :]
        v = blk[..., 1, :, :]
        if s_i:                              # stage-0 twiddle is 1: skip
            v = F.mont_mul(ctx, v, jnp.asarray(tables[s_i]))
        a = jnp.stack([F.add(ctx, u, v), F.sub(ctx, u, v)],
                      axis=-3).reshape(a.shape[:-2] + (n, F.NLIMBS))
    return a


def _ntt_fourstep(a, logn: int, omega: int, scale=None,
                  kernel: str = "stages"):
    """Single-device four-step (Bailey) NTT of [..., n, 16]: view x as an
    Rr x Cc matrix (A[jr, jc] = x[jc*Rr + jr]), length-Cc row NTTs, the
    omega^(jr*kc) twiddle multiply, a transpose, then length-Rr row NTTs —
    the exact decomposition `parallel/sharded_ntt.py` shards over a mesh,
    here kept on one device: log n sequential full-array gather stages
    become two batches of short NTTs plus one MXU-shaped elementwise +
    transpose step. `kernel` picks the short-transform body (butterflies or
    the DFT matmul). Output is natural order, byte-identical to radix2."""
    ctx = F.fr_ctx()
    logr = logn // 2
    logc = logn - logr
    rr, cc = 1 << logr, 1 << logc
    omega_row = pow(omega, rr, R)            # length-Cc root (step 1)
    omega_col = pow(omega, cc, R)            # length-Rr root (step 4)
    tw = _twiddle_matrix(logr, logc, omega)

    lead = a.shape[:-2]
    # A[jr, jc] = x[jc*rr + jr]
    A = jnp.moveaxis(a.reshape(lead + (cc, rr, F.NLIMBS)), -2, -3)
    if scale is not None:
        s = np.asarray(scale)
        if s.shape[0] == (1 << logn):
            s = np.moveaxis(s.reshape(cc, rr, F.NLIMBS), -2, -3)
        A = F.mont_mul(ctx, A, jnp.asarray(s))
    y = _short_transform(A, logc, omega_row, kernel)  # step 1: row NTTs
    y = F.mont_mul(ctx, y, jnp.asarray(tw))  # step 2: twiddle
    y = jnp.moveaxis(y, -2, -3)              # step 3: transpose
    y = _short_transform(y, logr, omega_col, kernel)  # step 4: column NTTs
    # y[kc, kr] = X[kr*cc + kc] -> natural order
    return jnp.moveaxis(y, -2, -3).reshape(a.shape)


def _ntt_nd(a, logn: int, omega: int, scale=None, mode: str = "radix2",
            kernel: str = "stages"):
    if mode == "fourstep":
        return _ntt_fourstep(a, logn, omega, scale, kernel)
    return _ntt_stages(a, logn, omega, scale)


def _logn_of(a) -> int:
    n = a.shape[-2]
    logn = n.bit_length() - 1
    assert 1 << logn == n, "transform length must be a power of two"
    return logn


# jitted entry kernels: (g, kinds, mode) are static so env flips retrace;
# tables resolve host-side at trace time and embed as constants


def _batch_rows(a, body):
    """Apply `body` ([n, 16] -> [n, 16]) over the leading batch axes.

    On CPU the columns run SEQUENTIALLY inside the one compiled program
    (lax.map): a 2^14 column's stage working set is ~1 MB and stays
    cache-hot across its log n stages, where the fully vectorized [B, n]
    layout streams B x that per stage and falls out of cache (measured:
    vectorized batch = 0.89x of a jitted per-column loop on the 1-core
    reference box; map = one dispatch AND per-column locality). Real
    vector machines keep the vectorized layout — the batch axis is what
    fills the VPU. Trace-time host decision; both layouts are the same
    exact arithmetic, so results are byte-identical either way."""
    if a.ndim == 2:
        return body(a)
    if jax.default_backend() == "cpu":
        flat = a.reshape((-1,) + a.shape[-2:])
        return jax.lax.map(body, flat).reshape(a.shape)
    return body(a)


# module-level jitted entry points (trace-cache hygiene lint roots):
# analysis/trace_lint verifies each name below is a stable module-level
# jit; every public ntt/intt/coset wrapper funnels through these two.
TRACE_JIT_ROOTS = ("_fwd_kernel", "_inv_kernel")


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _fwd_kernel(a, omega: int, in_kind, mode: str, kernel: str = "stages"):
    """in_kind: None (mont input, no scale), ("mont", g) fused coset
    pre-scale on a Montgomery input, ("std", g_or_None) standard-form input
    with the boundary conversion (+ optional coset scale) fused in."""
    logn = _logn_of(a)
    if in_kind is None:
        scale = None
    elif in_kind[0] == "mont":
        scale = _power_table(logn, in_kind[1])
    else:
        scale = _fused_in_table(logn, in_kind[1])
    return _batch_rows(
        a, lambda row: _ntt_nd(row, logn, omega, scale, mode, kernel))


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
def _inv_kernel(a, omega: int, g, std: bool, mode: str,
                kernel: str = "stages", pre: tuple | None = None):
    """Inverse transform of [..., n, 16]: forward with omega^{-1}, then ONE
    fused multiply by the combined (1/n, g^{-i}, mont→std) table. `pre` (a
    hashable tuple of host ints, period-tiled over the domain) folds an
    elementwise pre-multiply — the quotient's vanishing-inverse — into
    stage 0 of the inverse transform, exactly like the forward coset
    fusions: same mont_mul, same canonical result as a separate pass."""
    logn = _logn_of(a)
    omega_inv = pow(omega, -1, R)
    tab = _fused_out_table(logn, g, std)
    scale = _vinv_in_table(logn, pre) if pre is not None else None

    def body(row):
        res = _ntt_nd(row, logn, omega_inv, scale, mode, kernel)
        return F.mont_mul(F.fr_ctx(), res, jnp.asarray(tab))

    return _batch_rows(a, body)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def ntt(a: jax.Array, omega: int, mode: str | None = None,
        kernel: str | None = None) -> jax.Array:
    """NTT of a [n, 16] Montgomery limb tensor; returns evaluations in
    natural order. omega must be a primitive n-th root of unity (host int).
    mode defaults to SPECTRE_NTT_MODE (see `ntt_mode`); kernel — the
    fourstep short-transform body — to SPECTRE_NTT_KERNEL."""
    m = _resolve_mode(mode, _logn_of(a))
    return _fwd_kernel(a, omega, None, m, _resolve_kernel(kernel, m))


def ntt_many(a: jax.Array, omega: int, mode: str | None = None,
             kernel: str | None = None) -> jax.Array:
    """Batched NTT of a [B, n, 16] stack in one compiled kernel: every
    butterfly stage processes all B polynomials with shared twiddles."""
    m = _resolve_mode(mode, _logn_of(a))
    return _fwd_kernel(a, omega, None, m, _resolve_kernel(kernel, m))


def intt(a: jax.Array, omega: int, mode: str | None = None,
         kernel: str | None = None) -> jax.Array:
    """Inverse NTT: forward with omega^{-1}, then scale by n^{-1}."""
    m = _resolve_mode(mode, _logn_of(a))
    return _inv_kernel(a, omega, None, False, m,
                       _resolve_kernel(kernel, m), None)


def intt_many(a: jax.Array, omega: int, mode: str | None = None,
              kernel: str | None = None) -> jax.Array:
    """Batched inverse NTT of a [B, n, 16] stack (see `ntt_many`)."""
    m = _resolve_mode(mode, _logn_of(a))
    return _inv_kernel(a, omega, None, False, m,
                       _resolve_kernel(kernel, m), None)


def coset_ntt(a: jax.Array, omega: int, g: int, mode: str | None = None,
              kernel: str | None = None) -> jax.Array:
    """Fused coset-LDE: evaluations of a on g*<omega> in ONE kernel — the
    g^i pre-scale rides stage 0 of the NTT instead of a separate pass."""
    m = _resolve_mode(mode, _logn_of(a))
    return _fwd_kernel(a, omega, ("mont", g), m, _resolve_kernel(kernel, m))


def coset_intt(a: jax.Array, omega: int, g: int, mode: str | None = None,
               kernel: str | None = None) -> jax.Array:
    """Fused inverse coset-LDE: one combined g^{-i}·n^{-1} multiply after
    the inverse transform (two elementwise passes become one)."""
    m = _resolve_mode(mode, _logn_of(a))
    return _inv_kernel(a, omega, g, False, m,
                       _resolve_kernel(kernel, m), None)


def coset_ntt_many(a: jax.Array, omega: int, g: int,
                   mode: str | None = None,
                   kernel: str | None = None) -> jax.Array:
    """Batched fused coset-LDE over a [B, n, 16] stack."""
    m = _resolve_mode(mode, _logn_of(a))
    return _fwd_kernel(a, omega, ("mont", g), m, _resolve_kernel(kernel, m))


def coset_intt_many(a: jax.Array, omega: int, g: int,
                    mode: str | None = None,
                    kernel: str | None = None) -> jax.Array:
    m = _resolve_mode(mode, _logn_of(a))
    return _inv_kernel(a, omega, g, False, m,
                       _resolve_kernel(kernel, m), None)


def coset_lde_std(a_std: jax.Array, omega: int, g: int | None,
                  mode: str | None = None,
                  kernel: str | None = None) -> jax.Array:
    """Coset-LDE of STANDARD-form limb input ([..., n, 16]): the std→mont
    boundary conversion and the coset scale fold into one stage-0 table, so
    the whole quotient-phase `to_ext` is a single kernel. Returns Montgomery
    evaluations (the quotient keeps working in Montgomery form)."""
    m = _resolve_mode(mode, _logn_of(a_std))
    return _fwd_kernel(a_std, omega, ("std", g), m,
                       _resolve_kernel(kernel, m))


def coset_intt_std(a: jax.Array, omega: int, g: int | None,
                   mode: str | None = None,
                   kernel: str | None = None) -> jax.Array:
    """Inverse coset-LDE emitting STANDARD-form limbs: 1/n, g^{-i} and the
    mont→std conversion are ONE multiply by a raw (un-encoded) table."""
    m = _resolve_mode(mode, _logn_of(a))
    return _inv_kernel(a, omega, g, True, m,
                       _resolve_kernel(kernel, m), None)


def coset_intt_std_vinv(a: jax.Array, omega: int, g: int | None,
                        vinv_vals, mode: str | None = None,
                        kernel: str | None = None) -> jax.Array:
    """`coset_intt_std(vinv ⊙ a, ...)` with the per-point vanishing-inverse
    multiply FOLDED into stage 0 of the inverse transform. `vinv_vals` is
    the short period of host ints tiled over the domain (the extended-domain
    vanishing inverse has only EXTENSION distinct values — see
    `plonk.domain.Domain.vanishing_inv_period_vals`). Byte-identical to the
    explicit multiply-then-transform (both paths are one canonical mont_mul
    per point), but the quotient h-path issues one fewer full-width
    elementwise pass per proof."""
    m = _resolve_mode(mode, _logn_of(a))
    return _inv_kernel(a, omega, g, True, m, _resolve_kernel(kernel, m),
                       tuple(int(v) % R for v in vinv_vals))


def coset_scale(a: jax.Array, g: int, inverse: bool = False) -> jax.Array:
    """a_i *= g^i (or g^{-i}) — the unfused building block, kept for
    composition outside the NTT (and for oracle tests of the fusion)."""
    ctx = F.fr_ctx()
    logn = _logn_of(a)
    tab = _power_table(logn, pow(g, -1, R) if inverse else g)
    return F.mont_mul(ctx, a, jnp.asarray(tab))

"""Radix-2 NTT / iNTT over BN254 Fr on limb tensors (device kernel N3).

Reference parity: halo2's FFT (`halo2_proofs` best_fft, SURVEY.md §2b N3),
re-designed for XLA: iterative Cooley-Tukey with a host-precomputed bit-reversal
permutation and per-stage twiddle tables shipped to device once per (k, omega).
Each stage is one fully-vectorized butterfly over the whole array — no
data-dependent control flow, shapes static per k.

Coset NTTs (quotient-poly evaluation) compose this with elementwise scaling by
a precomputed power table (see `coset_scale`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import bn254
from . import field_ops as F

R = bn254.R


@functools.cache
def _bitrev(logn: int) -> np.ndarray:
    n = 1 << logn
    idx = np.arange(n)
    rev = np.zeros(n, dtype=np.int32)
    for b in range(logn):
        rev |= ((idx >> b) & 1) << (logn - 1 - b)
    return rev


@functools.cache
def _stage_twiddles(logn: int, omega: int):
    """Montgomery twiddle tables per stage: stage s has m=2^s butterflies per
    block, twiddle_j = omega^(n/(2m) * j), j < m."""
    ctx = F.fr_ctx()
    n = 1 << logn
    tables = []
    for s in range(logn):
        m = 1 << s
        w = pow(omega, n // (2 * m), R)
        powers = [1] * m
        for j in range(1, m):
            powers[j] = powers[j - 1] * w % R
        tables.append(ctx.encode(powers))
    return tables


def ntt(a: jax.Array, omega: int) -> jax.Array:
    """NTT of [n, 16] Montgomery limb tensor; returns evaluations in natural
    order. omega must be a primitive n-th root of unity (host int)."""
    ctx = F.fr_ctx()
    n = a.shape[0]
    logn = n.bit_length() - 1
    assert 1 << logn == n
    tables = _stage_twiddles(logn, omega)
    a = a[jnp.asarray(_bitrev(logn))]
    for s in range(logn):
        m = 1 << s
        tw = tables[s]                       # [m, 16]
        blk = a.reshape(n // (2 * m), 2, m, F.NLIMBS)
        u = blk[:, 0]                        # [n/2m, m, 16]
        v = F.mont_mul(ctx, blk[:, 1], tw[None])
        a = jnp.stack([F.add(ctx, u, v), F.sub(ctx, u, v)], axis=1).reshape(n, F.NLIMBS)
    return a


def intt(a: jax.Array, omega: int) -> jax.Array:
    """Inverse NTT: forward with omega^{-1}, then scale by n^{-1}."""
    ctx = F.fr_ctx()
    n = a.shape[0]
    res = ntt(a, pow(omega, -1, R))
    ninv = ctx.encode([pow(n, -1, R)])[0]
    return F.mont_mul(ctx, res, ninv[None])


@functools.cache
def _power_table(logn: int, g: int):
    """[n, 16] Montgomery table of g^i (host-computed once, cached)."""
    ctx = F.fr_ctx()
    n = 1 << logn
    powers = [1] * n
    for i in range(1, n):
        powers[i] = powers[i - 1] * g % R
    return ctx.encode(powers)


def coset_scale(a: jax.Array, g: int, inverse: bool = False) -> jax.Array:
    """a_i *= g^i (or g^{-i}) — composes with ntt/intt for coset evaluation."""
    ctx = F.fr_ctx()
    n = a.shape[0]
    logn = n.bit_length() - 1
    tab = _power_table(logn, pow(g, -1, R) if inverse else g)
    return F.mont_mul(ctx, a, tab)


def coset_ntt(a: jax.Array, omega: int, g: int) -> jax.Array:
    return ntt(coset_scale(a, g), omega)


def coset_intt(a: jax.Array, omega: int, g: int) -> jax.Array:
    return coset_scale(intt(a, omega), g, inverse=True)

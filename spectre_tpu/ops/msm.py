"""Pippenger MSM over BN254 G1 on device (the north-star kernel, N2).

Reference parity: halo2's CPU Pippenger (`halo2_proofs` best_multiexp, rayon-
parallel, SURVEY.md §2b N2). That algorithm is branch-and-scatter per point —
the wrong shape for a vector machine — so this is a ground-up redesign around
three TPU constraints: static shapes, no random-access writes, no data-
dependent control flow.

Per window (processed under `lax` control flow so the graph stays small):
  1. digit extraction from limb scalars (branchless bit windowing)
  2. stable sort of point indices by bucket digit
  3. segmented halving reduction over the sorted array: at each of ~log2(n)
     levels adjacent pairs in the same bucket merge (complete projective add);
     pairs straddling a bucket boundary emit their left element into a
     [level, bucket] emission slot — each bucket emits at most once per level,
     so the scatter is conflict-free (OOB indices dropped). Skew-proof: a
     bucket with ALL n points still reduces in log2(n) levels with O(n) work,
     unlike padded-gather schemes whose memory explodes.
  4. bucket totals = tree-reduce of the emission array over levels
  5. weighted bucket aggregation sum_b b*B_b via bit decomposition: for each
     digit bit j, tree-reduce the masked buckets, then a 13-step double-and-add
     — depth log(nbuckets) instead of a 2^c-step serial scan.
  6. window combine: fori_loop of c doublings + add.

Complete RCB addition (ops.ec) makes every step branchless; infinity is the
identity everywhere, so masking = setting slots to (0:1:0).

On top of the vanilla path sit three composable, individually-flagged
optimizations (`SPECTRE_MSM_MODE`, see `msm_mode()`):

  glv         scalars split k = k1 + k2*lambda via the BN254 cube-root
              endomorphism (ops.glv, host prep): 2x the points (P and
              phi(P) = (beta*x, y), one field mul each) but ~127-bit half-
              scalars — half the window passes. Negative halves become point
              negations (one field sub).
  glv+signed  digits recoded on device into [-2^(c-1), 2^(c-1)] (carry scan,
              branchless): the bucket array and the emission space HALVE
              (2^(c-1)+1 instead of 2^c); digit signs fold into the same
              cheap point-negation mask as the GLV signs.
  fixed       for fixed commitment bases (the KZG SRS): the per-window
              doubling chains move into a PRECOMPUTED table T[w] = 2^{cw}*B
              cached per SRS digest (host-side byte-budgeted LRU mirroring
              the quotient cache in plonk/prover.py). Bucket sums merge
              ACROSS windows before one weighted aggregation and the final
              window-combine chain disappears; the reduction itself stays
              per-window-sized (a flattened nwin*2n mega-reduction measured
              ~2x slower — see msm_fixed_run). Implies glv+signed. A table
              that would exceed the budget BY ITSELF degrades the call to
              glv+signed instead of thrashing (see _degrade_fixed).

All modes produce the identical group element (the byteeq harness pins
byte-identical commitments); they differ only in work shape.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import ec
from . import field_ops as F

NLIMBS = F.NLIMBS

MSM_MODES = ("vanilla", "glv", "glv+signed", "fixed")


def msm_mode() -> str:
    """Active MSM mode from SPECTRE_MSM_MODE (default: vanilla). Read per
    call so tests/benches can flip it without reimporting."""
    mode = os.environ.get("SPECTRE_MSM_MODE", "vanilla")
    if mode not in MSM_MODES:
        raise ValueError(
            f"SPECTRE_MSM_MODE={mode!r}: expected one of {MSM_MODES}")
    return mode


MSM_IMPLS = ("xla", "pallas")


def msm_impl() -> str:
    """Active MSM implementation from SPECTRE_MSM_IMPL (default: xla).

    `pallas` routes EVERY mode's bucket phase through the VMEM-resident
    bucket kernel (`ops/msm_pallas.py`; interpret-mode off-TPU): vanilla
    recodes the full scalars to signed digits, glv/glv+signed decompose
    on device (glv.decompose_device) and share the signed kernel, fixed
    feeds its endo-expanded window tables as SoA blocks. Only the mesh-
    sharded and DP-batch runners stay XLA — those degrade visibly
    (_record_pallas_degrade)."""
    impl = os.environ.get("SPECTRE_MSM_IMPL", "xla")
    if impl not in MSM_IMPLS:
        raise ValueError(
            f"SPECTRE_MSM_IMPL={impl!r}: expected one of {MSM_IMPLS}")
    return impl


def window_override() -> int | None:
    """Operator window override from SPECTRE_MSM_WINDOW (1..13, empty/unset
    = autotuned table). The device retuning knob: `bench.py --sweep-window`
    emits per-c points/s so a real-TPU run can pick the value, and every
    `default_window*` consumer (ops/msm.py, parallel/batch_msm.py,
    plonk/backend.py) honors it without plumbing c by hand."""
    v = os.environ.get("SPECTRE_MSM_WINDOW")
    if v is None or v == "":
        return None
    c = int(v)
    if not 1 <= c <= 13:
        raise ValueError(
            f"SPECTRE_MSM_WINDOW={v}: expected 1..13 (c > 13 OOMs the "
            "bucket aggregation — see default_window)")
    return c


def _digits_traced(scalars, w, c: int):
    """Extract window-w c-bit digits from [n, L] 16-bit limb scalars; w may
    be a traced int32 (used inside lax loops). Width-generic — see
    field_ops.limb_digits (GLV half-scalars are [n, 8])."""
    return F.limb_digits(scalars, w, c)


def signed_digit_stream(scalars, c: int, nwin: int):
    """[n, L] limb scalars -> [nwin, n] int32 signed digits in
    [-2^(c-1)+1, 2^(c-1)], lowest window first.

    Branchless carry recode (lax.scan over windows): a digit above 2^(c-1)
    becomes d - 2^c with a +1 carry into the next window. Needs
    nwin >= ceil((nbits+1)/c) so the final carry is always absorbed (the
    top digit is then <= 2^(c-1) and cannot re-carry)."""
    half = 1 << (c - 1)

    def step(carry, w):
        d = F.limb_digits(scalars, w, c) + carry
        cout = (d > half).astype(jnp.int32)
        return cout, d - (cout << c)

    _carry, digs = jax.lax.scan(
        step, jnp.zeros(scalars.shape[0], dtype=jnp.int32), jnp.arange(nwin))
    return digs


def _segmented_bucket_sums(points, digits, nbuckets: int):
    """Sorted segmented reduction -> [nbuckets, 3, 16] bucket sums.

    points: [n, 3, 16] projective Montgomery; digits: [n] int32 bucket ids
    (0 = skip — bucket 0 has weight zero in aggregation). Odd level widths
    append ONE sentinel (bucket id == nbuckets: sorts after every real
    digit, never merges with one, its emissions are OOB and dropped) instead
    of padding to a power of two up front — total work stays n + log n
    instead of up to 2n for awkward sizes (the fixed-base path feeds
    nwin*2n-sized arrays that are never powers of two)."""
    n = points.shape[0]
    order = jnp.argsort(digits, stable=True)
    buckets = digits[order]
    pts = points[order]
    levels = (n - 1).bit_length()

    emissions = ec.inf_point((levels + 1, nbuckets))
    for lvl in range(levels):
        if pts.shape[0] % 2:
            pts = jnp.concatenate([pts, ec.inf_point((1,))], axis=0)
            buckets = jnp.concatenate(
                [buckets, jnp.full((1,), nbuckets, dtype=buckets.dtype)])
        left, right = pts[0::2], pts[1::2]
        bl, br = buckets[0::2], buckets[1::2]
        same = bl == br
        merged = ec.padd(left, right)
        pts = ec.select_point(same, merged, right)
        # boundary pairs: left element is the tail of bucket bl -> emit.
        # at most one emission per bucket per level => conflict-free scatter;
        # non-emitting lanes target an out-of-range row and are dropped.
        emit_idx = jnp.where(same, nbuckets, bl)
        emissions = emissions.at[lvl, emit_idx].set(left, mode="drop")
        buckets = br
    # final survivor
    emissions = emissions.at[levels, buckets[0]].set(pts[0], mode="drop")

    # tree-reduce emissions over the level axis
    acc = emissions
    while acc.shape[0] > 1:
        k = acc.shape[0]
        half = k // 2
        merged = ec.padd(acc[:half], acc[half:2 * half])
        acc = jnp.concatenate([merged, acc[2 * half:]], axis=0) \
            if k % 2 else merged
    return acc[0]


def _aggregate_buckets(bucket_sums, c: int):
    """sum_b b * B_b for each window via bit decomposition.

    bucket_sums: [nwin, nbuckets, 3, 16] -> [nwin, 3, 16]. nbuckets may be
    any size with ids < 2^c (the signed paths pass 2^(c-1)+1)."""
    nwin, nbuckets = bucket_sums.shape[0], bucket_sums.shape[1]
    idx = jnp.arange(nbuckets)
    # [nwin, c, nbuckets, 3, 16] masked by bit j of the bucket index
    masks = ((idx[None, :] >> jnp.arange(c)[:, None]) & 1).astype(bool)  # [c, nbuckets]
    sel = ec.select_point(masks[None, :, :], bucket_sums[:, None],
                          ec.inf_point((1, 1, 1)))
    # tree-reduce over the bucket axis
    while sel.shape[2] > 1:
        k = sel.shape[2]
        half = k // 2
        merged = ec.padd(sel[:, :, :half], sel[:, :, half:2 * half])
        sel = jnp.concatenate([merged, sel[:, :, 2 * half:]], axis=2) \
            if k % 2 else merged
    bit_sums = sel[:, :, 0]                      # [nwin, c, 3, 16]
    # acc = sum_j 2^j bit_sums[:, j] by high-to-low double-and-add
    acc = ec.inf_point((nwin,))
    for j in range(c - 1, -1, -1):
        acc = ec.padd(acc, acc)
        acc = ec.padd(acc, bit_sums[:, j])
    return acc


def _msm_windows_impl(points, scalars, c: int, nbits: int):
    nwin = (nbits + c - 1) // c
    nbuckets = 1 << c

    def one_window(w):
        d = F.limb_digits(scalars, w, c)
        return _segmented_bucket_sums(points, d, nbuckets)

    bucket_sums = jax.lax.map(one_window, jnp.arange(nwin))  # [nwin, nb, 3, 16]
    return _aggregate_buckets(bucket_sums, c)


# module-level jitted entry points (trace-cache hygiene lint roots):
# analysis/trace_lint verifies each name below is a stable module-level
# jit — the discipline that keeps per-prove calls on a warm trace cache.
TRACE_JIT_ROOTS = ("msm_windows", "msm_windows_bits", "msm_windows_signed",
                   "combine_windows", "_build_window_table", "msm_fixed_run",
                   "msm_windows_batch")


@functools.partial(jax.jit, static_argnums=(2,))
def msm_windows(points, scalars, c: int):
    """Per-window partial MSM sums: [nwin, 3, 16].

    points: [n, 3, 16] projective Montgomery; scalars: [n, 16] standard-form
    16-bit limbs. Separated from the final combine so the window axis can be
    sharded across devices (parallel.sharded_msm all-reduces these)."""
    return _msm_windows_impl(points, scalars, c, 254)


@functools.partial(jax.jit, static_argnums=(2, 3))
def msm_windows_bits(points, scalars, c: int, nbits: int):
    """msm_windows for scalars of a declared bit-length (GLV half-scalars:
    nbits = glv.glv_bits(), scalars [n, 8])."""
    return _msm_windows_impl(points, scalars, c, nbits)


@functools.partial(jax.jit, static_argnums=(3, 4))
def msm_windows_signed(points, scalars, neg, c: int, nbits: int):
    """Signed-digit window phase: [nwin, 3, 16] partial sums.

    scalars: [n, L] limb magnitudes; neg: [n] bool per-point sign (the GLV
    half-scalar signs). Digit signs and point signs fold into ONE negation
    mask per window — negation is a single field subtract, so the halved
    bucket array (2^(c-1)+1) is nearly free."""
    nwin = (nbits + c) // c          # ceil((nbits + 1) / c): room for carry
    nbuckets = (1 << (c - 1)) + 1
    digs = signed_digit_stream(scalars, c, nwin)

    def one_window(s):
        eff = ec.cneg((s < 0) ^ neg, points)
        return _segmented_bucket_sums(eff, jnp.abs(s), nbuckets)

    bucket_sums = jax.lax.map(one_window, digs)
    return _aggregate_buckets(bucket_sums, c)


@functools.partial(jax.jit, static_argnums=(1,))
def combine_windows(window_sums, c: int):
    """res = sum_w 2^{cw} W_w, high window to low: c doublings + add each."""
    nwin = window_sums.shape[0]

    def body(i, acc):
        for _ in range(c):
            acc = ec.padd(acc, acc)
        return ec.padd(acc, window_sums[nwin - 1 - i])

    return jax.lax.fori_loop(0, nwin, body, ec.inf_point(()))


# ---------------------------------------------------------------------------
# GLV expansion (device side; host scalar prep lives in ops.glv)
# ---------------------------------------------------------------------------

@jax.jit
def _expand_endo(points):
    """[n, 3, 16] -> [2n, 3, 16]: [P ; phi(P)], phi the GLV endomorphism."""
    return jnp.concatenate([points, ec.endo(points)], axis=0)


@jax.jit
def _apply_sign(points, neg):
    return ec.cneg(neg, points)


def _glv_scalars_device(scalars):
    """(sc2 [2n, 8], neg [2n]) via the TRACED decomposition — no host
    round trip (glv.decompose_device matches decompose_batch bit-exactly,
    so every impl/mode keeps byte-identical results)."""
    from . import glv
    a1, a2, n1, n2 = glv.decompose_device(jnp.asarray(scalars))
    return (jnp.concatenate([a1, a2], axis=0),
            jnp.concatenate([n1, n2], axis=0))


def glv_split(points, scalars):
    """Device GLV prep: (points2 [2n,3,16], sc2 [2n,8], neg [2n]).

    points2 = [P ; phi(P)] WITHOUT signs applied — the signed-digit kernel
    folds `neg` into its digit-sign mask; the unsigned path applies it with
    `_apply_sign` once. The Babai rounding runs on device
    (glv.decompose_device) so scalar prep never serializes against the
    device windows."""
    sc2, neg = _glv_scalars_device(scalars)
    return _expand_endo(points), sc2, neg


def _msm_pallas(points, scalars, c, mode: str, base_key):
    """SPECTRE_MSM_IMPL=pallas dispatch: every mode through the
    VMEM-resident bucket kernel (ops/msm_pallas). Mode differences that
    change the group-element computation shape are preserved (GLV point
    expansion, fixed-base tables, table-budget degrade); digit recoding is
    canonicalized to signed digits in-kernel — the same group element with
    half the bucket columns, pinned byte-identical by tests."""
    from . import msm_pallas as MP

    n = points.shape[0]
    if mode == "vanilla":
        # default_window_pallas caps at 11: the kernel keeps all nwin bucket
        # arrays VMEM-resident and 254-bit scalars double nwin vs the GLV
        # paths (see the VMEM budget note in msm_pallas)
        cc = c if c is not None else default_window_pallas(n)
        return MP.combine_windows_soa(
            MP.msm_bucket_windows(MP.to_soa(points), scalars, None, cc, 254),
            cc)

    from . import glv
    nbits = glv.glv_bits()
    if mode == "fixed":
        cf = c if c is not None else default_window_pallas(2 * n, signed=True)
        if _degrade_fixed(n, cf, nbits):
            mode = "glv+signed"
        else:
            nwin = (nbits + cf) // cf
            sc2, neg = _glv_scalars_device(scalars)
            table = fixed_base_table(points, cf, nwin, base_key=base_key)
            return MP.msm_bucket_fixed(
                MP.to_soa_windows(table), sc2, neg, cf, nbits)

    cc = c if c is not None else default_window_pallas(2 * n, signed=True)
    pts2, sc2, neg = glv_split(points, scalars)
    return MP.combine_windows_soa(
        MP.msm_bucket_windows(MP.to_soa(pts2), sc2, neg, cc, nbits), cc)


# ---------------------------------------------------------------------------
# fixed-base tables (per-SRS precompute, host-side budgeted LRU)
# ---------------------------------------------------------------------------

class _TableLRU:
    """Byte-budgeted LRU over derived device tables (OOM guard).

    Mirrors the quotient-phase `_BudgetedExtLRU` (plonk/prover.py): every
    entry is pure DERIVED data — a doubling-chain expansion of a base the
    caller still holds, or an NTT twiddle/coset power table — so eviction
    costs recompute time, never correctness. A 2^16-point GLV table at c=13
    is ~252 MB; an unbounded cache across several SRS sizes would quietly
    eat the prover's memory pool. Entries hold a strong ref to the base
    object so id-derived keys can never alias a recycled array.

    Shared machinery: `ops/ntt.py` instantiates a second LRU over its
    twiddle/coset tables (SPECTRE_NTT_TABLE_MB); entries there are TUPLES
    of per-stage arrays, so byte accounting sums over sequence entries."""

    def __init__(self, budget_bytes: int, label: str = "msm fixed-base table",
                 budget_var: str = "SPECTRE_MSM_TABLE_MB", on_event=None):
        import collections
        self.budget = budget_bytes
        self.label = label
        self.budget_var = budget_var
        # best-effort `fn(kind, **detail)` hook (provenance-manifest event
        # recorder): fires on evictions and oversize passthroughs so cache
        # churn during a prove lands in that job's manifest
        self.on_event = on_event
        self._d = collections.OrderedDict()   # key -> (base_ref, table)
        self._bytes = 0
        self.hits = 0
        self.builds = 0
        self.evictions = 0
        # thrash visibility (exported via GET /metrics): a build whose
        # key was previously evicted is a RECOMPUTE — budget too small
        # for the working set
        self.recomputes = 0
        self._evicted_keys: set = set()

    @staticmethod
    def _entry_bytes(table) -> int:
        if isinstance(table, (tuple, list)):
            return sum(t.size * t.dtype.itemsize for t in table)
        return table.size * table.dtype.itemsize

    def get(self, key, base):
        hit = self._d.get(key)
        if hit is not None and (hit[0] is None or hit[0] is base):
            self._d.move_to_end(key)
            self.hits += 1
            return hit[1]
        return None

    def put(self, key, base, table):
        nbytes = self._entry_bytes(table)
        self.builds += 1
        if key in self._evicted_keys:
            self.recomputes += 1
            self._evicted_keys.discard(key)
        if nbytes > self.budget:
            import sys
            print(f"[lru] {self.label} ({nbytes >> 20} MB) exceeds "
                  f"{self.budget_var} budget ({self.budget >> 20} MB): "
                  f"uncached — every use rebuilds it",
                  file=sys.stderr, flush=True)
            if self.on_event is not None:
                self.on_event("lru_oversize", cache=self.label,
                              entry_mb=nbytes >> 20,
                              budget_mb=self.budget >> 20)
            return table
        evicted = 0
        while self._bytes + nbytes > self.budget and self._d:
            _k, (_ref, old) = self._d.popitem(last=False)
            self._bytes -= self._entry_bytes(old)
            self.evictions += 1
            evicted += 1
            self._evicted_keys.add(_k)
        if evicted and self.on_event is not None:
            self.on_event("lru_evictions", cache=self.label, count=evicted)
        self._d[key] = (base, table)
        self._bytes += nbytes
        return table

    def stats(self) -> dict:
        """Counter/occupancy snapshot for the Prometheus exporter
        (observability/prom.py reads this via `lru_stats()`)."""
        return {"hits": self.hits, "builds": self.builds,
                "evictions": self.evictions,
                "recomputes": self.recomputes,
                "bytes": self._bytes, "budget_bytes": self.budget,
                "entries": len(self._d)}


def _table_budget_bytes() -> int:
    mb = os.environ.get("SPECTRE_MSM_TABLE_MB")
    if mb is not None:
        return int(mb) << 20
    try:
        with open("/proc/meminfo") as f:
            total = int(f.readline().split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return 8 << 30
    return min(8 << 30, int(total * 0.25))


def _record_event(kind, **detail):
    """Forward cache/degrade events to the per-job provenance-manifest
    collector (no-op outside a collecting job; stdlib-only import)."""
    from ..observability.manifest import record_event
    record_event(kind, **detail)


_TABLES = _TableLRU(_table_budget_bytes(), on_event=_record_event)


def lru_stats() -> dict:
    """Fixed-base table cache stats for GET /metrics."""
    return _TABLES.stats()


@functools.partial(jax.jit, static_argnums=(1, 2))
def _build_window_table(points, c: int, nwin: int):
    """[nwin, n, 3, 16] with T[w] = 2^{cw} * points, by chained doubling
    (c doublings per window step; the last step skips its chain — T[nwin]
    is never read)."""
    def step(cur, w):
        def dbl_chain(p):
            return jax.lax.fori_loop(0, c, lambda _i, q: ec.padd(q, q), p)
        nxt = jax.lax.cond(w < nwin - 1, dbl_chain, lambda p: p, cur)
        return nxt, cur

    _last, tables = jax.lax.scan(step, points, jnp.arange(nwin))
    return tables


def _fixed_table_bytes(n: int, c: int, nbits: int) -> int:
    """Exact byte size of the [nwin, 2n, 3, 16] uint32 GLV window table."""
    nwin = (nbits + c) // c
    return nwin * 2 * n * 3 * 16 * 4


def _fixed_fits_budget(n: int, c: int, nbits: int) -> bool:
    return _fixed_table_bytes(n, c, nbits) <= _TABLES.budget


def _record_pallas_degrade(mode: str, n, c, site: str):
    """SPECTRE_MSM_IMPL=pallas asked for the fused kernel but `site` has no
    pallas lowering (the mesh-sharded and DP-batch runners are XLA
    shard_map programs): fall back to XLA VISIBLY — a ServiceHealth counter
    (`spectre_msm_pallas_degraded_total` in /metrics) plus a provenance
    event carrying enough detail (mode, n, c, caller site) to find the
    half-covered path from a farm manifest."""
    from ..utils.health import HEALTH
    HEALTH.incr("msm_pallas_degraded")
    _record_event("msm_pallas_unsupported_mode", mode=mode, n=int(n),
                  c=None if c is None else int(c), site=site)


def _degrade_fixed(n: int, c: int, nbits: int) -> bool:
    """Graceful degradation (ISSUE 3): when one fixed-base table would
    exceed the SPECTRE_MSM_TABLE_MB budget, fall back to glv+signed
    (identical group element, no precompute residency) instead of
    thrashing an uncacheable doubling-chain rebuild on every MSM — the
    mesh-sharded path already degrades the same way. Recorded on the
    ServiceHealth counter `msm_fixed_degraded`."""
    if _fixed_fits_budget(n, c, nbits):
        return False
    from ..utils.health import HEALTH
    HEALTH.incr("msm_fixed_degraded")
    _record_event("msm_fixed_degraded", n=n, window=c,
                  table_mb=_fixed_table_bytes(n, c, nbits) >> 20,
                  budget_mb=_TABLES.budget >> 20)
    return True


def fixed_base_table(points, c: int, nwin: int, base_key=None):
    """[nwin, 2n, 3, 16] GLV fixed-base table, LRU-cached: T[w] holds
    2^{cw} * [P ; phi(P)].

    The doubling chains run on the P half only — phi commutes with
    doubling, so the endomorphism half is one field multiply per entry
    instead of a second chain. base_key (e.g. the SRS digest) names the
    base stably across processes/encodings; without it the cache keys on
    id(points) with a strong ref pin."""
    n = points.shape[0]
    key = (base_key if base_key is not None else ("id", id(points)),
           int(n), int(c), int(nwin))
    ref = None if base_key is not None else points
    hit = _TABLES.get(key, ref)
    if hit is not None:
        return hit
    tab = _build_window_table(points, c, nwin)            # [nwin, n, 3, 16]
    tab = jnp.concatenate([tab, ec.endo(tab)], axis=1)    # [nwin, 2n, 3, 16]
    return _TABLES.put(key, ref, tab)


@functools.partial(jax.jit, static_argnums=(3, 4))
def msm_fixed_run(table, scalars, neg, c: int, nbits: int):
    """Fixed-base MSM over a precomputed window table. Returns [3, 16].

    table: [nwin, N, 3, 16] from fixed_base_table; scalars: [N, L] half-
    scalar magnitudes; neg: [N] bool signs. Three structural savings over
    the dynamic-base signed path: no per-window doubling work (the table
    pre-shifts the base), bucket sums MERGE ACROSS WINDOWS before the
    weighted aggregation (one aggregation pass instead of nwin — sound
    because weight b is window-independent once bases carry 2^{cw}), and
    the final combine chain disappears entirely. The reduction stays
    per-window-sized: a single nwin*N mega-reduction measured ~2x slower
    per element on CPU (the 250 MB working set falls out of cache; the
    ~25 MB window slices stream)."""
    nwin = (nbits + c) // c
    nbuckets = (1 << (c - 1)) + 1
    digs = signed_digit_stream(scalars, c, nwin)          # [nwin, N]

    def one_window(args):
        tw, s = args
        eff = ec.cneg((s < 0) ^ neg, tw)
        return _segmented_bucket_sums(eff, jnp.abs(s), nbuckets)

    bucket_sums = jax.lax.map(one_window, (table, digs))  # [nwin, nb, 3, 16]
    # cross-window bucket merge: tree-fold the window axis
    acc = bucket_sums
    while acc.shape[0] > 1:
        k = acc.shape[0]
        half = k // 2
        merged = ec.padd(acc[:half], acc[half:2 * half])
        acc = jnp.concatenate([merged, acc[2 * half:]], axis=0) \
            if k % 2 else merged
    return _aggregate_buckets(acc, c)[0]


# ---------------------------------------------------------------------------
# window-size tuning + top-level dispatch
# ---------------------------------------------------------------------------

def default_window(n: int, signed: bool = False) -> int:
    """Pippenger window size for n points (the EXPANDED count under GLV).

    c > 13 OOMs in _aggregate_buckets (the bit-decomposition select
    materializes [nwin, c, nbuckets, 3, 16]); 13 is the practical ceiling.
    With signed digits the bucket array is 2^(c-1)+1 — the aggregation and
    emission terms that cap c relax by one bucket-doubling, so each size
    class affords a larger window (pinned by tests/test_msm_modes.py).
    SPECTRE_MSM_WINDOW overrides the whole table (device retuning)."""
    ov = window_override()
    if ov is not None:
        return ov
    if signed:
        if n >= 1 << 18:
            return 13
        if n >= 1 << 12:
            return 11
        if n >= 1 << 7:
            return 8
        return 5
    if n >= 1 << 18:
        return 13
    if n >= 1 << 12:
        return 10
    if n >= 1 << 7:
        return 7
    return 4


def default_window_fixed(n: int) -> int:
    """Window size for the fixed-base path (n = expanded point count).

    The reduction shape matches the signed path window-for-window (the
    table removes doubling/combine work, not reduction work), so the
    signed tuning table applies; table MEMORY scales with nwin*n, which
    the larger signed windows also help."""
    return default_window(n, signed=True)


# VMEM the pallas bucket kernel may spend on resident bucket arrays. 8 MB
# leaves half of a 16 MB core for the double-buffered point DMA and the
# aggregation scratch (see the budget note in msm_pallas).
_PALLAS_BUCKET_VMEM_BUDGET = 8 << 20


def _pallas_bucket_bytes(c: int, nbits: int) -> int:
    """Bytes of VMEM the kernel's resident buckets claim at window width c:
    all nwin [48, 2^(c-1)] u32 bucket arrays live for the whole grid."""
    nwin = (nbits + c) // c
    return nwin * 48 * (1 << (c - 1)) * 4


def default_window_pallas(n: int, signed: bool = False) -> int:
    """Window table for the pallas bucket kernel (SPECTRE_MSM_IMPL=pallas).

    The XLA table tunes around _aggregate_buckets' materialized select; the
    bucket kernel's binding constraint is VMEM residency instead, so it gets
    its own table: start from the XLA width for the size class and shrink
    until the resident buckets fit _PALLAS_BUCKET_VMEM_BUDGET. 254-bit
    vanilla scalars (nwin ~ 254/c, roughly double the GLV window count)
    land on c <= 11 (~4.5 MB) where c = 13 would claim ~15 MB; the 126-bit
    signed/GLV paths fit their XLA widths unchanged (c = 13 is 7.5 MB).
    The CPU interpret-mode sweep in BASELINE.md (PR 19) byte-checks every
    width and records compile cost; it is NOT a silicon tuning run, so the
    table is sized by the VMEM budget, not by those timings.
    SPECTRE_MSM_WINDOW still overrides the whole table (via
    default_window)."""
    from . import glv
    nbits = glv.glv_bits() if signed else 254
    c = default_window(n, signed=signed)
    if window_override() is not None:
        return c
    while c > 1 and _pallas_bucket_bytes(c, nbits) > _PALLAS_BUCKET_VMEM_BUDGET:
        c -= 1
    return c


def msm(points, scalars, c: int | None = None, mode: str | None = None,
        base_key=None):
    """Full MSM on one device. points [n,3,16] proj Montgomery
    (ec.encode_points), scalars [n,16] standard limbs
    (limbs.ints_to_limbs16). Returns [3,16].

    mode defaults to SPECTRE_MSM_MODE (msm_mode()); base_key names a fixed
    base (SRS digest) for the fixed-mode table cache."""
    mode = mode if mode is not None else msm_mode()
    if mode not in MSM_MODES:
        raise ValueError(f"unknown MSM mode {mode!r}")
    n = points.shape[0]
    if msm_impl() == "pallas":
        return _msm_pallas(points, scalars, c, mode, base_key)
    if mode == "vanilla":
        if c is None:
            c = default_window(n)
        return combine_windows(msm_windows(points, scalars, c), c)

    from . import glv
    nbits = glv.glv_bits()
    if mode == "fixed":
        cf = c if c is not None else default_window_fixed(2 * n)
        if _degrade_fixed(n, cf, nbits):
            mode = "glv+signed"
        else:
            nwin = (nbits + cf) // cf
            sc2, neg = _glv_scalars_device(scalars)
            table = fixed_base_table(points, cf, nwin, base_key=base_key)
            return msm_fixed_run(table, sc2, neg, cf, nbits)

    pts2, sc2, neg = glv_split(points, scalars)
    if mode == "glv":
        if c is None:
            c = default_window(2 * n)
        wins = msm_windows_bits(_apply_sign(pts2, neg), sc2, c, nbits)
    else:  # glv+signed
        if c is None:
            c = default_window(2 * n, signed=True)
        wins = msm_windows_signed(pts2, sc2, neg, c, nbits)
    return combine_windows(wins, c)


@functools.partial(jax.jit, static_argnums=(2,))
def msm_windows_batch(points, scalars_batch, c: int):
    """Batched MSM window phase: one point set, many scalar vectors.

    scalars_batch: [m, n, 16] -> [m, nwin, 3, 16]. The inter-proof /
    multi-column batching axis (SURVEY.md §2c(b)). MEASURED NOTE: on a single
    chip this is bandwidth-bound and vmap multiplies HBM traffic — batch=8 at
    2^16 ran ~3x slower than sequential single MSMs, so the sequential path
    stays the default; this entry point exists for multi-chip sharding where
    the batch axis maps onto the mesh."""
    return jax.vmap(lambda sc: msm_windows.__wrapped__(points, sc, c))(scalars_batch)


def msm_batch(points, scalars_batch, c: int | None = None,
              mode: str | None = None, base_key=None):
    """[m] results (projective [m, 3, 16]) for m scalar vectors.

    Non-vanilla modes run the rows SEQUENTIALLY through the single-MSM
    kernels (the measured-faster single-chip shape — see msm_windows_batch)
    with the GLV expansion / fixed table shared across rows; the mesh-
    parallel batch axis lives in parallel.batch_msm."""
    mode = mode if mode is not None else msm_mode()
    n = points.shape[0]
    if msm_impl() == "pallas":
        # per-row dispatch through the bucket pipeline: the fixed table is
        # LRU-shared across rows and every trace below is a cached jit
        return jnp.stack([
            _msm_pallas(points, sc, c, mode, base_key)
            for sc in scalars_batch])
    if mode == "vanilla":
        if c is None:
            c = default_window(n)
        wins = msm_windows_batch(points, scalars_batch, c)
        return jax.vmap(lambda w: combine_windows.__wrapped__(w, c))(wins)

    from . import glv
    nbits = glv.glv_bits()
    outs = []
    if mode == "fixed":
        cf = c if c is not None else default_window_fixed(2 * n)
        if _degrade_fixed(n, cf, nbits):
            mode = "glv+signed"
        else:
            nwin = (nbits + cf) // cf
            table = fixed_base_table(points, cf, nwin, base_key=base_key)
            for sc in scalars_batch:
                sc2, neg = _glv_scalars_device(sc)
                outs.append(msm_fixed_run(table, sc2, neg, cf, nbits))
            return jnp.stack(outs)

    pts2 = _expand_endo(points)
    if c is None:
        c = default_window(2 * n, signed=(mode == "glv+signed"))
    for sc in scalars_batch:
        sc2, neg = _glv_scalars_device(sc)
        if mode == "glv":
            wins = msm_windows_bits(_apply_sign(pts2, neg), sc2, c, nbits)
        else:
            wins = msm_windows_signed(pts2, sc2, neg, c, nbits)
        outs.append(combine_windows(wins, c))
    return jnp.stack(outs)

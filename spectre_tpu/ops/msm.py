"""Pippenger MSM over BN254 G1 on device (the north-star kernel, N2).

Reference parity: halo2's CPU Pippenger (`halo2_proofs` best_multiexp, rayon-
parallel, SURVEY.md §2b N2). That algorithm is branch-and-scatter per point —
the wrong shape for a vector machine — so this is a ground-up redesign around
three TPU constraints: static shapes, no random-access writes, no data-
dependent control flow.

Per window (processed under `lax` control flow so the graph stays small):
  1. digit extraction from limb scalars (branchless bit windowing)
  2. stable sort of point indices by bucket digit
  3. segmented halving reduction over the sorted array: at each of log2(n)
     levels adjacent pairs in the same bucket merge (complete projective add);
     pairs straddling a bucket boundary emit their left element into a
     [level, bucket] emission slot — each bucket emits at most once per level,
     so the scatter is conflict-free (OOB indices dropped). Skew-proof: a
     bucket with ALL n points still reduces in log2(n) levels with O(n) work,
     unlike padded-gather schemes whose memory explodes.
  4. bucket totals = tree-reduce of the emission array over levels
  5. weighted bucket aggregation sum_b b*B_b via bit decomposition: for each
     digit bit j, tree-reduce the masked buckets, then a 13-step double-and-add
     — depth log(nbuckets) instead of a 2^c-step serial scan.
  6. window combine: fori_loop of c doublings + add.

Complete RCB addition (ops.ec) makes every step branchless; infinity is the
identity everywhere, so masking = setting slots to (0:1:0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import ec
from . import field_ops as F

NLIMBS = F.NLIMBS


def _digits_traced(scalars, w, c: int):
    """Extract window-w c-bit digits from [n, 16] 16-bit limb scalars; w may
    be a traced int32 (used inside lax loops). Branchless across limb
    boundaries: a digit spans at most 2 limbs for c <= 16."""
    off = w * c
    limb_idx = off // 16
    shift = off % 16
    col = jnp.take(scalars, limb_idx, axis=1)
    nxt = jnp.take(scalars, jnp.minimum(limb_idx + 1, NLIMBS - 1), axis=1)
    lo = col >> shift
    hi = jnp.where(shift > 0, nxt << (16 - shift), 0)
    hi = jnp.where(limb_idx + 1 < NLIMBS, hi, 0)
    return ((lo | hi) & ((1 << c) - 1)).astype(jnp.int32)


def _segmented_bucket_sums(points, digits, nbuckets: int):
    """Sorted segmented reduction -> [nbuckets, 3, 16] bucket sums.

    points: [n, 3, 16] projective Montgomery; digits: [n] int32 bucket ids
    (0 = skip — bucket 0 has weight zero in aggregation)."""
    n = points.shape[0]
    order = jnp.argsort(digits, stable=True)
    buckets = digits[order]
    pts = points[order]
    # pad to a power of two >= 2 with sentinel bucket id == nbuckets: sorts
    # after every real digit, never merges with one (emissions to it are OOB
    # and dropped), so correctness is unaffected.
    n_pad = max(1 << ((n - 1).bit_length() if n > 1 else 1), 2)
    if n_pad != n:
        pts = jnp.concatenate([pts, ec.inf_point((n_pad - n,))], axis=0)
        buckets = jnp.concatenate(
            [buckets, jnp.full((n_pad - n,), nbuckets, dtype=buckets.dtype)])
    n = n_pad
    levels = n.bit_length() - 1

    emissions = ec.inf_point((levels + 1, nbuckets))
    for lvl in range(levels):
        m = pts.shape[0]
        left, right = pts[0::2], pts[1::2]
        bl, br = buckets[0::2], buckets[1::2]
        same = bl == br
        merged = ec.padd(left, right)
        pts = ec.select_point(same, merged, right)
        # boundary pairs: left element is the tail of bucket bl -> emit.
        # at most one emission per bucket per level => conflict-free scatter;
        # non-emitting lanes target an out-of-range row and are dropped.
        emit_idx = jnp.where(same, nbuckets, bl)
        emissions = emissions.at[lvl, emit_idx].set(left, mode="drop")
        buckets = br
    # final survivor
    emissions = emissions.at[levels, buckets[0]].set(pts[0], mode="drop")

    # tree-reduce emissions over the level axis
    acc = emissions
    total_levels = levels + 1
    while acc.shape[0] > 1:
        k = acc.shape[0]
        half = k // 2
        merged = ec.padd(acc[:half], acc[half:2 * half])
        acc = jnp.concatenate([merged, acc[2 * half:]], axis=0) \
            if k % 2 else merged
    return acc[0]


def _aggregate_buckets(bucket_sums, c: int):
    """sum_b b * B_b for each window via bit decomposition.

    bucket_sums: [nwin, nbuckets, 3, 16] -> [nwin, 3, 16]."""
    nwin, nbuckets = bucket_sums.shape[0], bucket_sums.shape[1]
    idx = jnp.arange(nbuckets)
    # [nwin, c, nbuckets, 3, 16] masked by bit j of the bucket index
    masks = ((idx[None, :] >> jnp.arange(c)[:, None]) & 1).astype(bool)  # [c, nbuckets]
    sel = ec.select_point(masks[None, :, :], bucket_sums[:, None],
                          ec.inf_point((1, 1, 1)))
    # tree-reduce over the bucket axis
    while sel.shape[2] > 1:
        k = sel.shape[2]
        half = k // 2
        merged = ec.padd(sel[:, :, :half], sel[:, :, half:2 * half])
        sel = jnp.concatenate([merged, sel[:, :, 2 * half:]], axis=2) \
            if k % 2 else merged
    bit_sums = sel[:, :, 0]                      # [nwin, c, 3, 16]
    # acc = sum_j 2^j bit_sums[:, j] by high-to-low double-and-add
    acc = ec.inf_point((nwin,))
    for j in range(c - 1, -1, -1):
        acc = ec.padd(acc, acc)
        acc = ec.padd(acc, bit_sums[:, j])
    return acc


@functools.partial(jax.jit, static_argnums=(2,))
def msm_windows(points, scalars, c: int):
    """Per-window partial MSM sums: [nwin, 3, 16].

    points: [n, 3, 16] projective Montgomery; scalars: [n, 16] standard-form
    16-bit limbs. Separated from the final combine so the window axis can be
    sharded across devices (parallel.sharded_msm all-reduces these)."""
    nwin = (254 + c - 1) // c
    nbuckets = 1 << c

    def one_window(w):
        d = _digits_traced(scalars, w, c)
        return _segmented_bucket_sums(points, d, nbuckets)

    bucket_sums = jax.lax.map(one_window, jnp.arange(nwin))  # [nwin, nb, 3, 16]
    return _aggregate_buckets(bucket_sums, c)


@functools.partial(jax.jit, static_argnums=(1,))
def combine_windows(window_sums, c: int):
    """res = sum_w 2^{cw} W_w, high window to low: c doublings + add each."""
    nwin = window_sums.shape[0]

    def body(i, acc):
        for _ in range(c):
            acc = ec.padd(acc, acc)
        return ec.padd(acc, window_sums[nwin - 1 - i])

    return jax.lax.fori_loop(0, nwin, body, ec.inf_point(()))


def default_window(n: int) -> int:
    # c > 13 OOMs in _aggregate_buckets (the bit-decomposition select
    # materializes [nwin, c, 2^c, 3, 16]); 13 is the practical ceiling.
    if n >= 1 << 18:
        return 13
    if n >= 1 << 12:
        return 10
    if n >= 1 << 7:
        return 7
    return 4


def msm(points, scalars, c: int | None = None):
    """Full MSM on one device. points [n,3,16] proj Montgomery (ec.encode_points),
    scalars [n,16] standard limbs (limbs.ints_to_limbs16). Returns [3,16]."""
    n = points.shape[0]
    if c is None:
        c = default_window(n)
    return combine_windows(msm_windows(points, scalars, c), c)


@functools.partial(jax.jit, static_argnums=(2,))
def msm_windows_batch(points, scalars_batch, c: int):
    """Batched MSM window phase: one point set, many scalar vectors.

    scalars_batch: [m, n, 16] -> [m, nwin, 3, 16]. The inter-proof /
    multi-column batching axis (SURVEY.md §2c(b)). MEASURED NOTE: on a single
    chip this is bandwidth-bound and vmap multiplies HBM traffic — batch=8 at
    2^16 ran ~3x slower than sequential single MSMs, so the sequential path
    stays the default; this entry point exists for multi-chip sharding where
    the batch axis maps onto the mesh."""
    return jax.vmap(lambda sc: msm_windows.__wrapped__(points, sc, c))(scalars_batch)


def msm_batch(points, scalars_batch, c: int | None = None):
    """[m] results (projective [m, 3, 16]) for m scalar vectors."""
    n = points.shape[0]
    if c is None:
        c = default_window(n)
    wins = msm_windows_batch(points, scalars_batch, c)
    return jax.vmap(lambda w: combine_windows.__wrapped__(w, c))(wins)

"""Batched BN254 G1 arithmetic on limb tensors (device side of N2).

Points are homogeneous projective (X:Y:Z) limb tensors [..., 3, 16] in
Montgomery form, with infinity = (0:1:0). Addition uses the Renes–Costello–
Batina COMPLETE formulas for j-invariant-0 curves (alg. 7: 12M + 2 small-const
M, branchless): one uniform vectorized formula covers generic add, doubling,
inverses and infinity — no data-dependent control flow, which is exactly what
the TPU/XLA execution model wants (the reference's CPU Pippenger branches per
point; branching is the wrong shape for SIMD lanes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import field_ops as F


def _fq():
    return F.fq_ctx()


def encode_points(points) -> jax.Array:
    """Host: list of affine (x, y) | None -> [n, 3, 16] projective Montgomery."""
    ctx = _fq()
    xs, ys, zs = [], [], []
    for pt in points:
        if pt is None:
            xs.append(0), ys.append(1), zs.append(0)
        else:
            xs.append(int(pt[0])), ys.append(int(pt[1])), zs.append(1)
    return jnp.stack([ctx.encode(xs), ctx.encode(ys), ctx.encode(zs)], axis=-2)


def decode_points(arr) -> list:
    """Device projective -> list of affine (x:int, y:int) | None."""
    ctx = _fq()
    arr = arr.reshape(-1, 3, F.NLIMBS)
    zs = arr[:, 2]
    zinv = F.inv(ctx, zs)
    xs = ctx.decode(F.mont_mul(ctx, arr[:, 0], zinv))
    ys = ctx.decode(F.mont_mul(ctx, arr[:, 1], zinv))
    z_int = ctx.decode(zs)
    return [None if z == 0 else (x, y) for x, y, z in zip(xs, ys, z_int)]


def inf_point(shape=()) -> jax.Array:
    """Projective infinity (0:1:0) broadcast to [..., 3, 16]."""
    ctx = _fq()
    pt = jnp.stack([ctx.zero, ctx.one_mont, ctx.zero], axis=0)
    return jnp.broadcast_to(pt, tuple(shape) + (3, F.NLIMBS))


def padd(p, q):
    """Complete projective add, a=0, b=3 (RCB alg. 7). p, q: [..., 3, 16].

    The 12 field multiplies are batched into TWO stacked mont_mul calls (the
    formula has two dependency layers of muls); adds/subs are likewise stacked.
    This matters: every field op lowers to a lax.scan over limb rounds, and
    XLA compile time scales with scan count, so 2 big scans beat 12 small ones
    — runtime also improves (wider batches per kernel)."""
    ctx = _fq()
    add = lambda a, b: F.add(ctx, a, b)       # noqa: E731
    sub = lambda a, b: F.sub(ctx, a, b)       # noqa: E731
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]

    # pre-sums, stacked: [x1+y1, y1+z1, x1+z1] and same for q
    s1 = add(jnp.stack([x1, y1, x1]), jnp.stack([y1, z1, z1]))
    s2 = add(jnp.stack([x2, y2, x2]), jnp.stack([y2, z2, z2]))

    # mul layer 1: t0=x1x2, t1=y1y2, t2=z1z2, m3=(x1+y1)(x2+y2),
    #              m4=(y1+z1)(y2+z2), m5=(x1+z1)(x2+z2)
    la = jnp.concatenate([jnp.stack([x1, y1, z1]), s1], axis=0)
    lb = jnp.concatenate([jnp.stack([x2, y2, z2]), s2], axis=0)
    t0, t1, t2, m3, m4, m5 = F.mont_mul(ctx, la, lb)

    # cross terms, stacked subtract: t3 = x1y2+x2y1, t4 = y1z2+y2z1, ycross = x1z2+x2z1
    sums = add(jnp.stack([t0, t1, t0]), jnp.stack([t1, t2, t2]))
    t3, t4, ycross = sub(jnp.stack([m3, m4, m5]), sums)

    t0_3 = add(add(t0, t0), t0)               # 3 x1x2
    # b3 = 3b = 9 multiples of t2 and ycross via stacked add chain
    v = jnp.stack([t2, ycross])
    v2 = add(v, v)
    v8 = add(v2, v2)
    v8 = add(v8, v8)
    b3t2, b3y = add(v8, v)

    z3 = add(t1, b3t2)
    t1m = sub(t1, b3t2)

    # mul layer 2: x3a=t4*b3y, x3b=t3*t1m, y3a=b3y*t0_3, y3b=t1m*z3,
    #              z3a=t0_3*t3, z3b=z3*t4
    la2 = jnp.stack([t4, t3, b3y, t1m, t0_3, z3])
    lb2 = jnp.stack([b3y, t1m, t0_3, z3, t3, t4])
    x3a, x3b, y3a, y3b, z3a, z3b = F.mont_mul(ctx, la2, lb2)

    res = jnp.stack([sub(x3b, x3a), add(y3b, y3a), add(z3b, z3a)], axis=-2)
    return res


def pdbl(p):
    """Doubling via the complete add (could specialize later; complete add
    already handles it — kept for call-site clarity)."""
    return padd(p, p)


def pneg(p):
    ctx = _fq()
    return jnp.stack([p[..., 0, :], F.neg(ctx, p[..., 1, :]), p[..., 2, :]], axis=-2)


def cneg(mask, p):
    """mask ? -p : p with mask shaped [...] (no point/limb axes).

    One field negation + select — the device half of signed-digit /
    GLV sign handling (a negated point replaces 2^(c-1)..2^c bucket work,
    and a negated half-scalar replaces ~127 doublings).

    Infinity caveat: -(0:1:0) = (0:p-1:0), a NON-CANONICAL representative
    of the same point (Z = 0). That is fine everywhere cneg output feeds
    `padd` — the complete formulas treat any Z = 0 input as the identity —
    but it means bucket/accumulator states are only representative-equal,
    never bit-equal, once a masked infinity has passed through. Compare
    via decode_points (or a Z-normalizing hash), not raw limbs; the
    in-kernel mirror `msm_pallas._k_cneg` inherits the same contract."""
    return select_point(mask, pneg(p), p)


@functools.cache
def _beta_mont():
    """GLV endomorphism constant beta (cube root of unity in Fq),
    Montgomery-encoded, as numpy (fresh embedded constant per trace)."""
    from . import glv
    return _fq().encode([glv.beta()])[0]


def endo(p):
    """phi(X:Y:Z) = (beta*X : Y : Z), the GLV endomorphism, batched.

    Completeness note: phi maps E to itself (beta^3 = 1 so the curve
    equation is preserved) and fixes infinity (beta*0 = 0 keeps (0:1:0)),
    so phi images — like negated points, which also stay on E — remain
    inside the domain where the RCB complete formulas in `padd` are proven
    exception-free: a = 0, b = 3, ALL input pairs including doubling,
    inverses, and the identity. No new case analysis is introduced by the
    GLV/signed-digit paths."""
    ctx = _fq()
    bx = F.mul_const(ctx, p[..., 0, :], jnp.asarray(_beta_mont()))
    return jnp.stack([bx, p[..., 1, :], p[..., 2, :]], axis=-2)


def select_point(mask, a, b):
    """mask ? a : b with mask shaped [...] (no point/limb axes)."""
    return jnp.where(mask[..., None, None], a, b)


def is_inf(p):
    return F.is_zero(p[..., 2, :])


def scalar_mul(p, k: int):
    """Single-point scalar mul by host int (double-and-add, unrolled bits)."""
    acc = inf_point(p.shape[:-2])
    base = p
    while k:
        if k & 1:
            acc = padd(acc, base)
        k >>= 1
        if k:
            base = padd(base, base)
    return acc

"""GLV scalar decomposition for BN254 G1 (host-side scalar prep, N2 MSM).

BN254 has j-invariant 0, so E: y^2 = x^3 + 3 admits the cube-root-of-unity
endomorphism phi(x, y) = (beta*x, y) with beta^3 = 1 in Fq; on the r-torsion
phi acts as multiplication by lambda, a cube root of unity in Fr. Splitting a
254-bit scalar k into k = k1 + k2*lambda (mod r) with |k1|, |k2| ~ sqrt(r)
turns one 254-bit MSM row into two ~127-bit rows over {P, phi(P)} — half the
Pippenger window passes for a doubling of (cheap, embarrassingly parallel)
point count. phi itself is ONE field multiply per point (ops.ec.endo).

Two implementations share the derived constants:

  host (decompose/decompose_batch): numpy/ints — branchy bigint reference,
      the oracle everything else is pinned against. ~1e-5 s/scalar.
  device (decompose_device): the same lattice math as a traced jnp
      carry-scan over 16-bit limbs, so the Pallas MSM path never round-trips
      scalars through the host (a per-MSM serialization against the device
      windows). The rounded division becomes a Barrett multiply by a
      precomputed reciprocal plus ONE exact correction step — bit-exact
      against the host floor division, pinned by tests/test_msm_modes.py.

Constants are DERIVED at import (cube roots via the field generators, the
short lattice basis via truncated extended-Euclid per the GLV paper) and
verified against the host curve oracle — no transcribed magic numbers to rot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import bn254
from . import limbs as L

R = bn254.R
P = bn254.P

HALF_LIMBS = 8          # 16-bit limbs per half-scalar (128 bits)


@functools.cache
def _constants():
    """(beta, lam, basis) with phi(x,y) = (beta*x, y) acting as mul-by-lam
    on G1, and basis = ((a1, b1), (a2, b2)) short lattice vectors with
    a + b*lam == 0 (mod r) and det == +r."""
    lam = pow(bn254.FR_GENERATOR, (R - 1) // 3, R)
    assert lam != 1 and pow(lam, 3, R) == 1, "lambda derivation broken"
    # beta: order-3 element of Fq* (search a generator-ish g; any g with
    # g^((p-1)/3) != 1 yields a primitive cube root)
    beta = 1
    g = 2
    while beta == 1:
        beta = pow(g, (P - 1) // 3, P)
        g += 1
    assert beta != 1 and pow(beta, 3, P) == 1, "beta derivation broken"

    # pick the (beta, lam) pairing that actually satisfies phi = [lam] on G1
    gx, gy = int(bn254.G1_GEN[0]), int(bn254.G1_GEN[1])
    curve = bn254.g1_curve

    def matches(b, l):
        want = curve.mul(bn254.G1_GEN, l)
        return (int(want[0]), int(want[1])) == (b * gx % P, gy)

    found = None
    for b in (beta, beta * beta % P):
        for l in (lam, lam * lam % R):
            if matches(b, l):
                found = (b, l)
                break
        if found:
            break
    assert found, "no (beta, lambda) pairing satisfies phi == [lambda]"
    beta, lam = found

    # short basis via extended Euclid on (r, lam), truncated at sqrt(r)
    # (GLV §4 / halo2curves g1::ENDO constants, derived instead of copied):
    # every remainder row satisfies s_i*r + t_i*lam = r_i, so (r_i, -t_i)
    # is a lattice vector of the kernel of (a, b) -> a + b*lam mod r.
    sqrt_r = 1 << ((R.bit_length() + 1) // 2)
    rows = [(R, 0), (lam, 1)]           # (remainder, t)
    while rows[-1][0] != 0:
        q = rows[-2][0] // rows[-1][0]
        rows.append((rows[-2][0] - q * rows[-1][0],
                     rows[-2][1] - q * rows[-1][1]))
    idx = next(i for i, (rem, _t) in enumerate(rows) if rem < sqrt_r)
    v1 = (rows[idx][0], -rows[idx][1])
    cand_a = (rows[idx - 1][0], -rows[idx - 1][1])
    cand_b = (rows[idx + 1][0], -rows[idx + 1][1]) \
        if idx + 1 < len(rows) else cand_a
    v2 = min(cand_a, cand_b, key=lambda v: v[0] * v[0] + v[1] * v[1])
    det = v1[0] * v2[1] - v2[0] * v1[1]
    assert abs(det) == R, "lattice basis determinant != r"
    if det < 0:
        v2 = (-v2[0], -v2[1])
    for a, b in (v1, v2):
        assert (a + b * lam) % R == 0, "basis vector outside the lattice"
    return beta, lam, (v1, v2)


def beta() -> int:
    return _constants()[0]


def lam() -> int:
    return _constants()[1]


@functools.cache
def _bound_bits() -> int:
    """Worst-case bit length of |k1|, |k2| (Babai rounding error bound:
    each coordinate of the residual is at most half the basis coordinate
    sums). 128 for BN254 — asserted so HALF_LIMBS stays honest."""
    (a1, b1), (a2, b2) = _constants()[2]
    bx = (abs(a1) + abs(a2) + 1) // 2
    by = (abs(b1) + abs(b2) + 1) // 2
    bits = max(bx.bit_length(), by.bit_length())
    assert bits <= 16 * HALF_LIMBS, "half-scalars overflow HALF_LIMBS limbs"
    return bits


def glv_bits() -> int:
    return _bound_bits()


def decompose(k: int) -> tuple[int, int]:
    """k (any int) -> (k1, k2), signed, with k1 + k2*lam == k (mod r) and
    |k1|, |k2| < 2^glv_bits()."""
    k = k % R
    (a1, b1), (a2, b2) = _constants()[2]
    # Babai round-off in the (v1, v2) basis: (k, 0) = beta1*v1 + beta2*v2
    # over Q with det == +r; c_i = round(beta_i)
    c1 = (2 * k * b2 + R) // (2 * R)
    c2 = (-2 * k * b1 + R) // (2 * R)
    k1 = k - c1 * a1 - c2 * a2
    k2 = -c1 * b1 - c2 * b2
    return k1, k2


def decompose_batch(scalars) -> tuple[np.ndarray, np.ndarray,
                                      np.ndarray, np.ndarray]:
    """Iterable of ints -> (abs1 [n, 8], abs2 [n, 8], neg1 [n], neg2 [n]).

    abs* are 16-bit-limb uint32 arrays of |k1|, |k2| (the device digit
    kernels' input); neg* are bool sign masks, applied on device as point
    negations (ops.ec.cneg) — negation is one field subtract, vastly cheaper
    than the 127 doublings the high half of k would have cost."""
    ks = [int(v) for v in scalars]
    n = len(ks)
    abs1 = np.zeros((n, HALF_LIMBS), dtype=np.uint32)
    abs2 = np.zeros((n, HALF_LIMBS), dtype=np.uint32)
    neg1 = np.zeros(n, dtype=bool)
    neg2 = np.zeros(n, dtype=bool)
    bound = 1 << _bound_bits()
    for i, k in enumerate(ks):
        k1, k2 = decompose(k)
        assert -bound < k1 < bound and -bound < k2 < bound, \
            "half-scalar out of bound (lattice basis regression)"
        # recomposition k1 + k2*lam == k (mod r) is pinned by the property
        # tests, not re-proved per scalar in this hot prep loop
        neg1[i], neg2[i] = k1 < 0, k2 < 0
        a1, a2 = abs(k1), abs(k2)
        for j in range(HALF_LIMBS):
            abs1[i, j] = (a1 >> (16 * j)) & 0xFFFF
            abs2[i, j] = (a2 >> (16 * j)) & 0xFFFF
    return abs1, abs2, neg1, neg2


def decompose_limbs16(sc16: np.ndarray):
    """[n, 16] 16-bit-limb scalars (the device MSM wire format) ->
    decompose_batch outputs."""
    return decompose_batch(L.limbs16_to_ints(np.asarray(sc16)))


# ---------------------------------------------------------------------------
# device-side decomposition (traced jnp; the Pallas MSM path)
#
# Exact-arithmetic plan, all over 16-bit limbs in uint32 lanes (limb-major
# [L, n] so lax.scan carries run down the limb axis, lanes across scalars):
#
#   c1 = (2k*b2 + r) // (2r)   c2 = (2k*|b1| + r) // (2r)      (b1 < 0)
#
# The floor division is a Barrett multiply by mu = floor(2^512 / 2r): for
# x < 2^384 the estimate floor(x*mu >> 512) is q or q-1, never more off, so
# ONE branchless correction (r_hat >= 2r) recovers the exact quotient. The
# residuals k1 = k - c1*a1 - c2*a2 and k2 = c1*|b1| - c2*b2 are computed
# mod 2^144 in two's complement (|k_i| < 2^126, so bit 143 is the sign).
# ---------------------------------------------------------------------------

_MASK16 = np.uint32(0xFFFF)


def _int_limbs(v: int, nl: int) -> np.ndarray:
    assert 0 <= v < 1 << (16 * nl), "constant overflows its limb count"
    return np.array([(v >> (16 * i)) & 0xFFFF for i in range(nl)], np.uint32)


@functools.cache
def _device_consts():
    """Static limb tables for decompose_device, derived (not transcribed)
    from the same lattice basis the host path uses."""
    (a1, b1), (a2, b2) = _constants()[2]
    # the sign structure below is what the BN254 basis derives to; the
    # device dataflow hardcodes it, so fail loudly if derivation changes
    assert a1 > 0 and b1 < 0 and a2 > 0 and b2 > 0, \
        "lattice basis sign structure changed — decompose_device is stale"
    mu = (1 << 512) // (2 * R)
    return {
        "tb2": _int_limbs(2 * b2, 8),        # x1 multiplier
        "tb1": _int_limbs(2 * (-b1), 8),     # x2 multiplier (|b1|)
        "r24": _int_limbs(R, 24),            # dividend addend, 24-limb frame
        "mu": _int_limbs(mu, 17),            # Barrett reciprocal of 2r
        "d17": _int_limbs(2 * R, 17),        # divisor, correction frame
        "a1": _int_limbs(a1, 8),
        "a2": _int_limbs(a2, 8),
        "nb1": _int_limbs(-b1, 8),
        "b2": _int_limbs(b2, 8),
    }


def _carry_norm(t):
    """Carry-propagate a limb-major accumulator (entries < 2^32) to
    normalized 16-bit limbs; returns (limbs, top_carry)."""
    def step(c, ti):
        cur = ti + c
        return cur >> 16, cur & _MASK16

    top, outs = jax.lax.scan(step, jnp.zeros_like(t[0]), t)
    return outs, top


def _mul_const(aT, const_limbs: np.ndarray, out_l: int):
    """Exact product of limb-major [La, n] (limbs < 2^16) with a static
    nonnegative constant, low `out_l` limbs. CIOS-shaped scan (one round
    per constant limb, emit the finished low limb, shift) minus the
    Montgomery reduction; accumulator entries stay < 2^22 — uint32-safe."""
    la = aT.shape[0]
    lane = aT.shape[1:]
    z1 = jnp.zeros((1,) + lane, jnp.uint32)

    def rnd(t, bj):
        prod = aT * bj
        t = (t + jnp.concatenate([prod & _MASK16, z1], 0)
             + jnp.concatenate([z1, prod >> 16], 0))
        out = t[0] & _MASK16
        carry = t[0] >> 16
        t = jnp.concatenate([(t[1] + carry)[None], t[2:], z1], 0)
        return t, out

    t0 = jnp.zeros((la + 1,) + lane, jnp.uint32)
    t, outs = jax.lax.scan(rnd, t0, jnp.asarray(const_limbs))
    hi, top = _carry_norm(t)
    full = jnp.concatenate([outs, hi, top[None]], 0)
    if full.shape[0] >= out_l:
        return full[:out_l]
    pad = jnp.zeros((out_l - full.shape[0],) + lane, jnp.uint32)
    return jnp.concatenate([full, pad], 0)


def _add_const(aT, const_limbs: np.ndarray):
    """a + const mod 2^(16L), limb-major carry scan."""
    cl = jnp.asarray(const_limbs)[:aT.shape[0]]
    pad = aT.shape[0] - cl.shape[0]
    if pad:
        cl = jnp.concatenate([cl, jnp.zeros((pad,), jnp.uint32)])

    def step(c, ab):
        ai, bi = ab
        cur = ai + bi + c
        return cur >> 16, cur & _MASK16

    _top, outs = jax.lax.scan(
        step, jnp.zeros_like(aT[0]), (aT, jnp.broadcast_to(
            cl[:, None] if aT.ndim == 2 else cl, aT.shape)))
    return outs


def _sub_mod(aT, bT):
    """(a - b) mod 2^(16L) and the final borrow lane (1 where a < b)."""
    def step(borrow, ab):
        ai, bi = ab
        cur = ai - bi - borrow
        return (cur >> 16) & np.uint32(1), cur & _MASK16

    borrow, outs = jax.lax.scan(
        step, jnp.zeros_like(aT[0]), (aT, bT))
    return outs, borrow


def _neg_mod(aT):
    """Two's-complement negation mod 2^(16L)."""
    def step(c, ai):
        cur = (ai ^ _MASK16) + c
        return cur >> 16, cur & _MASK16

    _top, outs = jax.lax.scan(
        step, jnp.ones_like(aT[0]), aT)
    return outs


def _floor_div_2r(xT):
    """Exact floor(x / 2r) for limb-major x [24, n] (< 2^384): Barrett
    estimate then one correction. Returns [9, n] (quotients < 2^128)."""
    cst = _device_consts()
    qhat = _mul_const(xT, cst["mu"], 41)[32:41]            # (x*mu) >> 512
    # r_hat = x - qhat*2r mod 2^272; true value in [0, 4r) < 2^272 => exact
    qd = _mul_const(qhat, cst["d17"], 17)
    rhat, _ = _sub_mod(xT[:17], qd)
    d17 = jnp.broadcast_to(jnp.asarray(cst["d17"])[:, None], rhat.shape)
    _, borrow = _sub_mod(rhat, d17)
    return _add_lane(qhat, (borrow == 0).astype(jnp.uint32))


def _add_lane(aT, bit):
    """a + bit (per-lane 0/1) mod 2^(16L)."""
    def step(c, ai):
        cur = ai + c
        return cur >> 16, cur & _MASK16

    _top, outs = jax.lax.scan(step, bit, aT)
    return outs


# module-level jitted entry point (trace-cache hygiene lint root)
TRACE_JIT_ROOTS = ("decompose_device",)


@jax.jit
def decompose_device(sc16):
    """[n, 16] standard-form limb scalars (values < r, the wire format) ->
    (abs1 [n, 8], abs2 [n, 8], neg1 [n] bool, neg2 [n] bool), bit-exact
    against decompose_batch — same Babai rounding, same signs."""
    cst = _device_consts()
    kT = jnp.transpose(jnp.asarray(sc16, jnp.uint32))      # [16, n]
    x1 = _add_const(_mul_const(kT, cst["tb2"], 24), cst["r24"])
    x2 = _add_const(_mul_const(kT, cst["tb1"], 24), cst["r24"])
    c1 = _floor_div_2r(x1)                                 # [9, n]
    c2 = _floor_div_2r(x2)
    k9 = kT[:9]                                            # k mod 2^144
    k1, _ = _sub_mod(k9, _mul_const(c1, cst["a1"], 9))
    k1, _ = _sub_mod(k1, _mul_const(c2, cst["a2"], 9))
    k2, _ = _sub_mod(_mul_const(c1, cst["nb1"], 9),
                     _mul_const(c2, cst["b2"], 9))

    def finish(v):
        negm = (v[8] >> 15) & np.uint32(1)                 # sign bit 143
        mag = jnp.where(negm[None] != 0, _neg_mod(v), v)
        return jnp.transpose(mag[:HALF_LIMBS]), negm != 0

    abs1, neg1 = finish(k1)
    abs2, neg2 = finish(k2)
    return abs1, abs2, neg1, neg2

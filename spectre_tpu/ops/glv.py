"""GLV scalar decomposition for BN254 G1 (host-side scalar prep, N2 MSM).

BN254 has j-invariant 0, so E: y^2 = x^3 + 3 admits the cube-root-of-unity
endomorphism phi(x, y) = (beta*x, y) with beta^3 = 1 in Fq; on the r-torsion
phi acts as multiplication by lambda, a cube root of unity in Fr. Splitting a
254-bit scalar k into k = k1 + k2*lambda (mod r) with |k1|, |k2| ~ sqrt(r)
turns one 254-bit MSM row into two ~127-bit rows over {P, phi(P)} — half the
Pippenger window passes for a doubling of (cheap, embarrassingly parallel)
point count. phi itself is ONE field multiply per point (ops.ec.endo).

This module is deliberately host-side numpy/ints: the decomposition needs
256-bit products and a rounded division — branchy bigint work that is wrong
for the VPU — while its output (8-limb half-scalars + sign masks) is exactly
the static-shape input the device kernels want. Cost is ~1e-5 s/scalar,
noise against the MSM it feeds.

Constants are DERIVED at import (cube roots via the field generators, the
short lattice basis via truncated extended-Euclid per the GLV paper) and
verified against the host curve oracle — no transcribed magic numbers to rot.
"""

from __future__ import annotations

import functools

import numpy as np

from ..fields import bn254
from . import limbs as L

R = bn254.R
P = bn254.P

HALF_LIMBS = 8          # 16-bit limbs per half-scalar (128 bits)


@functools.cache
def _constants():
    """(beta, lam, basis) with phi(x,y) = (beta*x, y) acting as mul-by-lam
    on G1, and basis = ((a1, b1), (a2, b2)) short lattice vectors with
    a + b*lam == 0 (mod r) and det == +r."""
    lam = pow(bn254.FR_GENERATOR, (R - 1) // 3, R)
    assert lam != 1 and pow(lam, 3, R) == 1, "lambda derivation broken"
    # beta: order-3 element of Fq* (search a generator-ish g; any g with
    # g^((p-1)/3) != 1 yields a primitive cube root)
    beta = 1
    g = 2
    while beta == 1:
        beta = pow(g, (P - 1) // 3, P)
        g += 1
    assert beta != 1 and pow(beta, 3, P) == 1, "beta derivation broken"

    # pick the (beta, lam) pairing that actually satisfies phi = [lam] on G1
    gx, gy = int(bn254.G1_GEN[0]), int(bn254.G1_GEN[1])
    curve = bn254.g1_curve

    def matches(b, l):
        want = curve.mul(bn254.G1_GEN, l)
        return (int(want[0]), int(want[1])) == (b * gx % P, gy)

    found = None
    for b in (beta, beta * beta % P):
        for l in (lam, lam * lam % R):
            if matches(b, l):
                found = (b, l)
                break
        if found:
            break
    assert found, "no (beta, lambda) pairing satisfies phi == [lambda]"
    beta, lam = found

    # short basis via extended Euclid on (r, lam), truncated at sqrt(r)
    # (GLV §4 / halo2curves g1::ENDO constants, derived instead of copied):
    # every remainder row satisfies s_i*r + t_i*lam = r_i, so (r_i, -t_i)
    # is a lattice vector of the kernel of (a, b) -> a + b*lam mod r.
    sqrt_r = 1 << ((R.bit_length() + 1) // 2)
    rows = [(R, 0), (lam, 1)]           # (remainder, t)
    while rows[-1][0] != 0:
        q = rows[-2][0] // rows[-1][0]
        rows.append((rows[-2][0] - q * rows[-1][0],
                     rows[-2][1] - q * rows[-1][1]))
    idx = next(i for i, (rem, _t) in enumerate(rows) if rem < sqrt_r)
    v1 = (rows[idx][0], -rows[idx][1])
    cand_a = (rows[idx - 1][0], -rows[idx - 1][1])
    cand_b = (rows[idx + 1][0], -rows[idx + 1][1]) \
        if idx + 1 < len(rows) else cand_a
    v2 = min(cand_a, cand_b, key=lambda v: v[0] * v[0] + v[1] * v[1])
    det = v1[0] * v2[1] - v2[0] * v1[1]
    assert abs(det) == R, "lattice basis determinant != r"
    if det < 0:
        v2 = (-v2[0], -v2[1])
    for a, b in (v1, v2):
        assert (a + b * lam) % R == 0, "basis vector outside the lattice"
    return beta, lam, (v1, v2)


def beta() -> int:
    return _constants()[0]


def lam() -> int:
    return _constants()[1]


@functools.cache
def _bound_bits() -> int:
    """Worst-case bit length of |k1|, |k2| (Babai rounding error bound:
    each coordinate of the residual is at most half the basis coordinate
    sums). 128 for BN254 — asserted so HALF_LIMBS stays honest."""
    (a1, b1), (a2, b2) = _constants()[2]
    bx = (abs(a1) + abs(a2) + 1) // 2
    by = (abs(b1) + abs(b2) + 1) // 2
    bits = max(bx.bit_length(), by.bit_length())
    assert bits <= 16 * HALF_LIMBS, "half-scalars overflow HALF_LIMBS limbs"
    return bits


def glv_bits() -> int:
    return _bound_bits()


def decompose(k: int) -> tuple[int, int]:
    """k (any int) -> (k1, k2), signed, with k1 + k2*lam == k (mod r) and
    |k1|, |k2| < 2^glv_bits()."""
    k = k % R
    (a1, b1), (a2, b2) = _constants()[2]
    # Babai round-off in the (v1, v2) basis: (k, 0) = beta1*v1 + beta2*v2
    # over Q with det == +r; c_i = round(beta_i)
    c1 = (2 * k * b2 + R) // (2 * R)
    c2 = (-2 * k * b1 + R) // (2 * R)
    k1 = k - c1 * a1 - c2 * a2
    k2 = -c1 * b1 - c2 * b2
    return k1, k2


def decompose_batch(scalars) -> tuple[np.ndarray, np.ndarray,
                                      np.ndarray, np.ndarray]:
    """Iterable of ints -> (abs1 [n, 8], abs2 [n, 8], neg1 [n], neg2 [n]).

    abs* are 16-bit-limb uint32 arrays of |k1|, |k2| (the device digit
    kernels' input); neg* are bool sign masks, applied on device as point
    negations (ops.ec.cneg) — negation is one field subtract, vastly cheaper
    than the 127 doublings the high half of k would have cost."""
    ks = [int(v) for v in scalars]
    n = len(ks)
    abs1 = np.zeros((n, HALF_LIMBS), dtype=np.uint32)
    abs2 = np.zeros((n, HALF_LIMBS), dtype=np.uint32)
    neg1 = np.zeros(n, dtype=bool)
    neg2 = np.zeros(n, dtype=bool)
    bound = 1 << _bound_bits()
    for i, k in enumerate(ks):
        k1, k2 = decompose(k)
        assert -bound < k1 < bound and -bound < k2 < bound, \
            "half-scalar out of bound (lattice basis regression)"
        # recomposition k1 + k2*lam == k (mod r) is pinned by the property
        # tests, not re-proved per scalar in this hot prep loop
        neg1[i], neg2[i] = k1 < 0, k2 < 0
        a1, a2 = abs(k1), abs(k2)
        for j in range(HALF_LIMBS):
            abs1[i, j] = (a1 >> (16 * j)) & 0xFFFF
            abs2[i, j] = (a2 >> (16 * j)) & 0xFFFF
    return abs1, abs2, neg1, neg2


def decompose_limbs16(sc16: np.ndarray):
    """[n, 16] 16-bit-limb scalars (the device MSM wire format) ->
    decompose_batch outputs."""
    return decompose_batch(L.limbs16_to_ints(np.asarray(sc16)))

"""Chain spec constants — per-network SSZ generalized indices and committee size.

Reference parity: `eth-types/src/spec.rs:8-83` (`trait Spec` + `Minimal`,
`Testnet`, `Mainnet` impls) and the circuit field/limb shape from
`eth-types/src/lib.rs:12-16`. Everything above this layer is generic over the
spec; circuits take a Spec instance instead of Rust's monomorphized generics.
"""

from __future__ import annotations

from dataclasses import dataclass

# BLS signature domain-separation tag (same for all reference networks,
# `spec.rs` `DST`). One definition; bls12_381 hashing takes it as an argument.
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


@dataclass(frozen=True)
class Spec:
    """Mirror of `eth-types/src/spec.rs` trait consts (same names, snake_case)."""

    name: str
    sync_committee_size: int
    sync_committee_depth: int
    sync_committee_root_index: int
    execution_state_root_index: int
    execution_state_root_depth: int
    finalized_header_index: int
    finalized_header_depth: int
    dst: bytes = DST
    bytes_per_logs_bloom: int = 256
    max_extra_data_bytes: int = 32
    # beacon time parameters (not in the reference Spec trait; used by the
    # preprocessor/service layer for sync-period math)
    slots_per_epoch: int = 32
    epochs_per_sync_committee_period: int = 256
    # header SSZ shape: slot, proposer_index, parent_root, state_root, body_root
    header_num_fields: int = 5

    # derived (spec.rs computes these from the root index/depth)
    @property
    def sync_committee_pubkeys_root_index(self) -> int:
        return self.sync_committee_root_index * 2

    @property
    def sync_committee_pubkeys_depth(self) -> int:
        return self.sync_committee_depth + 1

    @property
    def slots_per_period(self) -> int:
        return self.slots_per_epoch * self.epochs_per_sync_committee_period

    def sync_period(self, slot: int) -> int:
        return slot // self.slots_per_period


# `spec.rs:28-44`
MINIMAL = Spec(
    name="minimal",
    sync_committee_size=32,
    sync_committee_depth=5,
    sync_committee_root_index=55,
    execution_state_root_index=9,
    execution_state_root_depth=4,
    finalized_header_index=105,
    finalized_header_depth=6,
    slots_per_epoch=8,
    epochs_per_sync_committee_period=8,
)

# `spec.rs:49-64`
TESTNET = Spec(
    name="testnet",
    sync_committee_size=512,
    sync_committee_depth=5,
    sync_committee_root_index=55,
    execution_state_root_index=25,
    execution_state_root_depth=4,
    finalized_header_index=105,
    finalized_header_depth=6,
)

# `spec.rs:69-83`
MAINNET = Spec(
    name="mainnet",
    sync_committee_size=512,
    sync_committee_depth=5,
    sync_committee_root_index=55,
    execution_state_root_index=25,
    execution_state_root_depth=4,
    finalized_header_index=105,
    finalized_header_depth=6,
)

# A 2-validator demo network for fast end-to-end runs (not in the reference;
# the circuits are size-generic, so this exercises every constraint at the
# smallest shape).
TINY = Spec(
    name="tiny",
    sync_committee_size=2,
    sync_committee_depth=5,
    sync_committee_root_index=55,
    execution_state_root_index=9,
    execution_state_root_depth=4,
    finalized_header_index=105,
    finalized_header_depth=6,
    slots_per_epoch=8,
    epochs_per_sync_committee_period=8,
)

SPECS = {s.name: s for s in (TINY, MINIMAL, TESTNET, MAINNET)}


# Circuit bigint shape for non-native BLS12-381 Fq over BN254 Fr
# (reference: `eth-types/src/lib.rs:12-13`).
NUM_LIMBS = 5
LIMB_BITS = 104

"""spectre-tpu prover CLI.

Reference parity: `prover/src/args.rs:32-170` + `cli.rs:35-242`:
  circuit {sync-step,committee-update} setup   -- SRS + pk generation
  circuit ... prove                            -- prove a witness file
  rpc                                          -- serve the JSON-RPC API
  utils committee-poseidon                     -- deployment bootstrap values
plus `--backend {cpu,tpu}` (the BASELINE.json north-star selection point) and
`--spec {minimal,testnet,mainnet}` network dispatch (`main.rs:27-57`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .. import spec as spec_mod


def _spec(name):
    return spec_mod.SPECS[name]


def main(argv=None):
    p = argparse.ArgumentParser(prog="spectre-tpu")
    p.add_argument("--spec", default="minimal", choices=list(spec_mod.SPECS))  # incl. "tiny" demo net
    p.add_argument("--backend", default="cpu", choices=["cpu", "tpu"])
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("circuit", help="circuit lifecycle")
    c.add_argument("which", choices=["sync-step", "committee-update",
                                     "sync-step-compressed",
                                     "committee-update-compressed"])
    c.add_argument("action", choices=["setup", "prove", "verify",
                                      "gen-verifier"])
    c.add_argument("--k", type=int, default=17)
    c.add_argument("--k-agg", type=int, default=17,
                   help="aggregation circuit degree (compressed variants)")
    c.add_argument("--witness", help="witness JSON path (default: mock witness)")
    c.add_argument("--proof-out", default="proof.bin")
    c.add_argument("--proof-in")
    c.add_argument("--sol-out", help="Solidity output path "
                   "(default: build/<name>_<spec>_<k>_verifier.sol)")

    r = sub.add_parser("rpc", help="serve JSON-RPC prover API")
    r.add_argument("--host", default="127.0.0.1")
    r.add_argument("--port", type=int, default=3000)
    r.add_argument("--k-step", type=int, default=17)
    r.add_argument("--k-committee", type=int, default=17)
    r.add_argument("--concurrency", type=int, default=1)
    r.add_argument("--compress", action="store_true",
                   help="serve two-stage (aggregated) EVM proofs")
    r.add_argument("--k-agg", type=int, default=17)
    r.add_argument("--params-dir", help="SRS/pk cache dir; also hosts the "
                   "crash-safe async job journal (jobs.journal.jsonl)")
    r.add_argument("--job-timeout", type=float, default=None,
                   help="default per-job deadline in seconds for async "
                   "submitProof_* jobs (default: none)")
    r.add_argument("--queue-depth", type=int, default=None,
                   help="admission-control backlog bound; a full queue "
                   "sheds submits with -32001/429 + Retry-After "
                   "(default: $SPECTRE_JOB_QUEUE_DEPTH or 64)")
    r.add_argument("--mem-watermark-mb", type=float, default=None,
                   help="shed NEW submissions once RSS exceeds this "
                   "(default: $SPECTRE_MEM_WATERMARK_MB; 0 disables)")
    r.add_argument("--worker-stall-s", type=float, default=None,
                   help="supervisor stall threshold: a worker whose "
                   "heartbeat is older than this is replaced and its job "
                   "failed (default: $SPECTRE_WORKER_STALL_S or 600)")
    r.add_argument("--replicas", default=None,
                   help="comma-separated prover replica URLs (default "
                        "$SPECTRE_REPLICAS): serve as a proof-farm "
                        "dispatcher over them instead of proving "
                        "locally (ISSUE 11)")
    r.add_argument("--replica-id", default=None,
                   help="this server's replica id within a farm "
                        "(default $SPECTRE_REPLICA_ID); stamped into "
                        "RPC errors and proof manifests")
    r.add_argument("--lease-s", type=float, default=None,
                   help="dispatcher lease duration in seconds (default "
                        "$SPECTRE_REPLICA_LEASE_S or 120): a replica "
                        "owns a job only while its heartbeat renews "
                        "within this window")
    r.add_argument("--announce-to", default=None,
                   help="dispatcher head URL to announce this replica "
                        "to (default $SPECTRE_ANNOUNCE_URL): joins the "
                        "proof farm dynamically via registerReplica "
                        "with a capability record + heartbeat (ISSUE 18)")
    r.add_argument("--announce-interval", type=float, default=None,
                   help="seconds between announce heartbeats (default "
                        "$SPECTRE_ANNOUNCE_INTERVAL_S or 15)")
    r.add_argument("--advertise-url", default=None,
                   help="URL the dispatcher should dial back (default "
                        "http://<host>:<port> of this server — set when "
                        "behind NAT/a proxy)")
    r.add_argument("--ttl-s", type=float, default=None,
                   help="dispatcher-side heartbeat TTL for dynamic "
                        "members (default $SPECTRE_REPLICA_TTL_S or "
                        "60): a silent replica is demoted through its "
                        "breaker and deregistered after this long")
    r.add_argument("--trace-dir", default=None,
                   help="write each completed job's span tree as Chrome "
                   "trace-event JSON (<job_id>.trace.json) under this "
                   "directory (default: $SPECTRE_TRACE_DIR; unset "
                   "disables the file sink — getTrace still serves the "
                   "in-memory ring)")

    f = sub.add_parser("follow", help="run the light-client follower: "
                       "track the beacon head, prove steps + committee "
                       "updates, serve verified updates over the RPC API")
    f.add_argument("--beacon-api", required=True,
                   help="Beacon REST base URL; pass a comma-separated "
                        "list to poll a quorum (2-of-N agreement on the "
                        "finalized head; a lone dissenting beacon is "
                        "demoted behind its breaker)")
    f.add_argument("--beacon-quorum", type=int, default=None,
                   help="matching finalized heads required before the "
                        "follower acts (default $SPECTRE_BEACON_QUORUM "
                        "or 2, clamped to the pool size)")
    f.add_argument("--params-dir", required=True,
                   help="SRS/pk cache dir; hosts the job journal AND the "
                   "follower's verified update store "
                   "(follower.updates.jsonl + results/)")
    f.add_argument("--host", default="127.0.0.1")
    f.add_argument("--port", type=int, default=3000)
    f.add_argument("--poll-s", type=float, default=None,
                   help="beacon poll cadence (default: "
                   "$SPECTRE_FOLLOW_POLL_S or 12)")
    f.add_argument("--backfill", type=int, default=None,
                   help="max committee-update periods queued per poll "
                   "(default: $SPECTRE_FOLLOW_BACKFILL or 8)")
    f.add_argument("--domain", default=None,
                   help="sync-committee signing domain (hex); step proofs "
                   "are disabled without it")
    f.add_argument("--pubkeys-file", default=None,
                   help="JSON list of compressed pubkey hex strings for "
                   "the current committee; step proofs are disabled "
                   "without it")
    f.add_argument("--k-step", type=int, default=17)
    f.add_argument("--k-committee", type=int, default=17)
    f.add_argument("--k-agg", type=int, default=17)
    f.add_argument("--concurrency", type=int, default=1)
    f.add_argument("--compress", action="store_true",
                   help="prove two-stage (aggregated) EVM proofs")
    f.add_argument("--job-timeout", type=float, default=None)
    f.add_argument("--queue-depth", type=int, default=None)
    f.add_argument("--gateway", action="store_true",
                   help="mount the cacheable GET /v1/* read plane "
                        "(ISSUE 14): content-addressed ETags, 304s, "
                        "immutable cache headers on sealed periods, "
                        "pre-built update-range packs")
    f.add_argument("--pack-periods", type=int, default=None,
                   help="periods per sealed update pack (default "
                        "$SPECTRE_PACK_PERIODS or 8)")
    f.add_argument("--agg-cadence", type=int, default=None,
                   help="publish an EVM-verifiable aggregation proof "
                        "every N sealed committee periods (default "
                        "$SPECTRE_AGG_CADENCE_PERIODS or 0 = off)")
    f.add_argument("--gateway-cache-mb", type=float, default=None,
                   help="gateway hot-cache byte budget in MB (default "
                        "$SPECTRE_GATEWAY_CACHE_MB or 64)")

    u = sub.add_parser("utils", help="deployment utilities")
    u.add_argument("util", choices=["committee-poseidon"])
    u.add_argument("--beacon-api", help="Beacon REST base URL")

    b = sub.add_parser("bench", help="run the MSM benchmark")

    fl = sub.add_parser("faults", help="fault-injection site registry")
    fl.add_argument("--list", action="store_true",
                    help="print the site table (markdown, the source of "
                    "the README fault-sites section)")
    fl.add_argument("--json", action="store_true",
                    help="machine-readable sites + kinds")

    s = sub.add_parser("scrub", help="offline artifact scrub: re-hash every "
                       "results/ file against its content address, "
                       "quarantine rot, expire journal orphans")
    s.add_argument("--params-dir", required=True,
                   help="the dir hosting the job journal + results/ store")
    s.add_argument("--min-age-s", type=float, default=0.0,
                   help="only expire orphans older than this (default 0: "
                   "the service is assumed stopped, everything is fair "
                   "game; the in-service scrubber defaults to "
                   "$SPECTRE_SCRUB_MIN_AGE_S or 60)")

    args = p.parse_args(argv)
    spec = _spec(args.spec)

    if args.cmd == "circuit":
        _circuit_cmd(args, spec)
    elif args.cmd == "rpc":
        from .rpc import serve
        from .state import ProverState
        # compile telemetry before the first jit: boot/pk-creation
        # compiles land in spectre_compile_seconds and per-job manifests
        # (render one with `python -m spectre_tpu.observability report`)
        from ..observability import compilelog
        compilelog.install()
        print(f"loading prover state (spec={spec.name}, backend={args.backend})...",
              flush=True)
        state = ProverState(spec, args.k_step, args.k_committee,
                            args.concurrency, args.backend,
                            params_dir=args.params_dir,
                            compress=args.compress, k_agg=args.k_agg)
        print(f"serving on {args.host}:{args.port} "
              f"(async jobs journaled under "
              f"{args.params_dir or 'params_dir unset: in-memory only'})",
              flush=True)
        if args.trace_dir is not None:
            from ..observability.tracing import TRACE_DIR_ENV
            os.environ[TRACE_DIR_ENV] = args.trace_dir
        queue_kw = {}
        if args.queue_depth is not None:
            queue_kw["queue_depth"] = args.queue_depth
        if args.mem_watermark_mb is not None:
            queue_kw["mem_watermark_mb"] = args.mem_watermark_mb
        if args.worker_stall_s is not None:
            queue_kw["stall_timeout"] = args.worker_stall_s
        dispatcher = None
        replicas_raw = args.replicas or os.environ.get("SPECTRE_REPLICAS")
        if replicas_raw:
            # proof farm (ISSUE 11): this process becomes the dispatcher
            # head — jobs route to the replica fleet; the local state
            # only cross-verifies what the replicas return
            from .dispatcher import Dispatcher, HttpReplica
            from .rpc_client import ProverClient
            urls = [u.strip() for u in replicas_raw.split(",") if u.strip()]
            dispatcher = Dispatcher(
                replicas=[HttpReplica(url, ProverClient(url))
                          for url in urls],
                journal_dir=args.params_dir, lease_s=args.lease_s,
                ttl_s=args.ttl_s, verify_state=state)
            print(f"dispatching over {len(urls)} replicas "
                  f"(lease {dispatcher.lease_s:g}s, heartbeat TTL "
                  f"{dispatcher.ttl_s:g}s, cross-verify on)",
                  flush=True)
        elif args.ttl_s is not None:
            # dispatcher head with an EMPTY static fleet (ISSUE 18):
            # every replica joins dynamically via registerReplica
            from .dispatcher import Dispatcher
            dispatcher = Dispatcher(replicas=[],
                                    journal_dir=args.params_dir,
                                    lease_s=args.lease_s,
                                    ttl_s=args.ttl_s, verify_state=state)
            print(f"dispatching over announce-only fleet (heartbeat TTL "
                  f"{dispatcher.ttl_s:g}s)", flush=True)
        serve(state, args.host, args.port, job_timeout=args.job_timeout,
              dispatcher=dispatcher, replica_id=args.replica_id,
              announce=args.announce_to,
              announce_interval=args.announce_interval,
              advertise_url=args.advertise_url,
              **queue_kw)
    elif args.cmd == "utils":
        _utils_cmd(args, spec)
    elif args.cmd == "bench":
        import subprocess
        subprocess.run([sys.executable, "bench.py"], check=True)
    elif args.cmd == "follow":
        _follow_cmd(args, spec)
    elif args.cmd == "faults":
        _faults_cmd(args)
    elif args.cmd == "scrub":
        _scrub_cmd(args)


def _follow_cmd(args, spec):
    """Supervised follower daemon (ISSUE 10): beacon head tracking +
    proof scheduling in the foreground, the RPC serving API (including
    getLightClientUpdate) in the background on the same process."""
    import threading

    from ..follower import Follower
    from ..observability import compilelog
    from ..preprocessor.beacon import BeaconClient, BeaconQuorum
    from .jobs import ensure_jobs
    from .rpc import serve
    from .state import ProverState

    compilelog.install()
    pubkeys = None
    if args.pubkeys_file:
        with open(args.pubkeys_file) as fh:
            pubkeys = json.load(fh)
    domain = args.domain
    if not (pubkeys and domain):
        print("step proofs disabled (need both --pubkeys-file and "
              "--domain); following committee updates only", flush=True)
    print(f"loading prover state (spec={spec.name}, "
          f"backend={args.backend})...", flush=True)
    state = ProverState(spec, args.k_step, args.k_committee,
                        args.concurrency, args.backend,
                        params_dir=args.params_dir,
                        compress=args.compress, k_agg=args.k_agg)
    queue_kw = {}
    if args.queue_depth is not None:
        queue_kw["queue_depth"] = args.queue_depth
    jobs = ensure_jobs(state, journal_dir=args.params_dir,
                       default_timeout=args.job_timeout, **queue_kw)
    beacon_urls = [u.strip() for u in args.beacon_api.split(",")
                   if u.strip()]
    if len(beacon_urls) > 1:
        # multi-beacon quorum (ISSUE 11 satellite): the follower acts
        # only on a finalized head 2-of-N beacons agree on; a lone
        # lying/forked beacon is demoted behind its own breaker
        beacon = BeaconQuorum([BeaconClient(u) for u in beacon_urls],
                              quorum=args.beacon_quorum)
        print(f"beacon quorum: {beacon.quorum}-of-{len(beacon_urls)}",
              flush=True)
    else:
        beacon = BeaconClient(beacon_urls[0])
    publisher = None
    if args.agg_cadence:
        # aggregation cadence (ISSUE 18): publish through the Spectre
        # contract reference model — swap in an EvmProofVerifier-backed
        # contract to gate publishes on the generated Solidity verifier
        from ..contracts.spectre import SpectreContract
        from ..follower.scheduler import AggregationPublisher
        contract = SpectreContract(spec, 0, 0)
        publisher = AggregationPublisher(contract)
        print(f"aggregation cadence: every {args.agg_cadence} sealed "
              f"periods", flush=True)
    fol = Follower(spec, beacon, jobs, directory=args.params_dir,
                   pubkeys=pubkeys, domain=domain, backfill=args.backfill,
                   cadence_periods=args.agg_cadence, publisher=publisher)
    gateway = None
    if args.gateway:
        from ..gateway import Gateway
        gateway = Gateway(fol.store, pack_periods=args.pack_periods,
                          cache_mb=args.gateway_cache_mb)
        print(f"gateway mounted on /v1/* (pack_periods="
              f"{gateway.packs.pack_periods}, cache "
              f"{gateway.cache.budget >> 20} MB)", flush=True)
    serve(state, args.host, args.port, background=True,
          journal_dir=args.params_dir, job_timeout=args.job_timeout,
          follower=fol, gateway=gateway, **queue_kw)
    print(f"following {args.beacon_api}; serving light-client updates "
          f"on {args.host}:{args.port}", flush=True)
    stop = threading.Event()
    try:
        fol.run(stop, poll_s=args.poll_s)
    except KeyboardInterrupt:
        stop.set()


def _faults_cmd(args):
    """Print the fault-site registry (ISSUE 10 satellite): `--list` is
    the markdown table the README embeds verbatim; `--json` the raw
    registry for tooling."""
    from ..utils import faults
    if args.json:
        print(json.dumps({"sites": {k: {"module": m, "injects": d}
                                    for k, (m, d) in faults.SITES.items()},
                          "kinds": list(faults.KINDS)}, indent=2))
    else:
        print(faults.render_site_table())


def _scrub_cmd(args):
    """One offline scrubber pass (ISSUE 9): replay the journal to learn
    which digests are live, then re-hash/quarantine/expire the store."""
    from ..observability.manifest import MANIFEST_SUFFIX
    from ..utils.artifacts import ArtifactStore
    from .jobs import JobJournal
    from .scrubber import Scrubber

    jobs = JobJournal(args.params_dir).replay()
    live = set()
    for job in jobs.values():
        if job.result_digest is not None:
            live.add((job.result_digest, ".bin"))
        if job.manifest_digest is not None:
            live.add((job.manifest_digest, MANIFEST_SUFFIX))
    # a follower params dir keeps its verified updates (and the
    # gateway's update-range packs) in the SAME artifact store — replay
    # those journals too, or an offline pass expires the whole chain
    from ..follower.updates import JOURNAL_NAME, UpdateStore
    if os.path.exists(os.path.join(args.params_dir, JOURNAL_NAME)):
        from ..gateway.packs import PackBuilder
        ustore = UpdateStore(args.params_dir)
        live |= ustore.live_artifacts()
        live |= PackBuilder(ustore).live_artifacts()
    store = ArtifactStore(args.params_dir)
    summary = Scrubber(store, lambda: live,
                       min_age_s=args.min_age_s).scrub()
    summary["live"] = len(live)
    print(json.dumps(summary))


def _circuit_cmd(args, spec):
    from ..models import CommitteeUpdateCircuit, StepCircuit
    from ..plonk import backend as B
    from ..plonk.srs import SRS
    from ..witness import default_committee_update_args, default_sync_step_args

    compressed = args.which.endswith("-compressed")
    base = args.which.removesuffix("-compressed")
    circuit = StepCircuit if base == "sync-step" else CommitteeUpdateCircuit
    default_args = (default_sync_step_args if base == "sync-step"
                    else default_committee_update_args)(spec)
    bk = B.get_backend(args.backend)
    srs = SRS.load_or_setup(args.k)

    if args.action == "setup" and not compressed:
        pk = circuit.create_pk(srs, spec, args.k, default_args, bk)
        print(f"pk ready: {circuit.pinning_path(spec, args.k)}")
        return

    witness_args = default_args
    if args.witness:
        with open(args.witness) as f:
            data = json.load(f)
        witness_args = _witness_from_json(base, data)

    pk = circuit.create_pk(srs, spec, args.k, default_args, bk)

    if compressed:
        _compressed_circuit_cmd(args, spec, circuit, pk, srs,
                                default_args, witness_args, bk)
        return

    if args.action == "gen-verifier":
        # reference: `spectre-prover circuit ... gen-verifier`
        # (`util/circuit.rs:182-194`)
        from ..evm import gen_evm_verifier
        from ..models.app_circuit import BUILD_DIR
        n_inst = len(circuit.get_instances(default_args, spec))
        src = gen_evm_verifier(pk.vk, srs, num_instances=n_inst,
                               contract_name=f"Verifier_{circuit.name}")
        out = args.sol_out or os.path.join(
            BUILD_DIR, f"{circuit.name}_{spec.name}_{args.k}_verifier.sol")
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            f.write(src)
        print(json.dumps({"verifier": out, "bytes": len(src)}))
        return
    if args.action == "prove":
        proof = circuit.prove(pk, srs, witness_args, spec, bk)
        with open(args.proof_out, "wb") as f:
            f.write(proof)
        instances = circuit.get_instances(witness_args, spec)
        print(json.dumps({"proof": args.proof_out, "bytes": len(proof),
                          "instances": [hex(v) for v in instances]}))
    elif args.action == "verify":
        with open(args.proof_in or args.proof_out, "rb") as f:
            proof = f.read()
        instances = circuit.get_instances(witness_args, spec)
        ok = circuit.verify(pk.vk, srs, instances, proof)
        print(json.dumps({"valid": bool(ok)}))
        sys.exit(0 if ok else 1)


def _compressed_circuit_cmd(args, spec, circuit, pk, srs, default_args,
                            witness_args, bk):
    """Two-stage lifecycle (reference: `sync-step-compressed` CLI paths):
    app snark (Poseidon transcript) -> aggregation circuit -> outer proof
    (Keccak for the EVM calldata path)."""
    from ..models import AggregationArgs, AggregationCircuit
    from ..plonk.srs import SRS
    from ..plonk.transcript import KeccakTranscript, PoseidonTranscript

    agg_cls = AggregationCircuit.variant(circuit.name)
    srs_agg = SRS.load_or_setup(args.k_agg)

    def agg_args_for(wargs):
        proof = circuit.prove(pk, srs, wargs, spec, bk,
                              transcript=PoseidonTranscript())
        inst = circuit.get_instances(wargs, spec)
        return AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=[inst], proof=proof)

    agg_pk = agg_cls.create_pk(srs_agg, spec, args.k_agg,
                               lambda: agg_args_for(default_args), bk)
    if args.action == "setup":
        print(f"pk ready: {agg_cls.pinning_path(spec, args.k_agg)}")
        return
    if args.action == "gen-verifier":
        from ..evm import gen_evm_verifier
        from ..models.app_circuit import BUILD_DIR
        # statement = 12 accumulator limbs + the app instances (no proving
        # needed to size it)
        n_inst = 12 + len(circuit.get_instances(default_args, spec))
        src = gen_evm_verifier(agg_pk.vk, srs_agg, num_instances=n_inst,
                               contract_name=f"Verifier_{agg_cls.name}",
                               num_acc_limbs=12)
        out = args.sol_out or os.path.join(
            BUILD_DIR, f"{agg_cls.name}_{spec.name}_{args.k_agg}_verifier.sol")
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            f.write(src)
        print(json.dumps({"verifier": out, "bytes": len(src)}))
        return
    inst_path = args.proof_out + ".instances.json"
    if args.action == "prove":
        agg_args = agg_args_for(witness_args)
        proof = agg_cls.prove(agg_pk, srs_agg, agg_args, spec, bk,
                              transcript=KeccakTranscript())
        instances = AggregationCircuit.get_instances(agg_args, spec)
        with open(args.proof_out, "wb") as f:
            f.write(proof)
        # the statement binds the (blinded, non-reproducible) app proof:
        # persist it next to the outer proof for later verification
        with open(inst_path, "w") as f:
            json.dump({"instances": [hex(v) for v in instances]}, f)
        print(json.dumps({"proof": args.proof_out, "bytes": len(proof),
                          "instances": inst_path}))
    elif args.action == "verify":
        with open(args.proof_in or args.proof_out, "rb") as f:
            proof = f.read()
        src_path = ((args.proof_in or args.proof_out)
                    + ".instances.json")
        with open(src_path) as f:
            instances = [int(v, 16) for v in json.load(f)["instances"]]
        ok = agg_cls.verify(agg_pk.vk, srs_agg, instances, proof,
                            transcript_cls=KeccakTranscript)
        print(json.dumps({"valid": bool(ok)}))
        sys.exit(0 if ok else 1)


def _witness_from_json(which: str, data: dict):
    from ..preprocessor.rotation import rotation_args_from_update
    from ..preprocessor.step import step_args_from_finality_update
    if which == "sync-step":
        raise SystemExit("sync-step witness JSON requires the update+pubkeys "
                         "format; use the rpc API or the preprocessor directly")
    return rotation_args_from_update(data, _spec(data.get("spec", "minimal")))


def _utils_cmd(args, spec):
    from ..fields import bls12_381 as bls
    from ..gadgets.poseidon_commit import committee_poseidon_from_uncompressed
    from .beacon_helpers import fetch_bootstrap_committee

    assert args.util == "committee-poseidon"
    assert args.beacon_api, "--beacon-api required"
    period, root, pubkeys = fetch_bootstrap_committee(args.beacon_api, spec)
    pts = [bls.g1_decompress(pk) for pk in pubkeys]
    commitment = committee_poseidon_from_uncompressed(pts)
    print(json.dumps({
        "sync_period": period,
        "committee_ssz_root": "0x" + root.hex(),
        "committee_poseidon": hex(commitment),
    }))


if __name__ == "__main__":
    main()

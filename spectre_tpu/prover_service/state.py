"""ProverState: SRS + proving keys loaded once at boot.

Reference parity: `prover/src/prover.rs:43-117` (`ProverState::new`: SRS map
by degree, pkeys for step/committee circuits created from default witnesses)
and the semaphore-based concurrency cap (`prover.rs:40`) — here a
threading.Semaphore, acquired by the RPC handlers.

PR 3: every prove routes through `backend.prove_with_fallback` — a device
OOM / Mosaic compile failure retries once on the CPU backend instead of
failing the request — and `params_dir` additionally hosts the async job
journal (`jobs.ensure_jobs` attaches the queue lazily at serve time).
"""

from __future__ import annotations

import threading

from ..models import CommitteeUpdateCircuit, StepCircuit
from ..plonk import backend as B
from ..plonk.srs import SRS
from ..utils.profiling import phase
from ..witness import default_committee_update_args, default_sync_step_args


class ProverState:
    def __init__(self, spec, k_step: int, k_committee: int,
                 concurrency: int = 1, backend: str = "cpu",
                 params_dir: str | None = None, compress: bool = False,
                 k_agg: int = 17):
        """compress: run the full two-stage flow (app snark with Poseidon
        transcript -> in-circuit verification in the aggregation circuit ->
        Keccak-transcript outer proof), the reference's `*Compressed` RPC
        semantics. Boot additionally creates the two aggregation pkeys from
        dummy app snarks (`cli.rs:241-280`'s dummy-proof-at-setup)."""
        # compile telemetry (ISSUE 8): register the jax.monitoring
        # listener BEFORE any jit fires, so pk-creation/boot compiles are
        # counted too — after boot, a prove whose manifest shows
        # compile.count == 0 provably hit the jit caches
        from ..observability import compilelog
        compilelog.install()
        self.spec = spec
        self.backend = B.get_backend(backend)
        self.concurrency = concurrency
        self.semaphore = threading.Semaphore(concurrency)
        self.params_dir = params_dir      # also hosts the async job journal
        self.jobs = None                  # attached lazily (jobs.ensure_jobs)
        self.srs = {}
        for k in {k_step, k_committee}:
            self.srs[k] = SRS.load_or_setup(k, params_dir)
        self.k_step, self.k_committee = k_step, k_committee
        self.step_pk = StepCircuit.create_pk(
            self.srs[k_step], spec, k_step,
            default_sync_step_args(spec), self.backend)
        self.committee_pk = CommitteeUpdateCircuit.create_pk(
            self.srs[k_committee], spec, k_committee,
            default_committee_update_args(spec), self.backend)
        self.compress = compress
        if compress:
            from ..models import AggregationArgs, AggregationCircuit
            from ..plonk.transcript import PoseidonTranscript
            self.k_agg = k_agg
            self.srs[k_agg] = SRS.load_or_setup(k_agg, params_dir)
            self.step_agg = AggregationCircuit.variant("sync_step")
            self.committee_agg = AggregationCircuit.variant("committee_update")
            # lazy thunks: a dummy inner proof is only generated when the
            # aggregation pk is not already cached
            self.step_agg_pk = self.step_agg.create_pk(
                self.srs[k_agg], spec, k_agg,
                lambda: self._dummy_agg_args(StepCircuit, self.step_pk,
                                             self.k_step,
                                             default_sync_step_args(spec)),
                self.backend)
            self.committee_agg_pk = self.committee_agg.create_pk(
                self.srs[k_agg], spec, k_agg,
                lambda: self._dummy_agg_args(CommitteeUpdateCircuit,
                                             self.committee_pk,
                                             self.k_committee,
                                             default_committee_update_args(spec)),
                self.backend)
        # readiness self-check (ISSUE 9): prove+verify a tiny cached
        # circuit before the box reports ready — GET /healthz stays 503
        # until it passes, and it re-runs after every SDC retry
        from .selfverify import SelfCheck
        self.self_check = SelfCheck()
        with phase("boot/self_check"):
            self.self_check.run()

    def _dummy_agg_args(self, circuit, pk, k, dummy_args):
        from ..models import AggregationArgs
        from ..plonk.transcript import PoseidonTranscript
        proof = circuit.prove(pk, self.srs[k], dummy_args, self.spec,
                              self.backend, transcript=PoseidonTranscript())
        inst = circuit.get_instances(dummy_args, self.spec)
        return AggregationArgs(inner_vk=pk.vk, srs=self.srs[k],
                               inner_instances=[inst], proof=proof)

    def _compressed(self, circuit, pk, k, agg_cls, agg_pk, args, bk=None,
                    heartbeat=None):
        from ..models import AggregationArgs, AggregationCircuit
        from ..plonk.transcript import KeccakTranscript, PoseidonTranscript
        hb = heartbeat or (lambda: None)
        bk = bk if bk is not None else self.backend
        with phase("prove/app_snark"):
            app_proof = circuit.prove(pk, self.srs[k], args, self.spec, bk,
                                      transcript=PoseidonTranscript())
        hb()              # phase boundary: app snark done, aggregation next
        inst = circuit.get_instances(args, self.spec)
        agg_args = AggregationArgs(inner_vk=pk.vk, srs=self.srs[k],
                                   inner_instances=[inst], proof=app_proof)
        with phase("prove/aggregation"):
            outer = agg_cls.prove(agg_pk, self.srs[self.k_agg], agg_args,
                                  self.spec, bk,
                                  transcript=KeccakTranscript())
        hb()
        return outer, AggregationCircuit.get_instances(agg_args, self.spec)

    def _release_idle_ext_caches(self, *active_pks):
        """Drop cached extended-domain fixed columns on every pk EXCEPT the
        ones about to prove: the per-pk caches are GBs at production degrees
        and would otherwise stack across circuit families (all four pks
        resident), raising the service's peak RSS well above one prove's."""
        for pk in (self.step_pk, self.committee_pk,
                   getattr(self, "step_agg_pk", None),
                   getattr(self, "committee_agg_pk", None)):
            if pk is not None and all(pk is not a for a in active_pks):
                pk.release_ext_cache()

    def prove_step(self, args, heartbeat=None,
                   backend=None) -> tuple[bytes, list]:
        """`heartbeat` (optional zero-arg callback, threaded in by the job
        queue's worker) is stamped between prove phases so the supervisor
        can tell a long legitimate prove from a hung worker. `backend`
        overrides the boot backend for this one prove — the self-verify
        SDC retry pins it to CPU (selfverify.verified_prove)."""
        hb = heartbeat or (lambda: None)
        bk0 = backend if backend is not None else self.backend
        with self.semaphore:
            hb()                     # phase: permit acquired, prove starts
            self._release_idle_ext_caches(self.step_pk,
                                          getattr(self, "step_agg_pk", None))
            if self.compress:
                return B.prove_with_fallback(
                    lambda bk: self._compressed(StepCircuit, self.step_pk,
                                                self.k_step, self.step_agg,
                                                self.step_agg_pk, args,
                                                bk=bk, heartbeat=hb),
                    bk0)
            proof = B.prove_with_fallback(
                lambda bk: StepCircuit.prove(self.step_pk,
                                             self.srs[self.k_step],
                                             args, self.spec, bk),
                bk0)
            hb()
        return proof, StepCircuit.get_instances(args, self.spec)

    def prove_step_batch(self, args_list: list) -> list:
        """Prove a batch of sync-step requests concurrently (SURVEY §2c(b)):
        a pool sized by the concurrency governor; each worker still takes a
        semaphore permit, so combined RPC + batch load honors one cap.
        Witness generation runs in threads (builder work releases the GIL
        during backend/numpy calls); commit-phase MSMs of concurrent proofs
        share the backend's cached device base and the mesh batch axis."""
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(1, self.concurrency)) as ex:
            return list(ex.map(self.prove_step, args_list))

    def prove_committee_batch(self, args_list: list) -> list:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(1, self.concurrency)) as ex:
            return list(ex.map(self.prove_committee, args_list))

    def prove_committee(self, args, heartbeat=None,
                        backend=None) -> tuple[bytes, list]:
        hb = heartbeat or (lambda: None)
        bk0 = backend if backend is not None else self.backend
        with self.semaphore:
            hb()
            self._release_idle_ext_caches(
                self.committee_pk, getattr(self, "committee_agg_pk", None))
            if self.compress:
                return B.prove_with_fallback(
                    lambda bk: self._compressed(CommitteeUpdateCircuit,
                                                self.committee_pk,
                                                self.k_committee,
                                                self.committee_agg,
                                                self.committee_agg_pk, args,
                                                bk=bk, heartbeat=hb),
                    bk0)
            proof = B.prove_with_fallback(
                lambda bk: CommitteeUpdateCircuit.prove(
                    self.committee_pk, self.srs[self.k_committee], args,
                    self.spec, bk),
                bk0)
            hb()
        return proof, CommitteeUpdateCircuit.get_instances(args, self.spec)

    def verify_proof(self, kind: str, proof: bytes, instances: list) -> bool:
        """Host-side check of a fresh proof against the matching verifying
        key — the milliseconds verify-before-serve spends so an SDC'd
        prove never leaves the box (selfverify.verified_prove). `kind` is
        "step" or "committee"; `instances` is the flat public-input list
        the prove returned."""
        if self.compress:
            from ..plonk.transcript import KeccakTranscript
            agg = self.step_agg if kind == "step" else self.committee_agg
            agg_pk = (self.step_agg_pk if kind == "step"
                      else self.committee_agg_pk)
            return bool(agg.verify(agg_pk.vk, self.srs[self.k_agg],
                                   instances, proof,
                                   transcript_cls=KeccakTranscript))
        circuit = StepCircuit if kind == "step" else CommitteeUpdateCircuit
        pk = self.step_pk if kind == "step" else self.committee_pk
        k = self.k_step if kind == "step" else self.k_committee
        return bool(circuit.verify(pk.vk, self.srs[k], instances, proof))

"""ProverState: SRS + proving keys loaded once at boot.

Reference parity: `prover/src/prover.rs:43-117` (`ProverState::new`: SRS map
by degree, pkeys for step/committee circuits created from default witnesses)
and the semaphore-based concurrency cap (`prover.rs:40`) — here a
threading.Semaphore, acquired by the RPC handlers.
"""

from __future__ import annotations

import threading

from .. import spec as spec_mod
from ..models import CommitteeUpdateCircuit, StepCircuit
from ..plonk import backend as B
from ..plonk.srs import SRS
from ..witness import default_committee_update_args, default_sync_step_args


class ProverState:
    def __init__(self, spec, k_step: int, k_committee: int,
                 concurrency: int = 1, backend: str = "cpu",
                 params_dir: str | None = None):
        self.spec = spec
        self.backend = B.get_backend(backend)
        self.semaphore = threading.Semaphore(concurrency)
        self.srs = {}
        for k in {k_step, k_committee}:
            self.srs[k] = SRS.load_or_setup(k, params_dir)
        self.k_step, self.k_committee = k_step, k_committee
        self.step_pk = StepCircuit.create_pk(
            self.srs[k_step], spec, k_step,
            default_sync_step_args(spec), self.backend)
        self.committee_pk = CommitteeUpdateCircuit.create_pk(
            self.srs[k_committee], spec, k_committee,
            default_committee_update_args(spec), self.backend)

    def prove_step(self, args) -> tuple[bytes, list]:
        with self.semaphore:
            proof = StepCircuit.prove(self.step_pk, self.srs[self.k_step],
                                      args, self.spec, self.backend)
        return proof, StepCircuit.get_instances(args, self.spec)

    def prove_committee(self, args) -> tuple[bytes, list]:
        with self.semaphore:
            proof = CommitteeUpdateCircuit.prove(
                self.committee_pk, self.srs[self.k_committee], args,
                self.spec, self.backend)
        return proof, CommitteeUpdateCircuit.get_instances(args, self.spec)

"""`python -m spectre_tpu.prover_service <cmd>` — delegates to cli.main
(the `scrub` subcommand is the usual reason to invoke the module form)."""

from .cli import main

main()

"""EVM calldata encoding for generated proofs.

Reference parity: snark-verifier's `encode_calldata` (`rpc.rs:160-162`):
instances as 32-byte big-endian words followed by the raw proof bytes — the
layout the generated Solidity verifier expects.
"""

from __future__ import annotations


def encode_calldata(instances: list[int], proof: bytes) -> bytes:
    out = b"".join(int(v).to_bytes(32, "big") for v in instances)
    return out + proof


def decode_calldata(data: bytes, num_instances: int) -> tuple[list[int], bytes]:
    instances = [int.from_bytes(data[32 * i:32 * (i + 1)], "big")
                 for i in range(num_instances)]
    return instances, data[32 * num_instances:]

"""Proof-farm dispatcher: fault-tolerant dispatch over prover replicas.

One box is the ceiling ROADMAP's "Proof farm" item names: the JobQueue
runs every prove on the local ProverState. This module lifts the PR-6
worker-supervision pattern one level — from threads inside one process
to replicas across hosts — so the service survives a replica dying
mid-prove, a silently corrupting host, or a whole rack going dark:

* **Replicas** register with a capability/health record.
  :class:`LocalReplica` wraps an in-process ProverState (or any runner
  callable — tests use canned runners); :class:`HttpReplica` fronts a
  remote prover via the existing ``rpc_client`` submit/poll API.
* **Routing** is rendezvous hashing on the witness digest — the
  JobQueue's existing dedup key — so retries and resubmits of the same
  witness land on the same replica (warm caches) without any shared
  routing state.
* **Leases**: a replica owns a job only while its heartbeat renews.
  A crashed replica signals nothing (its prove thread just dies); a
  stalled one stops renewing; either way the lease expires and the job
  is re-dispatched with the failed replica excluded
  (``dispatcher_lease_takeovers``). Grants and releases are journaled
  (``dispatcher.leases.jsonl``, fsync'd like the job journal), so a
  dispatcher restart replays open leases as exclusions instead of
  re-trusting the replica that died holding them — combined with the
  queue's witness-digest dedup, a restart never double-proves.
* **Per-replica circuit breaker** — the exact beacon breaker machinery
  (utils/breaker.py): N consecutive failures stop a replica receiving
  work for a cooldown, one half-open trial re-admits it.
* **Cross-host verification** (closes the PR-9 carry): with a
  ``verify_state``, every proof a replica returns is re-verified by the
  *dispatcher's* host before release; a verify failure quarantines the
  bytes and re-dispatches to a *different* replica
  (``dispatcher_sdc_rerouted``) — a bad DIMM can no longer hit both the
  prove and the retry.

The Dispatcher is callable with the JobQueue runner signature
``(method, params, heartbeat=None)``, so ``ensure_jobs(state,
runner=dispatcher)`` points an unchanged queue (and the follower above
it) at the farm. Fault sites ``replica.dispatch`` / ``replica.health`` /
``replica.lease`` / ``replica.register`` (utils/faults.py) make the
whole failover matrix drillable; every ``dispatcher_*`` counter rides
HEALTH.snapshot() into ``/healthz`` and ``/metrics`` with zero exporter
changes.

ISSUE 18 makes the farm self-managing:

* **Dynamic membership with liveness** — replicas announce themselves
  (``registerReplica`` RPC -> :meth:`Dispatcher.register_remote`) with
  a structured :class:`ReplicaCapabilities` record (device kind, memory
  MB, mesh shape, supported methods, max k). Re-announcements are
  heartbeats; a replica silent past ``SPECTRE_REPLICA_TTL_S`` is
  demoted through its existing circuit breaker and deregistered
  (:meth:`sweep_members`). Joins and leaves are fsync-journaled
  (``dispatcher.members.jsonl``), replayed and compacted exactly like
  the lease journal, so a dispatcher restart reconstructs the fleet —
  every replayed member gets one fresh TTL window to re-announce.
* **Capability-aware placement** — rendezvous hashing stays, but ranks
  the *eligible* set first: aggregation/compression proves go to
  replicas advertising a mesh or the largest memory, k-sized work to
  replicas whose declared ``max_k`` covers the job. Only when no
  capable replica is healthy does routing fall back to the rest,
  visibly (``dispatcher_placement_fallbacks``).

Importable without jax (prom.py pulls :func:`dispatcher_snapshot`);
heavy prover imports stay inside the replica prove paths.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import weakref

from ..observability import manifest as obs_manifest
from ..utils import faults
from ..utils.breaker import BreakerOpen, CircuitBreaker
from ..utils.health import HEALTH

LEASE_JOURNAL_NAME = "dispatcher.leases.jsonl"
MEMBER_JOURNAL_NAME = "dispatcher.members.jsonl"

TTL_ENV = "SPECTRE_REPLICA_TTL_S"
TTL_DEFAULT_S = 60.0
ANNOUNCE_ENV = "SPECTRE_ANNOUNCE_INTERVAL_S"
ANNOUNCE_DEFAULT_S = 15.0

# exclusion-map bound: digests of completed jobs are dropped eagerly;
# this caps pathological churn (many distinct failing digests)
_MAX_EXCLUDED_DIGESTS = 4096


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


class NoReplicaAvailable(RuntimeError):
    """Every candidate replica is excluded, unhealthy, breaker-open or
    has already failed this job."""


def _is_infra_error(exc: BaseException) -> bool:
    """Failures worth failing over: another replica may well succeed.

    Deterministic prover errors (witness rejection, verify failure,
    bad params) re-raise unchanged so the RPC error taxonomy — and any
    caller matching on exception class — sees exactly what a
    single-replica deployment would."""
    if isinstance(exc, (TimeoutError, ConnectionError,
                        faults.InjectedFault, OSError)):
        return True
    # RpcError from an HttpReplica: retry elsewhere only for
    # overload/internal; -32000/-32005-style outcomes are deterministic
    return getattr(exc, "code", None) in (-32001, -32603)


# -- capability records -----------------------------------------------------


class ReplicaCapabilities:
    """Structured capability record a replica announces (ISSUE 18):
    device kind, memory MB, mesh shape, the set of supported RPC
    methods (None = all) and the largest circuit size (``max_k``) the
    box can prove. ``url`` is where the dispatcher reaches the replica.
    Every field is optional — an empty record constrains nothing, so a
    capability-less fleet routes exactly like before."""

    FIELDS = ("device", "memory_mb", "mesh_shape", "methods", "max_k", "url")

    def __init__(self, device=None, memory_mb=None, mesh_shape=None,
                 methods=None, max_k=None, url=None):
        self.device = str(device) if device else None
        self.memory_mb = float(memory_mb) if memory_mb is not None else None
        self.mesh_shape = (tuple(int(x) for x in mesh_shape)
                           if mesh_shape else None)
        self.methods = set(methods) if methods else None
        self.max_k = int(max_k) if max_k is not None else None
        self.url = str(url) if url else None

    @classmethod
    def coerce(cls, value) -> "ReplicaCapabilities | None":
        """Accept the structured record, a plain dict (the RPC wire
        form), or — backward compatibility with the PR-11 surface — a
        bare iterable of method names."""
        if value is None or isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**{k: v for k, v in value.items() if k in cls.FIELDS})
        return cls(methods=value)

    def supports_method(self, method: str) -> bool:
        return self.methods is None or method in self.methods

    def to_dict(self) -> dict:
        return {
            "device": self.device,
            "memory_mb": self.memory_mb,
            "mesh_shape": list(self.mesh_shape) if self.mesh_shape else None,
            "methods": sorted(self.methods) if self.methods else None,
            "max_k": self.max_k,
            "url": self.url,
        }

    def __repr__(self):
        return f"<ReplicaCapabilities {self.to_dict()}>"


def capability_record(state=None, url: str | None = None) -> dict:
    """Best-effort capability record for THIS host, announced by
    ``serve()``'s announce loop. Memory comes from sysconf, the mesh
    shape from ``SPECTRE_MESH_SHAPE`` (the parallel/ knob), device kind
    and max k from the ProverState when one is given."""
    rec: dict = {"device": None, "memory_mb": None, "mesh_shape": None,
                 "methods": None, "max_k": None, "url": url}
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
        rec["memory_mb"] = round(pages * page / 2 ** 20, 1)
    except (AttributeError, OSError, ValueError):
        pass
    mesh = os.environ.get("SPECTRE_MESH_SHAPE", "")
    if mesh.strip():
        try:
            rec["mesh_shape"] = [int(x) for x in
                                 mesh.replace("x", ",").split(",")
                                 if x.strip()]
        except ValueError:
            pass
    if state is not None:
        backend = getattr(state, "backend", None)
        if backend is not None:
            rec["device"] = type(backend).__name__.removesuffix(
                "Backend").lower() or None
        ks = [getattr(state, a, None) for a in ("k_step", "k_committee")]
        if getattr(state, "compress", False):
            ks.append(getattr(state, "k_agg", None))
        ks = [k for k in ks if isinstance(k, int)]
        if ks:
            rec["max_k"] = max(ks)
    return rec


# -- replicas ---------------------------------------------------------------


class Replica:
    """Registration record + prove entry for one prover replica."""

    def __init__(self, replica_id: str, capabilities=None):
        self.replica_id = str(replica_id)
        # structured record; bare method-name sets coerce (PR-11 compat)
        self.caps = ReplicaCapabilities.coerce(capabilities)

    def supports(self, method: str) -> bool:
        return self.caps is None or self.caps.supports_method(method)

    def healthy(self) -> bool:
        faults.check("replica.health")
        return self._healthy()

    def _healthy(self) -> bool:
        return True

    def prove(self, method: str, params: dict, heartbeat=None) -> dict:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.replica_id}>"


class LocalReplica(Replica):
    """In-process replica: proves on a ProverState (or a custom runner
    callable with the queue-runner signature — tests use canned ones)."""

    def __init__(self, replica_id: str, state=None, runner=None,
                 capabilities=None):
        super().__init__(replica_id, capabilities)
        self.state = state
        self._runner = runner

    def prove(self, method: str, params: dict, heartbeat=None) -> dict:
        faults.check("replica.dispatch")
        if self._runner is not None:
            return self._runner(method, params, heartbeat=heartbeat)
        from .rpc import run_proof_method
        return run_proof_method(self.state, method, params,
                                heartbeat=heartbeat)

    def _healthy(self) -> bool:
        return self.state is not None or self._runner is not None


class HttpReplica(Replica):
    """Remote replica via the resilient rpc_client: submit + poll, each
    status poll renewing the dispatcher lease (heartbeat)."""

    def __init__(self, replica_id: str, client, poll_s: float = 1.0,
                 sleep=time.sleep, capabilities=None):
        super().__init__(replica_id, capabilities)
        self.client = client
        self.poll_s = poll_s
        self._sleep = sleep

    def _healthy(self) -> bool:
        try:
            return self.client.ping() == "pong"
        except faults.InjectedCrash:
            raise
        except Exception:
            return False

    def prove(self, method: str, params: dict, heartbeat=None) -> dict:
        faults.check("replica.dispatch")
        from .rpc import (RPC_METHOD_AGG, RPC_METHOD_AGG_SUBMIT,
                          RPC_METHOD_COMMITTEE, RPC_METHOD_COMMITTEE_SUBMIT,
                          RPC_METHOD_STEP, RPC_METHOD_STEP_SUBMIT)
        submit = {RPC_METHOD_STEP: RPC_METHOD_STEP_SUBMIT,
                  RPC_METHOD_COMMITTEE: RPC_METHOD_COMMITTEE_SUBMIT,
                  RPC_METHOD_AGG: RPC_METHOD_AGG_SUBMIT,
                  }.get(method)
        if submit is None:
            return self.client._call(method, params)
        jid = self.client._call_shedding(
            submit, params,
            timeout=min(self.client.timeout, 60.0))["job_id"]
        while True:
            st = self.client.proof_status(jid)
            if heartbeat is not None:
                heartbeat()      # remote made progress -> renew the lease
            if st["status"] in ("done", "failed", "cancelled"):
                return self.client.proof_result(jid)
            self._sleep(self.poll_s)


# -- registry for /metrics (prom.py) ---------------------------------------

_DISPATCHERS: "weakref.WeakSet" = weakref.WeakSet()


def dispatcher_snapshot() -> list[dict]:
    """Per-replica state of every live Dispatcher, for the Prometheus
    exporter (spectre_replica_* gauges) — mirrors beacon.breaker_snapshot."""
    out: list[dict] = []
    for d in list(_DISPATCHERS):
        out.extend(d.snapshot()["replicas"])
    return out


# -- dispatcher -------------------------------------------------------------


class Dispatcher:
    """Routes queue jobs across replicas with leases, breakers and
    cross-host verification. Callable with the JobQueue runner
    signature, so ``ensure_jobs(state, runner=dispatcher)`` is the whole
    integration."""

    def __init__(self, replicas=(), journal_dir=None, lease_s=None,
                 verify_state=None, health=HEALTH, clock=time.monotonic,
                 poll_s: float = 0.02, health_ttl_s: float = 5.0,
                 breaker_threshold: int | None = None,
                 breaker_cooldown: float | None = None,
                 ttl_s: float | None = None,
                 method_k: dict | None = None):
        self.lease_s = lease_s if lease_s is not None \
            else _env_float("SPECTRE_REPLICA_LEASE_S", 120.0)
        self.ttl_s = ttl_s if ttl_s is not None \
            else _env_float(TTL_ENV, TTL_DEFAULT_S)
        self.verify_state = verify_state
        self.health = health
        self._clock = clock
        self.poll_s = poll_s
        self.health_ttl_s = health_ttl_s
        # per-method circuit-size hints for max-k placement; methods the
        # dict (and the verify_state fallback) don't cover route unhinted
        self.method_k = dict(method_k) if method_k else {}
        self._breaker_threshold = breaker_threshold \
            if breaker_threshold is not None \
            else _env_int("SPECTRE_REPLICA_CB_THRESHOLD", 5)
        self._breaker_cooldown = breaker_cooldown \
            if breaker_cooldown is not None \
            else _env_float("SPECTRE_REPLICA_CB_COOLDOWN", 30.0)
        self._lock = threading.Lock()
        self.replicas: list[Replica] = []
        self._breakers: dict[str, CircuitBreaker] = {}
        self._stats: dict[str, dict] = {}
        self._excluded: dict[str, set] = {}     # digest -> failed replica ids
        self._takeover_due: set[str] = set()    # digests with a dead lease
        self._active: dict[str, str] = {}       # digest -> replica id
        self._health_cache: dict[str, tuple] = {}
        self._heartbeats: dict[str, float] = {}  # rid -> last announce
        self._dynamic: set[str] = set()          # TTL-governed member ids
        self._queue = None                      # attached by ensure_jobs
        for r in replicas:
            self.register(r)
        self._journal_path = None
        self._member_journal_path = None
        if journal_dir is not None:
            os.makedirs(journal_dir, exist_ok=True)
            self._journal_path = os.path.join(journal_dir, LEASE_JOURNAL_NAME)
            self._member_journal_path = os.path.join(journal_dir,
                                                     MEMBER_JOURNAL_NAME)
            self._replay_journal()
            self._replay_members()
        _DISPATCHERS.add(self)

    # -- registration ------------------------------------------------------

    def register(self, replica: Replica, dynamic: bool = False) -> None:
        with self._lock:
            if any(r.replica_id == replica.replica_id for r in self.replicas):
                raise ValueError(f"duplicate replica id {replica.replica_id}")
            self.replicas.append(replica)
            if replica.replica_id not in self._breakers:
                self._breakers[replica.replica_id] = CircuitBreaker(
                    threshold=self._breaker_threshold,
                    cooldown=self._breaker_cooldown,
                    health=self.health, counter_prefix="dispatcher_breaker")
            if dynamic:
                self._dynamic.add(replica.replica_id)
                self._heartbeats[replica.replica_id] = self._clock()
        self.health.incr("dispatcher_replicas_registered")

    def register_remote(self, replica_id: str, url: str | None = None,
                        capabilities=None, _journal: bool = True) -> dict:
        """``registerReplica`` RPC entry: first announce joins the fleet
        as a TTL-governed :class:`HttpReplica`; re-announces are
        heartbeats that refresh the capability record. A re-join after a
        TTL deregistration keeps the replica's existing breaker — an
        open breaker stays open, so a flapping box earns readmission
        through the half-open trial like any other failure."""
        faults.check("replica.register")
        rid = str(replica_id)
        caps = ReplicaCapabilities.coerce(capabilities)
        if caps is not None and url and caps.url is None:
            caps.url = str(url)
        with self._lock:
            existing = next((r for r in self.replicas
                             if r.replica_id == rid), None)
        if existing is None:
            if not url:
                raise ValueError(
                    f"registerReplica for {rid} needs a url to dial back")
            from .rpc_client import ProverClient
            replica = HttpReplica(
                rid, ProverClient(url),
                capabilities=caps or ReplicaCapabilities(url=url))
            self.register(replica, dynamic=True)
            self.health.incr("dispatcher_members_joined")
            if _journal:
                self._member_journal({
                    "event": "join", "replica": rid, "url": url,
                    "capabilities": replica.caps.to_dict(),
                    "ts": time.time()})
        else:
            if caps is not None:
                existing.caps = caps
            if url and isinstance(existing, HttpReplica) \
                    and url not in existing.client.urls:
                existing.client.url = url   # replica moved (new port)
            with self._lock:
                self._heartbeats[rid] = self._clock()
            self.health.incr("dispatcher_heartbeats")
        return {"replica_id": rid, "ttl_s": self.ttl_s,
                "members": len(self.replicas)}

    def deregister(self, replica_id: str, reason: str = "manual") -> bool:
        """Remove a replica from membership (journaled). Breaker and
        dispatch stats survive, so a later re-join keeps its history."""
        rid = str(replica_id)
        with self._lock:
            before = len(self.replicas)
            self.replicas = [r for r in self.replicas
                             if r.replica_id != rid]
            removed = len(self.replicas) < before
            self._dynamic.discard(rid)
            self._heartbeats.pop(rid, None)
            self._health_cache.pop(rid, None)
        if removed:
            self.health.incr("dispatcher_members_left")
            self._member_journal({"event": "leave", "replica": rid,
                                  "reason": reason, "ts": time.time()})
        return removed

    def sweep_members(self) -> list[str]:
        """Liveness sweep (clock-driven — called from dispatch() and
        snapshot(), no background thread): a dynamic member whose last
        announce is older than ``ttl_s`` is demoted through its existing
        circuit breaker (in-flight routing stops admitting it before it
        is even gone) and then deregistered, journaled as a leave."""
        now = self._clock()
        with self._lock:
            expired = [rid for rid in self._dynamic
                       if now - self._heartbeats.get(rid, 0.0) > self.ttl_s]
        for rid in expired:
            br = self._breakers.get(rid)
            while br is not None and br.state != "open":
                br.record(False)
            self.deregister(rid, reason="ttl")
            self.health.incr("dispatcher_member_ttl_expired")
        return expired

    def breaker(self, replica_id: str) -> CircuitBreaker:
        return self._breakers[replica_id]

    def attach_queue(self, jobsq) -> None:
        """Called by ensure_jobs: gives the dispatcher the queue's
        artifact store (SDC quarantine) without a constructor cycle."""
        self._queue = jobsq

    # -- lease journal -----------------------------------------------------

    def _replay_journal(self):
        try:
            with open(self._journal_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        open_leases: dict[str, str] = {}
        failed: list[tuple] = []
        lines = 0
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            lines += 1
            try:
                rec = json.loads(line)
            except ValueError:
                continue        # torn tail (crash mid-append)
            ev = rec.get("event")
            if ev == "lease":
                open_leases[rec["digest"]] = rec["replica"]
            elif ev == "release":
                open_leases.pop(rec["digest"], None)
                if rec.get("outcome") != "done":
                    failed.append((rec["digest"], rec["replica"]))
        for digest, rid in failed:
            self._excluded.setdefault(digest, set()).add(rid)
        for digest, rid in open_leases.items():
            # the previous dispatcher died while this replica held the
            # lease: don't re-trust it for this digest, and count the
            # first re-grant as a takeover
            self._excluded.setdefault(digest, set()).add(rid)
            self._takeover_due.add(digest)
            self.health.incr("dispatcher_leases_replayed")
        # startup compaction (ISSUE 14 satellite, carried from PR 11):
        # keep only what replay needs — the full grant/release history
        # grows without bound on a long-lived farm head
        kept = len(open_leases) + len(failed)
        if lines > kept:
            self._compact_journal(open_leases, failed)

    def _compact_journal(self, open_leases: dict, failed: list):
        """Atomically rewrite the lease journal down to its replay
        fixpoint (the JobJournal.compact idiom): one `lease` record per
        still-open lease and one failed `release` per exclusion —
        replaying the compacted file reconstructs exactly the state
        replaying the full history did. Crash-safe: the rewrite is
        staged to a sidecar, fsync'd, then `os.replace`d; a crash in the
        staged-but-unswapped window (fault site `replica.lease_compact`)
        leaves the ORIGINAL journal untouched and the next startup
        re-compacts. IO errors are tolerated (the journal keeps its full
        history, counted on dispatcher_lease_compact_failures)."""
        tmp = self._journal_path + ".compact"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for digest, rid in sorted(failed):
                    # a release with no prior grant replays straight
                    # into the exclusion set
                    f.write(json.dumps(
                        {"event": "release", "digest": digest,
                         "replica": rid, "outcome": "failed"},
                        sort_keys=True) + "\n")
                for digest, rid in sorted(open_leases.items()):
                    f.write(json.dumps(
                        {"event": "lease", "digest": digest,
                         "replica": rid}, sort_keys=True) + "\n")
                f.flush()
                # crash window: sidecar staged, original journal intact
                faults.check("replica.lease_compact")
                os.fsync(f.fileno())
            os.replace(tmp, self._journal_path)
            try:
                dfd = os.open(os.path.dirname(self._journal_path) or ".",
                              os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
            self.health.incr("dispatcher_lease_compactions")
        except faults.InjectedCrash:
            raise
        except Exception:
            self.health.incr("dispatcher_lease_compact_failures")

    def _journal(self, rec: dict):
        """fsync'd append; `replica.lease` fires AFTER a grant lands on
        disk (the post-append crash window journal replay must cover).
        IO errors are tolerated — the farm keeps proving with in-memory
        lease state, counted on dispatcher_lease_journal_failures."""
        try:
            if self._journal_path is not None:
                with open(self._journal_path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            if rec.get("event") == "lease":
                faults.check("replica.lease")
        except faults.InjectedCrash:
            raise
        except Exception:
            self.health.incr("dispatcher_lease_journal_failures")

    # -- membership journal ------------------------------------------------

    def _member_journal(self, rec: dict):
        """fsync'd append of a join/leave — same tolerance contract as
        the lease journal: IO errors keep the in-memory fleet authoritative,
        counted on dispatcher_member_journal_failures."""
        if self._member_journal_path is None:
            return
        try:
            with open(self._member_journal_path, "a",
                      encoding="utf-8") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except Exception:
            self.health.incr("dispatcher_member_journal_failures")

    def _replay_members(self):
        """Reconstruct the fleet from ``dispatcher.members.jsonl``: last
        join/leave per replica id wins. A restored member re-dials its
        announced url and gets ONE fresh TTL window — it either
        re-announces (it survived the dispatcher restart) or the next
        sweep deregisters it. Statically-registered ids are never
        shadowed by the journal."""
        try:
            with open(self._member_journal_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        live: dict[str, dict] = {}
        lines = 0
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            lines += 1
            try:
                rec = json.loads(line)
            except ValueError:
                continue        # torn tail (crash mid-append)
            ev = rec.get("event")
            if ev == "join" and rec.get("replica"):
                live[rec["replica"]] = rec
            elif ev == "leave":
                live.pop(rec.get("replica"), None)
        for rid, rec in live.items():
            url = rec.get("url")
            if not url or any(r.replica_id == rid for r in self.replicas):
                continue
            try:
                from .rpc_client import ProverClient
                caps = ReplicaCapabilities.coerce(rec.get("capabilities")) \
                    or ReplicaCapabilities(url=url)
                self.register(HttpReplica(rid, ProverClient(url),
                                          capabilities=caps), dynamic=True)
                self.health.incr("dispatcher_members_replayed")
            except Exception:
                continue        # malformed record: membership is best-effort
        if lines > len(live):
            self._compact_members(live)

    def _compact_members(self, live: dict):
        """Rewrite the member journal to its replay fixpoint — one join
        per live member — with the lease-compaction idiom: staged
        sidecar, fsync, atomic replace; IO failures keep the full
        history (dispatcher_member_compact_failures)."""
        tmp = self._member_journal_path + ".compact"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for rid in sorted(live):
                    f.write(json.dumps(live[rid], sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._member_journal_path)
            try:
                dfd = os.open(
                    os.path.dirname(self._member_journal_path) or ".",
                    os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
            self.health.incr("dispatcher_member_compactions")
        except Exception:
            self.health.incr("dispatcher_member_compact_failures")

    # -- routing -----------------------------------------------------------

    def _healthy_cached(self, replica: Replica) -> bool:
        now = self._clock()
        cached = self._health_cache.get(replica.replica_id)
        if cached is not None and now - cached[0] < self.health_ttl_s:
            return cached[1]
        try:
            ok = bool(replica.healthy())
        except faults.InjectedCrash:
            raise
        except Exception:
            ok = False
        self._health_cache[replica.replica_id] = (now, ok)
        return ok

    def _method_k(self, method: str) -> int | None:
        """Circuit-size hint for max-k placement: an explicit
        ``method_k`` entry wins, else the verify_state's own k knobs
        (the dispatcher head is configured like its replicas)."""
        if method in self.method_k:
            return self.method_k[method]
        vs = self.verify_state
        if vs is None:
            return None
        if getattr(vs, "compress", False):
            k = getattr(vs, "k_agg", None)
        elif "Committee" in method or "Aggregation" in method:
            k = getattr(vs, "k_committee", None)
        else:
            k = getattr(vs, "k_step", None)
        return k if isinstance(k, int) else None

    def _eligible(self, method: str) -> tuple[set, bool]:
        """Capability-aware eligible set (ISSUE 18). Returns
        ``(eligible_ids, constrained)`` — constrained=False means the
        fleet advertises nothing to distinguish on for this method and
        routing degenerates to plain rendezvous."""
        with self._lock:
            replicas = list(self.replicas)
        eligible = {r.replica_id for r in replicas}
        constrained = False
        if "Aggregation" in method:
            # the big compression prove wants a mesh or the biggest box
            meshy = {r.replica_id for r in replicas
                     if r.caps is not None and r.caps.mesh_shape}
            mems = [(r.caps.memory_mb, r.replica_id) for r in replicas
                    if r.caps is not None and r.caps.memory_mb is not None]
            big = set()
            if mems:
                top = max(mb for mb, _ in mems)
                big = {rid for mb, rid in mems if mb == top}
            if meshy or big:
                eligible &= meshy | big
                constrained = True
        k = self._method_k(method)
        if k is not None:
            # only replicas DECLARING a too-small max_k are ruled out;
            # an undeclared max_k constrains nothing
            small = {r.replica_id for r in replicas
                     if r.caps is not None and r.caps.max_k is not None
                     and r.caps.max_k < k}
            if small:
                eligible -= small
                constrained = True
        return eligible, constrained

    def _route(self, method: str, digest: str, excluded) -> Replica | None:
        """Rendezvous hashing: stable per-digest replica ranking with no
        shared routing state — the same witness always prefers the same
        replica, and losing a replica only moves its own keys. With
        capability constraints the eligible set ranks first; dispatching
        from the remainder is a visible fallback
        (``dispatcher_placement_fallbacks``)."""
        ranked = sorted(self.replicas, key=lambda r: hashlib.sha256(
            f"{digest}|{r.replica_id}".encode()).hexdigest())
        eligible, constrained = self._eligible(method)
        if constrained:
            tiers = [[r for r in ranked if r.replica_id in eligible],
                     [r for r in ranked if r.replica_id not in eligible]]
        else:
            tiers = [ranked]
        for tier_i, pool in enumerate(tiers):
            for replica in pool:
                rid = replica.replica_id
                if rid in excluded or not replica.supports(method):
                    continue
                try:
                    self._breakers[rid].admit()
                except BreakerOpen:
                    self.health.incr("dispatcher_breaker_skips")
                    continue
                if not self._healthy_cached(replica):
                    self.health.incr("dispatcher_replica_unhealthy")
                    continue
                if tier_i == 1:
                    self.health.incr("dispatcher_placement_fallbacks")
                return replica
        return None

    # -- lease lifecycle ---------------------------------------------------

    def _grant(self, digest: str, rid: str, takeover: bool):
        with self._lock:
            self._active[digest] = rid
            self._stats[rid] = st = self._stats.get(
                rid, {"dispatched": 0, "failures": 0})
            st["dispatched"] += 1
        self.health.incr("dispatcher_jobs_dispatched")
        if takeover:
            self.health.incr("dispatcher_lease_takeovers")
        obs_manifest.record_event("replica_lease", replica=rid,
                                  takeover=bool(takeover))
        self._journal({"event": "lease", "digest": digest, "replica": rid,
                       "lease_s": self.lease_s, "takeover": bool(takeover),
                       "ts": time.time()})

    def _release(self, digest: str, rid: str, outcome: str):
        with self._lock:
            self._active.pop(digest, None)
            if outcome != "done" and rid in self._stats:
                self._stats[rid]["failures"] += 1
        self._journal({"event": "release", "digest": digest, "replica": rid,
                       "outcome": outcome, "ts": time.time()})

    def _exclude(self, digest: str, rid: str):
        with self._lock:
            self._excluded.setdefault(digest, set()).add(rid)
            while len(self._excluded) > _MAX_EXCLUDED_DIGESTS:
                self._excluded.pop(next(iter(self._excluded)))

    def _run_leased(self, replica: Replica, method: str, params: dict,
                    heartbeat):
        """Run one prove under a lease. Returns (outcome, result, exc):
        outcome is "ok", "error" (replica raised), "crashed" (replica
        thread died signalling nothing — InjectedCrash semantics), or
        "expired" (heartbeat stopped renewing; thread disowned)."""
        lease = {"expires": self._clock() + self.lease_s}

        def renew():
            lease["expires"] = self._clock() + self.lease_s
            if heartbeat is not None:
                heartbeat()

        done = threading.Event()
        box: dict = {}

        def work():
            try:
                box["result"] = replica.prove(method, params, heartbeat=renew)
            except faults.InjectedCrash:
                # a dead replica writes nothing and renews nothing: no
                # done.set() (deliberately NOT try/finally) — the main
                # loop sees a dead thread and takes the lease back
                return
            except BaseException as exc:    # noqa: BLE001 — relayed below
                box["exc"] = exc
            done.set()

        t = threading.Thread(target=work, daemon=True,
                             name=f"replica-{replica.replica_id}")
        t.start()
        while True:
            if done.wait(self.poll_s):
                if "exc" in box:
                    return "error", None, box["exc"]
                return "ok", box["result"], None
            if heartbeat is not None:
                heartbeat()     # supervising IS progress (queue-level stall
                                # detection defers to lease expiry here)
            if not t.is_alive():
                if done.is_set():   # finished in the wait/is_alive window
                    continue
                return "crashed", None, None
            if self._clock() >= lease["expires"]:
                self.health.incr("dispatcher_lease_expired")
                return "expired", None, None    # thread disowned

    # -- dispatch ----------------------------------------------------------

    def __call__(self, method: str, params: dict, heartbeat=None) -> dict:
        return self.dispatch(method, params, heartbeat=heartbeat)

    def dispatch(self, method: str, params: dict, heartbeat=None) -> dict:
        from .jobs import witness_digest
        self.sweep_members()
        digest = witness_digest(method, params)
        with self._lock:
            excluded = set(self._excluded.get(digest, ()))
            lease_failed = digest in self._takeover_due
            self._takeover_due.discard(digest)
        tried: set[str] = set()
        sdc_from: str | None = None
        last_exc: BaseException | None = None
        while True:
            replica = self._route(method, digest, excluded | tried)
            if replica is None:
                self.health.incr("dispatcher_no_replica")
                err = NoReplicaAvailable(
                    f"no replica available for {method} (digest "
                    f"{digest[:12]}…, {len(tried)} failed this dispatch, "
                    f"{len(excluded)} excluded, "
                    f"{len(self.replicas)} registered)")
                raise err from last_exc
            rid = replica.replica_id
            self._grant(digest, rid, takeover=lease_failed)
            lease_failed = False
            outcome, result, exc = self._run_leased(
                replica, method, params, heartbeat)
            br = self._breakers[rid]

            if outcome == "ok":
                br.record(True)
                verified = True
                if self.verify_state is not None:
                    from . import selfverify
                    verified = selfverify.cross_verify(
                        self.verify_state, method, result,
                        health=self.health)
                if verified:
                    self._release(digest, rid, "done")
                    with self._lock:
                        self._excluded.pop(digest, None)
                    if sdc_from is not None:
                        obs_manifest.record_event(
                            "sdc_reroute", from_replica=sdc_from,
                            to_replica=rid)
                    return result
                # SDC: this replica's host produced bytes its own
                # verifier liked but ours rejects — quarantine, stop
                # trusting the host for this job, re-prove elsewhere
                self._quarantine_result(result)
                br.record(False)
                self._release(digest, rid, "sdc")
                self._exclude(digest, rid)
                tried.add(rid)
                self.health.incr("dispatcher_sdc_rerouted")
                if sdc_from is not None:
                    # two hosts produced unverifiable proofs: that's not
                    # an SDC, the job is bad — same terminal error as the
                    # single-host path
                    from .selfverify import ProofVerifyFailed, proof_kind
                    raise ProofVerifyFailed(proof_kind(method))
                sdc_from = rid
                continue

            br.record(False)
            self.health.incr("dispatcher_replica_failures")
            self._release(digest, rid, outcome)
            if outcome == "error" and not _is_infra_error(exc):
                raise exc       # deterministic prover error: unchanged
            self._exclude(digest, rid)
            tried.add(rid)
            last_exc = exc
            lease_failed = True     # next grant is a takeover

    def _quarantine_result(self, result):
        store = getattr(getattr(self._queue, "store", None),
                        "quarantine_bytes", None)
        if store is None:
            return
        try:
            from .selfverify import decode_result
            proof, _ = decode_result(result)
            store(proof, suffix=".proof")
        except Exception:
            pass    # quarantine is best-effort; the reroute is the fix

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """Per-replica state for /healthz and the Prometheus gauges —
        including each member's capability record and announce-heartbeat
        age (ISSUE 18). Snapshotting also runs the liveness sweep, so a
        scraped-but-idle dispatcher still expires silent members."""
        self.sweep_members()
        now = self._clock()
        with self._lock:
            reps = []
            for r in self.replicas:
                rid = r.replica_id
                cached = self._health_cache.get(rid)
                st = self._stats.get(rid, {"dispatched": 0, "failures": 0})
                hb = self._heartbeats.get(rid)
                reps.append({
                    "replica_id": rid,
                    "breaker": self._breakers[rid].snapshot(),
                    "healthy": None if cached is None else bool(cached[1]),
                    "active_leases": sum(
                        1 for v in self._active.values() if v == rid),
                    "dispatched": st["dispatched"],
                    "failures": st["failures"],
                    "dynamic": rid in self._dynamic,
                    "capabilities": (None if r.caps is None
                                     else r.caps.to_dict()),
                    "url": None if r.caps is None else r.caps.url,
                    "last_heartbeat_age_s": (None if hb is None
                                             else round(now - hb, 3)),
                })
            return {"replicas": reps, "lease_s": self.lease_s,
                    "ttl_s": self.ttl_s,
                    "members": len(self.replicas),
                    "dynamic_members": len(self._dynamic),
                    "active_leases": len(self._active),
                    "excluded_digests": len(self._excluded)}

"""Verify-before-serve: the output-integrity layer (ISSUE 9 tentpole).

A proof that fails on-chain verification is worse than no proof — the
client burns gas and trust on bytes the service swore were good. Proving
is minutes of accelerator-heavy MSM/NTT arithmetic (exactly where silent
data corruption creeps in); *verification* is milliseconds of host-side
pairing checks. This module spends those milliseconds on every fresh
proof before the job queue marks it ``done``:

* ``verified_prove(state, kind, args)`` wraps ``ProverState.prove_*``:
  the fresh proof bytes pass through fault site ``proof.bytes`` (kind
  ``corrupt`` bit-flips them — the deterministic stand-in for SDC), then
  get verified host-side under a ``prove/self_verify`` span. A verify
  failure is classified as suspected silent data corruption: the suspect
  bytes are quarantined (``results/quarantine/``), the prove is retried
  ONCE on the CPU backend (mirroring ``prove_with_fallback``'s degrade
  semantics), the readiness self-check re-runs, and only a twice-failed
  job goes ``failed(ProofVerifyFailed)``.
* ``SPECTRE_SELF_VERIFY=always|sampled:<p>|off`` (default ``always``)
  trades the verify cost away; ``off`` skips the span entirely. The
  sampling RNG is module-level (``RNG``) so tests inject sequences.
* ``SelfCheck`` proves+verifies a tiny cached K=6 circuit: until it
  passes at startup (and after every SDC retry), ``GET /healthz``
  reports 503 with ``self_check`` in the body — a box that cannot prove
  correctly never reports ready.

Counters (ServiceHealth -> /healthz -> `spectre_*_total` in /metrics):
``proofs_verified``, ``proofs_verify_failed``, ``proofs_sdc_retried``,
``self_check_failures``.
"""

from __future__ import annotations

import functools
import inspect
import os
import random
import threading

from ..observability import manifest as obs_manifest
from ..observability import tracing
from ..utils import faults
from ..utils.health import HEALTH
from ..utils.profiling import phase

ENV_VAR = "SPECTRE_SELF_VERIFY"
PROOF_FAULT_SITE = "proof.bytes"

# sampling RNG for `sampled:<p>` mode — module-level so tests can inject
# a deterministic sequence (monkeypatch selfverify.RNG)
RNG = random.random


class ProofVerifyFailed(RuntimeError):
    """A fresh proof failed host-side verification twice (device prove +
    CPU retry) — suspected silent data corruption; the bytes were
    quarantined, the job must fail rather than serve them."""

    def __init__(self, kind: str):
        super().__init__(
            f"{kind} proof failed self-verification after CPU retry "
            f"(suspected silent data corruption); proof bytes quarantined")
        self.kind = kind


def policy() -> tuple[str, float]:
    """Resolve SPECTRE_SELF_VERIFY into ('always'|'sampled'|'off', p).

    Unparseable values fail SAFE to 'always' — an operator typo must not
    silently disable the integrity layer."""
    raw = os.environ.get(ENV_VAR, "always").strip().lower()
    if raw in ("", "always"):
        return "always", 1.0
    if raw == "off":
        return "off", 0.0
    if raw.startswith("sampled:"):
        try:
            p = float(raw.split(":", 1)[1])
        except ValueError:
            return "always", 1.0
        return "sampled", min(max(p, 0.0), 1.0)
    return "always", 1.0


def _call_prove(fn, args, heartbeat=None, backend=None):
    """Invoke a prove callable, passing heartbeat/backend only if its
    signature accepts them (fakes and legacy states stay callable)."""
    try:
        params = inspect.signature(fn).parameters
        var_kw = any(p.kind == p.VAR_KEYWORD for p in params.values())
    except (TypeError, ValueError):
        params, var_kw = {}, False
    kw = {}
    if heartbeat is not None and ("heartbeat" in params or var_kw):
        kw["heartbeat"] = heartbeat
    if backend is not None and ("backend" in params or var_kw):
        kw["backend"] = backend
    return fn(args, **kw)


def _verify_once(state, kind: str, proof: bytes, instances, attempt: int,
                 health=HEALTH) -> bool:
    with phase("prove/self_verify"):
        try:
            ok = bool(state.verify_proof(kind, proof, instances))
        except Exception as exc:
            # a verifier blow-up on suspect bytes IS a rejection (malformed
            # transcripts normally return False, but never serve on a crash)
            tracing.annotate(self_verify_error=f"{type(exc).__name__}")
            ok = False
    if ok:
        health.incr("proofs_verified")
    else:
        health.incr("proofs_verify_failed")
        tracing.annotate(self_verify_failed=attempt)
        obs_manifest.record_event("proof_verify_failed", proof_kind=kind,
                                  attempt=attempt)
    return ok


def _quarantine_proof(state, proof: bytes):
    """Best-effort: park the suspect bytes in the artifact store's
    quarantine dir (when the state is attached to a journaled queue)."""
    store = getattr(getattr(state, "jobs", None), "store", None)
    if store is None:
        return None
    try:
        return store.quarantine_bytes(proof)
    except Exception:
        return None


def _rerun_self_check(state):
    sc = getattr(state, "self_check", None)
    if sc is None:
        return
    try:
        sc.run()
    except Exception:
        pass                       # readiness probing must not fail the job


def verified_prove(state, kind: str, args, heartbeat=None, health=HEALTH):
    """Prove, then verify before serving. Returns (proof, instances).

    `kind` is "step" or "committee" (selects ``state.prove_<kind>`` and
    the verifying key inside ``state.verify_proof``). States without a
    ``verify_proof`` method (test fakes) skip verification entirely.
    """
    prove_fn = getattr(state, f"prove_{kind}")
    proof, instances = _call_prove(prove_fn, args, heartbeat=heartbeat)
    # SDC stand-in: armed `proof.bytes:corrupt` bit-flips the fresh bytes
    # here, between prove and verify — with self-verify off they are
    # SERVED, which is what the negative pin proves the layer against
    proof = faults.mangle(PROOF_FAULT_SITE, proof)

    mode, p = policy()
    if mode == "off" or not hasattr(state, "verify_proof"):
        return proof, instances
    if mode == "sampled" and RNG() >= p:
        return proof, instances

    if _verify_once(state, kind, proof, instances, attempt=1, health=health):
        return proof, instances

    # suspected SDC: quarantine the suspect bytes, retry once on the CPU
    # backend (the numerically boring path), re-probe readiness
    _quarantine_proof(state, proof)
    health.incr("proofs_sdc_retried")
    tracing.annotate(sdc_retry="cpu")
    obs_manifest.record_event("sdc_retry", proof_kind=kind,
                              retry_backend="cpu")
    from ..plonk import backend as B
    proof, instances = _call_prove(prove_fn, args, heartbeat=heartbeat,
                                   backend=B.get_backend("cpu"))
    proof = faults.mangle(PROOF_FAULT_SITE, proof)
    ok = _verify_once(state, kind, proof, instances, attempt=2, health=health)
    _rerun_self_check(state)
    if ok:
        return proof, instances
    _quarantine_proof(state, proof)
    raise ProofVerifyFailed(kind)


# -- cross-host verification (ISSUE 11: proof farm) -------------------------

def proof_kind(method: str) -> str:
    """Map an RPC prove method to its verifying-key kind. The
    aggregation cadence (ISSUE 18) emits the window tip's committee
    aggregate, so it verifies against the committee keys."""
    if "Committee" in method or "Aggregation" in method:
        return "committee"
    return "step"


def decode_result(result: dict) -> tuple[bytes, list[int]]:
    """Decode a queue-runner result dict back into (proof, instances) —
    the inverse of run_proof_method's hex encoding."""
    proof = bytes.fromhex(result["proof"].removeprefix("0x"))
    instances = [int(h, 16) for h in result["instances"]]
    return proof, instances


def cross_verify(verify_state, method: str, result, health=HEALTH) -> bool:
    """Re-verify a proof produced by ANOTHER host, on this host's keys.

    The PR-9 SDC retry reuses the producing host's own CPU — a bad DIMM
    hits both paths. The dispatcher calls this on every replica result
    so corruption is caught by hardware the suspect host never touched.
    Returns True when the proof verifies (or verification is skipped:
    policy ``off``, sampled-out, no verifier on this state, or a result
    shape that isn't a proof); False means suspected SDC — the caller
    quarantines and re-dispatches to a different replica."""
    if (verify_state is None
            or not hasattr(verify_state, "verify_proof")
            or not isinstance(result, dict) or "proof" not in result):
        return True
    mode, p = policy()
    if mode == "off" or (mode == "sampled" and RNG() >= p):
        return True
    kind = proof_kind(method)
    try:
        proof, instances = decode_result(result)
    except (KeyError, ValueError):
        return True         # not a proof-shaped result; nothing to verify
    with phase("prove/cross_verify"):
        try:
            ok = bool(verify_state.verify_proof(kind, proof, instances))
        except Exception as exc:
            tracing.annotate(cross_verify_error=f"{type(exc).__name__}")
            ok = False
    if ok:
        health.incr("proofs_cross_verified")
    else:
        health.incr("proofs_cross_verify_failed")
        obs_manifest.record_event("cross_verify_failed", proof_kind=kind)
    return ok


# -- readiness self-check ---------------------------------------------------

@functools.lru_cache(maxsize=1)
def _tiny_setup():
    """Tiny K=6 gate+lookup+copy circuit (cached: keygen once per process).

    Mirrors the resilience suite's toy circuit: out = x + x*y with a
    fixed-column constant, one lookup, and three copy constraints — small
    enough to prove in seconds on CPU, rich enough that a box silently
    miscomputing MSM/NTT cannot pass it."""
    from ..plonk.constraint_system import Assignment, CircuitConfig
    from ..plonk.keygen import keygen
    from ..plonk.srs import SRS

    k = 6
    cfg = CircuitConfig(k=k, num_advice=1, num_lookup_advice=1, num_fixed=1,
                        lookup_bits=4)
    n = cfg.n
    x_w, y_w = 7, 3
    out = x_w + x_w * y_w
    advice = [[0] * n]
    advice[0][0:5] = [x_w, x_w, y_w, out, 5]
    selectors = [[0] * n]
    selectors[0][0] = 1
    lookup = [[0] * n]
    lookup[0][0] = x_w
    fixed = [[0] * n]
    fixed[0][0] = 5
    copies = [
        ((cfg.col_instance(0), 0), (cfg.col_gate_advice(0), 3)),
        ((cfg.col_fixed(0), 0), (cfg.col_gate_advice(0), 4)),
        ((cfg.col_gate_advice(0), 0), (cfg.col_lookup_advice(0), 0)),
    ]
    srs = SRS.unsafe_setup(k)
    pk = keygen(srs, cfg, fixed, selectors, copies)
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    return pk, srs, asg, out


def _tiny_prove_verify() -> bool:
    from ..plonk import backend as B
    from ..plonk.prover import prove
    from ..plonk.verifier import verify
    pk, srs, asg, out = _tiny_setup()
    proof = prove(pk, srs, asg, B.get_backend("cpu"))
    return bool(verify(pk.vk, srs, [[out]], proof))


class SelfCheck:
    """Prove+verify the tiny cached circuit; gate readiness on the result.

    ``run()`` executes the injectable `runner` (default: real tiny-circuit
    prove+verify on CPU) and records the outcome; ``GET /healthz`` returns
    503 with ``snapshot()`` in the body until ``ok``. Re-run after every
    SDC retry so a box that has started flipping bits drops out of the
    ready pool instead of grinding through per-proof retries."""

    def __init__(self, runner=None, health=HEALTH):
        self._lock = threading.Lock()
        self._runner = runner if runner is not None else _tiny_prove_verify
        self._health = health
        self.ok = False
        self.runs = 0
        self.last_error: str | None = None

    def run(self) -> bool:
        try:
            ok = bool(self._runner())
            err = None if ok else "tiny-circuit proof failed verification"
        except Exception as exc:
            ok, err = False, f"{type(exc).__name__}: {exc}"
        with self._lock:
            self.runs += 1
            self.ok = ok
            self.last_error = err
        if not ok:
            self._health.incr("self_check_failures")
        return ok

    def snapshot(self) -> dict:
        with self._lock:
            return {"ok": self.ok, "runs": self.runs,
                    "last_error": self.last_error}

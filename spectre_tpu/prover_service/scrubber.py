"""Background artifact scrubber (ISSUE 9 tentpole part 2).

Replay verifies an artifact when a RESTART happens to read it; the wire
verifies it when a CLIENT happens to fetch it. Disk rot in between goes
unnoticed until the worst moment. The scrubber closes that gap: a
supervised background thread incrementally re-hashes every
``results/<sha256>.bin`` and ``<sha256>.manifest.json`` against its
content address,

* **quarantining** mismatches exactly like replay does
  (``ArtifactStore._quarantine`` -> ``results/quarantine/``,
  ``artifacts_quarantined`` + ``artifacts_scrub_corrupt``), and
* **expiring** orphans — hash-clean files whose ``(digest, suffix)`` no
  longer appears in any journaled job (journal compaction dropped the
  job, a crash landed between artifact write and journal append, or an
  operator pruned the journal). Deletion is age-gated
  (``SPECTRE_SCRUB_MIN_AGE_S``, default 60 s) so a file an in-flight
  worker wrote moments before its journal record lands is never
  reaped. Counted on ``artifacts_expired``; closes the PR-8 ROADMAP
  follow-up together with the post-compaction pass in JobQueue._recover.

One pass is exposed as ``Scrubber.scrub()`` (the ``scrubNow`` RPC and
``python -m spectre_tpu.prover_service scrub`` CLI call it directly);
the periodic thread (``SPECTRE_SCRUB_INTERVAL_S``, default 300 s, 0
disables) follows the worker-supervisor discipline: injectable
clock/interval, exceptions counted (``artifacts_scrub_errors``) and
never fatal, shutdown via the queue's stop event.

**IO-pressure pacing (ISSUE 10, closes the PR-9 follow-up):** a pass
re-hashes every byte in ``results/``, so on a box where that takes
longer than ``SPECTRE_SCRUB_BUDGET_S`` (default 30 s — a proxy for IO
pressure: a healthy store scans in seconds) the next wait is STRETCHED
by the overrun ratio (capped at 8x) instead of immediately grinding the
disk again. Each stretched wait counts on ``scrub_passes_deferred``.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

from ..utils.health import HEALTH

INTERVAL_ENV = "SPECTRE_SCRUB_INTERVAL_S"
INTERVAL_DEFAULT_S = 300.0
MIN_AGE_ENV = "SPECTRE_SCRUB_MIN_AGE_S"
MIN_AGE_DEFAULT_S = 60.0
BUDGET_ENV = "SPECTRE_SCRUB_BUDGET_S"
BUDGET_DEFAULT_S = 30.0
MAX_STRETCH = 8.0

_HEX = frozenset("0123456789abcdef")
_CHUNK = 1 << 20


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def parse_name(name: str):
    """``<64-hex><suffix>`` -> (digest, suffix); None for anything else
    (quarantine/ dir, ``.tmp`` staging files, strangers)."""
    if len(name) <= 64 or name.endswith(".tmp"):
        return None
    digest, suffix = name[:64], name[64:]
    if not suffix.startswith(".") or not _HEX.issuperset(digest):
        return None
    return digest, suffix


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while chunk := f.read(_CHUNK):
            h.update(chunk)
    return h.hexdigest()


class Scrubber:
    """`live_artifacts` is a zero-arg callable returning the set of
    ``(digest, suffix)`` pairs some journaled job still references —
    everything else that hashes clean is an expirable orphan."""

    def __init__(self, store, live_artifacts, health=HEALTH,
                 min_age_s: float | None = None, clock=time.time,
                 budget_s: float | None = None):
        self.store = store
        self.live_artifacts = live_artifacts
        self.health = health
        self.min_age_s = (min_age_s if min_age_s is not None
                          else _env_float(MIN_AGE_ENV, MIN_AGE_DEFAULT_S))
        self.budget_s = (budget_s if budget_s is not None
                         else _env_float(BUDGET_ENV, BUDGET_DEFAULT_S))
        self.last_pass_s = 0.0
        self._clock = clock
        self._thread: threading.Thread | None = None

    def scrub(self) -> dict:
        """One full pass; returns {"scanned","corrupt","expired","skipped"}."""
        started = self._clock()
        try:
            return self._scrub(started)
        finally:
            self.last_pass_s = max(0.0, self._clock() - started)

    def _scrub(self, now: float) -> dict:
        summary = {"scanned": 0, "corrupt": 0, "expired": 0, "skipped": 0}
        try:
            names = sorted(os.listdir(self.store.dir))
        except OSError:
            return summary
        live = set(self.live_artifacts())
        for name in names:
            parsed = parse_name(name)
            path = os.path.join(self.store.dir, name)
            if parsed is None:
                if os.path.isfile(path):
                    summary["skipped"] += 1
                continue
            digest, suffix = parsed
            try:
                actual = _hash_file(path)
            except OSError:
                summary["skipped"] += 1   # vanished mid-pass (racing reader)
                continue
            summary["scanned"] += 1
            self.health.incr("artifacts_scrubbed")
            if actual != digest:
                self.store._quarantine(path)
                summary["corrupt"] += 1
                self.health.incr("artifacts_scrub_corrupt")
                continue
            if (digest, suffix) not in live:
                try:
                    if now - os.path.getmtime(path) < self.min_age_s:
                        continue      # too fresh: may be a not-yet-journaled
                    os.unlink(path)   # write racing this pass
                except OSError:
                    continue
                summary["expired"] += 1
                self.health.incr("artifacts_expired")
        return summary

    # -- periodic thread ----------------------------------------------------

    def start(self, interval_s: float | None, stop_event: threading.Event):
        """Spawn the periodic pass; interval<=0 disables (scrubNow / the
        CLI still work). Exceptions inside a pass are counted and
        swallowed — the scrubber must never take the queue down."""
        if interval_s is None:
            interval_s = _env_float(INTERVAL_ENV, INTERVAL_DEFAULT_S)
        if interval_s <= 0:
            return None
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s, stop_event),
            daemon=True, name="prover-artifact-scrubber")
        self._thread.start()
        return self._thread

    def next_interval(self, interval_s: float) -> float:
        """IO-pressure pacing: when the last pass blew its wall-clock
        budget, stretch the next wait by the overrun ratio (capped at
        ``MAX_STRETCH``) and count the deferral. A within-budget pass
        keeps the configured cadence."""
        if self.budget_s <= 0 or self.last_pass_s <= self.budget_s:
            return interval_s
        stretch = min(MAX_STRETCH, self.last_pass_s / self.budget_s)
        self.health.incr("scrub_passes_deferred")
        return interval_s * stretch

    def _loop(self, interval_s: float, stop_event: threading.Event):
        wait = interval_s
        while not stop_event.wait(wait):
            try:
                self.scrub()
            except Exception:
                self.health.incr("artifacts_scrub_errors")
            wait = self.next_interval(interval_s)

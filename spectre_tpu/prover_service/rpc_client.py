"""Typed JSON-RPC client (reference parity: `prover/src/rpc_client.rs:39-93`)."""

from __future__ import annotations

import json
import urllib.request

from .rpc import RPC_METHOD_COMMITTEE, RPC_METHOD_STEP


class ProverClient:
    def __init__(self, url: str, timeout: float = 3600.0):
        self.url = url
        self.timeout = timeout
        self._id = 0

    def _call(self, method: str, params: dict):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "method": method,
                           "params": params, "id": self._id}).encode()
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            data = json.load(resp)
        if "error" in data:
            raise RuntimeError(f"rpc error: {data['error']}")
        return data["result"]

    def ping(self) -> str:
        return self._call("ping", {})

    def gen_evm_proof_sync_step_compressed(self, finality_update: dict,
                                           pubkeys: list, domain: str):
        return self._call(RPC_METHOD_STEP, {
            "light_client_finality_update": finality_update,
            "pubkeys": pubkeys,
            "domain": domain,
        })

    def gen_evm_proof_committee_update_compressed(self, update: dict):
        return self._call(RPC_METHOD_COMMITTEE, {"light_client_update": update})

"""Typed JSON-RPC client (reference parity: `prover/src/rpc_client.rs:39-93`).

PR 3: requests carry a timeout and retry ONCE on a connection reset (the
service restarting under a rolling deploy is the common case); an `error`
member in the response raises a typed `RpcError(code, message)` instead of
a bare KeyError. The async job API (`submitProof_*` / `getProofStatus` /
`getProofResult`) is exposed alongside the blocking reference methods,
plus a `wait_for_proof` poll helper and `health`/`healthz` probes.
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.request

from .rpc import (RPC_METHOD_COMMITTEE, RPC_METHOD_COMMITTEE_SUBMIT,
                  RPC_METHOD_STEP, RPC_METHOD_STEP_SUBMIT)


class RpcError(RuntimeError):
    """A JSON-RPC error response (code + message, as sent by the server)."""

    def __init__(self, code: int, message: str):
        super().__init__(f"rpc error {code}: {message}")
        self.code = code
        self.message = message


def _is_conn_reset(exc: BaseException) -> bool:
    if isinstance(exc, (ConnectionResetError, ConnectionRefusedError,
                        http.client.RemoteDisconnected)):
        return True
    reason = getattr(exc, "reason", None)
    return isinstance(reason, (ConnectionResetError, ConnectionRefusedError))


class ProverClient:
    def __init__(self, url: str, timeout: float = 3600.0,
                 conn_retries: int = 1):
        self.url = url
        self.timeout = timeout
        self.conn_retries = conn_retries
        self._id = 0

    def _call(self, method: str, params: dict, timeout: float | None = None):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "method": method,
                           "params": params, "id": self._id}).encode()
        attempt = 0
        while True:
            req = urllib.request.Request(
                self.url, data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout or self.timeout) as resp:
                    data = json.load(resp)
                break
            except Exception as exc:
                if _is_conn_reset(exc) and attempt < self.conn_retries:
                    attempt += 1
                    continue
                raise
        if "error" in data:
            err = data["error"] or {}
            raise RpcError(err.get("code", -32603),
                           err.get("message", "unknown error"))
        return data["result"]

    def ping(self) -> str:
        return self._call("ping", {}, timeout=min(self.timeout, 30.0))

    # -- blocking reference methods ---------------------------------------

    def gen_evm_proof_sync_step_compressed(self, finality_update: dict,
                                           pubkeys: list, domain: str):
        return self._call(RPC_METHOD_STEP, {
            "light_client_finality_update": finality_update,
            "pubkeys": pubkeys,
            "domain": domain,
        })

    def gen_evm_proof_committee_update_compressed(self, update: dict):
        return self._call(RPC_METHOD_COMMITTEE, {"light_client_update": update})

    # -- async job API -----------------------------------------------------

    def submit_sync_step(self, finality_update: dict, pubkeys: list,
                         domain: str, job_timeout: float | None = None) -> str:
        params = {"light_client_finality_update": finality_update,
                  "pubkeys": pubkeys, "domain": domain}
        if job_timeout is not None:
            params["timeout"] = job_timeout
        return self._call(RPC_METHOD_STEP_SUBMIT, params,
                          timeout=min(self.timeout, 60.0))["job_id"]

    def submit_committee_update(self, update: dict,
                                job_timeout: float | None = None) -> str:
        params = {"light_client_update": update}
        if job_timeout is not None:
            params["timeout"] = job_timeout
        return self._call(RPC_METHOD_COMMITTEE_SUBMIT, params,
                          timeout=min(self.timeout, 60.0))["job_id"]

    def proof_status(self, job_id: str) -> dict:
        return self._call("getProofStatus", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))

    def proof_result(self, job_id: str) -> dict:
        return self._call("getProofResult", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))

    def cancel_proof(self, job_id: str) -> bool:
        return self._call("cancelProof", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))["cancelled"]

    def wait_for_proof(self, job_id: str, poll: float = 1.0,
                       timeout: float | None = None) -> dict:
        """Poll getProofStatus until terminal, then return the result.
        Raises RpcError on a failed job and TimeoutError past `timeout`."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            st = self.proof_status(job_id)
            if st["status"] in ("done", "failed", "cancelled"):
                return self.proof_result(job_id)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"job {job_id} still {st['status']} "
                                   f"after {timeout}s")
            time.sleep(poll)

    def health(self) -> dict:
        return self._call("health", {}, timeout=min(self.timeout, 30.0))

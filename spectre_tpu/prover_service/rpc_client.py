"""Typed JSON-RPC client (reference parity: `prover/src/rpc_client.rs:39-93`).

PR 3: requests carry a timeout and retry ONCE on a connection reset (the
service restarting under a rolling deploy is the common case); an `error`
member in the response raises a typed `RpcError(code, message)` instead of
a bare KeyError. The async job API (`submitProof_*` / `getProofStatus` /
`getProofResult`) is exposed alongside the blocking reference methods,
plus a `wait_for_proof` poll helper and `health`/`healthz` probes.

ISSUE 6: the service now LOAD-SHEDS (`-32001 service overloaded` /
HTTP 429 with `Retry-After`). Submits and polls honor the server's
`retry_after_s` hint with capped jitter in ONE bounded retry loop
(`overload_retries`, default 2); an exhausted loop surfaces the typed
`RpcError` with `.retry_after` set so callers can schedule their own
retry. `sleep`/`rng` are injectable (the BeaconClient pattern) so the
backoff paths test deterministically.

ISSUE 10: `wait_for_proof` threads ONE overall deadline (computed once
from the injectable `clock`) through per-poll HTTP timeouts, overload
backoffs and poll sleeps, and the follower's stored light-client
updates are exposed via `get_light_client_update` / `get_update_range`
/ `follower_status`.
"""

from __future__ import annotations

import http.client
import json
import random
import time
import urllib.error
import urllib.request

from collections import OrderedDict

from .rpc import (RPC_METHOD_COMMITTEE, RPC_METHOD_COMMITTEE_SUBMIT,
                  RPC_METHOD_STEP, RPC_METHOD_STEP_SUBMIT,
                  SERVICE_OVERLOADED, UPDATE_UNAVAILABLE)


class RpcError(RuntimeError):
    """A JSON-RPC error response (code + message, as sent by the server).
    `retry_after` carries the server's backoff hint (seconds) on a
    `-32001 service overloaded` shed, else None. `replica_id` names the
    farm replica that served the error (ISSUE 11; None outside a farm)."""

    def __init__(self, code: int, message: str,
                 retry_after: float | None = None,
                 replica_id: str | None = None):
        super().__init__(f"rpc error {code}: {message}"
                         + (f" [replica {replica_id}]" if replica_id else ""))
        self.code = code
        self.message = message
        self.retry_after = retry_after
        self.replica_id = replica_id


def _is_conn_reset(exc: BaseException) -> bool:
    if isinstance(exc, (ConnectionResetError, ConnectionRefusedError,
                        http.client.RemoteDisconnected)):
        return True
    reason = getattr(exc, "reason", None)
    return isinstance(reason, (ConnectionResetError, ConnectionRefusedError))


class ProverClient:
    def __init__(self, url, timeout: float = 3600.0,
                 conn_retries: int = 1, overload_retries: int = 2,
                 retry_after_cap: float = 30.0,
                 sleep=time.sleep, rng=random.random, clock=time.time):
        """`url` is one endpoint or a list of them (ISSUE 11: a proof
        farm has many frontends). Calls go to the current endpoint; a
        connection-reset retry ROTATES to the next one first, so the
        retry lands on a different replica instead of hammering the one
        that just dropped the connection."""
        self.urls = [url] if isinstance(url, str) else list(url)
        if not self.urls:
            raise ValueError("ProverClient needs at least one URL")
        self._url_index = 0
        self.timeout = timeout
        self.conn_retries = conn_retries
        self.overload_retries = overload_retries
        self.retry_after_cap = retry_after_cap
        self._sleep = sleep
        self._rng = rng
        self._clock = clock
        self._id = 0
        # gateway-side conditional-request cache (ISSUE 14): path ->
        # (etag, decoded body). Bounded LRU; 304 revalidations re-serve
        # the cached decode without re-downloading the proof bytes.
        self._etag_cache: "OrderedDict[str, tuple]" = OrderedDict()
        self.etag_cache_max = 256
        self.cache_304s = 0         # revalidated-not-modified responses
        self.endpoint_refreshes = 0  # membership-driven rotations grown

    @property
    def url(self) -> str:
        """Current endpoint (rotates on connection-reset retries)."""
        return self.urls[self._url_index % len(self.urls)]

    @url.setter
    def url(self, value: str):
        self.urls = [value]
        self._url_index = 0

    def _rotate_url(self):
        if len(self.urls) > 1:
            self._url_index = (self._url_index + 1) % len(self.urls)

    def _refresh_endpoints(self) -> bool:
        """Membership-driven endpoint discovery (ISSUE 18): when the
        conn-reset rotation has exhausted every configured URL, ask each
        endpoint's `health` RPC for the dispatcher membership and adopt
        replica URLs this client doesn't know yet — a fleet that grew or
        moved since the client was configured keeps serving it. One-shot
        direct POSTs (no retry recursion). Returns True when the
        rotation grew, with the current endpoint pointed at the first
        new URL."""
        for base in list(self.urls):
            self._id += 1
            body = json.dumps({"jsonrpc": "2.0", "method": "health",
                               "params": {}, "id": self._id}).encode()
            req = urllib.request.Request(
                base, data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=min(self.timeout, 10.0)) as resp:
                    data = json.load(resp)
            except Exception:
                continue
            replicas = ((data.get("result") or {}).get("dispatcher")
                        or {}).get("replicas") or []
            fresh = [r.get("url") for r in replicas
                     if isinstance(r, dict) and r.get("url")
                     and r["url"] not in self.urls]
            if fresh:
                first = len(self.urls)
                self.urls.extend(dict.fromkeys(fresh))
                self._url_index = first
                self.endpoint_refreshes += 1
                return True
        return False

    def _raise_rpc_error(self, data: dict, headers=None):
        err = (data or {}).get("error") or {}
        retry_after = None
        if err.get("code") == SERVICE_OVERLOADED:
            retry_after = (err.get("data") or {}).get("retry_after_s")
            if retry_after is None and headers is not None:
                try:
                    retry_after = float(headers.get("Retry-After"))
                except (TypeError, ValueError):
                    pass
        data_field = err.get("data")
        replica_id = data_field.get("replica_id") \
            if isinstance(data_field, dict) else None
        raise RpcError(err.get("code", -32603),
                       err.get("message", "unknown error"),
                       retry_after=retry_after, replica_id=replica_id)

    def _call(self, method: str, params: dict, timeout: float | None = None):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "method": method,
                           "params": params, "id": self._id}).encode()
        attempt = 0
        refreshed = False
        while True:
            req = urllib.request.Request(
                self.url, data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout or self.timeout) as resp:
                    data = json.load(resp)
                break
            except urllib.error.HTTPError as exc:
                # HTTP 429 load shed: the body still carries the JSON-RPC
                # -32001 envelope; surface it typed, with the Retry-After
                if exc.code == 429:
                    try:
                        data = json.load(exc)
                    except ValueError:
                        data = {}
                    self._raise_rpc_error(data, headers=exc.headers)
                raise
            except Exception as exc:
                if _is_conn_reset(exc):
                    if attempt < self.conn_retries:
                        # farm-aware retry (ISSUE 11): prefer a DIFFERENT
                        # replica — the endpoint that reset us is the one
                        # most likely mid-restart
                        self._rotate_url()
                        attempt += 1
                        continue
                    if not refreshed and self._refresh_endpoints():
                        # rotation exhausted (ISSUE 18): refresh the
                        # endpoint list from dispatcher membership once
                        # before failing hard — the adopted URLs get
                        # their own conn-retry budget
                        refreshed = True
                        attempt = 0
                        continue
                raise
        if "error" in data:
            self._raise_rpc_error(data)
        return data["result"]

    def _call_shedding(self, method: str, params: dict,
                       timeout: float | None = None,
                       deadline: float | None = None):
        """`_call` plus the ONE bounded overload-retry loop: a -32001/429
        shed sleeps the server's retry_after_s (capped, with jitter so a
        shed fleet doesn't re-stampede) up to `overload_retries` times,
        then surfaces the typed RpcError (with .retry_after) to the
        caller. `deadline` (absolute, `clock()` domain) caps the retry
        sleeps: a backoff that would overshoot it surfaces the RpcError
        immediately instead — the caller's overall deadline wins."""
        for attempt in range(self.overload_retries + 1):
            try:
                return self._call(method, params, timeout=timeout)
            except RpcError as exc:
                if exc.code != SERVICE_OVERLOADED \
                        or attempt >= self.overload_retries:
                    raise
                base = exc.retry_after if exc.retry_after is not None else 1.0
                delay = min(self.retry_after_cap, base) \
                    * (1.0 + 0.25 * self._rng())
                if deadline is not None \
                        and self._clock() + delay > deadline:
                    raise
                self._sleep(delay)

    def ping(self) -> str:
        return self._call("ping", {}, timeout=min(self.timeout, 30.0))

    # -- blocking reference methods ---------------------------------------

    def gen_evm_proof_sync_step_compressed(self, finality_update: dict,
                                           pubkeys: list, domain: str):
        return self._call(RPC_METHOD_STEP, {
            "light_client_finality_update": finality_update,
            "pubkeys": pubkeys,
            "domain": domain,
        })

    def gen_evm_proof_committee_update_compressed(self, update: dict):
        return self._call(RPC_METHOD_COMMITTEE, {"light_client_update": update})

    # -- async job API -----------------------------------------------------

    def submit_sync_step(self, finality_update: dict, pubkeys: list,
                         domain: str, job_timeout: float | None = None,
                         deadline_s: float | None = None) -> str:
        params = {"light_client_finality_update": finality_update,
                  "pubkeys": pubkeys, "domain": domain}
        if job_timeout is not None:
            params["timeout"] = job_timeout
        if deadline_s is not None:
            params["deadline_s"] = deadline_s
        return self._call_shedding(RPC_METHOD_STEP_SUBMIT, params,
                                   timeout=min(self.timeout, 60.0))["job_id"]

    def submit_committee_update(self, update: dict,
                                job_timeout: float | None = None,
                                deadline_s: float | None = None) -> str:
        params = {"light_client_update": update}
        if job_timeout is not None:
            params["timeout"] = job_timeout
        if deadline_s is not None:
            params["deadline_s"] = deadline_s
        return self._call_shedding(RPC_METHOD_COMMITTEE_SUBMIT, params,
                                   timeout=min(self.timeout, 60.0))["job_id"]

    def proof_status(self, job_id: str) -> dict:
        return self._call("getProofStatus", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))

    def proof_result(self, job_id: str) -> dict:
        return self._call("getProofResult", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))

    def cancel_proof(self, job_id: str) -> bool:
        return self._call("cancelProof", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))["cancelled"]

    def wait_for_proof(self, job_id: str, poll: float = 1.0,
                       timeout: float | None = None) -> dict:
        """Poll getProofStatus until terminal, then return the result.
        Raises RpcError on a failed job and TimeoutError past `timeout`.

        ISSUE 10: ONE overall deadline, computed once from the injected
        clock, bounds the whole wait — every per-poll HTTP timeout, every
        overload-retry sleep inside `_call_shedding`, and every poll
        sleep is clamped to the time remaining, so a slow or shedding
        server cannot stretch the wait past `timeout`."""
        deadline = (None if timeout is None
                    else self._clock() + timeout)
        last_status = "unknown"
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    raise TimeoutError(f"job {job_id} still {last_status} "
                                       f"after {timeout}s")
            call_timeout = min(self.timeout, 30.0)
            if remaining is not None:
                call_timeout = min(call_timeout, max(remaining, 0.1))
            # polls ride the same bounded overload-retry loop as submits,
            # but the deadline caps its backoff sleeps too
            st = self._call_shedding("getProofStatus", {"job_id": job_id},
                                     timeout=call_timeout, deadline=deadline)
            last_status = st["status"]
            if st["status"] in ("done", "failed", "cancelled"):
                result_timeout = min(self.timeout, 30.0)
                if deadline is not None:
                    result_timeout = min(
                        result_timeout,
                        max(deadline - self._clock(), 0.1))
                return self._call("getProofResult", {"job_id": job_id},
                                  timeout=result_timeout)
            delay = poll
            if deadline is not None:
                delay = min(delay, max(deadline - self._clock(), 0.0))
            self._sleep(delay)

    def health(self) -> dict:
        return self._call("health", {}, timeout=min(self.timeout, 30.0))

    # -- observability (ISSUE 7) -------------------------------------------

    def get_trace(self, job_id: str) -> dict:
        """Chrome trace-event JSON for a completed job (trace id = job
        id). Raises RpcError -32002 while the job is still live, -32004
        for unknown jobs / traces past the retention ring."""
        return self._call("getTrace", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))

    def get_manifest(self, job_id: str) -> dict:
        """Provenance manifest for a terminal job (ISSUE 8): timestamps
        with the queue-wait/prove split, resolved modes + env knobs,
        degrade/fault events, table-LRU deltas, compile events, phase
        seconds, peak RSS and the result digest. Raises RpcError -32002
        while the job is live, -32004 for unknown jobs, -32006 when the
        manifest degraded to absent (the result itself is unaffected)."""
        return self._call("getProofManifest", {"job_id": job_id},
                          timeout=min(self.timeout, 30.0))

    # -- follower / light-client updates (ISSUE 10) ------------------------

    def get_light_client_update(self, period: int | None = None,
                                slot: int | None = None) -> dict:
        """Stored verified update: a committee update by `period` or a
        step proof by `slot`. Served straight from the follower's update
        store — a hit never touches the prover. Raises RpcError -32007
        when the update is not (yet) proved."""
        params: dict = {}
        if period is not None:
            params["period"] = period
        if slot is not None:
            params["slot"] = slot
        return self._call("getLightClientUpdate", params,
                          timeout=min(self.timeout, 30.0))

    def get_update_range(self, start_period: int, count: int = 1) -> dict:
        """Contiguous committee updates starting at `start_period`:
        {"updates": [...], "missing": [periods]} (count capped at 128)."""
        return self._call("getUpdateRange",
                          {"start_period": start_period, "count": count},
                          timeout=min(self.timeout, 30.0))

    def follower_status(self) -> dict:
        """Follower snapshot: head lag, periods behind, scheduler
        backlog, chain health (`chain_ok`), stored counts."""
        return self._call("followerStatus", {},
                          timeout=min(self.timeout, 30.0))

    # -- gateway read plane (ISSUE 14) -------------------------------------

    def _gateway_url(self, path: str, query: str = "") -> str:
        from urllib.parse import urlsplit, urlunsplit
        parts = urlsplit(self.url)
        return urlunsplit((parts.scheme, parts.netloc, path, query, ""))

    def _cached_get(self, path: str, query: str = "") -> dict:
        """Conditional GET against the gateway's /v1/* routes: sends
        If-None-Match from the client-side digest cache, honors 304 by
        re-serving the cached decode. 404 surfaces as the same typed
        -32007 `update unavailable` the RPC method raises."""
        key = path + ("?" + query if query else "")
        cached = self._etag_cache.get(key)
        req = urllib.request.Request(self._gateway_url(path, query))
        if cached is not None:
            req.add_header("If-None-Match", cached[0])
        try:
            with urllib.request.urlopen(
                    req, timeout=min(self.timeout, 30.0)) as resp:
                body = json.load(resp)
                etag = resp.headers.get("ETag")
        except urllib.error.HTTPError as exc:
            if exc.code == 304 and cached is not None:
                exc.read()
                self.cache_304s += 1
                self._etag_cache.move_to_end(key)
                return cached[1]
            if exc.code == 404:
                try:
                    message = json.load(exc).get("error", "not found")
                except ValueError:
                    message = "not found"
                raise RpcError(UPDATE_UNAVAILABLE, message)
            raise
        if etag:
            self._etag_cache[key] = (etag, body)
            self._etag_cache.move_to_end(key)
            while len(self._etag_cache) > self.etag_cache_max:
                self._etag_cache.popitem(last=False)
        return body

    def get_update_cached(self, period: int) -> dict:
        """One committee update via the cacheable gateway route
        (GET /v1/update/<period>): ETag-revalidated from the client-side
        digest cache, so a sealed update is downloaded at most once per
        client. Requires the server to mount the gateway
        (`follow --gateway`); raises RpcError -32007 when the update is
        not (yet) proved."""
        return self._cached_get(f"/v1/update/{int(period)}")

    def get_update_range_cached(self, start_period: int,
                                count: int = 1) -> dict:
        """Range variant of :meth:`get_update_cached`
        (GET /v1/updates?start=..&count=..): returns
        {"updates": [...], "missing": [...]} like get_update_range."""
        return self._cached_get(
            "/v1/updates", f"start={int(start_period)}&count={int(count)}")

    def get_bootstrap_cached(self) -> dict:
        """Cold-start document (GET /v1/bootstrap): trust anchor update
        + tip period, short-TTL cached."""
        return self._cached_get("/v1/bootstrap")

    def metrics_text(self) -> str:
        """Raw GET /metrics body (Prometheus text exposition 0.0.4) from
        the same host as the RPC endpoint."""
        from urllib.parse import urlsplit, urlunsplit
        parts = urlsplit(self.url)
        url = urlunsplit((parts.scheme, parts.netloc, "/metrics", "", ""))
        with urllib.request.urlopen(
                url, timeout=min(self.timeout, 30.0)) as resp:
            return resp.read().decode()

"""JSON-RPC 2.0 server (stdlib http.server; no framework deps).

Reference parity: `prover/src/rpc.rs` + `rpc_api.rs:8-36` — POST /rpc with
methods `genEvmProof_SyncStepCompressed` and
`genEvmProof_CommitteeUpdateCompressed`; responses carry proof + instances
(calldata-shaped); the committee variant additionally surfaces the committee
poseidon commitment (`rpc.rs:106`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..preprocessor.rotation import rotation_args_from_update
from ..preprocessor.step import step_args_from_finality_update
from .calldata import encode_calldata
from .state import ProverState

RPC_METHOD_STEP = "genEvmProof_SyncStepCompressed"
RPC_METHOD_COMMITTEE = "genEvmProof_CommitteeUpdateCompressed"


def _error(code, message, id_=None):
    return {"jsonrpc": "2.0", "error": {"code": code, "message": message}, "id": id_}


class _Handler(BaseHTTPRequestHandler):
    state: ProverState = None  # class attr injected by serve()

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def do_POST(self):
        if self.path not in ("/rpc", "/"):
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            resp = self._dispatch(req)
        except Exception as exc:  # malformed request
            resp = _error(-32700, f"parse error: {exc}")
        body = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _dispatch(self, req: dict) -> dict:
        id_ = req.get("id")
        method = req.get("method")
        params = req.get("params") or {}
        try:
            if method == RPC_METHOD_STEP:
                spec = self.state.spec
                args = step_args_from_finality_update(
                    params["light_client_finality_update"],
                    params["pubkeys"],
                    bytes.fromhex(params["domain"].removeprefix("0x")),
                    spec)
                proof, instances = self.state.prove_step(args)
                result = {
                    "proof": "0x" + proof.hex(),
                    "instances": [hex(v) for v in instances],
                    "calldata": "0x" + encode_calldata(instances, proof).hex(),
                }
            elif method == RPC_METHOD_COMMITTEE:
                args = rotation_args_from_update(
                    params["light_client_update"], self.state.spec)
                proof, instances = self.state.prove_committee(args)
                # compressed layout: 12 accumulator limbs then app instances,
                # poseidon at [12] (reference: rpc.rs:106 `instances[0][12]`)
                pos_idx = 12 if self.state.compress else 0
                result = {
                    "proof": "0x" + proof.hex(),
                    "instances": [hex(v) for v in instances],
                    "calldata": "0x" + encode_calldata(instances, proof).hex(),
                    "committee_poseidon": hex(instances[pos_idx]),
                }
            elif method == "ping":
                result = "pong"
            else:
                return _error(-32601, f"unknown method {method}", id_)
        except AssertionError as exc:
            return _error(-32000, f"witness rejected: {exc}", id_)
        except KeyError as exc:
            return _error(-32602, f"missing param: {exc}", id_)
        return {"jsonrpc": "2.0", "result": result, "id": id_}


def serve(state: ProverState, host: str = "127.0.0.1", port: int = 3000,
          background: bool = False):
    _Handler.state = state
    server = ThreadingHTTPServer((host, port), _Handler)
    if background:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    server.serve_forever()

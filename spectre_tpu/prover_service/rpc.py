"""JSON-RPC 2.0 server (stdlib http.server; no framework deps).

Reference parity: `prover/src/rpc.rs` + `rpc_api.rs:8-36` — POST /rpc with
methods `genEvmProof_SyncStepCompressed` and
`genEvmProof_CommitteeUpdateCompressed`; responses carry proof + instances
(calldata-shaped); the committee variant additionally surfaces the committee
poseidon commitment (`rpc.rs:106`).

Beyond the reference (PR 3, resilient service):

* **Async job API** — `submitProof_SyncStepCompressed` /
  `submitProof_CommitteeUpdateCompressed` return a job id immediately;
  `getProofStatus` / `getProofResult` poll it; `cancelProof` cancels.
  The blocking `genEvmProof_*` methods keep their reference semantics but
  run ON TOP of the same queue (submit + wait), so every proof flows
  through the crash-safe journal and the dedup-by-witness-digest path
  (prover_service/jobs.py).
* **Error taxonomy** — request *parsing* and method *dispatch* are
  separate failure domains: malformed JSON is `-32700 parse error`,
  non-dict / missing-`jsonrpc` bodies are `-32600 invalid request`,
  unknown methods `-32601`, missing params `-32602`, witness rejection
  `-32000`, and unexpected internal prover errors are `-32603 internal
  error` with a sanitized (exception-class-only) message — internals
  never leak to the wire as a bogus "parse error".
* **Health** — the `health` RPC method and GET `/healthz` surface the
  ServiceHealth degradation counters (utils/health.py) plus queue stats.
* **Observability (ISSUE 7)** — GET `/metrics` serves Prometheus text
  exposition (observability/prom.py, counter parity with /healthz);
  `getTrace` returns a completed job's span tree as Chrome trace-event
  JSON (observability/tracing.py).
* **Provenance (ISSUE 8)** — `getProofManifest` returns a terminal
  job's provenance manifest (observability/manifest.py), re-verified
  through the artifact store on every read. A terminal job whose
  manifest was never written (crash, tolerated sink failure) or fails
  verification answers `-32006 manifest unavailable` — the RESULT is
  still served by `getProofResult`; manifests degrade independently.
* **Output integrity (ISSUE 9)** — every prove is verified host-side
  before its job goes `done` (selfverify.verified_prove; twice-failed
  proofs surface as `-32005 proof failed self-verification`); the
  `scrubNow` method runs one artifact-scrubber pass; `GET /healthz`
  additionally gates readiness on the prove+verify self-check.
* **Follower serving (ISSUE 10)** — `getLightClientUpdate` (by period
  or slot), `getUpdateRange` and `followerStatus` serve pre-proved
  light-client updates out of the follower's verified update store: a
  cache hit is one content-verified artifact read — it never submits a
  job, acquires the prover semaphore, or touches the device. A missing
  or invalidated update answers `-32007 update unavailable` while the
  follower (re-)proves it in the background.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..preprocessor.rotation import rotation_args_from_update
from ..preprocessor.step import step_args_from_finality_update
from ..utils.health import HEALTH
from ..utils.profiling import phase
from .calldata import encode_calldata
from .jobs import ServiceOverloaded, ensure_jobs
from .selfverify import verified_prove
from .state import ProverState

RPC_METHOD_STEP = "genEvmProof_SyncStepCompressed"
RPC_METHOD_COMMITTEE = "genEvmProof_CommitteeUpdateCompressed"
RPC_METHOD_STEP_SUBMIT = "submitProof_SyncStepCompressed"
RPC_METHOD_COMMITTEE_SUBMIT = "submitProof_CommitteeUpdateCompressed"
# aggregation cadence (ISSUE 18): one job per cadence window of sealed
# periods — re-verifies the stored chain and emits the window tip's
# EVM-verifiable aggregate for contract publication
RPC_METHOD_AGG = "genEvmProof_AggregationCadence"
RPC_METHOD_AGG_SUBMIT = "submitProof_AggregationCadence"

# JSON-RPC 2.0 + implementation-defined codes (-32000..-32099 server errors)
PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603
WITNESS_REJECTED = -32000
SERVICE_OVERLOADED = -32001     # load shed: carries data.retry_after_s
JOB_NOT_DONE = -32002
JOB_NOT_FOUND = -32004
JOB_FAILED = -32005
MANIFEST_UNAVAILABLE = -32006   # terminal job, manifest absent/corrupt
UPDATE_UNAVAILABLE = -32007     # follower has no verified update (yet)


def _error(code, message, id_=None, data=None):
    err = {"code": code, "message": message}
    if data is not None:
        err["data"] = data
    return {"jsonrpc": "2.0", "error": err, "id": id_}


def run_proof_method(state, method: str, params: dict,
                     heartbeat=None) -> dict:
    """Prove one request. This is the job-queue runner: everything here runs
    in a worker thread, and the returned dict is the JSON-RPC `result`
    (JSON-serializable, journal-safe). `heartbeat` (optional zero-arg
    callback) is the worker's stall-detection stamp, invoked between
    prove phases."""
    if method == RPC_METHOD_STEP:
        spec = state.spec
        with phase("job/preprocess"):
            args = step_args_from_finality_update(
                params["light_client_finality_update"],
                params["pubkeys"],
                bytes.fromhex(params["domain"].removeprefix("0x")),
                spec)
        # verify-before-serve (ISSUE 9): no proof reaches the journal or
        # the wire without passing the host-side verifier
        proof, instances = verified_prove(state, "step", args,
                                          heartbeat=heartbeat)
        return {
            "proof": "0x" + proof.hex(),
            "instances": [hex(v) for v in instances],
            "calldata": "0x" + encode_calldata(instances, proof).hex(),
        }
    if method == RPC_METHOD_COMMITTEE:
        with phase("job/preprocess"):
            args = rotation_args_from_update(
                params["light_client_update"], state.spec)
        proof, instances = verified_prove(state, "committee", args,
                                          heartbeat=heartbeat)
        # compressed layout: 12 accumulator limbs then app instances,
        # poseidon at [12] (reference: rpc.rs:106 `instances[0][12]`)
        pos_idx = 12 if getattr(state, "compress", False) else 0
        return {
            "proof": "0x" + proof.hex(),
            "instances": [hex(v) for v in instances],
            "calldata": "0x" + encode_calldata(instances, proof).hex(),
            "committee_poseidon": hex(instances[pos_idx]),
        }
    if method == RPC_METHOD_AGG:
        # aggregation cadence (ISSUE 18): the params carry the stored
        # chain window [start_period, period]. The job re-checks every
        # poseidon chain link, re-verifies the window tip's compressed
        # proof on THIS host's keys (cross-host: the window may have
        # been proved anywhere in the farm), and returns the tip's
        # EVM-verifiable artifact as the publishable aggregate.
        with phase("job/aggregate"):
            chain = params["chain"]
            assert chain, "empty aggregation window"
            for prev, cur in zip(chain, chain[1:]):
                assert int(cur["period"]) == int(prev["period"]) + 1, \
                    f"aggregation window not contiguous at {cur['period']}"
                assert cur.get("prev_poseidon") == prev.get(
                    "committee_poseidon"), \
                    f"chain link broken at period {cur['period']}"
            if heartbeat is not None:
                heartbeat()
            tip = chain[-1]
            if hasattr(state, "verify_proof"):
                from .selfverify import decode_result
                proof, instances = decode_result(tip)
                assert state.verify_proof("committee", proof, instances), \
                    "aggregation window tip proof failed verification"
        return {
            "proof": tip["proof"],
            "instances": list(tip["instances"]),
            "calldata": tip.get("calldata"),
            "committee_poseidon": tip.get("committee_poseidon"),
            "start_period": int(params["start_period"]),
            "period": int(params["period"]),
            "aggregated": len(chain),
        }
    raise ValueError(f"unprovable method {method}")


# error payloads recorded by the job worker map back onto RPC codes when a
# blocking genEvmProof_* (or getProofResult) surfaces the failure; typed
# kinds keep their message, anything unexpected becomes a sanitized
# -32603 (exception class only — internals never leak to the wire)
_ERROR_KIND_CODES = {
    "AssertionError": (WITNESS_REJECTED, "witness rejected"),
    "KeyError": (INVALID_PARAMS, "missing param"),
    "TimeoutError": (JOB_FAILED, "job failed"),
    "StalledWorker": (JOB_FAILED, "job failed"),
    "ArtifactCorrupt": (JOB_FAILED, "result artifact corrupt"),
    "ProofVerifyFailed": (JOB_FAILED, "proof failed self-verification"),
}


def _job_error(job, id_):
    err = job.error or {"kind": "Unknown", "message": "job failed"}
    kind = err.get("kind")
    if kind in _ERROR_KIND_CODES:
        code, label = _ERROR_KIND_CODES[kind]
        return _error(code, f"{label}: {err.get('message', '')}", id_)
    HEALTH.incr("rpc_internal_errors")
    return _error(INTERNAL_ERROR, f"internal error ({kind})", id_)


class _Handler(BaseHTTPRequestHandler):
    state: ProverState = None  # class attrs injected by serve()
    jobs = None
    follower = None            # optional: the light-client follower daemon
    dispatcher = None          # optional: proof-farm dispatcher (ISSUE 11)
    replica_id = None          # this server's id within a farm
    gateway = None             # optional: cacheable read plane (ISSUE 14)

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, resp: dict, status: int = 200, headers: dict = None):
        # farm debuggability (ISSUE 11): every RPC error names the
        # serving replica, so a client retrying across endpoints can say
        # WHICH box failed (rpc_client surfaces it as RpcError.replica_id)
        if self.replica_id is not None and isinstance(resp, dict) \
                and isinstance(resp.get("error"), dict):
            resp["error"].setdefault("data", {})
            if isinstance(resp["error"]["data"], dict):
                resp["error"]["data"].setdefault("replica_id",
                                                 self.replica_id)
        body = json.dumps(resp).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/v1/"):
            # gateway read plane (ISSUE 14): content-addressed ETags,
            # If-None-Match -> 304, immutable cache headers on sealed
            # periods — designed so a stock CDN in front of this port
            # absorbs the light-client fan-out
            if self.gateway is None:
                self.send_error(404, "gateway not mounted (serve with "
                                     "--gateway)")
                return
            status, headers, body = self.gateway.handle_http(
                self.path, self.headers)
            self.send_response(status)
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)
            return
        if self.path == "/metrics":
            # Prometheus scrape (ISSUE 7): text exposition 0.0.4 with
            # exact counter parity against /healthz (both read the same
            # HEALTH.snapshot())
            from ..observability import prom
            body = prom.render(jobs=self.jobs).encode()
            self.send_response(200)
            self.send_header("Content-Type", prom.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path not in ("/healthz", "/health"):
            self.send_error(404)
            return
        from ..preprocessor.beacon import breaker_snapshot
        snap = HEALTH.snapshot()
        snap["jobs"] = self.jobs.stats() if self.jobs is not None else {}
        breakers = breaker_snapshot()
        snap["beacon_breakers"] = breakers
        # readiness (ROADMAP PR-3 follow-up): an OPEN beacon circuit
        # breaker means the upstream is considered down — report 503 so
        # orchestrators stop routing, with the counters in the body for
        # the operator. half-open admits a trial request, so it is ready.
        # (ISSUE 9) a failing prove+verify self-check gates readiness the
        # same way: a box that cannot prove correctly never reports ok.
        sc = getattr(self.state, "self_check", None)
        if sc is not None:
            snap["self_check"] = sc.snapshot()
        if self.dispatcher is not None:
            snap["dispatcher"] = self.dispatcher.snapshot()
        if any(b["state"] == "open" for b in breakers) \
                or (sc is not None and not snap["self_check"]["ok"]):
            snap["status"] = "degraded"
            self._reply(snap, status=503)
            return
        snap["status"] = "ok"
        self._reply(snap)

    def do_POST(self):
        if self.path not in ("/rpc", "/"):
            self.send_error(404)
            return
        # failure domain 1: transport + JSON parsing -> -32700
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length)
            req = json.loads(raw)
        except Exception as exc:
            self._reply(_error(PARSE_ERROR, f"parse error: {exc}"))
            return
        # failure domain 2: JSON-RPC envelope validation -> -32600
        if not isinstance(req, dict) or req.get("jsonrpc") != "2.0" \
                or not isinstance(req.get("method"), str):
            self._reply(_error(INVALID_REQUEST,
                               "invalid request: expected a JSON-RPC 2.0 "
                               "object with jsonrpc='2.0' and a method"))
            return
        # failure domain 3: dispatch — typed app errors keep their codes,
        # anything unexpected is a sanitized -32603 internal error
        id_ = req.get("id")
        try:
            resp = self._dispatch(req)
        except ServiceOverloaded as exc:
            # load shed (ISSUE 6): -32001 on the RPC envelope, 429 +
            # Retry-After on the transport — well-behaved clients back
            # off by retry_after_s instead of hammering a drowning box
            resp = _error(SERVICE_OVERLOADED,
                          f"service overloaded: {exc}", id_,
                          data={"retry_after_s": exc.retry_after_s})
            self._reply(resp, status=429,
                        headers={"Retry-After":
                                 str(max(1, int(exc.retry_after_s + 0.5)))})
            return
        except AssertionError as exc:
            resp = _error(WITNESS_REJECTED, f"witness rejected: {exc}", id_)
        except KeyError as exc:
            resp = _error(INVALID_PARAMS, f"missing param: {exc}", id_)
        except Exception as exc:
            HEALTH.incr("rpc_internal_errors")
            resp = _error(INTERNAL_ERROR,
                          f"internal error ({type(exc).__name__})", id_)
        self._reply(resp)

    def _dispatch(self, req: dict) -> dict:
        id_ = req.get("id")
        method = req["method"]
        params = req.get("params") or {}
        if method in (RPC_METHOD_STEP, RPC_METHOD_COMMITTEE,
                      RPC_METHOD_AGG):
            # blocking reference semantics, implemented over the queue:
            # submit (dedup'd + journaled) then wait for the terminal state
            jid = self.jobs.submit(method, params)
            job = self.jobs.wait(jid)
            if job.status == "done":
                return {"jsonrpc": "2.0", "result": job.result, "id": id_}
            if job.status == "cancelled":
                return _error(JOB_FAILED, "job cancelled", id_)
            return _job_error(job, id_)
        if method in (RPC_METHOD_STEP_SUBMIT, RPC_METHOD_COMMITTEE_SUBMIT,
                      RPC_METHOD_AGG_SUBMIT):
            blocking = {RPC_METHOD_STEP_SUBMIT: RPC_METHOD_STEP,
                        RPC_METHOD_COMMITTEE_SUBMIT: RPC_METHOD_COMMITTEE,
                        RPC_METHOD_AGG_SUBMIT: RPC_METHOD_AGG}
            timeout = params.pop("timeout", None)
            # deadline propagation: the client's own deadline clamps the
            # per-job timeout — no worker burns on an unread result
            deadline_s = params.pop("deadline_s", None)
            jid = self.jobs.submit(blocking[method], params, timeout=timeout,
                                   deadline_s=deadline_s)
            st = self.jobs.status(jid)
            result = {"job_id": jid, "status": st["status"]}
        elif method == "getProofStatus":
            st = self.jobs.status(params["job_id"])
            if st is None:
                return _error(JOB_NOT_FOUND,
                              f"unknown job {params['job_id']}", id_)
            result = st
        elif method == "getProofResult":
            job = self.jobs.result(params["job_id"])
            if job is None:
                return _error(JOB_NOT_FOUND,
                              f"unknown job {params['job_id']}", id_)
            if job.status in ("queued", "running"):
                return _error(JOB_NOT_DONE,
                              f"job {job.id} is {job.status}", id_)
            if job.status != "done":
                return _job_error(job, id_)
            result = job.result
        elif method == "getProofManifest":
            jid = params["job_id"]
            job = self.jobs.result(jid)
            if job is None:
                return _error(JOB_NOT_FOUND, f"unknown job {jid}", id_)
            if job.status in ("queued", "running"):
                return _error(JOB_NOT_DONE,
                              f"job {jid} is {job.status}; no manifest "
                              f"yet", id_)
            man = self.jobs.manifest(jid)
            if man is None:
                # manifests degrade to absent (crashed worker, tolerated
                # write failure, quarantined corruption) — the result
                # itself is unaffected and still served
                return _error(MANIFEST_UNAVAILABLE,
                              f"manifest for job {jid} unavailable "
                              f"(never written, or failed verification)",
                              id_)
            result = man
        elif method == "cancelProof":
            result = {"cancelled": self.jobs.cancel(params["job_id"])}
        elif method == "getTrace":
            # per-job span tree as Chrome trace-event JSON (ISSUE 7);
            # trace id = job id, retained for the last
            # SPECTRE_TRACE_KEEP completed jobs
            from ..observability import tracing
            jid = params["job_id"]
            tr = tracing.get_trace(jid)
            if tr is None:
                st = self.jobs.status(jid) if self.jobs else None
                if st is None:
                    return _error(JOB_NOT_FOUND, f"unknown job {jid}", id_)
                if st["status"] in ("queued", "running"):
                    return _error(JOB_NOT_DONE,
                                  f"job {jid} is {st['status']}; no trace "
                                  f"yet", id_)
                return _error(JOB_NOT_FOUND,
                              f"trace for job {jid} expired from the "
                              f"retention ring", id_)
            result = tracing.chrome_trace(tr)
        elif method in ("getLightClientUpdate", "getUpdateRange",
                        "followerStatus"):
            # follower serving path (ISSUE 10): pre-proved updates out of
            # the verified update store — one content-verified artifact
            # read, never a prover-semaphore acquisition or device touch
            fol = self.follower
            if fol is None:
                return _error(METHOD_NOT_FOUND,
                              "follower not running (start with "
                              "`python -m spectre_tpu.prover_service "
                              "follow`)", id_)
            if method == "followerStatus":
                result = fol.snapshot()
            elif method == "getUpdateRange":
                count = min(int(params.get("count", 1)), 128)
                updates, missing = fol.store.range_committee(
                    int(params["start_period"]), count)
                result = {"updates": updates, "missing": missing}
            else:
                if "period" in params:
                    rec = fol.store.get_committee(int(params["period"]))
                    what = f"period {params['period']}"
                elif "slot" in params:
                    rec = fol.store.get_step(int(params["slot"]))
                    what = f"slot {params['slot']}"
                else:
                    raise KeyError("period")
                if rec is None:
                    return _error(UPDATE_UNAVAILABLE,
                                  f"no verified update for {what} "
                                  f"(not yet proved, or invalidated and "
                                  f"re-proving)", id_)
                result = rec
        elif method == "scrubNow":
            # one synchronous artifact-scrubber pass (ISSUE 9): re-hash
            # every results/ file, quarantine rot, expire orphans
            result = self.jobs.scrub_now()
        elif method == "health":
            from ..preprocessor.beacon import breaker_snapshot
            result = HEALTH.snapshot()
            result["jobs"] = self.jobs.stats() if self.jobs else {}
            result["beacon_breakers"] = breaker_snapshot()
            sc = getattr(self.state, "self_check", None)
            if sc is not None:
                result["self_check"] = sc.snapshot()
            if self.dispatcher is not None:
                result["dispatcher"] = self.dispatcher.snapshot()
        elif method == "registerReplica":
            # farm membership (ISSUE 18): replicas announce themselves
            # (and heartbeat) here; the dispatcher journals joins and
            # TTL-expires the silent
            if self.dispatcher is None:
                return _error(METHOD_NOT_FOUND,
                              "not a dispatcher head (serve with a "
                              "Dispatcher to accept replica announces)",
                              id_)
            result = self.dispatcher.register_remote(
                params["replica_id"], url=params.get("url"),
                capabilities=params.get("capabilities"))
        elif method == "ping":
            result = "pong"
        else:
            return _error(METHOD_NOT_FOUND, f"unknown method {method}", id_)
        return {"jsonrpc": "2.0", "result": result, "id": id_}


def _announce_loop(stop: threading.Event, head_url: str, payload: dict,
                   interval: float):
    """Replica-side membership announce (ISSUE 18): POST
    ``registerReplica`` to the dispatcher head — once immediately, then
    every `interval` seconds as the liveness heartbeat. Failures are
    tolerated and counted (``replica_announce_failures``); only a TTL
    of silence deregisters the replica, and the next successful
    announce re-joins it."""
    from ..utils import faults
    from .rpc_client import ProverClient
    client = ProverClient(head_url, timeout=10.0)
    while True:
        try:
            faults.check("replica.announce")
            client._call("registerReplica", payload, timeout=10.0)
            HEALTH.incr("replica_announces")
        except Exception:
            HEALTH.incr("replica_announce_failures")
        if stop.wait(interval):
            return


def serve(state: ProverState, host: str = "127.0.0.1", port: int = 3000,
          background: bool = False, journal_dir: str | None = None,
          job_timeout: float | None = None, follower=None, dispatcher=None,
          replica_id: str | None = None, gateway=None, announce=None,
          announce_interval: float | None = None,
          advertise_url: str | None = None, capabilities=None, **queue_kw):
    """`journal_dir` defaults to the state's params_dir (when set) — pass
    explicitly to place the crash-safe job journal elsewhere; `job_timeout`
    is the default per-job deadline for async submissions. `follower`
    (optional) enables the getLightClientUpdate / getUpdateRange /
    followerStatus serving methods. `dispatcher` (optional, ISSUE 11)
    replaces the local-state queue runner with a proof-farm Dispatcher —
    the queue, dedup and journal are unchanged; only WHERE proofs run
    moves. `replica_id` (default $SPECTRE_REPLICA_ID) names this server
    in a farm: it is stamped into every RPC error's data. `gateway`
    (ISSUE 14) mounts the cacheable GET /v1/* read plane: pass a
    constructed Gateway, or True to build one over `follower`'s update
    store. `announce` (ISSUE 18, default $SPECTRE_ANNOUNCE_URL) is a
    dispatcher-head URL this server announces itself to — every
    `announce_interval` seconds ($SPECTRE_ANNOUNCE_INTERVAL_S) it POSTs
    ``registerReplica`` with its `capabilities` record (default: a
    best-effort :func:`~.dispatcher.capability_record` for this host)
    and `advertise_url` (default http://`host`:`port`, with the bound
    port when port=0). Extra `queue_kw` (queue_depth, mem_watermark_mb,
    stall_timeout, ...) reach the JobQueue's admission/supervision
    layer."""
    _Handler.state = state
    _Handler.jobs = ensure_jobs(state, journal_dir=journal_dir,
                                default_timeout=job_timeout,
                                runner=dispatcher, **queue_kw)
    _Handler.follower = follower
    _Handler.dispatcher = dispatcher
    _Handler.replica_id = replica_id if replica_id is not None \
        else (os.environ.get("SPECTRE_REPLICA_ID") or None)
    if gateway is True:
        if follower is None:
            raise ValueError("gateway=True requires a follower (the "
                             "gateway serves its update store)")
        from ..gateway import Gateway
        gateway = Gateway(follower.store)
    _Handler.gateway = gateway
    if gateway is not None and _Handler.jobs is not None:
        # packs must survive the scrubber's orphan expiry exactly like
        # stored updates do
        _Handler.jobs.add_live_provider(gateway.live_artifacts)
    server = ThreadingHTTPServer((host, port), _Handler)
    announce = announce if announce is not None \
        else (os.environ.get("SPECTRE_ANNOUNCE_URL") or None)
    if announce:
        from .dispatcher import (ANNOUNCE_DEFAULT_S, ANNOUNCE_ENV,
                                 capability_record)
        if announce_interval is None:
            try:
                announce_interval = float(
                    os.environ.get(ANNOUNCE_ENV, ANNOUNCE_DEFAULT_S))
            except ValueError:
                announce_interval = ANNOUNCE_DEFAULT_S
        bound_port = server.server_address[1]
        own_url = advertise_url or f"http://{host}:{bound_port}"
        rid = _Handler.replica_id or f"replica-{host}:{bound_port}"
        caps = capabilities if capabilities is not None \
            else capability_record(state, url=own_url)
        stop = threading.Event()
        threading.Thread(
            target=_announce_loop,
            args=(stop, announce,
                  {"replica_id": rid, "url": own_url,
                   "capabilities": caps}, announce_interval),
            daemon=True, name="spectre-announce").start()
        server._announce_stop = stop    # tests/shutdown hook
    if background:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    server.serve_forever()

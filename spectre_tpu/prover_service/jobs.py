"""Async proof job queue with a crash-safe journal.

The reference Spectre prover is an always-on coprocessor; proving
synchronously inside the HTTP handler couples request lifetime to a
multi-minute compute and loses every in-flight proof on a restart. This
module gives `prover_service` the host-orchestration layer the
accelerator-resident pipelines (zkSpeed/SZKP, PAPERS.md) assume:

* **JobQueue** — `submit()` returns a job id immediately; a bounded worker
  pool (sharing `ProverState.semaphore`, so batch + RPC + async load honor
  ONE concurrency cap) runs a `runner(method, params)` callback per job
  with per-job timeout and cancellation. The blocking `genEvmProof_*`
  RPC methods are `submit()` + `wait()` on top of the same queue.
* **JobJournal** — append-only JSONL under `params_dir`, fsync'd on every
  state transition (queued -> running -> done/failed). A restarted service
  replays the journal: finished jobs keep their results (dedup hits),
  jobs caught mid-prove are re-queued instead of lost. A torn final line
  (crash mid-append) is tolerated and ignored.
* **Dedup by witness digest** — jobs are keyed by a sha256 over the
  canonical (method, params) JSON, so a client that retries a submit (or a
  restart replay racing a client resubmit) never double-proves.

Timeouts cannot interrupt a compute-bound Python thread, so expiry is
enforced at the bookkeeping layer: the job is marked failed the moment its
deadline passes (observed by pollers and by the worker), and the eventual
runner result is discarded. Cancellation works the same way for running
jobs and dequeues queued ones outright.

Fault-injection site: `journal.write` (utils/faults) fires inside the
append path so CI can prove that a journal-write failure fails the job
rather than wedging the queue.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import queue
import threading
import time

from ..utils import faults
from ..utils.health import HEALTH

JOURNAL_NAME = "jobs.journal.jsonl"

# terminal states never transition again; "queued"/"running" are live
TERMINAL = ("done", "failed", "cancelled")

# startup-replay compaction trigger: past this size the journal is
# rewritten keeping only the terminal-state tail per job (ISSUE 4 /
# ROADMAP PR-3 follow-up: the JSONL otherwise grows unbounded)
COMPACT_ENV = "SPECTRE_JOURNAL_COMPACT_BYTES"
COMPACT_DEFAULT_BYTES = 4 << 20


def _compact_threshold() -> int:
    return int(os.environ.get(COMPACT_ENV, str(COMPACT_DEFAULT_BYTES)))


def witness_digest(method: str, params: dict) -> str:
    """Canonical digest of a proof request — the dedup key."""
    blob = json.dumps([method, params], sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


@dataclasses.dataclass
class Job:
    id: str
    method: str
    params: dict
    digest: str
    status: str = "queued"
    submitted_at: float = 0.0
    started_at: float | None = None
    finished_at: float | None = None
    timeout: float | None = None
    attempts: int = 0
    result: dict | None = None
    error: dict | None = None
    cancel_requested: bool = False

    def public(self) -> dict:
        """Status view returned by getProofStatus (no result payload)."""
        d = {"job_id": self.id, "status": self.status,
             "method": self.method, "digest": self.digest,
             "attempts": self.attempts,
             "submitted_at": self.submitted_at}
        if self.error is not None:
            d["error"] = self.error
        return d


class JobJournal:
    """Append-only JSONL journal, fsync'd per record."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, JOURNAL_NAME)
        self._lock = threading.Lock()

    def append(self, record: dict):
        faults.check("journal.write")
        line = json.dumps(record, sort_keys=True,
                          separators=(",", ":")) + "\n"
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())

    def replay(self) -> dict[str, Job]:
        """Fold the journal into the last-known state per job.

        Torn final lines (a crash mid-append) parse-fail and are skipped;
        every complete record was fsync'd so ordering is trustworthy."""
        jobs: dict[str, Job] = {}
        if not os.path.exists(self.path):
            return jobs
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                     # torn tail record
                ev, jid = rec.get("event"), rec.get("job_id")
                if not jid:
                    continue
                if ev == "submit":
                    jobs[jid] = Job(
                        id=jid, method=rec.get("method", ""),
                        params=rec.get("params") or {},
                        digest=rec.get("digest", ""),
                        submitted_at=rec.get("ts", 0.0),
                        timeout=rec.get("timeout"))
                    continue
                job = jobs.get(jid)
                if job is None:
                    continue                     # journal truncated earlier
                if ev == "running":
                    job.status = "running"
                    job.started_at = rec.get("ts")
                    job.attempts = rec.get("attempt", job.attempts + 1)
                elif ev == "requeued":
                    job.status = "queued"
                    job.started_at = None
                elif ev == "done":
                    job.status = "done"
                    job.result = rec.get("result")
                    job.finished_at = rec.get("ts")
                elif ev == "failed":
                    job.status = "failed"
                    job.error = rec.get("error")
                    job.finished_at = rec.get("ts")
                elif ev == "cancelled":
                    job.status = "cancelled"
                    job.finished_at = rec.get("ts")
        return jobs

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def compact(self, jobs):
        """Rewrite the JSONL keeping only the terminal-state tail per job:
        one `submit` record plus (for terminal jobs) the final event —
        every intermediate running/requeued transition is dropped. Done
        jobs keep their results so a restarted service still serves them.

        Crash-safe: the replacement is written to a sidecar file, fsync'd,
        and atomically `os.replace`d over the journal — a crash mid-compact
        (fault site `journal.compact`, fired after the rewrite is staged
        but before the swap) leaves the ORIGINAL journal untouched and the
        next startup simply re-compacts."""
        tmp = self.path + ".compact"
        with self._lock:
            with open(tmp, "w") as f:
                for job in sorted(jobs, key=lambda j: j.submitted_at):
                    recs = [{"event": "submit", "job_id": job.id,
                             "method": job.method, "params": job.params,
                             "digest": job.digest, "timeout": job.timeout,
                             "ts": job.submitted_at}]
                    if job.status in TERMINAL:
                        rec = {"event": job.status, "job_id": job.id,
                               "ts": job.finished_at}
                        if job.result is not None:
                            rec["result"] = job.result
                        if job.error is not None:
                            rec["error"] = job.error
                        recs.append(rec)
                    for rec in recs:
                        f.write(json.dumps(rec, sort_keys=True,
                                           separators=(",", ":")) + "\n")
                f.flush()
                # crash window: sidecar staged, original journal intact
                faults.check("journal.compact")
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            # fsync the directory so the rename survives power loss
            try:
                dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass


class JobQueue:
    """Bounded async worker pool over a `runner(method, params)` callback.

    `concurrency` sizes the worker threads. `semaphore` (optional) is an
    EXTERNAL concurrency governor for runners that do not self-govern; the
    ProverState runner acquires `state.semaphore` inside prove_* itself
    (non-reentrant — do not pass the same semaphore at both layers), so
    async jobs, blocking RPCs and batch proves already draw from one
    permit pool.
    """

    def __init__(self, runner, concurrency: int = 1,
                 journal_dir: str | None = None, semaphore=None,
                 default_timeout: float | None = None, health=HEALTH):
        self.runner = runner
        self.concurrency = max(1, int(concurrency))
        self.semaphore = semaphore
        self.default_timeout = default_timeout
        self.health = health
        self.journal = JobJournal(journal_dir) if journal_dir else None
        self._jobs: dict[str, Job] = {}
        self._by_digest: dict[str, str] = {}
        self._q: queue.Queue = queue.Queue()
        self._cv = threading.Condition()
        self._seq = 0
        self._stopped = False
        if self.journal is not None:
            self._recover()
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"prover-job-worker-{i}")
            for i in range(self.concurrency)]
        for t in self._workers:
            t.start()

    # -- recovery ----------------------------------------------------------

    def _recover(self):
        replayed = self.journal.replay()
        for job in replayed.values():
            self._jobs[job.id] = job
            # last submit wins the digest slot; terminal-but-failed jobs
            # stay resubmittable (dedup only pins live/done jobs)
            if job.status not in ("failed", "cancelled"):
                self._by_digest[job.digest] = job.id
            if job.status == "running":
                # caught mid-prove by a crash: re-run it
                job.status = "queued"
                job.started_at = None
                self._append({"event": "requeued", "job_id": job.id,
                              "ts": time.time()})
                self._q.put(job.id)
                self.health.incr("jobs_requeued")
            elif job.status == "queued":
                self._q.put(job.id)
        if replayed:
            self.health.incr("journal_replays")
        # startup compaction: replay (plus its requeue appends) is the one
        # moment the full job map is authoritative and no workers write
        if self.journal.size() > _compact_threshold():
            try:
                self.journal.compact(list(self._jobs.values()))
                self.health.incr("journal_compactions")
            except faults.InjectedCrash:
                raise          # simulated death mid-compact (tests)
            except Exception:
                # a failed compaction costs disk, never correctness: the
                # original journal is still the source of truth
                self.health.incr("journal_compact_failures")

    # -- journal helper ----------------------------------------------------

    def _append(self, record: dict):
        if self.journal is not None:
            self.journal.append(record)

    # -- submission / polling ---------------------------------------------

    def submit(self, method: str, params: dict,
               timeout: float | None = None) -> str:
        digest = witness_digest(method, params)
        with self._cv:
            existing = self._by_digest.get(digest)
            if existing is not None:
                job = self._jobs.get(existing)
                if job is not None and job.status not in ("failed",
                                                          "cancelled"):
                    self.health.incr("jobs_deduped")
                    return job.id
            self._seq += 1
            jid = f"{digest[:16]}-{self._seq:04d}"
            job = Job(id=jid, method=method, params=params, digest=digest,
                      submitted_at=time.time(),
                      timeout=(timeout if timeout is not None
                               else self.default_timeout))
            self._jobs[jid] = job
            self._by_digest[digest] = jid
        try:
            self._append({"event": "submit", "job_id": jid, "method": method,
                          "params": params, "digest": digest,
                          "timeout": job.timeout, "ts": job.submitted_at})
        except Exception as exc:
            # a dead journal must not wedge the queue: fail the job loudly
            with self._cv:
                job.status = "failed"
                job.error = _error_dict(exc)
                job.finished_at = time.time()
                self._cv.notify_all()
            self.health.incr("journal_write_failures")
            return jid
        self._q.put(jid)
        self.health.incr("jobs_submitted")
        return jid

    def status(self, job_id: str) -> dict | None:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            self._expire_locked(job)
            return job.public()

    def result(self, job_id: str) -> Job | None:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is not None:
                self._expire_locked(job)
            return job

    def wait(self, job_id: str, timeout: float | None = None) -> Job:
        deadline = None if timeout is None else time.time() + timeout
        with self._cv:
            while True:
                job = self._jobs[job_id]
                self._expire_locked(job)
                if job.status in TERMINAL:
                    return job
                remain = None if deadline is None else deadline - time.time()
                if remain is not None and remain <= 0:
                    return job
                self._cv.wait(timeout=min(0.5, remain)
                              if remain is not None else 0.5)

    def cancel(self, job_id: str) -> bool:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None or job.status in TERMINAL:
                return False
            job.cancel_requested = True
            if job.status == "queued":
                self._finish_locked(job, "cancelled")
                return True
        # running: the worker's result is discarded at completion
        return True

    def stats(self) -> dict:
        with self._cv:
            counts: dict[str, int] = {}
            for job in self._jobs.values():
                counts[job.status] = counts.get(job.status, 0) + 1
            return {"jobs": counts, "workers": self.concurrency}

    def stop(self):
        self._stopped = True
        for _ in self._workers:
            self._q.put(None)

    # -- worker ------------------------------------------------------------

    def _expire_locked(self, job: Job):
        if (job.status == "running" and job.timeout is not None
                and job.started_at is not None
                and time.time() > job.started_at + job.timeout):
            self._finish_locked(job, "failed",
                                error={"kind": "TimeoutError",
                                       "message": f"job exceeded "
                                       f"{job.timeout}s timeout"})
            self.health.incr("jobs_timed_out")

    def _finish_locked(self, job: Job, status: str, result=None, error=None):
        job.status = status
        job.result = result
        job.error = error
        job.finished_at = time.time()
        self._cv.notify_all()
        try:
            rec = {"event": status, "job_id": job.id, "ts": job.finished_at}
            if result is not None:
                rec["result"] = result
            if error is not None:
                rec["error"] = error
            self._append(rec)
        except Exception:
            # the in-memory state already transitioned; a journal failure
            # here only costs replay fidelity, never a wedged client
            self.health.incr("journal_write_failures")

    def _worker_loop(self):
        while True:
            jid = self._q.get()
            if jid is None or self._stopped:
                return
            with self._cv:
                job = self._jobs.get(jid)
                if job is None or job.status != "queued":
                    continue                    # cancelled / replaced
                job.status = "running"
                job.started_at = time.time()
                job.attempts += 1
                attempt = job.attempts
            try:
                self._append({"event": "running", "job_id": jid,
                              "attempt": attempt, "ts": job.started_at})
            except Exception as exc:
                with self._cv:
                    self._finish_locked(job, "failed",
                                        error=_error_dict(exc))
                self.health.incr("journal_write_failures")
                continue
            sem = self.semaphore
            try:
                if sem is not None:
                    sem.acquire()
                try:
                    result = self.runner(job.method, job.params)
                finally:
                    if sem is not None:
                        sem.release()
            except faults.InjectedCrash:
                # simulated hard kill: write NOTHING (that is the point —
                # journal replay must recover a torn "running" state) and
                # take this worker down like a dead process would
                raise
            except Exception as exc:
                with self._cv:
                    if job.status == "running":
                        self._finish_locked(job, "failed",
                                            error=_error_dict(exc))
                self.health.incr("jobs_failed")
                continue
            with self._cv:
                if job.cancel_requested:
                    self._finish_locked(job, "cancelled")
                    continue
                if job.status != "running":
                    continue                    # expired meanwhile: discard
                self._finish_locked(job, "done", result=result)
            self.health.incr("jobs_done")


def _error_dict(exc: BaseException) -> dict:
    return {"kind": type(exc).__name__, "message": str(exc)}


def ensure_jobs(state, journal_dir: str | None = None, runner=None,
                default_timeout: float | None = None) -> JobQueue:
    """Attach (once) a JobQueue to any prover-state-like object.

    Reuses `state.semaphore`/`state.concurrency` when present so the async
    queue and the blocking/batch paths share one concurrency cap. `runner`
    defaults to the RPC proof dispatcher."""
    jobsq = getattr(state, "jobs", None)
    if jobsq is not None:
        return jobsq
    if runner is None:
        from .rpc import run_proof_method
        runner = lambda method, params: run_proof_method(state, method,
                                                         params)
    # NOTE: no JobQueue-level semaphore here — the default runner goes
    # through state.prove_* which acquire state.semaphore THEMSELVES
    # (threading.Semaphore is not reentrant; acquiring at both layers
    # deadlocks at concurrency=1). The worker-pool size mirrors the same
    # cap, so queued jobs drain at exactly the governed parallelism.
    jobsq = JobQueue(
        runner,
        concurrency=getattr(state, "concurrency", 1),
        journal_dir=journal_dir if journal_dir is not None
        else getattr(state, "params_dir", None),
        default_timeout=default_timeout)
    state.jobs = jobsq
    return jobsq

"""Async proof job queue with a crash-safe journal.

The reference Spectre prover is an always-on coprocessor; proving
synchronously inside the HTTP handler couples request lifetime to a
multi-minute compute and loses every in-flight proof on a restart. This
module gives `prover_service` the host-orchestration layer the
accelerator-resident pipelines (zkSpeed/SZKP, PAPERS.md) assume:

* **JobQueue** — `submit()` returns a job id immediately; a bounded worker
  pool (sharing `ProverState.semaphore`, so batch + RPC + async load honor
  ONE concurrency cap) runs a `runner(method, params)` callback per job
  with per-job timeout and cancellation. The blocking `genEvmProof_*`
  RPC methods are `submit()` + `wait()` on top of the same queue.
* **JobJournal** — append-only JSONL under `params_dir`, fsync'd on every
  state transition (queued -> running -> done/failed). A restarted service
  replays the journal: finished jobs keep their results (dedup hits),
  jobs caught mid-prove are re-queued instead of lost. A torn final line
  (crash mid-append) is tolerated and ignored.
* **Dedup by witness digest** — jobs are keyed by a sha256 over the
  canonical (method, params) JSON, so a client that retries a submit (or a
  restart replay racing a client resubmit) never double-proves.

Timeouts cannot interrupt a compute-bound Python thread, so expiry is
enforced at the bookkeeping layer: the job is marked failed the moment its
deadline passes (observed by pollers and by the worker), and the eventual
runner result is discarded. Cancellation works the same way for running
jobs and dequeues queued ones outright.

Overload + integrity layer (ISSUE 6):

* **Admission control** — the queue is bounded (`SPECTRE_JOB_QUEUE_DEPTH`,
  default 64): a full backlog rejects new submissions with a typed
  :class:`ServiceOverloaded` carrying `retry_after_s` (priced at the p90
  of the queue-local prove-latency histogram, ISSUE 7; ServiceHealth
  mean as the cold-start fallback) instead of buffering unboundedly. A
  host-memory watermark (`SPECTRE_MEM_WATERMARK_MB`, psutil-free
  `/proc/self/statm`; graceful no-op off-Linux) sheds NEW work before
  the box OOMs. Counters: `jobs_shed_queue` / `jobs_shed_memory`; a
  memory shed journals a `shed_memory` record attributing the per-job
  `peak_rss_mb` of every running job (replay-inert: no job_id).
* **Deadline propagation** — a client-supplied `deadline_s` clamps the
  per-job timeout at submit time.
* **Worker supervision** — workers stamp a monotonic heartbeat between
  prove phases (a `heartbeat` callback threaded through the runner into
  `ProverState.prove_*`); a supervisor thread detects a worker stalled
  past `SPECTRE_WORKER_STALL_S`, marks its job `failed(stalled)`, spawns
  a replacement worker for the slot (the hung thread is disowned — on an
  eventual return it notices it lost its slot and exits) and bumps
  `workers_replaced`. The supervisor only does bookkeeping: it NEVER
  proves inline (the non-reentrant `state.semaphore` rule).
* **Artifact offload** — proof results live in an integrity-checked
  content-addressed store (utils/artifacts) under
  `params_dir/results/<sha256>.bin`; the journal records the digest, not
  the payload, so it stays O(#jobs). Replay re-verifies digests and
  quarantines corrupt files (the job degrades to failed + re-provable)
  instead of serving poison.

Provenance manifests (ISSUE 8): every job that reaches a worker also
emits a per-proof manifest (observability/manifest.py — timestamps with
queue wait split out, resolved modes + env knobs, degrade/fault events,
MSM/NTT table-LRU deltas, JIT compile events, phase seconds, peak RSS,
result digest). Manifests are artifacts (`<sha256>.manifest.json` via
utils/artifacts, journal stores only the digest) and are IO-tolerant
like the metrics sink: fault site `manifest.write`, counter
`manifest_write_failures` — a broken manifest sink never fails a prove,
the manifest just degrades to absent (`getProofManifest` → unavailable).

Fault-injection sites: `journal.write` fires inside the append path so CI
can prove a journal-write failure fails the job rather than wedging the
queue; `artifact.write`/`artifact.read` cover the result store;
`manifest.write` covers the manifest sink.
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
import json
import os
import queue
import threading
import time

from ..observability import compilelog as obs_compilelog
from ..observability import manifest as obs_manifest
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from ..observability.rss import SAMPLER as RSS_SAMPLER
from ..observability.rss import rss_mb  # noqa: F401  (re-export: the
# watermark check lives here historically; tests import it from jobs)
from ..utils import faults
from ..utils.artifacts import ArtifactCorrupt, ArtifactStore
from ..utils.health import HEALTH
from .scrubber import Scrubber

JOURNAL_NAME = "jobs.journal.jsonl"

# admission control (ISSUE 6): bound the backlog, shed before the box OOMs
QUEUE_DEPTH_ENV = "SPECTRE_JOB_QUEUE_DEPTH"
QUEUE_DEPTH_DEFAULT = 64
MEM_WATERMARK_ENV = "SPECTRE_MEM_WATERMARK_MB"      # 0 / unset = disabled
WORKER_STALL_ENV = "SPECTRE_WORKER_STALL_S"
WORKER_STALL_DEFAULT_S = 600.0

# retry_after_s fallback when no prove has completed yet (nothing observed)
DEFAULT_PROVE_LATENCY_S = 30.0

# terminal states never transition again; "queued"/"running" are live
TERMINAL = ("done", "failed", "cancelled")

# startup-replay compaction trigger: past this size the journal is
# rewritten keeping only the terminal-state tail per job (ISSUE 4 /
# ROADMAP PR-3 follow-up: the JSONL otherwise grows unbounded)
COMPACT_ENV = "SPECTRE_JOURNAL_COMPACT_BYTES"
COMPACT_DEFAULT_BYTES = 4 << 20


def _compact_threshold() -> int:
    return int(os.environ.get(COMPACT_ENV, str(COMPACT_DEFAULT_BYTES)))


def _env_num(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


class ServiceOverloaded(RuntimeError):
    """Load shed: the submission was REJECTED (queue full / memory
    watermark), not queued. Carries the backoff hint the RPC layer turns
    into `-32001` + HTTP 429 `Retry-After`."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(f"service overloaded ({reason}); "
                         f"retry after {retry_after_s:.1f}s")
        self.reason = reason
        self.retry_after_s = retry_after_s


def witness_digest(method: str, params: dict) -> str:
    """Canonical digest of a proof request — the dedup key."""
    blob = json.dumps([method, params], sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


@dataclasses.dataclass
class Job:
    id: str
    method: str
    params: dict
    digest: str
    status: str = "queued"
    submitted_at: float = 0.0
    admitted_at: float | None = None    # admission-control pass (ISSUE 8)
    started_at: float | None = None
    finished_at: float | None = None
    timeout: float | None = None
    attempts: int = 0
    result: dict | None = None
    result_digest: str | None = None    # sha256 of the offloaded artifact
    error: dict | None = None
    cancel_requested: bool = False
    peak_rss_mb: float | None = None    # per-job RSS attribution (ISSUE 7)
    queue_wait_s: float | None = None   # admission -> worker start
    manifest_digest: str | None = None  # provenance manifest artifact

    def public(self) -> dict:
        """Status view returned by getProofStatus (no result payload)."""
        d = {"job_id": self.id, "status": self.status,
             "method": self.method, "digest": self.digest,
             "attempts": self.attempts,
             "submitted_at": self.submitted_at}
        if self.error is not None:
            d["error"] = self.error
        if self.peak_rss_mb is not None:
            d["peak_rss_mb"] = self.peak_rss_mb
        if self.queue_wait_s is not None:
            d["queue_wait_s"] = self.queue_wait_s
        if self.manifest_digest is not None:
            d["manifest_digest"] = self.manifest_digest
        return d


class JobJournal:
    """Append-only JSONL journal, fsync'd per record."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, JOURNAL_NAME)
        self._lock = threading.Lock()

    def append(self, record: dict):
        faults.check("journal.write")
        line = json.dumps(record, sort_keys=True,
                          separators=(",", ":")) + "\n"
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())

    def replay(self) -> dict[str, Job]:
        """Fold the journal into the last-known state per job.

        Torn final lines (a crash mid-append) parse-fail and are skipped;
        every complete record was fsync'd so ordering is trustworthy."""
        jobs: dict[str, Job] = {}
        if not os.path.exists(self.path):
            return jobs
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                     # torn tail record
                ev, jid = rec.get("event"), rec.get("job_id")
                if not jid:
                    continue
                if ev == "submit":
                    jobs[jid] = Job(
                        id=jid, method=rec.get("method", ""),
                        params=rec.get("params") or {},
                        digest=rec.get("digest", ""),
                        submitted_at=rec.get("ts", 0.0),
                        admitted_at=rec.get("admitted"),
                        timeout=rec.get("timeout"))
                    continue
                job = jobs.get(jid)
                if job is None:
                    continue                     # journal truncated earlier
                if ev == "running":
                    job.status = "running"
                    job.started_at = rec.get("ts")
                    job.attempts = rec.get("attempt", job.attempts + 1)
                elif ev == "requeued":
                    job.status = "queued"
                    job.started_at = None
                elif ev == "done":
                    job.status = "done"
                    # post-offload records carry the artifact digest; the
                    # inline form stays readable (pre-ISSUE-6 journals)
                    job.result = rec.get("result")
                    job.result_digest = rec.get("result_digest")
                    job.finished_at = rec.get("ts")
                    job.peak_rss_mb = rec.get("peak_rss_mb")
                    job.manifest_digest = rec.get("manifest_digest")
                elif ev == "failed":
                    job.status = "failed"
                    job.error = rec.get("error")
                    job.finished_at = rec.get("ts")
                    job.peak_rss_mb = rec.get("peak_rss_mb")
                    job.manifest_digest = rec.get("manifest_digest")
                elif ev == "cancelled":
                    job.status = "cancelled"
                    job.finished_at = rec.get("ts")
        return jobs

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def compact(self, jobs):
        """Rewrite the JSONL keeping only the terminal-state tail per job:
        one `submit` record plus (for terminal jobs) the final event —
        every intermediate running/requeued transition is dropped. Done
        jobs keep their results so a restarted service still serves them.

        Crash-safe: the replacement is written to a sidecar file, fsync'd,
        and atomically `os.replace`d over the journal — a crash mid-compact
        (fault site `journal.compact`, fired after the rewrite is staged
        but before the swap) leaves the ORIGINAL journal untouched and the
        next startup simply re-compacts."""
        tmp = self.path + ".compact"
        with self._lock:
            with open(tmp, "w") as f:
                for job in sorted(jobs, key=lambda j: j.submitted_at):
                    sub = {"event": "submit", "job_id": job.id,
                           "method": job.method, "params": job.params,
                           "digest": job.digest, "timeout": job.timeout,
                           "ts": job.submitted_at}
                    if job.admitted_at is not None:
                        sub["admitted"] = job.admitted_at
                    recs = [sub]
                    if job.status in TERMINAL:
                        rec = {"event": job.status, "job_id": job.id,
                               "ts": job.finished_at}
                        # journal slimming (ISSUE 6): an offloaded result
                        # compacts to its digest — NEVER re-inline the
                        # payload, the journal must stay O(#jobs)
                        if job.result_digest is not None:
                            rec["result_digest"] = job.result_digest
                        elif job.result is not None:
                            rec["result"] = job.result
                        if job.error is not None:
                            rec["error"] = job.error
                        if job.peak_rss_mb is not None:
                            rec["peak_rss_mb"] = job.peak_rss_mb
                        # the manifest stays an O(1) digest through
                        # compaction, exactly like the result artifact
                        if job.manifest_digest is not None:
                            rec["manifest_digest"] = job.manifest_digest
                        recs.append(rec)
                    for rec in recs:
                        f.write(json.dumps(rec, sort_keys=True,
                                           separators=(",", ":")) + "\n")
                f.flush()
                # crash window: sidecar staged, original journal intact
                faults.check("journal.compact")
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            # fsync the directory so the rename survives power loss
            try:
                dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass


class JobQueue:
    """Bounded async worker pool over a `runner(method, params)` callback.

    `concurrency` sizes the worker threads. `semaphore` (optional) is an
    EXTERNAL concurrency governor for runners that do not self-govern; the
    ProverState runner acquires `state.semaphore` inside prove_* itself
    (non-reentrant — do not pass the same semaphore at both layers), so
    async jobs, blocking RPCs and batch proves already draw from one
    permit pool.
    """

    def __init__(self, runner, concurrency: int = 1,
                 journal_dir: str | None = None, semaphore=None,
                 default_timeout: float | None = None, health=HEALTH,
                 queue_depth: int | None = None,
                 mem_watermark_mb: float | None = None,
                 stall_timeout: float | None = None,
                 clock=time.monotonic, sleep_interval: float | None = None,
                 latency_hist=None, scrub_interval: float | None = None,
                 scrub_min_age: float | None = None, live_providers=()):
        """`queue_depth`/`mem_watermark_mb`/`stall_timeout` default to the
        SPECTRE_JOB_QUEUE_DEPTH / SPECTRE_MEM_WATERMARK_MB /
        SPECTRE_WORKER_STALL_S env knobs. `clock` and `sleep_interval` are
        the supervisor's injectable time source and scan period (the
        BeaconClient pattern: stall tests run deterministic + fast).
        `latency_hist` (injectable for tests) is the queue-local prove
        latency histogram that prices `retry_after_s` at its p90.
        `scrub_interval`/`scrub_min_age` (ISSUE 9; SPECTRE_SCRUB_INTERVAL_S
        / SPECTRE_SCRUB_MIN_AGE_S) govern the artifact scrubber — interval
        0 disables the periodic thread (scrubNow still works)."""
        self.runner = runner
        self.concurrency = max(1, int(concurrency))
        self.semaphore = semaphore
        self.default_timeout = default_timeout
        self.health = health
        self.journal = JobJournal(journal_dir) if journal_dir else None
        self.store = ArtifactStore(journal_dir, health=health) \
            if journal_dir else None
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else _env_num(QUEUE_DEPTH_ENV,
                                             QUEUE_DEPTH_DEFAULT))
        self.mem_watermark_mb = float(
            mem_watermark_mb if mem_watermark_mb is not None
            else _env_num(MEM_WATERMARK_ENV, 0.0))
        self.stall_timeout = float(
            stall_timeout if stall_timeout is not None
            else _env_num(WORKER_STALL_ENV, WORKER_STALL_DEFAULT_S))
        self._clock = clock
        # retry_after pricing (ISSUE 7, closes the PR-6 follow-up): a
        # queue-LOCAL histogram — p90 of what *this* queue observed, not
        # a process-wide mean a single outlier can poison
        self.latency = (latency_hist if latency_hist is not None
                        else obs_metrics.queue_latency_histogram())
        self._jobs: dict[str, Job] = {}
        self._by_digest: dict[str, str] = {}
        self._q: queue.Queue = queue.Queue()
        self._cv = threading.Condition()
        self._seq = 0
        self._stopped = False
        self._stop_event = threading.Event()
        # does the runner accept a heartbeat callback? (inspected once —
        # plain runner(method, params) callables keep working unchanged)
        self._runner_heartbeat = _accepts_heartbeat(runner)
        # external keep-set providers (ISSUE 10): subsystems sharing the
        # results/ namespace (the follower's update store) contribute
        # their own (digest, suffix) pairs so neither compaction-time nor
        # periodic scrubs expire an artifact a chain record references.
        # Registered BEFORE the scrubber/_recover so the post-compaction
        # pass already sees them.
        self._live_providers = list(live_providers)
        # artifact scrubber (ISSUE 9): built before _recover so the
        # post-compaction pass can expire freshly-orphaned artifacts
        self.scrubber = Scrubber(self.store, self._live_artifacts,
                                 health=health, min_age_s=scrub_min_age) \
            if self.store is not None else None
        if self.journal is not None:
            self._recover()
        # per-slot worker bookkeeping: the supervisor compares each slot's
        # heartbeat against `clock()` and replaces the thread on stall
        self._slots = [{"thread": None, "beat": self._clock(), "job": None}
                       for _ in range(self.concurrency)]
        for i in range(self.concurrency):
            self._spawn_worker(i)
        self._supervisor = threading.Thread(
            target=self._supervise_loop, daemon=True,
            name="prover-job-supervisor",
            args=(sleep_interval if sleep_interval is not None
                  else max(0.05, min(self.stall_timeout / 4.0, 1.0)),))
        self._supervisor.start()
        if self.scrubber is not None:
            self.scrubber.start(scrub_interval, self._stop_event)

    @property
    def _workers(self):
        """Live worker threads (legacy-test compat view over the slots)."""
        return [s["thread"] for s in self._slots if s["thread"] is not None]

    def _spawn_worker(self, slot: int):
        t = threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"prover-job-worker-{slot}",
                             args=(slot,))
        self._slots[slot]["thread"] = t
        self._slots[slot]["beat"] = self._clock()
        t.start()

    # -- recovery ----------------------------------------------------------

    def _recover(self):
        replayed = self.journal.replay()
        for job in replayed.values():
            self._jobs[job.id] = job
            # restore the id counter past every replayed job: a fresh
            # submission after restart must never mint a colliding id
            # (which would silently OVERWRITE the replayed record)
            try:
                self._seq = max(self._seq, int(job.id.rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                pass
            if job.status == "done":
                self._resolve_result(job)
            # last submit wins the digest slot; terminal-but-failed jobs
            # stay resubmittable (dedup only pins live/done jobs)
            if job.status not in ("failed", "cancelled"):
                self._by_digest[job.digest] = job.id
            if job.status == "running":
                # caught mid-prove by a crash: re-run it
                job.status = "queued"
                job.started_at = None
                self._append({"event": "requeued", "job_id": job.id,
                              "ts": time.time()})
                self._q.put(job.id)
                self.health.incr("jobs_requeued")
            elif job.status == "queued":
                self._q.put(job.id)
        if replayed:
            self.health.incr("journal_replays")
        # startup compaction: replay (plus its requeue appends) is the one
        # moment the full job map is authoritative and no workers write
        if self.journal.size() > _compact_threshold():
            try:
                self.journal.compact(list(self._jobs.values()))
                self.health.incr("journal_compactions")
            except faults.InjectedCrash:
                raise          # simulated death mid-compact (tests)
            except Exception:
                # a failed compaction costs disk, never correctness: the
                # original journal is still the source of truth
                self.health.incr("journal_compact_failures")
            else:
                # the scrub pass that follows compaction (ISSUE 9, closes
                # the PR-8 follow-up): the compacted journal is now the
                # authority on which digests are live — artifacts it no
                # longer references are expired, corrupt ones quarantined
                if self.scrubber is not None:
                    try:
                        self.scrubber.scrub()
                    except Exception:
                        self.health.incr("artifacts_scrub_errors")

    def _resolve_result(self, job: Job):
        """Re-hydrate a done job's result from the artifact store,
        RE-VERIFYING the digest. A corrupt artifact is quarantined (by
        the store) and the job degrades to failed — its digest slot is
        not pinned, so a resubmission simply re-proves."""
        if job.result is not None or job.result_digest is None:
            return                       # inline (legacy) or nothing to do
        if self.store is None:
            job.status = "failed"
            job.error = {"kind": "ArtifactCorrupt",
                         "message": "result artifact store unavailable"}
            return
        try:
            job.result = json.loads(self.store.read(job.result_digest))
        except (ArtifactCorrupt, OSError, ValueError) as exc:
            job.status = "failed"
            job.error = _error_dict(exc)
            try:
                self._append({"event": "failed", "job_id": job.id,
                              "error": job.error, "ts": time.time()})
            except Exception:
                self.health.incr("journal_write_failures")

    # -- journal helper ----------------------------------------------------

    def _append(self, record: dict):
        if self.journal is not None:
            self.journal.append(record)

    # -- submission / polling ---------------------------------------------

    def retry_after_s(self) -> float:
        with self._cv:
            return self.retry_after_locked()

    def _admit_locked(self, digest: str):
        """Load-shedding gate (called with _cv held, AFTER the dedup
        check — a retry of known work is free and never shed)."""
        pending = sum(1 for j in self._jobs.values()
                      if j.status == "queued")
        if pending >= self.queue_depth:
            self.health.incr("jobs_shed_queue")
            raise ServiceOverloaded("queue full", self.retry_after_locked())
        if self.mem_watermark_mb > 0:
            rss = rss_mb()
            if rss is not None and rss >= self.mem_watermark_mb:
                self.health.incr("jobs_shed_memory")
                # attribution (ISSUE 7, closes the PR-6 follow-up): name
                # the running jobs (and their RSS high-water marks) the
                # shed protected the box from. No top-level job_id, so
                # journal replay skips the record by design.
                running = [{"job_id": j.id,
                            "peak_rss_mb": RSS_SAMPLER.peak(j.id)}
                           for j in self._jobs.values()
                           if j.status == "running"]
                try:
                    self._append({"event": "shed_memory",
                                  "ts": time.time(),
                                  "rss_mb": round(rss, 1),
                                  "running": running})
                except Exception:
                    self.health.incr("journal_write_failures")
                raise ServiceOverloaded("memory watermark",
                                        self.retry_after_locked())

    def retry_after_locked(self) -> float:
        """Backoff hint for shed submissions: the backlog ahead of a
        retrying client, priced at the p90 of this queue's observed
        prove latency (a single outlier must not inflate the hint the
        way it inflates a mean — pinned in tests/test_observability.py).
        Falls back to the ServiceHealth running mean until the queue has
        completed a job of its own.

        Note on wait vs prove (ISSUE 8): the p90 here covers the PROVE
        only (worker start -> finish); the time a job spends queued is
        modelled by the `backlog / concurrency` factor. The observed
        split is exported separately — `spectre_queue_wait_seconds`
        (admission -> start) vs `spectre_prove_latency_seconds` — and
        every manifest records its own `queue_wait_s`/`prove_s`, so an
        inflated retry hint can be attributed to queueing or to slow
        proves, not guessed at."""
        p90 = self.latency.quantile(0.9)
        if p90 is None:
            p90 = self.health.mean("prove_latency_s",
                                   DEFAULT_PROVE_LATENCY_S)
        backlog = sum(1 for j in self._jobs.values()
                      if j.status in ("queued", "running"))
        est = p90 * max(1.0, float(backlog)) / float(self.concurrency)
        return round(min(max(est, 1.0), 600.0), 3)

    def submit(self, method: str, params: dict,
               timeout: float | None = None,
               deadline_s: float | None = None) -> str:
        """`deadline_s` (client-supplied) CLAMPS the effective per-job
        timeout — a client that must answer its own caller in 60s gets a
        job that gives up by then rather than burning a worker on a
        result nobody will read. Raises :class:`ServiceOverloaded` when
        admission control sheds the submission."""
        arrival = time.time()           # request arrival, pre-admission
        digest = witness_digest(method, params)
        eff_timeout = timeout if timeout is not None else self.default_timeout
        if deadline_s is not None:
            eff_timeout = deadline_s if eff_timeout is None \
                else min(eff_timeout, deadline_s)
        with self._cv:
            existing = self._by_digest.get(digest)
            if existing is not None:
                job = self._jobs.get(existing)
                if job is not None and job.status not in ("failed",
                                                          "cancelled"):
                    self.health.incr("jobs_deduped")
                    return job.id
            self._admit_locked(digest)
            self._seq += 1
            jid = f"{digest[:16]}-{self._seq:04d}"
            # submitted == request arrival, admitted == the instant the
            # admission gate passed; the worker measures queue wait from
            # `admitted` (the job only exists as queue work from then on)
            job = Job(id=jid, method=method, params=params, digest=digest,
                      submitted_at=arrival, admitted_at=time.time(),
                      timeout=eff_timeout)
            self._jobs[jid] = job
            self._by_digest[digest] = jid
        try:
            self._append({"event": "submit", "job_id": jid, "method": method,
                          "params": params, "digest": digest,
                          "timeout": job.timeout, "ts": job.submitted_at,
                          "admitted": job.admitted_at})
        except Exception as exc:
            # a dead journal must not wedge the queue: fail the job loudly
            with self._cv:
                job.status = "failed"
                job.error = _error_dict(exc)
                job.finished_at = time.time()
                self._cv.notify_all()
            self.health.incr("journal_write_failures")
            return jid
        self._q.put(jid)
        self.health.incr("jobs_submitted")
        return jid

    def status(self, job_id: str) -> dict | None:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            self._expire_locked(job)
            return job.public()

    def result(self, job_id: str) -> Job | None:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is not None:
                self._expire_locked(job)
            return job

    def wait(self, job_id: str, timeout: float | None = None) -> Job:
        deadline = None if timeout is None else time.time() + timeout
        with self._cv:
            while True:
                job = self._jobs[job_id]
                self._expire_locked(job)
                if job.status in TERMINAL:
                    return job
                remain = None if deadline is None else deadline - time.time()
                if remain is not None and remain <= 0:
                    return job
                self._cv.wait(timeout=min(0.5, remain)
                              if remain is not None else 0.5)

    def cancel(self, job_id: str) -> bool:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None or job.status in TERMINAL:
                return False
            job.cancel_requested = True
            if job.status == "queued":
                self._finish_locked(job, "cancelled")
                return True
        # running: the worker's result is discarded at completion
        return True

    def stats(self) -> dict:
        with self._cv:
            counts: dict[str, int] = {}
            for job in self._jobs.values():
                counts[job.status] = counts.get(job.status, 0) + 1
            return {"jobs": counts, "workers": self.concurrency,
                    "queue_depth": self.queue_depth}

    def stop(self):
        self._stopped = True
        self._stop_event.set()     # also stops the scrubber's wait loop
        for _ in range(self.concurrency):
            self._q.put(None)

    # -- artifact scrubbing (ISSUE 9) --------------------------------------

    def _live_artifacts(self) -> set:
        """(digest, suffix) pairs some known job still references — the
        scrubber's keep-set. Every status counts: a failed job's partial
        artifacts are cheap, and expiry must never race a retry."""
        live = set()
        with self._cv:
            for job in self._jobs.values():
                if job.result_digest is not None:
                    live.add((job.result_digest, ".bin"))
                if job.manifest_digest is not None:
                    live.add((job.manifest_digest,
                              obs_manifest.MANIFEST_SUFFIX))
        for provider in list(self._live_providers):
            # a broken provider propagates: the scrub PASS fails (counted
            # by its caller) rather than running with a partial keep-set
            # and expiring artifacts that are actually live
            live |= set(provider())
        return live

    def add_live_provider(self, provider):
        """Register a zero-arg callable returning extra (digest, suffix)
        pairs to protect from orphan expiry (idempotent)."""
        if provider not in self._live_providers:
            self._live_providers.append(provider)

    def scrub_now(self) -> dict:
        """One synchronous scrubber pass (the scrubNow RPC / CLI entry)."""
        if self.scrubber is None:
            return {"scanned": 0, "corrupt": 0, "expired": 0, "skipped": 0}
        return self.scrubber.scrub()

    # -- worker ------------------------------------------------------------

    def _expire_locked(self, job: Job):
        if (job.status == "running" and job.timeout is not None
                and job.started_at is not None
                and time.time() > job.started_at + job.timeout):
            self._finish_locked(job, "failed",
                                error={"kind": "TimeoutError",
                                       "message": f"job exceeded "
                                       f"{job.timeout}s timeout"})
            self.health.incr("jobs_timed_out")

    def _finish_locked(self, job: Job, status: str, result=None, error=None,
                       result_digest=None):
        job.status = status
        job.result = result
        job.result_digest = result_digest
        job.error = error
        job.finished_at = time.time()
        self._cv.notify_all()
        try:
            rec = {"event": status, "job_id": job.id, "ts": job.finished_at}
            # offloaded results journal as their digest; the payload lives
            # in the integrity-checked artifact store
            if result_digest is not None:
                rec["result_digest"] = result_digest
            elif result is not None:
                rec["result"] = result
            if error is not None:
                rec["error"] = error
            if job.peak_rss_mb is not None:
                rec["peak_rss_mb"] = job.peak_rss_mb
            # journal carries the manifest DIGEST only (O(#jobs), like
            # the result artifact); replay re-verifies through the store
            if job.manifest_digest is not None:
                rec["manifest_digest"] = job.manifest_digest
            self._append(rec)
        except Exception:
            # the in-memory state already transitioned; a journal failure
            # here only costs replay fidelity, never a wedged client
            self.health.incr("journal_write_failures")

    def _write_manifest(self, job: Job, *, trace, compile_events, events,
                        lru_before, peak_rss_mb, finished,
                        result_digest=None, error=None) -> str | None:
        """Build + persist the job's provenance manifest through the
        artifact store (`<sha256>.manifest.json`); returns the digest.

        IO-tolerant by the metrics.write contract: fault site
        `manifest.write` fires inside the store write, and ANY failure
        (broken disk, serialization surprise) counts
        `manifest_write_failures` and returns None — the job still
        finishes, its manifest degrades to absent. Only an InjectedCrash
        propagates (a dead process writes nothing, which is the state
        replay tests recover from)."""
        if self.store is None:
            return None
        try:
            man = obs_manifest.build(
                job_id=job.id, method=job.method,
                witness_digest=job.digest, attempts=job.attempts,
                submitted=job.submitted_at, admitted=job.admitted_at,
                started=job.started_at, finished=finished,
                queue_wait_s=job.queue_wait_s, trace=trace,
                compile_events=compile_events, events=events,
                lru_before=lru_before,
                lru_after=obs_manifest.lru_snapshot(),
                peak_rss_mb=peak_rss_mb, result_digest=result_digest,
                error=None if error is None
                else f"{error.get('kind')}: {error.get('message')}")
            return self.store.write(obs_manifest.to_bytes(man),
                                    suffix=obs_manifest.MANIFEST_SUFFIX,
                                    fault_site="manifest.write")
        except faults.InjectedCrash:
            raise
        except Exception:
            self.health.incr("manifest_write_failures")
            return None

    def manifest(self, job_id: str) -> dict | None:
        """Load + RE-VERIFY a job's provenance manifest from the artifact
        store. Returns None when the job has no manifest digest yet (live
        job, crashed worker, tolerated write failure) or when the stored
        bytes fail verification (the store quarantines them) — manifests
        degrade to absent; result-serving rules are unchanged."""
        with self._cv:
            job = self._jobs.get(job_id)
            digest = job.manifest_digest if job is not None else None
        if digest is None or self.store is None:
            return None
        try:
            return obs_manifest.from_bytes(
                self.store.read(digest,
                                suffix=obs_manifest.MANIFEST_SUFFIX))
        except (ArtifactCorrupt, OSError, ValueError):
            self.health.incr("manifest_read_failures")
            return None

    def _beat(self, slot: int, jid: str):
        """Heartbeat stamp — called by the worker between prove phases
        (threaded into the runner as a zero-arg callback)."""
        s = self._slots[slot]
        if s["job"] == jid:
            s["beat"] = self._clock()

    def _owns_slot(self, slot: int) -> bool:
        return self._slots[slot]["thread"] is threading.current_thread()

    def _worker_loop(self, slot: int):
        while True:
            jid = self._q.get()
            # a replaced (previously stalled) worker that wakes back up
            # has LOST its slot: put the item back and die quietly — the
            # replacement thread owns the queue now
            if not self._owns_slot(slot):
                if jid is not None:
                    self._q.put(jid)
                return
            if jid is None or self._stopped:
                return
            with self._cv:
                job = self._jobs.get(jid)
                if job is None or job.status != "queued":
                    continue                    # cancelled / replaced
                job.status = "running"
                job.started_at = time.time()
                job.attempts += 1
                attempt = job.attempts
                # queue-wait decomposition (ISSUE 8): one float, three
                # sinks — the job record, the manifest, and the
                # spectre_queue_wait_seconds histogram observe the SAME
                # value (tests pin exact parity). Replayed pre-ISSUE-8
                # journals lack `admitted`; fall back to submit time.
                job.queue_wait_s = round(
                    max(0.0, job.started_at
                        - (job.admitted_at if job.admitted_at is not None
                           else job.submitted_at)), 6)
                self._slots[slot]["job"] = jid
                self._slots[slot]["beat"] = self._clock()
            obs_metrics.QUEUE_WAIT.observe(job.queue_wait_s)
            try:
                self._append({"event": "running", "job_id": jid,
                              "attempt": attempt, "ts": job.started_at})
            except Exception as exc:
                with self._cv:
                    self._finish_locked(job, "failed",
                                        error=_error_dict(exc))
                    if self._slots[slot]["job"] == jid:
                        self._slots[slot]["job"] = None
                self.health.incr("journal_write_failures")
                continue
            sem = self.semaphore
            heartbeat = (lambda s=slot, j=jid: self._beat(s, j))
            t0 = time.time()
            # per-job attribution (ISSUE 7): RSS peak + span trace for
            # the runner's lifetime. prove runs ON this thread, so every
            # profiling.phase below the runner attaches to the trace via
            # the thread-local — no plumbing through prove_* signatures.
            RSS_SAMPLER.start(jid)
            # provenance capture (ISSUE 8): compile events, degrade/fault
            # events and table-LRU deltas for the runner's lifetime — all
            # thread-local, so concurrent workers collect independently
            lru_before = obs_manifest.lru_snapshot()
            compile_events: list = []
            run_events: list = []
            job_trace = None
            try:
                if sem is not None:
                    sem.acquire()
                try:
                    with obs_tracing.trace(jid) as tr, \
                            obs_compilelog.capture(compile_events), \
                            obs_manifest.collect_events(run_events):
                        job_trace = tr
                        if self._runner_heartbeat:
                            result = self.runner(job.method, job.params,
                                                 heartbeat=heartbeat)
                        else:
                            result = self.runner(job.method, job.params)
                finally:
                    if sem is not None:
                        sem.release()
            except faults.InjectedCrash:
                # simulated hard kill: write NOTHING (that is the point —
                # journal replay must recover a torn "running" state) and
                # take this worker down like a dead process would. The
                # sampler entry is still released (a real dead process
                # takes its sampler thread with it; this one is shared).
                RSS_SAMPLER.finish(jid)
                raise
            except Exception as exc:
                peak = RSS_SAMPLER.finish(jid)
                # failed proves get manifests too — "what degraded before
                # it died" is exactly what post-mortems need
                man_digest = self._write_manifest(
                    job, trace=job_trace, compile_events=compile_events,
                    events=run_events, lru_before=lru_before,
                    peak_rss_mb=peak, finished=time.time(),
                    error=_error_dict(exc))
                with self._cv:
                    if self._slots[slot]["job"] == jid:
                        self._slots[slot]["job"] = None
                    if not self._owns_slot(slot):
                        return      # disowned: replacement took the slot
                    if job.status == "running":
                        job.peak_rss_mb = peak
                        job.manifest_digest = man_digest
                        self._finish_locked(job, "failed",
                                            error=_error_dict(exc))
                self.health.incr("jobs_failed")
                continue
            peak = RSS_SAMPLER.finish(jid)
            # retry_after estimates feed on real observed latency: the
            # running-mean gauge (healthz view + cold-start fallback),
            # the queue-local p90 pricing histogram, and the registered
            # exposition histogram
            dt = time.time() - t0
            self.health.observe("prove_latency_s", dt)
            self.latency.observe(dt)
            obs_metrics.PROVE_LATENCY.observe(dt)
            # offload the result OUTSIDE the lock (file IO); a write
            # failure (fault site artifact.write) fails the job, never
            # the queue
            digest, offload_err = None, None
            if self.store is not None and self.journal is not None:
                try:
                    digest = self.store.write(_result_blob(result))
                except Exception as exc:
                    offload_err = _error_dict(exc)
            # the provenance manifest is itself an artifact (written
            # before the terminal journal record so that record can carry
            # its digest); its sink is IO-tolerant — see _write_manifest
            man_digest = self._write_manifest(
                job, trace=job_trace, compile_events=compile_events,
                events=run_events, lru_before=lru_before,
                peak_rss_mb=peak, finished=time.time(),
                result_digest=None if offload_err is not None else digest,
                error=offload_err)
            with self._cv:
                if self._slots[slot]["job"] == jid:
                    self._slots[slot]["job"] = None
                if not self._owns_slot(slot):
                    # a stalled-then-returned worker: the supervisor
                    # already failed this job and replaced us — discard
                    # the late result and die without touching the slot
                    return
                if job.cancel_requested:
                    self._finish_locked(job, "cancelled")
                    continue
                if job.status != "running":
                    continue                    # expired meanwhile: discard
                job.peak_rss_mb = peak
                job.manifest_digest = man_digest
                if offload_err is not None:
                    self._finish_locked(job, "failed", error=offload_err)
                    self.health.incr("jobs_failed")
                    continue
                self._finish_locked(job, "done", result=result,
                                    result_digest=digest)
            self.health.incr("jobs_done")

    # -- supervision -------------------------------------------------------

    def _supervise_loop(self, interval: float):
        """Watchdog: a worker whose heartbeat is older than
        `stall_timeout` while it owns a job is presumed hung (wedged
        device call, deadlocked extension, ...). Python threads cannot be
        killed, so the job is marked failed(stalled), the thread is
        DISOWNED and a replacement takes over the slot. Bookkeeping only
        — the supervisor never proves inline (state.semaphore is
        non-reentrant)."""
        while not self._stop_event.wait(interval):
            if self._stopped:
                return
            now = self._clock()
            for i, s in enumerate(self._slots):
                jid = s["job"]
                if jid is None or now - s["beat"] <= self.stall_timeout:
                    continue
                with self._cv:
                    if self._slots[i]["job"] != jid:
                        continue               # finished while we looked
                    job = self._jobs.get(jid)
                    if job is not None and job.status == "running":
                        self._finish_locked(
                            job, "failed",
                            error={"kind": "StalledWorker",
                                   "message":
                                   f"worker heartbeat stalled > "
                                   f"{self.stall_timeout}s; worker "
                                   f"replaced"})
                    self._slots[i]["job"] = None
                    self._spawn_worker(i)      # disowns the hung thread
                self.health.incr("workers_replaced")


def _error_dict(exc: BaseException) -> dict:
    return {"kind": type(exc).__name__, "message": str(exc)}


def _result_blob(result) -> bytes:
    """Canonical bytes of a job result for the artifact store (the journal
    records sha256 over exactly these)."""
    return json.dumps(result, sort_keys=True,
                      separators=(",", ":")).encode()


def _accepts_heartbeat(fn) -> bool:
    """Does this runner take a `heartbeat` callback? Inspected once at
    queue construction; plain runner(method, params) callables keep
    working unchanged."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return any(p.name == "heartbeat" or p.kind == p.VAR_KEYWORD
               for p in sig.parameters.values())


def ensure_jobs(state, journal_dir: str | None = None, runner=None,
                default_timeout: float | None = None, **queue_kw) -> JobQueue:
    """Attach (once) a JobQueue to any prover-state-like object.

    Reuses `state.semaphore`/`state.concurrency` when present so the async
    queue and the blocking/batch paths share one concurrency cap. `runner`
    defaults to the RPC proof dispatcher (heartbeat-aware: the worker's
    stall-detection stamp threads through run_proof_method into
    ProverState.prove_*). Extra `queue_kw` (queue_depth,
    mem_watermark_mb, stall_timeout, ...) pass straight to JobQueue."""
    jobsq = getattr(state, "jobs", None)
    if jobsq is not None:
        return jobsq
    if runner is None:
        from .rpc import run_proof_method
        runner = lambda method, params, heartbeat=None: run_proof_method(
            state, method, params, heartbeat=heartbeat)
    # NOTE: no JobQueue-level semaphore here — the default runner goes
    # through state.prove_* which acquire state.semaphore THEMSELVES
    # (threading.Semaphore is not reentrant; acquiring at both layers
    # deadlocks at concurrency=1). The worker-pool size mirrors the same
    # cap, so queued jobs drain at exactly the governed parallelism.
    jobsq = JobQueue(
        runner,
        concurrency=getattr(state, "concurrency", 1),
        journal_dir=journal_dir if journal_dir is not None
        else getattr(state, "params_dir", None),
        default_timeout=default_timeout, **queue_kw)
    state.jobs = jobsq
    # proof farm (ISSUE 11): a Dispatcher runner gets the queue handed
    # back so SDC quarantine can reach the queue's artifact store
    if hasattr(runner, "attach_queue"):
        runner.attach_queue(jobsq)
    return jobsq

"""Beacon helpers for the utils CLI.

Reference parity: `prover/src/utils.rs:18-66` (`committee-poseidon`
bootstrap: head block root -> light-client bootstrap -> committee pubkeys).
"""

from __future__ import annotations

from ..preprocessor.beacon import BeaconClient
from ..preprocessor.step import _bytes
from ..witness.types import CommitteeUpdateArgs


def fetch_bootstrap_committee(base_url: str, spec):
    client = BeaconClient(base_url)
    root = client.head_block_root()
    boot = client.bootstrap(root)
    committee = boot["current_sync_committee"]
    pubkeys = [_bytes(pk) for pk in committee["pubkeys"]]
    slot = int(boot["header"]["beacon"]["slot"]) if "beacon" in boot.get("header", {}) \
        else int(boot["header"]["slot"])
    period = spec.sync_period(slot)
    args = CommitteeUpdateArgs(pubkeys_compressed=pubkeys)
    return period, args.committee_pubkeys_root(), pubkeys

"""Prover service: CLI, JSON-RPC server/client, preloaded prover state.

Reference parity (SURVEY.md L5): `prover/src/` — clap CLI (`args.rs`,
`cli.rs`), axum JSON-RPC server with `genEvmProof_*` methods (`rpc.rs`,
`rpc_api.rs`), boot-time `ProverState` (`prover.rs:43-117`), typed client
(`rpc_client.rs`), `utils committee-poseidon` (`utils.rs`).

Beyond the reference (PR 3): async job pipeline with a crash-safe journal
(`jobs.py`), health surface (`health` RPC + GET /healthz), and graceful
degradation of every external dependency (see README "Prover service").
"""

"""Pure-Python snappy *raw block* codec.

The official consensus-spec-tests fixtures are `.ssz_snappy` files: SSZ bytes
under snappy raw-block compression (no framing). The reference reads them via
the `snap` crate (`test-utils` `load_snappy_ssz`); this environment has no
snappy binding, so decompression is implemented here from the format spec
(varint preamble + literal/copy tagged elements).

`compress` emits a valid literal-only stream (legal snappy: the format does
not require copy elements), which is all the self-generated fixture needs —
real downloaded fixtures exercise the full decompressor.
"""

from __future__ import annotations


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        assert shift < 64, "uvarint too long"


def _write_uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    """Raw snappy block decompression (literals + copy1/copy2/copy4)."""
    expected, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:                      # literal
            ln = tag >> 2
            if ln >= 60:
                nbytes = ln - 59
                if pos + nbytes > n:
                    raise ValueError("truncated snappy stream (literal length)")
                ln = int.from_bytes(data[pos:pos + nbytes], "little")
                pos += nbytes
            ln += 1
            if pos + ln > n:
                raise ValueError("truncated snappy stream (literal body)")
            out += data[pos:pos + ln]
            pos += ln
        else:
            if kind == 1:                  # copy, 1-byte offset
                if pos + 1 > n:
                    raise ValueError("truncated snappy stream (copy1 offset)")
                ln = ((tag >> 2) & 7) + 4
                off = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:                # copy, 2-byte offset
                if pos + 2 > n:
                    raise ValueError("truncated snappy stream (copy2 offset)")
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:                          # copy, 4-byte offset
                if pos + 4 > n:
                    raise ValueError("truncated snappy stream (copy4 offset)")
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if not 0 < off <= len(out):
                raise ValueError("snappy copy offset out of range")
            start = len(out) - off
            if off >= ln:                  # non-overlapping: slice copy
                out += out[start:start + ln]
            else:
                # overlapping copies are legal (byte-at-a-time semantics)
                for i in range(ln):
                    out.append(out[start + i])
    if len(out) != expected:
        raise ValueError(f"snappy length mismatch: {len(out)} != {expected}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """Literal-only snappy stream (valid for any decompressor)."""
    out = bytearray(_write_uvarint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + 65536]
        ln = len(chunk) - 1
        if ln < 60:
            out.append(ln << 2)
        elif ln < (1 << 8):
            out.append(60 << 2)
            out += ln.to_bytes(1, "little")
        else:                       # chunks are capped at 65536: ln < 2^16
            out.append(61 << 2)
            out += ln.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)

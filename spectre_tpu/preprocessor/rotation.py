"""Committee update -> CommitteeUpdateArgs, with native pre-verification.

Reference parity: `preprocessor/src/rotation.rs:18-106`
(`rotation_args_from_update`), including the committee-branch construction
that proves the pubkeys list root inside the finalized state
(`lib.rs:262-267` — the branch is extended by the aggregate-pubkey sibling so
the PUBKEYS root, not the SyncCommittee container root, is the proven leaf).
"""

from __future__ import annotations

from ..gadgets.ssz_merkle import verify_merkle_proof_native
from ..utils.profiling import phase
from ..witness.types import CommitteeUpdateArgs, bytes48_root
from .step import _b32, _bytes, _hdr


def rotation_args_from_update(update: dict, spec) -> CommitteeUpdateArgs:
    """update keys: finalized_header, next_sync_committee {pubkeys,
    aggregate_pubkey}, next_sync_committee_branch."""
    finalized = _hdr(update["finalized_header"])
    pubkeys = [_bytes(pk) for pk in update["next_sync_committee"]["pubkeys"]]
    assert len(pubkeys) == spec.sync_committee_size
    branch = [_b32(b) for b in update["next_sync_committee_branch"]]

    # the chain's branch proves the SyncCommittee container root at
    # SYNC_COMMITTEE_ROOT_INDEX; extend it with the aggregate-pubkey sibling so
    # the leaf becomes the pubkeys list root at SYNC_COMMITTEE_PUBKEYS_ROOT_INDEX
    # (reference "magic swap", `preprocessor/src/lib.rs:262-267`)
    if len(branch) == spec.sync_committee_depth:
        agg_root = bytes48_root(_bytes(
            update["next_sync_committee"]["aggregate_pubkey"]))
        branch = [agg_root] + branch

    args = CommitteeUpdateArgs(
        pubkeys_compressed=pubkeys,
        finalized_header=finalized,
        sync_committee_branch=branch,
    )
    # spanned (ISSUE 8): hashing 512 pubkeys into the committee root is
    # the dominant cost here and belongs under job/preprocess in traces
    with phase("preprocess/verify_branches"):
        assert verify_merkle_proof_native(
            args.committee_pubkeys_root(), branch,
            spec.sync_committee_pubkeys_root_index, finalized.state_root), \
            "sync committee branch does not verify"
    return args

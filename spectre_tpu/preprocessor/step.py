"""Finality update -> SyncStepArgs, with native pre-verification.

Reference parity: `preprocessor/src/step.rs:21-158`
(`step_args_from_finality_update`): decompress committee pubkeys, rebuild the
execution payload root, natively verify BOTH merkle branches and the
aggregate signature before any proving starts — a witness that cannot satisfy
the circuit is rejected here with a real error message instead of a prover
failure.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from ..gadgets.ssz_merkle import verify_merkle_proof_native
from ..utils.profiling import phase
from ..witness.types import BeaconBlockHeader, SyncStepArgs


def _hdr(d: dict) -> BeaconBlockHeader:
    return BeaconBlockHeader(
        slot=int(d["slot"]),
        proposer_index=int(d["proposer_index"]),
        parent_root=_b32(d["parent_root"]),
        state_root=_b32(d["state_root"]),
        body_root=_b32(d["body_root"]),
    )


def _b32(v) -> bytes:
    if isinstance(v, bytes):
        assert len(v) == 32
        return v
    return bytes.fromhex(v.removeprefix("0x"))


def _bytes(v) -> bytes:
    return v if isinstance(v, bytes) else bytes.fromhex(v.removeprefix("0x"))


def step_args_from_finality_update(update: dict, pubkeys_compressed: list,
                                   domain: bytes, spec) -> SyncStepArgs:
    """update: parsed LightClientFinalityUpdate-shaped dict with keys
    attested_header, finalized_header, finality_branch, sync_aggregate,
    execution_payload_root, execution_branch."""
    attested = _hdr(update["attested_header"])
    finalized = _hdr(update["finalized_header"])
    fin_branch = [_b32(b) for b in update["finality_branch"]]
    exec_root = _b32(update["execution_payload_root"])
    exec_branch = [_b32(b) for b in update["execution_branch"]]

    # native branch verification (reference `step.rs:90-120`); spanned
    # (ISSUE 8) so `job/preprocess` has real children in getTrace
    with phase("preprocess/verify_branches"):
        assert verify_merkle_proof_native(
            finalized.hash_tree_root(), fin_branch,
            spec.finalized_header_index, attested.state_root), \
            "finality branch does not verify"
        assert verify_merkle_proof_native(
            exec_root, exec_branch,
            spec.execution_state_root_index, finalized.body_root), \
            "execution branch does not verify"

    bits = _participation_bits(update["sync_aggregate"]["sync_committee_bits"],
                               spec.sync_committee_size)
    with phase("preprocess/decompress_pubkeys"):
        from ..ops.field384 import g1_decompress_batch
        pubkeys = [(bls.Fq(x), bls.Fq(y)) for x, y in
                   g1_decompress_batch([_bytes(pk)
                                        for pk in pubkeys_compressed])]
    assert len(pubkeys) == spec.sync_committee_size

    args = SyncStepArgs(
        signature_compressed=_bytes(
            update["sync_aggregate"]["sync_committee_signature"]),
        pubkeys_uncompressed=[(int(p[0]), int(p[1])) for p in pubkeys],
        participation_bits=bits,
        attested_header=attested,
        finalized_header=finalized,
        finality_branch=fin_branch,
        execution_payload_root=exec_root,
        execution_payload_branch=exec_branch,
        domain=domain,
    )

    # native signature verification (reject before proving)
    with phase("preprocess/verify_signature"):
        participating = [p for p, b in zip(pubkeys, bits) if b]
        sig = bls.g2_decompress(args.signature_compressed)
        assert bls.fast_aggregate_verify(participating, args.signing_root(),
                                         sig, dst=spec.dst), \
            "aggregate signature does not verify"
    return args


def _participation_bits(bitfield, n: int) -> list[int]:
    if isinstance(bitfield, list):
        return [int(b) for b in bitfield][:n]
    raw = _bytes(bitfield)
    return [(raw[i // 8] >> (i % 8)) & 1 for i in range(n)]

"""Consensus-spec-test loader: official pyspec light_client/sync fixtures
-> circuit witnesses.

Reference parity: `test-utils/src/lib.rs` — `read_test_files_and_gen_witness`
(`:87-131`), `valid_updates_from_test_path` (`:64-85`),
`get_initial_sync_committee_poseidon` (`:32-51`), and the converter
`to_sync_ciruit_witness` (`:133-244`): the step witness takes the signing
committee from `bootstrap.ssz_snappy`, participation + signature from the
update's sync_aggregate, the domain from ForkData(fork_version,
genesis_validators_root), the execution payload root as
hash_tree_root(finalized_header.execution); the rotation witness proves the
update's NEXT committee into the ATTESTED header's state root, with the
aggregate-pubkey root prepended to the branch
(`test-utils/src/lib.rs:104-118`).

Fixture directory layout (ethereum/consensus-specs test format):
    <test_dir>/meta.yaml
    <test_dir>/bootstrap.ssz_snappy
    <test_dir>/steps.yaml
    <test_dir>/updates_<n>.ssz_snappy   (names referenced from steps.yaml)
"""

from __future__ import annotations

import os

from ..fields import bls12_381 as bls
from ..gadgets.ssz_merkle import verify_merkle_proof_native
from ..witness.types import (BeaconBlockHeader, CommitteeUpdateArgs,
                             SyncStepArgs, bytes48_root)
from . import snappy_codec, ssz

# Capella fork versions (consensus-specs config): the reference hardcodes the
# minimal-preset version `[3, 0, 0, 1]` (`test-utils/src/lib.rs:215`).
CAPELLA_FORK_VERSION = {
    "minimal": bytes([3, 0, 0, 1]),
    "mainnet": bytes([3, 0, 0, 0]),
    # The repo-local presets have no official consensus config; these
    # self-assigned versions only need to be internally consistent (the same
    # value signs and verifies the self-generated fixtures — distinct from
    # the official ones so domains can never cross).
    "testnet": bytes([3, 0, 0, 2]),
    "tiny": bytes([3, 0, 0, 3]),
}


def _fork_version(spec) -> bytes:
    assert spec.name in CAPELLA_FORK_VERSION, \
        f"no Capella fork version registered for spec {spec.name!r} — " \
        f"signing-domain computation would be wrong"
    return CAPELLA_FORK_VERSION[spec.name]


def load_snappy_ssz(path: str, ssz_type: ssz.SSZType):
    with open(path, "rb") as f:
        return ssz_type.decode(snappy_codec.decompress(f.read()))


def dump_snappy_ssz(path: str, ssz_type: ssz.SSZType, value) -> None:
    with open(path, "wb") as f:
        f.write(snappy_codec.compress(ssz_type.encode(value)))


def read_meta(test_dir: str) -> dict:
    import yaml
    with open(os.path.join(test_dir, "meta.yaml")) as f:
        return yaml.safe_load(f)


def read_steps(test_dir: str) -> list:
    import yaml
    with open(os.path.join(test_dir, "steps.yaml")) as f:
        return yaml.safe_load(f)


def valid_updates_from_test_path(test_dir: str, spec) -> list:
    """The prefix of process_update steps (cut at the first force_update),
    deserialized (`test-utils/src/lib.rs:64-85`)."""
    update_type = ssz.light_client_update(spec)
    updates = []
    for step in read_steps(test_dir):
        if "process_update" not in step:
            break
        name = step["process_update"]["update"]
        updates.append(load_snappy_ssz(
            os.path.join(test_dir, f"{name}.ssz_snappy"), update_type))
    return updates


def _beacon_header(obj: ssz.Obj) -> BeaconBlockHeader:
    return BeaconBlockHeader(
        slot=obj.slot, proposer_index=obj.proposer_index,
        parent_root=obj.parent_root, state_root=obj.state_root,
        body_root=obj.body_root)


def to_sync_circuit_witness(spec, bootstrap_committee: ssz.Obj, update: ssz.Obj,
                            genesis_validators_root: bytes) -> SyncStepArgs:
    """`to_sync_ciruit_witness` (`test-utils/src/lib.rs:133-244`)."""
    exec_type = ssz.execution_payload_header(
        spec.bytes_per_logs_bloom, spec.max_extra_data_bytes)
    from ..ops.field384 import g1_decompress_batch
    pubkeys = g1_decompress_batch(list(bootstrap_committee.pubkeys))
    domain = ssz.compute_domain(
        ssz.DOMAIN_SYNC_COMMITTEE,
        _fork_version(spec), genesis_validators_root)
    return SyncStepArgs(
        signature_compressed=update.sync_aggregate.sync_committee_signature,
        pubkeys_uncompressed=pubkeys,
        participation_bits=list(update.sync_aggregate.sync_committee_bits),
        attested_header=_beacon_header(update.attested_header.beacon),
        finalized_header=_beacon_header(update.finalized_header.beacon),
        finality_branch=list(update.finality_branch),
        execution_payload_root=exec_type.hash_tree_root(
            update.finalized_header.execution),
        execution_payload_branch=list(update.finalized_header.execution_branch),
        domain=domain)


def read_test_files_and_gen_witness(test_dir: str, spec) \
        -> tuple[SyncStepArgs, CommitteeUpdateArgs]:
    """`read_test_files_and_gen_witness` (`test-utils/src/lib.rs:87-131`)."""
    bootstrap = load_snappy_ssz(
        os.path.join(test_dir, "bootstrap.ssz_snappy"),
        ssz.light_client_bootstrap(spec))
    meta = read_meta(test_dir)
    gvr = bytes.fromhex(meta["genesis_validators_root"].replace("0x", ""))
    updates = valid_updates_from_test_path(test_dir, spec)
    if not updates:
        # official fixtures may open with force_update steps — Spectre can
        # only prove process_update sequences (reference cuts the same way,
        # `test-utils/src/lib.rs:64-66`)
        raise ValueError(f"no leading process_update steps in {test_dir}")
    update = updates[0]

    step_args = to_sync_circuit_witness(
        spec, bootstrap.current_sync_committee, update, gvr)

    # rotation witness: NEXT committee proven into the ATTESTED state root;
    # branch[0] = aggregate-pubkey root (sibling of the pubkeys root inside
    # the SyncCommittee container), per `test-utils/src/lib.rs:104-118`
    branch = [bytes48_root(update.next_sync_committee.aggregate_pubkey)]
    branch += list(update.next_sync_committee_branch)
    rotation_args = CommitteeUpdateArgs(
        pubkeys_compressed=list(update.next_sync_committee.pubkeys),
        finalized_header=step_args.attested_header,
        sync_committee_branch=branch)
    return step_args, rotation_args


def get_initial_sync_committee_poseidon(test_dir: str, spec) -> tuple[int, int]:
    """(sync_period, poseidon_commitment) from the bootstrap — the contract
    constructor params (`test-utils/src/lib.rs:32-51`)."""
    from ..gadgets import poseidon_commit as PC
    bootstrap = load_snappy_ssz(
        os.path.join(test_dir, "bootstrap.ssz_snappy"),
        ssz.light_client_bootstrap(spec))
    from ..ops.field384 import g1_decompress_batch
    pts = [(bls.Fq(x), bls.Fq(y)) for x, y in g1_decompress_batch(
        list(bootstrap.current_sync_committee.pubkeys))]
    commitment = PC.committee_poseidon_from_uncompressed(pts)
    period = bootstrap.header.beacon.slot // spec.slots_per_period
    return period, commitment


def verify_witness_branches(spec, step_args: SyncStepArgs,
                            rotation_args: CommitteeUpdateArgs) -> None:
    """Native pre-verification of every Merkle branch in the generated
    witnesses (the preprocessor does the same before proving,
    `preprocessor/src/step.rs:90-120`, `rotation.rs:105-118`)."""
    assert verify_merkle_proof_native(
        step_args.finalized_header.hash_tree_root(),
        step_args.finality_branch,
        spec.finalized_header_index,
        step_args.attested_header.state_root), "finality branch invalid"
    assert verify_merkle_proof_native(
        step_args.execution_payload_root,
        step_args.execution_payload_branch,
        spec.execution_state_root_index,
        step_args.finalized_header.body_root), "execution branch invalid"
    assert verify_merkle_proof_native(
        rotation_args.committee_pubkeys_root(),
        rotation_args.sync_committee_branch,
        spec.sync_committee_pubkeys_root_index,
        rotation_args.finalized_header.state_root), "committee branch invalid"


# ---------------------------------------------------------------------------
# Self-generated fixture in the official format (reference analog:
# `unit_test_gen.rs` builds test_data fixtures; here the output is the
# *pyspec directory layout* so real downloaded fixtures drop in unchanged)
# ---------------------------------------------------------------------------

def _filler(g: int) -> bytes:
    import hashlib
    return hashlib.sha256(b"spectre-tpu/spec-test-filler/%d" % g).digest()


class GindexTree:
    """Sparse Merkle tree keyed by generalized index: internal nodes may be
    pinned directly (e.g. a committee root at gindex 55), unassigned
    subtrees fall back to deterministic filler nodes."""

    def __init__(self, assigned: dict[int, bytes]):
        self.assigned = dict(assigned)
        for g in self.assigned:
            for h in self.assigned:
                if g != h:
                    a, b = min(g, h), max(g, h)
                    while b > a:
                        b //= 2
                    assert b != a, f"gindex {min(g, h)} is an ancestor of {max(g, h)}"

    def _has_descendant(self, g: int) -> bool:
        return any(self._is_ancestor(g, k) for k in self.assigned)

    @staticmethod
    def _is_ancestor(anc: int, g: int) -> bool:
        while g > anc:
            g //= 2
        return g == anc

    def node(self, g: int) -> bytes:
        from ..gadgets.ssz_merkle import sha256_pair_native
        if g in self.assigned:
            return self.assigned[g]
        if self._has_descendant(g):
            return sha256_pair_native(self.node(2 * g), self.node(2 * g + 1))
        return _filler(g)

    def root(self) -> bytes:
        return self.node(1)

    def branch(self, g: int) -> list[bytes]:
        out = []
        while g > 1:
            out.append(self.node(g ^ 1))
            g //= 2
        return out


def generate_spec_test(test_dir: str, spec, seed: int = 7) -> None:
    """Write a self-consistent light_client/sync fixture in the official
    pyspec file format. The BLS signature is real (own keys), branches are
    honest paths through sparse state trees."""
    import yaml

    n = spec.sync_committee_size
    cur_sks = [seed * 7919 + i + 1 for i in range(n)]
    cur_pks = [bls.g1_compress(bls.sk_to_pk(sk)) for sk in cur_sks]
    nxt_pks = [bls.g1_compress(bls.sk_to_pk(seed * 104729 + i + 1))
               for i in range(n)]

    def committee_obj(pks):
        from ..ops.field384 import g1_decompress_batch
        agg = bls.aggregate_pubkeys(
            [(bls.Fq(x), bls.Fq(y)) for x, y in g1_decompress_batch(list(pks))])
        return ssz.Obj(pubkeys=list(pks), aggregate_pubkey=bls.g1_compress(agg))

    cur_committee = committee_obj(cur_pks)
    nxt_committee = committee_obj(nxt_pks)
    committee_type = ssz.sync_committee(spec)
    cur_root = committee_type.hash_tree_root(cur_committee)
    nxt_root = committee_type.hash_tree_root(nxt_committee)

    exec_type = ssz.execution_payload_header(
        spec.bytes_per_logs_bloom, spec.max_extra_data_bytes)

    def execution_header(tag: int) -> ssz.Obj:
        return ssz.Obj(
            parent_hash=_filler(1000 + tag), fee_recipient=b"\xee" * 20,
            state_root=_filler(1001 + tag), receipts_root=_filler(1002 + tag),
            logs_bloom=b"\x00" * spec.bytes_per_logs_bloom,
            prev_randao=_filler(1003 + tag), block_number=100 + tag,
            gas_limit=30_000_000, gas_used=21_000, timestamp=1_700_000_000 + tag,
            extra_data=b"spectre-tpu", base_fee_per_gas=7,
            block_hash=_filler(1004 + tag), transactions_root=_filler(1005 + tag),
            withdrawals_root=_filler(1006 + tag))

    def light_client_header(slot: int, proposer: int, tag: int,
                            state_root: bytes) -> ssz.Obj:
        execution = execution_header(tag)
        exec_root = exec_type.hash_tree_root(execution)
        # honest body tree: the execution payload sits at
        # EXECUTION_PAYLOAD gindex (depth 4) inside the block body
        gindex_exec = (1 << spec.execution_state_root_depth) | (
            spec.execution_state_root_index
            & ((1 << spec.execution_state_root_depth) - 1))
        body_tree = GindexTree({gindex_exec: exec_root})
        beacon = ssz.Obj(
            slot=slot, proposer_index=proposer,
            parent_root=_filler(2000 + tag), state_root=state_root,
            body_root=body_tree.root())
        return ssz.Obj(beacon=beacon, execution=execution,
                       execution_branch=body_tree.branch(gindex_exec))

    period_start = 2 * spec.slots_per_period
    # finalized header (its own state tree holds both committees, so the
    # bootstrap taken at this header verifies too)
    fin_state = GindexTree({spec.sync_committee_root_index - 1: cur_root,
                            spec.sync_committee_root_index: nxt_root})
    finalized = light_client_header(period_start + 8, 3, 0, fin_state.root())
    fin_beacon_root = ssz.BEACON_BLOCK_HEADER.hash_tree_root(finalized.beacon)

    # attested header: state holds finalized root @105, committees @54/55
    att_state = GindexTree({
        spec.finalized_header_index: fin_beacon_root,
        spec.sync_committee_root_index - 1: cur_root,
        spec.sync_committee_root_index: nxt_root,
    })
    attested = light_client_header(period_start + 16, 11, 1, att_state.root())
    att_beacon_root = ssz.BEACON_BLOCK_HEADER.hash_tree_root(attested.beacon)

    gvr = _filler(3)
    domain = ssz.compute_domain(
        ssz.DOMAIN_SYNC_COMMITTEE, _fork_version(spec), gvr)
    from ..gadgets.ssz_merkle import sha256_pair_native
    signing_root = sha256_pair_native(att_beacon_root, domain)
    msg_point = bls.hash_to_g2(signing_root, spec.dst)
    bits = [1] * n
    sig = bls.aggregate_signatures(
        [bls.g2_curve.mul(msg_point, sk) for sk, b in zip(cur_sks, bits) if b])

    update = ssz.Obj(
        attested_header=attested,
        next_sync_committee=nxt_committee,
        next_sync_committee_branch=att_state.branch(
            spec.sync_committee_root_index),
        finalized_header=finalized,
        finality_branch=att_state.branch(spec.finalized_header_index),
        sync_aggregate=ssz.Obj(sync_committee_bits=bits,
                               sync_committee_signature=bls.g2_compress(sig)),
        signature_slot=attested.beacon.slot + 1)

    bootstrap = ssz.Obj(
        header=finalized,
        current_sync_committee=cur_committee,
        current_sync_committee_branch=fin_state.branch(
            spec.sync_committee_root_index - 1))

    os.makedirs(test_dir, exist_ok=True)
    dump_snappy_ssz(os.path.join(test_dir, "bootstrap.ssz_snappy"),
                    ssz.light_client_bootstrap(spec), bootstrap)
    dump_snappy_ssz(os.path.join(test_dir, "updates_0.ssz_snappy"),
                    ssz.light_client_update(spec), update)

    exec_root_hex = "0x" + exec_type.hash_tree_root(finalized.execution).hex()
    steps = [{"process_update": {
        "update_fork_digest": "0x" + _filler(4)[:4].hex(),
        "update": "updates_0",
        "current_slot": int(attested.beacon.slot + 2),
        "checks": {
            "optimistic_header": {
                "slot": int(attested.beacon.slot),
                "beacon_root": "0x" + att_beacon_root.hex(),
                "execution_root": "0x" + exec_type.hash_tree_root(
                    attested.execution).hex(),
            },
            "finalized_header": {
                "slot": int(finalized.beacon.slot),
                "beacon_root": "0x" + fin_beacon_root.hex(),
                "execution_root": exec_root_hex,
            },
        },
    }}]
    with open(os.path.join(test_dir, "steps.yaml"), "w") as f:
        yaml.safe_dump(steps, f, sort_keys=False)
    meta = {
        "genesis_validators_root": "0x" + gvr.hex(),
        "trusted_block_root": "0x" + fin_beacon_root.hex(),
        "bootstrap_fork_digest": "0x" + _filler(4)[:4].hex(),
        "store_fork_digest": "0x" + _filler(4)[:4].hex(),
    }
    with open(os.path.join(test_dir, "meta.yaml"), "w") as f:
        yaml.safe_dump(meta, f, sort_keys=False)

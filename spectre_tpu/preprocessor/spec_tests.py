"""Consensus-spec-test loader: official pyspec light_client/sync fixtures
-> circuit witnesses.

Reference parity: `test-utils/src/lib.rs` — `read_test_files_and_gen_witness`
(`:87-131`), `valid_updates_from_test_path` (`:64-85`),
`get_initial_sync_committee_poseidon` (`:32-51`), and the converter
`to_sync_ciruit_witness` (`:133-244`): the step witness takes the signing
committee from `bootstrap.ssz_snappy`, participation + signature from the
update's sync_aggregate, the domain from ForkData(fork_version,
genesis_validators_root), the execution payload root as
hash_tree_root(finalized_header.execution); the rotation witness proves the
update's NEXT committee into the ATTESTED header's state root, with the
aggregate-pubkey root prepended to the branch
(`test-utils/src/lib.rs:104-118`).

Fixture directory layout (ethereum/consensus-specs test format):
    <test_dir>/meta.yaml
    <test_dir>/bootstrap.ssz_snappy
    <test_dir>/steps.yaml
    <test_dir>/updates_<n>.ssz_snappy   (names referenced from steps.yaml)
"""

from __future__ import annotations

import os

from ..fields import bls12_381 as bls
from ..gadgets.ssz_merkle import verify_merkle_proof_native
from ..witness.types import (BeaconBlockHeader, CommitteeUpdateArgs,
                             SyncStepArgs, bytes48_root)
from . import snappy_codec, ssz

# Capella fork versions (consensus-specs config): the reference hardcodes the
# minimal-preset version `[3, 0, 0, 1]` (`test-utils/src/lib.rs:215`).
CAPELLA_FORK_VERSION = {
    "minimal": bytes([3, 0, 0, 1]),
    "mainnet": bytes([3, 0, 0, 0]),
    # The repo-local presets have no official consensus config; these
    # self-assigned versions only need to be internally consistent (the same
    # value signs and verifies the self-generated fixtures — distinct from
    # the official ones so domains can never cross).
    "testnet": bytes([3, 0, 0, 2]),
    "tiny": bytes([3, 0, 0, 3]),
}


def _fork_version(spec) -> bytes:
    assert spec.name in CAPELLA_FORK_VERSION, \
        f"no Capella fork version registered for spec {spec.name!r} — " \
        f"signing-domain computation would be wrong"
    return CAPELLA_FORK_VERSION[spec.name]


def load_snappy_ssz(path: str, ssz_type: ssz.SSZType):
    with open(path, "rb") as f:
        return ssz_type.decode(snappy_codec.decompress(f.read()))


def dump_snappy_ssz(path: str, ssz_type: ssz.SSZType, value) -> None:
    with open(path, "wb") as f:
        f.write(snappy_codec.compress(ssz_type.encode(value)))


def read_meta(test_dir: str) -> dict:
    import yaml
    with open(os.path.join(test_dir, "meta.yaml")) as f:
        return yaml.safe_load(f)


def read_steps(test_dir: str) -> list:
    import yaml
    with open(os.path.join(test_dir, "steps.yaml")) as f:
        return yaml.safe_load(f)


def valid_updates_from_test_path(test_dir: str, spec) -> list:
    """The prefix of process_update steps (cut at the first force_update),
    deserialized (`test-utils/src/lib.rs:64-85`)."""
    update_type = ssz.light_client_update(spec)
    updates = []
    for step in read_steps(test_dir):
        if "process_update" not in step:
            break
        name = step["process_update"]["update"]
        updates.append(load_snappy_ssz(
            os.path.join(test_dir, f"{name}.ssz_snappy"), update_type))
    return updates


def _beacon_header(obj: ssz.Obj) -> BeaconBlockHeader:
    return BeaconBlockHeader(
        slot=obj.slot, proposer_index=obj.proposer_index,
        parent_root=obj.parent_root, state_root=obj.state_root,
        body_root=obj.body_root)


def to_sync_circuit_witness(spec, bootstrap_committee: ssz.Obj, update: ssz.Obj,
                            genesis_validators_root: bytes) -> SyncStepArgs:
    """`to_sync_ciruit_witness` (`test-utils/src/lib.rs:133-244`)."""
    exec_type = ssz.execution_payload_header(
        spec.bytes_per_logs_bloom, spec.max_extra_data_bytes)
    from ..ops.field384 import g1_decompress_batch
    pubkeys = g1_decompress_batch(list(bootstrap_committee.pubkeys))
    domain = ssz.compute_domain(
        ssz.DOMAIN_SYNC_COMMITTEE,
        _fork_version(spec), genesis_validators_root)
    return SyncStepArgs(
        signature_compressed=update.sync_aggregate.sync_committee_signature,
        pubkeys_uncompressed=pubkeys,
        participation_bits=list(update.sync_aggregate.sync_committee_bits),
        attested_header=_beacon_header(update.attested_header.beacon),
        finalized_header=_beacon_header(update.finalized_header.beacon),
        finality_branch=list(update.finality_branch),
        execution_payload_root=exec_type.hash_tree_root(
            update.finalized_header.execution),
        execution_payload_branch=list(update.finalized_header.execution_branch),
        domain=domain)


def read_test_files_and_gen_witness(test_dir: str, spec) \
        -> tuple[SyncStepArgs, CommitteeUpdateArgs]:
    """`read_test_files_and_gen_witness` (`test-utils/src/lib.rs:87-131`)."""
    bootstrap = load_snappy_ssz(
        os.path.join(test_dir, "bootstrap.ssz_snappy"),
        ssz.light_client_bootstrap(spec))
    meta = read_meta(test_dir)
    gvr = bytes.fromhex(meta["genesis_validators_root"].replace("0x", ""))
    updates = valid_updates_from_test_path(test_dir, spec)
    if not updates:
        # official fixtures may open with force_update steps — Spectre can
        # only prove process_update sequences (reference cuts the same way,
        # `test-utils/src/lib.rs:64-66`)
        raise ValueError(f"no leading process_update steps in {test_dir}")
    update = updates[0]

    step_args = to_sync_circuit_witness(
        spec, bootstrap.current_sync_committee, update, gvr)

    # rotation witness: NEXT committee proven into the ATTESTED state root;
    # branch[0] = aggregate-pubkey root (sibling of the pubkeys root inside
    # the SyncCommittee container), per `test-utils/src/lib.rs:104-118`
    branch = [bytes48_root(update.next_sync_committee.aggregate_pubkey)]
    branch += list(update.next_sync_committee_branch)
    rotation_args = CommitteeUpdateArgs(
        pubkeys_compressed=list(update.next_sync_committee.pubkeys),
        finalized_header=step_args.attested_header,
        sync_committee_branch=branch)
    return step_args, rotation_args


def get_initial_sync_committee_poseidon(test_dir: str, spec) -> tuple[int, int]:
    """(sync_period, poseidon_commitment) from the bootstrap — the contract
    constructor params (`test-utils/src/lib.rs:32-51`)."""
    from ..gadgets import poseidon_commit as PC
    bootstrap = load_snappy_ssz(
        os.path.join(test_dir, "bootstrap.ssz_snappy"),
        ssz.light_client_bootstrap(spec))
    from ..ops.field384 import g1_decompress_batch
    pts = [(bls.Fq(x), bls.Fq(y)) for x, y in g1_decompress_batch(
        list(bootstrap.current_sync_committee.pubkeys))]
    commitment = PC.committee_poseidon_from_uncompressed(pts)
    period = bootstrap.header.beacon.slot // spec.slots_per_period
    return period, commitment


def verify_witness_branches(spec, step_args: SyncStepArgs,
                            rotation_args: CommitteeUpdateArgs) -> None:
    """Native pre-verification of every Merkle branch in the generated
    witnesses (the preprocessor does the same before proving,
    `preprocessor/src/step.rs:90-120`, `rotation.rs:105-118`)."""
    assert verify_merkle_proof_native(
        step_args.finalized_header.hash_tree_root(),
        step_args.finality_branch,
        spec.finalized_header_index,
        step_args.attested_header.state_root), "finality branch invalid"
    assert verify_merkle_proof_native(
        step_args.execution_payload_root,
        step_args.execution_payload_branch,
        spec.execution_state_root_index,
        step_args.finalized_header.body_root), "execution branch invalid"
    assert verify_merkle_proof_native(
        rotation_args.committee_pubkeys_root(),
        rotation_args.sync_committee_branch,
        spec.sync_committee_pubkeys_root_index,
        rotation_args.finalized_header.state_root), "committee branch invalid"


# ---------------------------------------------------------------------------
# Self-generated fixture in the official format (reference analog:
# `unit_test_gen.rs` builds test_data fixtures; here the output is the
# *pyspec directory layout* so real downloaded fixtures drop in unchanged)
# ---------------------------------------------------------------------------

def _filler(g: int) -> bytes:
    import hashlib
    return hashlib.sha256(b"spectre-tpu/spec-test-filler/%d" % g).digest()


class GindexTree:
    """Sparse Merkle tree keyed by generalized index: internal nodes may be
    pinned directly (e.g. a committee root at gindex 55), unassigned
    subtrees fall back to deterministic filler nodes."""

    def __init__(self, assigned: dict[int, bytes]):
        self.assigned = dict(assigned)
        for g in self.assigned:
            for h in self.assigned:
                if g != h:
                    a, b = min(g, h), max(g, h)
                    while b > a:
                        b //= 2
                    assert b != a, f"gindex {min(g, h)} is an ancestor of {max(g, h)}"

    def _has_descendant(self, g: int) -> bool:
        return any(self._is_ancestor(g, k) for k in self.assigned)

    @staticmethod
    def _is_ancestor(anc: int, g: int) -> bool:
        while g > anc:
            g //= 2
        return g == anc

    def node(self, g: int) -> bytes:
        from ..gadgets.ssz_merkle import sha256_pair_native
        if g in self.assigned:
            return self.assigned[g]
        if self._has_descendant(g):
            return sha256_pair_native(self.node(2 * g), self.node(2 * g + 1))
        return _filler(g)

    def root(self) -> bytes:
        return self.node(1)

    def branch(self, g: int) -> list[bytes]:
        out = []
        while g > 1:
            out.append(self.node(g ^ 1))
            g //= 2
        return out


#: scenario -> description; mirrors the official suite's case shapes
#: (`consensus-specs` light_client/sync tests + `test-utils/src/lib.rs:64-85`
#: cut semantics)
SPEC_TEST_SCENARIOS = {
    "sync": "single happy-path process_update (finality + committee branches)",
    "multi_update": "two sequential process_update steps (updates_0/1); the "
                    "loader must return BOTH in order",
    "force_update_cut": "process_update followed by a force_update step; "
                        "valid_updates_from_test_path must CUT after the "
                        "first update",
    "no_finality": "process_update whose update carries NO finalized header "
                   "(zeroed header + zero branch, the official "
                   "no-finality shape); witness pre-verification must reject",
    "force_update_only": "fixture OPENING with force_update (skipped-period "
                         "shape): no provable prefix, the loader must raise",
}


def generate_spec_test(test_dir: str, spec, seed: int = 7,
                       scenario: str = "sync") -> None:
    """Write a self-consistent light_client/sync fixture in the official
    pyspec file format. The BLS signature is real (own keys), branches are
    honest paths through sparse state trees. `scenario` selects one of the
    official case shapes (SPEC_TEST_SCENARIOS)."""
    import yaml
    assert scenario in SPEC_TEST_SCENARIOS, scenario

    n = spec.sync_committee_size
    cur_sks = [seed * 7919 + i + 1 for i in range(n)]
    cur_pks = [bls.g1_compress(bls.sk_to_pk(sk)) for sk in cur_sks]
    nxt_pks = [bls.g1_compress(bls.sk_to_pk(seed * 104729 + i + 1))
               for i in range(n)]

    def committee_obj(pks):
        from ..ops.field384 import g1_decompress_batch
        agg = bls.aggregate_pubkeys(
            [(bls.Fq(x), bls.Fq(y)) for x, y in g1_decompress_batch(list(pks))])
        return ssz.Obj(pubkeys=list(pks), aggregate_pubkey=bls.g1_compress(agg))

    cur_committee = committee_obj(cur_pks)
    nxt_committee = committee_obj(nxt_pks)
    committee_type = ssz.sync_committee(spec)
    cur_root = committee_type.hash_tree_root(cur_committee)
    nxt_root = committee_type.hash_tree_root(nxt_committee)

    exec_type = ssz.execution_payload_header(
        spec.bytes_per_logs_bloom, spec.max_extra_data_bytes)

    def execution_header(tag: int) -> ssz.Obj:
        return ssz.Obj(
            parent_hash=_filler(1000 + tag), fee_recipient=b"\xee" * 20,
            state_root=_filler(1001 + tag), receipts_root=_filler(1002 + tag),
            logs_bloom=b"\x00" * spec.bytes_per_logs_bloom,
            prev_randao=_filler(1003 + tag), block_number=100 + tag,
            gas_limit=30_000_000, gas_used=21_000, timestamp=1_700_000_000 + tag,
            extra_data=b"spectre-tpu", base_fee_per_gas=7,
            block_hash=_filler(1004 + tag), transactions_root=_filler(1005 + tag),
            withdrawals_root=_filler(1006 + tag))

    def light_client_header(slot: int, proposer: int, tag: int,
                            state_root: bytes) -> ssz.Obj:
        execution = execution_header(tag)
        exec_root = exec_type.hash_tree_root(execution)
        # honest body tree: the execution payload sits at
        # EXECUTION_PAYLOAD gindex (depth 4) inside the block body
        gindex_exec = (1 << spec.execution_state_root_depth) | (
            spec.execution_state_root_index
            & ((1 << spec.execution_state_root_depth) - 1))
        body_tree = GindexTree({gindex_exec: exec_root})
        beacon = ssz.Obj(
            slot=slot, proposer_index=proposer,
            parent_root=_filler(2000 + tag), state_root=state_root,
            body_root=body_tree.root())
        return ssz.Obj(beacon=beacon, execution=execution,
                       execution_branch=body_tree.branch(gindex_exec))

    period_start = 2 * spec.slots_per_period
    gvr = _filler(3)
    domain = ssz.compute_domain(
        ssz.DOMAIN_SYNC_COMMITTEE, _fork_version(spec), gvr)
    from ..gadgets.ssz_merkle import sha256_pair_native

    def zeroed_light_client_header() -> ssz.Obj:
        """The official no-finality shape: an all-zero LightClientHeader."""
        execution = ssz.Obj(
            parent_hash=b"\x00" * 32, fee_recipient=b"\x00" * 20,
            state_root=b"\x00" * 32, receipts_root=b"\x00" * 32,
            logs_bloom=b"\x00" * spec.bytes_per_logs_bloom,
            prev_randao=b"\x00" * 32, block_number=0, gas_limit=0,
            gas_used=0, timestamp=0, extra_data=b"", base_fee_per_gas=0,
            block_hash=b"\x00" * 32, transactions_root=b"\x00" * 32,
            withdrawals_root=b"\x00" * 32)
        beacon = ssz.Obj(slot=0, proposer_index=0, parent_root=b"\x00" * 32,
                         state_root=b"\x00" * 32, body_root=b"\x00" * 32)
        return ssz.Obj(beacon=beacon, execution=execution,
                       execution_branch=[b"\x00" * 32]
                       * spec.execution_state_root_depth)

    def make_update(slot_off: int, tag_base: int, with_finality: bool = True):
        """One signed LightClientUpdate at period_start+slot_off, with its
        own finalized/attested headers over shared committees. Returns
        (update, artifacts-dict for steps.yaml/bootstrap)."""
        fin_state = GindexTree({spec.sync_committee_root_index - 1: cur_root,
                                spec.sync_committee_root_index: nxt_root})
        finalized = light_client_header(period_start + slot_off - 8, 3,
                                        tag_base, fin_state.root())
        fin_beacon_root = ssz.BEACON_BLOCK_HEADER.hash_tree_root(
            finalized.beacon)
        att_assigned = {
            spec.sync_committee_root_index - 1: cur_root,
            spec.sync_committee_root_index: nxt_root,
        }
        if with_finality:
            att_assigned[spec.finalized_header_index] = fin_beacon_root
        att_state = GindexTree(att_assigned)
        attested = light_client_header(period_start + slot_off, 11,
                                       tag_base + 1, att_state.root())
        att_beacon_root = ssz.BEACON_BLOCK_HEADER.hash_tree_root(
            attested.beacon)
        signing_root = sha256_pair_native(att_beacon_root, domain)
        msg_point = bls.hash_to_g2(signing_root, spec.dst)
        bits = [1] * n
        sig = bls.aggregate_signatures(
            [bls.g2_curve.mul(msg_point, sk)
             for sk, b in zip(cur_sks, bits) if b])
        fin_branch = (att_state.branch(spec.finalized_header_index)
                      if with_finality else
                      [b"\x00" * 32] * spec.finalized_header_depth)
        update = ssz.Obj(
            attested_header=attested,
            next_sync_committee=nxt_committee,
            next_sync_committee_branch=att_state.branch(
                spec.sync_committee_root_index),
            finalized_header=(finalized if with_finality
                              else zeroed_light_client_header()),
            finality_branch=fin_branch,
            sync_aggregate=ssz.Obj(sync_committee_bits=bits,
                                   sync_committee_signature=bls.g2_compress(sig)),
            signature_slot=attested.beacon.slot + 1)
        return update, {
            "finalized": finalized, "fin_state": fin_state,
            "fin_beacon_root": fin_beacon_root,
            "attested": attested, "att_beacon_root": att_beacon_root,
        }

    def process_update_step(idx: int, update: ssz.Obj, art: dict) -> dict:
        fin = update.finalized_header
        return {"process_update": {
            "update_fork_digest": "0x" + _filler(4)[:4].hex(),
            "update": f"updates_{idx}",
            "current_slot": int(art["attested"].beacon.slot + 2),
            "checks": {
                "optimistic_header": {
                    "slot": int(art["attested"].beacon.slot),
                    "beacon_root": "0x" + art["att_beacon_root"].hex(),
                    "execution_root": "0x" + exec_type.hash_tree_root(
                        art["attested"].execution).hex(),
                },
                "finalized_header": {
                    "slot": int(fin.beacon.slot),
                    "beacon_root": "0x" + ssz.BEACON_BLOCK_HEADER
                    .hash_tree_root(fin.beacon).hex(),
                    "execution_root": "0x" + exec_type.hash_tree_root(
                        fin.execution).hex(),
                },
            },
        }}

    def force_update_step(current_slot: int) -> dict:
        # official shape: advance past the update timeout with no
        # process_update (`TestStep::ForceUpdate`, ref test_types)
        return {"force_update": {
            "current_slot": int(current_slot),
            "checks": {},
        }}

    # -- assemble per scenario --
    updates: list = []       # (update, artifacts), files updates_<i>
    steps: list = []
    if scenario == "sync":
        u, a = make_update(16, 0)
        updates, steps = [(u, a)], [process_update_step(0, u, a)]
    elif scenario == "multi_update":
        u0, a0 = make_update(16, 0)
        u1, a1 = make_update(32, 10)
        updates = [(u0, a0), (u1, a1)]
        steps = [process_update_step(0, u0, a0),
                 process_update_step(1, u1, a1)]
    elif scenario == "force_update_cut":
        u, a = make_update(16, 0)
        updates = [(u, a)]
        steps = [process_update_step(0, u, a),
                 force_update_step(a["attested"].beacon.slot
                                   + spec.slots_per_period)]
    elif scenario == "no_finality":
        u, a = make_update(16, 0, with_finality=False)
        updates, steps = [(u, a)], [process_update_step(0, u, a)]
    elif scenario == "force_update_only":
        # a provable update file may exist on disk, but the step sequence
        # OPENS with force_update — nothing for Spectre to prove
        u, a = make_update(16, 0)
        updates = [(u, a)]
        steps = [force_update_step(a["attested"].beacon.slot + 2),
                 process_update_step(0, u, a)]

    # bootstrap anchored at the first update's finalized header (its state
    # tree holds both committees, so the bootstrap branch verifies)
    _, a0 = updates[0]
    bootstrap = ssz.Obj(
        header=a0["finalized"],
        current_sync_committee=cur_committee,
        current_sync_committee_branch=a0["fin_state"].branch(
            spec.sync_committee_root_index - 1))

    os.makedirs(test_dir, exist_ok=True)
    dump_snappy_ssz(os.path.join(test_dir, "bootstrap.ssz_snappy"),
                    ssz.light_client_bootstrap(spec), bootstrap)
    for i, (u, _) in enumerate(updates):
        dump_snappy_ssz(os.path.join(test_dir, f"updates_{i}.ssz_snappy"),
                        ssz.light_client_update(spec), u)
    with open(os.path.join(test_dir, "steps.yaml"), "w") as f:
        yaml.safe_dump(steps, f, sort_keys=False)
    meta = {
        "genesis_validators_root": "0x" + gvr.hex(),
        "trusted_block_root": "0x" + a0["fin_beacon_root"].hex(),
        "bootstrap_fork_digest": "0x" + _filler(4)[:4].hex(),
        "store_fork_digest": "0x" + _filler(4)[:4].hex(),
    }
    with open(os.path.join(test_dir, "meta.yaml"), "w") as f:
        yaml.safe_dump(meta, f, sort_keys=False)


def update_has_finality(step_args: SyncStepArgs) -> bool:
    """False for the official no-finality update shape (zeroed finalized
    header + zero branch): Spectre proves only finalized updates, so
    witness pre-verification is expected to REJECT such witnesses."""
    fh = step_args.finalized_header
    return not (fh.slot == 0 and fh.state_root == b"\x00" * 32
                and all(b == b"\x00" * 32 for b in step_args.finality_branch))

"""Minimal SSZ codec for the Capella light-client container types.

Reference parity: the reference consumes these containers through the
`ssz_rs` crate + `ethereum-consensus-types` fork (SURVEY.md L0) and its
spec-test loader deserializes `bootstrap.ssz_snappy` / `updates_*.ssz_snappy`
(`test-utils/src/lib.rs:87-131`, `test-utils/src/execution_payload_header.rs`).
This module implements just enough of the SSZ spec — basic uints, byte
vectors/lists, bitvectors, vectors of composites, containers with
variable-size members (4-byte offsets) — to encode/decode/hash_tree_root
those exact containers, so the official `consensus-spec-tests` fixture files
load unchanged.

Values are plain Python: ints, bytes, lists, and `Obj` (attribute bag) for
containers.
"""

from __future__ import annotations

from ..gadgets.ssz_merkle import merkleize_chunks_native, sha256_pair_native

BYTES_PER_CHUNK = 32
OFFSET_SIZE = 4


class Obj:
    """Container value: attribute bag with dict-style construction."""

    def __init__(self, **kw):
        self.__dict__.update(kw)

    def __repr__(self):
        return f"Obj({', '.join(f'{k}={v!r}' for k, v in self.__dict__.items())})"

    def __eq__(self, other):
        return isinstance(other, Obj) and self.__dict__ == other.__dict__


def _pack_bytes(data: bytes) -> list[bytes]:
    """Pack serialized basic values into 32-byte chunks (zero-padded)."""
    if not data:
        return [b"\x00" * BYTES_PER_CHUNK]
    chunks = [data[i:i + BYTES_PER_CHUNK] for i in range(0, len(data), BYTES_PER_CHUNK)]
    chunks[-1] = chunks[-1].ljust(BYTES_PER_CHUNK, b"\x00")
    return chunks


def _mix_in_length(root: bytes, length: int) -> bytes:
    return sha256_pair_native(root, length.to_bytes(32, "little"))


class SSZType:
    is_fixed = True

    def size(self) -> int:            # fixed size in bytes
        raise NotImplementedError

    def encode(self, v) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, v) -> bytes:
        raise NotImplementedError


class UintN(SSZType):
    def __init__(self, nbytes: int):
        self.nbytes = nbytes

    def size(self):
        return self.nbytes

    def encode(self, v) -> bytes:
        return int(v).to_bytes(self.nbytes, "little")

    def decode(self, data: bytes):
        assert len(data) == self.nbytes, f"uint{self.nbytes * 8} size mismatch"
        return int.from_bytes(data, "little")

    def hash_tree_root(self, v) -> bytes:
        return int(v).to_bytes(self.nbytes, "little").ljust(BYTES_PER_CHUNK, b"\x00")


uint64 = UintN(8)
uint256 = UintN(32)


class ByteVector(SSZType):
    def __init__(self, n: int):
        self.n = n

    def size(self):
        return self.n

    def encode(self, v) -> bytes:
        assert len(v) == self.n, f"ByteVector[{self.n}] got {len(v)}"
        return bytes(v)

    def decode(self, data: bytes):
        assert len(data) == self.n, f"ByteVector[{self.n}] size mismatch"
        return bytes(data)

    def hash_tree_root(self, v) -> bytes:
        return merkleize_chunks_native(_pack_bytes(bytes(v)))


class ByteList(SSZType):
    is_fixed = False

    def __init__(self, limit: int):
        self.limit = limit

    def encode(self, v) -> bytes:
        assert len(v) <= self.limit
        return bytes(v)

    def decode(self, data: bytes):
        assert len(data) <= self.limit, "ByteList over limit"
        return bytes(data)

    def hash_tree_root(self, v) -> bytes:
        limit_chunks = (self.limit + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
        root = merkleize_chunks_native(_pack_bytes(bytes(v)), limit=limit_chunks)
        return _mix_in_length(root, len(v))


class Bitvector(SSZType):
    """Value is a list of 0/1 ints, length n."""

    def __init__(self, n: int):
        self.n = n

    def size(self):
        return (self.n + 7) // 8

    def encode(self, v) -> bytes:
        assert len(v) == self.n
        out = bytearray(self.size())
        for i, b in enumerate(v):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def decode(self, data: bytes):
        assert len(data) == self.size(), "Bitvector size mismatch"
        bits = [(data[i // 8] >> (i % 8)) & 1 for i in range(self.n)]
        # excess bits in the final byte must be zero
        for j in range(self.n, len(data) * 8):
            assert (data[j // 8] >> (j % 8)) & 1 == 0, "Bitvector padding bits set"
        return bits

    def hash_tree_root(self, v) -> bytes:
        limit_chunks = (self.size() + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
        return merkleize_chunks_native(_pack_bytes(self.encode(v)), limit=limit_chunks)


class Vector(SSZType):
    """Vector of composite (or basic non-byte) elements."""

    def __init__(self, elem: SSZType, n: int):
        assert elem.is_fixed, "variable-size vector elements not needed here"
        self.elem = elem
        self.n = n

    def size(self):
        return self.elem.size() * self.n

    def encode(self, v) -> bytes:
        assert len(v) == self.n, f"Vector[{self.n}] got {len(v)}"
        return b"".join(self.elem.encode(x) for x in v)

    def decode(self, data: bytes):
        es = self.elem.size()
        assert len(data) == es * self.n, "Vector size mismatch"
        return [self.elem.decode(data[i * es:(i + 1) * es]) for i in range(self.n)]

    def hash_tree_root(self, v) -> bytes:
        return merkleize_chunks_native(
            [self.elem.hash_tree_root(x) for x in v], limit=self.n)


class Container(SSZType):
    def __init__(self, name: str, fields: list[tuple[str, SSZType]]):
        self.name = name
        self.fields = fields
        self.is_fixed = all(t.is_fixed for _, t in fields)

    def size(self):
        assert self.is_fixed
        return sum(t.size() for _, t in self.fields)

    def encode(self, v) -> bytes:
        fixed_parts = []
        var_parts = []
        fixed_len = sum(t.size() if t.is_fixed else OFFSET_SIZE
                        for _, t in self.fields)
        offset = fixed_len
        for fname, ftype in self.fields:
            val = getattr(v, fname)
            if ftype.is_fixed:
                fixed_parts.append(ftype.encode(val))
            else:
                enc = ftype.encode(val)
                fixed_parts.append(offset.to_bytes(OFFSET_SIZE, "little"))
                var_parts.append(enc)
                offset += len(enc)
        return b"".join(fixed_parts) + b"".join(var_parts)

    def decode(self, data: bytes):
        if self.is_fixed:
            assert len(data) == self.size(), \
                f"{self.name}: size mismatch {len(data)} != {self.size()}"
        # pass 1: fixed fields + collect offsets
        pos = 0
        raw: list = []
        offsets: list[int] = []
        for fname, ftype in self.fields:
            if ftype.is_fixed:
                sz = ftype.size()
                raw.append(("fixed", fname, ftype, data[pos:pos + sz]))
                pos += sz
            else:
                off = int.from_bytes(data[pos:pos + OFFSET_SIZE], "little")
                raw.append(("var", fname, ftype, off))
                offsets.append(off)
                pos += OFFSET_SIZE
        assert not offsets or offsets[0] == pos, \
            f"{self.name}: first offset {offsets} != fixed length {pos}"
        offsets.append(len(data))
        out = Obj()
        vi = 0
        for kind, fname, ftype, payload in raw:
            if kind == "fixed":
                setattr(out, fname, ftype.decode(payload))
            else:
                start, end = offsets[vi], offsets[vi + 1]
                assert start <= end <= len(data), f"{self.name}: bad offsets"
                setattr(out, fname, ftype.decode(data[start:end]))
                vi += 1
        return out

    def hash_tree_root(self, v) -> bytes:
        return merkleize_chunks_native(
            [ftype.hash_tree_root(getattr(v, fname))
             for fname, ftype in self.fields])


# ---------------------------------------------------------------------------
# Capella light-client containers (ethereum/consensus-specs, capella preset;
# reference types: `ethereum-consensus-types` + `execution_payload_header.rs:13-33`)
# ---------------------------------------------------------------------------

Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)

BEACON_BLOCK_HEADER = Container("BeaconBlockHeader", [
    ("slot", uint64),
    ("proposer_index", uint64),
    ("parent_root", Bytes32),
    ("state_root", Bytes32),
    ("body_root", Bytes32),
])


def execution_payload_header(bytes_per_logs_bloom=256, max_extra_data_bytes=32):
    return Container("ExecutionPayloadHeader", [
        ("parent_hash", Bytes32),
        ("fee_recipient", Bytes20),
        ("state_root", Bytes32),
        ("receipts_root", Bytes32),
        ("logs_bloom", ByteVector(bytes_per_logs_bloom)),
        ("prev_randao", Bytes32),
        ("block_number", uint64),
        ("gas_limit", uint64),
        ("gas_used", uint64),
        ("timestamp", uint64),
        ("extra_data", ByteList(max_extra_data_bytes)),
        ("base_fee_per_gas", uint256),
        ("block_hash", Bytes32),
        ("transactions_root", Bytes32),
        ("withdrawals_root", Bytes32),
    ])


EXECUTION_BRANCH_DEPTH = 4       # floorlog2(EXECUTION_PAYLOAD_INDEX=25)
FINALITY_BRANCH_DEPTH = 6        # floorlog2(FINALIZED_ROOT_INDEX=105)
SYNC_COMMITTEE_BRANCH_DEPTH = 5  # floorlog2(NEXT_SYNC_COMMITTEE_INDEX=55)


def light_client_header(spec):
    return Container("LightClientHeader", [
        ("beacon", BEACON_BLOCK_HEADER),
        ("execution", execution_payload_header(
            spec.bytes_per_logs_bloom, spec.max_extra_data_bytes)),
        ("execution_branch", Vector(Bytes32, EXECUTION_BRANCH_DEPTH)),
    ])


def sync_committee(spec):
    return Container("SyncCommittee", [
        ("pubkeys", Vector(Bytes48, spec.sync_committee_size)),
        ("aggregate_pubkey", Bytes48),
    ])


def light_client_bootstrap(spec):
    return Container("LightClientBootstrap", [
        ("header", light_client_header(spec)),
        ("current_sync_committee", sync_committee(spec)),
        ("current_sync_committee_branch",
         Vector(Bytes32, SYNC_COMMITTEE_BRANCH_DEPTH)),
    ])


def sync_aggregate(spec):
    return Container("SyncAggregate", [
        ("sync_committee_bits", Bitvector(spec.sync_committee_size)),
        ("sync_committee_signature", Bytes96),
    ])


def light_client_update(spec):
    return Container("LightClientUpdate", [
        ("attested_header", light_client_header(spec)),
        ("next_sync_committee", sync_committee(spec)),
        ("next_sync_committee_branch",
         Vector(Bytes32, SYNC_COMMITTEE_BRANCH_DEPTH)),
        ("finalized_header", light_client_header(spec)),
        ("finality_branch", Vector(Bytes32, FINALITY_BRANCH_DEPTH)),
        ("sync_aggregate", sync_aggregate(spec)),
        ("signature_slot", uint64),
    ])


FORK_DATA = Container("ForkData", [
    ("current_version", ByteVector(4)),
    ("genesis_validators_root", Bytes32),
])

DOMAIN_SYNC_COMMITTEE = bytes([7, 0, 0, 0])


def compute_domain(domain_type: bytes, fork_version: bytes,
                   genesis_validators_root: bytes) -> bytes:
    """`compute_domain` per the consensus spec (reference:
    `ethereum_consensus_types::signing::compute_domain`, used at
    `test-utils/src/lib.rs:215-218`)."""
    fork_data_root = FORK_DATA.hash_tree_root(Obj(
        current_version=fork_version,
        genesis_validators_root=genesis_validators_root))
    return domain_type + fork_data_root[:28]

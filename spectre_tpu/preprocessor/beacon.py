"""Minimal Beacon-chain REST client (stdlib urllib; no external deps).

Reference parity: the `beacon-api-client` usage in `preprocessor/src/lib.rs`:
light-client endpoints for finality updates, committee updates and bootstrap.
Network egress may be unavailable in dev environments; everything above this
client consumes plain dicts, so tests inject fixtures instead.
"""

from __future__ import annotations

import json
import urllib.request


class BeaconClient:
    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str) -> dict:
        req = urllib.request.Request(self.base_url + path,
                                     headers={"Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.load(resp)

    def finality_update(self) -> dict:
        return self._get("/eth/v1/beacon/light_client/finality_update")["data"]

    def committee_updates(self, period: int, count: int = 1) -> list[dict]:
        data = self._get(f"/eth/v1/beacon/light_client/updates"
                         f"?start_period={period}&count={count}")
        return [d["data"] for d in data] if isinstance(data, list) else [data["data"]]

    def bootstrap(self, block_root: str) -> dict:
        return self._get(f"/eth/v1/beacon/light_client/bootstrap/{block_root}")["data"]

    def head_block_root(self) -> str:
        return self._get("/eth/v1/beacon/blocks/head/root")["data"]["root"]

    def sync_period(self, spec, slot: int) -> int:
        return spec.sync_period(slot)

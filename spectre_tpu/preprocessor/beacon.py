"""Resilient Beacon-chain REST client (stdlib urllib; no external deps).

Reference parity: the `beacon-api-client` usage in `preprocessor/src/lib.rs`:
light-client endpoints for finality updates, committee updates and bootstrap.
Network egress may be unavailable in dev environments; everything above this
client consumes plain dicts, so tests inject fixtures instead.

PR 3 (resilient service): upstream beacon nodes hiccup constantly under
load — a client that gives up on the first transient error starves the
prover. Every GET therefore runs under:

* **retry with exponential backoff + full jitter** — transient failures
  (HTTP 5xx/429, connection errors, timeouts) retry up to
  `SPECTRE_BEACON_RETRIES` times with `delay = U(0, min(max, base*2^i))`
  (full jitter decorrelates a retrying fleet); non-transient HTTP 4xx
  raise immediately.
* **Retry-After honor** — a 429/503 carrying Retry-After waits at least
  that long (seconds form; HTTP-date form falls back to the backoff).
* **per-attempt vs total deadline split** — each attempt gets at most
  `timeout` (per-attempt) but the whole call never exceeds
  `SPECTRE_BEACON_TOTAL_TIMEOUT`; the last attempt's socket timeout is
  clipped to the remaining budget.
* **circuit breaker** — `SPECTRE_BEACON_CB_THRESHOLD` consecutive
  failures trip the breaker OPEN: calls fail fast (CircuitBreakerOpen)
  without touching the network for `SPECTRE_BEACON_CB_COOLDOWN` seconds,
  then HALF-OPEN admits one trial request — success closes the breaker,
  failure re-opens it for another cooldown.

Retries/trips/half-opens are counted on utils.health (HEALTH) and the
fault-injection site `beacon.fetch` (utils/faults) fires before each
attempt, so every path above is deterministically testable in CI.
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.error
import urllib.request

from ..utils import faults
from ..utils.health import HEALTH


class CircuitBreakerOpen(RuntimeError):
    """Failing fast: the breaker is open (upstream considered down)."""


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


def _is_transient(exc: BaseException) -> bool:
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 or exc.code == 429
    return isinstance(exc, (urllib.error.URLError, TimeoutError,
                            ConnectionError, OSError))


def _retry_after_seconds(exc: BaseException) -> float | None:
    """Seconds-form Retry-After from a 429/503 response, if present."""
    hdrs = getattr(exc, "headers", None)
    if hdrs is None:
        return None
    ra = hdrs.get("Retry-After")
    if ra is None:
        return None
    try:
        return max(0.0, float(ra))
    except ValueError:
        return None     # HTTP-date form: fall back to computed backoff


# live-client registry for readiness reporting: GET /healthz consults the
# breaker state of every BeaconClient this process created (weak refs — a
# dropped client leaves the registry; no lifecycle coupling to the service)
import weakref

_CLIENTS: "weakref.WeakSet" = weakref.WeakSet()


# numeric codes for the Prometheus exporter (a gauge can't carry a
# string; alerting rules compare against these)
BREAKER_STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}


def breaker_snapshot() -> list[dict]:
    """Breaker state of every live BeaconClient, for /healthz readiness
    (ROADMAP PR-3 follow-up): an OPEN breaker means the upstream beacon is
    considered down and the service cannot make proving progress that
    needs fresh chain data — the readiness probe turns 503."""
    return [{"base_url": c.base_url, "state": c.breaker_state,
             "state_code": BREAKER_STATE_CODES.get(c.breaker_state, -1),
             "consecutive_failures": c._consecutive_failures}
            for c in list(_CLIENTS)]


class BeaconClient:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 retries: int | None = None,
                 backoff_base: float | None = None,
                 backoff_max: float | None = None,
                 total_timeout: float | None = None,
                 breaker_threshold: int | None = None,
                 breaker_cooldown: float | None = None,
                 health=HEALTH, sleep=time.sleep, rng=random.random):
        """`timeout` is PER-ATTEMPT; `total_timeout` caps the whole
        retried call. `sleep`/`rng` are injectable for deterministic
        tests (rng() in [0,1) scales the full-jitter backoff)."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = retries if retries is not None \
            else _env_int("SPECTRE_BEACON_RETRIES", 4)
        self.backoff_base = backoff_base if backoff_base is not None \
            else _env_float("SPECTRE_BEACON_BACKOFF_BASE", 0.25)
        self.backoff_max = backoff_max if backoff_max is not None \
            else _env_float("SPECTRE_BEACON_BACKOFF_MAX", 8.0)
        self.total_timeout = total_timeout if total_timeout is not None \
            else _env_float("SPECTRE_BEACON_TOTAL_TIMEOUT", 120.0)
        self.breaker_threshold = breaker_threshold \
            if breaker_threshold is not None \
            else _env_int("SPECTRE_BEACON_CB_THRESHOLD", 5)
        self.breaker_cooldown = breaker_cooldown \
            if breaker_cooldown is not None \
            else _env_float("SPECTRE_BEACON_CB_COOLDOWN", 30.0)
        self.health = health
        self._sleep = sleep
        self._rng = rng
        # breaker state: consecutive failures + open-until timestamp
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        self._half_open = False
        _CLIENTS.add(self)     # readiness registry (breaker_snapshot)

    # -- circuit breaker ---------------------------------------------------

    @property
    def breaker_state(self) -> str:
        if self._opened_at is None:
            return "closed"
        if time.time() - self._opened_at >= self.breaker_cooldown:
            return "half-open"
        return "open"

    def _breaker_admit(self):
        state = self.breaker_state
        if state == "open":
            remain = self.breaker_cooldown - (time.time() - self._opened_at)
            raise CircuitBreakerOpen(
                f"beacon circuit breaker open for another {remain:.1f}s "
                f"after {self._consecutive_failures} consecutive failures")
        if state == "half-open" and not self._half_open:
            self._half_open = True
            self.health.incr("beacon_breaker_half_open")

    def _breaker_record(self, ok: bool):
        if ok:
            self._consecutive_failures = 0
            self._opened_at = None
            self._half_open = False
            return
        self._consecutive_failures += 1
        half_open_failed = self._half_open
        self._half_open = False
        if (half_open_failed
                or self._consecutive_failures >= self.breaker_threshold):
            if self._opened_at is None or half_open_failed:
                self.health.incr("beacon_breaker_trips")
            self._opened_at = time.time()

    # -- retried GET -------------------------------------------------------

    def _get(self, path: str) -> dict:
        # spanned (ISSUE 8): beacon IO becomes a real `beacon/fetch`
        # child under job/preprocess in getTrace (and the phase
        # histogram) instead of unattributed converter time; the span
        # covers the FULL retry loop, annotated with path + attempts
        from ..observability import tracing
        from ..utils.profiling import phase
        with phase("beacon/fetch"):
            tracing.annotate(path=path)
            return self._get_retrying(path)

    def _get_retrying(self, path: str) -> dict:
        self._breaker_admit()
        url = self.base_url + path
        deadline = time.time() + self.total_timeout
        attempt = 0
        while True:
            remain = deadline - time.time()
            if remain <= 0:
                self._breaker_record(False)
                raise TimeoutError(
                    f"beacon GET {path}: total deadline "
                    f"({self.total_timeout}s) exceeded after "
                    f"{attempt} attempts")
            try:
                faults.check("beacon.fetch")
                req = urllib.request.Request(
                    url, headers={"Accept": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=min(self.timeout, remain)) as resp:
                    data = json.load(resp)
                self._breaker_record(True)
                return data
            except faults.InjectedCrash:
                raise
            except Exception as exc:
                self._breaker_record(False)
                if not _is_transient(exc):
                    raise
                if self.breaker_state == "open":
                    # tripped mid-call: stop hammering a dead upstream
                    raise CircuitBreakerOpen(
                        f"beacon circuit breaker tripped during GET {path} "
                        f"({self._consecutive_failures} consecutive "
                        f"failures)") from exc
                if attempt >= self.retries:
                    raise
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** attempt)) * self._rng()
                ra = _retry_after_seconds(exc)
                if ra is not None:
                    delay = max(delay, ra)
                delay = min(delay, max(0.0, deadline - time.time()))
                self.health.incr("beacon_retries")
                self._sleep(delay)
                attempt += 1

    # -- endpoints ---------------------------------------------------------

    def finality_update(self) -> dict:
        return self._get("/eth/v1/beacon/light_client/finality_update")["data"]

    def committee_updates(self, period: int, count: int = 1) -> list[dict]:
        data = self._get(f"/eth/v1/beacon/light_client/updates"
                         f"?start_period={period}&count={count}")
        return [d["data"] for d in data] if isinstance(data, list) else [data["data"]]

    def bootstrap(self, block_root: str) -> dict:
        return self._get(f"/eth/v1/beacon/light_client/bootstrap/{block_root}")["data"]

    def head_block_root(self) -> str:
        return self._get("/eth/v1/beacon/blocks/head/root")["data"]["root"]

    def sync_period(self, spec, slot: int) -> int:
        return spec.sync_period(slot)

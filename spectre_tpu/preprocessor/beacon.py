"""Resilient Beacon-chain REST client (stdlib urllib; no external deps).

Reference parity: the `beacon-api-client` usage in `preprocessor/src/lib.rs`:
light-client endpoints for finality updates, committee updates and bootstrap.
Network egress may be unavailable in dev environments; everything above this
client consumes plain dicts, so tests inject fixtures instead.

PR 3 (resilient service): upstream beacon nodes hiccup constantly under
load — a client that gives up on the first transient error starves the
prover. Every GET therefore runs under:

* **retry with exponential backoff + full jitter** — transient failures
  (HTTP 5xx/429, connection errors, timeouts) retry up to
  `SPECTRE_BEACON_RETRIES` times with `delay = U(0, min(max, base*2^i))`
  (full jitter decorrelates a retrying fleet); non-transient HTTP 4xx
  raise immediately.
* **Retry-After honor** — a 429/503 carrying Retry-After waits at least
  that long (seconds form; HTTP-date form falls back to the backoff).
* **per-attempt vs total deadline split** — each attempt gets at most
  `timeout` (per-attempt) but the whole call never exceeds
  `SPECTRE_BEACON_TOTAL_TIMEOUT`; the last attempt's socket timeout is
  clipped to the remaining budget.
* **circuit breaker** — `SPECTRE_BEACON_CB_THRESHOLD` consecutive
  failures trip the breaker OPEN: calls fail fast (CircuitBreakerOpen)
  without touching the network for `SPECTRE_BEACON_CB_COOLDOWN` seconds,
  then HALF-OPEN admits one trial request — success closes the breaker,
  failure re-opens it for another cooldown.

Retries/trips/half-opens are counted on utils.health (HEALTH) and the
fault-injection site `beacon.fetch` (utils/faults) fires before each
attempt, so every path above is deterministically testable in CI.

ISSUE 11 (proof farm): the breaker state machine moved to
``utils/breaker.CircuitBreaker`` (the dispatcher reuses it per prover
replica); this client keeps its exact public surface on top. New here:
:class:`BeaconQuorum` — an N-client pool that only acts on a finalized
head at least ``quorum`` beacons agree on, demoting a lone dissenting
(lying or forked) beacon behind its own breaker so it cannot stall or
fork the follower chain (``beacon_quorum_dissent`` counts it).
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.error
import urllib.request

from ..utils import faults
from ..utils.breaker import BreakerOpen, CircuitBreaker
from ..utils.health import HEALTH


class CircuitBreakerOpen(RuntimeError):
    """Failing fast: the breaker is open (upstream considered down)."""


class QuorumNotReached(RuntimeError):
    """The beacon pool could not assemble `quorum` matching finalized
    heads — no single answer is trustworthy enough to act on."""


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


def _is_transient(exc: BaseException) -> bool:
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 or exc.code == 429
    return isinstance(exc, (urllib.error.URLError, TimeoutError,
                            ConnectionError, OSError))


def _retry_after_seconds(exc: BaseException) -> float | None:
    """Seconds-form Retry-After from a 429/503 response, if present."""
    hdrs = getattr(exc, "headers", None)
    if hdrs is None:
        return None
    ra = hdrs.get("Retry-After")
    if ra is None:
        return None
    try:
        return max(0.0, float(ra))
    except ValueError:
        return None     # HTTP-date form: fall back to computed backoff


# live-client registry for readiness reporting: GET /healthz consults the
# breaker state of every BeaconClient this process created (weak refs — a
# dropped client leaves the registry; no lifecycle coupling to the service)
import weakref

_CLIENTS: "weakref.WeakSet" = weakref.WeakSet()


# numeric codes for the Prometheus exporter (a gauge can't carry a
# string; alerting rules compare against these)
BREAKER_STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}


def breaker_snapshot() -> list[dict]:
    """Breaker state of every live BeaconClient, for /healthz readiness
    (ROADMAP PR-3 follow-up): an OPEN breaker means the upstream beacon is
    considered down and the service cannot make proving progress that
    needs fresh chain data — the readiness probe turns 503."""
    return [{"base_url": c.base_url, "state": c.breaker_state,
             "state_code": BREAKER_STATE_CODES.get(c.breaker_state, -1),
             "consecutive_failures": c._consecutive_failures}
            for c in list(_CLIENTS)]


class BeaconClient:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 retries: int | None = None,
                 backoff_base: float | None = None,
                 backoff_max: float | None = None,
                 total_timeout: float | None = None,
                 breaker_threshold: int | None = None,
                 breaker_cooldown: float | None = None,
                 health=HEALTH, sleep=time.sleep, rng=random.random):
        """`timeout` is PER-ATTEMPT; `total_timeout` caps the whole
        retried call. `sleep`/`rng` are injectable for deterministic
        tests (rng() in [0,1) scales the full-jitter backoff)."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = retries if retries is not None \
            else _env_int("SPECTRE_BEACON_RETRIES", 4)
        self.backoff_base = backoff_base if backoff_base is not None \
            else _env_float("SPECTRE_BEACON_BACKOFF_BASE", 0.25)
        self.backoff_max = backoff_max if backoff_max is not None \
            else _env_float("SPECTRE_BEACON_BACKOFF_MAX", 8.0)
        self.total_timeout = total_timeout if total_timeout is not None \
            else _env_float("SPECTRE_BEACON_TOTAL_TIMEOUT", 120.0)
        self.breaker_threshold = breaker_threshold \
            if breaker_threshold is not None \
            else _env_int("SPECTRE_BEACON_CB_THRESHOLD", 5)
        self.breaker_cooldown = breaker_cooldown \
            if breaker_cooldown is not None \
            else _env_float("SPECTRE_BEACON_CB_COOLDOWN", 30.0)
        self.health = health
        self._sleep = sleep
        self._rng = rng
        # breaker state machine shared with the dispatcher (utils/breaker)
        self._breaker = CircuitBreaker(
            threshold=self.breaker_threshold,
            cooldown=self.breaker_cooldown,
            health=health, counter_prefix="beacon_breaker")
        _CLIENTS.add(self)     # readiness registry (breaker_snapshot)

    # -- circuit breaker ---------------------------------------------------

    @property
    def breaker_state(self) -> str:
        return self._breaker.state

    @property
    def _consecutive_failures(self) -> int:
        return self._breaker.consecutive_failures

    def _breaker_admit(self):
        try:
            self._breaker.admit()
        except BreakerOpen:
            raise CircuitBreakerOpen(
                f"beacon circuit breaker open for another "
                f"{self._breaker.remaining():.1f}s after "
                f"{self._consecutive_failures} consecutive failures") \
                from None

    def _breaker_record(self, ok: bool):
        self._breaker.record(ok)

    def demote(self) -> None:
        """Penalize this beacon without a network call: a quorum
        dissent (divergent finalized head) counts as a failure, so a
        persistently lying/forked beacon trips its own breaker and
        drops out of the pool until cooldown."""
        self._breaker.record(False)
        self.health.incr("beacon_demoted")

    # -- retried GET -------------------------------------------------------

    def _get(self, path: str) -> dict:
        # spanned (ISSUE 8): beacon IO becomes a real `beacon/fetch`
        # child under job/preprocess in getTrace (and the phase
        # histogram) instead of unattributed converter time; the span
        # covers the FULL retry loop, annotated with path + attempts
        from ..observability import tracing
        from ..utils.profiling import phase
        with phase("beacon/fetch"):
            tracing.annotate(path=path)
            return self._get_retrying(path)

    def _get_retrying(self, path: str) -> dict:
        self._breaker_admit()
        url = self.base_url + path
        deadline = time.time() + self.total_timeout
        attempt = 0
        while True:
            remain = deadline - time.time()
            if remain <= 0:
                self._breaker_record(False)
                raise TimeoutError(
                    f"beacon GET {path}: total deadline "
                    f"({self.total_timeout}s) exceeded after "
                    f"{attempt} attempts")
            try:
                faults.check("beacon.fetch")
                req = urllib.request.Request(
                    url, headers={"Accept": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=min(self.timeout, remain)) as resp:
                    data = json.load(resp)
                self._breaker_record(True)
                return data
            except faults.InjectedCrash:
                raise
            except Exception as exc:
                self._breaker_record(False)
                if not _is_transient(exc):
                    raise
                if self.breaker_state == "open":
                    # tripped mid-call: stop hammering a dead upstream
                    raise CircuitBreakerOpen(
                        f"beacon circuit breaker tripped during GET {path} "
                        f"({self._consecutive_failures} consecutive "
                        f"failures)") from exc
                if attempt >= self.retries:
                    raise
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** attempt)) * self._rng()
                ra = _retry_after_seconds(exc)
                if ra is not None:
                    delay = max(delay, ra)
                delay = min(delay, max(0.0, deadline - time.time()))
                self.health.incr("beacon_retries")
                self._sleep(delay)
                attempt += 1

    # -- endpoints ---------------------------------------------------------

    def finality_update(self) -> dict:
        return self._get("/eth/v1/beacon/light_client/finality_update")["data"]

    def committee_updates(self, period: int, count: int = 1) -> list[dict]:
        data = self._get(f"/eth/v1/beacon/light_client/updates"
                         f"?start_period={period}&count={count}")
        return [d["data"] for d in data] if isinstance(data, list) else [data["data"]]

    def bootstrap(self, block_root: str) -> dict:
        return self._get(f"/eth/v1/beacon/light_client/bootstrap/{block_root}")["data"]

    def head_block_root(self) -> str:
        return self._get("/eth/v1/beacon/blocks/head/root")["data"]["root"]

    def sync_period(self, spec, slot: int) -> int:
        return spec.sync_period(slot)


class BeaconQuorum:
    """N-beacon pool requiring `quorum` agreement on the finalized head.

    The follower's head tracker polls one beacon today; a lying (or
    long-forked) beacon can stall the chain or feed it a head the
    committee chain will never verify against. The quorum pool polls
    every non-breaker-open client, groups their finalized headers by
    canonical JSON, and only returns a head at least ``quorum`` beacons
    agree on. A dissenting minority is demoted behind each client's own
    breaker (``beacon_quorum_dissent``), so one bad beacon degrades to
    harmless noise instead of a fork.

    Drop-in for :class:`BeaconClient` where the follower consumes it:
    `finality_update` / `committee_updates` / `bootstrap` /
    `head_block_root` / `sync_period` are provided; the non-quorum
    endpoints simply fail over through healthy clients in order.
    """

    def __init__(self, clients, quorum: int | None = None, health=HEALTH):
        if not clients:
            raise ValueError("BeaconQuorum needs at least one BeaconClient")
        self.clients = list(clients)
        self.quorum = min(len(self.clients),
                          quorum if quorum is not None
                          else _env_int("SPECTRE_BEACON_QUORUM", 2))
        self.health = health

    # -- quorum head -------------------------------------------------------

    @staticmethod
    def _head_key(update: dict) -> str:
        hdr = update.get("finalized_header", update)
        return json.dumps(hdr, sort_keys=True, separators=(",", ":"))

    def finality_update(self) -> dict:
        """Finalized head at least `quorum` beacons agree on.

        Breaker-open clients are skipped; per-client fetch errors are
        tolerated (counted on ``beacon_quorum_errors``) as long as a
        quorum remains. Raises :class:`QuorumNotReached` otherwise."""
        votes: dict[str, list] = {}   # head key -> [(client, update), ...]
        errors = 0
        for c in self.clients:
            if c.breaker_state == "open":
                continue
            try:
                upd = c.finality_update()
            except faults.InjectedCrash:
                raise
            except Exception:
                errors += 1
                self.health.incr("beacon_quorum_errors")
                continue
            votes.setdefault(self._head_key(upd), []).append((c, upd))
        if not votes:
            self.health.incr("beacon_quorum_failures")
            raise QuorumNotReached(
                f"no beacon answered ({errors} errors, "
                f"{len(self.clients)} clients)")
        best_key = max(votes, key=lambda k: len(votes[k]))
        if len(votes[best_key]) < self.quorum:
            self.health.incr("beacon_quorum_failures")
            raise QuorumNotReached(
                f"finalized heads split {sorted(len(v) for v in votes.values())} "
                f"across {len(votes)} answers; need {self.quorum} matching")
        for key, members in votes.items():
            if key == best_key:
                continue
            for c, _ in members:
                c.demote()
                self.health.incr("beacon_quorum_dissent")
        return votes[best_key][0][1]

    # -- failover passthrough ---------------------------------------------

    def _any(self, fn_name: str, *args, **kw):
        last_exc: Exception | None = None
        for c in self.clients:
            if c.breaker_state == "open":
                continue
            try:
                return getattr(c, fn_name)(*args, **kw)
            except faults.InjectedCrash:
                raise
            except Exception as exc:
                last_exc = exc
                self.health.incr("beacon_quorum_errors")
        raise last_exc if last_exc is not None else CircuitBreakerOpen(
            f"all {len(self.clients)} beacon breakers open")

    def committee_updates(self, period: int, count: int = 1) -> list[dict]:
        return self._any("committee_updates", period, count)

    def bootstrap(self, block_root: str) -> dict:
        return self._any("bootstrap", block_root)

    def head_block_root(self) -> str:
        return self._any("head_block_root")

    def sync_period(self, spec, slot: int) -> int:
        return spec.sync_period(slot)

"""Witness acquisition: Beacon-chain REST -> circuit witnesses.

Reference parity (SURVEY.md L4): `preprocessor/src/` — fetchers for
LightClientFinalityUpdate / LightClientUpdate / Bootstrap and converters to
SyncStepArgs / CommitteeUpdateArgs, with NATIVE verification of the merkle
branches and the aggregate signature before proving
(`step.rs:90-120`, `rotation.rs:105-118`).
"""

from .beacon import BeaconClient  # noqa: F401
from .step import step_args_from_finality_update  # noqa: F401
from .rotation import rotation_args_from_update  # noqa: F401

"""spectre_tpu — a TPU-native ZK proving framework.

From-scratch rebuild of the capabilities of ChainSafe/Spectre (reference at
/root/reference): an Ethereum Altair light-client prover — PLONKish circuits over
BN254 with KZG/SHPLONK commitments, BLS12-381 signature verification in-circuit,
SSZ merkleization, Poseidon committee commitments — with the dominant proving
costs (MSM, NTT, bulk SHA256/Poseidon hashing) running as JAX/Pallas kernels on
TPU, sharded over device meshes via jax.sharding.

Layer map (mirrors SURVEY.md §1, rebuilt TPU-first):
  fields/        host-side exact arithmetic: BN254, BLS12-381 (oracle + verifier)
  native/        C++ host library: Montgomery field ops, Pippenger MSM (CPU
                 baseline), transcript hashing
  ops/           device kernels: limbed Montgomery Fr, NTT, MSM, SHA256, Poseidon
  plonk/         the proving system: KZG/SHPLONK, lookup + permutation arguments,
                 prover/verifier (halo2-compatible protocol shape)
  builder/       virtual circuit builder: flex gate, range chip, CRT bigint,
                 non-native Fp/ECC chips (halo2-lib equivalent)
  gadgets/       SSZ merkleization, merkle proofs, poseidon commitment
  models/        application circuits: StepCircuit, CommitteeUpdateCircuit,
                 AggregationCircuit
  witness/       witness types + builders (SyncStepArgs, CommitteeUpdateArgs)
  preprocessor/  Beacon API -> witness conversion
  prover_service/ CLI, JSON-RPC server/client, prover state
  parallel/      mesh sharding: distributed MSM/NTT, batched proving
  utils/         pinning, serialization, SRS cache
"""

__version__ = "0.1.0"

"""Solidity verifier generator for the Keccak-transcript SHPLONK verifier.

Reference parity: snark-verifier's EVM verifier codegen
(`gen_evm_verifier_shplonk`, `util/circuit.rs:182-194`) — the reference
emits Yul from its PlonkVerifier; here the generator walks the SAME
verification program as plonk/verifier.py (transcript replay, identity
check at x via `all_expressions`, SHPLONK pairing check) and emits a
self-contained Solidity contract:

- the Fiat–Shamir transcript is unrolled: the absorb sequence between
  challenges is static for a fixed vk shape, so each challenge becomes one
  keccak over (state || absorbed-bytes || "C" || counter), exactly
  mirroring `transcript.KeccakTranscript`;
- the gate/permutation/lookup identity is emitted by running
  `all_expressions` with a code-emitting ctx (the same single-source trick
  the prover/verifier/mock share — the generated contract provably checks
  the same polynomial identity);
- the SHPLONK check uses the EVM BN254 precompiles (ecMul 0x7, ecAdd 0x6,
  pairing 0x8; modexp 0x5 for inversions), with [1]_2 / [tau]_2 embedded
  from the SRS.

Proof byte layout and challenge schedule match `plonk/verifier.py` line by
line; `encode_calldata` produces the `verify(uint256[],bytes)` ABI call.
"""

from __future__ import annotations

from ..fields import bn254
from ..plonk.expressions import all_expressions
from ..plonk.keygen import ROT_LAST, VerifyingKey
from ..plonk.srs import SRS
from ..plonk.transcript import keccak256

R = bn254.R
Q = bn254.P


class _Sym:
    """Symbolic transcript challenge: supports the `beta * dj % R` integer
    arithmetic all_expressions performs, emitting Solidity instead."""

    def __init__(self, expr: str):
        self.expr = expr

    def __mul__(self, k: int):
        return _Sym(f"mulmod({self.expr}, {hex(k % R)}, R_MOD)")

    def __mod__(self, _r: int):
        return self


def _sym_expr(s) -> str:
    return s.expr if isinstance(s, _Sym) else hex(int(s) % R)


class _Emit:
    def __init__(self):
        self.lines: list[str] = []
        self._tmp = 0

    def line(self, s: str):
        self.lines.append(s)

    def fresh(self) -> str:
        """Memory-array temporary slot: `t[i]`. Stack locals would blow the
        EVM's 16-slot reach in legacy solc codegen (hundreds of field-op
        temporaries); one memory array costs a single stack slot."""
        self._tmp += 1
        return f"t[{self._tmp - 1}]"

    @property
    def num_tmps(self) -> int:
        return self._tmp


class _SolCtx:
    """all_expressions ctx that EMITS Solidity mulmod/addmod statements.
    Values are Solidity expressions (variable names or literals)."""

    def __init__(self, em: _Emit, eval_var):
        self._em = em
        self._eval_var = eval_var   # (key, rot) -> solidity expr
        self.l0 = "l0"
        self.llast = "llast"
        self.lblind = "lblind"
        self.x_col = "x"

    def var(self, key, rot):
        return self._eval_var(key, rot)

    def _bin(self, op, a, b):
        v = self._em.fresh()
        self._em.line(f"{v} = {op}({a}, {b}, R_MOD);")
        return v

    def mul(self, a, b):
        return self._bin("mulmod", a, b)

    def add(self, a, b):
        return self._bin("addmod", a, b)

    def sub(self, a, b):
        v = self._em.fresh()
        self._em.line(f"{v} = addmod({a}, R_MOD - {b}, R_MOD);")
        return v

    def scale(self, a, s):
        return self._bin("mulmod", a, _sym_expr(s))

    def add_const(self, a, s):
        return self._bin("addmod", a, _sym_expr(s))

    def const(self, s):
        return hex(int(s) % R)


def _pt_words(pt):
    if pt is None:
        return (0, 0)
    return (int(pt[0]), int(pt[1]))


def gen_evm_verifier(vk: VerifyingKey, srs: SRS, num_instances: int,
                     contract_name: str = "SpectreVerifier",
                     num_acc_limbs: int = 0) -> str:
    """Solidity source for `function verify(uint256[] calldata instances,
    bytes calldata proof) external view returns (bool)`.

    num_acc_limbs=12 (aggregation circuits): the first 12 instances are the
    deferred KZG accumulator (lhs.x, lhs.y, rhs.x, rhs.y as 3 x 88-bit LE
    limbs, snark-verifier `LimbsEncoding<3, 88>` parity) and the contract
    ALSO performs the deferred pairing e(lhs, [tau]_2) == e(rhs, [1]_2) —
    without it a compressed proof wrapping an invalid inner proof would
    verify (mirrors `AggregationCircuit.verify`)."""
    cfg = vk.config
    dom = vk.domain
    n, u = cfg.n, cfg.usable_rows
    QMOD = int(bn254.P)
    assert cfg.num_instance == 1, \
        "EVM codegen supports a single instance column (flat uint256[] ABI)"

    # ---- static proof layout (the same plan verifier.py consumes) ----
    read_points, pre_bg, pre_y, pre_x = vk.commitment_plan()

    plan = vk.query_plan()
    evals_off = pre_x * 64
    w1_off = evals_off + len(plan) * 32
    w2_off = w1_off + 64
    proof_len = w2_off + 64
    point_off = {key: 64 * i for i, key in enumerate(read_points)}
    eval_off = {kr: evals_off + 32 * i for i, kr in enumerate(plan)}

    em = _Emit()
    L = em.line

    # ---- helpers to emit transcript squeezes ----
    def absorb_chunks(items):
        """items: ('pt', key) | ('scalar_eval', idx_offset) — returns the
        abi.encodePacked argument list for the absorbed byte run."""
        parts = []
        for kind, v in items:
            if kind == "pt":
                off = point_off[v]
                parts.append(f'hex"50", proof[{off}:{off + 64}]')
            elif kind == "evals":
                lo, hi = v
                for o in range(lo, hi, 32):
                    parts.append(f'hex"53", proof[{o}:{o + 32}]')
        return parts

    ctr = [0]

    def squeeze(var, parts):
        ctr[0] += 1
        packed = ", ".join(["h"] + parts + [f'hex"43", uint32({ctr[0]})'])
        L(f"h = keccak256(abi.encodePacked({packed}));")
        L(f"uint256 {var} = _wide(h);")

    # ---- body: transcript replay ----
    L("bytes32 h = INIT_STATE;")
    L(f"require(proof.length == {proof_len}, \"proof length\");")
    L(f"require(instances.length == {num_instances}, \"instances length\");")
    # vk digest + instances absorbed into the first squeeze's buffer
    pre_parts = ["VK_DIGEST"]
    L("bytes memory instAbsorb;")
    L("for (uint256 i = 0; i < instances.length; i++) {")
    L("    require(instances[i] < R_MOD, \"instance range\");")
    L("    instAbsorb = abi.encodePacked(instAbsorb, hex\"53\", "
      "bytes32(instances[i]));")
    L("}")
    pre_parts.append("instAbsorb")
    # on-curve checks are delegated to the EC precompiles (they reject
    # non-curve and non-canonical points on first use)
    pre_parts += absorb_chunks([("pt", k) for k in read_points[:pre_bg]])
    squeeze("beta", pre_parts)
    squeeze("gamma", [])   # consecutive squeeze, nothing absorbed between
    squeeze("y", absorb_chunks([("pt", k)
                                for k in read_points[pre_bg:pre_y]]))
    squeeze("x", absorb_chunks([("pt", k)
                                for k in read_points[pre_y:pre_x]]))

    # scalar eval canonicity
    L(f"for (uint256 o = {evals_off}; o < {w1_off}; o += 32) "
      "{ require(uint256(bytes32(proof[o:o+32])) < R_MOD, \"eval range\"); }")

    # ---- lagrange evals: l0, llast, lblind, instance rows ----
    L(f"uint256 xn = _pow(x, {n});")
    L("uint256 zx = addmod(xn, R_MOD - 1, R_MOD);")
    L(f"uint256 ninv = {hex(pow(n, -1, R))};")
    omega = dom.omega

    def lagrange_expr(row):
        wi = pow(omega, row, R)
        return (f"mulmod(mulmod(mulmod({hex(wi)}, zx, R_MOD), "
                f"_inv(addmod(x, R_MOD - {hex(wi)}, R_MOD)), R_MOD), "
                "ninv, R_MOD)")

    L(f"uint256 l0 = {lagrange_expr(0)};")
    L(f"uint256 llast = {lagrange_expr(cfg.last_row)};")
    L("uint256 lblind = 0;")
    for i in range(u + 1, n):
        L(f"lblind = addmod(lblind, {lagrange_expr(i)}, R_MOD);")

    # instance evaluations (public-input binding); wi tracked incrementally
    L("uint256 instEval = 0;")
    L("{")
    L("uint256 wi = 1;")
    L("for (uint256 i = 0; i < instances.length; i++) {")
    L("    uint256 li = mulmod(mulmod(mulmod(wi, zx, R_MOD), "
      "_inv(addmod(x, R_MOD - wi, R_MOD)), R_MOD), ninv, R_MOD);")
    L("    instEval = addmod(instEval, mulmod(instances[i], li, R_MOD), R_MOD);")
    L(f"    wi = mulmod(wi, {hex(omega)}, R_MOD);")
    L("}")
    L("}")

    # ---- identity check via all_expressions ----
    def eval_var(key, rot):
        kind = key[0]
        if kind == "inst":
            return "instEval"
        if (key, rot) in eval_off:
            o = eval_off[(key, rot)]
            return f"uint256(bytes32(proof[{o}:{o + 32}]))"
        raise KeyError((key, rot))

    ctx = _SolCtx(em, eval_var)
    exprs = all_expressions(cfg, ctx, _Sym("beta"), _Sym("gamma"))
    L("uint256 acc = 0;")
    for e in exprs:
        L(f"acc = addmod(mulmod(acc, y, R_MOD), {e}, R_MOD);")
    h0 = eval_var(("h", 0), 0)
    h1 = eval_var(("h", 1), 0)
    h2 = eval_var(("h", 2), 0)
    L(f"uint256 hAtX = addmod({h0}, mulmod(xn, addmod({h1}, "
      f"mulmod(xn, {h2}, R_MOD), R_MOD), R_MOD), R_MOD);")
    L("require(acc == mulmod(hAtX, zx, R_MOD), \"identity\");")

    # ---- SHPLONK ----
    squeeze("v", absorb_chunks([("evals", (evals_off, w1_off))]))
    squeeze("uch", [f'hex"50", proof[{w1_off}:{w1_off + 64}]'])
    # fixed commitments table (one source with the Python verifier)
    fixed_commits = vk.fixed_commitment_map()

    by_key: dict = {}
    for key, rot in plan:
        by_key.setdefault(key, []).append(rot)

    def rot_factor(rot):
        if rot == ROT_LAST:
            return pow(omega, cfg.last_row, R)
        if rot < 0:
            return pow(dom.omega_inv, -rot, R)
        return pow(omega, rot, R)

    all_rots = []
    for key, rots in by_key.items():
        for r in rots:
            if r not in all_rots:
                all_rots.append(r)
    # rotation point values p_r = x * omega^rot
    for i, rot in enumerate(all_rots):
        L(f"uint256 p{i} = mulmod(x, {hex(rot_factor(rot))}, R_MOD);")
    rot_var = {rot: f"p{i}" for i, rot in enumerate(all_rots)}

    L("uint256[2] memory F = [uint256(0), uint256(0)];")
    L("uint256 eScalar = 0;")
    L("uint256 vk_pow = 1;")
    for key, rots in by_key.items():
        # z_rest(u) = prod over rots NOT in this entry
        L("{")
        L("uint256 zRest = 1;")
        for rot in all_rots:
            if rot not in rots:
                L(f"zRest = mulmod(zRest, addmod(uch, R_MOD - {rot_var[rot]},"
                  " R_MOD), R_MOD);")
        L("uint256 w = mulmod(vk_pow, zRest, R_MOD);")
        # r(u): lagrange interpolation through the (p_rot, eval) pairs
        L("uint256 rU = 0;")
        for i, ri in enumerate(rots):
            num = "1"
            den = "1"
            for rj in rots:
                if rj == ri:
                    continue
                num = (f"mulmod({num}, addmod(uch, R_MOD - {rot_var[rj]}, "
                       "R_MOD), R_MOD)")
                den = (f"mulmod({den}, addmod({rot_var[ri]}, R_MOD - "
                       f"{rot_var[rj]}, R_MOD), R_MOD)")
            ev = eval_var(key, ri)
            L(f"rU = addmod(rU, mulmod(mulmod({ev}, {num}, R_MOD), "
          f"_inv({den}), R_MOD), R_MOD);")
        # commitment source
        if key in point_off:
            o = point_off[key]
            L(f"F = _ecAdd(F, _ecMul([uint256(bytes32(proof[{o}:{o + 32}])), "
              f"uint256(bytes32(proof[{o + 32}:{o + 64}]))], w));")
        else:
            cx, cy = _pt_words(fixed_commits[key])
            L(f"F = _ecAdd(F, _ecMul([{hex(cx)}, {hex(cy)}], w));")
        L("eScalar = addmod(eScalar, mulmod(w, rU, R_MOD), R_MOD);")
        L("vk_pow = mulmod(vk_pow, v, R_MOD);")
        L("}")
    # z_T(u)
    L("uint256 zT = 1;")
    for rot in all_rots:
        L(f"zT = mulmod(zT, addmod(uch, R_MOD - {rot_var[rot]}, R_MOD), "
          "R_MOD);")
    gx, gy = _pt_words(bn254.G1_GEN)
    L(f"F = _ecAdd(F, _ecMul([{hex(gx)}, {hex(gy)}], "
      "R_MOD - eScalar));")
    L(f"F = _ecAdd(F, _ecMul(_negPt([uint256(bytes32(proof[{w1_off}:"
      f"{w1_off + 32}])), uint256(bytes32(proof[{w1_off + 32}:"
      f"{w1_off + 64}]))]), zT));")
    L(f"uint256[2] memory W2 = [uint256(bytes32(proof[{w2_off}:"
      f"{w2_off + 32}])), uint256(bytes32(proof[{w2_off + 32}:"
      f"{w2_off + 64}]))];")
    L("uint256[2] memory lhs = _ecAdd(F, _ecMul(W2, uch));")
    # pairing: e(lhs, G2_GEN) * e(-W2, G2_TAU) == 1
    g2g = srs.g2_gen
    g2t = srs.g2_tau
    L("uint256[12] memory pin;")
    for i, val in enumerate(
            ["lhs[0]", "lhs[1]",
             hex(int(g2g[0].c[1])), hex(int(g2g[0].c[0])),
             hex(int(g2g[1].c[1])), hex(int(g2g[1].c[0]))]):
        L(f"pin[{i}] = {val};")
    L("uint256[2] memory negW2 = _negPt(W2);")
    for i, val in enumerate(
            ["negW2[0]", "negW2[1]",
             hex(int(g2t[0].c[1])), hex(int(g2t[0].c[0])),
             hex(int(g2t[1].c[1])), hex(int(g2t[1].c[0]))]):
        L(f"pin[{6 + i}] = {val};")
    if not num_acc_limbs:
        L("return _pairing(pin);")
    else:
        # --- deferred KZG accumulator pairing (aggregation statements) ---
        assert num_acc_limbs == 12, "accumulator layout is 12 x 88-bit limbs"
        # the pairing and accumulator-limb checks return false (not revert)
        # so both the plain and accumulator paths agree on how an invalid
        # final check reports; structural requires (lengths, canonicity)
        # still revert in both paths
        L("if (!_pairing(pin)) { return false; }")
        L("// deferred accumulator: e(accL, [tau]_2) * e(-accR, [1]_2) == 1")
        for c, name in enumerate(["aLx", "aLy", "aRx", "aRy"]):
            terms = " + ".join(
                f"(instances[{3 * c + i}] << {88 * i})" if i
                else f"instances[{3 * c}]"
                for i in range(3))
            # limb ranges so the shifted sum cannot wrap uint256 (top limb
            # < 2^80 since 80 + 176 = 256); the coord < Q check then pins
            # the canonical value
            L(f"if (!(instances[{3 * c}] < (1 << 88) && "
              f"instances[{3 * c + 1}] < (1 << 88) && "
              f"instances[{3 * c + 2}] < (1 << 80))) {{ return false; }}")
            L(f"uint256 {name} = {terms};")
            L(f"if (!({name} < Q_MOD)) {{ return false; }}")
        L("uint256[2] memory negAccR = _negPt([aRx, aRy]);")
        for i, val in enumerate(
                ["aLx", "aLy",
                 hex(int(g2t[0].c[1])), hex(int(g2t[0].c[0])),
                 hex(int(g2t[1].c[1])), hex(int(g2t[1].c[0]))]):
            L(f"pin[{i}] = {val};")
        for i, val in enumerate(
                ["negAccR[0]", "negAccR[1]",
                 hex(int(g2g[0].c[1])), hex(int(g2g[0].c[0])),
                 hex(int(g2g[1].c[1])), hex(int(g2g[1].c[0]))]):
            L(f"pin[{6 + i}] = {val};")
        L("return _pairing(pin);")

    # temp slots live in one memory array (stack-depth safety); declared first
    body_lines = ([f"uint256[{max(em.num_tmps, 1)}] memory t;"] + em.lines)
    body_src = "\n        ".join(body_lines)

    init_state = keccak256(b"spectre-tpu-transcript-v1")
    src = f"""// SPDX-License-Identifier: MIT
// Auto-generated by spectre_tpu.evm.codegen — DO NOT EDIT.
// Verifier for circuit shape k={cfg.k} advice={cfg.num_advice} \
lookup={cfg.num_lookup_advice} fixed={cfg.num_fixed}
// NOTE: compile with `solc --via-ir` (field-op temporaries live in one
// memory array; ~20 named locals remain, beyond the legacy pipeline's
// comfortable stack reach for some shapes).
pragma solidity ^0.8.19;

contract {contract_name} {{
    uint256 internal constant R_MOD =
        {hex(R)};
    uint256 internal constant Q_MOD =
        {hex(QMOD)};
    bytes32 internal constant INIT_STATE =
        {"0x" + init_state.hex()};
    bytes32 internal constant VK_DIGEST =
        {"0x" + vk.digest().hex()};
    // 2^256 mod R (for folding the 64-byte squeeze into a scalar)
    uint256 internal constant POW256 = {hex((1 << 256) % R)};

    function _wide(bytes32 hi) internal pure returns (uint256) {{
        uint256 lo = uint256(keccak256(abi.encodePacked(hi)));
        return addmod(mulmod(uint256(hi) % R_MOD, POW256, R_MOD),
                      lo % R_MOD, R_MOD);
    }}

    function _pow(uint256 base, uint256 e) internal view returns (uint256 r) {{
        (bool ok, bytes memory out) = address(5).staticcall(abi.encode(
            uint256(32), uint256(32), uint256(32), base, e, R_MOD));
        require(ok, "modexp");
        r = abi.decode(out, (uint256));
    }}

    function _inv(uint256 a) internal view returns (uint256) {{
        require(a != 0, "inv(0)");
        return _pow(a, R_MOD - 2);
    }}

    function _ecMul(uint256[2] memory p, uint256 s)
            internal view returns (uint256[2] memory r) {{
        (bool ok, bytes memory out) = address(7).staticcall(
            abi.encode(p[0], p[1], s));
        require(ok, "ecMul");
        (r[0], r[1]) = abi.decode(out, (uint256, uint256));
    }}

    function _ecAdd(uint256[2] memory p, uint256[2] memory q)
            internal view returns (uint256[2] memory r) {{
        (bool ok, bytes memory out) = address(6).staticcall(
            abi.encode(p[0], p[1], q[0], q[1]));
        require(ok, "ecAdd");
        (r[0], r[1]) = abi.decode(out, (uint256, uint256));
    }}

    function _negPt(uint256[2] memory p)
            internal pure returns (uint256[2] memory) {{
        if (p[0] == 0 && p[1] == 0) return p;
        return [p[0], Q_MOD - p[1]];
    }}

    function _pairing(uint256[12] memory pin)
            internal view returns (bool) {{
        (bool ok, bytes memory out) = address(8).staticcall(abi.encode(
            pin[0], pin[1], pin[2], pin[3], pin[4], pin[5],
            pin[6], pin[7], pin[8], pin[9], pin[10], pin[11]));
        require(ok, "pairing");
        return abi.decode(out, (uint256)) == 1;
    }}

    function verify(uint256[] calldata instances, bytes calldata proof)
            external view returns (bool) {{
        {body_src}
    }}
}}
"""
    return src


def encode_calldata(instances: list, proof: bytes) -> bytes:
    """ABI call bytes for verify(uint256[],bytes) (reference:
    `encode_calldata` in snark-verifier, `rpc.rs:160-162`)."""
    sel = keccak256(b"verify(uint256[],bytes)")[:4]
    head = (64).to_bytes(32, "big")      # offset of instances
    inst_data = len(instances).to_bytes(32, "big") + b"".join(
        (int(v) % R).to_bytes(32, "big") for v in instances)
    proof_off = 64 + len(inst_data)
    head += proof_off.to_bytes(32, "big")
    proof_data = len(proof).to_bytes(32, "big") + proof
    if len(proof) % 32:
        proof_data += b"\x00" * (32 - len(proof) % 32)
    return sel + head + inst_data + proof_data

"""Compiler: generated-verifier Solidity subset -> EVM bytecode.

The reference's generated Yul is compiled by solc and executed in revm
(SURVEY.md N11, `prover/src/cli.rs:249-277`). No solc exists offline, but
none is needed: `evm/codegen.py` emits a closed, regular Solidity subset —
uint256 locals and fixed arrays, addmod/mulmod, keccak over
`abi.encodePacked`, calldata slices, precompile-backed helpers, two loop
shapes, `require`, guard-returns. This module compiles exactly that subset
to real EVM bytecode (runtime + deploy init code), so the generated
verifiers get ACTUAL deployed-code sizes (EIP-170 is a measurement, not an
estimate) and ACTUAL metered gas when executed in `evm/vm.py`.

Semantics notes (all hold on codegen's output, asserted where cheap):
- arithmetic outside mulmod/addmod never over/underflows (operands are
  range-checked field values / shifted 88-bit limbs), so unchecked EVM
  ADD/SUB match Solidity 0.8's checked ops on the non-reverting domain;
- `&&` compiles to bitwise AND of 0/1 values (operands are effect-free
  comparisons, so short-circuit is unobservable);
- helper functions (`_inv`, `_pow`, `_wide`, `_ecMul`, `_ecAdd`, `_negPt`,
  `_pairing`) become internal subroutines performing real STATICCALLs to
  precompile addresses 0x5-0x8 — the same calls solc emits for them;
- one `bytes memory` variable (the instance absorb buffer) is supported,
  as an append-only region sized from the static instance count.

Layout: scratch 0x00, big-modulus constants cached in memory (R_MOD/Q_MOD
appear thousands of times; MLOAD costs 3 bytes vs PUSH32's 33), calldata
ABI pointers, the staticcall buffer, then named variables / the `t[]`
temp array / the absorb buffers, assigned by the assembler.
"""

from __future__ import annotations

import re

# ---- memory map (fixed region) ----
SCRATCH = 0x00
CONST_R = 0x40
CONST_Q = 0x60
INSTLEN = 0x80
INSTDATA = 0xA0
PROOFLEN = 0xC0
PROOFDATA = 0xE0
CUR = 0x100
CALLBUF = 0x120           # 384 B staticcall arg/ret area, ends 0x2a0
VARS_BASE = 0x2A0

OPS = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "DIV": 0x04,
    "MOD": 0x06, "ADDMOD": 0x08, "MULMOD": 0x09, "EXP": 0x0A,
    "LT": 0x10, "GT": 0x11, "EQ": 0x14, "ISZERO": 0x15, "AND": 0x16,
    "OR": 0x17, "XOR": 0x18, "NOT": 0x19, "BYTE": 0x1A, "SHL": 0x1B,
    "SHR": 0x1C, "SHA3": 0x20, "CALLVALUE": 0x34, "CALLDATALOAD": 0x35,
    "CALLDATASIZE": 0x36, "CALLDATACOPY": 0x37, "CODESIZE": 0x38,
    "CODECOPY": 0x39, "RETURNDATASIZE": 0x3D, "RETURNDATACOPY": 0x3E,
    "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52, "MSTORE8": 0x53,
    "SLOAD": 0x54, "SSTORE": 0x55,
    "JUMP": 0x56, "JUMPI": 0x57, "PC": 0x58, "GAS": 0x5A,
    "JUMPDEST": 0x5B, "ADDRESS": 0x30, "CALLER": 0x33,
    "CALL": 0xF1, "RETURN": 0xF3, "STATICCALL": 0xFA, "REVERT": 0xFD,
}
for _i in range(16):
    OPS[f"DUP{_i + 1}"] = 0x80 + _i
    OPS[f"SWAP{_i + 1}"] = 0x90 + _i


# ======================================================================
# tokenizer / parser for the statement subset
# ======================================================================

_TOKEN_RE = re.compile(
    r'\s+|//[^\n]*'
    r'|hex"(?P<hex>[0-9a-fA-F]*)"'
    r'|"(?P<str>[^"]*)"'
    r'|(?P<num>0x[0-9a-fA-F]+|\d+)'
    r'|(?P<id>[A-Za-z_$]\w*)'
    r'|(?P<op><<|>>|\+\+|\+=|==|!=|&&|[-+*/!<>=&|(),;:\[\]{}.])')


def _tokenize(s: str):
    toks, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise SyntaxError(f"bad token at: {s[pos:pos + 30]!r}")
        pos = m.end()
        if m.group("hex") is not None:
            toks.append(("hex", bytes.fromhex(m.group("hex"))))
        elif m.group("str") is not None:
            toks.append(("str", m.group("str")))
        elif m.group("num") is not None:
            toks.append(("num", int(m.group("num"), 0)))
        elif m.group("id") is not None:
            toks.append(("id", m.group("id")))
        elif m.group("op") is not None:
            toks.append(("op", m.group("op")))
    return toks


class _Parser:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self, k=0):
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def eat(self, kind, val=None):
        k, v = self.next()
        if k != kind or (val is not None and v != val):
            raise SyntaxError(f"expected {kind} {val}, got {k} {v}")
        return v

    def at(self, kind, val=None):
        k, v = self.peek()
        return k == kind and (val is None or v == val)

    # expression grammar (loosest to tightest): && > cmp > | > & > shift
    # > additive > multiplicative > unary > postfix > primary. The emitted
    # sources parenthesize every mixed-precedence site, so only relative
    # order within each chain matters.
    def expr(self):
        e = self.cmp()
        while self.at("op", "&&"):
            self.next()
            e = ("bin", "&&", e, self.cmp())
        return e

    def cmp(self):
        e = self.bitor()
        while self.at("op", "<") or self.at("op", "==") or \
                self.at("op", "!=") or self.at("op", ">"):
            op = self.next()[1]
            e = ("bin", op, e, self.bitor())
        return e

    def bitor(self):
        e = self.bitand()
        while self.at("op", "|"):
            self.next()
            e = ("bin", "|", e, self.bitand())
        return e

    def bitand(self):
        e = self.shift()
        while self.at("op", "&"):
            self.next()
            e = ("bin", "&", e, self.shift())
        return e

    def shift(self):
        e = self.add()
        while self.at("op", "<<") or self.at("op", ">>"):
            op = self.next()[1]
            e = ("bin", op, e, self.add())
        return e

    def add(self):
        e = self.mult()
        while self.at("op", "+") or self.at("op", "-"):
            op = self.next()[1]
            e = ("bin", op, e, self.mult())
        return e

    def mult(self):
        e = self.unary()
        while self.at("op", "*") or self.at("op", "/"):
            op = self.next()[1]
            e = ("bin", op, e, self.unary())
        return e

    def unary(self):
        if self.at("op", "!"):
            self.next()
            return ("not", self.unary())
        return self.postfix()

    def postfix(self):
        e = self.primary()
        while True:
            if self.at("op", "["):
                self.next()
                lo = self.expr()
                if self.at("op", ":"):
                    self.next()
                    hi = self.expr()
                    self.eat("op", "]")
                    e = ("slice", e, lo, hi)
                else:
                    self.eat("op", "]")
                    e = ("index", e, lo)
            elif self.at("op", "."):
                self.next()
                name = self.eat("id")
                if name == "length":
                    e = ("length", e)
                elif name == "encodePacked":  # abi.encodePacked(...)
                    self.eat("op", "(")
                    args = self._args()
                    e = ("packed", args)
                elif self.at("op", "("):      # method call: x.f(...)
                    self.next()
                    e = ("method", e, name, self._args())
                else:                         # struct member: x.f
                    e = ("member", e, name)
            else:
                return e

    def _args(self):
        args = []
        if not self.at("op", ")"):
            args.append(self.expr())
            while self.at("op", ","):
                self.next()
                args.append(self.expr())
        self.eat("op", ")")
        return args

    def primary(self):
        k, v = self.peek()
        if k == "num":
            self.next()
            return ("num", v)
        if k == "hex":
            self.next()
            return ("hexlit", v)
        if k == "op" and v == "(":
            self.next()
            e = self.expr()
            self.eat("op", ")")
            return e
        if k == "op" and v == "[":
            self.next()
            items = [self.expr()]
            while self.at("op", ","):
                self.next()
                items.append(self.expr())
            self.eat("op", "]")
            return ("arraylit", items)
        if k == "id":
            self.next()
            if self.at("op", "("):
                self.next()
                return ("call", v, self._args())
            return ("var", v)
        raise SyntaxError(f"unexpected {k} {v}")


# ======================================================================
# assembler
# ======================================================================

class Asm:
    """Instruction stream with symbolic labels and variable slots."""

    def __init__(self):
        self.items: list = []     # ("b", bytes)|("pushl", lbl)|("label", lbl)
        self._lbl = 0

    def op(self, *names):
        self.items.append(("b", bytes(OPS[n] for n in names)))

    def push(self, v: int):
        self.items.append(("b", _push_bytes(v)))

    def pushl(self, label: str):
        self.items.append(("pushl", label))

    def label(self, name: str):
        self.items.append(("label", name))
        self.op("JUMPDEST")

    def fresh_label(self, base: str) -> str:
        self._lbl += 1
        return f"{base}_{self._lbl}"

    def assemble(self) -> bytes:
        for width in (2, 3, 4):
            offs, size = {}, 0
            for it in self.items:
                if it[0] == "b":
                    size += len(it[1])
                elif it[0] == "pushl":
                    size += 1 + width
                else:
                    offs[it[1]] = size
            if size < (1 << (8 * width)):
                out = bytearray()
                for it in self.items:
                    if it[0] == "b":
                        out += it[1]
                    elif it[0] == "pushl":
                        out.append(0x5F + width)
                        out += offs[it[1]].to_bytes(width, "big")
                return bytes(out)
        raise AssertionError("code too large to assemble")


# ======================================================================
# compiler
# ======================================================================

class _Compiler:
    def __init__(self, consts: dict, num_instances: int):
        self.a = Asm()
        self.consts = consts
        self.num_instances = num_instances
        self.slots: dict[str, int] = {}   # name -> offset
        self.arrays: dict[str, int] = {}  # name -> length (slots)
        self.bytes_var: str | None = None
        self.next_off = VARS_BASE
        self.revert_msgs: dict[str, str] = {}   # msg -> label
        self.used_subs: set[str] = set()
        self.instbuf = None               # data offset for the bytes var

    # ---- slot management -------------------------------------------
    def slot(self, name: str, length: int = 1) -> int:
        if name not in self.slots:
            self.slots[name] = self.next_off
            self.next_off += 32 * length
            if length > 1:
                self.arrays[name] = length
        return self.slots[name]

    def is_array(self, name: str) -> bool:
        return name in self.arrays

    # ---- expression compilation ------------------------------------
    def const_word(self, name: str):
        """Emit a contract-level constant."""
        if name == "R_MOD":
            self.a.push(CONST_R)
            self.a.op("MLOAD")
        elif name == "Q_MOD":
            self.a.push(CONST_Q)
            self.a.op("MLOAD")
        else:
            v = self.consts[name]
            self.a.push(v if isinstance(v, int)
                        else int.from_bytes(v, "big"))

    def eval_scalar(self, e):
        """Compile e, leaving exactly one word on the stack."""
        a = self.a
        kind = e[0]
        if kind == "num":
            a.push(e[1])
        elif kind == "var":
            name = e[1]
            if name in self.consts or name in ("R_MOD", "Q_MOD"):
                self.const_word(name)
            elif self.is_array(name):
                raise SyntaxError(f"array {name} used as scalar")
            else:
                a.push(self.slot(name))
                a.op("MLOAD")
        elif kind == "bin":
            self.eval_bin(e)
        elif kind == "not":
            self.eval_scalar(e[1])
            a.op("ISZERO")
        elif kind == "length":
            base = e[1]
            assert base[0] == "var"
            if base[1] == "instances":
                a.push(INSTLEN)
            elif base[1] == "proof":
                a.push(PROOFLEN)
            elif base[1] == self.bytes_var:
                a.push(self.slot(self.bytes_var))
            else:
                raise SyntaxError(f"length of {base[1]}")
            a.op("MLOAD")
        elif kind == "index":
            self.eval_index(e)
        elif kind == "call":
            self.eval_call(e)
        elif kind == "slice":
            # bare slice in scalar context: 32-byte calldata word
            self.eval_slice_word(e)
        else:
            raise SyntaxError(f"scalar: {e}")

    def eval_bin(self, e):
        _, op, l, r = e
        a = self.a
        if op in ("+", "-", "*", "/", "&", "|"):
            # EVM binops pop (top, next) as (a, b) -> a op b
            self.eval_scalar(r)
            self.eval_scalar(l)
            a.op({"+": "ADD", "-": "SUB", "*": "MUL", "/": "DIV",
                  "&": "AND", "|": "OR"}[op])
        elif op in ("<<", ">>"):
            self.eval_scalar(l)          # value
            self.eval_scalar(r)          # shift (top)
            a.op("SHL" if op == "<<" else "SHR")
        elif op == "<":
            self.eval_scalar(r)
            self.eval_scalar(l)
            a.op("LT")
        elif op == ">":
            self.eval_scalar(r)
            self.eval_scalar(l)
            a.op("GT")
        elif op == "==":
            self.eval_scalar(l)
            self.eval_scalar(r)
            a.op("EQ")
        elif op == "!=":
            self.eval_scalar(l)
            self.eval_scalar(r)
            a.op("EQ", "ISZERO")
        elif op == "&&":
            self.eval_scalar(l)
            self.eval_scalar(r)
            a.op("AND")
        else:
            raise SyntaxError(f"binop {op}")

    def eval_index(self, e):
        _, base, idx = e
        a = self.a
        assert base[0] == "var"
        name = base[1]
        if name == "instances":
            self.eval_scalar(idx)
            a.push(5)
            a.op("SHL")
            a.push(INSTDATA)
            a.op("MLOAD", "ADD", "CALLDATALOAD")
        elif name == "proof":
            raise SyntaxError("proof must be sliced, not indexed")
        elif self.is_array(name):
            if idx[0] == "num":
                a.push(self.slot(name) + 32 * idx[1])
            else:
                self.eval_scalar(idx)
                a.push(5)
                a.op("SHL")
                a.push(self.slot(name))
                a.op("ADD")
            a.op("MLOAD")
        elif name == "t":
            raise SyntaxError("t[] must be declared before use")
        else:
            raise SyntaxError(f"index into {name}")

    def eval_slice_word(self, e):
        """proof[a:b] with b-a == 32 as a calldata word."""
        _, base, lo, hi = e
        assert base == ("var", "proof"), f"slice of {base}"
        if lo[0] == "num" and hi[0] == "num":
            assert hi[1] - lo[1] == 32, "scalar slice must be 32 bytes"
            self.a.push(PROOFDATA)
            self.a.op("MLOAD")
            if lo[1]:
                self.a.push(lo[1])
                self.a.op("ADD")
        else:
            # dynamic offset (eval-canonicity loop): hi must be lo+32
            self.eval_scalar(lo)
            self.a.push(PROOFDATA)
            self.a.op("MLOAD", "ADD")
        self.a.op("CALLDATALOAD")

    def eval_pair(self, e):
        """Compile a G1-point expression: two words, y on top."""
        a = self.a
        if e[0] == "arraylit":
            assert len(e[1]) == 2
            self.eval_scalar(e[1][0])
            self.eval_scalar(e[1][1])
        elif e[0] == "var" and self.is_array(e[1]):
            base = self.slot(e[1])
            a.push(base)
            a.op("MLOAD")
            a.push(base + 32)
            a.op("MLOAD")
        elif e[0] == "call" and e[1] in ("_ecMul", "_ecAdd", "_negPt"):
            self.eval_call(e)
        else:
            raise SyntaxError(f"pair: {e}")

    def call_sub(self, name: str, nargs_push):
        """Internal-call convention: [ret, args...] -> sub -> [rets...]."""
        a = self.a
        ret = a.fresh_label(f"ret_{name}")
        a.pushl(ret)
        nargs_push()
        a.pushl(f"sub_{name}")
        a.op("JUMP")
        a.label(ret)
        self.used_subs.add(name)

    def eval_call(self, e):
        _, fname, args = e
        a = self.a
        if fname in ("mulmod", "addmod"):
            self.eval_scalar(args[2])
            self.eval_scalar(args[1])
            self.eval_scalar(args[0])
            a.op("MULMOD" if fname == "mulmod" else "ADDMOD")
        elif fname in ("uint256", "bytes32"):
            self.eval_scalar(args[0])
        elif fname == "_inv":
            self.call_sub("inv", lambda: self.eval_scalar(args[0]))
        elif fname == "_pow":
            def push_args():
                self.eval_scalar(args[0])
                self.eval_scalar(args[1])
            self.call_sub("pow", push_args)
        elif fname == "_wide":
            self.call_sub("wide", lambda: self.eval_scalar(args[0]))
        elif fname == "_ecMul":
            def push_args():
                self.eval_pair(args[0])
                self.eval_scalar(args[1])
            self.call_sub("ecmul", push_args)
        elif fname == "_ecAdd":
            def push_args():
                self.eval_pair(args[0])
                self.eval_pair(args[1])
            self.call_sub("ecadd", push_args)
        elif fname == "_negPt":
            self.call_sub("negpt", lambda: self.eval_pair(args[0]))
        elif fname == "_pairing":
            assert args[0][0] == "var" and self.arrays.get(args[0][1]) == 12
            self.call_sub(
                "pairing", lambda: a.push(self.slot(args[0][1])))
        elif fname == "keccak256":
            assert args[0][0] == "packed"
            self.eval_packed_keccak(args[0][1])
        else:
            raise SyntaxError(f"call {fname}")

    # ---- abi.encodePacked staging ----------------------------------
    def _cur_load(self):
        self.a.push(CUR)
        self.a.op("MLOAD")

    def _cur_add(self, n: int):
        a = self.a
        a.push(CUR)
        a.op("MLOAD")
        a.push(n)
        a.op("ADD")
        a.push(CUR)
        a.op("MSTORE")

    def eval_packed_keccak(self, chunks):
        """keccak256(abi.encodePacked(...)) -> hash word on the stack."""
        a = self.a
        a.pushl("__absorb")          # runtime-resolved absorb base
        a.push(CUR)
        a.op("MSTORE")
        for ch in chunks:
            self.write_chunk(ch)
        # size = CUR - base ; SHA3(base, size)
        a.pushl("__absorb")
        a.push(CUR)
        a.op("MLOAD", "SUB")         # size = cur - base
        a.pushl("__absorb")
        a.op("SHA3")

    def write_chunk(self, ch):
        a = self.a
        if ch[0] == "hexlit":
            assert len(ch[1]) == 1, "only single-byte hex literals"
            a.push(ch[1][0])
            self._cur_load()
            a.op("MSTORE8")
            self._cur_add(1)
        elif ch[0] == "call" and ch[1] == "uint32":
            assert ch[2][0][0] == "num"
            a.push(ch[2][0][1] << 224)
            self._cur_load()
            a.op("MSTORE")
            self._cur_add(4)
        elif ch[0] == "slice":
            _, base, lo, hi = ch
            assert base == ("var", "proof")
            assert lo[0] == "num" and hi[0] == "num", "absorb slice static"
            size = hi[1] - lo[1]
            a.push(size)
            a.push(PROOFDATA)
            a.op("MLOAD")
            if lo[1]:
                a.push(lo[1])
                a.op("ADD")
            self._cur_load()
            a.op("CALLDATACOPY")
            self._cur_add(size)
        elif ch[0] == "var" and ch[1] == self.bytes_var:
            self.write_bytes_copy()
        else:
            # 32-byte word chunk (h, VK_DIGEST, bytes32(instances[i]), ...)
            self.eval_scalar(ch)
            self._cur_load()
            a.op("MSTORE")
            self._cur_add(32)

    def write_bytes_copy(self):
        """Append the bytes var to the absorb buffer (word-loop copy)."""
        a = self.a
        lenslot = self.slot(self.bytes_var)
        j = self.slot("__copy_j")
        loop = a.fresh_label("bcopy")
        done = a.fresh_label("bcopy_done")
        a.push(0)
        a.push(j)
        a.op("MSTORE")
        a.label(loop)
        # while j < len
        a.push(lenslot)
        a.op("MLOAD")
        a.push(j)
        a.op("MLOAD", "LT", "ISZERO")
        a.pushl(done)
        a.op("JUMPI")
        # mem[cur + j] = instbuf[j]
        a.push(j)
        a.op("MLOAD")
        a.pushl("__instbuf")
        a.op("ADD", "MLOAD")         # value
        a.push(j)
        a.op("MLOAD")
        self._cur_load()
        a.op("ADD", "MSTORE")
        # j += 32
        a.push(j)
        a.op("MLOAD")
        a.push(32)
        a.op("ADD")
        a.push(j)
        a.op("MSTORE")
        a.pushl(loop)
        a.op("JUMP")
        a.label(done)
        # cur += len (exact byte length)
        a.push(lenslot)
        a.op("MLOAD")
        a.push(CUR)
        a.op("MLOAD", "ADD")
        a.push(CUR)
        a.op("MSTORE")

    # ---- statements -------------------------------------------------
    def store_scalar(self, name: str):
        self.a.push(self.slot(name))
        self.a.op("MSTORE")

    def store_pair(self, name: str):
        base = self.slot(name, 2)
        self.a.push(base + 32)
        self.a.op("MSTORE")          # y (top)
        self.a.push(base)
        self.a.op("MSTORE")          # x

    def revert_label(self, msg: str) -> str:
        if msg not in self.revert_msgs:
            self.revert_msgs[msg] = f"rev_{len(self.revert_msgs)}"
        return self.revert_msgs[msg]

    def emit_require(self, cond, msg: str):
        self.eval_scalar(cond)
        self.a.op("ISZERO")
        self.a.pushl(self.revert_label(msg))
        self.a.op("JUMPI")

    def emit_revert_stubs(self):
        a = self.a
        for msg, lbl in self.revert_msgs.items():
            a.label(lbl)
            data = msg.encode()
            a.push(0x08C379A0)       # Error(string) selector (right-aligned)
            a.push(0)
            a.op("MSTORE")
            a.push(0x20)
            a.push(0x20)
            a.op("MSTORE")
            a.push(len(data))
            a.push(0x40)
            a.op("MSTORE")
            a.push(int.from_bytes(data.ljust(32, b"\x00"), "big"))
            a.push(0x60)
            a.op("MSTORE")
            a.push(0x64)             # 4 + 3*32
            a.push(0x1C)
            a.op("REVERT")

    def emit_return_bool_stubs(self):
        a = self.a
        a.label("ret_false")
        a.push(0)
        a.push(0)
        a.op("MSTORE")
        a.push(32)
        a.push(0)
        a.op("RETURN")

    # ---- subroutines -------------------------------------------------
    def _staticcall(self, addr: int, in_off: int, in_size: int,
                    out_off: int, out_size: int, fail_msg: str):
        a = self.a
        a.push(out_size)
        a.push(out_off)
        a.push(in_size)
        a.push(in_off)
        a.push(addr)
        a.op("GAS", "STATICCALL", "ISZERO")
        a.pushl(self.revert_label(fail_msg))
        a.op("JUMPI")

    def emit_subs(self):
        a = self.a
        R = self.consts["R_MOD"]
        if "inv" in self.used_subs or "pow" in self.used_subs:
            # inv(a) = pow(a, R-2); falls through into pow
            a.label("sub_inv")       # [ret, a]
            a.op("DUP1", "ISZERO")
            a.pushl(self.revert_label("inv(0)"))
            a.op("JUMPI")
            a.push(R - 2)            # [ret, a, e]
            a.label("sub_pow")       # [ret, base, e]
            a.push(CALLBUF + 128)
            a.op("MSTORE")           # e
            a.push(CALLBUF + 96)
            a.op("MSTORE")           # base
            a.push(32)
            a.push(CALLBUF)
            a.op("MSTORE")
            a.push(32)
            a.push(CALLBUF + 32)
            a.op("MSTORE")
            a.push(32)
            a.push(CALLBUF + 64)
            a.op("MSTORE")
            self.const_word("R_MOD")
            a.push(CALLBUF + 160)
            a.op("MSTORE")
            self._staticcall(5, CALLBUF, 192, CALLBUF, 32, "modexp")
            a.push(CALLBUF)
            a.op("MLOAD")            # [ret, r]
            a.op("SWAP1", "JUMP")
            self.used_subs.add("pow")
        if "wide" in self.used_subs:
            # wide(h) = addmod(mulmod(h % R, POW256, R), keccak(h) % R, R)
            a.label("sub_wide")      # [ret, h]
            a.op("DUP1")
            a.push(SCRATCH)
            a.op("MSTORE")
            self.const_word("R_MOD")
            a.op("SWAP1", "MOD")     # h % R
            a.push(self.consts["POW256"])
            self.const_word("R_MOD")
            a.op("SWAP2", "MULMOD")  # [ret, hi_term]
            self.const_word("R_MOD")
            a.op("SWAP1")            # [ret, R, hi]
            a.push(32)
            a.push(SCRATCH)
            a.op("SHA3")             # keccak(h)
            self.const_word("R_MOD")
            a.op("SWAP1", "MOD")     # lo % R
            a.op("ADDMOD")           # [ret, r]
            a.op("SWAP1", "JUMP")
        if "ecmul" in self.used_subs:
            a.label("sub_ecmul")     # [ret, px, py, s]
            a.push(CALLBUF + 64)
            a.op("MSTORE")
            a.push(CALLBUF + 32)
            a.op("MSTORE")
            a.push(CALLBUF)
            a.op("MSTORE")
            self._staticcall(7, CALLBUF, 96, CALLBUF, 64, "ecMul")
            a.push(CALLBUF)
            a.op("MLOAD")            # rx
            a.push(CALLBUF + 32)
            a.op("MLOAD")            # ry  [ret, rx, ry]
            a.op("SWAP1", "SWAP2", "JUMP")   # -> [rx, ry] (y on top)
        if "ecadd" in self.used_subs:
            a.label("sub_ecadd")     # [ret, px, py, qx, qy]
            a.push(CALLBUF + 96)
            a.op("MSTORE")
            a.push(CALLBUF + 64)
            a.op("MSTORE")
            a.push(CALLBUF + 32)
            a.op("MSTORE")
            a.push(CALLBUF)
            a.op("MSTORE")
            self._staticcall(6, CALLBUF, 128, CALLBUF, 64, "ecAdd")
            a.push(CALLBUF)
            a.op("MLOAD")
            a.push(CALLBUF + 32)
            a.op("MLOAD")
            a.op("SWAP1", "SWAP2", "JUMP")   # [ret,rx,ry] -> [rx,ry]
        if "negpt" in self.used_subs:
            a.label("sub_negpt")     # [ret, px, py]
            skip = a.fresh_label("neg_zero")
            a.op("DUP2", "DUP2", "OR", "ISZERO")
            a.pushl(skip)
            a.op("JUMPI")
            self.const_word("Q_MOD")
            a.op("SUB")              # py' = Q - py
            a.label(skip)
            a.op("SWAP1", "SWAP2", "JUMP")   # [ret,px,py] -> [px,py]
        if "pairing" in self.used_subs:
            a.label("sub_pairing")   # [ret, base]
            a.push(32)
            a.push(SCRATCH)
            a.push(384)
            a.op("DUP4")             # base (below the 3 pushed words)
            a.push(8)
            a.op("GAS", "STATICCALL", "ISZERO")
            a.pushl(self.revert_label("pairing"))
            a.op("JUMPI")
            a.op("POP")              # drop base
            a.push(SCRATCH)
            a.op("MLOAD")
            a.push(1)
            a.op("EQ", "SWAP1", "JUMP")


# ======================================================================
# statement-level compilation of the verify() body
# ======================================================================

def _parse_line(line: str):
    return _Parser(_tokenize(line))


def _compile_body(c: _Compiler, lines: list[str]):
    a = c.a
    blocks: list = []      # ("scope",) | ("loop", var, step, limit_expr,
    #                         start_lbl, end_lbl)
    i = 0
    while i < len(lines):
        s = lines[i].strip()
        i += 1
        if not s or s.startswith("//"):
            continue
        if s == "{":
            blocks.append(("scope",))
            continue
        if s == "}":
            blk = blocks.pop()
            if blk[0] == "loop":
                _, var, step, start, end = blk
                a.push(c.slot(var))
                a.op("MLOAD")
                a.push(step)
                a.op("ADD")
                a.push(c.slot(var))
                a.op("MSTORE")
                a.pushl(start)
                a.op("JUMP")
                a.label(end)
            continue

        # ---- for loops ----
        m = re.match(r"for \(uint256 (\w+) = (\w+); \1 < ([\w.]+); "
                     r"\1(\+\+|\s*\+= 32)\) \{( .* )?\}?$", s)
        if m:
            var, init, limit, stepw, inline = m.groups()
            step = 1 if stepw == "++" else 32
            a.push(int(init, 0))
            a.push(c.slot(var))
            a.op("MSTORE")
            start = a.fresh_label("loop")
            end = a.fresh_label("loop_end")
            a.label(start)
            if limit == "instances.length":
                a.push(INSTLEN)
                a.op("MLOAD")
            else:
                a.push(int(limit, 0))
            a.push(c.slot(var))
            a.op("MLOAD", "LT", "ISZERO")
            a.pushl(end)
            a.op("JUMPI")
            if inline is not None and inline.strip():
                _compile_stmt(c, inline.strip())
                a.push(c.slot(var))
                a.op("MLOAD")
                a.push(step)
                a.op("ADD")
                a.push(c.slot(var))
                a.op("MSTORE")
                a.pushl(start)
                a.op("JUMP")
                a.label(end)
            else:
                blocks.append(("loop", var, step, start, end))
            continue

        _compile_stmt(c, s)
    assert not blocks, "unbalanced blocks"


def _compile_stmt(c: _Compiler, s: str):
    a = c.a
    s = s.strip()
    if s.endswith(";"):
        s = s[:-1]

    # guard return: if (!cond) { return false; }
    m = re.match(r"if \(!(.*)\) \{ return false; \}$", s)
    if m:
        cond = _parse_line(m.group(1)).expr()
        c.eval_scalar(cond)
        a.op("ISZERO")
        a.pushl("ret_false")
        a.op("JUMPI")
        return
    # require(cond, "msg")
    m = re.match(r'require\((.*), "(.*)"\)$', s)
    if m:
        c.emit_require(_parse_line(m.group(1)).expr(), m.group(2))
        return
    # returns
    if s == "return false":
        a.pushl("ret_false")
        a.op("JUMP")
        return
    m = re.match(r"return (.*)$", s)
    if m:
        c.eval_scalar(_parse_line(m.group(1)).expr())
        a.push(0)
        a.op("MSTORE")
        a.push(32)
        a.push(0)
        a.op("RETURN")
        return

    # declarations
    m = re.match(r"uint256\[(\d+)\] memory (\w+) = (.*)$", s)
    if m:
        n, name, rhs = int(m.group(1)), m.group(2), m.group(3)
        assert n == 2, "only pair initializers"
        c.slot(name, 2)
        c.eval_pair(_parse_line(rhs).expr())
        c.store_pair(name)
        return
    m = re.match(r"uint256\[(\d+)\] memory (\w+)$", s)
    if m:
        n, name = int(m.group(1)), m.group(2)
        c.slot(name, n)
        return                        # fresh memory is zero
    m = re.match(r"bytes memory (\w+)$", s)
    if m:
        assert c.bytes_var is None, "one bytes var supported"
        c.bytes_var = m.group(1)
        c.slot(c.bytes_var)           # length slot (zero-init by fiat)
        a.push(0)
        a.push(c.slot(c.bytes_var))
        a.op("MSTORE")
        return
    m = re.match(r"(?:uint256|bytes32) (\w+) = (.*)$", s)
    if m:
        name, rhs = m.group(1), m.group(2)
        c.eval_scalar(_parse_line(rhs).expr())
        c.store_scalar(name)
        return

    # assignments
    m = re.match(r"(\w+)\[(\d+)\] = (.*)$", s)
    if m:
        name, idx, rhs = m.group(1), int(m.group(2)), m.group(3)
        c.eval_scalar(_parse_line(rhs).expr())
        a.push(c.slot(name) + 32 * idx)
        a.op("MSTORE")
        return
    m = re.match(r"(\w+) = (.*)$", s)
    if m:
        name, rhs = m.group(1), m.group(2)
        e = _parse_line(rhs).expr()
        if name == c.bytes_var:
            # instAbsorb = abi.encodePacked(instAbsorb, hex"53", word)
            assert e[0] == "packed" and e[1][0] == ("var", name) and \
                e[1][1][0] == "hexlit" and len(e[1][1][1]) == 1, \
                f"unsupported bytes append: {s}"
            lenslot = c.slot(name)
            a.push(e[1][1][1][0])
            a.push(lenslot)
            a.op("MLOAD")
            a.pushl("__instbuf")
            a.op("ADD", "MSTORE8")
            c.eval_scalar(e[1][2])
            a.push(lenslot)
            a.op("MLOAD")
            a.pushl("__instbuf")
            a.op("ADD")
            a.push(1)
            a.op("ADD", "MSTORE")
            a.push(lenslot)
            a.op("MLOAD")
            a.push(33)
            a.op("ADD")
            a.push(lenslot)
            a.op("MSTORE")
            return
        if c.is_array(name):
            c.eval_pair(e)
            c.store_pair(name)
        else:
            c.eval_scalar(e)
            c.store_scalar(name)
        return
    raise SyntaxError(f"unhandled statement: {s}")


# ======================================================================
# public API
# ======================================================================

def compile_verifier(sol_src: str):
    """Compile a generated verifier contract to EVM bytecode.

    Returns (runtime_code, init_code, meta) where meta carries the layout
    facts a caller may want to report."""
    consts = {}
    for name in ("R_MOD", "Q_MOD", "POW256"):
        m = re.search(rf"constant {name} =\s*(0x[0-9a-fA-F]+)", sol_src)
        consts[name] = int(m.group(1), 16)
    for name in ("INIT_STATE", "VK_DIGEST"):
        m = re.search(rf"constant {name} =\s*(0x[0-9a-fA-F]+)", sol_src)
        consts[name] = int(m.group(1), 16)

    m = re.search(r"function verify\(.*?\{\n(.*)\n\s*\}\n\}", sol_src,
                  re.DOTALL)
    assert m, "verify body not found"
    body_lines = m.group(1).split("\n")
    m = re.search(r"require\(instances\.length == (\d+)", sol_src)
    assert m, "instance count not found"
    num_instances = int(m.group(1))

    c = _Compiler(consts, num_instances)
    a = c.a

    # ---- dispatcher ----
    from ..plonk.transcript import keccak256
    selector = int.from_bytes(keccak256(b"verify(uint256[],bytes)")[:4],
                              "big")
    a.push(4)
    a.op("CALLDATASIZE", "LT")
    a.pushl(c.revert_label("bad selector"))
    a.op("JUMPI")
    a.push(0)
    a.op("CALLDATALOAD")
    a.push(224)
    a.op("SHR")
    a.push(selector)
    a.op("EQ", "ISZERO")
    a.pushl(c.revert_label("bad selector"))
    a.op("JUMPI")
    # cache big constants in memory
    a.push(consts["R_MOD"])
    a.push(CONST_R)
    a.op("MSTORE")
    a.push(consts["Q_MOD"])
    a.push(CONST_Q)
    a.op("MSTORE")
    # ABI decode: verify(uint256[] instances, bytes proof)
    a.push(4)
    a.op("CALLDATALOAD")
    a.push(4)
    a.op("ADD")                       # &instances.len
    a.op("DUP1", "CALLDATALOAD")
    a.push(INSTLEN)
    a.op("MSTORE")
    a.push(32)
    a.op("ADD")
    a.push(INSTDATA)
    a.op("MSTORE")
    a.push(36)
    a.op("CALLDATALOAD")
    a.push(4)
    a.op("ADD")                       # &proof.len
    a.op("DUP1", "CALLDATALOAD")
    a.push(PROOFLEN)
    a.op("MSTORE")
    a.push(32)
    a.op("ADD")
    a.push(PROOFDATA)
    a.op("MSTORE")

    _compile_body(c, body_lines)
    # verify() always returns explicitly; falling off the end is a bug
    a.pushl(c.revert_label("no return"))
    a.op("JUMP")
    c.emit_return_bool_stubs()
    c.emit_subs()
    c.emit_revert_stubs()

    # ---- place the dynamic regions and resolve their labels ----
    instbuf = c.next_off
    absorb = instbuf + 33 * num_instances + 64
    for idx, it in enumerate(a.items):
        if it[0] == "pushl" and it[1] == "__instbuf":
            a.items[idx] = ("b", _push_bytes(instbuf))
        elif it[0] == "pushl" and it[1] == "__absorb":
            a.items[idx] = ("b", _push_bytes(absorb))

    runtime = a.assemble()
    init = _init_code(runtime)
    meta = {
        "runtime_bytes": len(runtime),
        "init_bytes": len(init),
        "eip170_ok": len(runtime) <= 24576,
        "num_slots": (c.next_off - VARS_BASE) // 32,
        "num_instances": num_instances,
    }
    return runtime, init, meta


def _push_bytes(v: int) -> bytes:
    """PUSH0 / minimal-width PUSHn encoding (single source for Asm.push
    and the late-bound __instbuf/__absorb patches)."""
    if v == 0:
        return bytes([0x5F])
    data = v.to_bytes((v.bit_length() + 7) // 8, "big")
    return bytes([0x5F + len(data)]) + data


def _init_code(runtime: bytes) -> bytes:
    a = Asm()
    a.push(len(runtime))
    a.op("DUP1")
    a.pushl("rt")
    a.push(0)
    a.op("CODECOPY")
    a.push(0)
    a.op("RETURN")
    a.label("rt")
    head = a.assemble()
    # the label points at the JUMPDEST we appended; strip it and use its
    # offset as the runtime blob start
    return head[:-1] + runtime


def vm_verify(sol_src: str, instances: list, proof: bytes,
              gas_limit: int = 500_000_000, tamper_byte: int | None = None):
    """Compile + execute a generated verifier on the real EVM.

    Returns a dict: ok, gas_used (execution), gas_total (with intrinsic),
    runtime_bytes, eip170_ok, revert (decoded reason or None). With
    tamper_byte set, the same compiled bytecode is also run against the
    proof with that byte flipped and `tamper_rejected` is reported."""
    from . import codegen, vm
    runtime, init, meta = compile_verifier(sol_src)

    def run(pf: bytes):
        calldata = codegen.encode_calldata(instances, pf)
        ok, out, gas_used = vm.execute(runtime, calldata, gas_limit)
        result = bool(ok and len(out) >= 32
                      and int.from_bytes(out[-32:], "big"))
        return result, ok, out, gas_used, calldata

    result, ok, out, gas_used, calldata = run(proof)
    r = {
        "ok": result,
        "reverted": not ok,
        "revert": vm.revert_reason(out) if not ok else None,
        "gas_execution": gas_used,
        "gas_total": gas_used + vm.tx_intrinsic_gas(calldata),
        "runtime_bytes": meta["runtime_bytes"],
        "eip170_ok": meta["eip170_ok"],
    }
    if tamper_byte is not None:
        bad = bytearray(proof)
        bad[tamper_byte] ^= 1
        r["tamper_rejected"] = not run(bytes(bad))[0]
    return r

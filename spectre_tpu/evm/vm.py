"""A real EVM: bytecode interpreter with mainnet gas metering + precompiles.

Reference parity: the reference executes its generated Yul verifier inside
revm (`prover/src/cli.rs:249-277`, SURVEY.md N11) to report gas and code
size. This module is that executor for the offline TPU environment: a
stack-machine EVM sufficient for the verifier contracts this repo's own
compiler (`evm/solc.py`) emits — executed from BYTECODE, with the
post-Berlin/London gas schedule (EIP-150/1108/2028/2565/2929) metered per
opcode, real memory-expansion costs, and the BN254/keccak/modexp
precompiles backed by `fields/bn254`.

Scope: the opcode subset the compiled verifier and protocol contracts use
— storage (SLOAD/SSTORE with EIP-2929+2200 pricing and revert journaling),
CALL/STATICCALL between World-deployed contracts and precompiles, but no
CREATE family, no logs, no value transfers. Unknown opcodes raise —
execution of arbitrary mainnet contracts is a non-goal; metering realism on
OUR contracts is the goal. (Known simplification: SSTORE refunds for
clearing slots are tracked and capped per EIP-3529, but other refund
sources are not modeled.)

Gas notes:
- precompile addresses are warm by definition (EIP-2929) — STATICCALL to
  them costs 100 base + the precompile's own price;
- memory expansion: 3w + floor(w^2/512) charged on the high-water word;
- the 63/64 rule applies to the gas forwarded by STATICCALL;
- intrinsic transaction gas (21000 + calldata bytes) is accounted by
  `tx_intrinsic_gas` so callers can report an end-to-end number.
"""

from __future__ import annotations

from ..fields import bn254
from ..plonk.transcript import keccak256

R = bn254.R
Q = bn254.P
U256 = (1 << 256) - 1


class EvmError(Exception):
    """Abnormal halt (invalid op, stack underflow, bad jump, OOG)."""


class _Frame:
    __slots__ = ("stack", "mem", "gas", "code", "pc", "calldata",
                 "returndata", "jumpdests", "mem_words", "world", "address",
                 "caller", "static")

    def __init__(self, code: bytes, calldata: bytes, gas: int, world=None,
                 address: int = 0, caller: int = 0, static: bool = False,
                 jumpdests: set | None = None):
        self.code = code
        self.calldata = calldata
        self.gas = gas
        self.stack: list[int] = []
        self.mem = bytearray()
        self.mem_words = 0
        self.pc = 0
        self.returndata = b""
        self.jumpdests = _jumpdests(code) if jumpdests is None else jumpdests
        self.world = world
        self.address = address
        self.caller = caller
        self.static = static


def _jumpdests(code: bytes) -> set:
    dests = set()
    i = 0
    while i < len(code):
        op = code[i]
        if op == 0x5B:
            dests.add(i)
        if 0x60 <= op <= 0x7F:
            i += op - 0x5F
        i += 1
    return dests


# ---- gas schedule (post-London mainnet) ----
G_VERYLOW, G_LOW, G_MID, G_HIGH = 3, 5, 8, 10
G_BASE, G_JUMPDEST, G_SHA3, G_SHA3WORD, G_COPY = 2, 1, 30, 6, 3
G_WARMACCESS = 100

_GAS = {}
for _op in (0x01, 0x03, 0x10, 0x11, 0x12, 0x13, 0x14, 0x15, 0x16, 0x17,
            0x18, 0x19, 0x1A, 0x1B, 0x1C, 0x1D, 0x35, 0x51, 0x52, 0x53):
    _GAS[_op] = G_VERYLOW          # add/sub/cmp/bit/shift/calldataload/mem
for _op in (0x02, 0x04, 0x05, 0x06, 0x07, 0x0B):
    _GAS[_op] = G_LOW              # mul/div/mod/signextend
for _op in (0x08, 0x09, 0x56):
    _GAS[_op] = G_MID              # addmod/mulmod/jump
_GAS[0x57] = G_HIGH                # jumpi
for _op in (0x30, 0x32, 0x33, 0x34, 0x36, 0x38, 0x3A, 0x3D, 0x41, 0x42,
            0x43, 0x44, 0x45, 0x46, 0x48, 0x50, 0x58, 0x59, 0x5A):
    _GAS[_op] = G_BASE
_GAS[0x5B] = G_JUMPDEST
_GAS[0x5F] = G_BASE                # PUSH0


def _mem_gas(words: int) -> int:
    return 3 * words + words * words // 512


def _charge(fr: _Frame, amount: int):
    fr.gas -= amount
    if fr.gas < 0:
        raise EvmError("out of gas")


def _expand(fr: _Frame, offset: int, size: int):
    """Charge memory expansion and grow the backing buffer."""
    if size == 0:
        return
    if offset + size > (1 << 32):
        raise EvmError("memory offset too large")
    words = (offset + size + 31) // 32
    if words > fr.mem_words:
        _charge(fr, _mem_gas(words) - _mem_gas(fr.mem_words))
        fr.mem_words = words
    need = words * 32
    if len(fr.mem) < need:
        fr.mem.extend(b"\x00" * (need - len(fr.mem)))


def _g2(words):
    # precompile ordering: (x_c1, x_c0, y_c1, y_c0)
    return (bn254.Fq2([int(words[1]), int(words[0])]),
            bn254.Fq2([int(words[3]), int(words[2])]))


def _modexp_gas(bsize: int, esize: int, msize: int, ehead: int) -> int:
    """EIP-2565."""
    words = (max(bsize, msize) + 7) // 8
    mult = words * words
    if esize <= 32:
        iters = max(ehead.bit_length() - 1, 0)
    else:
        iters = 8 * (esize - 32) + max(ehead.bit_length() - 1, 0)
    iters = max(iters, 1)
    return max(200, mult * iters // 3)


def _precompile(addr: int, data: bytes, gas: int):
    """Returns (ok, returndata, gas_used); ok=False consumes all gas."""
    g1 = bn254.g1_curve

    if addr == 0x02:               # SHA-256
        import hashlib
        cost = 60 + 12 * ((len(data) + 31) // 32)
        if cost > gas:
            return False, b"", gas
        return True, hashlib.sha256(data).digest(), cost

    def word(i):
        return int.from_bytes(data[32 * i:32 * i + 32].ljust(32, b"\x00"),
                              "big")

    def to_pt(x, y):
        if x == 0 and y == 0:
            return None
        if x >= Q or y >= Q:
            raise ValueError("coordinate out of range")
        pt = (bn254.Fq(x), bn254.Fq(y))
        if not g1.is_on_curve(pt):
            raise ValueError("not on curve")
        return pt

    def from_pt(pt):
        if pt is None:
            return b"\x00" * 64
        return int(pt[0]).to_bytes(32, "big") + int(pt[1]).to_bytes(32, "big")

    if addr == 0x05:               # modexp (EIP-2565)
        bsize, esize, msize = word(0), word(1), word(2)
        if max(bsize, esize, msize) > 1024:
            return False, b"", gas
        body = data[96:].ljust(bsize + esize + msize, b"\x00")
        ehead = int.from_bytes(body[bsize:bsize + min(esize, 32)], "big")
        cost = _modexp_gas(bsize, esize, msize, ehead)
        if cost > gas:
            return False, b"", gas
        b = int.from_bytes(body[:bsize], "big")
        e = int.from_bytes(body[bsize:bsize + esize], "big")
        m = int.from_bytes(body[bsize + esize:bsize + esize + msize], "big")
        out = (pow(b, e, m) if m else 0).to_bytes(msize, "big") if msize \
            else b""
        return True, out, cost
    if addr == 0x06:               # bn254 ecAdd (EIP-1108: 150)
        if gas < 150:
            return False, b"", gas
        try:
            p = to_pt(word(0), word(1))
            q2 = to_pt(word(2), word(3))
        except ValueError:
            return False, b"", gas
        return True, from_pt(g1.add(p, q2)), 150
    if addr == 0x07:               # bn254 ecMul (EIP-1108: 6000)
        if gas < 6000:
            return False, b"", gas
        try:
            p = to_pt(word(0), word(1))
        except ValueError:
            return False, b"", gas
        return True, from_pt(g1.mul_unsafe(p, word(2) % R)), 6000
    if addr == 0x08:               # bn254 pairing (EIP-1108)
        if len(data) % 192:
            return False, b"", gas
        k = len(data) // 192
        cost = 45000 + 34000 * k
        if cost > gas:
            return False, b"", gas
        pairs = []
        for i in range(k):
            w = [word(6 * i + j) for j in range(6)]
            try:
                p = to_pt(w[0], w[1])
            except ValueError:
                return False, b"", gas
            if any(v >= Q for v in w[2:]):
                return False, b"", gas
            g2pt = _g2(w[2:]) if any(w[2:]) else None
            if g2pt is not None:
                g2c = bn254.g2_curve
                if not g2c.is_on_curve(g2pt):
                    return False, b"", gas
                # EIP-197 requires order-r subgroup membership for G2
                if g2c.mul_unsafe(g2pt, R) is not None:
                    return False, b"", gas
            if p is None or g2pt is None:
                continue           # infinity factors contribute 1
            pairs.append((p, g2pt))
        ok = bn254.pairing_check(pairs) if pairs else True
        return True, (1 if ok else 0).to_bytes(32, "big"), cost
    raise EvmError(f"unsupported precompile 0x{addr:x}")


def execute(code: bytes, calldata: bytes, gas: int = 30_000_000,
            world=None, address: int = 0, caller: int = 0,
            static: bool = False):
    """Run `code` as a message call. Returns (success, returndata, gas_used).

    success=False covers both REVERT (returndata = revert payload) and
    abnormal halts (returndata = b"", all gas consumed)."""
    fr = _Frame(code, calldata, gas, world=world, address=address,
                caller=caller, static=static)
    try:
        out = _run(fr)
        return True, out, gas - fr.gas
    except _Revert as rv:
        return False, rv.data, gas - fr.gas
    except EvmError:
        return False, b"", gas


class _Revert(Exception):
    def __init__(self, data: bytes):
        self.data = data


class _Return(Exception):
    def __init__(self, data: bytes):
        self.data = data


def _run(fr: _Frame) -> bytes:
    code = fr.code
    stack = fr.stack
    try:
        while fr.pc < len(code):
            op = code[fr.pc]
            fr.pc += 1
            base = _GAS.get(op)
            if base is not None:
                _charge(fr, base)
            if 0x60 <= op <= 0x7F:             # PUSH1..PUSH32
                n = op - 0x5F
                _charge(fr, G_VERYLOW)
                stack.append(
                    int.from_bytes(code[fr.pc:fr.pc + n].ljust(n, b"\x00"),
                                   "big"))
                fr.pc += n
            elif 0x80 <= op <= 0x8F:           # DUP1..DUP16
                _charge(fr, G_VERYLOW)
                stack.append(stack[-(op - 0x7F)])
            elif 0x90 <= op <= 0x9F:           # SWAP1..SWAP16
                _charge(fr, G_VERYLOW)
                n = op - 0x8F
                stack[-1], stack[-n - 1] = stack[-n - 1], stack[-1]
            elif op == 0x5F:                   # PUSH0
                stack.append(0)
            elif op == 0x01:                   # ADD
                stack.append((stack.pop() + stack.pop()) & U256)
            elif op == 0x02:                   # MUL
                stack.append((stack.pop() * stack.pop()) & U256)
            elif op == 0x03:                   # SUB
                a = stack.pop()
                stack.append((a - stack.pop()) & U256)
            elif op == 0x04:                   # DIV
                a, b = stack.pop(), stack.pop()
                stack.append(a // b if b else 0)
            elif op == 0x06:                   # MOD
                a, b = stack.pop(), stack.pop()
                stack.append(a % b if b else 0)
            elif op == 0x08:                   # ADDMOD
                a, b, m = stack.pop(), stack.pop(), stack.pop()
                stack.append((a + b) % m if m else 0)
            elif op == 0x09:                   # MULMOD
                a, b, m = stack.pop(), stack.pop(), stack.pop()
                stack.append((a * b) % m if m else 0)
            elif op == 0x0A:                   # EXP
                a, e = stack.pop(), stack.pop()
                _charge(fr, 10 + 50 * ((e.bit_length() + 7) // 8))
                stack.append(pow(a, e, 1 << 256))
            elif op == 0x10:                   # LT
                a, b = stack.pop(), stack.pop()
                stack.append(1 if a < b else 0)
            elif op == 0x11:                   # GT
                a, b = stack.pop(), stack.pop()
                stack.append(1 if a > b else 0)
            elif op == 0x14:                   # EQ
                stack.append(1 if stack.pop() == stack.pop() else 0)
            elif op == 0x15:                   # ISZERO
                stack.append(1 if stack.pop() == 0 else 0)
            elif op == 0x16:                   # AND
                stack.append(stack.pop() & stack.pop())
            elif op == 0x17:                   # OR
                stack.append(stack.pop() | stack.pop())
            elif op == 0x18:                   # XOR
                stack.append(stack.pop() ^ stack.pop())
            elif op == 0x19:                   # NOT
                stack.append(stack.pop() ^ U256)
            elif op == 0x1A:                   # BYTE
                i, x = stack.pop(), stack.pop()
                stack.append((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
            elif op == 0x1B:                   # SHL
                s, v = stack.pop(), stack.pop()
                stack.append((v << s) & U256 if s < 256 else 0)
            elif op == 0x1C:                   # SHR
                s, v = stack.pop(), stack.pop()
                stack.append(v >> s if s < 256 else 0)
            elif op == 0x20:                   # SHA3
                off, size = stack.pop(), stack.pop()
                _charge(fr, G_SHA3 + G_SHA3WORD * ((size + 31) // 32))
                _expand(fr, off, size)
                stack.append(int.from_bytes(
                    keccak256(bytes(fr.mem[off:off + size])), "big"))
            elif op == 0x34:                   # CALLVALUE (always 0 here)
                stack.append(0)
            elif op == 0x35:                   # CALLDATALOAD
                off = stack.pop()
                stack.append(int.from_bytes(
                    fr.calldata[off:off + 32].ljust(32, b"\x00"), "big"))
            elif op == 0x36:                   # CALLDATASIZE
                stack.append(len(fr.calldata))
            elif op == 0x37:                   # CALLDATACOPY
                dst, src, size = stack.pop(), stack.pop(), stack.pop()
                _charge(fr, G_VERYLOW + G_COPY * ((size + 31) // 32))
                _expand(fr, dst, size)
                fr.mem[dst:dst + size] = \
                    fr.calldata[src:src + size].ljust(size, b"\x00")
            elif op == 0x38:                   # CODESIZE
                stack.append(len(code))
            elif op == 0x39:                   # CODECOPY
                dst, src, size = stack.pop(), stack.pop(), stack.pop()
                _charge(fr, G_VERYLOW + G_COPY * ((size + 31) // 32))
                _expand(fr, dst, size)
                fr.mem[dst:dst + size] = code[src:src + size].ljust(
                    size, b"\x00")
            elif op == 0x3D:                   # RETURNDATASIZE
                stack.append(len(fr.returndata))
            elif op == 0x3E:                   # RETURNDATACOPY
                dst, src, size = stack.pop(), stack.pop(), stack.pop()
                _charge(fr, G_VERYLOW + G_COPY * ((size + 31) // 32))
                if src + size > len(fr.returndata):
                    raise EvmError("returndatacopy out of bounds")
                _expand(fr, dst, size)
                fr.mem[dst:dst + size] = fr.returndata[src:src + size]
            elif op == 0x50:                   # POP
                stack.pop()
            elif op == 0x51:                   # MLOAD
                off = stack.pop()
                _expand(fr, off, 32)
                stack.append(int.from_bytes(fr.mem[off:off + 32], "big"))
            elif op == 0x52:                   # MSTORE
                off, val = stack.pop(), stack.pop()
                _expand(fr, off, 32)
                fr.mem[off:off + 32] = val.to_bytes(32, "big")
            elif op == 0x53:                   # MSTORE8
                off, val = stack.pop(), stack.pop()
                _expand(fr, off, 1)
                fr.mem[off] = val & 0xFF
            elif op == 0x56:                   # JUMP
                dst = stack.pop()
                if dst not in fr.jumpdests:
                    raise EvmError(f"bad jump dest {dst}")
                fr.pc = dst
            elif op == 0x57:                   # JUMPI
                dst, cond = stack.pop(), stack.pop()
                if cond:
                    if dst not in fr.jumpdests:
                        raise EvmError(f"bad jump dest {dst}")
                    fr.pc = dst
            elif op == 0x58:                   # PC
                stack.append(fr.pc - 1)
            elif op == 0x5A:                   # GAS
                stack.append(fr.gas)
            elif op == 0x5B:                   # JUMPDEST
                pass
            elif op in (0xFA, 0xF1):           # STATICCALL / CALL
                g, addr = stack.pop(), stack.pop()
                value = stack.pop() if op == 0xF1 else 0
                aoff, asize, roff, rsize = (stack.pop(), stack.pop(),
                                            stack.pop(), stack.pop())
                if value:
                    raise EvmError("value transfers unsupported")
                _expand(fr, aoff, asize)
                _expand(fr, roff, rsize)
                args = bytes(fr.mem[aoff:aoff + asize])
                if 1 <= addr <= 9:
                    _charge(fr, G_WARMACCESS)  # precompiles are always warm
                    avail = fr.gas - fr.gas // 64
                    sub_gas = min(g, avail)
                    ok, out, used = _precompile(addr, args, sub_gas)
                    _charge(fr, used if ok else sub_gas)
                elif fr.world is not None and addr in fr.world.contracts:
                    _charge(fr, fr.world.touch_address(addr))
                    avail = fr.gas - fr.gas // 64
                    sub_gas = min(g, avail)
                    ok, out, used = fr.world.message_call(
                        addr, args, sub_gas, caller=fr.address,
                        static=fr.static or op == 0xFA)
                    _charge(fr, used)
                else:
                    raise EvmError(f"call to unknown account {addr:#x}")
                fr.returndata = out
                # geth copies returndata into [roff, rsize) on success AND
                # on REVERT (exceptional halts return no data)
                n_copy = min(rsize, len(out))
                if n_copy:
                    fr.mem[roff:roff + n_copy] = out[:n_copy]
                stack.append(1 if ok else 0)
            elif op == 0x54:                   # SLOAD
                if fr.world is None:
                    raise EvmError("SLOAD without world state")
                key = stack.pop()
                _charge(fr, fr.world.touch_slot(fr.address, key))
                stack.append(
                    fr.world.contracts[fr.address].storage.get(key, 0))
            elif op == 0x55:                   # SSTORE (EIP-2200/2929/3529)
                if fr.world is None:
                    raise EvmError("SSTORE without world state")
                if fr.static:
                    raise EvmError("SSTORE in static context")
                key, val = stack.pop(), stack.pop()
                w = fr.world
                st = w.contracts[fr.address].storage
                cold = w.touch_slot(fr.address, key, base_charge=False)
                cur = st.get(key, 0)
                orig = w.tx_original(fr.address, key, cur)
                if val == cur:
                    cost = 100
                elif orig == cur:              # clean slot
                    cost = 20000 if orig == 0 else 2900
                    if orig != 0 and val == 0:
                        w.refund += 4800
                else:                          # dirty slot (EIP-3529 rules)
                    cost = 100
                    if orig != 0:
                        if cur == 0:           # un-clearing: revoke refund
                            w.refund -= 4800
                        elif val == 0:
                            w.refund += 4800
                    if val == orig:            # restored to original
                        w.refund += (20000 - 100) if orig == 0 \
                            else (2900 - 100)
                _charge(fr, cold + cost)
                if val:
                    st[key] = val
                else:
                    st.pop(key, None)
            elif op == 0x30:                   # ADDRESS
                stack.append(fr.address)
            elif op == 0x33:                   # CALLER
                stack.append(fr.caller)
            elif op == 0xF3:                   # RETURN
                off, size = stack.pop(), stack.pop()
                _expand(fr, off, size)
                raise _Return(bytes(fr.mem[off:off + size]))
            elif op == 0xFD:                   # REVERT
                off, size = stack.pop(), stack.pop()
                _expand(fr, off, size)
                raise _Revert(bytes(fr.mem[off:off + size]))
            elif op == 0x00:                   # STOP
                return b""
            else:
                raise EvmError(f"unsupported opcode 0x{op:02x} @ {fr.pc - 1}")
        return b""
    except _Return as r:
        return r.data
    except IndexError:
        raise EvmError("stack underflow")


def tx_intrinsic_gas(calldata: bytes) -> int:
    """21000 + EIP-2028 calldata pricing."""
    zeros = calldata.count(0)
    return 21000 + 4 * zeros + 16 * (len(calldata) - zeros)


def deploy(init_code: bytes, gas: int = 30_000_000):
    """Run standalone constructor code (no world state); returns
    (runtime_code, gas_used) with the 200/byte deposit (EIP-170 enforced).
    Storage-using constructors must deploy through World.deploy."""
    ok, runtime, used = execute(init_code, b"", gas)
    if not ok:
        raise EvmError("constructor reverted")
    return runtime, used + _enforce_code_deposit(runtime)


def revert_reason(returndata: bytes) -> str | None:
    """Decode Error(string) revert payloads."""
    if len(returndata) >= 68 and returndata[:4] == bytes.fromhex("08c379a0"):
        ln = int.from_bytes(returndata[36:68], "big")
        return returndata[68:68 + ln].decode("utf-8", "replace")
    return None


class Contract:
    __slots__ = ("code", "storage", "_jumpdests")

    def __init__(self, code: bytes):
        self.code = code
        self.storage: dict[int, int] = {}
        self._jumpdests = None

    def jumpdests(self) -> set:
        if self._jumpdests is None:
            self._jumpdests = _jumpdests(self.code)
        return self._jumpdests


def _enforce_code_deposit(runtime: bytes) -> int:
    """EIP-170 limit + EIP-3860-era 200/byte deposit gas."""
    if len(runtime) > 24576:
        raise EvmError(f"EIP-170: runtime code {len(runtime)} B > 24576 B")
    return 200 * len(runtime)


class World:
    """Minimal multi-contract chain state: deployed code + storage, the
    per-transaction EIP-2929 warm sets, EIP-2200 original-value tracking,
    and revert journaling. The stand-in for the reference's anvil node in
    contract tests (`contract-tests/tests/spectre.rs`)."""

    def __init__(self):
        self.contracts: dict[int, Contract] = {}
        self._next_addr = 0x1000
        self._warm_addrs: set[int] = set()
        self._warm_slots: set[tuple[int, int]] = set()
        self._tx_original: dict[tuple[int, int], int] = {}
        self.refund = 0

    # -- per-transaction accounting --
    def begin_tx(self):
        self._warm_addrs = set()
        self._warm_slots = set()
        self._tx_original = {}
        self.refund = 0

    def tx_original(self, addr: int, key: int, current: int) -> int:
        """Value of the slot at transaction start (EIP-2200)."""
        return self._tx_original.setdefault((addr, key), current)

    def touch_address(self, addr: int) -> int:
        if addr in self._warm_addrs:
            return G_WARMACCESS
        self._warm_addrs.add(addr)
        return 2600

    def touch_slot(self, addr: int, key: int,
                   base_charge: bool = True) -> int:
        """SLOAD price (base_charge=True): 2100 cold / 100 warm.
        SSTORE cold surcharge (base_charge=False): 2100 cold / 0 warm."""
        if (addr, key) in self._warm_slots:
            return G_WARMACCESS if base_charge else 0
        self._warm_slots.add((addr, key))
        return 2100

    # -- revert journaling: snapshot world-visible state per call frame --
    def _snapshot(self):
        return ({a: dict(c.storage) for a, c in self.contracts.items()},
                set(self._warm_addrs), set(self._warm_slots),
                dict(self._tx_original), self.refund)

    def _restore(self, snap):
        storages, warm_a, warm_s, orig, refund = snap
        for a, st in storages.items():
            self.contracts[a].storage = st
        self._warm_addrs = warm_a
        self._warm_slots = warm_s
        self._tx_original = orig
        self.refund = refund

    def deploy(self, init_code: bytes, ctor_args: bytes = b"",
               gas: int = 30_000_000,
               enforce_eip170: bool = True) -> tuple[int, int]:
        """Run constructor (args appended to init code, solc-style);
        registers the returned runtime. Returns (address, gas_used).

        enforce_eip170=False admits oversized runtimes a real chain would
        reject — for exercising verifiers whose measured size exceeds the
        limit (the measurement itself is the honest result; callers must
        record it)."""
        addr = self._next_addr
        self._next_addr += 1
        self.contracts[addr] = Contract(b"")   # storage visible to ctor
        self.begin_tx()
        ok, runtime, used = execute(init_code + ctor_args, b"", gas,
                                    world=self, address=addr)
        if not ok:
            del self.contracts[addr]
            raise EvmError(f"constructor reverted: "
                           f"{revert_reason(runtime) or runtime.hex()}")
        self.contracts[addr].code = runtime
        deposit = _enforce_code_deposit(runtime) if enforce_eip170 \
            else 200 * len(runtime)
        return addr, used + deposit

    def transact(self, to: int, calldata: bytes, gas: int = 30_000_000,
                 caller: int = 0xCA11E12):
        """Top-level transaction. Returns (ok, returndata,
        gas_incl_intrinsic); refunds applied per EIP-3529 (<= used/5)."""
        self.begin_tx()
        self._warm_addrs.add(to)
        ok, out, used = self.message_call(to, calldata, gas, caller=caller)
        total = used + tx_intrinsic_gas(calldata)
        if ok:
            # EIP-3529: the refund cap is gas_used/5 INCLUDING intrinsic
            total -= min(max(self.refund, 0), total // 5)
        return ok, out, total

    def call_view(self, to: int, calldata: bytes, gas: int = 30_000_000):
        """eth_call-style read; no intrinsic gas added."""
        self.begin_tx()
        self._warm_addrs.add(to)
        return self.message_call(to, calldata, gas, caller=0, static=True)

    def message_call(self, to: int, calldata: bytes, gas: int,
                     caller: int = 0, static: bool = False):
        """Nested message call with revert semantics: a failing frame's
        storage writes and access-set additions are rolled back."""
        c = self.contracts[to]
        snap = self._snapshot()
        fr = _Frame(c.code, calldata, gas, world=self, address=to,
                    caller=caller, static=static, jumpdests=c.jumpdests())
        try:
            out = _run(fr)
            return True, out, gas - fr.gas
        except _Revert as rv:
            self._restore(snap)
            return False, rv.data, gas - fr.gas
        except EvmError:
            self._restore(snap)
            return False, b"", gas

"""Compiler: the generated Spectre.sol protocol contract -> EVM bytecode.

`contracts/sol_gen.py` emits the on-chain light-client protocol contract
(reference ABI observed in `contract-tests/tests/spectre.rs:56-110`; the
reference's own contracts submodule is empty). The statement interpreter
(`SolSpectre`) executes that source directly; this module compiles the
SAME source to real EVM bytecode so the protocol can run as deployed
contracts in `evm/vm.py`'s World — constructor, storage, mappings,
keccak-slot addressing, external STATICCALLs to the verifier contracts,
the sha256 precompile, and metered gas — mirroring the reference's
anvil-based contract tests end-to-end.

Subset semantics (hold on sol_gen's output, asserted where cheap):
- state variables take slots in declaration order; `mapping(uint256 => T)`
  values live at keccak256(key ++ slot) (Solidity storage layout);
- public state vars / constants get their implicit external getters;
- `uint64` fields never overflow 64 bits in the emitted code (byte masks
  and shifts only), so 256-bit EVM ops match checked Solidity arithmetic;
  calldata uint64 params are range-validated like solc's ABI decoder;
- `bytes8` values are carried low-aligned and shifted left at use sites
  (encodePacked emits 8 bytes; external returns are left-aligned);
- a failed external verifier call bubbles its revert data (solc 0.8
  behavior); a `false` return hits the surrounding require.
"""

from __future__ import annotations

import re

from .solc import OPS, Asm, _Parser, _tokenize  # noqa: F401 (shared infra)

# ---- memory map ----
SCRATCH = 0x00            # 0x00-0x5f: mapping-slot hashing, return staging
VARS_BASE = 0x100         # named locals / decoded params (assembler-fixed)
# memory arrays, the encodePacked absorb buffer and the external-call
# staging area are placed after the variable slots by the assembler
# (symbolic labels __arrays / __absorb / __callbuf).

_SELECTOR_TYPES = {"uint256": "uint256", "uint64": "uint64",
                   "bytes32": "bytes32", "bytes8": "bytes8",
                   "address": "address", "bytes": "bytes", "bool": "bool",
                   "uint256[12]": "uint256[12]"}


def _keccak(data: bytes) -> bytes:
    from ..plonk.transcript import keccak256
    return keccak256(data)


class _Fn:
    def __init__(self, name, params, returns, body_lines, external=True):
        self.name = name
        self.params = params          # [(type, location, name)]
        self.returns = returns        # type or None
        self.body = body_lines
        self.external = external

    def selector_sig(self, structs) -> str:
        parts = []
        for typ, _loc, _name in self.params:
            if typ in structs:
                parts.append("(" + ",".join(
                    f[0] for f in structs[typ]) + ")")
            else:
                parts.append(_SELECTOR_TYPES[typ])
        return f"{self.name}({','.join(parts)})"


class SpectreCompiler:
    def __init__(self, src: str):
        self.src = src
        self.a = Asm()
        self.slots: dict[str, int] = {}        # local var -> memory offset
        self.next_off = VARS_BASE
        self.arrays: dict[str, tuple] = {}     # memory arr -> (label_off, n)
        self.array_bytes = 0
        self.revert_msgs: dict[str, str] = {}
        self.constants: dict[str, int] = {}
        self.storage_vars: dict[str, dict] = {}  # name -> {slot, kind, type}
        self.structs: dict[str, list] = {}     # name -> [(type, name)]
        self.fns: dict[str, _Fn] = {}
        self.ctor: _Fn | None = None
        self.var_types: dict[str, str] = {}    # local name -> type
        self.struct_bases: dict[str, int] = {}  # struct param -> cd offset
        self.cd_arrays: dict[str, tuple] = {}  # fixed-array param -> (off, n)
        self.cur_fn: _Fn | None = None
        self._parse_contract()

    # ================= source-level parsing =================
    def _parse_contract(self):
        src = self.src
        for m in re.finditer(
                r"uint256 public constant (\w+) = (\d+);", src):
            self.constants[m.group(1)] = int(m.group(2))
        # state variables, in declaration order
        slot = 0
        body = src[src.index("contract Spectre"):]
        for line in body.split("\n"):
            s = line.strip()
            m = re.match(r"uint256 public (\w+);", s)
            if m:
                self.storage_vars[m.group(1)] = {
                    "slot": slot, "kind": "scalar", "type": "uint256"}
                slot += 1
                continue
            m = re.match(r"mapping\(uint256 => (\w+)\) public (\w+);", s)
            if m:
                self.storage_vars[m.group(2)] = {
                    "slot": slot, "kind": "mapping", "type": m.group(1)}
                slot += 1
                continue
            m = re.match(r"IVerifier public (\w+);", s)
            if m:
                self.storage_vars[m.group(1)] = {
                    "slot": slot, "kind": "scalar", "type": "address"}
                slot += 1
        # structs / constructor / functions: contract body only (the
        # IVerifier interface above declares verify() too)
        src = body
        for m in re.finditer(r"struct (\w+) \{([^}]*)\}", src):
            fields = []
            for fm in re.finditer(r"(\w+) (\w+);", m.group(2)):
                fields.append((fm.group(1), fm.group(2)))
            self.structs[m.group(1)] = fields
        # constructor
        m = re.search(r"constructor\(([^)]*)\)\s*\{(.*?)\n    \}", src,
                      re.DOTALL)
        assert m, "constructor not found"
        self.ctor = _Fn("constructor", self._parse_params(m.group(1)),
                        None, m.group(2).split("\n"))
        # functions
        for m in re.finditer(
                r"function (\w+)\(([^)]*)\)\s*\n?\s*"
                r"(external|public|internal)"
                r"[^{]*?(?:returns \((\w+)\))?\s*\{(.*?)\n    \}", src,
                re.DOTALL):
            name, params, vis, ret, body = m.groups()
            self.fns[name] = _Fn(name, self._parse_params(params), ret,
                                 body.split("\n"),
                                 external=vis != "internal")

    @staticmethod
    def _parse_params(s: str):
        params = []
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            toks = part.split()
            if len(toks) == 2:
                typ, name = toks
                loc = "stack"
            else:
                typ, loc, name = toks
            params.append((typ, loc, name))
        return params

    # ================= low-level helpers =================
    def slot(self, name: str) -> int:
        if name not in self.slots:
            self.slots[name] = self.next_off
            self.next_off += 32
        return self.slots[name]

    def revert_label(self, msg: str) -> str:
        if msg not in self.revert_msgs:
            self.revert_msgs[msg] = f"rev_{len(self.revert_msgs)}"
        return self.revert_msgs[msg]

    def _fresh(self, base):
        return self.a.fresh_label(base)

    def _cur_load(self):
        self.a.push(self.slot("__cur"))
        self.a.op("MLOAD")

    def _cur_add(self, n: int):
        a = self.a
        a.push(self.slot("__cur"))
        a.op("MLOAD")
        a.push(n)
        a.op("ADD")
        a.push(self.slot("__cur"))
        a.op("MSTORE")

    # ================= expression typing =================
    def typ_of(self, e) -> str:
        k = e[0]
        if k == "num":
            return "uint256"
        if k == "var":
            n = e[1]
            if n in self.var_types:
                return self.var_types[n]
            if n in self.constants:
                return "uint256"
            if n in self.storage_vars:
                return self.storage_vars[n]["type"]
            return "uint256"
        if k == "member":
            sname = self.var_types.get(e[1][1], "")
            for ftyp, fname in self.structs.get(sname, []):
                if fname == e[2]:
                    return ftyp
            return "uint256"
        if k == "call":
            fname = e[1]
            if fname in self.fns:
                return self.fns[fname].returns or "uint256"
            if fname in ("uint256", "bytes32", "bytes8", "uint64",
                         "address"):
                return fname
            if fname == "sha256":
                return "bytes32"
            return "uint256"
        if k == "bin":
            return self.typ_of(e[2])
        return "uint256"

    # ================= expression compilation =================
    def eval(self, e):
        """Compile e to one stack word."""
        a = self.a
        k = e[0]
        if k == "num":
            a.push(e[1])
        elif k == "hexlit":
            a.push(int.from_bytes(e[1].ljust(32, b"\x00"), "big"))
        elif k == "var":
            self.eval_var(e[1])
        elif k == "member":
            self.eval_member(e)
        elif k == "bin":
            self.eval_bin(e)
        elif k == "not":
            self.eval(e[1])
            a.op("ISZERO")
        elif k == "index":
            self.eval_index(e)
        elif k == "call":
            self.eval_call(e)
        elif k == "method":
            self.eval_external_call(e)
        else:
            raise SyntaxError(f"expr: {e}")

    def eval_member(self, e):
        """struct field access: the struct param lives in calldata at a
        compile-time base offset (struct_bases, not a memory slot)."""
        _, base, field = e
        assert base[0] == "var"
        sname = self.var_types[base[1]]
        off = self.struct_bases[base[1]]
        for i, (_ftyp, fname) in enumerate(self.structs[sname]):
            if fname == field:
                self.a.push(off + 32 * i)
                self.a.op("CALLDATALOAD")
                return
        raise SyntaxError(f"no field {field} in {sname}")

    def eval_bin(self, e):
        _, op, l, r = e
        a = self.a
        if op in ("+", "-", "*", "/", "&", "|"):
            self.eval(r)
            self.eval(l)
            a.op({"+": "ADD", "-": "SUB", "*": "MUL", "/": "DIV",
                  "&": "AND", "|": "OR"}[op])
        elif op in ("<<", ">>"):
            self.eval(l)
            self.eval(r)
            a.op("SHL" if op == "<<" else "SHR")
        elif op in ("<", ">"):
            # both compile to LT; '>' swaps the operands instead
            self.eval(r if op == "<" else l)
            self.eval(l if op == "<" else r)
            a.op("LT")
        elif op == "==":
            self.eval(l)
            self.eval(r)
            a.op("EQ")
        elif op == "!=":
            self.eval(l)
            self.eval(r)
            a.op("EQ", "ISZERO")
        elif op == "&&":
            self.eval(l)
            self.eval(r)
            a.op("AND")
        else:
            raise SyntaxError(f"binop {op}")

    def eval_index(self, e):
        _, base, idx = e
        a = self.a
        assert base[0] == "var"
        name = base[1]
        if name in self.cd_arrays:             # fixed-size calldata array
            off, n = self.cd_arrays[name]
            if idx[0] == "num":
                assert idx[1] < n
                a.push(off + 32 * idx[1])
            else:
                self.eval(idx)
                a.push(5)
                a.op("SHL")
                a.push(off)
                a.op("ADD")
            a.op("CALLDATALOAD")
        elif name in self.arrays:
            lbl, n = self.arrays[name]
            if idx[0] == "num":
                assert idx[1] < n
                a.pushl("__arrays")
                a.push(lbl + 32 * (idx[1] + 1))
            else:
                self.eval(idx)
                a.push(5)
                a.op("SHL")
                a.pushl("__arrays")
                a.push(lbl + 32)
                a.op("ADD")          # [i*32, base+off]; shared ADD follows
            a.op("ADD", "MLOAD")
        elif name in self.storage_vars and \
                self.storage_vars[name]["kind"] == "mapping":
            self.mapping_slot(name, idx)
            a.op("SLOAD")
        else:
            raise SyntaxError(f"index into {name}")

    def mapping_slot(self, name: str, key_expr):
        """keccak256(key ++ slot) on the stack."""
        a = self.a
        self.eval(key_expr)
        a.push(SCRATCH)
        a.op("MSTORE")
        a.push(self.storage_vars[name]["slot"])
        a.push(SCRATCH + 32)
        a.op("MSTORE")
        a.push(64)
        a.push(SCRATCH)
        a.op("SHA3")

    def eval_call(self, e):
        _, fname, args = e
        a = self.a
        if fname in ("uint256", "uint64", "address", "bool", "IVerifier"):
            self.eval(args[0])
            if fname == "uint64" and self.typ_of(args[0]) == "bytes8":
                a.push(192)
                a.op("SHR")
            return
        if fname in ("bytes32", "bytes8"):
            self.eval(args[0])      # low-aligned carry (see module doc)
            return
        if fname == "sha256":
            assert args[0][0] == "packed"
            self.eval_packed_sha256(args[0][1])
            return
        if fname in self.fns:
            fn = self.fns[fname]
            ret = self._fresh(f"ret_{fname}")
            a.pushl(ret)
            for (ptyp, _loc, _pname), arg in zip(fn.params, args):
                if ptyp in self.structs:
                    # struct params compile to a fixed calldata base; the
                    # callee reads the caller's own calldata (both ABIs
                    # place the struct first) — nothing to pass
                    assert arg[0] == "var" and \
                        self.var_types.get(arg[1]) == ptyp
                else:
                    self.eval(arg)
            a.pushl(f"fn_{fname}")
            a.op("JUMP")
            a.label(ret)
            return
        raise SyntaxError(f"call {fname}")

    def eval_packed_sha256(self, chunks):
        """sha256(abi.encodePacked(...)) via precompile 0x2."""
        a = self.a
        a.pushl("__absorb")
        a.push(self.slot("__cur"))
        a.op("MSTORE")
        for ch in chunks:
            typ = self.typ_of(ch)
            self.eval(ch)
            if typ == "bytes8":
                a.push(192)
                a.op("SHL")          # left-align the 8 bytes
                self._cur_load()
                a.op("MSTORE")
                self._cur_add(8)
            else:                    # bytes32 / uint256 word
                self._cur_load()
                a.op("MSTORE")
                self._cur_add(32)
        # STATICCALL(gas, 0x2, absorb, cur - absorb, SCRATCH, 32)
        a.push(32)                   # retSize
        a.push(SCRATCH)              # retOff
        a.pushl("__absorb")
        a.push(self.slot("__cur"))
        a.op("MLOAD", "SUB")         # argSize = cur - absorb
        a.pushl("__absorb")          # argOff
        a.push(2)
        a.op("GAS", "STATICCALL", "ISZERO")
        a.pushl(self.revert_label("sha256"))
        a.op("JUMPI")
        a.push(SCRATCH)
        a.op("MLOAD")

    def eval_external_call(self, e):
        """stepVerifier.verify(instances, proof) -> bool word.

        Builds verify(uint256[],bytes) calldata in the __callbuf region;
        instances is a compile-time-length memory array, proof forwards
        this function's own bytes-calldata param."""
        _, target_node, mname, args = e
        assert mname == "verify" and len(args) == 2
        assert target_node[0] == "var"
        target = target_node[1]
        arr = args[0]
        assert arr[0] == "var" and arr[1] in self.arrays
        lbl, n = self.arrays[arr[1]]
        proof = args[1]
        assert proof[0] == "var"
        plen_slot = self.slot(f"__bytes_len_{proof[1]}")
        pdata_slot = self.slot(f"__bytes_data_{proof[1]}")
        a = self.a
        sel = int.from_bytes(_keccak(b"verify(uint256[],bytes)")[:4], "big")
        # header: selector ++ off_instances(64) ++ off_proof
        a.push(sel << 224)
        a.pushl("__callbuf")
        a.op("MSTORE")
        a.push(64)
        a.pushl("__callbuf")
        a.push(4)
        a.op("ADD", "MSTORE")
        a.push(64 + 32 + 32 * n)     # proof head offset (after instances)
        a.pushl("__callbuf")
        a.push(36)
        a.op("ADD", "MSTORE")
        # instances array: length + items copied from the memory array
        a.push(n)
        a.pushl("__callbuf")
        a.push(68)
        a.op("ADD", "MSTORE")
        for i in range(n):
            a.pushl("__arrays")
            a.push(lbl + 32 * (i + 1))
            a.op("ADD", "MLOAD")
            a.pushl("__callbuf")
            a.push(100 + 32 * i)
            a.op("ADD", "MSTORE")
        # proof: length word + calldata copy (padded to words)
        pbase = 100 + 32 * n
        a.push(plen_slot)
        a.op("MLOAD")
        a.pushl("__callbuf")
        a.push(pbase)
        a.op("ADD", "MSTORE")
        a.push(plen_slot)
        a.op("MLOAD")                # size
        a.push(pdata_slot)
        a.op("MLOAD")                # src (calldata offset)
        a.pushl("__callbuf")
        a.push(pbase + 32)
        a.op("ADD")                  # dst
        a.op("CALLDATACOPY")
        # total calldata size = pbase + 32 + ceil(len/32)*32
        a.push(plen_slot)
        a.op("MLOAD")
        a.push(31)
        a.op("ADD")
        a.push(0xFFFFFFE0)           # & ~31 (lengths < 2^32 in practice)
        a.op("AND")
        a.push(pbase + 32)
        a.op("ADD")                  # [insize]
        # STATICCALL(gas, addr, callbuf, insize, SCRATCH=0, 32):
        # stack must be [32, 0, insize, buf, addr, gas] bottom->top
        a.push(SCRATCH)              # == 0
        a.push(32)
        a.op("SWAP2")                # [32, 0, insize]
        a.pushl("__callbuf")
        self.eval_var(target)        # verifier address from storage
        a.op("GAS", "STATICCALL")
        # failure: bubble the callee's revert data (solc 0.8 behavior)
        ok_lbl = self._fresh("extok")
        a.op("DUP1")
        a.pushl(ok_lbl)
        a.op("JUMPI")
        a.op("RETURNDATASIZE")
        a.push(0)
        a.push(0)
        a.op("RETURNDATACOPY")
        a.op("RETURNDATASIZE")
        a.push(0)
        a.op("REVERT")
        a.label(ok_lbl)
        a.op("POP")                  # drop the success flag
        a.push(SCRATCH)
        a.op("MLOAD")                # bool word

    # ================= statements =================
    def lslot(self, name: str) -> int:
        """Function-scoped local slot (prefixed so nested internal calls
        cannot alias the caller's locals)."""
        return self.slot(f"{self.cur_fn.name}.{name}")

    def eval_var(self, name: str):
        a = self.a
        if name in self.var_types:              # function local / param
            a.push(self.lslot(name))
            a.op("MLOAD")
        elif name in self.constants:
            a.push(self.constants[name])
        elif name in self.storage_vars:
            sv = self.storage_vars[name]
            assert sv["kind"] == "scalar", f"{name} needs a key"
            a.push(sv["slot"])
            a.op("SLOAD")
        else:
            raise SyntaxError(f"unknown identifier {name}")

    def emit_require(self, cond, msg: str):
        self.eval(cond)
        self.a.op("ISZERO")
        self.a.pushl(self.revert_label(msg))
        self.a.op("JUMPI")

    def compile_stmt(self, s: str, blocks: list) -> bool:
        """Compile one statement line; returns True if handled as a block
        opener/closer."""
        a = self.a
        s = s.strip()
        if not s or s.startswith("//"):
            return True
        if s == "}":
            blk = blocks.pop()
            if blk[0] == "loop":
                _, var, start, end = blk
                a.push(self.lslot(var))
                a.op("MLOAD")
                a.push(1)
                a.op("ADD")
                a.push(self.lslot(var))
                a.op("MSTORE")
                a.pushl(start)
                a.op("JUMP")
                a.label(end)
            elif blk[0] == "if":
                a.label(blk[1])
            return True
        m = re.match(r"for \(uint256 (\w+) = (\d+); \1 < (\d+); \1\+\+\) \{$",
                     s)
        if m:
            var, init, limit = m.group(1), int(m.group(2)), int(m.group(3))
            self.var_types[var] = "uint256"
            a.push(init)
            a.push(self.lslot(var))
            a.op("MSTORE")
            start, end = self._fresh("loop"), self._fresh("loop_end")
            a.label(start)
            a.push(limit)
            a.push(self.lslot(var))
            a.op("MLOAD", "LT", "ISZERO")
            a.pushl(end)
            a.op("JUMPI")
            blocks.append(("loop", var, start, end))
            return True
        m = re.match(r"if \((.*)\) \{$", s)
        if m:
            end = self._fresh("if_end")
            self.eval(_Parser(_tokenize(m.group(1))).expr())
            a.op("ISZERO")
            a.pushl(end)
            a.op("JUMPI")
            blocks.append(("if", end))
            return True

        if s.endswith(";"):
            s = s[:-1]
        m = re.match(r'require\((.*), "(.*)"\)$', s, re.DOTALL)
        if m:
            self.emit_require(_Parser(_tokenize(m.group(1))).expr(),
                              m.group(2))
            return False
        m = re.match(r"return (.*)$", s, re.DOTALL)
        if m:
            # internal-call convention: [ret] -> push value, SWAP1, JUMP
            self.eval(_Parser(_tokenize(m.group(1))).expr())
            a.op("SWAP1", "JUMP")
            return False
        # declarations
        m = re.match(r"uint256\[\] memory (\w+) = new uint256\[\]\((\d+)\)$",
                     s)
        if m:
            name, n = m.group(1), int(m.group(2))
            self.arrays[name] = (self.array_bytes, n)
            self.var_types[name] = "uint256[]"
            a.push(n)
            a.pushl("__arrays")
            a.push(self.array_bytes)
            a.op("ADD", "MSTORE")    # length word
            self.array_bytes += 32 * (n + 1)
            return False
        m = re.match(r"(uint256|uint64|bytes32|bytes8) (\w+) = (.*)$", s,
                     re.DOTALL)
        if m:
            typ, name, rhs = m.groups()
            e = _Parser(_tokenize(rhs)).expr()
            self.var_types[name] = typ
            self.eval(e)
            a.push(self.lslot(name))
            a.op("MSTORE")
            return False
        # assignments
        m = re.match(r"(\w+)\[(.+?)\] = (.*)$", s, re.DOTALL)
        if m:
            name, key_src, rhs = m.groups()
            val = _Parser(_tokenize(rhs)).expr()
            if name in self.arrays:
                lbl, n = self.arrays[name]
                idx = _Parser(_tokenize(key_src)).expr()
                self.eval(val)
                if idx[0] == "num":
                    assert idx[1] < n
                    a.pushl("__arrays")
                    a.push(lbl + 32 * (idx[1] + 1))
                else:
                    self.eval(idx)
                    a.push(5)
                    a.op("SHL")
                    a.pushl("__arrays")
                    a.push(lbl + 32)
                    a.op("ADD")
                a.op("ADD", "MSTORE")
            else:
                sv = self.storage_vars[name]
                assert sv["kind"] == "mapping"
                self.eval(val)
                self.mapping_slot(name,
                                  _Parser(_tokenize(key_src)).expr())
                a.op("SSTORE")
            return False
        m = re.match(r"(\w+) = (.*)$", s, re.DOTALL)
        if m:
            name, rhs = m.groups()
            e = _Parser(_tokenize(rhs)).expr()
            self.eval(e)
            if name in self.var_types:
                a.push(self.lslot(name))
                a.op("MSTORE")
            else:
                sv = self.storage_vars[name]
                assert sv["kind"] == "scalar"
                a.push(sv["slot"])
                a.op("SSTORE")
            return False
        m = re.match(r"(\w+)\((.*)\)$", s, re.DOTALL)
        if m and m.group(1) in self.fns:       # bare internal call
            fn = self.fns[m.group(1)]
            self.eval_call(_Parser(_tokenize(s)).expr())
            if fn.returns is not None:
                a.op("POP")                    # discarded return value
            return False
        raise SyntaxError(f"unhandled statement: {s}")

    @staticmethod
    def _join_lines(lines: list) -> list:
        """Merge continuation lines until parens balance and the statement
        terminates (';', block opener '{', or a bare '}')."""
        out, buf, depth = [], "", 0
        for raw in lines:
            s = raw.strip()
            if not s or s.startswith("//"):
                continue
            buf = f"{buf} {s}".strip() if buf else s
            depth += s.count("(") - s.count(")")
            if depth == 0 and (buf.endswith(";") or buf.endswith("{")
                               or buf == "}"):
                out.append(buf)
                buf = ""
        assert not buf, f"dangling statement: {buf!r}"
        return out

    def compile_body(self, lines: list):
        blocks: list = []
        for stmt in self._join_lines(lines):
            self.compile_stmt(stmt, blocks)
        assert not blocks, "unbalanced blocks"

    # ================= functions =================
    def compile_fn(self, fn: _Fn):
        """Emit the function body as an internal subroutine fn_<name>.

        Convention: entry stack [ret, a1..an] (stack params only; struct
        and bytes params are calldata-resident). Exit: value fns leave the
        result via `return` statements; void fns fall through to JUMP."""
        a = self.a
        self.cur_fn = fn
        self.var_types = {}
        self.struct_bases = {}
        self.cd_arrays = {}
        a.label(f"fn_{fn.name}")
        stack_params = []
        cd_off = 4
        for typ, loc, name in fn.params:
            if typ in self.structs:
                assert cd_off == 4, "struct param must come first"
                self.struct_bases[name] = 4
                self.var_types[name] = typ
                cd_off += 32 * len(self.structs[typ])
            elif typ.endswith("]"):              # uint256[12] calldata
                n = int(typ[typ.index("[") + 1:-1])
                self.cd_arrays[name] = (cd_off, n)
                self.var_types[name] = typ
                cd_off += 32 * n
            elif typ == "bytes":
                self.var_types[name] = "bytes"   # len/data slots, stub-set
                cd_off += 32
            else:
                stack_params.append(name)
                self.var_types[name] = typ
                cd_off += 32
        for name in reversed(stack_params):      # last arg is on top
            a.push(self.lslot(name))
            a.op("MSTORE")
        self.compile_body(fn.body)
        if fn.returns is None:
            a.op("JUMP")                         # [ret] void return
        # value functions end via `return <expr>` statements

    def _abi_stub(self, fn: _Fn):
        """External entry: decode calldata, run the subroutine, encode."""
        a = self.a
        self.cur_fn = fn
        a.label(f"stub_{fn.name}")
        # head layout: structs inline their fields; bytes take one offset
        head_off = 4
        bytes_params = []
        scalar_loads = []
        for typ, _loc, name in fn.params:
            if typ in self.structs:
                # solc's ABI decoder validates narrow struct fields
                for i, (ftyp, _fn) in enumerate(self.structs[typ]):
                    if ftyp == "uint64":
                        a.push(head_off + 32 * i)
                        a.op("CALLDATALOAD")
                        a.push(64)
                        a.op("SHR")
                        a.pushl(self.revert_label("abi: uint64"))
                        a.op("JUMPI")
                head_off += 32 * len(self.structs[typ])
            elif typ.endswith("]"):              # fixed array: inline words
                head_off += 32 * int(typ[typ.index("[") + 1:-1])
            elif typ == "bytes":
                bytes_params.append((name, head_off))
                head_off += 32
            else:
                scalar_loads.append((typ, name, head_off))
                head_off += 32
        for name, off in bytes_params:
            a.push(off)
            a.op("CALLDATALOAD")
            a.push(4)
            a.op("ADD", "DUP1", "CALLDATALOAD")
            a.push(self.slot(f"__bytes_len_{name}"))
            a.op("MSTORE")
            a.push(32)
            a.op("ADD")
            a.push(self.slot(f"__bytes_data_{name}"))
            a.op("MSTORE")
        ret = self._fresh(f"stubret_{fn.name}")
        a.pushl(ret)
        for typ, name, off in scalar_loads:
            a.push(off)
            a.op("CALLDATALOAD")
            if typ == "uint64":                  # solc ABI decoder check
                a.op("DUP1")
                a.push(64)
                a.op("SHR")
                a.pushl(self.revert_label("abi: uint64"))
                a.op("JUMPI")
        a.pushl(f"fn_{fn.name}")
        a.op("JUMP")
        a.label(ret)
        if fn.returns is None:
            a.push(0)
            a.push(0)
            a.op("RETURN")
        else:
            if fn.returns == "bytes8":
                a.push(192)
                a.op("SHL")                      # ABI: left-aligned
            a.push(0)
            a.op("MSTORE")
            a.push(32)
            a.push(0)
            a.op("RETURN")

    def _getter_stub(self, name: str):
        a = self.a
        a.label(f"stub_get_{name}")
        if name in self.constants:
            a.push(self.constants[name])
        else:
            sv = self.storage_vars[name]
            if sv["kind"] == "scalar":
                a.push(sv["slot"])
                a.op("SLOAD")
            else:
                a.push(4)
                a.op("CALLDATALOAD")
                a.push(SCRATCH)
                a.op("MSTORE")
                a.push(sv["slot"])
                a.push(SCRATCH + 32)
                a.op("MSTORE")
                a.push(64)
                a.push(SCRATCH)
                a.op("SHA3", "SLOAD")
        a.push(0)
        a.op("MSTORE")
        a.push(32)
        a.push(0)
        a.op("RETURN")

    # ================= top level =================
    def _dispatcher(self, entries):
        """entries: [(sig, label)]"""
        a = self.a
        a.push(4)
        a.op("CALLDATASIZE", "LT")
        a.pushl(self.revert_label("bad selector"))
        a.op("JUMPI")
        a.push(0)
        a.op("CALLDATALOAD")
        a.push(224)
        a.op("SHR")
        for sig, label in entries:
            sel = int.from_bytes(_keccak(sig.encode())[:4], "big")
            a.op("DUP1")
            a.push(sel)
            a.op("EQ")
            a.pushl(label)
            a.op("JUMPI")
        a.pushl(self.revert_label("bad selector"))
        a.op("JUMP")

    def emit_revert_stubs(self):
        a = self.a
        for msg, lbl in self.revert_msgs.items():
            a.label(lbl)
            data = msg.encode()
            assert len(data) <= 32
            a.push(0x08C379A0)
            a.push(0)
            a.op("MSTORE")
            a.push(0x20)
            a.push(0x20)
            a.op("MSTORE")
            a.push(len(data))
            a.push(0x40)
            a.op("MSTORE")
            a.push(int.from_bytes(data.ljust(32, b"\x00"), "big"))
            a.push(0x60)
            a.op("MSTORE")
            a.push(0x64)
            a.push(0x1C)
            a.op("REVERT")

    def _finalize(self, asm: Asm) -> bytes:
        """Place the dynamic regions and assemble."""
        arrays = self.next_off
        absorb = arrays + max(self.array_bytes, 32)
        callbuf = absorb + 256
        sub = {"__arrays": arrays, "__absorb": absorb, "__callbuf": callbuf}
        from .solc import _push_bytes
        for i, it in enumerate(asm.items):
            if it[0] == "pushl" and it[1] in sub:
                asm.items[i] = ("b", _push_bytes(sub[it[1]]))
        return asm.assemble()

    def compile(self):
        """Returns (runtime_code, init_code_without_args, meta)."""
        a = self.a
        entries = []
        for fn in self.fns.values():
            if fn.external:
                entries.append((fn.selector_sig(self.structs),
                                f"stub_{fn.name}"))
        for name in self.constants:
            entries.append((f"{name}()", f"stub_get_{name}"))
        for name, sv in self.storage_vars.items():
            sig = f"{name}()" if sv["kind"] == "scalar" \
                else f"{name}(uint256)"
            entries.append((sig, f"stub_get_{name}"))
        self._dispatcher(entries)
        for fn in self.fns.values():
            if fn.external:
                self._abi_stub(fn)
        for fn in self.fns.values():
            self.compile_fn(fn)
        for name in list(self.constants) + list(self.storage_vars):
            self._getter_stub(name)
        self.emit_revert_stubs()
        runtime = self._finalize(a)

        # ---- constructor / init code ----
        ia = Asm()
        self.a = ia
        self.cur_fn = self.ctor
        self.var_types = {}
        nargs = len(self.ctor.params)
        for i, (typ, _loc, name) in enumerate(self.ctor.params):
            self.var_types[name] = "address" if typ == "IVerifier" else typ
        ia.push(32 * nargs)
        ia.op("DUP1", "CODESIZE", "SUB")     # [size, argstart]
        ia.push(self.lslot(self.ctor.params[0][2]))
        ia.op("CODECOPY")                    # args -> param slots (contig.)
        # param slots must be contiguous in declaration order
        base = self.lslot(self.ctor.params[0][2])
        for i, (_t, _l, name) in enumerate(self.ctor.params):
            assert self.lslot(name) == base + 32 * i, \
                "constructor params must land contiguously"
        n_msgs_before = len(self.revert_msgs)
        self.compile_body(self.ctor.body)
        assert len(self.revert_msgs) == n_msgs_before, \
            "constructor reverts need stubs emitted before the rt label"
        ia.push(len(runtime))
        ia.op("DUP1")
        ia.pushl("rt")
        ia.push(0)
        ia.op("CODECOPY")
        ia.push(0)
        ia.op("RETURN")
        ia.label("rt")                       # MUST stay the last item
        head = self._finalize(ia)
        # strip the trailing JUMPDEST marking "rt"; the label's offset is
        # then exactly where the appended runtime blob starts
        init = head[:-1] + runtime
        meta = {"runtime_bytes": len(runtime), "init_bytes": len(init)}
        return runtime, init, meta


def compile_spectre(sol_src: str):
    """Compile a generated Spectre.sol; returns (runtime, init, meta)."""
    return SpectreCompiler(sol_src).compile()
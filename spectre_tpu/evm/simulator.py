"""Executable oracle for generated verifier contracts (NOT an EVM).

The generator emits a tiny, regular Solidity subset (uint256 locals,
addmod/mulmod, keccak over abi.encodePacked, calldata slices, the helper
functions backed by precompiles). This module translates that subset to
Python line-by-line and executes it with host BN254 ops standing in for the
precompiles — so tests can run the ACTUAL generated code against real
proofs and tampered ones. Solidity-compiler semantics (gas, memory) are out
of scope; arithmetic, transcript replay, offsets, and the pairing equation
are exactly what is exercised.

Reference-parity note: the reference tests its generated Yul with revm
(`evm_verify`, SURVEY.md N11); this simulator is the offline stand-in until
an EVM toolchain is available.
"""

from __future__ import annotations

import re

from ..fields import bn254
from ..plonk.transcript import keccak256 as _keccak

R = bn254.R
Q = bn254.P


class _U32(int):
    pass


class _Abi:
    @staticmethod
    def encodePacked(*args):
        out = b""
        for a in args:
            if isinstance(a, _U32):
                out += int(a).to_bytes(4, "big")
            elif isinstance(a, (bytes, bytearray)):
                out += bytes(a)
            else:
                raise TypeError(f"encodePacked: {type(a)}")
        return out


def _translate(body_lines: list[str]) -> str:
    py = []
    indent = 1
    for raw in body_lines:
        s = raw.strip()
        if not s or s.startswith("//"):
            continue
        if s == "{":
            continue
        if s == "}":
            if indent > 1:
                indent -= 1
            continue
        # for-loop over instances / eval canonicity
        m = re.match(r"for \(uint256 (\w+) = (\w+); \1 < instances\.length; "
                     r"\1\+\+\) \{", s)
        if m:
            py.append("    " * indent + f"for {m.group(1)} in range(len(instances)):")
            indent += 1
            continue
        m = re.match(r"for \(uint256 (\w+) = (\d+); \1 < (\d+); \1 \+= 32\) "
                     r"\{ (.*) \}", s)
        if m:
            var, lo, hi, inner = m.groups()
            py.append("    " * indent +
                      f"for {var} in range({lo}, {hi}, 32):")
            py.append("    " * (indent + 1) + _stmt(inner))
            continue
        if s.endswith("{") and s.startswith("for"):
            raise ValueError(f"unhandled loop: {s}")
        py.append("    " * indent + _stmt(s))
        # closing of one-line instance loop bodies is handled by '}' lines,
        # which only pop nested indents
        if s.endswith("{"):
            indent += 1
    return "\n".join(py)


def _stmt(s: str) -> str:
    s = s.rstrip()
    if s.endswith(";"):
        s = s[:-1]
    # declarations (typed-with-initializer first, then bare declarations)
    s = re.sub(r"uint256\[(\d+)\] memory (\w+) = ", r"\2 = ", s)
    s = re.sub(r"uint256\[(\d+)\] memory (\w+)$", r"\2 = [0] * \1", s)
    s = re.sub(r"^bytes32 (\w+) = ", r"\1 = ", s)
    s = re.sub(r"^bytes memory (\w+)$", r"\1 = b''", s)
    s = re.sub(r"^uint256 (\w+) = ", r"\1 = ", s)
    # casts and literals
    s = re.sub(r"(\w+)\.length", r"len(\1)", s)
    s = re.sub(r'hex"([0-9a-fA-F]+)"', r'bytes.fromhex("\1")', s)
    s = re.sub(r"uint32\((\d+)\)", r"_U32(\1)", s)
    # require
    m = re.match(r'require\((.*), "(.*)"\)$', s)
    if m:
        cond = m.group(1).replace("&&", "and")
        return f"assert {cond}, {m.group(2)!r}"
    # guard returns: if (!cond) { return false; }
    m = re.match(r"if \(!(.*)\) \{ return false; \}$", s)
    if m:
        cond = m.group(1).replace("&&", "and")
        return f"if not ({cond}): return False"
    s = s.replace("return false", "return False").replace(
        "return true", "return True")
    assert "uint256[" not in s, f"untranslated: {s}"
    return s


def _final_src(body: str) -> str:
    return body.replace("abi.encodePacked", "abi_encodePacked")


def run_verifier(sol_src: str, instances: list, proof: bytes) -> bool:
    """Execute the verify() body of a generated contract."""
    m = re.search(r"function verify\(.*?\{\n(.*)\n\s*\}\n\}", sol_src,
                  re.DOTALL)
    assert m, "verify body not found"
    body_lines = m.group(1).split("\n")
    consts = {}
    for name in ("R_MOD", "Q_MOD", "POW256"):
        cm = re.search(rf"constant {name} =\s*(0x[0-9a-fA-F]+)", sol_src)
        consts[name] = int(cm.group(1), 16)
    for name in ("INIT_STATE", "VK_DIGEST"):
        cm = re.search(rf"constant {name} =\s*(0x[0-9a-fA-F]+)", sol_src)
        consts[name] = bytes.fromhex(cm.group(1)[2:])

    g1 = bn254.g1_curve

    def to_pt(xy):
        x, y = int(xy[0]), int(xy[1])
        if x == 0 and y == 0:
            return None
        pt = (bn254.Fq(x), bn254.Fq(y))
        assert g1.is_on_curve(pt), "precompile: point not on curve"
        return pt

    def from_pt(pt):
        if pt is None:
            return [0, 0]
        return [int(pt[0]), int(pt[1])]

    env = {
        "instances": [int(v) for v in instances],
        "proof": bytes(proof),
        "abi_encodePacked": _Abi.encodePacked,
        "_U32": _U32,
        "keccak256": _keccak,
        "addmod": lambda a, b, m: (a + b) % m,
        "mulmod": lambda a, b, m: (a * b) % m,
        "uint256": lambda v: int.from_bytes(v, "big")
            if isinstance(v, (bytes, bytearray)) else int(v),
        "bytes32": lambda v: int(v).to_bytes(32, "big")
            if isinstance(v, int) else bytes(v),
        "_wide": lambda h: ((int.from_bytes(h, "big") % R)
                            * ((1 << 256) % R)
                            + int.from_bytes(_keccak(h), "big")) % R,
        "_pow": lambda b, e: pow(b, e, R),
        "_inv": lambda a: pow(a, -1, R),
        "_ecMul": lambda p, s: from_pt(g1.mul_unsafe(to_pt(p), s % R)),
        "_ecAdd": lambda p, q: from_pt(g1.add(to_pt(p), to_pt(q))),
        "_negPt": lambda p: [p[0], (Q - p[1]) % Q] if p != [0, 0] else p,
        "_pairing": lambda pin: bn254.pairing_check([
            (to_pt(pin[0:2]), _g2(pin[2:6])),
            (to_pt(pin[6:8]), _g2(pin[8:12])),
        ]),
    }
    env.update(consts)

    py_body = _final_src(_translate(body_lines))
    src = "def _verify():\n" + py_body + "\n"
    exec(src, env)
    try:
        return bool(env["_verify"]())
    except AssertionError:
        return False


def _g2(words):
    # precompile ordering: (x_c1, x_c0, y_c1, y_c0)
    return (bn254.Fq2([int(words[1]), int(words[0])]),
            bn254.Fq2([int(words[3]), int(words[2])]))

"""EVM layer: Solidity verifier generation + calldata encoding.

Reference parity: snark-verifier's `gen_evm_verifier_shplonk` +
`encode_calldata` (`util/circuit.rs:182-218`, SURVEY.md L0/N11 and §2a
"Prover CLI gen-verifier").
"""

from .codegen import encode_calldata, gen_evm_verifier  # noqa: F401
from .gas import estimate_deployed_size, estimate_gas  # noqa: F401

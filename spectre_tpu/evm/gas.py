"""Static gas + deployed-code-size estimation for generated verifiers.

The reference prints `sol size` and estimates gas by executing its generated
Yul in revm (`prover/src/cli.rs:249-277`). No EVM or solc exists in this
offline environment, so this module derives both numbers STATICALLY from the
generated source's runtime structure, which — unlike source bytes — maps
predictably to bytecode: the verifier is a straight-line program of field
ops, keccaks, calldata loads, and precompile calls.

Gas model (post-Berlin mainnet schedule, EIP-150/1108/2028/2565/2929):
  mulmod / addmod          8 gas each + ~10 for operand plumbing
  keccak256 over N bytes   30 + 6*ceil(N/32) + abi.encodePacked copy (~3/word)
  ecMul  (0x07)            6,000 (EIP-1108) + 100 warm staticcall + abi glue
  ecAdd  (0x06)            150 + 100 + glue
  pairing(0x08), k pairs   45,000 + 34,000k + 100 + glue
  modexp (0x05) 32B inv    ~1,350 (EIP-2565: 16 * 255 / 3) + 100 + glue
  calldataload             3 each (proof slices / instance reads)
  intrinsic tx             21,000 + calldata (16/nonzero, 4/zero byte)
  memory expansion         3w + w^2/512 for the t[] scratch array

Deployed-size model (per-construct bytecode expansion, legacy codegen):
  PUSH32 literal           33 B        mulmod/addmod statement   ~18 B
  t[i] memory ref          ~8 B        proof/calldata slice      ~25 B
  helper fns + scaffold    ~2,200 B    other statement           ~30 B
The EIP-170 runtime limit is 24,576 B; `deployed_size_risk` states where the
estimate falls. Both estimators are calibrated to structure counts, not
source length, so comments/whitespace don't distort them.
"""

from __future__ import annotations

import re


def _count(pattern: str, src: str) -> int:
    return len(re.findall(pattern, src))


def analyze_verifier(sol_src: str) -> dict:
    """Structure counts of a generated verifier source (codegen.py shapes)."""
    body = sol_src
    return {
        "mulmod": _count(r"\bmulmod\(", body),
        "addmod": _count(r"\baddmod\(", body),
        "keccak": _count(r"\bkeccak256\(", body),
        "ecmul": _count(r"_ecMul\(", body),
        "ecadd": _count(r"_ecAdd\(", body),
        "pairing": _count(r"_pairing\(", body),
        "inv": _count(r"_inv\(", body),
        "calldata_slice": _count(r"proof\[\d+:\d+\]", body)
        + _count(r"instances\[\d+\]", body),
        "push32_literals": _count(r"0x[0-9a-fA-F]{48,64}", body),
        "statements": _count(r";\n", body),
        "tmp_slots": max([int(m) + 1 for m in
                          re.findall(r"t\[(\d+)\]", body)] or [0]),
    }


# average absorbed bytes per transcript keccak: the unrolled absorb chunks
# are point (64B) / scalar (32B) batches plus the 34B state||tag||ctr frame;
# generated verifiers average ~5 words
_KECCAK_AVG_WORDS = 5


def estimate_gas(sol_src: str, calldata: bytes | None = None) -> dict:
    """Static execution-gas estimate for one verify(...) call."""
    c = analyze_verifier(sol_src)
    field_ops = (c["mulmod"] + c["addmod"]) * (8 + 10)
    keccaks = c["keccak"] * (30 + 6 * _KECCAK_AVG_WORDS
                             + 3 * _KECCAK_AVG_WORDS)
    ecmul = c["ecmul"] * (6000 + 100 + 50)
    ecadd = c["ecadd"] * (150 + 100 + 50)
    # every _pairing call in the source checks the same 2-pair input shape
    # (lhs/G2_GEN, -W2/G2_TAU — codegen emits uint256[12])
    pairing = c["pairing"] * (45000 + 34000 * 2 + 100 + 100)
    inv = c["inv"] * (1350 + 100 + 50)
    calldata_reads = c["calldata_slice"] * 3
    w = c["tmp_slots"] + 64            # scratch + abi staging
    memory = 3 * w + w * w // 512
    execution = (field_ops + keccaks + ecmul + ecadd + pairing + inv
                 + calldata_reads + memory)
    out = {
        "counts": c,
        "gas_field_ops": field_ops,
        "gas_keccak": keccaks,
        "gas_precompiles": ecmul + ecadd + pairing + inv,
        "gas_memory": memory,
        "gas_execution": execution,
    }
    if calldata is not None:
        nz = sum(1 for b in calldata if b)
        intrinsic = 21000 + 16 * nz + 4 * (len(calldata) - nz)
        out["gas_intrinsic"] = intrinsic
        out["gas_total"] = execution + intrinsic
    return out


def estimate_deployed_size(sol_src: str) -> dict:
    """Deployed (runtime) bytecode size estimate + EIP-170 assessment."""
    c = analyze_verifier(sol_src)
    size = (33 * c["push32_literals"]
            + 18 * (c["mulmod"] + c["addmod"])
            + 8 * c["tmp_slots"]
            + 25 * c["calldata_slice"]
            + 30 * max(0, c["statements"] - c["mulmod"] - c["addmod"])
            + 2200)
    limit = 24576
    if size <= limit * 3 // 4:
        risk = "ok"
    elif size <= limit:
        risk = "tight"
    else:
        risk = "exceeds-eip170"
    return {
        "deployed_bytes_estimate": size,
        "eip170_limit": limit,
        "deployed_size_risk": risk,
        "note": "static per-construct model (see evm/gas.py header); "
                "the dominant term is PUSH32 literals x33B — large shapes "
                "must split the verifier or move constants to calldata",
    }

"""NTT sharded over a device mesh (four-step/Bailey decomposition).

SURVEY.md §2c/§5: the reference's FFT is rayon shared-memory; the TPU-native
equivalent shards one large NTT across chips with the transpose riding ICI as
an all-to-all — the classic distributed-FFT structure:

    view x as A[jr, jc] = x[jc*Rr + jr]            (Rr x Cc matrix, Rr*Cc = n)
    1. per-row NTT of length Cc with root omega^Rr     (local: rows sharded)
    2. elementwise twiddle A[jr, kc] *= omega^(jr*kc)  (local)
    3. transpose                                        (all_to_all over ICI)
    4. per-row NTT of length Rr with root omega^Cc     (local)

and X[kr*Cc + kc] lands at out[kc, kr] — `sharded_ntt` returns the flat
natural-order result. Identity with the single-device kernel is pinned by
`tests/test_parallel.py::TestShardedNTT` on the virtual 8-device mesh.

Program + twiddle residency (ISSUE 13): the SPMD program is built once per
(plan, logn, omega) and the [Rr, Cc, 16] twiddle matrix is device_put onto
the mesh once and kept resident — the prover hits the same (domain, root)
pair for every polynomial of a proof, and the previous per-call re-jit +
twiddle re-transfer was (with sharded_msm's identical bug) the
MULTICHIP rc=124 root cause: ~40 NTTs per prove, each paying a full 8-way
SPMD retrace/relower on a 1-core host.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..fields import bn254
from ..observability import compilelog
from ..ops import field_ops as F, ntt as NTT
from .plan import ShardingPlan, plan_for_mesh

R = bn254.R


# Montgomery [Rr, Cc, 16] table of omega^(jr*kc). Shared with the
# single-device four-step kernel and LRU-budgeted there
# (SPECTRE_NTT_TABLE_MB): the prover reuses one omega per domain, but a
# long-running service touching many circuit sizes must stay bounded.
_twiddle_matrix = NTT._twiddle_matrix

# compiled SPMD programs keyed (plan, axis, logn, omega); mesh-resident
# twiddles keyed the same. Stable function objects are the point — see
# module docstring.
_RUNNERS: dict = {}
_TWIDDLES: dict = {}

# runner registry (trace-cache hygiene contract, parallel/plan.py):
# declared builders are cross-checked by analysis/trace_lint
# (TC-UNCACHED-RUNNER) and exercised by its retrace probes.
TRACE_RUNNER_CACHES = (("_ntt_runner", "_RUNNERS"),)


# --- per-shard local compute (no collectives) -------------------------------
# Extracted from the shard_map closure so the kernel linter can trace them
# at tiny shapes without a mesh (analysis/kernel_lint known-root table).

def _rows_local(block, twb, omega_row: int, mode: str,
                kernel: str = "stages"):
    """Steps 1-2 on one shard: length-Cc NTT along each local row, then the
    elementwise twiddle multiply. block/twb: [rows_local, Cc, 16]."""
    y = jax.vmap(
        lambda row: NTT._fwd_kernel.__wrapped__(row, omega_row, None,
                                                mode, kernel))(block)
    return F.mont_mul(F.fr_ctx(), y, twb)


def _cols_local(y, omega_col: int, mode: str, kernel: str = "stages"):
    """Step 4 on one shard: length-Rr NTT along each post-transpose row."""
    return jax.vmap(
        lambda row: NTT._fwd_kernel.__wrapped__(row, omega_col, None,
                                                mode, kernel))(y)


def _ntt_runner(plan: ShardingPlan, axis: str, logn: int, omega: int):
    s = plan.mesh.shape[axis]
    logr = logn // 2
    logc = logn - logr
    # the LOCAL transforms are sqrt(n)-sized; resolve their mode/kernel once
    # at build time and key the cached program on them (the env knobs must
    # not silently go stale inside a resident program)
    row_mode = NTT._resolve_mode(None, logc)
    col_mode = NTT._resolve_mode(None, logr)
    row_kernel = NTT._resolve_kernel(None, row_mode)
    col_kernel = NTT._resolve_kernel(None, col_mode)
    key = (plan.key, axis, logn, omega, row_mode, col_mode,
           row_kernel, col_kernel)
    hit = _RUNNERS.get(key)
    if hit is not None:
        return hit

    rr, cc = 1 << logr, 1 << logc
    assert rr % s == 0 and cc % s == 0, \
        f"shard count {s} must divide both matrix dims {rr}x{cc}"
    omega_row = pow(omega, rr, R)        # length-Cc root (step 1)
    omega_col = pow(omega, cc, R)        # length-Rr root (step 4)
    spec = P(axis, None, None)

    @functools.partial(
        shard_map, mesh=plan.mesh, in_specs=(spec, spec), out_specs=spec,
        check_vma=False)
    def run(block, twb):
        y = _rows_local(block, twb, omega_row, row_mode, row_kernel)
        # step 3: transpose via all-to-all (split columns, gather rows)
        y = jax.lax.all_to_all(y, axis, split_axis=1, concat_axis=0,
                               tiled=True)              # [rr, cc/s, 16]
        y = y.transpose(1, 0, 2)                        # [cc/s, rr, 16]
        return _cols_local(y, omega_col, col_mode, col_kernel)

    fn = jax.jit(run)
    if len(_RUNNERS) > 32:
        _RUNNERS.clear()
    _RUNNERS[key] = fn
    return fn


def _resident_twiddle(plan: ShardingPlan, axis: str, logn: int, omega: int):
    key = (plan.key, axis, logn, omega)
    tw = _TWIDDLES.get(key)
    if tw is None:
        logr = logn // 2
        tw = jax.device_put(
            jnp.asarray(_twiddle_matrix(logr, logn - logr, omega)),
            plan.sharding(P(axis, None, None)))
        if len(_TWIDDLES) > 8:
            _TWIDDLES.clear()
        _TWIDDLES[key] = tw
    return tw


def sharded_ntt(a: jax.Array, omega: int, mesh: Mesh,
                axis: str = "data",
                plan: ShardingPlan | None = None) -> jax.Array:
    """Distributed NTT of a [n, 16] Montgomery limb tensor; returns the same
    natural-order [n, 16] result as `ops.ntt.ntt(a, omega)`.

    n must split as Rr*Cc with the shard count dividing both Rr and Cc."""
    plan = plan or plan_for_mesh(mesh)
    n = a.shape[0]
    logn = n.bit_length() - 1
    assert 1 << logn == n, "n must be a power of two"
    logr = logn // 2
    rr, cc = 1 << logr, 1 << (logn - logr)

    run = _ntt_runner(plan, axis, logn, omega)
    twd = _resident_twiddle(plan, axis, logn, omega)

    # A[jr, jc] = x[jc*rr + jr]
    A = a.reshape(cc, rr, 16).transpose(1, 0, 2)
    Ad = jax.device_put(A, plan.sharding(P(axis, None, None)))
    # compile attribution: a miss here is THIS runner's retrace, not the
    # parent prove phase's (per-entry-point compile telemetry)
    with compilelog.entry_point("parallel.sharded_ntt"):
        out = run(Ad, twd)                               # [cc, rr, 16]
    # out[kc, kr] = X[kr*cc + kc]
    return out.transpose(1, 0, 2).reshape(n, 16)

"""NTT sharded over a device mesh (four-step/Bailey decomposition).

SURVEY.md §2c/§5: the reference's FFT is rayon shared-memory; the TPU-native
equivalent shards one large NTT across chips with the transpose riding ICI as
an all-to-all — the classic distributed-FFT structure:

    view x as A[jr, jc] = x[jc*Rr + jr]            (Rr x Cc matrix, Rr*Cc = n)
    1. per-row NTT of length Cc with root omega^Rr     (local: rows sharded)
    2. elementwise twiddle A[jr, kc] *= omega^(jr*kc)  (local)
    3. transpose                                        (all_to_all over ICI)
    4. per-row NTT of length Rr with root omega^Cc     (local)

and X[kr*Cc + kc] lands at out[kc, kr] — `sharded_ntt` returns the flat
natural-order result. Identity with the single-device kernel is pinned by
`tests/test_parallel.py::TestShardedNTT` on the virtual 8-device mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fields import bn254
from ..ops import field_ops as F, ntt as NTT

R = bn254.R


# Montgomery [Rr, Cc, 16] table of omega^(jr*kc). Shared with the
# single-device four-step kernel and LRU-budgeted there
# (SPECTRE_NTT_TABLE_MB): the prover reuses one omega per domain, but a
# long-running service touching many circuit sizes must stay bounded.
_twiddle_matrix = NTT._twiddle_matrix


def sharded_ntt(a: jax.Array, omega: int, mesh: Mesh,
                axis: str = "data") -> jax.Array:
    """Distributed NTT of a [n, 16] Montgomery limb tensor; returns the same
    natural-order [n, 16] result as `ops.ntt.ntt(a, omega)`.

    n must split as Rr*Cc with the shard count dividing both Rr and Cc."""
    n = a.shape[0]
    logn = n.bit_length() - 1
    assert 1 << logn == n, "n must be a power of two"
    s = mesh.shape[axis]
    logr = logn // 2
    logc = logn - logr
    rr, cc = 1 << logr, 1 << logc
    assert rr % s == 0 and cc % s == 0, \
        f"shard count {s} must divide both matrix dims {rr}x{cc}"

    omega_row = pow(omega, rr, R)        # length-Cc root (step 1)
    omega_col = pow(omega, cc, R)        # length-Rr root (step 4)
    tw = _twiddle_matrix(logr, logc, omega)
    ctx = F.fr_ctx()

    # A[jr, jc] = x[jc*rr + jr]
    A = a.reshape(cc, rr, 16).transpose(1, 0, 2)
    spec = P(*( [axis] + [None] * 2 ))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
        check_vma=False)
    def run(block, twb):
        # step 1: length-Cc NTT along the local row axis
        y = jax.vmap(lambda row: NTT.ntt(row, omega_row))(block)
        # step 2: twiddle
        y = F.mont_mul(ctx, y, twb)
        # step 3: transpose via all-to-all (split columns, gather rows)
        y = jax.lax.all_to_all(y, axis, split_axis=1, concat_axis=0,
                               tiled=True)              # [rr, cc/s, 16]
        y = y.transpose(1, 0, 2)                        # [cc/s, rr, 16]
        # step 4: length-Rr NTT per (now-local) column of the original
        return jax.vmap(lambda row: NTT.ntt(row, omega_col))(y)

    sharding = NamedSharding(mesh, spec)
    Ad = jax.device_put(A, sharding)
    twd = jax.device_put(jnp.asarray(tw), sharding)
    out = jax.jit(run)(Ad, twd)                          # [cc, rr, 16]
    # out[kc, kr] = X[kr*cc + kc]
    return out.transpose(1, 0, 2).reshape(n, 16)

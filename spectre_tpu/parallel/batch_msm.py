"""Batched MSM with the batch axis sharded over the device mesh (DP axis).

SURVEY.md §2c(b): inter-proof / multi-column batching. One commitment base
(the SRS tau powers), B scalar vectors (advice columns of one proof, or
columns of several proofs); each device computes full Pippenger MSMs for its
slice of the batch — embarrassingly parallel, no collectives beyond the
output gather. Complements `sharded_msm` (intra-MSM TP axis): this one wins
when there are many independent MSMs; that one when a single MSM is huge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import compilelog
from ..ops import msm as MSM


def _batch_mesh(ndev: int | None = None) -> Mesh:
    if ndev is None:
        # the interned plan's 1-D batch mesh: same device subset as the
        # ("data","win") mesh (honors SPECTRE_MESH_SHAPE), stable object so
        # the runner caches below never churn
        from .plan import current_plan
        return current_plan().batch_mesh
    devs = jax.devices()[:ndev]
    return Mesh(devs, ("batch",))


# replicated-base and jitted-SPMD caches: commit_many calls this once per
# chunk with the SAME base — without these every chunk re-broadcasts the
# full SRS to all devices and re-wraps jit (losing its trace cache)
_repl_cache: dict = {}      # (id(points), n, mesh key) -> (strong ref, dev arr)
_runner_cache: dict = {}    # (mesh key, c) -> jitted shard_map program

# runner registry (trace-cache hygiene contract, parallel/plan.py):
# declared builders are cross-checked by analysis/trace_lint
# (TC-UNCACHED-RUNNER) and exercised by its retrace probes.
TRACE_RUNNER_CACHES = (
    ("_runner", "_runner_cache"),
    ("_runner_glv", "_runner_cache"),
)


def _mesh_key(mesh: Mesh) -> tuple:
    return tuple(d.id for d in mesh.devices.flat)


def _replicated_base(points, mesh: Mesh):
    key = (id(points), getattr(points, "shape", (0,))[0], _mesh_key(mesh))
    hit = _repl_cache.get(key)
    if hit is not None and hit[0] is points:
        return hit[1]
    dev = jax.device_put(points, NamedSharding(mesh, P(None, None, None)))
    if len(_repl_cache) > 8:
        _repl_cache.clear()
    _repl_cache[key] = (points, dev)
    return dev


def _runner(mesh: Mesh, c: int):
    key = (_mesh_key(mesh), c)
    fn = _runner_cache.get(key)
    if fn is None:
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, None, None), P("batch", None, None)),
            out_specs=P("batch", None, None),
            check_vma=False,
        )
        def run(p, sc):
            # lax.map (not vmap): sequential per local batch element keeps
            # HBM traffic flat — the parallelism is the mesh axis
            return jax.lax.map(
                lambda s: MSM.combine_windows.__wrapped__(
                    MSM.msm_windows.__wrapped__(p, s, c), c), sc)

        fn = jax.jit(run)
        _runner_cache[key] = fn
    return fn


def _runner_glv(mesh: Mesh, c: int, nbits: int, signed: bool):
    """GLV-prepped variant: scalars are half-scalar magnitudes riding with a
    per-row sign mask; `signed` picks the signed-digit window kernel (sign
    folded into the digit mask) vs point-level negation + unsigned windows."""
    key = (_mesh_key(mesh), c, nbits, signed)
    fn = _runner_cache.get(key)
    if fn is None:
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, None, None), P("batch", None, None),
                      P("batch", None)),
            out_specs=P("batch", None, None),
            check_vma=False,
        )
        def run(p, sc, ng):
            def one(args):
                s, g = args
                if signed:
                    wins = MSM.msm_windows_signed.__wrapped__(
                        p, s, g, c, nbits)
                else:
                    wins = MSM._msm_windows_impl(
                        MSM._apply_sign.__wrapped__(p, g), s, c, nbits)
                return MSM.combine_windows.__wrapped__(wins, c)

            return jax.lax.map(one, (sc, ng))

        fn = jax.jit(run)
        _runner_cache[key] = fn
    return fn


def batch_msm_dp(points, scalars_batch, c: int | None = None,
                 mesh: Mesh | None = None, neg_batch=None, nbits: int = 254,
                 signed: bool = False):
    """points [n,3,16] projective Montgomery (replicated); scalars_batch
    [B,n,L] standard limbs. Returns [B,3,16] projective results.

    B is padded to a multiple of the mesh size with zero scalar vectors
    (their MSM is the identity; padding is dropped before returning).

    GLV threading (backend.msm_many): pass the endomorphism-EXPANDED base,
    half-scalar magnitudes (L=8), `neg_batch` [B,n] sign masks, and
    nbits=glv.glv_bits(); signed=True routes through the signed-digit
    kernels (halved buckets).

    Window width: explicit `c` wins; otherwise `MSM.default_window`, which
    honors the SPECTRE_MSM_WINDOW override before its tuned table — one env
    knob sweeps every MSM path (bench.py --sweep-window)."""
    n = points.shape[0]
    if c is None:
        c = MSM.default_window(n, signed=signed)
    if MSM.msm_impl() == "pallas":
        # the DP shard_map runner has no pallas lowering — fall back to
        # XLA visibly (health counter + provenance event, ops/msm.py)
        MSM._record_pallas_degrade(MSM.msm_mode(), n, c,
                                   "parallel.batch_msm_dp")
    mesh = mesh or _batch_mesh()
    ndev = mesh.shape["batch"]
    b = scalars_batch.shape[0]
    pad = (-b) % ndev
    if pad:
        scalars_batch = jnp.concatenate(
            [jnp.asarray(scalars_batch),
             jnp.zeros((pad,) + scalars_batch.shape[1:],
                       dtype=scalars_batch.dtype)])
        if neg_batch is not None:
            neg_batch = jnp.concatenate(
                [jnp.asarray(neg_batch),
                 jnp.zeros((pad,) + neg_batch.shape[1:], dtype=bool)])
    sb = jax.device_put(jnp.asarray(scalars_batch),
                        NamedSharding(mesh, P("batch", None, None)))
    pts = _replicated_base(points, mesh)
    # per-entry-point compile attribution (innermost entry wins)
    with compilelog.entry_point("parallel.batch_msm"):
        if neg_batch is None:
            out = _runner(mesh, c)(pts, sb)
        else:
            ngb = jax.device_put(jnp.asarray(neg_batch),
                                 NamedSharding(mesh, P("batch", None)))
            out = _runner_glv(mesh, c, nbits, signed)(pts, sb, ngb)
    return out[:b]

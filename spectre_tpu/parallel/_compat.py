"""shard_map import/signature compatibility across JAX versions.

Newer JAX exports `jax.shard_map` with a `check_vma` kwarg; 0.4.x ships it
under `jax.experimental.shard_map` with the older `check_rep` name for the
same replication-check toggle. The mesh kernels are written against the new
spelling; this shim rewrites it where needed so one source runs on both.
"""

from __future__ import annotations

import functools

try:
    from jax import shard_map as _shard_map
    _NATIVE = True
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map
    _NATIVE = False


@functools.wraps(_shard_map)
def shard_map(*args, **kwargs):
    if not _NATIVE and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)

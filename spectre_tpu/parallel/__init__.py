"""Distributed execution: device meshes, sharded MSM/NTT, batched proving.

The reference is a single-process prover (rayon shared-memory, SURVEY.md §2c);
the TPU-native equivalents:
  (a) intra-proof sharding: one MSM/NTT sharded over chips via shard_map
      (tensor-parallel analog) — partial bucket/window sums all-reduced over ICI
  (b) inter-proof batching: vmap/pmap over independent proofs (data-parallel)
  (c) pipeline: witness gen (host) overlapped with device commit phases
"""

from .mesh import make_mesh, default_mesh, MeshShapeError  # noqa: F401
from .plan import ShardingPlan, plan_for_mesh, current_plan  # noqa: F401
from .sharded_msm import sharded_msm  # noqa: F401

"""MSM sharded over a device mesh (tensor-parallel analog for the prover).

Decomposition (SURVEY.md §2c(a)): points are sharded along the mesh "data"
axis — each shard computes per-window partial sums over its local points —
and Pippenger windows are sharded along the "win" axis. Partial window sums
are combined with an all-gather over "data" followed by a local projective
tree-fold (EC addition is not a psum-able monoid over limb tensors, so the
reduction is an explicit gather+fold riding ICI), then windows are gathered
over "win" and the final double-and-add combine runs replicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._compat import shard_map

from ..ops import ec, msm as MSM


def _fold_points(stacked):
    """Tree-fold [k, nwin, 3, 16] partial sums -> [nwin, 3, 16]."""
    acc = stacked
    while acc.shape[0] > 1:
        k = acc.shape[0]
        half = k // 2
        merged = ec.padd(acc[:half], acc[half:2 * half])
        acc = jnp.concatenate([merged, acc[2 * half:]], axis=0) if k % 2 else merged
    return acc[0]


def sharded_msm(points, scalars, c: int, mesh: Mesh):
    """MSM over a ("data", "win") mesh.

    points [n, 3, 16] projective Montgomery, scalars [n, 16] standard limbs;
    n must divide evenly by the data-axis size. Returns a replicated [3, 16]
    projective result."""
    nwin = (254 + c - 1) // c
    n_win_shards = mesh.shape["win"]
    # pad the window count so it shards evenly; extra windows read digit bits
    # beyond 254 which are always zero -> contribute infinity, harmless.
    nwin_padded = ((nwin + n_win_shards - 1) // n_win_shards) * n_win_shards

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("data", None, None), P("data", None)),
        out_specs=P(None, None, None),
        check_vma=False,  # scan carries start as unvarying constants (vma mismatch)
    )
    def windows_phase(pts, sc):
        widx = jax.lax.axis_index("win")
        nloc = nwin_padded // n_win_shards

        def one_window(i):
            w = widx * nloc + i
            d = MSM._digits_traced(sc, w, c)
            # mask digits for windows beyond the real count
            d = jnp.where(w < nwin, d, 0)
            return MSM._segmented_bucket_sums(pts, d, 1 << c)

        bucket_sums = jax.lax.map(one_window, jnp.arange(nloc))
        local = MSM._aggregate_buckets(bucket_sums, c)     # [nloc, 3, 16]
        # combine partials across the data axis: gather + projective fold
        gathered = jax.lax.all_gather(local, "data")        # [ndata, nloc, 3, 16]
        folded = _fold_points(gathered)                     # [nloc, 3, 16]
        # gather window shards: [nwin_shards, nloc, 3, 16] -> flatten
        wins = jax.lax.all_gather(folded, "win")
        return wins.reshape(nwin_padded, 3, ec.F.NLIMBS)

    # jit the SPMD program: eager shard_map calls bypass the persistent
    # compile cache, which made every dryrun/bench pay the full multi-minute
    # XLA CPU compile (round-1 MULTICHIP timeout)
    window_sums = jax.jit(windows_phase)(points, scalars)[:nwin]
    return MSM.combine_windows(window_sums, c)


def shard_points(points, scalars, mesh: Mesh):
    """Place host arrays onto the mesh with data-axis sharding."""
    ps = NamedSharding(mesh, P("data", None, None))
    ss = NamedSharding(mesh, P("data", None))
    return jax.device_put(points, ps), jax.device_put(scalars, ss)

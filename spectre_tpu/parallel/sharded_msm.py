"""MSM sharded over a device mesh (tensor-parallel analog for the prover).

Decomposition (SURVEY.md §2c(a)): points are sharded along the mesh "data"
axis — each shard computes per-window partial sums over its local points —
and Pippenger windows are sharded along the "win" axis. Partial window sums
are combined with an all-gather over "data" followed by a local projective
tree-fold (EC addition is not a psum-able monoid over limb tensors, so the
reduction is an explicit gather+fold riding ICI), then windows are gathered
over "win" and the final double-and-add combine runs replicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._compat import shard_map

from ..ops import ec, msm as MSM


def _fold_points(stacked):
    """Tree-fold [k, nwin, 3, 16] partial sums -> [nwin, 3, 16]."""
    acc = stacked
    while acc.shape[0] > 1:
        k = acc.shape[0]
        half = k // 2
        merged = ec.padd(acc[:half], acc[half:2 * half])
        acc = jnp.concatenate([merged, acc[2 * half:]], axis=0) if k % 2 else merged
    return acc[0]


def sharded_msm(points, scalars, c: int, mesh: Mesh, nbits: int = 254,
                signed: bool = False, neg=None):
    """MSM over a ("data", "win") mesh.

    points [n, 3, 16] projective Montgomery, scalars [n, L] standard limbs
    (L=16 full scalars, L=8 GLV half-scalar magnitudes with nbits set to
    glv.glv_bits()); n must divide evenly by the data-axis size. Returns a
    replicated [3, 16] projective result.

    The GLV scalar-prep stage happens BEFORE sharding (backend._msm_sharded:
    host decomposition, endomorphism expansion, sign handling), so rows here
    are already aligned (point, scalar[, sign]) triples and the data axis
    shards them uniformly. signed=True runs the signed-digit recode PER
    SHARD (each shard holds whole scalars, so the carry chain never crosses
    a shard boundary) with `neg` [n] bool sign masks folded into the digit
    signs; buckets halve to 2^(c-1)+1."""
    nwin = (nbits + c) // c if signed else (nbits + c - 1) // c
    n_win_shards = mesh.shape["win"]
    # pad the window count so it shards evenly; extra windows read digit bits
    # beyond nbits which are always zero -> contribute infinity, harmless.
    nwin_padded = ((nwin + n_win_shards - 1) // n_win_shards) * n_win_shards
    nbuckets = (1 << (c - 1)) + 1 if signed else 1 << c

    in_specs = [P("data", None, None), P("data", None)]
    args = [points, scalars]
    if signed:
        in_specs.append(P("data"))
        args.append(neg if neg is not None
                    else jnp.zeros(points.shape[0], dtype=bool))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, None, None),
        check_vma=False,  # scan carries start as unvarying constants (vma mismatch)
    )
    def windows_phase(pts, sc, *rest):
        widx = jax.lax.axis_index("win")
        nloc = nwin_padded // n_win_shards

        if signed:
            ng = rest[0]
            digs = MSM.signed_digit_stream(sc, c, nwin)   # [nwin, n_local]
            if nwin_padded > nwin:
                digs = jnp.concatenate(
                    [digs, jnp.zeros((nwin_padded - nwin,) + digs.shape[1:],
                                     dtype=digs.dtype)])
            local_digs = jax.lax.dynamic_slice_in_dim(
                digs, widx * nloc, nloc, axis=0)

            def one_window(i):
                s = local_digs[i]
                eff = ec.cneg((s < 0) ^ ng, pts)
                return MSM._segmented_bucket_sums(eff, jnp.abs(s), nbuckets)
        else:
            def one_window(i):
                w = widx * nloc + i
                d = MSM._digits_traced(sc, w, c)
                # mask digits for windows beyond the real count
                d = jnp.where(w < nwin, d, 0)
                return MSM._segmented_bucket_sums(pts, d, nbuckets)

        bucket_sums = jax.lax.map(one_window, jnp.arange(nloc))
        local = MSM._aggregate_buckets(bucket_sums, c)     # [nloc, 3, 16]
        # combine partials across the data axis: gather + projective fold
        gathered = jax.lax.all_gather(local, "data")        # [ndata, nloc, 3, 16]
        folded = _fold_points(gathered)                     # [nloc, 3, 16]
        # gather window shards: [nwin_shards, nloc, 3, 16] -> flatten
        wins = jax.lax.all_gather(folded, "win")
        return wins.reshape(nwin_padded, 3, ec.F.NLIMBS)

    # jit the SPMD program: eager shard_map calls bypass the persistent
    # compile cache, which made every dryrun/bench pay the full multi-minute
    # XLA CPU compile (round-1 MULTICHIP timeout)
    window_sums = jax.jit(windows_phase)(*args)[:nwin]
    return MSM.combine_windows(window_sums, c)


def shard_points(points, scalars, mesh: Mesh):
    """Place host arrays onto the mesh with data-axis sharding."""
    ps = NamedSharding(mesh, P("data", None, None))
    ss = NamedSharding(mesh, P("data", None))
    return jax.device_put(points, ps), jax.device_put(scalars, ss)

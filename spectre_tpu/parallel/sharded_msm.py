"""MSM sharded over a device mesh (tensor-parallel analog for the prover).

Decomposition (SURVEY.md §2c(a)): points are sharded along the mesh "data"
axis — each shard computes per-window partial sums over its local points —
and Pippenger windows are sharded along the "win" axis. Partial window sums
are combined with an all-gather over "data" followed by a local projective
tree-fold (EC addition is not a psum-able monoid over limb tensors, so the
reduction is an explicit gather+fold riding ICI), then windows are gathered
over "win" and the final double-and-add combine runs replicated.

Program caching (ISSUE 13 tentpole): every SPMD program here is built ONCE
per (ShardingPlan, static-shape-class) and held in module-level runner
caches. The previous shape — a fresh shard_map closure wrapped in a fresh
`jax.jit` per call — re-traced and re-lowered the full 8-way SPMD program
for every MSM in a prove, which is exactly the MULTICHIP_r01/r05 rc=124
timeout. The persistent compile cache never helped because tracing +
lowering (not XLA compilation) was the per-call cost.

Fixed-base mode (`SPECTRE_MSM_MODE=fixed`) runs sharded since ISSUE 13:
the [nwin, N, 3, 16] window table is built BY the mesh (each data shard
runs the doubling chains over its own point rows) and stays resident
sharded along the row axis — `T[w]` slices co-resident with their point
shards, per `ShardingPlan.table_spec`. Cross-window bucket merge before a
single aggregation pass is still sound (the table bases carry `2^{cw}`),
and the per-DEVICE table budget is what gates degradation: a mesh can
afford fixed tables a single device cannot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import shard_map

from ..observability import compilelog
from ..ops import ec, msm as MSM
from .plan import ShardingPlan, plan_for_mesh


def _fold_points(stacked):
    """Tree-fold [k, *, 3, 16] partial sums -> [*, 3, 16]."""
    acc = stacked
    while acc.shape[0] > 1:
        k = acc.shape[0]
        half = k // 2
        merged = ec.padd(acc[:half], acc[half:2 * half])
        acc = jnp.concatenate([merged, acc[2 * half:]], axis=0) if k % 2 else merged
    return acc[0]


# compiled SPMD programs, one per (plan, shape-class). Keys embed plan.key
# plus every static parameter the closure bakes in; values are stable
# jitted function objects so jax's trace cache actually hits.
_RUNNERS: dict = {}

# runner registry (the trace-cache hygiene contract, parallel/plan.py):
# every builder that stores a jitted program in a module cache is declared
# here; analysis/trace_lint cross-checks the pairs against the AST
# (TC-UNCACHED-RUNNER) and its retrace probes exercise the runners.
TRACE_RUNNER_CACHES = (
    ("_windows_runner", "_RUNNERS"),
    ("_table_build_runner", "_RUNNERS"),
    ("_fixed_runner", "_RUNNERS"),
)


def _nwin_for(c: int, nbits: int, signed: bool) -> int:
    return (nbits + c) // c if signed else (nbits + c - 1) // c


# --- per-shard local compute (no collectives) -------------------------------
# Extracted from the shard_map closures so the kernel linter can trace them
# at tiny shapes without a mesh (analysis/kernel_lint registers each as a
# known root); the SPMD bodies below call these with widx = axis_index.

def _pad_digit_windows(digs, nwin_padded):
    if nwin_padded > digs.shape[0]:
        digs = jnp.concatenate(
            [digs, jnp.zeros((nwin_padded - digs.shape[0],) + digs.shape[1:],
                             dtype=digs.dtype)])
    return digs


def _shard_windows_signed(pts, sc, ng, widx, c, nwin, nwin_padded, nloc,
                          nbuckets):
    """One shard's window partial sums, signed-digit path: local recode
    (carry chains stay within whole scalars, so per-shard recode is exact),
    sign-folded bucket accumulation, aggregation. Returns [nloc, 3, 16]."""
    digs = _pad_digit_windows(
        MSM.signed_digit_stream(sc, c, nwin), nwin_padded)  # [nwin_p, n_local]
    local_digs = jax.lax.dynamic_slice_in_dim(
        digs, widx * nloc, nloc, axis=0)

    def one_window(i):
        s = local_digs[i]
        eff = ec.cneg((s < 0) ^ ng, pts)
        return MSM._segmented_bucket_sums(eff, jnp.abs(s), nbuckets)

    bucket_sums = jax.lax.map(one_window, jnp.arange(nloc))
    return MSM._aggregate_buckets(bucket_sums, c)           # [nloc, 3, 16]


def _shard_windows_unsigned(pts, sc, widx, c, nwin, nwin_padded, nloc,
                            nbuckets):
    """One shard's window partial sums, vanilla unsigned digits; windows
    past the real count contribute digit 0 (bucket 0 is dropped by the
    aggregation). Returns [nloc, 3, 16]."""
    def one_window(i):
        w = widx * nloc + i
        d = MSM._digits_traced(sc, w, c)
        d = jnp.where(w < nwin, d, 0)
        return MSM._segmented_bucket_sums(pts, d, nbuckets)

    bucket_sums = jax.lax.map(one_window, jnp.arange(nloc))
    return MSM._aggregate_buckets(bucket_sums, c)


def _shard_fixed_local(tab, sc, ng, widx, c, nwin, nwin_padded, nloc,
                       nbuckets):
    """One shard of the fixed-base phase: window slices taken locally from
    the resident table, bucket sums merged ACROSS the shard's windows (the
    table bases carry 2^{cw}, so one aggregation pass at the end of the
    full reduction is sound). Returns [nbuckets, 3, 16]."""
    digs = _pad_digit_windows(
        MSM.signed_digit_stream(sc, c, nwin), nwin_padded)
    local_digs = jax.lax.dynamic_slice_in_dim(
        digs, widx * nloc, nloc, axis=0)
    local_tab = jax.lax.dynamic_slice_in_dim(
        tab, widx * nloc, nloc, axis=0)       # [nloc, n_local, 3, 16]

    def one_window(args):
        tw, s = args
        eff = ec.cneg((s < 0) ^ ng, tw)
        return MSM._segmented_bucket_sums(eff, jnp.abs(s), nbuckets)

    bucket_sums = jax.lax.map(
        one_window, (local_tab, local_digs))  # [nloc, nb, 3, 16]
    return _fold_points(bucket_sums)          # [nb, 3, 16]


def _build_table_local(pts_local, c, nwin, nwin_padded):
    """One shard of the fixed-base table build: c-doubling chains over the
    shard's own expanded rows (pointwise per row, fully local), padded
    windows filled with infinity. Returns [nwin_padded, n_local, 3, 16]."""
    tab = MSM._build_window_table.__wrapped__(pts_local, c, nwin)
    if nwin_padded > nwin:
        pad = ec.inf_point((nwin_padded - nwin, tab.shape[1]))
        tab = jnp.concatenate([tab, pad.astype(tab.dtype)], axis=0)
    return tab


def _windows_runner(plan: ShardingPlan, c: int, nbits: int, signed: bool):
    """Cached jitted windows-phase program for variable-base MSM."""
    key = (plan.key, "windows", c, nbits, signed)
    fn = _RUNNERS.get(key)
    if fn is not None:
        return fn

    nwin = _nwin_for(c, nbits, signed)
    nwin_padded = plan.pad_windows(nwin)
    nbuckets = (1 << (c - 1)) + 1 if signed else 1 << c
    n_win_shards = plan.nwin_shards
    data_axis, win_axis = plan.data_axis, plan.win_axis

    in_specs = [plan.point_spec, plan.scalar_spec]
    if signed:
        in_specs.append(plan.sign_spec)

    @functools.partial(
        shard_map, mesh=plan.mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, None, None),
        check_vma=False,  # scan carries start as unvarying constants (vma mismatch)
    )
    def windows_phase(pts, sc, *rest):
        widx = jax.lax.axis_index(win_axis)
        nloc = nwin_padded // n_win_shards

        if signed:
            local = _shard_windows_signed(
                pts, sc, rest[0], widx, c, nwin, nwin_padded, nloc, nbuckets)
        else:
            local = _shard_windows_unsigned(
                pts, sc, widx, c, nwin, nwin_padded, nloc, nbuckets)
        # combine partials across the data axis: gather + projective fold
        gathered = jax.lax.all_gather(local, data_axis)     # [ndata, nloc, 3, 16]
        folded = _fold_points(gathered)                     # [nloc, 3, 16]
        # gather window shards: [nwin_shards, nloc, 3, 16] -> flatten
        wins = jax.lax.all_gather(folded, win_axis)
        return wins.reshape(nwin_padded, 3, ec.F.NLIMBS)

    fn = jax.jit(windows_phase)
    if len(_RUNNERS) > 64:
        _RUNNERS.clear()
    _RUNNERS[key] = fn
    return fn


def sharded_msm(points, scalars, c: int, mesh: Mesh, nbits: int = 254,
                signed: bool = False, neg=None,
                plan: ShardingPlan | None = None):
    """MSM over a ("data", "win") mesh.

    points [n, 3, 16] projective Montgomery, scalars [n, L] standard limbs
    (L=16 full scalars, L=8 GLV half-scalar magnitudes with nbits set to
    glv.glv_bits()); n must divide evenly by the data-axis size. Returns a
    replicated [3, 16] projective result.

    The GLV scalar-prep stage happens BEFORE sharding (backend._msm_sharded:
    host decomposition, endomorphism expansion, sign handling), so rows here
    are already aligned (point, scalar[, sign]) triples and the data axis
    shards them uniformly. signed=True runs the signed-digit recode PER
    SHARD (each shard holds whole scalars, so the carry chain never crosses
    a shard boundary) with `neg` [n] bool sign masks folded into the digit
    signs; buckets halve to 2^(c-1)+1."""
    plan = plan or plan_for_mesh(mesh)
    nwin = _nwin_for(c, nbits, signed)
    args = [points, scalars]
    if signed:
        args.append(neg if neg is not None
                    else jnp.zeros(points.shape[0], dtype=bool))
    # any compile fired here is attributed to THIS runner (not lumped
    # into the parent prove phase) — per-entry-point compile telemetry
    with compilelog.entry_point("parallel.sharded_msm"):
        window_sums = _windows_runner(plan, c, nbits, signed)(*args)[:nwin]
        return MSM.combine_windows(window_sums, c)


def shard_points(points, scalars, mesh: Mesh,
                 plan: ShardingPlan | None = None):
    """Place host arrays onto the mesh with data-axis sharding."""
    plan = plan or plan_for_mesh(mesh)
    return (plan.place(points, plan.point_spec),
            plan.place(scalars, plan.scalar_spec))


# ---------------------------------------------------------------------------
# fixed-base mode on the mesh (sharded window tables)
# ---------------------------------------------------------------------------

def _sharded_table_bytes(n_expanded: int, c: int, nbits: int,
                         plan: ShardingPlan) -> int:
    """Exact bytes of the mesh table [nwin_padded, n_expanded, 3, 16] u32
    (n_expanded = endo-expanded, row-padded point count)."""
    nwin_padded = plan.pad_windows(_nwin_for(c, nbits, signed=True))
    return nwin_padded * n_expanded * 3 * 16 * 4


def fixed_fits_mesh(n_expanded: int, c: int, nbits: int,
                    plan: ShardingPlan) -> bool:
    """Per-DEVICE budget check for a mesh-sharded fixed-base table: each
    data shard holds table_bytes/ndata (the win axis replicates its row
    slice), so the SPECTRE_MSM_TABLE_MB budget applies per shard — a mesh
    affords tables `ndata`x larger than one device."""
    return _sharded_table_bytes(n_expanded, c, nbits, plan) // plan.ndata \
        <= MSM._TABLES.budget


def _degrade_fixed_mesh(n_expanded: int, c: int, nbits: int,
                        plan: ShardingPlan) -> bool:
    """Mesh analog of ops.msm._degrade_fixed: fall back to glv+signed when
    even the per-shard table slice busts the budget, recording the same
    `msm_fixed_degraded` health counter + manifest event."""
    if fixed_fits_mesh(n_expanded, c, nbits, plan):
        return False
    from ..utils.health import HEALTH
    HEALTH.incr("msm_fixed_degraded")
    MSM._record_event(
        "msm_fixed_degraded", n=n_expanded, window=c,
        table_mb=_sharded_table_bytes(n_expanded, c, nbits, plan) >> 20,
        budget_mb=MSM._TABLES.budget >> 20, mesh_ndata=plan.ndata)
    return True


def _table_build_runner(plan: ShardingPlan, c: int, nwin: int,
                        nwin_padded: int):
    """Cached SPMD table builder: each data shard runs the c-doubling
    chains over ITS OWN expanded point rows (the chains are pointwise per
    row — fully local, no collectives), so the [nwin, N, 3, 16] table is
    born sharded along the row axis and never transits whole. Padded
    windows hold infinity (their digits are always zero anyway)."""
    key = (plan.key, "tbuild", c, nwin, nwin_padded)
    fn = _RUNNERS.get(key)
    if fn is not None:
        return fn

    @functools.partial(
        shard_map, mesh=plan.mesh,
        in_specs=(plan.point_spec,),
        out_specs=plan.table_spec,
        check_vma=False)
    def build(pts_local):
        return _build_table_local(pts_local, c, nwin, nwin_padded)

    fn = jax.jit(build)
    _RUNNERS[key] = fn
    return fn


# resident sharded tables: (base key, shape statics, plan) -> device table.
# Strong host ref pins id()-keyed bases (same contract as ops.msm._TABLES);
# tiny cap — one SRS base per prover is the norm, and each entry is budget-
# sized per device.
_SHARD_TABLES: dict = {}


def sharded_fixed_table(points, c: int, nwin: int, plan: ShardingPlan,
                        base_key=None):
    """[nwin_padded, N, 3, 16] fixed-base window table, built by and
    resident on the mesh (rows sharded along "data", windows whole).

    `points` is the endomorphism-EXPANDED, row-padded base already placed
    with `plan.point_spec` (backend._mesh_base). Unlike the single-device
    `fixed_base_table`, the doubling chains here run over the expanded rows
    directly (phi rows double exactly like P rows) — a one-time build cost
    traded for never shipping the table across hosts."""
    n = points.shape[0]
    nwin_padded = plan.pad_windows(nwin)
    key = (base_key if base_key is not None else ("id", id(points)),
           int(n), int(c), int(nwin_padded), plan.key)
    ref = None if base_key is not None else points
    hit = _SHARD_TABLES.get(key)
    if hit is not None:
        return hit[1]
    with compilelog.entry_point("parallel.sharded_fixed_table"):
        tab = _table_build_runner(plan, c, nwin, nwin_padded)(points)
    if len(_SHARD_TABLES) > 4:
        _SHARD_TABLES.clear()
    _SHARD_TABLES[key] = (ref, tab)
    return tab


def _fixed_runner(plan: ShardingPlan, c: int, nbits: int):
    """Cached jitted fixed-base MSM program over a sharded window table.

    Mirrors ops.msm.msm_fixed_run on the mesh: per-shard signed-digit
    recode, window slices taken locally from the resident table, bucket
    sums MERGED ACROSS WINDOWS before one aggregation pass (sound because
    table bases carry 2^{cw}), then gather+fold over both mesh axes."""
    key = (plan.key, "fixed", c, nbits)
    fn = _RUNNERS.get(key)
    if fn is not None:
        return fn

    nwin = _nwin_for(c, nbits, signed=True)
    nwin_padded = plan.pad_windows(nwin)
    nbuckets = (1 << (c - 1)) + 1
    n_win_shards = plan.nwin_shards
    data_axis, win_axis = plan.data_axis, plan.win_axis

    @functools.partial(
        shard_map, mesh=plan.mesh,
        in_specs=(plan.table_spec, plan.scalar_spec, plan.sign_spec),
        out_specs=P(None, None),
        check_vma=False)
    def fixed_phase(tab, sc, ng):
        widx = jax.lax.axis_index(win_axis)
        nloc = nwin_padded // n_win_shards
        # cross-window merge INSIDE the shard (bases carry 2^{cw}), then
        # across both mesh axes — one aggregation pass total
        merged = _shard_fixed_local(
            tab, sc, ng, widx, c, nwin, nwin_padded, nloc, nbuckets)
        merged = _fold_points(jax.lax.all_gather(merged, data_axis))
        merged = _fold_points(jax.lax.all_gather(merged, win_axis))
        return MSM._aggregate_buckets(merged[None], c)[0]  # [3, 16]

    fn = jax.jit(fixed_phase)
    _RUNNERS[key] = fn
    return fn


def sharded_msm_fixed(table, scalars, neg, c: int, plan: ShardingPlan,
                      nbits: int):
    """Fixed-base MSM against a mesh-resident sharded table. scalars
    [N, 8] GLV half-scalar magnitudes placed per plan.scalar_spec, neg [N]
    signs per plan.sign_spec. Returns a replicated [3, 16] result."""
    with compilelog.entry_point("parallel.sharded_msm_fixed"):
        return _fixed_runner(plan, c, nbits)(table, scalars, neg)

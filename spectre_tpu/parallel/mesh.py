"""Device mesh construction for multi-chip proving.

Axes:
  "data" — shards MSM points / NTT rows / witness columns (the wide axis)
  "win"  — shards Pippenger windows (small, independent work units)

On a v4-8 (8 chips) the default is a 4x2 (data, win) mesh; single-chip and
virtual-CPU configurations collapse gracefully.

`SPECTRE_MESH_SHAPE` overrides the default: "4x2" -> data=4, win=2;
a bare "8" means data=8, win=1. Shapes over a SUBSET of the local devices
are allowed (e.g. "2x1" on an 8-device host picks the first 2) — that is
how the mesh-vs-single-device identity tests run 1/2/8-device proves in
one process. A shape that needs more devices than exist, or that isn't a
positive DxW grid, raises `MeshShapeError` instead of silently collapsing
to one device (the round-1 failure mode: a 1x1 mesh "validating" nothing).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh


class MeshShapeError(ValueError):
    """Requested mesh shape cannot be built from the available devices."""


def _parse_shape(spec: str) -> tuple[int, int]:
    parts = spec.lower().replace("×", "x").split("x")
    try:
        dims = [int(p) for p in parts if p != ""]
    except ValueError:
        dims = []
    if len(dims) == 1:
        dims.append(1)
    if len(dims) != 2 or dims[0] < 1 or dims[1] < 1:
        raise MeshShapeError(
            f"SPECTRE_MESH_SHAPE={spec!r}: expected 'DATAxWIN' with positive "
            f"integers (e.g. '4x2', '8', '2x1')")
    return dims[0], dims[1]


def make_mesh(n_devices: int | None = None, data_axis: int | None = None,
              devices: list | None = None, strict: bool = False) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            msg = (f"make_mesh: {n_devices} devices requested but only "
                   f"{len(devs)} available — refusing to validate a collapsed "
                   f"mesh (round-1 failure mode: silently truncating to 1x1)")
            if strict:
                raise RuntimeError(msg)
            raise MeshShapeError(msg)
        devs = devs[:n_devices]
    n = len(devs)
    if data_axis is None:
        # prefer a 2D split when we have >= 4 devices
        data_axis = n // 2 if n >= 4 else n
    if data_axis < 1 or n % data_axis != 0:
        raise MeshShapeError(
            f"make_mesh: data axis {data_axis} does not divide {n} devices")
    win_axis = n // data_axis
    arr = np.array(devs).reshape(data_axis, win_axis)
    return Mesh(arr, axis_names=("data", "win"))


def default_mesh() -> Mesh:
    """All-local-devices ("data", "win") mesh, honoring SPECTRE_MESH_SHAPE.

    With the knob set, the requested DxW grid is carved from the first D*W
    local devices; needing more than exist is a MeshShapeError, never a
    silent 1-device mesh."""
    spec = os.environ.get("SPECTRE_MESH_SHAPE", "").strip()
    if not spec:
        return make_mesh()
    d, w = _parse_shape(spec)
    avail = len(jax.devices())
    if d * w > avail:
        raise MeshShapeError(
            f"SPECTRE_MESH_SHAPE={spec!r} needs {d * w} devices but only "
            f"{avail} are available")
    return make_mesh(n_devices=d * w, data_axis=d)

"""Device mesh construction for multi-chip proving.

Axes:
  "data" — shards MSM points / NTT rows / witness columns (the wide axis)
  "win"  — shards Pippenger windows (small, independent work units)

On a v4-8 (8 chips) the default is a 4x2 (data, win) mesh; single-chip and
virtual-CPU configurations collapse gracefully.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None, data_axis: int | None = None,
              devices: list | None = None, strict: bool = False) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        if strict and len(devs) < n_devices:
            raise RuntimeError(
                f"make_mesh: {n_devices} devices requested but only "
                f"{len(devs)} available — refusing to validate a collapsed "
                f"mesh (round-1 failure mode: silently truncating to 1x1)")
        devs = devs[:n_devices]
    n = len(devs)
    if data_axis is None:
        # prefer a 2D split when we have >= 4 devices
        data_axis = n // 2 if n >= 4 else n
    win_axis = n // data_axis
    assert data_axis * win_axis == n, (data_axis, n)
    arr = np.array(devs).reshape(data_axis, win_axis)
    return Mesh(arr, axis_names=("data", "win"))


def default_mesh() -> Mesh:
    return make_mesh()

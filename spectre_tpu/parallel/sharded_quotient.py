"""Mesh-sharded quotient pipeline (ISSUE 19).

`plonk/quotient_device.py` evaluates the quotient on ONE device even when an
8-way mesh is up: every [4n, 16] extended-domain tensor, every gate
expression, and the two full-width NTT boundaries run on device 0. This
module shards all three phases over the interned `ShardingPlan`'s batch mesh
(every device, one axis) while keeping the eager-primitive dispatch
discipline the quotient engine is built on (tracing the whole expression
tree into one program blows up LLVM codegen — see quotient_device's design
note). Layers:

  * LDE prefetch (`_lde_runner`): the chunked `coset_lde_std` batch is
    sharded over the BATCH axis — each device runs the same fused
    single-device `_fwd_kernel` body on its own columns (embarrassingly
    parallel, byte-identical by construction) — then ONE all_to_all
    resharding turns the batch-sharded [B, 4n, 16] stack into row-sharded
    [4n, 16] columns for the pointwise phase.
  * Gate evaluation (`_eval_runner` family): mont mul/add/sub, scalar
    broadcast ops and the y-fold as tiny shard_map programs over row-sharded
    tensors — pure local math, no collectives.
  * Rotations (`_roll_runner`): `jnp.roll` does not shard; a static-shift
    roll decomposes into at most two `ppermute`s (the whole-block shift
    s // block and the remainder halo) plus a local concat. Any shift works
    — the SHA region reaches 65 base rows back and the permutation argument
    rotates by `last_row`, so a fixed small halo would not cover the
    expression stream (this is the "rotation-closed" requirement: the
    blockwise partition is closed under arbitrary static rolls at the cost
    of one neighbor exchange).
  * Fused inverse (`_inv_runner`): the `coset_intt_std_vinv` boundary as a
    sharded Bailey/four-step transform — the vanishing-inverse stage-0
    pre-scale, the inverse-root row/col short transforms, the all_to_all
    transpose, and the combined g^{-i}·n^{-1}·(mont→std) output table all
    inside one SPMD program, mirroring `sharded_ntt` with the quotient's
    boundary fusions riding along as mesh-resident tables.

Runner discipline (TC-FRESH-JIT): every program is built once per
(plan, shape, static-config) key in a module-level cache declared in
`TRACE_RUNNER_CACHES`, registered in `plan.RUNNER_REGISTRY_MODULES`, and
exercised by the trace-lint double-call probe. Byte-identity with the
single-device path across {mesh shape} x {SPECTRE_NTT_MODE} x
{SPECTRE_NTT_KERNEL} is pinned by tests/test_quotient_sharded.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..fields import bn254
from ..observability import compilelog
from ..ops import field_ops as F, ntt as NTT
from ._compat import shard_map
from .plan import ShardingPlan

R = bn254.R

# compiled SPMD programs, keyed on (plan.key, <static shape/config>); the
# resident-table caches hold mesh-placed device arrays (device_put only —
# no compiles), like sharded_ntt._TWIDDLES
_RUNNERS: dict = {}
_ROLLS: dict = {}
_LDES: dict = {}
_INVS: dict = {}
_INV_TABLES: dict = {}

# runner registry (trace-cache hygiene contract, parallel/plan.py):
# declared builders are cross-checked by analysis/trace_lint
# (TC-UNCACHED-RUNNER) and exercised by its retrace probes.
TRACE_RUNNER_CACHES = (
    ("_eval_runner", "_RUNNERS"),
    ("_roll_runner", "_ROLLS"),
    ("_lde_runner", "_LDES"),
    ("_inv_runner", "_INVS"),
)


def _clear_caches():
    for c in (_RUNNERS, _ROLLS, _LDES, _INVS, _INV_TABLES):
        c.clear()


def _fence(x):
    """Serialize rendezvous programs on the CPU backend.

    XLA:CPU runs each partition of a collective execution as a thread-pool
    task; with async dispatch, two rendezvous-bearing programs (ppermute
    rolls, all_to_all reshards) in flight at once can interleave their
    partition tasks and starve each other's rendezvous — observed as the
    k=13 collective-permute hang in bench-quotient-multichip after ~2.4k
    clean collective runs. Blocking after every collective launch keeps at
    most ONE rendezvous program in flight. Real accelerators execute
    programs in per-core launch order, so they skip the barrier and keep
    the async pipeline."""
    if jax.default_backend() == "cpu":
        jax.block_until_ready(x)
    return x


# --- per-shard local compute (no collectives) -------------------------------
# Extracted from the shard_map closures so the kernel linter can trace them
# at tiny shapes without a mesh (analysis/kernel_lint known-root table).

def _lde_local(stack, omega: int, g, mode: str, kernel: str):
    """Local slice of the batch-sharded fused coset-LDE: full-length
    transforms of this device's columns — the SAME `_fwd_kernel` body as the
    single-device batched prefetch, so results are byte-identical column by
    column. stack: [B_local, n, 16] standard-form limbs."""
    return NTT._fwd_kernel.__wrapped__(stack, omega, ("std", g), mode, kernel)


def _inv_rows_local(block, scb, twb, omega_row: int, mode: str, kernel: str):
    """Fused-inverse steps 0-2 on one shard: the stage-0 pre-scale (the
    quotient's vanishing inverse — an explicit mont_mul, byte-identical to
    the single-device stage-0 fusion since elementwise order commutes with
    the Bailey reshape), length-Cc inverse-root NTTs along each local row,
    then the inter-pass twiddle. block/scb/twb: [rows_local, Cc, 16]."""
    fctx = F.fr_ctx()
    y = F.mont_mul(fctx, block, scb)
    y = jax.vmap(
        lambda row: NTT._fwd_kernel.__wrapped__(row, omega_row, None,
                                                mode, kernel))(y)
    return F.mont_mul(fctx, y, twb)


def _inv_cols_local(y, outb, omega_col: int, mode: str, kernel: str):
    """Fused-inverse step 4 + output boundary on one shard: length-Rr NTTs
    along each post-transpose row, then ONE multiply by the combined
    g^{-i}·n^{-1}·(mont→std) table slice (raw table: output is standard
    form). y/outb: [cols_local, Rr, 16]."""
    fctx = F.fr_ctx()
    y = jax.vmap(
        lambda row: NTT._fwd_kernel.__wrapped__(row, omega_col, None,
                                                mode, kernel))(y)
    return F.mont_mul(fctx, y, outb)


# --- cached SPMD runners ----------------------------------------------------

def _eval_runner(plan: ShardingPlan, op: str, m: int):
    """Pointwise expression primitive over row-sharded [m, 16] tensors:
    op in {mul, add, sub, mul_s, add_s, fold}. Scalars ride replicated."""
    key = (plan.key, op, m)
    hit = _RUNNERS.get(key)
    if hit is not None:
        return hit
    fctx = F.fr_ctx()
    ax = plan.batch_axis
    row, rep = P(ax, None), P(None)
    if op == "mul":
        specs, body = (row, row), lambda a, b: F.mont_mul(fctx, a, b)
    elif op == "add":
        specs, body = (row, row), lambda a, b: F.add(fctx, a, b)
    elif op == "sub":
        specs, body = (row, row), lambda a, b: F.sub(fctx, a, b)
    elif op == "mul_s":
        specs, body = (row, rep), lambda a, s: F.mont_mul(fctx, a, s[None, :])
    elif op == "add_s":
        specs, body = (row, rep), lambda a, s: F.add(
            fctx, a, jnp.broadcast_to(s[None, :], a.shape))
    elif op == "fold":
        specs = (row, rep, row)
        body = lambda acc, y, e: F.add(
            fctx, F.mont_mul(fctx, acc, y[None, :]), e)
    else:
        raise ValueError(f"unknown quotient eval op {op!r}")
    fn = jax.jit(functools.partial(
        shard_map, mesh=plan.batch_mesh, in_specs=specs, out_specs=row,
        check_vma=False)(body))
    if len(_RUNNERS) > 64:
        _RUNNERS.clear()
    _RUNNERS[key] = fn
    return fn


def _roll_runner(plan: ShardingPlan, m: int, shift: int):
    """`jnp.roll(arr, -shift, axis=0)` of a row-sharded [m, 16] tensor as a
    shard_map program: out[j] = arr[(j + shift) mod m]. With block size
    B = m/D the static shift decomposes as q·B + r — each device needs
    shard (d+q) and, when r > 0, a halo from shard (d+q+1): at most two
    ppermutes and one local concat, for ANY shift (rotation-closed under
    the blockwise partition)."""
    d = plan.n_devices
    shift = shift % m
    key = (plan.key, m, shift)
    hit = _ROLLS.get(key)
    if hit is not None:
        return hit
    ax = plan.batch_axis
    block = m // d
    q, rem = shift // block, shift % block
    spec = P(ax, None)

    @functools.partial(
        shard_map, mesh=plan.batch_mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False)
    def run(x):
        a = x if q % d == 0 else jax.lax.ppermute(
            x, ax, [((i + q) % d, i) for i in range(d)])
        if rem == 0:
            return a
        b = x if (q + 1) % d == 0 else jax.lax.ppermute(
            x, ax, [((i + q + 1) % d, i) for i in range(d)])
        return jnp.concatenate([a[rem:], b[:rem]], axis=0)

    fn = jax.jit(run)
    if len(_ROLLS) > 256:
        _ROLLS.clear()
    _ROLLS[key] = fn
    return fn


def _lde_runner(plan: ShardingPlan, b: int, logm: int, omega: int, g):
    """Batch-sharded fused coset-LDE + ONE all_to_all reshard: [B, n, 16]
    standard-form columns in (batch over devices), row-sharded Montgomery
    evaluations out. B must be a multiple of the device count."""
    mode = NTT._resolve_mode(None, logm)
    kernel = NTT._resolve_kernel(None, mode)
    key = (plan.key, b, logm, omega, g, mode, kernel)
    hit = _LDES.get(key)
    if hit is not None:
        return hit
    ax = plan.batch_axis

    @functools.partial(
        shard_map, mesh=plan.batch_mesh, in_specs=(P(ax, None, None),),
        out_specs=P(None, ax, None), check_vma=False)
    def run(stack):                       # [B/D, n, 16] local columns
        y = _lde_local(stack, omega, g, mode, kernel)
        # batch-sharded -> row-sharded: split the row axis, gather batch
        return jax.lax.all_to_all(y, ax, split_axis=1, concat_axis=0,
                                  tiled=True)        # [B, n/D, 16]

    fn = jax.jit(run)
    if len(_LDES) > 32:
        _LDES.clear()
    _LDES[key] = fn
    return fn


def _inv_runner(plan: ShardingPlan, logm: int, omega: int, g,
                vinv_vals: tuple | None):
    """Sharded fused inverse boundary (`coset_intt_std_vinv` semantics):
    Bailey decomposition at size m = 2^logm with the inverse root, the
    vanishing-inverse pre-scale and the combined output table fused into the
    shard-local legs. In/out: Bailey-matrix layout (see `_inv_apply`)."""
    logr = logm // 2
    logc = logm - logr
    row_mode = NTT._resolve_mode(None, logc)
    col_mode = NTT._resolve_mode(None, logr)
    row_kernel = NTT._resolve_kernel(None, row_mode)
    col_kernel = NTT._resolve_kernel(None, col_mode)
    key = (plan.key, logm, omega, g, vinv_vals, row_mode, col_mode,
           row_kernel, col_kernel)
    hit = _INVS.get(key)
    if hit is not None:
        return hit
    d = plan.n_devices
    rr, cc = 1 << logr, 1 << logc
    assert rr % d == 0 and cc % d == 0, \
        f"shard count {d} must divide both matrix dims {rr}x{cc}"
    omega_inv = pow(omega, -1, R)
    omega_row = pow(omega_inv, rr, R)    # length-Cc root (step 1)
    omega_col = pow(omega_inv, cc, R)    # length-Rr root (step 4)
    ax = plan.batch_axis
    spec = P(ax, None, None)

    @functools.partial(
        shard_map, mesh=plan.batch_mesh, in_specs=(spec,) * 4,
        out_specs=spec, check_vma=False)
    def run(block, scb, twb, outb):
        y = _inv_rows_local(block, scb, twb, omega_row, row_mode, row_kernel)
        y = jax.lax.all_to_all(y, ax, split_axis=1, concat_axis=0,
                               tiled=True)           # [rr, cc/d, 16]
        y = y.transpose(1, 0, 2)                     # [cc/d, rr, 16]
        return _inv_cols_local(y, outb, omega_col, col_mode, col_kernel)

    fn = jax.jit(run)
    if len(_INVS) > 16:
        _INVS.clear()
    _INVS[key] = fn
    return fn


def _inv_tables(plan: ShardingPlan, logm: int, omega: int, g,
                vinv_vals: tuple | None):
    """Mesh-resident table triple for `_inv_runner`: the stage-0 pre-scale
    (tiled vanishing inverse, identity when None), the inverse-root
    inter-pass twiddles, and the combined raw output table — each reshaped
    into its shard-local layout and device_put row-sharded ONCE per
    (plan, size, root, vinv) like sharded_ntt's resident twiddles."""
    key = (plan.key, logm, omega, g, vinv_vals)
    hit = _INV_TABLES.get(key)
    if hit is not None:
        return hit
    logr = logm // 2
    logc = logm - logr
    rr, cc = 1 << logr, 1 << logc
    omega_inv = pow(omega, -1, R)
    sc = NTT._vinv_in_table(logm, vinv_vals if vinv_vals is not None
                            else (1,))               # [m, 16] mont
    # A[jr, jc] = x[jc*rr + jr]: same view the data enters the runner in
    sc_a = np.moveaxis(np.asarray(sc).reshape(cc, rr, 16), 0, 1)
    tw = NTT._twiddle_matrix(logr, logc, omega_inv)  # [rr, cc, 16]
    out = NTT._fused_out_table(logm, g, True)        # [m, 16] raw (std out)
    # final layout Y[kc, kr] = X[kr*cc + kc]
    out_y = np.transpose(np.asarray(out).reshape(rr, cc, 16), (1, 0, 2))
    sh = NamedSharding(plan.batch_mesh, P(plan.batch_axis, None, None))
    tables = tuple(jax.device_put(jnp.asarray(t), sh)
                   for t in (sc_a, tw, out_y))
    if len(_INV_TABLES) > 8:
        _INV_TABLES.clear()
    _INV_TABLES[key] = tables
    return tables


def _inv_apply(plan: ShardingPlan, acc, logm: int, omega: int, g,
               vinv_vals: tuple | None):
    """Run the sharded fused inverse on a row-sharded [m, 16] accumulator;
    returns the natural-order standard-form [m, 16] result (host numpy)."""
    logr = logm // 2
    rr, cc = 1 << logr, 1 << (logm - logr)
    run = _inv_runner(plan, logm, omega, g, vinv_vals)
    scb, twb, outb = _inv_tables(plan, logm, omega, g, vinv_vals)
    sh = NamedSharding(plan.batch_mesh, P(plan.batch_axis, None, None))
    # A[jr, jc] = acc[jc*rr + jr], rows (jr) sharded
    a = jax.device_put(acc.reshape(cc, rr, 16).transpose(1, 0, 2), sh)
    with compilelog.entry_point("parallel.sharded_quotient.inverse"):
        out = run(a, scb, twb, outb)                 # [cc, rr, 16]
    return np.asarray(out).transpose(1, 0, 2).reshape(1 << logm, 16)


# --- eligibility + the expression-evaluation context ------------------------

def eligible(plan: ShardingPlan, m: int) -> bool:
    """Shape feasibility of the sharded pipeline: the device count must
    divide both Bailey dims of the extended domain (which also gives an
    exact blockwise row partition for the pointwise/roll phase)."""
    d = plan.n_devices
    if d <= 1 or m % d:
        return False
    logm = m.bit_length() - 1
    if 1 << logm != m:
        return False
    logr = logm // 2
    return (1 << logr) % d == 0 and (1 << (logm - logr)) % d == 0


class MeshCtx:
    """`all_expressions` context over ROW-SHARDED [m, 16] Montgomery
    tensors: the mesh twin of quotient_device._DeviceCtx, dispatching every
    primitive through the cached shard_map runners."""

    def __init__(self, plan: ShardingPlan, cols, m: int, last_row: int,
                 mont_scalar):
        self._plan = plan
        self._cols = cols
        self._m = m
        self._last_row = last_row
        self._base_mont = mont_scalar  # int -> [16] mont scalar (any device)
        self._scalars: dict = {}       # value -> mesh-replicated [16]
        self._rot_cache: dict = {}
        self._rep = NamedSharding(plan.batch_mesh, P(None))
        zero = jnp.zeros((m, 16), jnp.uint32)
        self._zero = jax.device_put(
            zero, NamedSharding(plan.batch_mesh, P(plan.batch_axis, None)))
        self.l0 = cols[("_l0",)]
        self.llast = cols[("_llast",)]
        self.lblind = cols[("_lblind",)]
        self.x_col = cols[("_xcol",)]

    def _mont(self, s):
        v = int(s) % R
        hit = self._scalars.get(v)
        if hit is None:
            hit = jax.device_put(self._base_mont(v), self._rep)
            self._scalars[v] = hit
        return hit

    def _run(self, op, *args):
        fn = _eval_runner(self._plan, op, self._m)
        with compilelog.entry_point("parallel.sharded_quotient.eval"):
            return fn(*args)

    def var(self, key, rot):
        arr = self._cols[key]
        if rot == 0:
            return arr
        hit = self._rot_cache.get((key, rot))
        if hit is None:
            r = self._last_row if rot == ROT_LAST else rot
            # extended-coset index shift: omega == omega_ext^EXTENSION
            roll = _roll_runner(self._plan, self._m, 4 * r)
            with compilelog.entry_point("parallel.sharded_quotient.roll"):
                hit = _fence(roll(arr))
            self._rot_cache[(key, rot)] = hit
        return hit

    def mul(self, a, b):
        return self._run("mul", a, b)

    def add(self, a, b):
        return self._run("add", a, b)

    def sub(self, a, b):
        return self._run("sub", a, b)

    def scale(self, a, s):
        return self._run("mul_s", a, self._mont(s))

    def add_const(self, a, s):
        return self._run("add_s", a, self._mont(s))

    def const(self, s):
        # a row-sharded constant column: 0 + s through the add_s runner
        # keeps the result on the mesh without a host-side materialize
        return self._run("add_s", self._zero, self._mont(s))

    def fold(self, acc, y_m, e):
        return self._run("fold", acc, self._mont(y_m), e)


# imported late to avoid a plonk <-> parallel import cycle at module load
from ..plonk.keygen import ROT_LAST  # noqa: E402


class MeshQuotientEngine:
    """Quotient-pipeline engine over the ShardingPlan batch mesh — the
    drop-in mesh twin of quotient_device's single-device engine (same
    skeleton, sharded runners). Built per compute_quotient call; all
    compiled programs and resident tables live in the module caches."""

    name = "sharded"

    def __init__(self, plan: ShardingPlan, dom):
        self.plan = plan
        self.dom = dom
        self.m = dom.n_ext
        self._logm = self.m.bit_length() - 1
        self._row_sh = NamedSharding(plan.batch_mesh,
                                     P(plan.batch_axis, None))

    def chunk(self, base: int) -> int:
        """LDE prefetch chunk: the single-device transient-bytes cap,
        rounded to a multiple of the device count for the batch shard."""
        d = self.plan.n_devices
        return max(d, (base // d) * d)

    def lde(self, std16: np.ndarray):
        """[B, m, 16] standard-form stack -> list of B row-sharded
        Montgomery [m, 16] evaluations (pads the batch up to a device-count
        multiple; duplicate tail columns are computed and dropped)."""
        b = std16.shape[0]
        d = self.plan.n_devices
        bp = max(d, ((b + d - 1) // d) * d)
        if bp != b:
            std16 = np.concatenate(
                [std16, np.repeat(std16[:1], bp - b, axis=0)], axis=0)
        run = _lde_runner(self.plan, bp, self._logm, self.dom.omega_ext,
                          self._g())
        sh = NamedSharding(self.plan.batch_mesh,
                           P(self.plan.batch_axis, None, None))
        stack = jax.device_put(jnp.asarray(std16), sh)
        with compilelog.entry_point("parallel.sharded_quotient.lde"):
            out = _fence(run(stack))
        return [out[i] for i in range(b)]

    def _g(self):
        from ..plonk.domain import COSET_GEN
        return COSET_GEN

    def device_col(self, arr16):
        """Place a host-built [m, 16] Montgomery column row-sharded."""
        return jax.device_put(jnp.asarray(arr16), self._row_sh)

    def ctx(self, cols, last_row: int, mont_scalar) -> MeshCtx:
        return MeshCtx(self.plan, cols, self.m, last_row, mont_scalar)

    def inverse_std(self, acc, vinv_vals) -> np.ndarray:
        """The h-path boundary: fused vanishing-inverse + inverse coset NTT
        + std output, sharded. vinv_vals None = identity pre-scale (the
        SPECTRE_QUOTIENT_FUSED_VINV=0 oracle path multiplies explicitly
        before calling in)."""
        return _inv_apply(self.plan, np.asarray(acc), self._logm,
                          self.dom.omega_ext, self._g(), vinv_vals)

"""One explicit ShardingPlan for the mesh prove path (ISSUE 13 tentpole).

Before this module existed every mesh call site improvised: `sharded_msm`
re-built (and re-jit) a fresh shard_map closure per call, `sharded_ntt`
re-transferred its twiddle matrix and re-jit per call, and the backend
re-expanded/re-placed the commitment base per MSM. On the 1-core
8-virtual-device box that meant a FULL 8-way SPMD retrace + lowering for
every one of the ~20 MSMs and ~40 NTTs in a k=13 prove — the recorded
MULTICHIP_r01/r05 rc=124 timeouts. SZKP (arXiv:2408.05890) and "Enabling
AI ASICs for ZKP" (arXiv:2604.17808) both make the same point from the
hardware side: the mesh kernels only win once data placement is explicit
and the SPMD program build is hoisted out of the hot path.

The ShardingPlan is that explicit placement contract:

  * mesh axes + shape      — ("data", "win"), honoring SPECTRE_MESH_SHAPE
  * point/scalar placement — rows sharded along "data" (pad_rows pads so
                             the axis divides evenly)
  * window placement       — Pippenger windows sharded along "win"
                             (pad_windows)
  * fixed-base tables      — [nwin, N, 3, 16] window tables sharded along
                             the ROW axis (`table_spec`): each data shard
                             holds exactly the T[w] row slices for its
                             point shard (co-resident, no re-transfer)
  * signed-digit recode    — per shard (each shard holds whole scalars,
                             so the carry scan never crosses a boundary)
  * NTT row/col split      — `ntt_split(logn)` picks the Bailey split the
                             data axis divides

Every consumer caches its compiled SPMD program keyed by `plan.key` (plus
its own static params): one jit per (plan, shape-class), not per call.
`plan_for_mesh` interns plans so the mesh object captured by those cached
closures stays alive and stable.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import default_mesh

DATA_AXIS = "data"
WIN_AXIS = "win"


@dataclasses.dataclass(frozen=True, eq=False)
class ShardingPlan:
    """Placement contract for one ("data", "win") device mesh.

    Immutable and interned per device-set (`plan_for_mesh`): consumers key
    their compiled-program caches on `plan.key` and capture `plan.mesh`
    in shard_map closures, so two calls under the same plan always reuse
    the same trace."""

    mesh: Mesh
    data_axis: str = DATA_AXIS
    win_axis: str = WIN_AXIS
    # signed-digit recode runs inside each data shard (whole scalars per
    # row -> the carry scan is shard-local); documented here because the
    # runner builders branch on it when composing kernels
    per_shard_recode: bool = True

    # -- shape --

    @property
    def ndata(self) -> int:
        return self.mesh.shape[self.data_axis]

    @property
    def nwin_shards(self) -> int:
        return self.mesh.shape.get(self.win_axis, 1)

    @property
    def n_devices(self) -> int:
        return self.ndata * self.nwin_shards

    @functools.cached_property
    def key(self) -> tuple:
        """Hashable identity for compiled-program caches."""
        return (tuple(d.id for d in self.mesh.devices.flat),
                self.ndata, self.nwin_shards,
                self.data_axis, self.win_axis)

    # -- placements (PartitionSpecs over the mesh) --

    @property
    def point_spec(self) -> P:
        """[n, 3, 16] projective points: rows along the data axis."""
        return P(self.data_axis, None, None)

    @property
    def scalar_spec(self) -> P:
        """[n, L] limb scalars: rows along the data axis."""
        return P(self.data_axis, None)

    @property
    def sign_spec(self) -> P:
        """[n] bool sign masks: along the data axis."""
        return P(self.data_axis,)

    @property
    def table_spec(self) -> P:
        """[nwin, N, 3, 16] fixed-base window table: ROW axis along
        "data" — T[w] slices co-resident with their point shards; the
        window axis stays whole (each win shard dynamic-slices its
        windows locally)."""
        return P(None, self.data_axis, None, None)

    @property
    def ntt_spec(self) -> P:
        """[rows, cols, 16] Bailey matrix: rows along the data axis."""
        return P(self.data_axis, None, None)

    def replicated(self, ndim: int) -> P:
        return P(*([None] * ndim))

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def place(self, arr, spec: P):
        """device_put onto the mesh with the given placement."""
        return jax.device_put(arr, self.sharding(spec))

    # -- padding --

    def pad_rows(self, n: int) -> int:
        """Rows padded so the data axis divides evenly (pad points with
        infinity / scalars with zero — identity contributions)."""
        d = self.ndata
        return ((n + d - 1) // d) * d

    def pad_windows(self, nwin: int) -> int:
        """Window count padded so the win axis divides evenly (padded
        windows read digits beyond nbits — always zero, harmless)."""
        s = self.nwin_shards
        return ((nwin + s - 1) // s) * s

    # -- NTT decomposition --

    def ntt_split(self, logn: int) -> tuple[int, int]:
        """(logr, logc) Bailey split for a 2^logn NTT such that the data
        axis divides both matrix dims; raises when the transform is too
        small for this mesh."""
        logs = (self.ndata - 1).bit_length()
        logr = logn // 2
        logc = logn - logr
        if logr < logs or logc < logs:
            raise ValueError(
                f"2^{logn} NTT cannot split across a {self.ndata}-way data "
                f"axis (needs 2^{2 * logs} rows minimum)")
        return logr, logc

    # -- batch (DP) axis --

    @functools.cached_property
    def batch_mesh(self) -> Mesh:
        """1-D ("batch",) mesh over the same device set, for the
        inter-proof / multi-column DP axis (parallel.batch_msm)."""
        return Mesh(self.mesh.devices.reshape(-1), (self.batch_axis,))

    @property
    def batch_axis(self) -> str:
        return "batch"

    # -- introspection (bench JSON / manifests) --

    def describe(self) -> dict:
        return {
            "mesh": dict(self.mesh.shape),
            "n_devices": self.n_devices,
            "points": f"rows over '{self.data_axis}'",
            "windows": f"over '{self.win_axis}'",
            "fixed_table": f"T[w] rows over '{self.data_axis}' "
                           f"(co-resident with point shards)",
            "recode": "per-shard signed-digit"
                      if self.per_shard_recode else "host",
            "ntt": f"Bailey row/col, rows over '{self.data_axis}', "
                   f"transpose = all_to_all",
        }


# interned plans: the mesh object held here is the one captured by every
# cached shard_map closure, so plan identity == program-cache validity
_PLANS: dict = {}


def plan_for_mesh(mesh: Mesh, data_axis: str = DATA_AXIS,
                  win_axis: str = WIN_AXIS) -> ShardingPlan:
    """Interned ShardingPlan for a mesh (same device set + axes -> the
    SAME plan object, holding the first mesh seen)."""
    axes = tuple(mesh.axis_names)
    if data_axis not in axes:
        # 1-D meshes (tests, the batch path) get a degenerate win axis
        data_axis = axes[0]
        win_axis = axes[1] if len(axes) > 1 else win_axis
    key = (tuple(d.id for d in mesh.devices.flat),
           tuple(mesh.shape.items()), data_axis, win_axis)
    plan = _PLANS.get(key)
    if plan is None:
        if len(_PLANS) > 16:
            _PLANS.clear()
        plan = ShardingPlan(mesh=mesh, data_axis=data_axis,
                            win_axis=win_axis)
        _PLANS[key] = plan
    return plan


def current_plan() -> ShardingPlan:
    """The process-default plan: `default_mesh()` (all local devices,
    honoring SPECTRE_MESH_SHAPE) interned through `plan_for_mesh`."""
    return plan_for_mesh(default_mesh())


# ---------------------------------------------------------------------------
# runner-registry contract (trace-cache hygiene)
# ---------------------------------------------------------------------------
# Every module that builds jitted/SPMD programs against a plan keys its
# compiled-program cache on `plan.key` + its own statics, and DECLARES the
# (builder, cache-dict) pairs in a module-level `TRACE_RUNNER_CACHES`
# tuple (modules whose jitted entry points live at module level declare a
# `TRACE_JIT_ROOTS` name tuple instead). The declarations are read by
# `spectre_tpu.analysis.trace_lint` via AST — no imports, so ops/ modules
# never grow an import edge into parallel/ — which flags undeclared or
# stale entries (TC-UNCACHED-RUNNER) and dynamically double-calls the
# registered runners asserting zero recompiles (TC-RETRACE-DYN).

# modules participating in the runner-registry contract
RUNNER_REGISTRY_MODULES = (
    "spectre_tpu.parallel.sharded_msm",
    "spectre_tpu.parallel.sharded_ntt",
    "spectre_tpu.parallel.sharded_quotient",
    "spectre_tpu.parallel.batch_msm",
    "spectre_tpu.plonk.quotient_device",
    "spectre_tpu.plonk.backend",
)


def runner_registry() -> dict:
    """{module name -> declared (builder, cache) pairs} — the live-import
    view of the contract (tests pin it against the AST view)."""
    import importlib

    out = {}
    for name in RUNNER_REGISTRY_MODULES:
        m = importlib.import_module(name)
        out[name] = tuple(getattr(m, "TRACE_RUNNER_CACHES", ()))
    return out

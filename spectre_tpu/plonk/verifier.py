"""The verifier: transcript replay, identity check at x, SHPLONK pairing check.

Reference parity: halo2's verify_proof / snark-verifier PlonkVerifier
(SURVEY.md L0). Pure host math (a handful of field ops + two pairings);
the same `all_expressions` definition the prover used guarantees the identity
is checked against exactly the constraint set that was proven.
"""

from __future__ import annotations

from ..fields import bn254
from . import kzg
from .expressions import ScalarCtx, all_expressions
from .keygen import VerifyingKey
from .srs import SRS
from .transcript import Blake2bTranscript

R = bn254.R


def verify(vk: VerifyingKey, srs: SRS, instances: list, proof: bytes,
           transcript_cls=Blake2bTranscript) -> bool:
    acc = verify_deferred(vk, srs, instances, proof, transcript_cls)
    if acc is None:
        return False
    tau_side, one_side = acc
    g1 = bn254.g1_curve
    return bn254.pairing_check([
        (one_side, srs.g2_gen),
        (g1.neg(tau_side), srs.g2_tau),
    ])


def verify_deferred(vk: VerifyingKey, srs: SRS, instances: list, proof: bytes,
                    transcript_cls=Blake2bTranscript):
    """Everything but the pairing: transcript replay, identity at x, SHPLONK
    combination. Returns the deferred check (tau_side, one_side) with
    e(tau_side, [tau]_2) == e(one_side, [1]_2), or None if the polynomial
    identity fails OR the proof bytes are malformed (short, non-canonical,
    trailing garbage) — untrusted bytes must yield a boolean reject, not an
    exception. The aggregation layer's native accumulator oracle and
    `verify` share this single definition."""
    try:
        return _verify_deferred_inner(vk, srs, instances, proof, transcript_cls)
    except (AssertionError, ValueError):
        return None


def _verify_deferred_inner(vk: VerifyingKey, srs: SRS, instances: list,
                           proof: bytes, transcript_cls):
    cfg = vk.config
    dom = vk.domain
    n, u = cfg.n, cfg.usable_rows
    tr = transcript_cls(proof)

    tr._absorb_bytes(vk.digest())
    for col in instances:
        assert len(col) <= u, "too many public inputs"
        for v in col:
            tr.common_scalar(int(v) % R)

    keys, pre_bg, pre_y, pre_x = vk.commitment_plan()
    commits = {}
    for key in keys[:pre_bg]:
        commits[key] = tr.read_point()
    beta = tr.challenge()
    gamma = tr.challenge()
    for key in keys[pre_bg:pre_y]:
        commits[key] = tr.read_point()
    y = tr.challenge()
    for key in keys[pre_y:pre_x]:
        commits[key] = tr.read_point()
    x = tr.challenge()

    plan = vk.query_plan()
    evals = {}
    for key, rot in plan:
        evals[(key, rot)] = tr.read_scalar()

    # --- instance evaluations (computed, not read: public input binding) ---
    for j in range(cfg.num_instance):
        rows = list(range(len(instances[j])))
        lag = dom.lagrange_evals(x, rows)
        evals[(("inst", j), 0)] = sum(
            int(v) * lag[i] for i, v in enumerate(instances[j])) % R

    # --- gate/permutation/lookup identity at x ---
    lag_special = dom.lagrange_evals(x, [0, cfg.last_row] + list(range(u + 1, n)))
    l0 = lag_special[0]
    llast = lag_special[cfg.last_row]
    lblind = sum(lag_special[i] for i in range(u + 1, n)) % R

    ctx = ScalarCtx(cfg, evals, l0, llast, lblind, x)
    exprs = all_expressions(cfg, ctx, beta, gamma)
    acc = 0
    for e in exprs:
        acc = (acc * y + e) % R
    vanishing = dom.evaluate_vanishing(x)
    xn = pow(x, n, R)
    h_at_x = (evals[(("h", 0), 0)] + xn * evals[(("h", 1), 0)]
              + xn * xn % R * evals[(("h", 2), 0)]) % R
    if acc != h_at_x * vanishing % R:
        return None

    # --- SHPLONK ---
    fixed_commits = vk.fixed_commitment_map()

    by_key: dict = {}
    for key, rot in plan:
        by_key.setdefault(key, []).append(rot)
    entries = []
    for key, rots in by_key.items():
        pts = tuple(vk.rotation_point(x, r) for r in rots)
        evs = tuple(evals[(key, r)] for r in rots)
        # a commitment may legitimately be None (infinity = zero polynomial,
        # e.g. an all-zero fixed column), so membership — not truthiness —
        # decides where it comes from
        com = commits[key] if key in commits else fixed_commits[key]
        entries.append(kzg.OpenEntry(None, com, pts, evs))
    tau_side, one_side = kzg.shplonk_accumulate(srs, entries, tr)
    tr.assert_consumed()
    return tau_side, one_side

"""The PLONK verifier as constraints: in-circuit SHPLONK proof verification.

Reference parity: snark-verifier's `PlonkVerifier` instantiated over
`Rc<Halo2Loader>` — the machinery under `AggregationCircuit`
(`aggregation_circuit.rs:69-124`): every scalar of the host verifier
(`plonk/verifier.py`) becomes a native-field cell, every proof commitment a
non-native BN254-Fq point with constrained limbs, the Fiat–Shamir transcript
a Poseidon duplex over cells, and the final pairing is NOT performed —
its two G1 inputs are returned as the KZG accumulator for the aggregation
statement (deferred to the outer verifier, `expose_previous_instances`
layout).

The same `all_expressions` definition the prover/verifier/mock use is
evaluated here over a `CellCtx`, so the in-circuit identity check combines
exactly the constraint set that was proven — one definition, four consumers.
"""

from __future__ import annotations

from ..builder.context import AssignedValue, Context
from ..builder.fp_chip import EccChip, FpChip
from ..builder.msm_chip import MsmChip
from ..builder.range_chip import RangeChip
from ..builder.transcript_chip import TranscriptChip
from ..fields import bn254
from .expressions import all_expressions
from .keygen import ROT_LAST, VerifyingKey
from .srs import SRS
from .transcript import PoseidonTranscript

R = bn254.R
P = bn254.P


class _CellChal:
    """Challenge cell wrapper supporting the `beta * dj % R` integer
    arithmetic all_expressions performs (the same trick as the EVM codegen's
    `_Sym`): * emits a constant-mul gate, % is the identity."""

    def __init__(self, ctx: Context, gate, cell):
        self._ctx = ctx
        self._gate = gate
        self.cell = cell

    def __mul__(self, k: int):
        return _CellChal(self._ctx, self._gate,
                         self._gate.mul(self._ctx, self.cell, k % R))

    def __mod__(self, _r: int):
        return self


def _chal_operand(s):
    return s.cell if isinstance(s, _CellChal) else s % R


class CellCtx:
    """all_expressions context over circuit cells (the fourth evaluation of
    the shared constraint definition: prover arrays, verifier scalars, mock
    rows, and now cells). Challenges arrive as `_CellChal` wrappers."""

    def __init__(self, ctx: Context, gate, evals: dict, l0, llast, lblind, x):
        self._ctx = ctx
        self._gate = gate
        self._evals = evals
        self.l0 = l0
        self.llast = llast
        self.lblind = lblind
        self.x_col = x

    def var(self, key, rot):
        return self._evals[(key, rot)]

    def mul(self, a, b):
        return self._gate.mul(self._ctx, a, b)

    def add(self, a, b):
        return self._gate.add(self._ctx, a, b)

    def sub(self, a, b):
        return self._gate.sub(self._ctx, a, b)

    def scale(self, a, s):
        return self._gate.mul(self._ctx, a, _chal_operand(s))

    def add_const(self, a, s):
        return self._gate.add(self._ctx, a, _chal_operand(s))

    def const(self, s):
        return self._ctx.load_constant(s % R)


class VerifierChip:
    """Verifies one inner proof; returns the deferred-pairing accumulator."""

    def __init__(self, rng: RangeChip):
        self.rng = rng
        self.gate = rng.gate
        self.fq = FpChip(rng, modulus=P, num_limbs=3, limb_bits=88)
        self.ecc = EccChip(self.fq, b=3)
        self.msm = MsmChip(self.ecc)

    # -- scalar helpers ---------------------------------------------------
    def _div(self, ctx: Context, a, b) -> AssignedValue:
        """a / b with b != 0 enforced (witnessed inverse, b*inv == 1)."""
        gate = self.gate
        bv = b.value if hasattr(b, "value") else b % R
        inv = ctx.load_witness(pow(bv, -1, R))
        prod = gate.mul(ctx, b, inv)
        ctx.constrain_constant(prod, 1)
        return gate.mul(ctx, a, inv)

    def _pow2k(self, ctx: Context, x, k: int) -> AssignedValue:
        out = x
        for _ in range(k):
            out = self.gate.mul(ctx, out, out)
        return out

    def _lagrange(self, ctx: Context, dom, zx, x, rows: list) -> dict:
        """L_i(x) = omega^i/n * (x^n - 1)/(x - omega^i) for each row."""
        gate = self.gate
        ninv = pow(dom.n, -1, R)
        out = {}
        for i in rows:
            wi = pow(dom.omega, i, R)
            den = gate.sub(ctx, x, wi)
            num = gate.mul(ctx, zx, wi * ninv % R)
            out[i] = self._div(ctx, num, den)
        return out

    # -- transcript-coupled readers --------------------------------------
    def _read_point(self, ctx: Context, tr, tchip):
        """Witness the next proof point: canonical 3x88 limbs per coordinate,
        on-curve constrained, limbs absorbed (the binding: in-circuit
        challenges depend on exactly these cells)."""
        pt = tr.read_point()
        x = self.fq.load(ctx, int(pt[0]))
        y = self.fq.load(ctx, int(pt[1]))
        self.fq.big.enforce_lt(ctx, x, P)
        self.fq.big.enforce_lt(ctx, y, P)
        self.ecc.constrain_on_curve(ctx, x, y)
        tchip.absorb_point_limbs(ctx, list(x.limbs) + list(y.limbs))
        return (x, y)

    def _read_scalar(self, ctx: Context, tr, tchip):
        v = tr.read_scalar()
        cell = ctx.load_witness(v)
        tchip.absorb([cell])
        return cell

    def _challenge(self, ctx: Context, tr, tchip):
        native = tr.challenge()
        cell = tchip.challenge(ctx)
        assert cell.value == native, "in-circuit transcript diverged"
        return cell

    # -- the verifier -----------------------------------------------------
    def verify_proof(self, ctx: Context, vk: VerifyingKey, srs: SRS,
                     instance_cells: list, proof: bytes):
        """instance_cells: [[AssignedValue]] — the inner proof's public
        inputs as cells (the caller exposes them in its own statement).
        Returns (acc_lhs, acc_rhs) point cells: the deferred pairing check
        e(acc_lhs, [tau]_2) == e(acc_rhs, [1]_2)."""
        gate = self.gate
        cfg = vk.config
        dom = vk.domain
        n, u = cfg.n, cfg.usable_rows
        tr = PoseidonTranscript(proof)
        tchip = TranscriptChip()

        tr._absorb_bytes(vk.digest())
        tchip.absorb_constant_bytes(ctx, vk.digest())
        for col in instance_cells:
            assert len(col) <= u, "too many public inputs"
            for cell in col:
                tr.common_scalar(cell.value)
                tchip.absorb([cell])

        keys, pre_bg, pre_y, pre_x = vk.commitment_plan()
        commits = {}
        for key in keys[:pre_bg]:
            commits[key] = self._read_point(ctx, tr, tchip)
        beta = self._challenge(ctx, tr, tchip)
        gamma = self._challenge(ctx, tr, tchip)
        for key in keys[pre_bg:pre_y]:
            commits[key] = self._read_point(ctx, tr, tchip)
        y = self._challenge(ctx, tr, tchip)
        for key in keys[pre_y:pre_x]:
            commits[key] = self._read_point(ctx, tr, tchip)
        x = self._challenge(ctx, tr, tchip)

        plan = vk.query_plan()
        evals = {}
        for key, rot in plan:
            evals[(key, rot)] = self._read_scalar(ctx, tr, tchip)

        # --- instance evaluations (computed in-circuit: the public-input
        # binding — these cells ARE the exposed instances) ---
        zx = gate.sub(ctx, self._pow2k(ctx, x, cfg.k), 1)  # x^n - 1
        for j in range(cfg.num_instance):
            rows = list(range(len(instance_cells[j])))
            lag = self._lagrange(ctx, dom, zx, x, rows)
            acc = ctx.load_constant(0)
            for i, cell in enumerate(instance_cells[j]):
                acc = gate.add(ctx, acc, gate.mul(ctx, cell, lag[i]))
            evals[(("inst", j), 0)] = acc

        # --- gate/permutation/lookup identity at x ---
        special = self._lagrange(ctx, dom, zx, x,
                                 [0, cfg.last_row] + list(range(u + 1, n)))
        l0 = special[0]
        llast = special[cfg.last_row]
        lblind = ctx.load_constant(0)
        for i in range(u + 1, n):
            lblind = gate.add(ctx, lblind, special[i])

        cctx = CellCtx(ctx, gate, evals, l0, llast, lblind, x)
        # MATERIALIZE the generator: every expression's cells must be
        # allocated before the fold's mul_add cells, or the circuit's cell
        # ordering (and with it every pinned layout, cached pk, and proof)
        # silently changes. Cells are ints here — the streaming that
        # matters for the prover's 512 MB arrays costs nothing to undo.
        exprs = list(all_expressions(cfg, cctx, _CellChal(ctx, gate, beta),
                                     _CellChal(ctx, gate, gamma)))
        acc = ctx.load_constant(0)
        for e in exprs:
            acc = gate.mul_add(ctx, acc, y, e)
        xn = gate.add(ctx, zx, 1)
        h01 = gate.mul(ctx, evals[(("h", 1), 0)], xn)
        xn2 = gate.mul(ctx, xn, xn)
        h_at_x = gate.add(ctx, gate.add(ctx, evals[(("h", 0), 0)], h01),
                          gate.mul(ctx, evals[(("h", 2), 0)], xn2))
        rhs = gate.mul(ctx, h_at_x, zx)
        ctx.constrain_equal(acc, rhs)

        # --- SHPLONK (mirrors kzg.shplonk_verify over cells) ---
        v = self._challenge(ctx, tr, tchip)
        w1 = self._read_point(ctx, tr, tchip)
        uch = self._challenge(ctx, tr, tchip)
        w2 = self._read_point(ctx, tr, tchip)

        by_key: dict = {}
        for key, rot in plan:
            by_key.setdefault(key, []).append(rot)

        # rotation point cells: rot -> x * omega^rot
        rot_cells = {}
        all_rots = []
        for key, rots in by_key.items():
            for r_ in rots:
                if r_ not in rot_cells:
                    if r_ == ROT_LAST:
                        wpow = pow(dom.omega, cfg.last_row, R)
                    elif r_ < 0:
                        wpow = pow(dom.omega_inv, -r_, R)
                    else:
                        wpow = pow(dom.omega, r_, R)
                    rot_cells[r_] = gate.mul(ctx, x, wpow)
                    all_rots.append(r_)

        fixed_commits = vk.fixed_commitment_map()
        e_scalar = ctx.load_constant(0)
        vk_pow = ctx.load_constant(1)
        witness_pairs = []       # (point_cells, scalar_cell)
        constant_pairs = []      # (host_point, scalar_cell)
        for key, rots in by_key.items():
            # z_rest(u) over the complement rotation set
            z_rest = ctx.load_constant(1)
            for r_ in all_rots:
                if r_ not in rots:
                    z_rest = gate.mul(
                        ctx, z_rest, gate.sub(ctx, uch, rot_cells[r_]))
            # r_k(u): lagrange interpolation through (points, evals) at u
            if len(rots) == 1:
                r_u = evals[(key, rots[0])]
            else:
                r_u = ctx.load_constant(0)
                for rj in rots:
                    term = evals[(key, rj)]
                    num = ctx.load_constant(1)
                    den = ctx.load_constant(1)
                    for rk in rots:
                        if rk is rj or rk == rj:
                            continue
                        num = gate.mul(
                            ctx, num, gate.sub(ctx, uch, rot_cells[rk]))
                        den = gate.mul(
                            ctx, den, gate.sub(ctx, rot_cells[rj],
                                               rot_cells[rk]))
                    r_u = gate.add(
                        ctx, r_u, gate.mul(ctx, term,
                                           self._div(ctx, num, den)))
            w = gate.mul(ctx, vk_pow, z_rest)
            e_scalar = gate.add(ctx, e_scalar, gate.mul(ctx, w, r_u))
            if key in commits:
                witness_pairs.append((commits[key], w))
            else:
                cpt = fixed_commits[key]
                if cpt is not None:   # infinity contributes nothing
                    constant_pairs.append((cpt, w))
            vk_pow = gate.mul(ctx, vk_pow, v)

        z_t_u = ctx.load_constant(1)
        for r_ in all_rots:
            z_t_u = gate.mul(ctx, z_t_u, gate.sub(ctx, uch, rot_cells[r_]))

        # F = sum w_k C_k - e_scalar*G - z_t_u*W1 ; acc_rhs = F + u*W2
        witness_pairs.append((w1, gate.neg(ctx, z_t_u)))
        witness_pairs.append((w2, uch))
        constant_pairs.append((bn254.G1_GEN, gate.neg(ctx, e_scalar)))
        acc_rhs = self.msm.msm(ctx, witness_pairs, constant_pairs)

        tr.assert_consumed()
        return w2, acc_rhs

    def fold_accumulators(self, ctx: Context, accs: list):
        """RLC-fold N deferred-pairing accumulators into one, with the
        challenges drawn from an in-circuit Poseidon transcript over the
        (canonicalized) accumulator points — the cell-for-cell mirror of
        `models.aggregation.accumulate` (reference: snark-verifier's
        accumulation scheme over multiple snarks). Returns (lhs, rhs)."""
        tchip = TranscriptChip()
        cans = []
        for lhs, rhs in accs:
            clhs = tuple(self.fq.canonicalize(ctx, c) for c in lhs)
            crhs = tuple(self.fq.canonicalize(ctx, c) for c in rhs)
            cans.append((clhs, crhs))
            tchip.absorb_point_limbs(
                ctx, list(clhs[0].limbs) + list(clhs[1].limbs))
            tchip.absorb_point_limbs(
                ctx, list(crhs[0].limbs) + list(crhs[1].limbs))
        rs = [tchip.challenge(ctx) for _ in accs]
        lhs = self.msm.msm(ctx, [(cans[i][0], rs[i])
                                 for i in range(len(accs))], [])
        rhs = self.msm.msm(ctx, [(cans[i][1], rs[i])
                                 for i in range(len(accs))], [])
        return lhs, rhs

    @staticmethod
    def native_accumulator(vk: VerifyingKey, srs: SRS, instances: list,
                           proof: bytes):
        """Host-side mirror returning the same accumulator (test oracle +
        witness cross-check): the shared `verify_deferred` definition with
        the Poseidon transcript — one verifier, three consumers (bool
        verify, this oracle, the in-circuit build)."""
        from ..models.aggregation import Accumulator
        from .verifier import verify_deferred
        acc = verify_deferred(vk, srs, instances, proof,
                              transcript_cls=PoseidonTranscript)
        if acc is None:
            return None
        tau_side, one_side = acc
        return Accumulator(lhs=tau_side, rhs=one_side)

"""PLONKish constraint system: the fixed arithmetization of spectre_tpu.

One universal gate (halo2-lib's "vertical" flex gate, SURVEY.md L2):
    q[i] * (a[i] + a[i+1] * a[i+2] - a[i+3]) = 0
per gate-advice column, plus copy constraints (chunked permutation argument),
plus a range-lookup argument binding designated lookup-advice columns to the
table column [0, 2^lookup_bits).

Column order (global permutation indexing):
    [gate advice][lookup advice][fixed][instance]

ZK: the last ZK_ROWS+1 rows are reserved (blinding + "last" row); the builder
may only use rows < usable_rows(k) and must keep gates off the final 3 usable
rows (the gate reads rotations +1..+3).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..fields import bn254
from .domain import DELTA

R = bn254.R

ZK_ROWS = 5
GATE_ROWS = 4   # the vertical gate reads rotations 0..+3 of its column
PERM_CHUNK = 2  # columns per permutation grand-product (degree 4 budget)
# Quotient commitment chunks: the prover commits h as NUM_H_CHUNKS size-n
# pieces, so deg h <= NUM_H_CHUNKS*n - 4 and every constraint expression
# must stay within degree NUM_H_CHUNKS + 1 in the column polynomials
# (CircuitConfig.max_expr_degree). Changing this means changing the proof
# format: keygen's commitment/query plans, the verifier's Horner fold, the
# in-circuit verifier, and the EVM codegen all read 3 h-commitments.
NUM_H_CHUNKS = 3

# ---------------------------------------------------------------------------
# Wide SHA-256 region (reference: the zkevm "vanilla" SHA circuit wrapped by
# `gadget/crypto/sha256_wide.rs` — fewer rows, more columns, no lookups).
# Redesigned for THIS framework's expression machinery: per block slot of
# SLOT_ROWS rows, SHA_BIT_COLS bit columns (excluded from the permutation)
# carry the w/a/e bit ladders + addition carries, and SHA_WORD_COLS word
# columns (in the permutation) expose h_in/h_out/input words + the pinned
# activity flag for copy-linking into the main region. All identities are homogeneous in the advice (the round
# constant enters as fixed_K * act), so all-zero unused slots satisfy them.
# ---------------------------------------------------------------------------
SHA_BIT_COLS = 104      # w[32] | a[32] | e[32] | carries[8]
SHA_WORD_COLS = 10      # h state words [8] | input words | act flag
SHA_SLOT_ROWS = 72      # 4 seed + 64 rounds + 1 output (+3 spare)
SHA_SEED_ROW = 3
SHA_OUT_ROW = 68
SHA_NUM_SELECTORS = 7   # bit, seed, round, sched, inp, out, act-chain
SHA_W, SHA_A, SHA_E, SHA_CARRY = 0, 32, 64, 96
# act lives in a WORD column (permutation-enabled) so the chip can PIN it to
# the constant 1 on used slots — were it a plain bit column, a malicious
# prover could zero it and prove a K-less hash variant
SHA_ACT_WORD = 9


def sha_selector_columns(cfg: "CircuitConfig") -> tuple[list, list]:
    """Structural fixed content for the SHA region: the 7 selector columns
    and the round-constant column, patterned per slot (keygen + mock share
    this single definition)."""
    from ..ops.sha256 import K as SHA_K

    n, u = cfg.n, cfg.usable_rows
    nsl = cfg.num_sha_slots
    sel = [[0] * n for _ in range(SHA_NUM_SELECTORS)]
    kcol = [0] * n
    for s in range(nsl):
        base = s * SHA_SLOT_ROWS
        assert base + SHA_OUT_ROW < u, "sha slot exceeds usable rows"
        for r in range(SHA_OUT_ROW + 1):              # q_bit rows 0..68
            sel[0][base + r] = 1
        sel[1][base + SHA_SEED_ROW] = 1               # q_seed
        for t in range(64):                           # q_round rows 4..67
            sel[2][base + 4 + t] = 1
        for t in range(16, 64):                       # q_sched rows 20..67
            sel[3][base + 4 + t] = 1
        for t in range(16):                           # q_inp rows 4..19
            sel[4][base + 4 + t] = 1
        sel[5][base + SHA_OUT_ROW] = 1                # q_out
        for r in range(1, SHA_OUT_ROW + 1):           # q_act rows 1..68
            sel[6][base + r] = 1
        for t in range(64):
            kcol[base + 4 + t] = int(SHA_K[t])
    return sel, kcol


def gate_coverage(selectors) -> np.ndarray:
    """[num_advice, n] uint8 mask of rows read by some active gate window:
    a selector firing at row r binds rows r..r+GATE_ROWS-1 of its column
    (the vertical gate's rotations 0..+3). Row-coverage primitive for the
    analysis auditor's CA-ROW-* rules."""
    sel = np.asarray(selectors, dtype=np.uint8)
    cov = sel.copy()
    for off in range(1, GATE_ROWS):
        cov[:, off:] |= sel[:, :sel.shape[1] - off]
    return cov


@dataclass(frozen=True)
class CircuitConfig:
    """Circuit shape — the pinning payload (reference: `Eth2ConfigPinning`
    {k, num_advice, lookup_bits, ...}, `util/circuit.rs:55-78`).

    lookup_tables: table id per lookup-advice column. "range" is the
    [0, 2^lookup_bits) table; "nibble_op" packs 4-bit XOR/AND triples
    (op<<12 | x<<8 | y<<4 | result) for the SHA chip. Empty tuple means
    num_lookup_advice columns of "range" (back-compat)."""

    k: int
    num_advice: int
    num_lookup_advice: int
    num_fixed: int
    lookup_bits: int
    num_instance: int = 1
    lookup_tables: tuple = ()
    num_sha_slots: int = 0

    @property
    def n(self) -> int:
        return 1 << self.k

    @property
    def zk_rows(self) -> int:
        # keep blinding rows strictly above the max per-column open count
        # (halo2's blinding_factors >= queries + 1 margin): the wide-SHA shb
        # columns are opened at 5 rotations, so SHA configs blind 7 rows.
        # NOT 6: zk_rows=6 puts last_row at n-7, whose rotation point
        # omega^(n-7)·x coincides with the SHA w-ladder's rot -7 query —
        # the tag-keyed SHPLONK consumers (in-circuit verifier, EVM codegen)
        # would then disagree with the value-deduping native verifier
        # (keygen asserts this injectivity).
        return ZK_ROWS + 2 if self.num_sha_slots else ZK_ROWS

    @property
    def usable_rows(self) -> int:
        return self.n - self.zk_rows - 1

    @property
    def last_row(self) -> int:
        return self.usable_rows  # l_last index

    @property
    def max_expr_degree(self) -> int:
        """Degree budget per constraint expression, counting each column
        polynomial (advice, fixed, selector, sigma, z, l0/llast/lblind, X)
        as degree 1: an expression of column-degree d has polynomial degree
        <= d*(n-1); after dividing by the degree-n vanishing polynomial the
        quotient must fit the NUM_H_CHUNKS committed chunks, so
        d <= NUM_H_CHUNKS + 1. Exceeding it makes the prover's quotient
        division inexact — the bug class the analysis auditor's CA-DEGREE
        rule catches statically instead of at prove time."""
        return NUM_H_CHUNKS + 1

    @property
    def num_sha_word(self) -> int:
        return SHA_WORD_COLS if self.num_sha_slots else 0

    @property
    def num_sha_bit(self) -> int:
        return SHA_BIT_COLS if self.num_sha_slots else 0

    @property
    def num_perm_columns(self) -> int:
        # sha WORD columns join the permutation (copy-linked to the main
        # region); sha bit columns do not (no copies ever target them)
        return (self.num_advice + self.num_lookup_advice + self.num_fixed
                + self.num_sha_word + self.num_instance)

    @property
    def num_perm_chunks(self) -> int:
        return (self.num_perm_columns + PERM_CHUNK - 1) // PERM_CHUNK

    def col_gate_advice(self, j):
        return j

    def col_lookup_advice(self, j):
        return self.num_advice + j

    def col_fixed(self, j):
        return self.num_advice + self.num_lookup_advice + j

    def col_sha_word(self, j):
        return self.num_advice + self.num_lookup_advice + self.num_fixed + j

    def col_instance(self, j):
        return (self.num_advice + self.num_lookup_advice + self.num_fixed
                + self.num_sha_word + j)

    def table_id(self, j: int) -> str:
        if self.lookup_tables:
            return self.lookup_tables[j]
        return "range"

    def validate(self):
        assert self.lookup_bits < self.k, "table must fit the usable rows"
        assert (1 << self.lookup_bits) <= self.usable_rows
        assert self.num_instance >= 1
        if self.lookup_tables:
            assert len(self.lookup_tables) == self.num_lookup_advice
        if "nibble_op" in (self.lookup_tables or ()):
            assert 2 * 16 * 16 <= self.usable_rows, \
                "nibble_op table (512 rows) does not fit usable rows"


@dataclass
class Assignment:
    """Witness-side circuit assignment (values as python-int lists).

    copies: list of ((col_a, row_a), (col_b, row_b)) equality constraints,
    using the global column indexing above."""

    config: CircuitConfig
    advice: list            # [num_advice][n] ints
    lookup_advice: list     # [num_lookup_advice][n] ints
    fixed: list             # [num_fixed][n] ints
    selectors: list         # [num_advice][n] 0/1 ints
    instances: list         # [num_instance][<=usable] ints
    copies: list = field(default_factory=list)
    # wide SHA region witness (numpy, small dtypes — these columns are
    # megacell-scale): [num_sha_bit][n] uint32 bits, [num_sha_word][n]
    # uint64 32-bit words
    sha_bit: object = None
    sha_word: object = None

    def instance_column(self, j) -> list:
        col = [0] * self.config.n
        for i, v in enumerate(self.instances[j]):
            col[i] = int(v) % R
        return col


def table_column(cfg: CircuitConfig, table_id: str = "range") -> list:
    """Table fixed polynomials, zero-padded (zero is a member of every table,
    so padding rows remain valid entries).

    "range":     0..2^lookup_bits-1
    "nibble_op": packed 4-bit bitwise triples — (op << 12) | (x << 8) |
                 (y << 4) | f_op(x, y), op 0 = XOR, op 1 = AND. The SHA chip
                 proves z = x op y by asserting membership of the packed
                 value (the TPU-era answer to the reference's spread-table
                 custom gates: pure lookups, no custom region)."""
    if table_id == "range":
        vals = list(range(1 << cfg.lookup_bits))
    elif table_id == "nibble":
        vals = list(range(16))
    elif table_id == "nibble_op":
        vals = []
        for x in range(16):
            for y in range(16):
                vals.append((0 << 12) | (x << 8) | (y << 4) | (x ^ y))
        for x in range(16):
            for y in range(16):
                vals.append((1 << 12) | (x << 8) | (y << 4) | (x & y))
    else:
        raise KeyError(table_id)
    vals += [0] * (cfg.n - len(vals))
    return vals


# ---------------------------------------------------------------------------
# permutation helpers
# ---------------------------------------------------------------------------

def build_sigma(cfg: CircuitConfig, copies) -> list[list[int]]:
    """Cycle copy pairs; return sigma value columns:
    sigma_j[i] = delta^{j'} * omega^{i'} where (j', i') = sigma(j, i).

    Cycle construction is halo2's next-pointer merge (swapping successors of
    two cells in distinct cycles concatenates them) with small-to-large
    membership relabeling; sigma evaluation is a vectorized gather over the
    backend's limb arrays — the previous union-find + per-cell bigint loop
    dominated keygen/mock wall-clock on megacell circuits."""
    from .domain import Domain
    from . import backend as B
    from ..native import host

    n = cfg.n
    m = cfg.num_perm_columns
    u = cfg.usable_rows

    nxt: dict = {}       # cell idx -> cycle successor
    cyc: dict = {}       # cell idx -> cycle representative
    members: dict = {}   # representative -> [cells]

    for (ca, ra), (cb, rb) in copies:
        assert 0 <= ca < m and 0 <= cb < m, "copy column out of range"
        assert ra < u and rb < u, "copy constraint in blinding rows"
        a = ca * n + ra
        b = cb * n + rb
        for x in (a, b):
            if x not in nxt:
                nxt[x] = x
                cyc[x] = x
                members[x] = [x]
        ia, ib = cyc[a], cyc[b]
        if ia == ib:
            continue
        if len(members[ia]) < len(members[ib]):
            ia, ib = ib, ia
        for cell in members[ib]:
            cyc[cell] = ia
        members[ia].extend(members.pop(ib))
        nxt[a], nxt[b] = nxt[b], nxt[a]

    # sigma(j, i) as a flat target index array, identity outside cycles
    tgt = np.arange(m * n, dtype=np.int64)
    if nxt:
        keys = np.fromiter(nxt.keys(), dtype=np.int64, count=len(nxt))
        vals = np.fromiter(nxt.values(), dtype=np.int64, count=len(nxt))
        tgt[keys] = vals
    jp = tgt // n
    ip = tgt % n

    bk = B.get_backend()
    dom = Domain(cfg.k)
    omega_arr = np.asarray(bk.powers(dom.omega, n))
    delta_limbs = host.ints_to_limbs([pow(DELTA, j, R) for j in range(m)])
    sigma = []
    for j in range(m):
        sl = slice(j * n, (j + 1) * n)
        col = bk.mul(delta_limbs[jp[sl]], omega_arr[ip[sl]])
        sigma.append(host.limbs_to_ints(col))
    return sigma


def permute_lookup(cfg: CircuitConfig, a_vals: list, t_vals: list):
    """halo2-style (A', T') for one lookup argument over the active rows.

    A' = sorted A; T' = permutation of T aligning first occurrences:
    A'[i] == A'[i-1] or A'[i] == T'[i]."""
    u = cfg.usable_rows
    a_active = [int(v) % R for v in a_vals[:u]]
    t_active = [int(v) % R for v in t_vals[:u]]
    a_sorted = sorted(a_active)
    t_remaining = {}
    for v in t_active:
        t_remaining[v] = t_remaining.get(v, 0) + 1
    t_prime = [None] * u
    # place required first-occurrences
    for i, v in enumerate(a_sorted):
        if i == 0 or v != a_sorted[i - 1]:
            assert t_remaining.get(v, 0) > 0, f"lookup value {v} not in table"
            t_remaining[v] -= 1
            t_prime[i] = v
    # fill the rest with unused table values
    leftovers = []
    for v, cnt in t_remaining.items():
        leftovers.extend([v] * cnt)
    it = iter(leftovers)
    for i in range(u):
        if t_prime[i] is None:
            t_prime[i] = next(it)
    # blinding tail: arbitrary (deactivated rows)
    pad = cfg.n - u
    return a_sorted + [0] * pad, t_prime + [0] * pad

"""Key generation: fixed-poly commitments, permutation sigmas, query plan.

Reference parity: halo2 keygen_vk/keygen_pk via `AppCircuit::create_pk`
(`util/circuit.rs:119-137`). The query plan (which poly is opened at which
rotations) is the shared contract between prover and verifier.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..fields import bn254
from . import backend as B
from .constraint_system import (CircuitConfig, NUM_H_CHUNKS, build_sigma,
                                table_column)
from .domain import Domain
from .srs import SRS
from . import kzg

R = bn254.R

# rotation tag for the "last usable row" query used by permutation chunk links
ROT_LAST = "last"


@dataclass
class VerifyingKey:
    config: CircuitConfig
    selector_commits: list
    fixed_commits: list
    sigma_commits: list
    table_commits: list    # one per lookup-advice column (cfg.table_id(j))
    sha_selector_commits: list = None   # 7 region selectors (num_sha_slots)
    sha_k_commit: object = None         # round-constant column

    @property
    def domain(self) -> Domain:
        from .domain import get_domain
        return get_domain(self.config.k)

    def digest(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        cfg = self.config
        h.update(repr((cfg.k, cfg.num_advice, cfg.num_lookup_advice, cfg.num_fixed,
                       cfg.lookup_bits, cfg.num_instance,
                       cfg.num_sha_slots)).encode())
        h.update(repr(cfg.lookup_tables).encode())
        for pt in (self.selector_commits + self.fixed_commits
                   + self.sigma_commits + self.table_commits
                   + (self.sha_selector_commits or [])
                   + ([self.sha_k_commit] if cfg.num_sha_slots else [])):
            h.update(bn254.g1_to_bytes(pt))
        return h.digest()

    def commitment_plan(self):
        """Ordered commitment keys as read from the proof stream, with the
        challenge boundaries: (keys, pre_beta_gamma, pre_y, pre_x). The
        SINGLE source for the verifier and the EVM codegen — any change to
        the prover's write order must land here."""
        cfg = self.config
        keys = []
        for j in range(cfg.num_advice):
            keys.append(("adv", j))
        for j in range(cfg.num_lookup_advice):
            keys.append(("ladv", j))
        for j in range(cfg.num_sha_bit):
            keys.append(("shb", j))
        for j in range(cfg.num_sha_word):
            keys.append(("shw", j))
        for j in range(cfg.num_lookup_advice):
            keys.append(("pA", j))
            keys.append(("pT", j))
        pre_bg = len(keys)
        for c in range(cfg.num_perm_chunks):
            keys.append(("pz", c))
        for j in range(cfg.num_lookup_advice):
            keys.append(("lz", j))
        pre_y = len(keys)
        for i in range(NUM_H_CHUNKS):
            keys.append(("h", i))
        return keys, pre_bg, pre_y, len(keys)

    def fixed_commitment_map(self) -> dict:
        """key -> commitment for the vk-side (non-proof) commitments."""
        out = {}
        for j, c in enumerate(self.table_commits):
            out[("tab", j)] = c
        for j, c in enumerate(self.selector_commits):
            out[("q", j)] = c
        for j, c in enumerate(self.fixed_commits):
            out[("fix", j)] = c
        for j, c in enumerate(self.sigma_commits):
            out[("sig", j)] = c
        for j, c in enumerate(self.sha_selector_commits or []):
            out[("shq", j)] = c
        if self.sha_k_commit is not None or self.config.num_sha_slots:
            out[("shk", 0)] = self.sha_k_commit
        return out

    def query_plan(self):
        """Ordered (key, rotation) pairs — the eval section of the proof."""
        cfg = self.config
        plan = []
        for j in range(cfg.num_advice):
            for rot in (0, 1, 2, 3):
                plan.append((("adv", j), rot))
        for j in range(cfg.num_lookup_advice):
            plan.append((("ladv", j), 0))
            plan.append((("pA", j), 0))
            plan.append((("pA", j), -1))
            plan.append((("pT", j), 0))
            plan.append((("lz", j), 0))
            plan.append((("lz", j), 1))
        for c in range(cfg.num_perm_chunks):
            plan.append((("pz", c), 0))
            plan.append((("pz", c), 1))
            if c + 1 < cfg.num_perm_chunks:
                plan.append((("pz", c), ROT_LAST))
        for j in range(cfg.num_advice):
            plan.append((("q", j), 0))
        for j in range(cfg.num_fixed):
            plan.append((("fix", j), 0))
        for j in range(cfg.num_perm_columns):
            plan.append((("sig", j), 0))
        for j in range(cfg.num_lookup_advice):
            plan.append((("tab", j), 0))
        if cfg.num_sha_slots:
            from .constraint_system import (SHA_A, SHA_ACT_WORD, SHA_CARRY,
                                            SHA_E, SHA_OUT_ROW, SHA_SEED_ROW,
                                            SHA_W)
            for i in range(32):                       # w bits
                for rot in (0, -2, -7, -15, -16):
                    plan.append((("shb", SHA_W + i), rot))
            for i in range(32):                       # a bits
                for rot in (0, -1, -2, -3, -4):
                    plan.append((("shb", SHA_A + i), rot))
            for i in range(32):                       # e bits
                for rot in (0, -1, -2, -3, -4):
                    plan.append((("shb", SHA_E + i), rot))
            for i in range(8):                        # carries
                plan.append((("shb", SHA_CARRY + i), 0))
            back = SHA_SEED_ROW - SHA_OUT_ROW
            for j in range(8):
                plan.append((("shw", j), 0))
                plan.append((("shw", j), back))
            plan.append((("shw", 8), 0))
            plan.append((("shw", SHA_ACT_WORD), 0))   # act flag
            plan.append((("shw", SHA_ACT_WORD), -1))
            from .constraint_system import SHA_NUM_SELECTORS
            for s in range(SHA_NUM_SELECTORS):
                plan.append((("shq", s), 0))
            plan.append((("shk", 0), 0))
        for i in range(NUM_H_CHUNKS):
            plan.append((("h", i), 0))
        return plan

    def assert_rotation_injective(self):
        """Distinct rotation TAGS in the query plan must evaluate to
        distinct points omega^rot · x: the in-circuit verifier and the EVM
        codegen key the SHPLONK sets by tag, while the native verifier
        dedupes by value — a collision (e.g. last_row ≡ a negative region
        rotation mod n) silently desynchronizes them."""
        dom = self.domain
        seen = {}
        for _key, rot in self.query_plan():
            idx = self.config.last_row if rot == ROT_LAST else rot % dom.n
            w = pow(dom.omega, idx, R)
            prev = seen.setdefault(w, rot)
            assert prev == rot or (isinstance(prev, int) and isinstance(rot, int)
                                   and prev % dom.n == rot % dom.n), \
                f"rotation tags {prev} and {rot} share omega^rot (zk_rows " \
                f"collision — adjust CircuitConfig.zk_rows)"

    def rotation_point(self, x: int, rot) -> int:
        dom = self.domain
        if rot == ROT_LAST:
            return pow(dom.omega, self.config.last_row, R) * x % R
        if rot < 0:
            return pow(dom.omega_inv, -rot, R) * x % R
        return pow(dom.omega, rot, R) * x % R


@dataclass
class ProvingKey:
    vk: VerifyingKey
    selector_polys: list      # coefficient form [n,4] arrays
    fixed_polys: list
    sigma_polys: list
    table_polys: list         # one per lookup-advice column
    # lagrange (value) forms kept for prover-side products
    selector_values: list
    fixed_values: list
    sigma_values: list        # int lists
    table_values: list        # one list per lookup-advice column
    sha_selector_polys: list = None
    sha_k_poly: object = None

    def release_ext_cache(self):
        """Drop the prover's cached extended-domain forms of the fixed
        columns (populated lazily by `_quotient_host`, ~GBs at k=21). A
        service holding several pks calls this on the idle families so
        peaks don't stack (`prover_service/state.py`)."""
        self.__dict__.pop("_ext_fixed_cache", None)


def keygen(srs: SRS, cfg: CircuitConfig, fixed_columns: list, selectors: list,
           copies: list, bk=None) -> ProvingKey:
    """Generate pk/vk from the circuit's fixed content.

    fixed_columns: [num_fixed][n] ints; selectors: [num_advice][n] 0/1;
    copies: global copy-constraint pairs."""
    bk = bk or B.get_backend()
    cfg.validate()
    dom = Domain(cfg.k)
    assert srs.n >= cfg.n, "SRS too small for circuit"

    sel_vals = [list(map(int, s)) for s in selectors]
    fix_vals = [list(map(int, f)) for f in fixed_columns]
    # one table build per DISTINCT table id; columns share the objects
    tab_by_id = {tid: table_column(cfg, tid)
                 for tid in {cfg.table_id(j) for j in range(cfg.num_lookup_advice)}}
    tab_vals = [tab_by_id[cfg.table_id(j)] for j in range(cfg.num_lookup_advice)]
    sigma_vals = build_sigma(cfg, copies)

    def to_poly(vals):
        return dom.lagrange_to_coeff(B.to_arr(vals), bk)

    sel_polys = [to_poly(v) for v in sel_vals]
    fix_polys = [to_poly(v) for v in fix_vals]
    sig_polys = [to_poly(v) for v in sigma_vals]
    tab_poly_by_id = {tid: to_poly(v) for tid, v in tab_by_id.items()}
    tab_polys = [tab_poly_by_id[cfg.table_id(j)]
                 for j in range(cfg.num_lookup_advice)]
    tab_ids = sorted(tab_poly_by_id)

    sha_sel_polys, sha_k_poly = None, None
    sha_sel_commits, sha_k_commit = None, None
    if cfg.num_sha_slots:
        from .constraint_system import sha_selector_columns
        sha_sel, sha_k = sha_selector_columns(cfg)
        sha_sel_polys = [to_poly(v) for v in sha_sel]
        sha_k_poly = to_poly(sha_k)

    # all vk commitments in ONE batched backend call (device base cached,
    # batch axis mesh-shardable — same machinery as the prover commit phase)
    batch = (sel_polys + fix_polys + sig_polys
             + [tab_poly_by_id[t] for t in tab_ids]
             + (sha_sel_polys or [])
             + ([sha_k_poly] if sha_k_poly is not None else []))
    pts = kzg.commit_many(srs, batch, bk)
    off = 0
    def take(k):
        nonlocal off
        out = pts[off:off + k]
        off += k
        return out
    sel_commits = take(len(sel_polys))
    fix_commits = take(len(fix_polys))
    sig_commits = take(len(sig_polys))
    tab_commit_by_id = dict(zip(tab_ids, take(len(tab_ids))))
    if cfg.num_sha_slots:
        sha_sel_commits = take(len(sha_sel_polys))
        sha_k_commit = take(1)[0]

    vk = VerifyingKey(
        config=cfg,
        selector_commits=sel_commits,
        fixed_commits=fix_commits,
        sigma_commits=sig_commits,
        table_commits=[tab_commit_by_id[cfg.table_id(j)]
                       for j in range(cfg.num_lookup_advice)],
        sha_selector_commits=sha_sel_commits,
        sha_k_commit=sha_k_commit,
    )
    vk.assert_rotation_injective()
    return ProvingKey(vk, sel_polys, fix_polys, sig_polys, tab_polys,
                      sel_vals, fix_vals, sigma_vals, tab_vals,
                      sha_selector_polys=sha_sel_polys,
                      sha_k_poly=sha_k_poly)

"""MockProver: constraint-satisfaction check without proving.

Reference parity: halo2's `MockProver::run(...).assert_satisfied()` — the
first rung of the test ladder (SURVEY.md §4). Evaluates every constraint
row-wise on the base domain (same `all_expressions` definition as the real
prover/verifier) and reports the exact (expression, row) of any violation;
also checks copy constraints and lookup membership directly.

Evaluation runs on the numeric backend's [n, 4] u64 limb arrays (the same
vectorized field ops the prover uses), with batch-inverted grand products —
per-row Python bigint loops made multi-megacell circuits (the aggregation
verifier, the pairing tests) minutes-slow to mock.
"""

from __future__ import annotations

import numpy as np

from ..fields import bn254
from . import backend as B
from .constraint_system import Assignment, CircuitConfig
from .domain import Domain
from .expressions import all_expressions, perm_column_keys
from .keygen import ROT_LAST

R = bn254.R


class _ArrCtx:
    """Expression context over [n,4] u64 backend arrays; rotations are index
    shifts mod n."""

    def __init__(self, cfg: CircuitConfig, dom: Domain, columns: dict, bk):
        self._cfg = cfg
        self._cols = columns
        self._bk = bk
        self._const_cache: dict = {}
        n = cfg.n
        self.x_col = bk.powers(dom.omega, n)
        l0 = np.zeros((n, 4), dtype=np.uint64)
        l0[0, 0] = 1
        self.l0 = l0
        llast = np.zeros((n, 4), dtype=np.uint64)
        llast[cfg.last_row, 0] = 1
        self.llast = llast
        lblind = np.zeros((n, 4), dtype=np.uint64)
        lblind[cfg.usable_rows + 1:, 0] = 1
        self.lblind = lblind

    def var(self, key, rot):
        col = self._cols[key]
        if rot == ROT_LAST:
            rot = self._cfg.last_row
        return np.roll(col, -rot, axis=0) if rot else col

    def mul(self, a, b):
        return self._bk.mul(a, b)

    def add(self, a, b):
        return self._bk.add(a, b)

    def sub(self, a, b):
        return self._bk.sub(a, b)

    def scale(self, a, s):
        return self._bk.scale(a, s % R)

    def add_const(self, a, s):
        return self._bk.add(a, self.const(s))

    def const(self, s):
        s = s % R
        arr = self._const_cache.get(s)
        if arr is None:
            from ..native import host
            arr = np.tile(host.ints_to_limbs([s]), (self._cfg.n, 1))
            self._const_cache[s] = arr
        return arr


def _running_product(bk, ratio_arr, start: int, u: int, n: int) -> list[int]:
    """z[0]=start; z[i+1]=z[i]*ratio[i] for i<u; constant afterwards."""
    pref = B.arr_to_ints(bk.prefix_prod(ratio_arr[:u]))
    z = [start] + [start * p % R for p in pref]
    z += [z[u]] * (n - len(z))
    return z


def mock_prove(cfg: CircuitConfig, assignment: Assignment, fixed_values=None,
               selector_values=None, sigma_values=None, table_values=None):
    """Raises AssertionError naming the first violated (expression, row).

    When keygen products (sigma/table) are not supplied they are rebuilt from
    the assignment — callers can mock-check a circuit without an SRS."""
    from .constraint_system import build_sigma, permute_lookup, table_column

    bk = B.get_backend()
    dom = Domain(cfg.k)
    n, u = cfg.n, cfg.usable_rows
    fixed_values = fixed_values or [list(map(int, f)) for f in assignment.fixed]
    selector_values = selector_values or [list(map(int, s)) for s in assignment.selectors]
    sigma_values = sigma_values or build_sigma(cfg, assignment.copies)
    table_values = table_values or [table_column(cfg, cfg.table_id(j))
                                    for j in range(cfg.num_lookup_advice)]

    # --- direct checks first (better error messages than the polynomial ones) ---
    # flat per-column value lists: the per-cell closure version cost ~2us/cell
    # and dominated mock wall-clock on megacell circuits
    keys = perm_column_keys(cfg)
    colv = []
    for kind, j in keys:
        if kind == "adv":
            colv.append(assignment.advice[j])
        elif kind == "ladv":
            colv.append(assignment.lookup_advice[j])
        elif kind == "fix":
            colv.append(fixed_values[j])
        elif kind == "shw":
            colv.append(assignment.sha_word[j])
        else:
            colv.append(assignment.instance_column(j))

    for (ca, ra), (cb, rb) in assignment.copies:
        if colv[ca][ra] != colv[cb][rb]:
            # values are stored reduced by the builder; re-reduce before
            # declaring violation in case a hand-built assignment was not
            if int(colv[ca][ra]) % R != int(colv[cb][rb]) % R:
                raise AssertionError(
                    f"copy constraint violated: col{ca}[{ra}]={colv[ca][ra]} "
                    f"!= col{cb}[{rb}]={colv[cb][rb]}")

    for j, col in enumerate(assignment.lookup_advice):
        table_set = set(int(v) % R for v in table_values[j][:u])
        bad = [i for i, v in enumerate(col[:u])
               if v not in table_set and int(v) % R not in table_set]
        assert not bad, \
            f"lookup col {j} row {bad[0]}: {col[bad[0]]} not in table"

    # --- full polynomial constraint evaluation (same exprs as the prover) ---
    beta, gamma = 0xBEEF, 0xCAFE  # any nonzero values work for satisfaction
    columns = {}
    for j, v in enumerate(assignment.advice):
        columns[("adv", j)] = B.to_arr([int(x) % R for x in v])
    for j, v in enumerate(assignment.lookup_advice):
        columns[("ladv", j)] = B.to_arr([int(x) % R for x in v])
    for j, v in enumerate(fixed_values):
        columns[("fix", j)] = B.to_arr([int(x) % R for x in v])
    for j, v in enumerate(selector_values):
        columns[("q", j)] = B.to_arr([int(x) % R for x in v])
    for j, v in enumerate(sigma_values):
        columns[("sig", j)] = B.to_arr([int(x) % R for x in v])
    for j in range(cfg.num_lookup_advice):
        columns[("tab", j)] = B.to_arr([int(x) % R for x in table_values[j]])
    for j in range(cfg.num_instance):
        columns[("inst", j)] = B.to_arr(assignment.instance_column(j))
    if cfg.num_sha_slots:
        from .constraint_system import sha_selector_columns
        for j in range(cfg.num_sha_bit):
            columns[("shb", j)] = B.to_arr(assignment.sha_bit[j].tolist())
        for j in range(cfg.num_sha_word):
            columns[("shw", j)] = B.to_arr(assignment.sha_word[j].tolist())
        sha_sel, sha_k = sha_selector_columns(cfg)
        for j, v in enumerate(sha_sel):
            columns[("shq", j)] = B.to_arr(v)
        columns[("shk", 0)] = B.to_arr(sha_k)

    # grand products, mirroring the prover (vectorized: the per-chunk
    # num/den columns are backend products with ONE batch inversion)
    from .constraint_system import PERM_CHUNK
    from .domain import DELTA
    from ..native import host
    col_keys = perm_column_keys(cfg)
    omega_pows = bk.powers(dom.omega, n)
    prev_end = 1
    beta_arr = np.tile(host.ints_to_limbs([beta]), (n, 1))
    gamma_arr = np.tile(host.ints_to_limbs([gamma]), (n, 1))
    for ch in range(cfg.num_perm_chunks):
        cols = list(enumerate(col_keys))[ch * PERM_CHUNK:(ch + 1) * PERM_CHUNK]
        num = None
        den = None
        for gidx, key in cols:
            v = columns[key]
            nterm = bk.add(bk.add(v, bk.scale(omega_pows,
                                              beta * pow(DELTA, gidx, R) % R)),
                           gamma_arr)
            dterm = bk.add(bk.add(v, bk.scale(columns[("sig", gidx)], beta)),
                           gamma_arr)
            num = nterm if num is None else bk.mul(num, nterm)
            den = dterm if den is None else bk.mul(den, dterm)
        ratio = bk.mul(num[:u], bk.inv(den[:u]))
        z = _running_product(bk, ratio, prev_end, u, n)
        prev_end = z[u]
        columns[("pz", ch)] = B.to_arr(z)
    assert prev_end == 1, "permutation grand product != 1"

    for j in range(cfg.num_lookup_advice):
        pa, pt = permute_lookup(cfg, B.arr_to_ints(columns[("ladv", j)]),
                                table_values[j])
        columns[("pA", j)] = B.to_arr(pa)
        columns[("pT", j)] = B.to_arr(pt)
        num = bk.mul(bk.add(columns[("ladv", j)], beta_arr),
                     bk.add(columns[("tab", j)], gamma_arr))
        den = bk.mul(bk.add(columns[("pA", j)], beta_arr),
                     bk.add(columns[("pT", j)], gamma_arr))
        ratio = bk.mul(num[:u], bk.inv(den[:u]))
        columns[("lz", j)] = B.to_arr(_running_product(bk, ratio, 1, u, n))

    ctx = _ArrCtx(cfg, dom, columns, bk)
    exprs = all_expressions(cfg, ctx, beta, gamma)
    for ei, vals in enumerate(exprs):
        nz = np.nonzero(vals.any(axis=1))[0]
        if len(nz):
            row = int(nz[0])
            val = B.arr_to_ints(vals[row:row + 1])[0]
            raise AssertionError(
                f"constraint #{ei} violated at row {row} (value {val})")
    return True

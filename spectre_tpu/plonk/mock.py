"""MockProver: constraint-satisfaction check without proving.

Reference parity: halo2's `MockProver::run(...).assert_satisfied()` — the
first rung of the test ladder (SURVEY.md §4). Evaluates every constraint
row-wise on the base domain (same `all_expressions` definition as the real
prover/verifier) and reports the exact (expression, row) of any violation;
also checks copy constraints and lookup membership directly.
"""

from __future__ import annotations

from ..fields import bn254
from .constraint_system import Assignment, CircuitConfig
from .domain import Domain
from .expressions import all_expressions, perm_column_keys
from .keygen import ROT_LAST

R = bn254.R


class _RowCtx:
    """Expression context over full value columns (python int lists);
    rotations are index shifts mod n."""

    def __init__(self, cfg: CircuitConfig, dom: Domain, columns: dict):
        self._cfg = cfg
        self._cols = columns
        n = cfg.n
        omega_pows = [1] * n
        for i in range(1, n):
            omega_pows[i] = omega_pows[i - 1] * dom.omega % R
        self.x_col = omega_pows
        self.l0 = [1] + [0] * (n - 1)
        self.llast = [1 if i == cfg.last_row else 0 for i in range(n)]
        self.lblind = [1 if i > cfg.usable_rows else 0 for i in range(n)]

    def var(self, key, rot):
        col = self._cols[key]
        n = len(col)
        if rot == ROT_LAST:
            rot = self._cfg.last_row
        return [col[(i + rot) % n] for i in range(n)]

    def mul(self, a, b):
        return [x * y % R for x, y in zip(a, b)]

    def add(self, a, b):
        return [(x + y) % R for x, y in zip(a, b)]

    def sub(self, a, b):
        return [(x - y) % R for x, y in zip(a, b)]

    def scale(self, a, s):
        return [x * s % R for x in a]

    def add_const(self, a, s):
        return [(x + s) % R for x in a]

    def const(self, s):
        return [s % R] * self._cfg.n


def mock_prove(cfg: CircuitConfig, assignment: Assignment, fixed_values=None,
               selector_values=None, sigma_values=None, table_values=None):
    """Raises AssertionError naming the first violated (expression, row).

    When keygen products (sigma/table) are not supplied they are rebuilt from
    the assignment — callers can mock-check a circuit without an SRS."""
    from .constraint_system import build_sigma, permute_lookup, table_column

    dom = Domain(cfg.k)
    n, u = cfg.n, cfg.usable_rows
    fixed_values = fixed_values or [list(map(int, f)) for f in assignment.fixed]
    selector_values = selector_values or [list(map(int, s)) for s in assignment.selectors]
    sigma_values = sigma_values or build_sigma(cfg, assignment.copies)
    table_values = table_values or [table_column(cfg, cfg.table_id(j))
                                    for j in range(cfg.num_lookup_advice)]

    # --- direct checks first (better error messages than the polynomial ones) ---
    def cell(col_idx, row):
        keys = perm_column_keys(cfg)
        kind, j = keys[col_idx]
        src = {"adv": assignment.advice, "ladv": assignment.lookup_advice,
               "fix": fixed_values}.get(kind)
        if kind == "inst":
            return assignment.instance_column(j)[row]
        return int(src[j][row]) % R

    for (ca, ra), (cb, rb) in assignment.copies:
        va, vb = cell(ca, ra), cell(cb, rb)
        assert va == vb, f"copy constraint violated: col{ca}[{ra}]={va} != col{cb}[{rb}]={vb}"

    for j, col in enumerate(assignment.lookup_advice):
        table_set = set(int(v) % R for v in table_values[j][:u])
        for i in range(u):
            v = int(col[i]) % R
            assert v in table_set, f"lookup col {j} row {i}: {v} not in table"

    # --- full polynomial constraint evaluation (same exprs as the prover) ---
    beta, gamma = 0xBEEF, 0xCAFE  # any nonzero values work for satisfaction
    columns = {}
    for j, v in enumerate(assignment.advice):
        columns[("adv", j)] = [int(x) % R for x in v]
    for j, v in enumerate(assignment.lookup_advice):
        columns[("ladv", j)] = [int(x) % R for x in v]
    for j, v in enumerate(fixed_values):
        columns[("fix", j)] = [int(x) % R for x in v]
    for j, v in enumerate(selector_values):
        columns[("q", j)] = [int(x) % R for x in v]
    for j, v in enumerate(sigma_values):
        columns[("sig", j)] = [int(x) % R for x in v]
    for j in range(cfg.num_lookup_advice):
        columns[("tab", j)] = [int(x) % R for x in table_values[j]]
    for j in range(cfg.num_instance):
        columns[("inst", j)] = assignment.instance_column(j)

    # grand products, mirroring the prover
    from .constraint_system import PERM_CHUNK
    from .domain import DELTA
    col_keys = perm_column_keys(cfg)
    omega_pows = [1] * n
    for i in range(1, n):
        omega_pows[i] = omega_pows[i - 1] * dom.omega % R
    prev_end = 1
    for ch in range(cfg.num_perm_chunks):
        cols = list(enumerate(col_keys))[ch * PERM_CHUNK:(ch + 1) * PERM_CHUNK]
        z = [0] * n
        z[0] = prev_end
        for i in range(n):
            if i + 1 < n:
                if i < u:
                    num = den = 1
                    for gidx, key in cols:
                        v = columns[key][i]
                        num = num * ((v + beta * pow(DELTA, gidx, R) * omega_pows[i] + gamma) % R) % R
                        den = den * ((v + beta * sigma_values[gidx][i] + gamma) % R) % R
                    z[i + 1] = z[i] * num % R * pow(den, -1, R) % R
                else:
                    z[i + 1] = z[i]
        prev_end = z[u]
        columns[("pz", ch)] = z
    assert prev_end == 1, "permutation grand product != 1"

    for j in range(cfg.num_lookup_advice):
        pa, pt = permute_lookup(cfg, columns[("ladv", j)], table_values[j])
        columns[("pA", j)] = pa
        columns[("pT", j)] = pt
        z = [0] * n
        z[0] = 1
        for i in range(n):
            if i + 1 < n:
                if i < u:
                    num = (columns[("ladv", j)][i] + beta) % R * ((table_values[j][i] + gamma) % R) % R
                    den = (pa[i] + beta) % R * ((pt[i] + gamma) % R) % R
                    z[i + 1] = z[i] * num % R * pow(den, -1, R) % R
                else:
                    z[i + 1] = z[i]
        columns[("lz", j)] = z

    ctx = _RowCtx(cfg, dom, columns)
    exprs = all_expressions(cfg, ctx, beta, gamma)
    for ei, vals in enumerate(exprs):
        for i in range(n):
            assert vals[i] == 0, \
                f"constraint #{ei} violated at row {i} (value {vals[i]})"
    return True

"""The constraint expressions, written once, evaluated two ways.

`all_expressions(cfg, ctx)` builds the ordered list of constraint values; the
prover instantiates ctx over extended-domain evaluation ARRAYS (backend ops),
the verifier over SCALARS at the challenge point. One definition guarantees
both sides combine identical polynomials with identical y-powers — the classic
source of prover/verifier drift in hand-rolled PLONK implementations.
"""

from __future__ import annotations

from ..fields import bn254
from .constraint_system import CircuitConfig, PERM_CHUNK
from .domain import DELTA
from .keygen import ROT_LAST

R = bn254.R


def perm_column_keys(cfg: CircuitConfig):
    """Global permutation column index -> var key."""
    keys = []
    for j in range(cfg.num_advice):
        keys.append(("adv", j))
    for j in range(cfg.num_lookup_advice):
        keys.append(("ladv", j))
    for j in range(cfg.num_fixed):
        keys.append(("fix", j))
    for j in range(cfg.num_instance):
        keys.append(("inst", j))
    return keys


def all_expressions(cfg: CircuitConfig, c, beta: int, gamma: int):
    """Ordered constraint list. ctx protocol:
    var(key, rot), mul/add/sub, scale(a, int), add_const(a, int), const(int),
    l0, llast, lblind, x_col (the identity polynomial X)."""
    exprs = []
    one = c.const(1)

    # --- gates: q_j * (a + a1*a2 - a3) ---
    for j in range(cfg.num_advice):
        a0 = c.var(("adv", j), 0)
        a1 = c.var(("adv", j), 1)
        a2 = c.var(("adv", j), 2)
        a3 = c.var(("adv", j), 3)
        q = c.var(("q", j), 0)
        exprs.append(c.mul(q, c.sub(c.add(a0, c.mul(a1, a2)), a3)))

    # --- permutation argument ---
    col_keys = perm_column_keys(cfg)
    nch = cfg.num_perm_chunks
    act = c.sub(one, c.add(c.llast, c.lblind))
    exprs.append(c.mul(c.l0, c.sub(c.var(("pz", 0), 0), one)))
    for ch in range(1, nch):
        exprs.append(c.mul(c.l0, c.sub(c.var(("pz", ch), 0),
                                       c.var(("pz", ch - 1), ROT_LAST))))
    for ch in range(nch):
        cols = list(enumerate(col_keys))[ch * PERM_CHUNK:(ch + 1) * PERM_CHUNK]
        left = c.var(("pz", ch), 1)
        right = c.var(("pz", ch), 0)
        for gidx, key in cols:
            v = c.var(key, 0)
            sig = c.var(("sig", gidx), 0)
            left = c.mul(left, c.add_const(c.add(v, c.scale(sig, beta)), gamma))
            dj = pow(DELTA, gidx, R)
            right = c.mul(right, c.add_const(
                c.add(v, c.scale(c.x_col, beta * dj % R)), gamma))
        exprs.append(c.mul(act, c.sub(left, right)))
    zl = c.var(("pz", nch - 1), 0)
    exprs.append(c.mul(c.llast, c.sub(c.mul(zl, zl), zl)))

    # --- lookups (range table) ---
    for j in range(cfg.num_lookup_advice):
        a = c.var(("ladv", j), 0)
        pa = c.var(("pA", j), 0)
        pa_prev = c.var(("pA", j), -1)
        pt = c.var(("pT", j), 0)
        tab = c.var(("tab", j), 0)
        lz = c.var(("lz", j), 0)
        lz1 = c.var(("lz", j), 1)
        exprs.append(c.mul(c.l0, c.sub(lz, one)))
        left = c.mul(lz1, c.mul(c.add_const(pa, beta), c.add_const(pt, gamma)))
        right = c.mul(lz, c.mul(c.add_const(a, beta), c.add_const(tab, gamma)))
        exprs.append(c.mul(act, c.sub(left, right)))
        # Boundary: lz(last) in {0,1}. Without this the lookup grand product's
        # final value is unconstrained and the A'~A / T'~T permutation relation
        # is never enforced (a prover could set A'=T'=table and "look up"
        # arbitrary advice). Mirrors the permutation z boundary above; lz at
        # rotation 0 is already in the query plan, so no new openings.
        exprs.append(c.mul(c.llast, c.sub(c.mul(lz, lz), lz)))
        exprs.append(c.mul(c.l0, c.sub(pa, pt)))
        exprs.append(c.mul(act, c.mul(c.sub(pa, pt), c.sub(pa, pa_prev))))

    return exprs


class ScalarCtx:
    """Verifier-side: everything is an int mod R; vars come from proof evals."""

    def __init__(self, cfg, evals: dict, l0: int, llast: int, lblind: int, x: int):
        self._evals = evals
        self.l0 = l0
        self.llast = llast
        self.lblind = lblind
        self.x_col = x

    def var(self, key, rot):
        return self._evals[(key, rot)]

    def mul(self, a, b):
        return a * b % R

    def add(self, a, b):
        return (a + b) % R

    def sub(self, a, b):
        return (a - b) % R

    def scale(self, a, s):
        return a * s % R

    def add_const(self, a, s):
        return (a + s) % R

    def const(self, s):
        return s % R

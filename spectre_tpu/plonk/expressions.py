"""The constraint expressions, written once, evaluated two ways.

`all_expressions(cfg, ctx)` yields the ordered STREAM of constraint values
(a generator — see its docstring for why materializing the list OOMs); the
prover instantiates ctx over extended-domain evaluation ARRAYS (backend ops),
the verifier over SCALARS at the challenge point. One definition guarantees
both sides combine identical polynomials with identical y-powers — the classic
source of prover/verifier drift in hand-rolled PLONK implementations.
"""

from __future__ import annotations

from ..fields import bn254
from .constraint_system import CircuitConfig, PERM_CHUNK
from .domain import DELTA
from .keygen import ROT_LAST

R = bn254.R


def perm_column_keys(cfg: CircuitConfig):
    """Global permutation column index -> var key."""
    keys = []
    for j in range(cfg.num_advice):
        keys.append(("adv", j))
    for j in range(cfg.num_lookup_advice):
        keys.append(("ladv", j))
    for j in range(cfg.num_fixed):
        keys.append(("fix", j))
    for j in range(cfg.num_sha_word):
        keys.append(("shw", j))
    for j in range(cfg.num_instance):
        keys.append(("inst", j))
    return keys


def all_expressions(cfg: CircuitConfig, c, beta: int, gamma: int):
    """Ordered constraint list. ctx protocol:
    var(key, rot), mul/add/sub, scale(a, int), add_const(a, int), const(int),
    l0, llast, lblind, x_col (the identity polynomial X).

    A GENERATOR: in the prover each expression is a full extended-domain
    array (512 MB at k=22), so materializing the whole list before folding
    held ~50 of them live at once — the r5 oom-kill. Yielding interleaves
    evaluation with the y-fold, keeping one expression live at a time; the
    scalar/cell/codegen contexts are indifferent."""
    one = c.const(1)

    # --- gates: q_j * (a + a1*a2 - a3) ---
    for j in range(cfg.num_advice):
        a0 = c.var(("adv", j), 0)
        a1 = c.var(("adv", j), 1)
        a2 = c.var(("adv", j), 2)
        a3 = c.var(("adv", j), 3)
        q = c.var(("q", j), 0)
        yield (c.mul(q, c.sub(c.add(a0, c.mul(a1, a2)), a3)))

    # --- permutation argument ---
    col_keys = perm_column_keys(cfg)
    nch = cfg.num_perm_chunks
    act = c.sub(one, c.add(c.llast, c.lblind))
    yield (c.mul(c.l0, c.sub(c.var(("pz", 0), 0), one)))
    for ch in range(1, nch):
        yield (c.mul(c.l0, c.sub(c.var(("pz", ch), 0),
                                       c.var(("pz", ch - 1), ROT_LAST))))
    for ch in range(nch):
        cols = list(enumerate(col_keys))[ch * PERM_CHUNK:(ch + 1) * PERM_CHUNK]
        left = c.var(("pz", ch), 1)
        right = c.var(("pz", ch), 0)
        for gidx, key in cols:
            v = c.var(key, 0)
            sig = c.var(("sig", gidx), 0)
            left = c.mul(left, c.add_const(c.add(v, c.scale(sig, beta)), gamma))
            dj = pow(DELTA, gidx, R)
            right = c.mul(right, c.add_const(
                c.add(v, c.scale(c.x_col, beta * dj % R)), gamma))
        yield (c.mul(act, c.sub(left, right)))
    zl = c.var(("pz", nch - 1), 0)
    yield (c.mul(c.llast, c.sub(c.mul(zl, zl), zl)))

    # --- lookups (range table) ---
    for j in range(cfg.num_lookup_advice):
        a = c.var(("ladv", j), 0)
        pa = c.var(("pA", j), 0)
        pa_prev = c.var(("pA", j), -1)
        pt = c.var(("pT", j), 0)
        tab = c.var(("tab", j), 0)
        lz = c.var(("lz", j), 0)
        lz1 = c.var(("lz", j), 1)
        yield (c.mul(c.l0, c.sub(lz, one)))
        left = c.mul(lz1, c.mul(c.add_const(pa, beta), c.add_const(pt, gamma)))
        right = c.mul(lz, c.mul(c.add_const(a, beta), c.add_const(tab, gamma)))
        yield (c.mul(act, c.sub(left, right)))
        # Boundary: lz(last) in {0,1}. Without this the lookup grand product's
        # final value is unconstrained and the A'~A / T'~T permutation relation
        # is never enforced (a prover could set A'=T'=table and "look up"
        # arbitrary advice). Mirrors the permutation z boundary above; lz at
        # rotation 0 is already in the query plan, so no new openings.
        yield (c.mul(c.llast, c.sub(c.mul(lz, lz), lz)))
        yield (c.mul(c.l0, c.sub(pa, pt)))
        yield (c.mul(act, c.mul(c.sub(pa, pt), c.sub(pa, pa_prev))))

    if cfg.num_sha_slots:
        yield from sha_expressions(cfg, c)


def sha_expressions(cfg: CircuitConfig, c):
    """Wide SHA-256 region identities (see constraint_system.py header).

    All identities are homogeneous in the advice cells — the only constant
    term is K_t, entering as fixed_K * act — so all-zero (unused) slots
    satisfy every one. Degree stays <= 4: selector(1) x bitexpr(<=3).

    Column layout inside ("shb", j): w bits 0-31, a bits 32-63, e bits
    64-95, carries 96-103 (ce[3] | ca[3] | cs[2]). act is WORD column 9
    (permutation-enabled so the chip pins it to 1 on used slots). Selectors
    ("shq", s): 0 bit-boolean, 1 seed, 2 round, 3 sched, 4 inp, 5 out,
    6 act-chain. ("shk", 0): per-round K constants."""
    from .constraint_system import (SHA_A, SHA_ACT_WORD, SHA_CARRY, SHA_E,
                                    SHA_W)

    def w(i, rot=0):
        return c.var(("shb", SHA_W + i), rot)

    def a(i, rot=0):
        return c.var(("shb", SHA_A + i), rot)

    def e(i, rot=0):
        return c.var(("shb", SHA_E + i), rot)

    def carry(i, rot=0):
        return c.var(("shb", SHA_CARRY + i), rot)

    def q(s):
        return c.var(("shq", s), 0)

    def xor2(x, y):
        # x + y - 2xy
        return c.sub(c.add(x, y), c.scale(c.mul(x, y), 2))

    def xor3(x, y, z):
        # x+y+z - 2(xy+yz+zx) + 4xyz
        s3 = c.add(c.add(x, y), z)
        p2 = c.add(c.add(c.mul(x, y), c.mul(y, z)), c.mul(z, x))
        p3 = c.mul(c.mul(x, y), z)
        return c.add(c.sub(s3, c.scale(p2, 2)), c.scale(p3, 4))

    def recomb(bit_fn, rot=0):
        acc = None
        for i in range(32):
            t = c.scale(bit_fn(i, rot), 1 << i)
            acc = t if acc is None else c.add(acc, t)
        return acc

    def wsum(terms):
        acc = None
        for t in terms:
            acc = t if acc is None else c.add(acc, t)
        return acc

    # --- booleanness of every bit column (incl. carries) + act ---
    qb = q(0)
    from .constraint_system import SHA_BIT_COLS
    for j in range(SHA_BIT_COLS):
        b = c.var(("shb", j), 0)
        yield (c.mul(qb, c.sub(c.mul(b, b), b)))
    actv = c.var(("shw", SHA_ACT_WORD), 0)
    yield (c.mul(qb, c.sub(c.mul(actv, actv), actv)))

    # --- act chain: constant within the slot ---
    yield (c.mul(q(6), c.sub(actv, c.var(("shw", SHA_ACT_WORD), -1))))

    # --- seed rows bind the a/e ladders to h_in words (q_seed, row 3) ---
    qs = q(1)
    for j in range(4):
        yield (c.mul(qs, c.sub(recomb(a, -j), c.var(("shw", j), 0))))
        yield (c.mul(qs, c.sub(recomb(e, -j), c.var(("shw", 4 + j), 0))))

    # --- input rows bind w to the input word column (q_inp, t=0..15) ---
    yield (c.mul(q(4), c.sub(recomb(w), c.var(("shw", 8), 0))))

    # --- round identities (q_round, t=0..63) ---
    qr = q(2)
    # sigma1(e[t-1]) bits: rotr6 ^ rotr11 ^ rotr25
    sig1 = recomb(lambda i, _r: xor3(e((i + 6) % 32, -1), e((i + 11) % 32, -1),
                                     e((i + 25) % 32, -1)))
    # ch(e,f,g) = g + e*(f-g) bitwise, on e(t-1), e(t-2), e(t-3)
    ch = recomb(lambda i, _r: c.add(e(i, -3),
                                    c.mul(e(i, -1), c.sub(e(i, -2), e(i, -3)))))
    k_act = c.mul(c.var(("shk", 0), 0), actv)
    # identity A: e(t) + ce*2^32 = a(t-4) + e(t-4) + sig1 + ch + K*act + w(t)
    ce = wsum([c.scale(carry(i), 1 << (32 + i)) for i in range(3)])
    lhs_a = c.add(recomb(e), ce)
    rhs_a = wsum([recomb(a, -4), recomb(e, -4), sig1, ch, k_act, recomb(w)])
    yield (c.mul(qr, c.sub(lhs_a, rhs_a)))
    # sigma0(a[t-1]) and maj(a(t-1), a(t-2), a(t-3))
    sig0 = recomb(lambda i, _r: xor3(a((i + 2) % 32, -1), a((i + 13) % 32, -1),
                                     a((i + 22) % 32, -1)))

    def majbit(i, _r):
        b1, b2, b3 = a(i, -1), a(i, -2), a(i, -3)
        p12 = c.mul(b1, b2)
        return c.sub(c.add(c.add(p12, c.mul(b1, b3)), c.mul(b2, b3)),
                     c.scale(c.mul(p12, b3), 2))

    maj = recomb(majbit)
    # identity B: a(t) + ca*2^32 + a(t-4) = e(t) + ce*2^32 + sig0 + maj
    ca = wsum([c.scale(carry(3 + i), 1 << (32 + i)) for i in range(3)])
    lhs_b = wsum([recomb(a), ca, recomb(a, -4)])
    rhs_b = wsum([recomb(e), ce, sig0, maj])
    yield (c.mul(qr, c.sub(lhs_b, rhs_b)))

    # --- schedule (q_sched, t=16..63) ---
    # sigma0s: rotr7 ^ rotr18 ^ shr3 on w(t-15); shr3 bit i = w[i+3], 0 for
    # i > 28; sigma1s: rotr17 ^ rotr19 ^ shr10 on w(t-2)
    def s0bit(i, _r):
        x = w((i + 7) % 32, -15)
        y = w((i + 18) % 32, -15)
        if i <= 28:
            return xor3(x, y, w(i + 3, -15))
        return xor2(x, y)

    def s1bit(i, _r):
        x = w((i + 17) % 32, -2)
        y = w((i + 19) % 32, -2)
        if i <= 21:
            return xor3(x, y, w(i + 10, -2))
        return xor2(x, y)

    cs = wsum([c.scale(carry(6 + i), 1 << (32 + i)) for i in range(2)])
    lhs_s = c.add(recomb(w), cs)
    rhs_s = wsum([recomb(w, -16), recomb(s0bit), recomb(w, -7), recomb(s1bit)])
    yield (c.mul(q(3), c.sub(lhs_s, rhs_s)))

    # --- output row: h_out = h_in + final ladder (q_out, row 68) ---
    qo = q(5)
    from .constraint_system import SHA_OUT_ROW, SHA_SEED_ROW
    back = SHA_SEED_ROW - SHA_OUT_ROW                # -65
    for j in range(8):
        fin = recomb(a if j < 4 else e, -(1 + (j % 4)))
        lhs_o = c.add(c.var(("shw", j), 0), c.scale(carry(j), 1 << 32))
        rhs_o = c.add(c.var(("shw", j), back), fin)
        yield (c.mul(qo, c.sub(lhs_o, rhs_o)))



class _KeyRecorder:
    """Null context that records every `var` key the expression stream
    reads — the PREFETCH PLAN for batched coset-LDE (ISSUE 4): the device
    quotient extends all referenced columns through one batched fused
    kernel up front instead of a lazy per-column dispatch per first read.
    Every op returns an opaque token; the tree's structure depends only on
    cfg, never on values, so recording is exact and costs no arithmetic."""

    l0 = llast = lblind = x_col = None

    def __init__(self):
        self.keys: dict = {}          # insertion-ordered de-dup

    def var(self, key, rot):
        self.keys[key] = None
        return None

    def mul(self, a, b):
        return None

    add = sub = mul

    def scale(self, a, s):
        return None

    add_const = scale

    def const(self, s):
        return None


def referenced_keys(cfg: CircuitConfig) -> list:
    """Ordered, de-duplicated column keys `all_expressions` reads for this
    config (beta/gamma only enter as scale/add_const constants, so any
    values work). Used by quotient_device's batched prefetch."""
    rec = _KeyRecorder()
    for _ in all_expressions(cfg, rec, 1, 1):
        pass
    return list(rec.keys)


class ScalarCtx:
    """Verifier-side: everything is an int mod R; vars come from proof evals."""

    def __init__(self, cfg, evals: dict, l0: int, llast: int, lblind: int, x: int):
        self._evals = evals
        self.l0 = l0
        self.llast = llast
        self.lblind = lblind
        self.x_col = x

    def var(self, key, rot):
        return self._evals[(key, rot)]

    def mul(self, a, b):
        return a * b % R

    def add(self, a, b):
        return (a + b) % R

    def sub(self, a, b):
        return (a - b) % R

    def scale(self, a, s):
        return a * s % R

    def add_const(self, a, s):
        return (a + s) % R

    def const(self, s):
        return s % R

"""Evaluation domains for the vanishing argument.

Mirrors halo2's EvaluationDomain (SURVEY.md L0): a 2^k multiplicative subgroup
for witness columns plus a 4x coset-extended domain for quotient evaluation
(max constraint degree 4: gate q*(a + b*c - d), permutation chunks of 2,
lookup product update).
"""

from __future__ import annotations

import functools

import numpy as np

from ..fields import bn254
from . import backend as B

R = bn254.R

# max constraint degree supported -> extension factor
EXTENSION = 4

# coset generator for the extended domain (halo2 uses the field's
# multiplicative generator); zeta-shifted so (g*omega_ext^i)^n never hits the
# vanishing roots
COSET_GEN = bn254.FR_GENERATOR  # 7

# delta for permutation column cosets: generator of the 2^28-torsion complement,
# delta^j * <omega> are disjoint cosets for distinct j < number of columns
DELTA = pow(bn254.FR_GENERATOR, 1 << bn254.FR_S, R)


@functools.cache
def get_domain(k: int) -> "Domain":
    return Domain(k)


class Domain:
    def __init__(self, k: int):
        assert k + 2 <= bn254.FR_S
        self.k = k
        self.n = 1 << k
        self.omega = bn254.fr_root_of_unity(k)
        self.omega_inv = pow(self.omega, -1, R)
        self.k_ext = k + 2  # EXTENSION = 4
        self.n_ext = 1 << self.k_ext
        self.omega_ext = bn254.fr_root_of_unity(self.k_ext)
        assert pow(self.omega_ext, EXTENSION, R) == self.omega

    # -- polynomial transforms ([m,4] u64 standard-form limb arrays) --
    def lagrange_to_coeff(self, evals, bk=None):
        bk = bk or B.get_backend()
        return bk.intt(evals, self.omega)

    def coeff_to_lagrange(self, coeffs, bk=None):
        bk = bk or B.get_backend()
        return bk.ntt(coeffs, self.omega)

    # -- batched many-polynomial transforms (ISSUE 4): one backend call
    #    per column stack instead of a Python loop of per-column NTTs --
    def lagrange_to_coeff_many(self, evals_list, bk=None) -> list:
        bk = bk or B.get_backend()
        return bk.intt_many(evals_list, self.omega)

    def coeff_to_lagrange_many(self, coeffs_list, bk=None) -> list:
        bk = bk or B.get_backend()
        return bk.ntt_many(coeffs_list, self.omega)

    def coset_lde_many(self, coeffs_list, bk=None) -> list:
        """Batched coset-LDE of degree <n polys onto g*<omega_ext> (size
        4n) — the many-column form of `coeff_to_extended`, fused
        scale+NTT on the device backend."""
        bk = bk or B.get_backend()
        return bk.coset_lde_many(
            coeffs_list, self.omega_ext, COSET_GEN, self.n_ext,
            powers=self._coset_powers(COSET_GEN, bk))

    def _coset_powers(self, gen: int, bk) -> np.ndarray:
        """Per-domain cache of [g^0..g^(4n-1)]: recomputing the serial power
        chain per coeff_to_extended call was ~0.3s x ~90 calls per prove."""
        cache = self.__dict__.setdefault("_coset_powers_cache", {})
        hit = cache.get(gen)
        if hit is None:
            hit = cache[gen] = bk.powers(gen, self.n_ext)
        return hit

    def coeff_to_extended(self, coeffs, bk=None):
        """Evaluate degree <n poly on the coset g*<omega_ext> (size 4n)."""
        bk = bk or B.get_backend()
        padded = np.zeros((self.n_ext, 4), dtype=np.uint64)
        padded[:coeffs.shape[0]] = coeffs
        # scale by coset powers then NTT
        powers = self._coset_powers(COSET_GEN, bk)
        return bk.ntt(bk.mul(padded, powers), self.omega_ext)

    def extended_to_coeff(self, evals, bk=None):
        bk = bk or B.get_backend()
        coeffs = bk.intt(evals, self.omega_ext)
        powers = self._coset_powers(pow(COSET_GEN, -1, R), bk)
        return bk.mul(coeffs, powers)

    # -- closed-form helper evaluations --
    def vanishing_on_extended(self) -> np.ndarray:
        """(g*omega_ext^i)^n - 1 on the extended coset, [4n, 4]."""
        gn = pow(COSET_GEN, self.n, R)
        wn = pow(self.omega_ext, self.n, R)  # order-4 root
        vals = [(gn * pow(wn, i, R) - 1) % R for i in range(EXTENSION)]
        out = [vals[i % EXTENSION] for i in range(self.n_ext)]
        return B.to_arr(out)

    def vanishing_inv_on_extended(self) -> np.ndarray:
        hit = self.__dict__.get("_vanish_inv_cache")
        if hit is None:
            bk = B.get_backend()
            hit = self.__dict__["_vanish_inv_cache"] = \
                bk.inv(self.vanishing_on_extended())
        return hit

    def vanishing_inv_period_vals(self) -> tuple[int, ...]:
        """The EXTENSION distinct values of 1/((g·omega_ext^i)^n - 1): the
        extended-domain vanishing inverse tiles these with period EXTENSION.
        A hashable host-int tuple, so the quotient can hand it to the NTT as
        a static jit argument and fold the whole [4n, 16] inverse multiply
        into stage 0 of `coset_intt_std` (ops.ntt.coset_intt_std_vinv)."""
        hit = self.__dict__.get("_vanish_inv_vals")
        if hit is None:
            gn = pow(COSET_GEN, self.n, R)
            wn = pow(self.omega_ext, self.n, R)  # order-4 root
            hit = self.__dict__["_vanish_inv_vals"] = tuple(
                pow((gn * pow(wn, i, R) - 1) % R, -1, R)
                for i in range(EXTENSION))
        return hit

    def evaluate_vanishing(self, x: int) -> int:
        return (pow(x, self.n, R) - 1) % R

    def lagrange_evals(self, x: int, rows) -> dict[int, int]:
        """L_i(x) = omega^i (x^n - 1) / (n (x - omega^i)) for given rows.

        Handles x on the domain itself (the closed form has a removable pole):
        L_i(omega^j) = [i == j]."""
        zx = self.evaluate_vanishing(x)
        out = {}
        ninv = pow(self.n, -1, R)
        for i in rows:
            wi = pow(self.omega, i, R)
            if (x - wi) % R == 0:
                out[i] = 1
            elif zx == 0:
                out[i] = 0  # x is a different domain point
            else:
                out[i] = wi * zx % R * pow((x - wi) % R, -1, R) % R * ninv % R
        return out

    def l0_lagrange(self) -> np.ndarray:
        """L_0 evaluations on the base domain = [1, 0, 0, ...]."""
        out = np.zeros((self.n, 4), dtype=np.uint64)
        out[0, 0] = 1
        return out

    def rotate(self, evals: np.ndarray, by: int) -> np.ndarray:
        """evals of p(omega^by * X) from evals of p: index shift."""
        return np.roll(evals, -by, axis=0)

    def rotate_extended(self, evals: np.ndarray, by: int) -> np.ndarray:
        """On the 4n coset: rotation by omega (base) = 4 steps of omega_ext."""
        return np.roll(evals, -by * EXTENSION, axis=0)

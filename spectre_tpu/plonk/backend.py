"""Pluggable bulk-math backend for the prover: cpu (native C++) or tpu (JAX).

All prover-side polynomial data lives as numpy [n, 4] uint64 limb arrays in
standard form (little-endian 64-bit limbs). The backend supplies the heavy
ops: batched field arithmetic, NTTs, MSMs. The reference's `--backend`
selection point (BASELINE.json north star: `ProverBackend` trait) is this
class.
"""

from __future__ import annotations

import numpy as np

from ..fields import bn254
from ..native import host

R = bn254.R


def _host_fingerprint() -> str:
    """4-byte tag of this host's CPU feature flags + CPU MODEL + jaxlib
    version. AOT entries compiled on a machine with different features
    ABORT (SIGILL class) when loaded by XLA:CPU — observed as `Fatal Python
    error: Aborted` inside _cache_read when /tmp survived a host migration.
    Flags alone are not enough: XLA also tunes codegen by model
    (+prefer-no-scatter/gather), so same-flags/different-model hosts make
    every entry stale and force per-kernel recompiles (observed: commit
    phase 9min -> 2h). Keying the cache dir by all three makes foreign
    entries unreachable instead of fatal/slow."""
    import hashlib
    import platform
    feat = model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 "flags", aarch64 "Features"
                if not feat and line.startswith(("flags", "Features")):
                    feat = line.strip()
                # XLA tunes codegen by CPU MODEL too (+prefer-no-scatter/
                # gather etc.): hosts with identical flag sets but different
                # models produce mutually-stale AOT entries (observed: every
                # kernel recompiled after a migration, commit phase 9min->2h)
                if not model and line.startswith("model name"):
                    model = line.strip()
                if feat and model:
                    break
    except OSError:
        pass
    try:
        import jaxlib
        jl = getattr(jaxlib, "__version__", "")
    except Exception:
        jl = ""
    ident = f"{platform.machine()}|{model}|{feat}|jaxlib-{jl}"
    return hashlib.blake2s(ident.encode(), digest_size=4).hexdigest()


def setup_compile_cache():
    """Per-platform, per-host-feature persistent JAX compile cache (shared
    policy for bench, backends, tests, and entry points).

    `SPECTRE_COMPILE_CACHE_DIR` overrides the /tmp default so CI/bench runs
    can mount a durable cache across containers — the multichip SPMD
    programs are the expensive entries (8-way lowering on a 1-core host)
    and should compile once per image, not once per run. The host
    fingerprint still keys a subdirectory: foreign AOT entries must stay
    unreachable (see _host_fingerprint)."""
    import os

    import jax
    if not jax.config.jax_compilation_cache_dir:
        root = os.environ.get("SPECTRE_COMPILE_CACHE_DIR", "").strip()
        tag = f"jax_cache_{jax.default_backend()}_{_host_fingerprint()}"
        path = os.path.join(root, tag) if root else f"/tmp/{tag}"
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def to_arr(vals) -> np.ndarray:
    return host.ints_to_limbs([int(v) % R for v in vals])


def arr_to_ints(arr) -> list[int]:
    return host.limbs_to_ints(arr)


def zeros(n: int) -> np.ndarray:
    return np.zeros((n, 4), dtype=np.uint64)


def const_arr(s: int, n: int) -> np.ndarray:
    """[n, 4] array of the constant s — np.tile of one marshalled row
    (building the same list[int] n times through ints_to_limbs was seconds
    per call at extended-domain sizes)."""
    return np.tile(host.ints_to_limbs([int(s) % R]), (n, 1))


class CpuBackend:
    """Native C++ single-host backend (the measured baseline)."""

    name = "cpu"

    # -- batched Fr ops on [n,4] arrays --
    def mul(self, a, b):
        return host.fp_mul_batch(host.FR, a, b)

    def add(self, a, b):
        return host.fp_add_batch(host.FR, a, b)

    def sub(self, a, b):
        return host.fp_sub_batch(host.FR, a, b)

    def inv(self, a):
        return host.fp_inv_batch(host.FR, a)

    def scale(self, a, s: int):
        return host.fp_scale_batch(host.FR, a, s)

    def add_scalar(self, a, s: int):
        return host.fp_add_scalar_batch(host.FR, a, s % R)

    def axpy(self, a, s: int, b):
        """a*s + b elementwise, one pass (quotient y-combination)."""
        return host.fp_axpy_batch(host.FR, a, s % R, b)

    def powers(self, x: int, n: int):
        return host.fp_powers(host.FR, x, n)

    def prefix_prod(self, a):
        return host.fp_prefix_prod(host.FR, a)

    # -- NTT (in place on a copy; returns new array) --
    def ntt(self, coeffs, omega: int):
        data = np.array(coeffs, dtype=np.uint64)
        return host.fr_ntt(data, omega)

    def intt(self, evals, omega: int):
        n = evals.shape[0]
        data = np.array(evals, dtype=np.uint64)
        host.fr_ntt(data, pow(omega, -1, R))
        return host.fp_scale_batch(host.FR, data, pow(n, -1, R))

    # -- batched many-polynomial NTT (ISSUE 4 tentpole): ONE backend call
    #    per column stack. The native kernel is per-polynomial, so the CPU
    #    tier loops; the device backend overrides with a single compiled
    #    [B, n, 16] kernel. All lists hold same-length [n, 4] u64 arrays.
    def ntt_many(self, coeffs_list, omega: int) -> list:
        return [self.ntt(c, omega) for c in coeffs_list]

    def intt_many(self, evals_list, omega: int) -> list:
        return [self.intt(e, omega) for e in evals_list]

    def coset_lde_many(self, coeffs_list, omega: int, g: int, n_out: int,
                       powers=None) -> list:
        """Coset low-degree extension of several coefficient-form polys to
        the size-n_out coset g*<omega>: pad, scale by g^i, NTT. `powers`
        is an optional pre-computed [n_out, 4] table of g^i (the domain
        caches one per generator); the device backend ignores it and fuses
        the scale into stage 0 of its batched kernel."""
        if powers is None:
            powers = self.powers(g, n_out)
        out = []
        for cf in coeffs_list:
            padded = np.zeros((n_out, 4), dtype=np.uint64)
            padded[:cf.shape[0]] = cf
            out.append(self.ntt(self.mul(padded, powers), omega))
        return out

    # -- MSM: points [m, 8] u64 affine standard, scalars [m, 4] --
    def msm(self, points, scalars, base_key=None):
        # base_key names a fixed base for the device table cache; the
        # native Pippenger has no precompute path, so it is ignored here
        m = min(points.shape[0], scalars.shape[0])
        return host.g1_msm(points[:m], scalars[:m])

    def msm_many(self, points, scalars_list, base_key=None):
        """Commit several scalar vectors against the same base points."""
        return [self.msm(points, sc, base_key=base_key)
                for sc in scalars_list]


class TpuBackend(CpuBackend):
    """JAX backend: MSM/NTT ride the device kernels; small ops stay native.

    Inherits the native implementations and overrides the ops where the device
    wins. Conversions to 16-bit limb tensors happen at the boundary.

    The commitment base (SRS tau powers) is encoded + shipped to device ONCE
    per distinct base array and cached — per-column commits were previously
    re-transferring the same 2^k-point base every call."""

    name = "tpu"
    # quotient phase as one device-resident XLA program (quotient_device.py)
    device_quotient = True

    def __init__(self):
        import jax  # noqa: F401  fail fast if jax unusable
        from ..ops import limbs as L16  # noqa: F401
        # per-shape compiles dominate small-circuit wall-clock; persist them
        setup_compile_cache()
        self._base_cache: dict = {}   # (id, n) -> device [n,3,16] points
        # (id, n, expand, plan) -> mesh-placed (expanded, padded) base:
        # the sharded MSM path previously re-ran endo expansion and
        # re-device_put the full base onto the mesh EVERY call
        self._mesh_base_cache: dict = {}
        import os
        self._shard_min_logn = int(os.environ.get(
            "SPECTRE_SHARD_MSM_MIN_LOGN", str(self.SHARD_MSM_MIN_LOGN)))
        self._shard_ntt_min_logn = int(os.environ.get(
            "SPECTRE_SHARD_NTT_MIN_LOGN", str(self.SHARD_NTT_MIN_LOGN)))

    def _encode_points(self, points):
        import jax
        import jax.numpy as jnp

        from ..ops import field_ops as F, limbs as L16

        m = points.shape[0]
        ctxq = F.fq_ctx()
        x16 = L16.u64limbs_to_u16limbs(points[:, :4])
        y16 = L16.u64limbs_to_u16limbs(points[:, 4:])
        if "toq" not in _mont_jits:
            _mont_jits["toq"] = jax.jit(lambda v: F.to_mont(ctxq, v))
        to_mont = _mont_jits["toq"]
        xm, ym = to_mont(jnp.asarray(x16)), to_mont(jnp.asarray(y16))
        inf_mask = jnp.asarray(
            (np.asarray(x16).sum(1) == 0) & (np.asarray(y16).sum(1) == 0))[:, None]
        one = jnp.broadcast_to(jnp.asarray(ctxq.one_mont), (m, F.NLIMBS))
        # infinity must be the RCB identity (0:1:0) — (0:0:0) is absorbing
        ym = jnp.where(inf_mask, one, ym)
        z = jnp.where(inf_mask, 0, one)
        return jnp.stack([xm, ym, z], axis=1)

    def _base_points(self, points, m: int):
        """Device-resident encoded points, cached per (array, prefix-len).

        The cache holds a STRONG reference to the host array: the id() key
        then cannot be reused by a different array while the entry lives
        (and SRS bases are never mutated in place), so a hit always refers
        to the same base."""
        key = (id(points), m)
        hit = self._base_cache.get(key)
        if hit is not None and hit[0] is points:
            return hit[1]
        pts = self._encode_points(points[:m])
        # one base per backend instance is the norm (the SRS); keep the
        # cache tiny so entries (and their host refs) cannot accumulate
        if len(self._base_cache) > 8:
            self._base_cache.clear()
        self._base_cache[key] = (points, pts)
        return pts

    # single MSMs at least this large route through the mesh-sharded
    # kernel when >1 device is attached (SURVEY §2c(a): TP axis; override
    # via SPECTRE_SHARD_MSM_MIN_LOGN)
    SHARD_MSM_MIN_LOGN = 20

    def msm(self, points, scalars, base_key=None):
        import jax
        import jax.numpy as jnp

        from ..ops import ec, limbs as L16, msm as MSM

        m = min(points.shape[0], scalars.shape[0])
        if self._use_mesh(m, self._shard_min_logn):
            return self._msm_sharded(points, scalars, m, base_key=base_key)
        pts = self._base_points(points, m)
        sc16 = jnp.asarray(L16.u64limbs_to_u16limbs(scalars[:m]))
        res = MSM.msm(pts, sc16, base_key=base_key)
        out = ec.decode_points(res[None])[0]
        return out

    def _mesh_base(self, points, m: int, plan, expand: bool):
        """Mesh-resident commitment base: encoded, optionally endomorphism-
        expanded, row-padded to the plan's data axis and placed per
        plan.point_spec — ONCE per (array, prefix, plan, expansion).

        Strong host ref pins the id() key, same contract as _base_points.
        Before this cache the sharded path re-ran _expand_endo and
        re-device_put the full base onto the mesh for every MSM of a
        prove."""
        key = (id(points), m, expand, plan.key)
        hit = self._mesh_base_cache.get(key)
        if hit is not None and hit[0] is points:
            return hit[1]
        import jax.numpy as jnp

        from ..ops import ec, msm as MSM

        pts = self._base_points(points, m)
        if expand:
            pts = MSM._expand_endo(pts)
        m2 = pts.shape[0]
        mp = plan.pad_rows(m2)
        if mp > m2:
            # RCB identity (0:1:0) padding — zero scalars ride these rows
            pts = jnp.concatenate(
                [pts, ec.inf_point((mp - m2,)).astype(pts.dtype)], axis=0)
        placed = plan.place(pts, plan.point_spec)
        if len(self._mesh_base_cache) > 4:
            self._mesh_base_cache.clear()
        self._mesh_base_cache[key] = (points, placed)
        return placed

    def _msm_sharded(self, points, scalars, m: int, base_key=None):
        """One MSM sharded over the ShardingPlan's ("data", "win") mesh.
        Points are padded with infinity (zero scalars) so the data axis
        divides evenly.

        GLV modes ride the mesh too: the host scalar-prep stage (Babai
        decomposition) runs per call, but the endomorphism-expanded base
        stays mesh-resident via _mesh_base, so each data shard holds
        aligned (point, half-scalar, sign) rows with no per-call base
        transfer. `fixed` mode runs SHARDED (ISSUE 13): the per-SRS window
        table is built by the mesh and stays resident with T[w] row slices
        co-resident with their point shards; it degrades to glv+signed
        only when even the per-device table slice busts the
        SPECTRE_MSM_TABLE_MB budget (health counter msm_fixed_degraded)."""
        import importlib

        import jax.numpy as jnp

        from ..ops import ec, limbs as L16, msm as MSM
        from ..parallel.plan import current_plan
        # the package re-exports the sharded_msm FUNCTION under the module's
        # name, so attribute-style module import resolves to the function
        SM = importlib.import_module("spectre_tpu.parallel.sharded_msm")

        mode = MSM.msm_mode()
        if MSM.msm_impl() == "pallas":
            # the shard_map mesh program has no pallas lowering — fall
            # back to XLA visibly (health counter + provenance event)
            MSM._record_pallas_degrade(mode, m, None,
                                       "backend._msm_sharded")
        plan = current_plan()
        sc16 = L16.u64limbs_to_u16limbs(scalars[:m])
        nbits, signed = 254, False
        if mode != "vanilla":
            from ..ops import glv
            a1, a2, n1, n2 = glv.decompose_limbs16(sc16)
            sc16 = np.concatenate([a1, a2], axis=0)
            neg_np = np.concatenate([n1, n2], axis=0)
            nbits = glv.glv_bits()
            signed = mode in ("glv+signed", "fixed")
            m2 = 2 * m
        else:
            neg_np = np.zeros(m, dtype=bool)
            m2 = m
        mp = plan.pad_rows(m2)
        sc = np.zeros((mp, sc16.shape[1]), dtype=np.uint32)
        sc[:m2] = sc16
        ng = np.zeros(mp, dtype=bool)
        ng[:m2] = neg_np

        if mode == "fixed":
            c = MSM.default_window_fixed(mp)
            nwin = (nbits + c) // c
            if not SM._degrade_fixed_mesh(mp, c, nbits, plan):
                base = self._mesh_base(points, m, plan, expand=True)
                tab = SM.sharded_fixed_table(base, c, nwin, plan,
                                             base_key=base_key)
                sd = plan.place(jnp.asarray(sc), plan.scalar_spec)
                ngd = plan.place(jnp.asarray(ng), plan.sign_spec)
                res = SM.sharded_msm_fixed(tab, sd, ngd, c, plan, nbits)
                return ec.decode_points(np.asarray(res)[None])[0]
            # per-device table slice over budget: glv+signed fallback below

        base = self._mesh_base(points, m, plan, expand=(mode != "vanilla"))
        if mode != "vanilla" and not signed:
            # unsigned glv folds the sign into the points — scalar-
            # dependent, so applied on device against the resident base
            base = MSM._apply_sign(
                base, plan.place(jnp.asarray(ng), plan.sign_spec))
            ng = np.zeros_like(ng)
        if mode == "vanilla":
            # mesh-tuned static window; SPECTRE_MSM_WINDOW still wins so a
            # sweep (bench.py --sweep-window) exercises the sharded path too
            c = MSM.window_override() or (13 if mp >= (1 << 18) else 10)
        else:
            c = MSM.default_window(mp, signed=signed)
        sd = plan.place(jnp.asarray(sc), plan.scalar_spec)
        ngd = plan.place(jnp.asarray(ng), plan.sign_spec) if signed else None
        res = SM.sharded_msm(base, sd, c, plan.mesh, nbits=nbits,
                             signed=signed, neg=ngd, plan=plan)
        return ec.decode_points(np.asarray(res)[None])[0]

    def msm_many(self, points, scalars_list, base_key=None):
        """Commit several scalar vectors against one cached device base.

        With >1 local device the batch axis is sharded over a 1-D mesh
        (SURVEY §2c(b): inter-proof/column DP); single-chip it loops the
        sequential kernel (measured faster than vmap there). GLV modes
        thread the scalar-prep stage through the DP path: half-scalars and
        sign masks are stacked per batch row against ONE replicated
        endomorphism-expanded base (`fixed` uses the glv+signed kernels
        here — replicating a per-window table across the mesh would
        multiply its memory by the device count)."""
        import jax
        import jax.numpy as jnp

        from ..ops import ec, limbs as L16, msm as MSM

        if not scalars_list:
            return []
        from ..parallel.plan import current_plan
        plan = current_plan()
        batch = len(scalars_list)
        if plan.n_devices > 1 and batch > 1:
            from ..parallel.batch_msm import batch_msm_dp
            bmesh = plan.batch_mesh
            # uniform batch length: pad shorter scalar vectors with zeros
            # (zero scalars select the empty bucket — identity contribution)
            mmax = min(points.shape[0],
                       max(s.shape[0] for s in scalars_list))
            pts = self._base_points(points, mmax)
            mode = MSM.msm_mode()
            if mode == "vanilla":
                sc = np.zeros((batch, mmax, 16), dtype=np.uint32)
                for i, s in enumerate(scalars_list):
                    mi = min(mmax, s.shape[0])
                    sc[i, :mi] = np.asarray(L16.u64limbs_to_u16limbs(s[:mi]))
                res = batch_msm_dp(pts, sc, mesh=bmesh)    # [B, 3, 16]
                return list(ec.decode_points(np.asarray(res)))
            from ..ops import glv
            signed = mode in ("glv+signed", "fixed")
            pts2 = MSM._expand_endo(pts)
            sc = np.zeros((batch, 2 * mmax, glv.HALF_LIMBS), dtype=np.uint32)
            ng = np.zeros((batch, 2 * mmax), dtype=bool)
            for i, s in enumerate(scalars_list):
                mi = min(mmax, s.shape[0])
                sc64 = np.zeros((mmax, 4), dtype=np.uint64)
                sc64[:mi] = s[:mi]
                a1, a2, n1, n2 = glv.decompose_limbs16(
                    L16.u64limbs_to_u16limbs(sc64))
                sc[i] = np.concatenate([a1, a2], axis=0)
                ng[i] = np.concatenate([n1, n2], axis=0)
            res = batch_msm_dp(pts2, sc, mesh=bmesh, neg_batch=ng,
                               nbits=glv.glv_bits(), signed=signed)
            return list(ec.decode_points(np.asarray(res)))
        return [self.msm(points, s, base_key=base_key)
                for s in scalars_list]

    # NTTs at least this large ride the four-step mesh-sharded kernel
    # (all-to-all transpose over ICI, parallel/sharded_ntt.py) when >1
    # device is attached — the same gate pattern as SHARD_MSM_MIN_LOGN;
    # override via SPECTRE_SHARD_NTT_MIN_LOGN (the mesh-prove dryrun/test
    # forces it low so a full tiny prove exercises the path end-to-end)
    SHARD_NTT_MIN_LOGN = 18

    def _use_mesh(self, n: int, min_logn: int) -> bool:
        # plan-aware gate: SPECTRE_MESH_SHAPE=1x1 means "prove on a
        # 1-device mesh" -> the plain single-device kernels (which IS the
        # degenerate mesh result; the identity tests lean on this)
        from ..parallel.plan import current_plan
        return current_plan().n_devices > 1 and n >= (1 << min_logn)

    def ntt(self, coeffs, omega: int):
        import jax.numpy as jnp

        from ..ops import field_ops as F, limbs as L16, ntt as NTT

        if self._use_mesh(coeffs.shape[0], self._shard_ntt_min_logn):
            return self._ntt_sharded(coeffs, omega)
        ctx = F.fr_ctx()
        mont = _u64_std_to_mont16(coeffs)
        out = NTT.ntt(jnp.asarray(mont), omega)
        return _mont16_to_u64_std(np.asarray(out))

    def intt(self, evals, omega: int):
        import jax.numpy as jnp

        from ..ops import field_ops as F, limbs as L16, ntt as NTT

        if self._use_mesh(evals.shape[0], self._shard_ntt_min_logn):
            n = evals.shape[0]
            res = self._ntt_sharded(evals, pow(omega, -1, R), mont_out=True)
            from ..ops import field_ops as Fo
            ctx = Fo.fr_ctx()
            ninv = ctx.encode([pow(n, -1, R)])[0]
            out = Fo.mont_mul(ctx, res, jnp.asarray(ninv)[None])
            return _mont16_to_u64_std(np.asarray(out))
        mont = _u64_std_to_mont16(evals)
        out = NTT.intt(jnp.asarray(mont), omega)
        return _mont16_to_u64_std(np.asarray(out))

    def _ntt_sharded(self, arr_u64, omega: int, mont_out: bool = False):
        """One NTT over the ("data",) mesh axis; exact same result as the
        single-device kernel (pinned by tests/test_parallel.py)."""
        import jax.numpy as jnp

        from ..parallel.plan import current_plan
        from ..parallel.sharded_ntt import sharded_ntt

        plan = current_plan()
        mont = _u64_std_to_mont16(arr_u64)
        res = sharded_ntt(jnp.asarray(mont), omega, plan.mesh, plan=plan)
        if mont_out:
            return res
        return _mont16_to_u64_std(np.asarray(res))

    # batch sizes are padded up to a power of two (zero columns transform
    # to zero columns and are sliced off) so the jitted [B, n, 16] kernels
    # compile for at most log2(chunk) distinct batch shapes per n instead
    # of one executable per ragged chunk length — XLA:CPU compile churn is
    # this box's known instability (see TestMsmModeCommitments note)
    @staticmethod
    def _pad_batch(stack: np.ndarray) -> np.ndarray:
        b = stack.shape[0]
        bp = 1 << max(b - 1, 0).bit_length()
        if bp == b:
            return stack
        pad = np.zeros((bp,) + stack.shape[1:], dtype=stack.dtype)
        pad[:b] = stack
        return pad

    def _ntt_many_device(self, arrs, omega: int, inverse: bool) -> list:
        """[B, n, 16] batched kernel path (single device, any NTT mode)."""
        import jax.numpy as jnp

        from ..ops import ntt as NTT

        b, n = len(arrs), arrs[0].shape[0]
        stack = self._pad_batch(np.stack(arrs))
        mont = _u64_std_to_mont16(stack.reshape(-1, 4)).reshape(
            stack.shape[0], n, 16)
        fn = NTT.intt_many if inverse else NTT.ntt_many
        out = fn(jnp.asarray(mont), omega)
        std = _mont16_to_u64_std(np.asarray(out).reshape(-1, 16))
        return list(std.reshape(stack.shape[0], n, 4)[:b])

    def ntt_many(self, coeffs_list, omega: int) -> list:
        if not coeffs_list:
            return []
        n = coeffs_list[0].shape[0]
        if len(coeffs_list) == 1 or self._use_mesh(
                n, self._shard_ntt_min_logn):
            return [self.ntt(c, omega) for c in coeffs_list]
        return self._ntt_many_device(coeffs_list, omega, inverse=False)

    def intt_many(self, evals_list, omega: int) -> list:
        if not evals_list:
            return []
        n = evals_list[0].shape[0]
        if len(evals_list) == 1 or self._use_mesh(
                n, self._shard_ntt_min_logn):
            return [self.intt(e, omega) for e in evals_list]
        return self._ntt_many_device(evals_list, omega, inverse=True)

    def coset_lde_many(self, coeffs_list, omega: int, g: int, n_out: int,
                       powers=None) -> list:
        """Batched FUSED coset-LDE: pad to n_out, then one compiled kernel
        per stack — the std→mont conversion and the g^i coset scale both
        fold into stage 0 of the batched NTT (ops/ntt.py:coset_lde_std),
        so the whole extension is a single device program with no separate
        scale pass and no intermediate Montgomery array."""
        import jax.numpy as jnp

        from ..ops import limbs as L16, ntt as NTT

        if not coeffs_list:
            return []
        if self._use_mesh(n_out, self._shard_ntt_min_logn):
            # mesh path: per-poly sharded NTT (scale via the host table)
            return super().coset_lde_many(coeffs_list, omega, g, n_out,
                                          powers=powers)
        b = len(coeffs_list)
        stack = np.zeros((b, n_out, 4), dtype=np.uint64)
        for i, cf in enumerate(coeffs_list):
            stack[i, :cf.shape[0]] = cf
        stack = self._pad_batch(stack)
        std16 = L16.u64limbs_to_u16limbs(stack.reshape(-1, 4)).reshape(
            stack.shape[0], n_out, 16)
        out = NTT.coset_lde_std(jnp.asarray(std16), omega, g)
        std = _mont16_to_u64_std(np.asarray(out).reshape(-1, 16))
        return list(std.reshape(stack.shape[0], n_out, 4)[:b])


# stable jitted boundary converters: a fresh `jax.jit(lambda ...)` per
# call (the previous shape) re-traces every time — jit caches by function
# identity — which taxed every NTT/MSM boundary crossing in the prove
_mont_jits: dict = {}

# runner registry (trace-cache hygiene contract, parallel/plan.py):
# analysis/trace_lint cross-checks these (builder, cache) pairs against
# the AST (TC-UNCACHED-RUNNER).
TRACE_RUNNER_CACHES = (
    ("_mont_fns", "_mont_jits"),
    ("_encode_points", "_mont_jits"),
)


def _mont_fns():
    # key-presence check, NOT dict truthiness — _encode_points shares this
    # dict for its "toq" jit, and its insertion must not mask ours
    if "to" not in _mont_jits:
        import jax

        from ..ops import field_ops as F

        ctx = F.fr_ctx()
        _mont_jits["to"] = jax.jit(lambda v: F.to_mont(ctx, v))
        _mont_jits["from"] = jax.jit(lambda v: F.from_mont(ctx, v))
    return _mont_jits


def _u64_std_to_mont16(arr):
    """[n,4] u64 standard -> [n,16] u32 Montgomery, via device to_mont."""
    import jax.numpy as jnp

    from ..ops import limbs as L16

    std16 = L16.u64limbs_to_u16limbs(arr)
    return _mont_fns()["to"](jnp.asarray(std16))


def _mont16_to_u64_std(arr):
    import jax.numpy as jnp

    from ..ops import limbs as L16

    std16 = _mont_fns()["from"](jnp.asarray(arr))
    return L16.u16limbs_to_u64limbs(np.asarray(std16))


_backends = {}


def get_backend(name: str = "cpu"):
    if name not in _backends:
        _backends[name] = CpuBackend() if name == "cpu" else TpuBackend()
    return _backends[name]


# ---------------------------------------------------------------------------
# graceful degradation: device prove -> CPU retry (PR 3, resilient service)
# ---------------------------------------------------------------------------

def is_device_oom(exc: BaseException) -> bool:
    """Device out-of-memory classification: XLA surfaces RESOURCE_EXHAUSTED
    through XlaRuntimeError (type name matched — jaxlib moves the class
    between releases); injected faults carry an explicit kind."""
    from ..utils.faults import InjectedFault
    if isinstance(exc, InjectedFault):
        return exc.kind == "oom"
    msg = str(exc)
    return type(exc).__name__ == "XlaRuntimeError" and (
        "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
        or "out of memory" in msg)


def is_compile_failure(exc: BaseException) -> bool:
    """Mosaic/XLA compilation failure classification (compile churn on new
    shapes is an expected hazard of accelerator-resident proving)."""
    from ..utils.faults import InjectedFault
    if isinstance(exc, InjectedFault):
        return exc.kind == "compile"
    msg = str(exc)
    if "Mosaic" in msg and ("failed" in msg or "error" in msg.lower()):
        return True
    return type(exc).__name__ == "XlaRuntimeError" and (
        "Compilation failure" in msg or "INTERNAL: Mosaic" in msg)


def prove_with_fallback(prove_fn, bk, health=None):
    """Run `prove_fn(bk)`; on device OOM or compile failure retry ONCE on
    the CPU backend instead of failing the request (ISSUE 3 tentpole (5)).

    `prove_fn` must be a closure over everything but the backend and
    byte-deterministic given the same backend + transcript randomness —
    the CPU retry produces exactly the proof a clean CPU prove would.
    Fault-injection site `backend.prove` fires here so the degradation
    path is deterministically testable without a real device OOM.
    Non-degradable exceptions (witness rejection, bugs) propagate
    untouched, as does anything raised while already on the CPU backend.
    """
    from ..utils import faults
    if health is None:
        from ..utils.health import HEALTH as health
    try:
        faults.check("backend.prove")
        return prove_fn(bk)
    except Exception as exc:
        if not (is_device_oom(exc) or is_compile_failure(exc)):
            raise
        cpu = get_backend("cpu")
        if bk is cpu or getattr(bk, "name", None) == "cpu":
            raise                     # already on the fallback tier
        kind = "oom" if is_device_oom(exc) else "compile"
        health.incr(f"prove_cpu_fallbacks_{kind}")
        # stamp the degradation onto the job's span tree (getTrace
        # `args`) AND the job's provenance manifest: a proof produced on
        # the fallback tier must say so everywhere it is inspected
        from ..observability import manifest, tracing
        tracing.annotate(cpu_fallback=kind)
        manifest.record_event("cpu_fallback", fallback_kind=kind,
                              from_backend=getattr(bk, "name", "device"))
        import sys
        print(f"[prover] device prove failed ({kind}: {exc}); retrying "
              f"once on the CPU backend", file=sys.stderr, flush=True)
        return prove_fn(cpu)

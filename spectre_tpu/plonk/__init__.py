"""The proving system: PLONKish arithmetization with KZG/SHPLONK on BN254.

Protocol shape follows halo2 (PSE) — vertical flex gate, chunked permutation
grand products, permutation-based range lookups, vanishing argument over a 4n
coset-extended domain, BDFG20 (SHPLONK) multiopen — re-implemented from the
protocol math, with all bulk polynomial work routed through a pluggable
backend (native C++ on host, JAX limb kernels on TPU).

Reference parity map (SURVEY.md §1 L0): `halo2_proofs` keygen/prover/verifier,
`snark-verifier` SHPLONK — here plonk/{keygen,prover,verifier,kzg}.py.
"""

from .backend import get_backend, CpuBackend  # noqa: F401
from .domain import Domain  # noqa: F401
from .srs import SRS  # noqa: F401
from .transcript import Blake2bTranscript  # noqa: F401

"""The prover: witness commitments -> grand products -> quotient -> multiopen.

Reference parity: halo2's create_proof (`gen_snark_shplonk` path,
`util/circuit.rs:163-180`, SURVEY.md §3.2 step 3 — "this is where the TPU
backend plugs in"). All bulk math goes through the backend (MSM commitments,
NTTs, pointwise quotient evaluation); transcript and control flow stay on host.
"""

from __future__ import annotations

import secrets

import numpy as np

from ..fields import bn254
from ..native import host
from ..utils.profiling import phase
from . import backend as B, kzg
from .constraint_system import (Assignment, NUM_H_CHUNKS, PERM_CHUNK,
                                permute_lookup)
from .domain import DELTA, Domain
from .expressions import all_expressions, perm_column_keys
from .keygen import ProvingKey, ROT_LAST
from .srs import SRS
from .transcript import Blake2bTranscript

R = bn254.R


class _ArrayCtx:
    """Prover-side expression context over extended-domain arrays."""

    def __init__(self, cfg, dom: Domain, bk, ext):
        self._cfg = cfg
        self._dom = dom
        self._bk = bk
        self._ext = ext        # key -> extended array (mapping or callable cache)
        # X on the extended coset: g * omega_ext^i (powers domain-cached)
        from .domain import COSET_GEN
        xs = dom._coset_powers(dom.omega_ext, bk)
        self.x_col = bk.scale(xs, COSET_GEN)
        self.l0 = None      # filled by prover
        self.llast = None
        self.lblind = None

    def var(self, key, rot):
        # _ext is a mapping (device path) or a callable cache (_quotient_host)
        arr = self._ext(key) if callable(self._ext) else self._ext[key]
        if rot == 0:
            return arr
        if rot == ROT_LAST:
            return self._dom.rotate_extended(arr, self._cfg.last_row)
        return self._dom.rotate_extended(arr, rot)

    def mul(self, a, b):
        return self._bk.mul(a, b)

    def add(self, a, b):
        return self._bk.add(a, b)

    def sub(self, a, b):
        return self._bk.sub(a, b)

    def scale(self, a, s):
        return self._bk.scale(a, s % R)

    def add_const(self, a, s):
        return self._bk.add_scalar(a, s)

    def const(self, s):
        return B.const_arr(s, self._dom.n_ext)


def lookup_grand_product(bk, n: int, u: int, a_v, pa_v, pt_v, t_v,
                         beta: int, gamma: int) -> list:
    """Running product z for one lookup column; telescopes to 1 at row u for
    honest witnesses (asserted — the l_last boundary constraint enforces it
    in-proof)."""
    num = bk.mul(bk.add_scalar(B.to_arr(a_v), beta),
                 bk.add_scalar(B.to_arr(t_v), gamma))
    den = bk.mul(bk.add_scalar(B.to_arr(pa_v), beta),
                 bk.add_scalar(B.to_arr(pt_v), gamma))
    ratio = B.arr_to_ints(bk.mul(num, bk.inv(den)))
    for i in range(u, n):
        ratio[i] = 1
    prefix = B.arr_to_ints(bk.prefix_prod(B.to_arr(ratio)))
    z = [1] + prefix[:-1]
    assert prefix[u - 1] == 1, "lookup product != 1"
    return z


def prove(pk: ProvingKey, srs: SRS, assignment: Assignment,
          bk=None, transcript=None, blinding_rng=None) -> bytes:
    """blinding_rng: optional zero-arg callable returning a uniform element
    of [0, R) for the ZK blinding rows/tails. Default is `secrets` (fresh
    system randomness). Passing a seeded generator makes the proof a pure
    function of (pk, witness, transcript) — the backend byte-equality tests
    (VERDICT r3 item 4) prove the SAME bytes come out of CpuBackend and
    TpuBackend; never seed it in production."""
    bk = bk or B.get_backend()
    rand = blinding_rng or (lambda: secrets.randbelow(R))
    cfg = pk.vk.config
    dom = pk.vk.domain
    n, u = cfg.n, cfg.usable_rows
    tr = transcript or Blake2bTranscript()

    # --- bind statement: vk digest + instances ---
    tr._absorb_bytes(pk.vk.digest())
    for col in assignment.instances:
        for v in col:
            tr.common_scalar(int(v) % R)

    # --- 1. blind + commit advice and lookup-advice columns ---
    def blind(vals):
        out = [int(v) % R for v in vals]
        for i in range(u, n):
            out[i] = rand()
        return out

    adv_vals = [blind(v) for v in assignment.advice]
    ladv_vals = [blind(v) for v in assignment.lookup_advice]
    shb_vals = [blind(assignment.sha_bit[j].tolist())
                for j in range(cfg.num_sha_bit)]
    shw_vals = [blind(assignment.sha_word[j].tolist())
                for j in range(cfg.num_sha_word)]
    inst_vals = [assignment.instance_column(j) for j in range(cfg.num_instance)]

    polys: dict = {}      # key -> coefficient form
    values: dict = {}     # key -> int list (lagrange values)

    def commit_col(key, vals, arr=None):
        values[key] = vals
        if arr is None:
            arr = B.to_arr(vals)
        coeffs = dom.lagrange_to_coeff(arr, bk)
        polys[key] = coeffs
        pt = kzg.commit(srs, coeffs, bk)
        tr.write_point(pt)

    COMMIT_CHUNK = 16   # bounds resident coefficient arrays (k=20: 512MB)

    def commit_cols_batched(item_list):
        """Pipelined + batched commits (SURVEY §2c axes (b)+(c)): host limb
        marshalling of the NEXT chunk overlaps the backend NTT+MSM of the
        current one on worker threads (ctypes/JAX release the GIL), each
        chunk's iNTTs run as ONE batched `lagrange_to_coeff_many` call
        (ISSUE 4: a single [B, n, 16] device kernel instead of B per-column
        dispatches), and each chunk's MSMs go through one `commit_many`
        call (device base cached; batch axis sharded on a mesh). Transcript
        order is unchanged — points are absorbed strictly in sequence."""
        from concurrent.futures import ThreadPoolExecutor

        if not item_list:
            return
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = {i: ex.submit(B.to_arr, item_list[i][1])
                    for i in range(min(COMMIT_CHUNK, len(item_list)))}
            for base in range(0, len(item_list), COMMIT_CHUNK):
                chunk = item_list[base:base + COMMIT_CHUNK]
                for j in range(base + COMMIT_CHUNK,
                               min(base + 2 * COMMIT_CHUNK, len(item_list))):
                    if j not in futs:
                        futs[j] = ex.submit(B.to_arr, item_list[j][1])
                arrs = [futs.pop(base + off).result()
                        for off in range(len(chunk))]
                coeffs = dom.lagrange_to_coeff_many(arrs, bk)
                for (key, vals), c in zip(chunk, coeffs):
                    values[key] = vals
                    polys[key] = c
                for pt in kzg.commit_many(srs, coeffs, bk):
                    tr.write_point(pt)

    with phase("prove/commit_advice"):
        items = ([(("adv", j), v) for j, v in enumerate(adv_vals)]
                 + [(("ladv", j), v) for j, v in enumerate(ladv_vals)]
                 + [(("shb", j), v) for j, v in enumerate(shb_vals)]
                 + [(("shw", j), v) for j, v in enumerate(shw_vals)])
        commit_cols_batched(items)

    # --- 2. lookup permuted columns ---
    with phase("prove/lookup_permute"):
        lk_items = []
        for j in range(cfg.num_lookup_advice):
            pa, pt_col = permute_lookup(cfg, ladv_vals[j], pk.table_values[j])
            lk_items.append(((("pA", j)), pa))
            lk_items.append(((("pT", j)), pt_col))
        commit_cols_batched(lk_items)

    beta = tr.challenge()
    gamma = tr.challenge()

    # --- 3. permutation grand products (chunk-linked) ---
    with phase("prove/grand_products"):
        col_keys = perm_column_keys(cfg)
        omega_pows = bk.powers(dom.omega, n)

        def col_values(key):
            kind, j = key
            if kind == "adv":
                return adv_vals[j]
            if kind == "ladv":
                return ladv_vals[j]
            if kind == "fix":
                return pk.fixed_values[j]
            if kind == "shw":
                return shw_vals[j]
            if kind == "inst":
                return inst_vals[j]
            raise KeyError(key)

        prev_end = 1
        nch = cfg.num_perm_chunks
        gp_items = []    # pz + lz columns, committed in one batched call
        for ch in range(nch):
            cols = list(enumerate(col_keys))[ch * PERM_CHUNK:
                                             (ch + 1) * PERM_CHUNK]
            num = B.to_arr([1] * n)
            den = B.to_arr([1] * n)
            for gidx, key in cols:
                v_arr = B.to_arr(col_values(key))
                dj = pow(DELTA, gidx, R)
                id_term = bk.add_scalar(
                    bk.add(v_arr, bk.scale(omega_pows, beta * dj % R)), gamma)
                sig_term = bk.add_scalar(
                    bk.add(v_arr, bk.scale(B.to_arr(pk.sigma_values[gidx]),
                                           beta)),
                    gamma)
                num = bk.mul(num, id_term)
                den = bk.mul(den, sig_term)
            ratio = bk.mul(num, bk.inv(den))
            # deactivate blinding rows
            ratio_ints = B.arr_to_ints(ratio)
            for i in range(u, n):
                ratio_ints[i] = 1
            prefix = bk.prefix_prod(B.to_arr(ratio_ints))
            prefix_ints = B.arr_to_ints(prefix)
            z = [prev_end] + [prev_end * p % R for p in prefix_ints[:-1]]
            prev_end = prev_end * prefix_ints[u - 1] % R if u >= 1 \
                else prev_end
            # Blind the tail: every constraint touching z is inactive on rows
            # u+1..n-1 (act excludes them, llast hits row u, ROT_LAST reads
            # row u), but z is opened at x and omega*x — deterministic tail
            # rows would leak witness information halo2 hides. Randomize them.
            for i in range(u + 1, n):
                z[i] = rand()
            gp_items.append((("pz", ch), z))
        assert prev_end == 1, \
            "permutation product != 1 (copy constraints unsatisfiable)"

        # --- 4. lookup grand products ---
        for j in range(cfg.num_lookup_advice):
            z = lookup_grand_product(
                bk, n, u, values[("ladv", j)], values[("pA", j)],
                values[("pT", j)], pk.table_values[j], beta, gamma)
            for i in range(u + 1, n):        # blind tail rows (see pz above)
                z[i] = rand()
            gp_items.append((("lz", j), z))
        # no challenge between pz and lz commits: one batched call
        commit_cols_batched(gp_items)

    y = tr.challenge()

    # instance polys (public-input binding in the identity) — both quotient
    # paths and nothing else create them, so hoist before the dispatch
    # (one batched iNTT over the instance-column stack)
    with phase("prove/instance_polys"):
        for j, c in enumerate(dom.lagrange_to_coeff_many(
                [B.to_arr(v) for v in inst_vals], bk)):
            polys[("inst", j)] = c

    def poly_for(key):
        kind, j = key
        if key in polys:
            return polys[key]
        if kind == "q":
            return pk.selector_polys[j]
        if kind == "fix":
            return pk.fixed_polys[j]
        if kind == "sig":
            return pk.sigma_polys[j]
        if kind == "tab":
            return pk.table_polys[j]
        if kind == "shq":
            return pk.sha_selector_polys[j]
        if kind == "shk":
            return pk.sha_k_poly
        raise KeyError(key)

    if getattr(bk, "device_quotient", False):
        # device-resident path: the whole identity as one jitted XLA
        # program (quotient_device.py)
        from .quotient_device import compute_quotient
        with phase("prove/quotient"):
            h_coeffs = compute_quotient(cfg, dom, poly_for, beta, gamma, y)
    else:
        h_coeffs = _quotient_host(cfg, dom, bk, pk, polys, beta, gamma, y)
    # deg h <= 3n-4, so the top chunk must vanish. A nonzero tail means the
    # division by the vanishing polynomial was inexact: either the witness
    # violates a constraint, or an expression exceeded the degree-4 budget.
    # Refusing here beats silently emitting an unverifiable proof.
    assert not np.any(h_coeffs[NUM_H_CHUNKS * n:]), \
        "quotient not a polynomial: witness violates constraints (or degree budget exceeded)"
    h_chunks = []
    for i in range(NUM_H_CHUNKS):
        chunk = h_coeffs[i * n:(i + 1) * n]
        if chunk.shape[0] < n:
            chunk = np.vstack([chunk, np.zeros((n - chunk.shape[0], 4), np.uint64)])
        polys[("h", i)] = chunk
        h_chunks.append(chunk)
    with phase("prove/commit_h"):
        for pt in kzg.commit_many(srs, h_chunks, bk):
            tr.write_point(pt)

    x = tr.challenge()

    # --- 6. evaluations per the query plan ---
    plan = pk.vk.query_plan()

    with phase("prove/evals"):
        evals = {}
        for key, rot in plan:
            pt = pk.vk.rotation_point(x, rot)
            ev = host.fp_horner(host.FR, poly_for(key), pt)
            evals[(key, rot)] = ev
            tr.write_scalar(ev)

    # --- 7. SHPLONK multiopen ---
    by_key: dict = {}
    for key, rot in plan:
        by_key.setdefault(key, []).append(rot)
    with phase("prove/multiopen"):
        entries = []
        for key, rots in by_key.items():
            pts = tuple(pk.vk.rotation_point(x, r) for r in rots)
            evs = tuple(evals[(key, r)] for r in rots)
            entries.append(kzg.OpenEntry(poly_for(key), None, pts, evs))
        kzg.shplonk_open(srs, dom, entries, tr, bk)

    return tr.finalize()


class _BudgetedExtLRU:
    """Byte-budgeted LRU over derived extended-coset arrays (OOM guard).

    Every entry is pure DERIVED data — an NTT of a coeff-form polynomial the
    prover still holds, or a cyclic roll of another entry — so eviction
    costs recompute time, never correctness. The guard exists because the
    unbounded caches held one 512 MB extended array per distinct (column)
    and (column, rotation): the committee-update aggregation circuit
    (63.7M cells, k_agg=22, r5) accumulated ~250 of them and the prover was
    oom-killed at 130 GB. Budget: SPECTRE_QUOTIENT_CACHE_MB, default 30% of
    MemTotal minus the pk-resident fixed-column cache budget (floor 1 GB) —
    small circuits stay fully cached, huge ones evict cold families instead
    of dying."""

    # evicted-then-refetched keys past this count = the working set does not
    # fit the budget and the quotient phase is recomputing 4n NTTs/rolls in
    # a loop; warn once so the operator knows to raise the budget
    THRASH_WARN_THRESHOLD = 32

    def __init__(self, budget_bytes: int):
        import collections
        self.budget = budget_bytes
        self._d = collections.OrderedDict()
        self._bytes = 0
        self._warned_passthrough = False
        self._evicted: dict = {}          # key -> times evicted
        self.recompute_count = 0          # gets of previously-evicted keys
        self._warned_thrash = False

    def get(self, key):
        hit = self._d.get(key)
        if hit is not None:
            self._d.move_to_end(key)
        elif key in self._evicted:
            self.recompute_count += 1
            if (self.recompute_count >= self.THRASH_WARN_THRESHOLD
                    and not self._warned_thrash):
                self._warned_thrash = True
                import sys
                worst = sorted(self._evicted.items(), key=lambda kv: -kv[1])
                fams = ", ".join(f"{k[0] if isinstance(k, tuple) else k}"
                                 f" x{c}" for k, c in worst[:4])
                # stamp the thrash into the job's provenance manifest —
                # "raise SPECTRE_QUOTIENT_CACHE_MB" advice must survive
                # past this process's stderr
                from ..observability.manifest import record_event
                record_event("quotient_cache_thrash",
                             recomputes=self.recompute_count,
                             budget_mb=self.budget >> 20)
                print(f"[quotient] extended-array cache thrashing: "
                      f"{self.recompute_count} recomputes after eviction "
                      f"(budget {self.budget >> 20} MB; hottest evicted "
                      f"families: {fams}) — raise SPECTRE_QUOTIENT_CACHE_MB "
                      f"to avoid repeated 4n NTT/roll recomputes",
                      file=sys.stderr, flush=True)
        return hit

    def put(self, key, arr):
        if arr.nbytes > self.budget:
            # larger than the whole budget: pass through uncached — every
            # read of this key recomputes a 4n NTT/roll, so make the
            # misconfiguration visible once rather than silently burning
            # the quotient phase
            if not self._warned_passthrough:
                self._warned_passthrough = True
                import sys
                print(f"[quotient] extended array ({arr.nbytes >> 20} MB) "
                      f"exceeds SPECTRE_QUOTIENT_CACHE_MB budget "
                      f"({self.budget >> 20} MB): caching disabled, every "
                      f"read recomputes", file=sys.stderr, flush=True)
            return arr
        while self._bytes + arr.nbytes > self.budget and self._d:
            old_key, old = self._d.popitem(last=False)
            self._evicted[old_key] = self._evicted.get(old_key, 0) + 1
            self._bytes -= old.nbytes
        self._d[key] = arr
        self._bytes += arr.nbytes
        return arr


def _meminfo_total_bytes():
    try:
        with open("/proc/meminfo") as f:
            return int(f.readline().split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None


def _quotient_budget_bytes(pk_ext_budget: int) -> int:
    """LRU budget: explicit env, else 30% of RAM MINUS the coexisting
    pk-resident fixed-column cache budget — the two caches draw from one
    memory pool, so bounding them independently would not bound the
    prover (the r5 oom-kill lesson)."""
    import os as _os
    mb = _os.environ.get("SPECTRE_QUOTIENT_CACHE_MB")
    if mb is not None:
        return int(mb) << 20
    total = _meminfo_total_bytes()
    if total is None:
        return 8 << 30
    return max(1 << 30, int(total * 0.30) - pk_ext_budget)


def _quotient_host(cfg, dom, bk, pk, polys, beta, gamma, y):
    """The original host-orchestrated quotient: per-op backend calls over
    the extended coset (CPU path)."""
    n, u = cfg.n, cfg.usable_rows
    # Circuit-FIXED columns (selectors, fixed, sigmas, tables) have the same
    # extended form every prove; their ~n-per-circuit 4n-NTTs were about half
    # of quotient wall-clock (BASELINE.md r4: quotient 41-49% of prove).
    # Cache them on the pk object (in-memory only, never persisted): a
    # prover service re-proving against one pk pays the NTTs once.
    _FIXED_KINDS = ("q", "fix", "sig", "tab", "shq", "shk")
    pk_ext = pk.__dict__.setdefault("_ext_fixed_cache", {})
    # cap resident bytes per pk (idle-circuit caches stack in a service —
    # see ProvingKey.release_ext_cache); over budget we compute transiently.
    # Default: min(16 GB, 15% of RAM) — shares one pool with the LRU below
    import os as _os
    _mb = _os.environ.get("SPECTRE_EXT_CACHE_MB")
    if _mb is not None:
        ext_budget = int(_mb) << 20
    else:
        _total = _meminfo_total_bytes()
        ext_budget = (16 << 30 if _total is None
                      else min(16 << 30, int(_total * 0.15)))
    lru = _BudgetedExtLRU(_quotient_budget_bytes(ext_budget))

    def _within_budget(arr):
        return (sum(a.nbytes for a in pk_ext.values()) + arr.nbytes
                <= ext_budget)

    def ext(key):
        hit = lru.get(key)
        if hit is not None:
            return hit
        if key in polys:
            return lru.put(key, dom.coeff_to_extended(polys[key], bk))
        if key[0] in _FIXED_KINDS:
            hit = pk_ext.get(key)
            if hit is None:
                if key[0] == "q":
                    coeffs = pk.selector_polys[key[1]]
                elif key[0] == "fix":
                    coeffs = pk.fixed_polys[key[1]]
                elif key[0] == "sig":
                    coeffs = pk.sigma_polys[key[1]]
                elif key[0] == "tab":
                    coeffs = pk.table_polys[key[1]]
                elif key[0] == "shq":
                    coeffs = pk.sha_selector_polys[key[1]]
                else:
                    coeffs = pk.sha_k_poly
                hit = dom.coeff_to_extended(coeffs, bk)
                if _within_budget(hit):
                    pk_ext[key] = hit
                else:
                    hit = lru.put(key, hit)   # per-prove lifetime only
            return hit
        # ("inst", j) is pre-populated in polys by prove()
        raise KeyError(key)

    class LazyCtx(_ArrayCtx):
        def var(self, key, rot):
            if rot == 0:
                return ext(key)
            # a (key, rot) pair is read by several expressions; rolling a
            # 4n-row array per read was measurable quotient time — but the
            # rolled copies share the byte budget with the base arrays.
            # Check the rolled entry FIRST: under eviction pressure the base
            # may be gone while the roll survives, and recomputing the base
            # NTT just to discard it would waste ~a 4n NTT per read
            rkey = (key, "rot", rot)
            hit = lru.get(rkey)
            if hit is None:
                r = cfg.last_row if rot == ROT_LAST else rot
                hit = lru.put(rkey, dom.rotate_extended(ext(key), r))
            return hit

    ctx = LazyCtx(cfg, dom, bk, ext)
    # l0 / l_last / l_blind on the extended coset — circuit-fixed, cached
    # alongside the fixed-column extended forms
    if ("l0",) not in pk_ext:
        l0_vals = [0] * n
        l0_vals[0] = 1
        llast_vals = [0] * n
        llast_vals[cfg.last_row] = 1
        lblind_vals = [0] * n
        for i in range(u + 1, n):
            lblind_vals[i] = 1
        for name, vals in (("l0", l0_vals), ("llast", llast_vals),
                           ("lblind", lblind_vals)):
            pk_ext[(name,)] = dom.coeff_to_extended(
                dom.lagrange_to_coeff(B.to_arr(vals), bk), bk)
    ctx.l0 = pk_ext[("l0",)]
    ctx.llast = pk_ext[("llast",)]
    ctx.lblind = pk_ext[("lblind",)]

    with phase("prove/quotient"):
        exprs = all_expressions(cfg, ctx, beta, gamma)
        acc = None
        for e in exprs:
            acc = e if acc is None else bk.axpy(acc, y, e)
        h_evals = bk.mul(acc, dom.vanishing_inv_on_extended())
        return dom.extended_to_coeff(h_evals, bk)

"""Device-resident quotient evaluation.

The CPU prover evaluates `all_expressions` through the native batch backend
(~6k sequential host calls over 32MB numpy arrays at k=18 — the dominant
prove phase, 1067s of the 512-committee prove). TPU-first shape: every
column is coset-NTT'd to the extended domain ON DEVICE and stays resident as
a [4n, 16] Montgomery tensor; the expression tree, the y-fold, the vanishing
division, and the inverse coset NTT all run as device ops with no host
round-trips between them.

ISSUE 4: the per-column `to_ext` dispatch is now a BATCHED FUSED prefetch —
the expression tree's column keys are enumerated up front
(`expressions.referenced_keys`), stacked in fixed-size chunks, and extended
through ONE compiled kernel per chunk (`ops/ntt.py:coset_lde_std`: the
std→mont conversion and the coset pre-scale fold into stage 0 of the
batched NTT, honoring SPECTRE_NTT_MODE). The inverse path folds the 1/n
iNTT scale, the g^{-i} coset unscale and the mont→std boundary into one
table multiply (`coset_intt_std`).

ISSUE 19: the pipeline is ENGINE-parameterized. The single-device engine
below is the original path verbatim; when more than one device is up (and
the domain clears the size gate) `compute_quotient` dispatches the same
pipeline through `parallel/sharded_quotient.py`, which runs the LDE
prefetch, every expression primitive, the rotations and the fused inverse
as shard_map programs over the ShardingPlan mesh. Ineligible shapes or a
mesh-path failure fall back here VISIBLY — the `quotient_sharded_degraded`
ServiceHealth counter plus a provenance event — never silently.

Design note (learned the hard way): tracing the WHOLE tree into one jitted
XLA program blows up LLVM codegen on the CPU backend (`Cannot allocate
memory` from the execution engine at ~6k fused scan-heavy ops). The ops are
therefore dispatched EAGERLY through a small set of jitted primitives
(mont mul/add/sub, batched NTT) — data residency, not mega-fusion, is where
the device win lives (each op is HBM-bandwidth-bound either way), and
compile cost stays bounded per primitive shape.

Parity: the device path produces EXACTLY the host path's u64 coefficient
arrays, compared in-situ during real proves
(tests/test_plonk.py::TestDeviceQuotient, gate+lookup and wide-SHA shapes;
mesh-vs-host in tests/test_quotient_sharded.py).
"""

from __future__ import annotations

import os

import numpy as np

from ..fields import bn254
from ..ops.msm import _TableLRU, _record_event
from .constraint_system import CircuitConfig
from .domain import COSET_GEN, Domain
from .expressions import all_expressions, referenced_keys
from .keygen import ROT_LAST

R = bn254.R

_jit_helpers: dict = {}
_static_cache: dict = {}

# runner registry (trace-cache hygiene contract, parallel/plan.py):
# analysis/trace_lint cross-checks these (builder, cache) pairs against
# the AST (TC-UNCACHED-RUNNER).
TRACE_RUNNER_CACHES = (("_helpers", "_jit_helpers"),)


def _fused_vinv() -> bool:
    """SPECTRE_QUOTIENT_FUSED_VINV=0 keeps the explicit [4n, 16] vanishing-
    inverse mont_mul pass (the pre-fusion path, byte-identical — kept as the
    oracle for tests/test_ntt_kernels.py). Default: fold it into stage 0 of
    the inverse coset NTT, one fewer full-width elementwise pass per proof."""
    return os.environ.get("SPECTRE_QUOTIENT_FUSED_VINV", "1") != "0"


def _helpers():
    """Jitted primitive ops, created once (stable trace cache)."""
    if not _jit_helpers:
        import jax

        from ..ops import field_ops as F

        fctx = F.fr_ctx()
        _jit_helpers["to_mont"] = jax.jit(lambda v: F.to_mont(fctx, v))
        _jit_helpers["from_mont"] = jax.jit(lambda v: F.from_mont(fctx, v))
        _jit_helpers["mul"] = jax.jit(lambda a, b: F.mont_mul(fctx, a, b))
        _jit_helpers["add"] = jax.jit(lambda a, b: F.add(fctx, a, b))
        _jit_helpers["sub"] = jax.jit(lambda a, b: F.sub(fctx, a, b))
        _jit_helpers["mul_s"] = jax.jit(
            lambda a, s: F.mont_mul(fctx, a, s[None, :]))
        _jit_helpers["add_s"] = jax.jit(
            lambda a, s: F.add(fctx, a, s[None, :].repeat(a.shape[0], 0)))
        _jit_helpers["fold"] = jax.jit(
            lambda acc, y, e: F.add(fctx, F.mont_mul(fctx, acc, y[None, :]), e))
    return _jit_helpers


def _scalar_budget_bytes() -> int:
    mb = os.environ.get("SPECTRE_QUOTIENT_SCALAR_MB")
    return (int(mb) if mb is not None else 4) << 20


# Montgomery [16] device scalars keyed by field value — gate coefficients,
# challenges, eval points. Previously a per-prove dict with a clear-at-4096
# panic valve that threw the WHOLE working set away mid-prove; now the same
# byte-budgeted LRU as the MSM/NTT tables (ISSUE 19): eviction is oldest-
# first, counted, and a recompute after eviction is visible in stats()
# (pinned by tests/test_quotient_sharded.py). ~64 bytes/entry — the default
# 4 MB holds every scalar any real circuit has produced; the knob exists so
# the bound is explicit, not so it's ever hit.
_scalar_cache = _TableLRU(_scalar_budget_bytes(),
                          label="quotient mont scalar",
                          budget_var="SPECTRE_QUOTIENT_SCALAR_MB",
                          on_event=_record_event)


def scalar_lru_stats() -> dict:
    """Quotient scalar-cache stats for GET /metrics."""
    return _scalar_cache.stats()


# columns per batched coset-LDE prefetch chunk: fixed so the [B, 4n, 16]
# kernel compiles once per domain, capped by transient bytes (chunk * 4n *
# 16 u32 lanes) so huge extended domains don't spike device memory
def _ext_chunk(m: int) -> int:
    cap = max(1, (256 << 20) // (m * 16 * 4))
    return min(8, 1 << (cap.bit_length() - 1))


class _DeviceCtx:
    """all_expressions context over device-resident [m, 16] Montgomery
    tensors, dispatching through the jitted primitives."""

    def __init__(self, cols, m: int, last_row: int, mont_scalar):
        self._h = _helpers()
        self._cols = cols
        self._m = m
        self._last_row = last_row
        self._mont = mont_scalar      # int -> [16] mont device scalar
        self._rot_cache: dict = {}
        self.l0 = cols[("_l0",)]
        self.llast = cols[("_llast",)]
        self.lblind = cols[("_lblind",)]
        self.x_col = cols[("_xcol",)]

    def var(self, key, rot):
        import jax.numpy as jnp

        arr = self._cols[key]
        if rot == 0:
            return arr
        hit = self._rot_cache.get((key, rot))
        if hit is None:
            r = self._last_row if rot == ROT_LAST else rot
            # extended-coset index shift: omega == omega_ext^EXTENSION
            hit = jnp.roll(arr, -4 * r, axis=0)
            self._rot_cache[(key, rot)] = hit
        return hit

    def mul(self, a, b):
        return self._h["mul"](a, b)

    def add(self, a, b):
        return self._h["add"](a, b)

    def sub(self, a, b):
        return self._h["sub"](a, b)

    def scale(self, a, s):
        return self._h["mul_s"](a, self._mont(s))

    def add_const(self, a, s):
        return self._h["add_s"](a, self._mont(s))

    def const(self, s):
        import jax.numpy as jnp

        return jnp.broadcast_to(self._mont(s), (self._m, 16))

    def fold(self, acc, y, e):
        return self._h["fold"](acc, self._mont(y), e)


class _LocalEngine:
    """Single-device quotient engine: the original pipeline, expressed
    through the same seam the mesh engine plugs into."""

    name = "local"

    def __init__(self, dom: Domain):
        self.dom = dom
        self.m = dom.n_ext

    def chunk(self, base: int) -> int:
        return base

    def lde(self, std16: np.ndarray):
        """Batched fused coset-LDE of a [B, m, 16] standard-form stack: ONE
        compiled kernel (std→mont + g^i scale fused into stage 0;
        SPECTRE_NTT_MODE selects radix2/fourstep)."""
        import jax.numpy as jnp

        from ..ops import ntt as NTT

        out = NTT.coset_lde_std(jnp.asarray(std16), self.dom.omega_ext,
                                COSET_GEN)
        return [out[i] for i in range(std16.shape[0])]

    def device_col(self, arr16):
        return arr16

    def ctx(self, cols, last_row: int, mont_scalar) -> _DeviceCtx:
        return _DeviceCtx(cols, self.m, last_row, mont_scalar)

    def inverse_std(self, acc, vinv_vals) -> np.ndarray:
        from ..ops import ntt as NTT

        if vinv_vals is None:
            std = NTT.coset_intt_std(acc, self.dom.omega_ext, COSET_GEN)
        else:
            std = NTT.coset_intt_std_vinv(acc, self.dom.omega_ext,
                                          COSET_GEN, vinv_vals)
        return np.asarray(std)


def _shard_min_logn() -> int:
    """Extended domains below 2^this stay single-device without noise: at
    small m the per-op collective + dispatch overhead swamps the shard win,
    and a dev-box 8-virtual-device mesh would otherwise silently route every
    ordinary test prove through the mesh runners on one physical core. The
    default mirrors SHARD_NTT_MIN_LOGN (the quotient is NTT-dominated):
    high enough that only an explicit opt-in (bench-quotient-multichip, the
    sharded-quotient tests) engages the mesh on a virtual-device box."""
    return int(os.environ.get("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", "18"))


def _degrade(reason: str, **detail):
    from ..utils.health import HEALTH
    HEALTH.incr("quotient_sharded_degraded")
    _record_event("quotient_sharded_degraded", reason=reason, **detail)


def _mesh_engine(dom: Domain):
    """The sharded engine when the mesh prove path applies, else None.

    Silent single-device: kill switch off, one device, or below the size
    gate. VISIBLE degrade (`quotient_sharded_degraded` counter + provenance
    event): a real mesh and a big enough domain, but a shape the Bailey
    row partition can't cover."""
    if os.environ.get("SPECTRE_QUOTIENT_SHARDED", "1") == "0":
        return None
    import jax
    if jax.device_count() <= 1:
        return None
    logm = dom.n_ext.bit_length() - 1
    if logm < _shard_min_logn():
        return None
    from ..parallel import sharded_quotient as SQ
    from ..parallel.plan import current_plan

    plan = current_plan()
    if plan.n_devices <= 1:
        return None
    if not SQ.eligible(plan, dom.n_ext):
        _degrade("ineligible_shape", n_ext=dom.n_ext,
                 n_devices=plan.n_devices)
        return None
    return SQ.MeshQuotientEngine(plan, dom)


def compute_quotient(cfg: CircuitConfig, dom: Domain, fetch_coeffs,
                     beta: int, gamma: int, y: int) -> np.ndarray:
    """Device quotient: returns h coefficients as [4n, 4] u64 standard form
    (drop-in for the host path's extended_to_coeff output).

    fetch_coeffs(key) -> [<=n, 4] u64 coefficient-form poly for every column
    key the expression tree reads."""
    engine = _mesh_engine(dom)
    if engine is not None:
        try:
            return _quotient_impl(cfg, dom, fetch_coeffs, beta, gamma, y,
                                  engine)
        except Exception as e:  # mesh-path failure: fall back, visibly
            _degrade("mesh_exception", error=f"{type(e).__name__}: {e}",
                     n_ext=dom.n_ext)
    return _quotient_impl(cfg, dom, fetch_coeffs, beta, gamma, y,
                          _LocalEngine(dom))


def _quotient_impl(cfg: CircuitConfig, dom: Domain, fetch_coeffs,
                   beta: int, gamma: int, y: int, engine) -> np.ndarray:
    import jax.numpy as jnp

    from ..ops import limbs as L16
    from . import backend as B

    h = _helpers()
    to_mont16 = h["to_mont"]
    mont_of = lambda ints: to_mont16(
        jnp.asarray(L16.u64limbs_to_u16limbs(B.to_arr(ints))))

    def mont_scalar(s):
        v = int(s) % R
        hit = _scalar_cache.get(v, None)
        if hit is None:
            hit = _scalar_cache.put(v, None, mont_of([v])[0])
        return hit

    # per-(cfg, domain) static device inputs: synthetic rows, x column —
    # built once, reused every proof (the coset scale / unscale tables now
    # live inside ops/ntt.py's budgeted table LRU as part of the fused
    # kernels, and the vanishing inverse rides the fused inverse path as a
    # stage-0 table; the explicit [4n, 16] tensor materializes lazily only
    # when SPECTRE_QUOTIENT_FUSED_VINV=0)
    n, m = dom.n, dom.n_ext
    ck = (cfg, dom.k)
    st = _static_cache.get(ck)
    if st is None:
        def row_of(idx_vals):
            vals = [0] * n
            for i in idx_vals:
                vals[i] = 1
            return dom.lagrange_to_coeff(B.to_arr(vals))

        st = {
            "xcol": mont_of([COSET_GEN * pow(dom.omega_ext, i, R) % R
                             for i in range(m)]),
            "l0": row_of([0]),
            "llast": row_of([cfg.last_row]),
            "lblind": row_of(range(cfg.usable_rows + 1, n)),
        }
        if len(_static_cache) > 4:
            _static_cache.clear()
        _static_cache[ck] = st

    def ext_of_many(arrs_u64):
        """Pack a coefficient-array list into ONE standard-form [B, m, 16]
        stack and extend it through the engine's batched LDE."""
        b = len(arrs_u64)
        stack = np.zeros((b, m, 4), dtype=np.uint64)
        for i, cf in enumerate(arrs_u64):
            stack[i, :cf.shape[0]] = cf
        std16 = L16.u64limbs_to_u16limbs(stack.reshape(-1, 4)).reshape(
            b, m, 16)
        return engine.lde(std16)

    def ext_of_coeffs(arr_u64):
        return ext_of_many([arr_u64])[0]

    # synthetic rows extend as one batched call; real columns prefetch in
    # fixed-size chunks enumerated from the expression tree
    l0_e, llast_e, lblind_e = ext_of_many(
        [st["l0"], st["llast"], st["lblind"]])
    cols: dict = {
        ("_l0",): l0_e,
        ("_llast",): llast_e,
        ("_lblind",): lblind_e,
        ("_xcol",): engine.device_col(st["xcol"]),
    }
    plan = [k for k in referenced_keys(cfg) if k not in cols]
    chunk_sz = engine.chunk(_ext_chunk(m))
    for base in range(0, len(plan), chunk_sz):
        chunk = plan[base:base + chunk_sz]
        # pad the tail chunk with the first key so the kernel sees one
        # batch shape per domain (duplicates are free — same NTT, sliced)
        padded = chunk + [chunk[0]] * (chunk_sz - len(chunk))
        outs = ext_of_many([fetch_coeffs(k) for k in padded])
        for k_, o in zip(chunk, outs):
            cols[k_] = o

    class LazyCols(dict):
        # safety net: any key the recorder missed still materializes
        def __missing__(self, key):
            arr = ext_of_coeffs(fetch_coeffs(key))
            self[key] = arr
            return arr

    ctx = engine.ctx(LazyCols(cols), cfg.last_row, mont_scalar)
    acc = None
    for e in all_expressions(cfg, ctx, beta, gamma):
        acc = e if acc is None else ctx.fold(acc, y, e)
    if acc is None:
        raise ValueError("config yields no constraint expressions — "
                         "nothing to fold into a quotient")
    # h = acc / Z_H on the coset, then the fused inverse path: ONE kernel —
    # the 1/Z_H stage-0 pre-scale, the iNTT, and the combined
    # g^{-i}·n^{-1}·(mont→std) output table all ride a single transform
    if _fused_vinv():
        std = engine.inverse_std(acc, dom.vanishing_inv_period_vals())
    else:
        vinv = st.get("vinv")
        if vinv is None:
            vinv = st["vinv"] = to_mont16(jnp.asarray(
                L16.u64limbs_to_u16limbs(dom.vanishing_inv_on_extended())))
        hacc = ctx.mul(acc, engine.device_col(vinv))
        std = engine.inverse_std(hacc, None)
    return L16.u16limbs_to_u64limbs(np.asarray(std))

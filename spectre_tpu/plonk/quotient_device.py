"""Device-resident quotient evaluation.

The CPU prover evaluates `all_expressions` through the native batch backend
(~6k sequential host calls over 32MB numpy arrays at k=18 — the dominant
prove phase, 1067s of the 512-committee prove). TPU-first shape: every
column is coset-NTT'd to the extended domain ON DEVICE and stays resident as
a [4n, 16] Montgomery tensor; the expression tree, the y-fold, the vanishing
division, and the inverse coset NTT all run as device ops with no host
round-trips between them.

Design note (learned the hard way): tracing the WHOLE tree into one jitted
XLA program blows up LLVM codegen on the CPU backend (`Cannot allocate
memory` from the execution engine at ~6k fused scan-heavy ops). The ops are
therefore dispatched EAGERLY through a small set of jitted primitives
(mont mul/add/sub, NTT) — data residency, not mega-fusion, is where the
device win lives (each op is HBM-bandwidth-bound either way), and compile
cost stays bounded per primitive shape.

Parity: the device path produces EXACTLY the host path's u64 coefficient
arrays, compared in-situ during real proves
(tests/test_plonk.py::TestDeviceQuotient, gate+lookup and wide-SHA shapes).
"""

from __future__ import annotations

import numpy as np

from ..fields import bn254
from .constraint_system import CircuitConfig
from .domain import COSET_GEN, Domain
from .expressions import all_expressions
from .keygen import ROT_LAST

R = bn254.R

_jit_helpers: dict = {}
_static_cache: dict = {}


def _helpers():
    """Jitted primitive ops, created once (stable trace cache)."""
    if not _jit_helpers:
        import functools

        import jax

        from ..ops import field_ops as F, ntt as NTT

        fctx = F.fr_ctx()
        _jit_helpers["to_mont"] = jax.jit(lambda v: F.to_mont(fctx, v))
        _jit_helpers["from_mont"] = jax.jit(lambda v: F.from_mont(fctx, v))
        _jit_helpers["mul"] = jax.jit(lambda a, b: F.mont_mul(fctx, a, b))
        _jit_helpers["add"] = jax.jit(lambda a, b: F.add(fctx, a, b))
        _jit_helpers["sub"] = jax.jit(lambda a, b: F.sub(fctx, a, b))
        _jit_helpers["mul_s"] = jax.jit(
            lambda a, s: F.mont_mul(fctx, a, s[None, :]))
        _jit_helpers["add_s"] = jax.jit(
            lambda a, s: F.add(fctx, a, s[None, :].repeat(a.shape[0], 0)))
        _jit_helpers["fold"] = jax.jit(
            lambda acc, y, e: F.add(fctx, F.mont_mul(fctx, acc, y[None, :]), e))

        @functools.partial(jax.jit, static_argnums=(2,))
        def to_ext(coeffs16, coset_pow, omega_ext):
            return NTT.ntt(F.mont_mul(fctx, coeffs16, coset_pow), omega_ext)

        _jit_helpers["to_ext"] = to_ext

        @functools.partial(jax.jit, static_argnums=(3,))
        def h_from_acc(acc, vinv, inv_coset, omega_ext):
            h = F.mont_mul(fctx, acc, vinv)
            return F.mont_mul(fctx, NTT.intt(h, omega_ext), inv_coset)

        _jit_helpers["h_from_acc"] = h_from_acc
    return _jit_helpers


class _DeviceCtx:
    """all_expressions context over device-resident [m, 16] Montgomery
    tensors, dispatching through the jitted primitives."""

    def __init__(self, cols, m: int, last_row: int, mont_scalar):
        self._h = _helpers()
        self._cols = cols
        self._m = m
        self._last_row = last_row
        self._mont = mont_scalar      # int -> [16] mont device scalar
        self._rot_cache: dict = {}
        self.l0 = cols[("_l0",)]
        self.llast = cols[("_llast",)]
        self.lblind = cols[("_lblind",)]
        self.x_col = cols[("_xcol",)]

    def var(self, key, rot):
        import jax.numpy as jnp

        arr = self._cols[key]
        if rot == 0:
            return arr
        hit = self._rot_cache.get((key, rot))
        if hit is None:
            r = self._last_row if rot == ROT_LAST else rot
            # extended-coset index shift: omega == omega_ext^EXTENSION
            hit = jnp.roll(arr, -4 * r, axis=0)
            self._rot_cache[(key, rot)] = hit
        return hit

    def mul(self, a, b):
        return self._h["mul"](a, b)

    def add(self, a, b):
        return self._h["add"](a, b)

    def sub(self, a, b):
        return self._h["sub"](a, b)

    def scale(self, a, s):
        return self._h["mul_s"](a, self._mont(s))

    def add_const(self, a, s):
        return self._h["add_s"](a, self._mont(s))

    def const(self, s):
        import jax.numpy as jnp

        return jnp.broadcast_to(self._mont(s), (self._m, 16))


def compute_quotient(cfg: CircuitConfig, dom: Domain, fetch_coeffs,
                     beta: int, gamma: int, y: int) -> np.ndarray:
    """Device quotient: returns h coefficients as [4n, 4] u64 standard form
    (drop-in for the host path's extended_to_coeff output).

    fetch_coeffs(key) -> [<=n, 4] u64 coefficient-form poly for every column
    key the expression tree reads."""
    import jax.numpy as jnp

    from ..ops import limbs as L16
    from . import backend as B

    h = _helpers()
    to_mont16 = h["to_mont"]
    mont_of = lambda ints: to_mont16(
        jnp.asarray(L16.u64limbs_to_u16limbs(B.to_arr(ints))))

    _scalar_cache: dict = {}

    def mont_scalar(s):
        v = int(s) % R
        if v not in _scalar_cache:
            if len(_scalar_cache) > 4096:
                _scalar_cache.clear()
            _scalar_cache[v] = mont_of([v])[0]
        return _scalar_cache[v]

    # per-(cfg, domain) static device inputs: synthetic rows, coset scaling
    # vectors, x column, vanishing inverse — built once, reused every proof
    n, m = dom.n, dom.n_ext
    ck = (cfg, dom.k)
    st = _static_cache.get(ck)
    if st is None:
        def row_of(idx_vals):
            vals = [0] * n
            for i in idx_vals:
                vals[i] = 1
            return dom.lagrange_to_coeff(B.to_arr(vals))

        st = {
            "coset_pow": mont_of([pow(COSET_GEN, i, R) for i in range(m)]),
            "inv_coset": mont_of(
                [pow(pow(COSET_GEN, -1, R), i, R) for i in range(m)]),
            "xcol": mont_of([COSET_GEN * pow(dom.omega_ext, i, R) % R
                             for i in range(m)]),
            "vinv": to_mont16(jnp.asarray(L16.u64limbs_to_u16limbs(
                dom.vanishing_inv_on_extended()))),
            "l0": row_of([0]),
            "llast": row_of([cfg.last_row]),
            "lblind": row_of(range(cfg.usable_rows + 1, n)),
        }
        if len(_static_cache) > 4:
            _static_cache.clear()
        _static_cache[ck] = st

    def ext_of_coeffs(arr_u64):
        padded = np.zeros((m, 4), dtype=np.uint64)
        padded[:arr_u64.shape[0]] = arr_u64
        return h["to_ext"](
            to_mont16(jnp.asarray(L16.u64limbs_to_u16limbs(padded))),
            st["coset_pow"], dom.omega_ext)

    # lazily materialize only the columns the tree actually reads
    cols: dict = {
        ("_l0",): ext_of_coeffs(st["l0"]),
        ("_llast",): ext_of_coeffs(st["llast"]),
        ("_lblind",): ext_of_coeffs(st["lblind"]),
        ("_xcol",): st["xcol"],
    }

    class LazyCols(dict):
        def __missing__(self, key):
            arr = ext_of_coeffs(fetch_coeffs(key))
            self[key] = arr
            return arr

    ctx = _DeviceCtx(LazyCols(cols), m, cfg.last_row, mont_scalar)
    y_m = mont_scalar(y)
    acc = None
    for e in all_expressions(cfg, ctx, beta, gamma):
        acc = e if acc is None else h["fold"](acc, y_m, e)
    if acc is None:
        raise ValueError("config yields no constraint expressions — "
                         "nothing to fold into a quotient")
    out = h["h_from_acc"](acc, st["vinv"], st["inv_coset"], dom.omega_ext)
    std = h["from_mont"](out)
    return L16.u16limbs_to_u64limbs(np.asarray(std))

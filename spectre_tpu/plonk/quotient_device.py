"""Device-resident quotient evaluation.

The CPU prover evaluates `all_expressions` through the native batch backend
(~6k sequential host calls over 32MB numpy arrays at k=18 — the dominant
prove phase, 1067s of the 512-committee prove). TPU-first shape: every
column is coset-NTT'd to the extended domain ON DEVICE and stays resident as
a [4n, 16] Montgomery tensor; the expression tree, the y-fold, the vanishing
division, and the inverse coset NTT all run as device ops with no host
round-trips between them.

ISSUE 4: the per-column `to_ext` dispatch is now a BATCHED FUSED prefetch —
the expression tree's column keys are enumerated up front
(`expressions.referenced_keys`), stacked in fixed-size chunks, and extended
through ONE compiled kernel per chunk (`ops/ntt.py:coset_lde_std`: the
std→mont conversion and the coset pre-scale fold into stage 0 of the
batched NTT, honoring SPECTRE_NTT_MODE). The inverse path folds the 1/n
iNTT scale, the g^{-i} coset unscale and the mont→std boundary into one
table multiply (`coset_intt_std`).

Design note (learned the hard way): tracing the WHOLE tree into one jitted
XLA program blows up LLVM codegen on the CPU backend (`Cannot allocate
memory` from the execution engine at ~6k fused scan-heavy ops). The ops are
therefore dispatched EAGERLY through a small set of jitted primitives
(mont mul/add/sub, batched NTT) — data residency, not mega-fusion, is where
the device win lives (each op is HBM-bandwidth-bound either way), and
compile cost stays bounded per primitive shape.

Parity: the device path produces EXACTLY the host path's u64 coefficient
arrays, compared in-situ during real proves
(tests/test_plonk.py::TestDeviceQuotient, gate+lookup and wide-SHA shapes).
"""

from __future__ import annotations

import os

import numpy as np

from ..fields import bn254
from .constraint_system import CircuitConfig
from .domain import COSET_GEN, Domain
from .expressions import all_expressions, referenced_keys
from .keygen import ROT_LAST

R = bn254.R

_jit_helpers: dict = {}
_static_cache: dict = {}

# runner registry (trace-cache hygiene contract, parallel/plan.py):
# analysis/trace_lint cross-checks these (builder, cache) pairs against
# the AST (TC-UNCACHED-RUNNER).
TRACE_RUNNER_CACHES = (("_helpers", "_jit_helpers"),)


def _fused_vinv() -> bool:
    """SPECTRE_QUOTIENT_FUSED_VINV=0 keeps the explicit [4n, 16] vanishing-
    inverse mont_mul pass (the pre-fusion path, byte-identical — kept as the
    oracle for tests/test_ntt_kernels.py). Default: fold it into stage 0 of
    the inverse coset NTT, one fewer full-width elementwise pass per proof."""
    return os.environ.get("SPECTRE_QUOTIENT_FUSED_VINV", "1") != "0"


def _helpers():
    """Jitted primitive ops, created once (stable trace cache)."""
    if not _jit_helpers:
        import jax

        from ..ops import field_ops as F

        fctx = F.fr_ctx()
        _jit_helpers["to_mont"] = jax.jit(lambda v: F.to_mont(fctx, v))
        _jit_helpers["from_mont"] = jax.jit(lambda v: F.from_mont(fctx, v))
        _jit_helpers["mul"] = jax.jit(lambda a, b: F.mont_mul(fctx, a, b))
        _jit_helpers["add"] = jax.jit(lambda a, b: F.add(fctx, a, b))
        _jit_helpers["sub"] = jax.jit(lambda a, b: F.sub(fctx, a, b))
        _jit_helpers["mul_s"] = jax.jit(
            lambda a, s: F.mont_mul(fctx, a, s[None, :]))
        _jit_helpers["add_s"] = jax.jit(
            lambda a, s: F.add(fctx, a, s[None, :].repeat(a.shape[0], 0)))
        _jit_helpers["fold"] = jax.jit(
            lambda acc, y, e: F.add(fctx, F.mont_mul(fctx, acc, y[None, :]), e))
    return _jit_helpers


# columns per batched coset-LDE prefetch chunk: fixed so the [B, 4n, 16]
# kernel compiles once per domain, capped by transient bytes (chunk * 4n *
# 16 u32 lanes) so huge extended domains don't spike device memory
def _ext_chunk(m: int) -> int:
    cap = max(1, (256 << 20) // (m * 16 * 4))
    return min(8, 1 << (cap.bit_length() - 1))


class _DeviceCtx:
    """all_expressions context over device-resident [m, 16] Montgomery
    tensors, dispatching through the jitted primitives."""

    def __init__(self, cols, m: int, last_row: int, mont_scalar):
        self._h = _helpers()
        self._cols = cols
        self._m = m
        self._last_row = last_row
        self._mont = mont_scalar      # int -> [16] mont device scalar
        self._rot_cache: dict = {}
        self.l0 = cols[("_l0",)]
        self.llast = cols[("_llast",)]
        self.lblind = cols[("_lblind",)]
        self.x_col = cols[("_xcol",)]

    def var(self, key, rot):
        import jax.numpy as jnp

        arr = self._cols[key]
        if rot == 0:
            return arr
        hit = self._rot_cache.get((key, rot))
        if hit is None:
            r = self._last_row if rot == ROT_LAST else rot
            # extended-coset index shift: omega == omega_ext^EXTENSION
            hit = jnp.roll(arr, -4 * r, axis=0)
            self._rot_cache[(key, rot)] = hit
        return hit

    def mul(self, a, b):
        return self._h["mul"](a, b)

    def add(self, a, b):
        return self._h["add"](a, b)

    def sub(self, a, b):
        return self._h["sub"](a, b)

    def scale(self, a, s):
        return self._h["mul_s"](a, self._mont(s))

    def add_const(self, a, s):
        return self._h["add_s"](a, self._mont(s))

    def const(self, s):
        import jax.numpy as jnp

        return jnp.broadcast_to(self._mont(s), (self._m, 16))


def compute_quotient(cfg: CircuitConfig, dom: Domain, fetch_coeffs,
                     beta: int, gamma: int, y: int) -> np.ndarray:
    """Device quotient: returns h coefficients as [4n, 4] u64 standard form
    (drop-in for the host path's extended_to_coeff output).

    fetch_coeffs(key) -> [<=n, 4] u64 coefficient-form poly for every column
    key the expression tree reads."""
    import jax.numpy as jnp

    from ..ops import limbs as L16
    from . import backend as B

    h = _helpers()
    to_mont16 = h["to_mont"]
    mont_of = lambda ints: to_mont16(
        jnp.asarray(L16.u64limbs_to_u16limbs(B.to_arr(ints))))

    _scalar_cache: dict = {}

    def mont_scalar(s):
        v = int(s) % R
        if v not in _scalar_cache:
            if len(_scalar_cache) > 4096:
                _scalar_cache.clear()
            _scalar_cache[v] = mont_of([v])[0]
        return _scalar_cache[v]

    from ..ops import ntt as NTT

    # per-(cfg, domain) static device inputs: synthetic rows, x column —
    # built once, reused every proof (the coset scale / unscale tables now
    # live inside ops/ntt.py's budgeted table LRU as part of the fused
    # kernels, and the vanishing inverse rides the fused inverse path as a
    # stage-0 table; the explicit [4n, 16] tensor materializes lazily only
    # when SPECTRE_QUOTIENT_FUSED_VINV=0)
    n, m = dom.n, dom.n_ext
    ck = (cfg, dom.k)
    st = _static_cache.get(ck)
    if st is None:
        def row_of(idx_vals):
            vals = [0] * n
            for i in idx_vals:
                vals[i] = 1
            return dom.lagrange_to_coeff(B.to_arr(vals))

        st = {
            "xcol": mont_of([COSET_GEN * pow(dom.omega_ext, i, R) % R
                             for i in range(m)]),
            "l0": row_of([0]),
            "llast": row_of([cfg.last_row]),
            "lblind": row_of(range(cfg.usable_rows + 1, n)),
        }
        if len(_static_cache) > 4:
            _static_cache.clear()
        _static_cache[ck] = st

    def ext_of_many(arrs_u64):
        """Batched fused coset-LDE of a coefficient-array list: ONE
        compiled [B, 4n, 16] kernel (std→mont + g^i scale fused into
        stage 0; SPECTRE_NTT_MODE selects radix2/fourstep)."""
        b = len(arrs_u64)
        stack = np.zeros((b, m, 4), dtype=np.uint64)
        for i, cf in enumerate(arrs_u64):
            stack[i, :cf.shape[0]] = cf
        std16 = L16.u64limbs_to_u16limbs(stack.reshape(-1, 4)).reshape(
            b, m, 16)
        out = NTT.coset_lde_std(jnp.asarray(std16), dom.omega_ext,
                                COSET_GEN)
        return [out[i] for i in range(b)]

    def ext_of_coeffs(arr_u64):
        return ext_of_many([arr_u64])[0]

    # synthetic rows extend as one batched call; real columns prefetch in
    # fixed-size chunks enumerated from the expression tree
    l0_e, llast_e, lblind_e = ext_of_many(
        [st["l0"], st["llast"], st["lblind"]])
    cols: dict = {
        ("_l0",): l0_e,
        ("_llast",): llast_e,
        ("_lblind",): lblind_e,
        ("_xcol",): st["xcol"],
    }
    plan = [k for k in referenced_keys(cfg) if k not in cols]
    chunk_sz = _ext_chunk(m)
    for base in range(0, len(plan), chunk_sz):
        chunk = plan[base:base + chunk_sz]
        # pad the tail chunk with the first key so the kernel sees one
        # batch shape per domain (duplicates are free — same NTT, sliced)
        padded = chunk + [chunk[0]] * (chunk_sz - len(chunk))
        outs = ext_of_many([fetch_coeffs(k) for k in padded])
        for k_, o in zip(chunk, outs):
            cols[k_] = o

    class LazyCols(dict):
        # safety net: any key the recorder missed still materializes
        def __missing__(self, key):
            arr = ext_of_coeffs(fetch_coeffs(key))
            self[key] = arr
            return arr

    ctx = _DeviceCtx(LazyCols(cols), m, cfg.last_row, mont_scalar)
    y_m = mont_scalar(y)
    acc = None
    for e in all_expressions(cfg, ctx, beta, gamma):
        acc = e if acc is None else h["fold"](acc, y_m, e)
    if acc is None:
        raise ValueError("config yields no constraint expressions — "
                         "nothing to fold into a quotient")
    # h = acc / Z_H on the coset, then the fused inverse path: ONE kernel —
    # the 1/Z_H stage-0 pre-scale, the iNTT, and the combined
    # g^{-i}·n^{-1}·(mont→std) output table all ride a single transform
    if _fused_vinv():
        std = NTT.coset_intt_std_vinv(acc, dom.omega_ext, COSET_GEN,
                                      dom.vanishing_inv_period_vals())
    else:
        vinv = st.get("vinv")
        if vinv is None:
            vinv = st["vinv"] = to_mont16(jnp.asarray(
                L16.u64limbs_to_u16limbs(dom.vanishing_inv_on_extended())))
        hacc = h["mul"](acc, vinv)
        std = NTT.coset_intt_std(hacc, dom.omega_ext, COSET_GEN)
    return L16.u16limbs_to_u64limbs(np.asarray(std))

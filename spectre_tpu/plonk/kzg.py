"""KZG commitments + BDFG20 (SHPLONK) multiopen.

Reference parity: halo2's KZGCommitmentScheme + snark-verifier's SHPLONK
multi-open (SURVEY.md §2b N4). Prover-side design is TPU-shaped: every
quotient ((p - r)/Z_S, L/(X - u)) is computed POINTWISE on the evaluation
domain (the divisor never vanishes on the domain because the open points are
random), so the whole multiopen is elementwise ops + one iNTT + one MSM per
witness commitment — no sequential synthetic division anywhere.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..fields import bn254
from . import backend as B
from .domain import Domain
from .srs import SRS

R = bn254.R


def commit(srs: SRS, coeffs: np.ndarray, bk=None):
    """Commit to coefficient-form poly: MSM over tau powers. The SRS digest
    rides along as the fixed-base table key (SPECTRE_MSM_MODE=fixed reuses
    one precomputed window table per SRS across every commitment)."""
    bk = bk or B.get_backend()
    assert coeffs.shape[0] <= srs.n, "poly larger than SRS"
    return bk.msm(srs.g1_powers, coeffs, base_key=srs.digest())


def commit_many(srs: SRS, coeffs_list: list, bk=None) -> list:
    """Commit to several coefficient-form polys in one backend call
    (device base cached + batch axis shardable — SURVEY §2c(b))."""
    bk = bk or B.get_backend()
    for c in coeffs_list:
        assert c.shape[0] <= srs.n, "poly larger than SRS"
    return bk.msm_many(srs.g1_powers, coeffs_list, base_key=srs.digest())


def commit_lagrange(srs: SRS, domain: Domain, evals: np.ndarray, bk=None):
    """Commit to lagrange-form poly (iNTT then power-basis MSM)."""
    bk = bk or B.get_backend()
    return commit(srs, domain.lagrange_to_coeff(evals, bk), bk)


@dataclass
class OpenEntry:
    """One committed polynomial opened at a set of points."""

    coeffs: np.ndarray          # [n, 4] coefficient form (prover side)
    commitment: object          # affine point (verifier side)
    points: tuple               # the query points (ints)
    evals: tuple                # claimed evaluations at those points


def _interp(points, evals) -> list[int]:
    """Lagrange interpolation -> coefficient list (degree < len(points))."""
    m = len(points)
    coeffs = [0] * m
    for j in range(m):
        # basis poly prod_{k!=j} (X - x_k) / (x_j - x_k)
        denom = 1
        basis = [1]
        for k2 in range(m):
            if k2 == j:
                continue
            denom = denom * ((points[j] - points[k2]) % R) % R
            # basis *= (X - x_k)
            nb = [0] * (len(basis) + 1)
            for d, c in enumerate(basis):
                nb[d + 1] = (nb[d + 1] + c) % R
                nb[d] = (nb[d] - c * points[k2]) % R
            basis = nb
        scale = evals[j] * pow(denom, -1, R) % R
        for d, c in enumerate(basis):
            coeffs[d] = (coeffs[d] + c * scale) % R
    return coeffs


def _z_eval(points, x: int) -> int:
    out = 1
    for s in points:
        out = out * ((x - s) % R) % R
    return out


def _domain_linear_factors(domain: Domain, points, bk) -> np.ndarray:
    """[n,4] evals of Z_S(omega^i) = prod (omega^i - s)."""
    omegas = bk.powers(domain.omega, domain.n)
    acc = None
    for s in points:
        term = bk.sub(omegas, B.to_arr([s] * domain.n))
        acc = term if acc is None else bk.mul(acc, term)
    return acc


def _eval_small_poly_on_domain(domain: Domain, coeffs: list[int], bk) -> np.ndarray:
    """Evaluate a degree<=3 poly on the whole domain, vectorized."""
    omegas = bk.powers(domain.omega, domain.n)
    acc = B.to_arr([coeffs[-1]] * domain.n)
    for c in reversed(coeffs[:-1]):
        acc = bk.add(bk.mul(acc, omegas), B.to_arr([c] * domain.n))
    return acc


def shplonk_open(srs: SRS, domain: Domain, entries: list[OpenEntry], transcript, bk=None):
    """Prover: BDFG20 two-commitment multiopen. Evals must already be absorbed
    into the transcript by the caller; this writes W1, W2."""
    bk = bk or B.get_backend()
    v = transcript.challenge()

    # group by point set (identical sets share one Z_S)
    n = domain.n
    all_points = []
    for e in entries:
        for p in e.points:
            if p not in all_points:
                all_points.append(p)

    h_evals = B.zeros(n)
    vk = 1
    lagrange_cache = {}
    zinv_cache = {}
    for e in entries:
        key = e.points
        if key not in zinv_cache:
            zinv_cache[key] = bk.inv(_domain_linear_factors(domain, e.points, bk))
        if e.coeffs.shape[0] < n:
            padded = np.zeros((n, 4), dtype=np.uint64)
            padded[:e.coeffs.shape[0]] = e.coeffs
        else:
            padded = e.coeffs
        p_evals = domain.coeff_to_lagrange(padded, bk)
        r_coeffs = _interp(e.points, e.evals)
        r_evals = _eval_small_poly_on_domain(domain, r_coeffs, bk)
        term = bk.mul(bk.sub(p_evals, r_evals), zinv_cache[key])
        h_evals = bk.add(h_evals, bk.scale(term, vk))
        lagrange_cache[id(e)] = (p_evals, r_coeffs)
        vk = vk * v % R

    h_coeffs = domain.lagrange_to_coeff(h_evals, bk)
    w1 = commit(srs, h_coeffs, bk)
    transcript.write_point(w1)
    u = transcript.challenge()

    # L(X) = sum v^k Z_{T \ S_k}(u) (p_k(X) - r_k(u)) - Z_T(u) h(X)
    l_evals = B.zeros(n)
    vk = 1
    for e in entries:
        p_evals, r_coeffs = lagrange_cache[id(e)]
        z_rest = _z_eval([p for p in all_points if p not in e.points], u)
        r_u = 0
        for c in reversed(r_coeffs):
            r_u = (r_u * u + c) % R
        term = bk.sub(p_evals, B.to_arr([r_u] * n))
        l_evals = bk.add(l_evals, bk.scale(term, vk * z_rest % R))
        vk = vk * v % R
    z_t_u = _z_eval(all_points, u)
    l_evals = bk.sub(l_evals, bk.scale(domain.coeff_to_lagrange(
        _pad(h_coeffs, n), bk), z_t_u))

    # W2 = commit(L / (X - u)) via pointwise division on the domain
    omegas = bk.powers(domain.omega, n)
    denom_inv = bk.inv(bk.sub(omegas, B.to_arr([u] * n)))
    w2_evals = bk.mul(l_evals, denom_inv)
    w2 = commit(srs, domain.lagrange_to_coeff(w2_evals, bk), bk)
    transcript.write_point(w2)


def _pad(coeffs, n):
    if coeffs.shape[0] >= n:
        return coeffs
    out = np.zeros((n, 4), dtype=np.uint64)
    out[:coeffs.shape[0]] = coeffs
    return out


def shplonk_accumulate(srs: SRS, entries: list[OpenEntry], transcript):
    """Verifier scalar/MSM work WITHOUT the pairing: returns the deferred
    check (lhs, rhs) with e(lhs, [1]_2) == e(rhs... — concretely the pair
    (w2, f_acc + u*w2) satisfying e(f_acc + u*w2, [1]_2) == e(w2, [tau]_2).
    One definition serves shplonk_verify AND the aggregation layer's native
    accumulator oracle (`plonk/in_circuit.py`)."""
    g1 = bn254.g1_curve
    v = transcript.challenge()
    w1 = transcript.read_point()
    u = transcript.challenge()
    w2 = transcript.read_point()

    all_points = []
    for e in entries:
        for p in e.points:
            if p not in all_points:
                all_points.append(p)

    # F = sum v^k Z_rest(u) C_k  -  [sum v^k Z_rest(u) r_k(u)] G  -  Z_T(u) W1
    f_acc = None
    e_scalar = 0
    vk = 1
    for e in entries:
        z_rest = _z_eval([p for p in all_points if p not in e.points], u)
        r_coeffs = _interp(e.points, e.evals)
        r_u = 0
        for c in reversed(r_coeffs):
            r_u = (r_u * u + c) % R
        w = vk * z_rest % R
        f_acc = g1.add(f_acc, g1.mul(e.commitment, w))
        e_scalar = (e_scalar + w * r_u) % R
        vk = vk * v % R
    z_t_u = _z_eval(all_points, u)
    f_acc = g1.add(f_acc, g1.neg(g1.mul(bn254.G1_GEN, e_scalar)))
    f_acc = g1.add(f_acc, g1.neg(g1.mul(w1, z_t_u)))

    # deferred: e(F + u W2, [1]_2) == e(W2, [tau]_2)
    return w2, g1.add(f_acc, g1.mul(w2, u))


def shplonk_verify(srs: SRS, entries: list[OpenEntry], transcript) -> bool:
    """Verifier: reads W1, W2; one pairing check."""
    tau_side, one_side = shplonk_accumulate(srs, entries, transcript)
    return bn254.pairing_check([
        (one_side, srs.g2_gen),
        (bn254.g1_curve.neg(tau_side), srs.g2_tau),
    ])

"""Fiat–Shamir transcripts: Blake2b (native proofs) and Keccak256 (EVM path).

Reference parity: halo2's Blake2bWrite/Blake2bRead and snark-verifier's
Keccak transcript for EVM verification (SURVEY.md §2b N8). The framing here is
spectre_tpu's own (domain-separated absorb/squeeze with a counter); both sides
of this framework use it consistently. Byte-level parity with the reference
fork is impossible to validate offline and is NOT claimed.

Proof stream format: every absorbed object is appended verbatim; the verifier
re-absorbs as it reads, so challenges are recomputed identically.
"""

from __future__ import annotations

import hashlib

from ..fields import bn254

R = bn254.R


def _keccak_f1600(state: list[int]) -> list[int]:
    """Keccak-f[1600] permutation on 25 lanes of 64 bits."""
    RC = [0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
          0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
          0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
          0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
          0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
          0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
          0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
          0x8000000000008080, 0x0000000080000001, 0x8000000080008008]
    ROT = [[0, 36, 3, 41, 18], [1, 44, 10, 45, 2], [62, 6, 43, 15, 61],
           [28, 55, 25, 21, 56], [27, 20, 39, 8, 14]]
    M = (1 << 64) - 1

    def rol(v, s):
        return ((v << s) | (v >> (64 - s))) & M

    a = state
    for rnd in range(24):
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ rol(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[x + 5 * y] ^ d[x] for y in range(5) for x in range(5)]
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = rol(a[x + 5 * y], ROT[x][y])
        a = [b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y] & M) & b[(x + 2) % 5 + 5 * y])
             for y in range(5) for x in range(5)]
        a[0] ^= RC[rnd]
    return a


def keccak256(data: bytes) -> bytes:
    """Keccak-256 (pre-NIST padding 0x01), as used by Ethereum."""
    rate = 136
    state = [0] * 25
    msg = bytearray(data)
    msg.append(0x01)
    while len(msg) % rate:
        msg.append(0)
    msg[-1] |= 0x80
    for off in range(0, len(msg), rate):
        block = msg[off:off + rate]
        for i in range(rate // 8):
            state[i] ^= int.from_bytes(block[8 * i:8 * i + 8], "little")
        state = _keccak_f1600(state)
    return b"".join(state[i].to_bytes(8, "little") for i in range(4))


class _TranscriptBase:
    """Absorb/squeeze transcript + proof stream reader/writer."""

    def __init__(self, proof: bytes | None = None):
        self._state = self._init_state()
        self._proof = bytearray() if proof is None else None
        self._read_buf = proof
        self._read_pos = 0
        self._counter = 0

    # -- hashing machinery (subclass provides) --
    def _init_state(self):
        raise NotImplementedError

    def _absorb_bytes(self, b: bytes):
        raise NotImplementedError

    def _squeeze_bytes(self) -> bytes:
        raise NotImplementedError

    # -- absorb (write side also appends to the proof stream) --
    def common_point(self, pt):
        self._absorb_bytes(b"P" + bn254.g1_to_bytes(pt))

    def common_scalar(self, v: int):
        self._absorb_bytes(b"S" + (int(v) % R).to_bytes(32, "big"))

    def write_point(self, pt):
        self.common_point(pt)
        self._proof += bn254.g1_to_bytes(pt)

    def write_scalar(self, v: int):
        self.common_scalar(v)
        self._proof += (int(v) % R).to_bytes(32, "big")

    def read_point(self):
        b = self._take(64)
        pt = bn254.g1_from_bytes(b)
        self.common_point(pt)
        return pt

    def read_scalar(self) -> int:
        # explicit raises (not asserts): the parse path handles untrusted
        # proof bytes and must reject under `python -O` too
        v = int.from_bytes(self._take(32), "big")
        if v >= R:
            raise ValueError("non-canonical scalar in proof")
        self.common_scalar(v)
        return v

    def _take(self, n: int) -> bytes:
        if self._read_buf is None:
            raise ValueError("read on a write transcript")
        if self._read_pos + n > len(self._read_buf):
            raise ValueError("proof too short")
        out = self._read_buf[self._read_pos:self._read_pos + n]
        self._read_pos += n
        return out

    def finalize(self) -> bytes:
        assert self._proof is not None
        return bytes(self._proof)

    def assert_consumed(self):
        if self._read_buf is None or self._read_pos != len(self._read_buf):
            raise ValueError("proof has trailing bytes")

    # -- squeeze --
    def challenge(self) -> int:
        self._counter += 1
        self._absorb_bytes(b"C" + self._counter.to_bytes(4, "big"))
        return int.from_bytes(self._squeeze_bytes(), "big") % R


class Blake2bTranscript(_TranscriptBase):
    def _init_state(self):
        return hashlib.blake2b(b"spectre-tpu-transcript-v1", digest_size=64)

    def _absorb_bytes(self, b: bytes):
        self._state.update(b)

    def _squeeze_bytes(self) -> bytes:
        return self._state.copy().digest()


ACC_LIMB_BITS = 88
ACC_LIMBS = 3  # per coordinate; snark-verifier LimbsEncoding<3, 88>


def point_to_transcript_elements(pt) -> list[int]:
    """G1 point -> 6 field elements (3 x 88-bit limbs per coordinate), the
    SAME encoding the aggregation circuit witnesses — so the in-circuit
    transcript absorbs exactly the cells the MSM operates on."""
    out = []
    for coord in (int(pt[0]), int(pt[1])):
        for i in range(ACC_LIMBS):
            out.append((coord >> (ACC_LIMB_BITS * i)) & ((1 << ACC_LIMB_BITS) - 1))
    return out


class PoseidonTranscript(_TranscriptBase):
    """Algebraic Fiat–Shamir over Fr: a Poseidon duplex sponge (same
    T/RATE/R_F/R_P parameters as the committee commitment, `ops/poseidon.py`).

    Reference parity: snark-verifier's `PoseidonTranscript<NativeLoader>` —
    the transcript used for snarks destined for in-circuit aggregation, where
    challenge derivation must be cheap to re-derive as constraints (one
    permutation per RATE absorbed elements, vs thousands of cells per byte
    for Blake2b/Keccak). The proof byte stream is identical to the other
    transcripts; only challenge derivation differs.

    Mirrored cell-for-cell by `builder.transcript_chip.TranscriptChip`.

    Sponge shape: T=3/RATE=2 (pse-poseidon's transcript shape, R_P=57 for
    x^5 over BN254 Fr) — an order of magnitude cheaper in-circuit than the
    T=12 committee sponge at transcript-sized absorb counts.
    """

    T = 3
    RATE = 2
    R_F = 8
    R_P = 57

    def _init_state(self):
        from ..ops import poseidon as _pos
        self._pos = _pos
        self._pending: list[int] = []
        return [0] * self.T

    # -- algebraic absorbs ------------------------------------------------
    def _absorb_bytes(self, b: bytes):
        # only used for the vk digest: split into 16-byte BE chunks (< R)
        for off in range(0, len(b), 16):
            self._pending.append(int.from_bytes(b[off:off + 16], "big"))

    def common_point(self, pt):
        self._pending.extend(point_to_transcript_elements(pt))

    def common_scalar(self, v: int):
        self._pending.append(int(v) % R)

    # write_point/write_scalar inherited: base methods dispatch to the
    # common_* overrides above and handle the (shared) proof byte framing

    # -- squeeze ----------------------------------------------------------
    def challenge(self) -> int:
        self._counter += 1
        self._pending.append(self._counter)
        state = self._state
        pend = self._pending
        for off in range(0, len(pend), self.RATE):
            chunk = pend[off:off + self.RATE]
            state = ([state[0]]
                     + [(state[1 + i] + v) % R for i, v in enumerate(chunk)]
                     + state[1 + len(chunk):])
            state = self._pos.permute_native(state, t=self.T, r_f=self.R_F,
                                             r_p=self.R_P)
        self._pending = []
        self._state = state
        return state[1]


class KeccakTranscript(_TranscriptBase):
    """Keccak-backed transcript for the EVM verification path: the state is a
    rolling hash h = keccak(h || absorbed)."""

    def _init_state(self):
        return keccak256(b"spectre-tpu-transcript-v1")

    def _absorb_bytes(self, b: bytes):
        self._buffer = getattr(self, "_buffer", b"") + b

    def _squeeze_bytes(self) -> bytes:
        self._state = keccak256(self._state + getattr(self, "_buffer", b""))
        self._buffer = b""
        return self._state + keccak256(self._state)  # 64 bytes for uniformity

    @property
    def state_bytes(self):
        return self._state

"""KZG structured reference string (powers-of-tau), with file cache.

Reference parity: halo2-base `gen_srs` / PARAMS_DIR caching
(`util/circuit.rs` + SURVEY.md §5 checkpoint/resume). Production use consumes
a ceremony transcript; tests generate an INSECURE deterministic setup from a
seed (tau derived and then discarded — fine for testing, never for deployment).
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from ..fields import bn254
from ..native import host

R = bn254.R

PARAMS_DIR = os.environ.get("PARAMS_DIR", os.path.join(os.path.dirname(__file__), "..", "..", "params"))


class SRS:
    """g1_powers: [n, 8] u64 affine standard limbs (tau^i G); g2 elements."""

    def __init__(self, k: int, g1_powers: np.ndarray, g2_gen, g2_tau):
        self.k = k
        self.n = 1 << k
        self.g1_powers = g1_powers
        self.g2_gen = g2_gen
        self.g2_tau = g2_tau
        self._digest = None

    def digest(self) -> str:
        """Stable content digest of the G1 base (hex). Keys the fixed-base
        MSM table cache (ops.msm) across processes and re-encodings — two
        SRS objects loaded from the same ceremony share tables. Computed
        once (blake2b over the full power table: ~0.1 s at k=20)."""
        if self._digest is None:
            h = hashlib.blake2b(digest_size=16)
            h.update(b"SPTSRS02")
            h.update(self.k.to_bytes(4, "little"))
            h.update(np.ascontiguousarray(self.g1_powers.astype("<u8")).tobytes())
            self._digest = h.hexdigest()
        return self._digest

    @classmethod
    def unsafe_setup(cls, k: int, seed: bytes = b"spectre-tpu-test-srs") -> "SRS":
        """tau depends on the seed ONLY (not k): different-k setups from one
        seed share tau, so a small SRS is a prefix of a large one — the
        ceremony-transcript property the aggregation layer requires (the
        deferred pairing of an inner proof at k1 is checked by the outer
        layer against the SAME [tau]_2; reference: per-k params files
        truncated from one perpetual-powers-of-tau ceremony)."""
        tau = int.from_bytes(hashlib.sha256(seed).digest() * 2, "big") % R
        n = 1 << k
        g1p = host.g1_scalar_powers((int(bn.G1_GEN[0]), int(bn.G1_GEN[1])), tau, n) \
            if (bn := bn254) else None
        g2_tau = bn254.g2_curve.mul(bn254.G2_GEN, tau)
        return cls(k, g1p, bn254.G2_GEN, g2_tau)

    @classmethod
    def load_or_setup(cls, k: int, directory: str | None = None) -> "SRS":
        from ..utils import faults
        faults.check("srs.load")    # injection site (resilience tests)
        directory = directory or PARAMS_DIR
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"kzg_bn254_{k}.srs")
        if os.path.exists(path):
            return cls.read(path)
        # derive from a larger cached SRS when available (prefix property)
        for bigger in range(k + 1, 27):
            bp = os.path.join(directory, f"kzg_bn254_{bigger}.srs")
            if os.path.exists(bp):
                big = cls.read(bp)
                srs = cls(k, big.g1_powers[:1 << k].copy(), big.g2_gen, big.g2_tau)
                srs.write(path)
                return srs
        srs = cls.unsafe_setup(k)
        srs.write(path)
        return srs

    def truncate(self, k: int) -> "SRS":
        assert k <= self.k
        return SRS(k, self.g1_powers[:1 << k], self.g2_gen, self.g2_tau)

    # -- serialization: header || g1 limbs || g2 points (uncompressed BE) --
    def write(self, path: str):
        with open(path, "wb") as f:
            f.write(b"SPTSRS02")
            f.write(self.k.to_bytes(4, "little"))
            f.write(self.g1_powers.astype("<u8").tobytes())
            f.write(bn254.g2_to_bytes(self.g2_gen))
            f.write(bn254.g2_to_bytes(self.g2_tau))
        # integrity sidecar (ISSUE 6): <path>.sha256 lets `read` detect a
        # bit-flipped params file as a typed ArtifactCorrupt at load time
        # instead of a deep keygen/prove blow-up hours later
        from ..utils import artifacts
        artifacts.write_sidecar(path)

    @classmethod
    def read(cls, path: str, verify: bool = True) -> "SRS":
        from ..utils import artifacts
        with open(path, "rb") as f:
            raw = f.read()
        if verify:
            # a MISSING sidecar stays loadable (pre-checksum params dirs);
            # a mismatching one refuses with a typed ArtifactCorrupt
            artifacts.verify_sidecar(path, raw)
        assert raw[:8] == b"SPTSRS02", \
            "bad/stale SRS file (tau derivation changed in SPTSRS02; delete the params dir)"
        k = int.from_bytes(raw[8:12], "little")
        n = 1 << k
        off = 12
        g1 = np.frombuffer(raw[off:off + n * 8 * 8],
                           dtype="<u8").reshape(n, 8).copy()
        off += n * 8 * 8
        g2_gen = bn254.g2_from_bytes(raw[off:off + 128])
        g2_tau = bn254.g2_from_bytes(raw[off + 128:off + 256])
        return cls(k, g1, g2_gen, g2_tau)

"""Test fixtures: deterministic witness generation + loaders.

Reference parity: the `test-utils` crate (spec-test loader,
`test-utils/src/lib.rs:87-131`) and the `unit_test_gen` fixture generator
(`preprocessor/src/unit_test_gen.rs:21-314` — builds `sync_step_512.json` /
`rotation_512.json` from deterministic keys). Here fixtures are generated
from the same default witness builders the circuits use, so any environment
can rebuild them bit-for-bit (seeded, no chain snapshot needed).
"""

from __future__ import annotations

import json
import os

from .witness import default_committee_update_args, default_sync_step_args
from .witness.types import BeaconBlockHeader, CommitteeUpdateArgs, SyncStepArgs


def _hdr_json(h: BeaconBlockHeader) -> dict:
    return {
        "slot": h.slot,
        "proposer_index": h.proposer_index,
        "parent_root": "0x" + h.parent_root.hex(),
        "state_root": "0x" + h.state_root.hex(),
        "body_root": "0x" + h.body_root.hex(),
    }


def _hdr_from(d: dict) -> BeaconBlockHeader:
    return BeaconBlockHeader(
        slot=int(d["slot"]), proposer_index=int(d["proposer_index"]),
        parent_root=bytes.fromhex(d["parent_root"][2:]),
        state_root=bytes.fromhex(d["state_root"][2:]),
        body_root=bytes.fromhex(d["body_root"][2:]))


def dump_step_fixture(args: SyncStepArgs, path: str):
    data = {
        "signature_compressed": "0x" + args.signature_compressed.hex(),
        "pubkeys_uncompressed": [[hex(x), hex(y)] for x, y in args.pubkeys_uncompressed],
        "participation_bits": args.participation_bits,
        "attested_header": _hdr_json(args.attested_header),
        "finalized_header": _hdr_json(args.finalized_header),
        "finality_branch": ["0x" + b.hex() for b in args.finality_branch],
        "execution_payload_root": "0x" + args.execution_payload_root.hex(),
        "execution_payload_branch": ["0x" + b.hex() for b in args.execution_payload_branch],
        "domain": "0x" + args.domain.hex(),
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(data, f, indent=1)


def load_step_fixture(path: str) -> SyncStepArgs:
    with open(path) as f:
        d = json.load(f)
    return SyncStepArgs(
        signature_compressed=bytes.fromhex(d["signature_compressed"][2:]),
        pubkeys_uncompressed=[(int(x, 16), int(y, 16))
                              for x, y in d["pubkeys_uncompressed"]],
        participation_bits=[int(b) for b in d["participation_bits"]],
        attested_header=_hdr_from(d["attested_header"]),
        finalized_header=_hdr_from(d["finalized_header"]),
        finality_branch=[bytes.fromhex(b[2:]) for b in d["finality_branch"]],
        execution_payload_root=bytes.fromhex(d["execution_payload_root"][2:]),
        execution_payload_branch=[bytes.fromhex(b[2:])
                                  for b in d["execution_payload_branch"]],
        domain=bytes.fromhex(d["domain"][2:]))


def load_reference_step_fixture(path: str) -> SyncStepArgs:
    """Load a fixture in the upstream layout (`test_data/sync_step_512.json`,
    produced by `preprocessor/src/unit_test_gen.rs`): byte-array lists for
    signature/branches/domain, hex-string header fields, 96-byte uncompressed
    pubkeys. Used as a blst interop oracle (the signatures were produced by
    the C blst library against the real eth2 ciphersuite)."""
    with open(path) as f:
        d = json.load(f)

    hdr = _hdr_from  # upstream header JSON uses the same hex-field layout
    pks = []
    for raw in d["pubkeys_uncompressed"]:
        b = bytes(raw)
        pks.append((int.from_bytes(b[:48], "big"), int.from_bytes(b[48:], "big")))
    return SyncStepArgs(
        signature_compressed=bytes(d["signature_compressed"]),
        pubkeys_uncompressed=pks,
        participation_bits=[int(bool(b)) for b in d["pariticipation_bits"]],
        attested_header=hdr(d["attested_header"]),
        finalized_header=hdr(d["finalized_header"]),
        finality_branch=[bytes(b) for b in d["finality_branch"]],
        execution_payload_root=bytes(d["execution_payload_root"]),
        execution_payload_branch=[bytes(b) for b in d["execution_payload_branch"]],
        domain=bytes(d["domain"]))


REFERENCE_STEP_FIXTURE = os.environ.get(
    "SPECTRE_REFERENCE_STEP_FIXTURE",
    "/root/reference/test_data/sync_step_512.json")


def dump_rotation_fixture(args: CommitteeUpdateArgs, path: str):
    data = {
        "pubkeys_compressed": ["0x" + pk.hex() for pk in args.pubkeys_compressed],
        "finalized_header": _hdr_json(args.finalized_header),
        "sync_committee_branch": ["0x" + b.hex() for b in args.sync_committee_branch],
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(data, f, indent=1)


def load_rotation_fixture(path: str) -> CommitteeUpdateArgs:
    with open(path) as f:
        d = json.load(f)
    return CommitteeUpdateArgs(
        pubkeys_compressed=[bytes.fromhex(pk[2:]) for pk in d["pubkeys_compressed"]],
        finalized_header=_hdr_from(d["finalized_header"]),
        sync_committee_branch=[bytes.fromhex(b[2:])
                               for b in d["sync_committee_branch"]])


def generate_fixtures(spec, directory: str = "test_data", seed: int = 42):
    """Rebuild the deterministic fixture set (reference: `just gen-fixtures`
    analog of `unit_test_gen.rs`)."""
    n = spec.sync_committee_size
    step = default_sync_step_args(spec, seed=seed)
    rot = default_committee_update_args(spec, seed=seed)
    dump_step_fixture(step, os.path.join(directory, f"sync_step_{n}.json"))
    dump_rotation_fixture(rot, os.path.join(directory, f"rotation_{n}.json"))
    return step, rot


# ---------------------------------------------------------------------------
# consensus-spec-test loader (directory layout of ethereum/consensus-specs
# light_client/sync pyspec tests; fixtures must be downloaded separately —
# no network egress in this environment)
# ---------------------------------------------------------------------------

def read_spec_test_steps(test_dir: str):
    """Parse `steps.yaml` of a light_client/sync pyspec test into a list of
    (kind, payload) tuples (reference `test-utils/src/lib.rs:87-131` +
    `test_types.rs`). The full fixture pipeline (ssz_snappy containers ->
    circuit witnesses) lives in `preprocessor.spec_tests`; this wrapper is
    kept for step-sequence consumers."""
    from .preprocessor.spec_tests import read_steps

    out = []
    for step in read_steps(test_dir):
        if "process_update" in step:
            out.append(("process_update", step["process_update"]))
        elif "force_update" in step:
            out.append(("force_update", step["force_update"]))
    return out


def mesh_prove_fixture(k: int = 13):
    """Deterministic circuit + assignment for the MESH-PROVE byte-equality
    check: a complete prove must run on a multi-device mesh (sharded MSM +
    sharded NTT riding the TpuBackend gates) and produce bytes IDENTICAL to
    the single-device/host prove under the same seeded blinding
    (SURVEY §2c(a); exercised by __graft_entry__.dryrun_multichip phase 4
    and tests/test_parallel.py). Returns (srs, pk, assignment).

    Shapes here are the contract: the dryrun and the RUN_SLOW test must use
    THE SAME k so the persistent compile cache is shared."""
    from .builder.context import Context
    from .builder.gate import GateChip
    from .builder.range_chip import RangeChip
    from .plonk import backend as B
    from .plonk.keygen import keygen
    from .plonk.srs import SRS

    ctx = Context()
    gate = GateChip()
    rng = RangeChip(8, gate)
    acc = ctx.load_zero()
    for i in range(1500):
        v = ctx.load_witness((i * 7 + 3) % 251)
        rng.range_check(ctx, v, 8)
        acc = gate.add(ctx, acc, v)
    ctx.expose_public(acc)
    cfg = ctx.auto_config(k=k, lookup_bits=8)
    asg = ctx.assignment(cfg)
    srs = SRS.load_or_setup(k)
    pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies,
                B.CpuBackend())
    return srs, pk, asg


def seeded_blinding_rng(seed: int = 12345):
    """Deterministic stand-in for the ZK blinding source: makes a proof a
    pure function of (pk, witness, transcript) so backend/mesh byte-equality
    is checkable. NEVER use in production proving."""
    state = [seed]

    def rng():
        state[0] += 1
        return (state[0] * 0x9E3779B97F4A7C15) % (2**61 - 1)

    return rng

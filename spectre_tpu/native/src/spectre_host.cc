// spectre_host: C++ host-side math for spectre_tpu.
//
// Role (SURVEY.md §2b): the native component of the stack — the CPU reference
// implementation of BN254 field arithmetic (N1), Pippenger MSM (N2) and NTT
// (N3) that (a) serves as the measured CPU baseline for bench.py and (b) is
// the exact oracle the JAX/Pallas device kernels are tested against. Where the
// reference uses Rust (`halo2curves-axiom`, halo2's rayon Pippenger/FFT), this
// is an independent C++ implementation: 4x64-bit limbs, CIOS Montgomery
// multiplication, jacobian coordinates.
//
// Exported ABI is C (ctypes-friendly): field elements are 4 little-endian
// uint64 limbs in standard (non-Montgomery) form at the boundary; points are
// affine (x, y) limb pairs, infinity flagged separately.

#include <array>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

using u64 = uint64_t;
using u128 = unsigned __int128;

namespace {

struct Fp {
  u64 v[4];
};

struct FpCtx {
  u64 mod[4];
  u64 n0inv;  // -mod^{-1} mod 2^64
  Fp r2;      // R^2 mod p, R = 2^256
  Fp one;     // R mod p (Montgomery 1)
};

// BN254 base field (G1 coordinates)
constexpr u64 FQ_MOD[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                           0xb85045b68181585dULL, 0x30644e72e131a029ULL};
// BN254 scalar field (NTT / witness scalars)
constexpr u64 FR_MOD[4] = {0x43e1f593f0000001ULL, 0x2833e84879b97091ULL,
                           0xb85045b68181585dULL, 0x30644e72e131a029ULL};

FpCtx g_fq, g_fr;

inline bool ge(const u64* a, const u64* b) {
  for (int i = 3; i >= 0; --i) {
    if (a[i] != b[i]) return a[i] > b[i];
  }
  return true;
}

inline void sub_nocheck(u64* out, const u64* a, const u64* b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a[i] - b[i] - (u64)borrow;
    out[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

inline void cond_sub_mod(u64* t, const FpCtx& C) {
  if (ge(t, C.mod)) sub_nocheck(t, t, C.mod);
}

inline void fp_add(Fp& out, const Fp& a, const Fp& b, const FpCtx& C) {
  u128 carry = 0;
  u64 t[5];
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + (u64)carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  t[4] = (u64)carry;
  if (t[4] || ge(t, C.mod)) sub_nocheck(t, t, C.mod);
  std::memcpy(out.v, t, 32);
}

inline void fp_sub(Fp& out, const Fp& a, const Fp& b, const FpCtx& C) {
  u128 borrow = 0;
  u64 t[4];
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - (u64)borrow;
    t[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)t[i] + C.mod[i] + (u64)carry;
      t[i] = (u64)s;
      carry = s >> 64;
    }
  }
  std::memcpy(out.v, t, 32);
}

// CIOS Montgomery multiplication (Acar): out = a*b*R^{-1} mod p
inline void fp_mul(Fp& out, const Fp& a, const Fp& b, const FpCtx& C) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u64 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[j] * b.v[i] + t[j] + carry;
      t[j] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    u128 cur = (u128)t[4] + carry;
    t[4] = (u64)cur;
    t[5] = (u64)(cur >> 64);

    u64 m = t[0] * C.n0inv;
    cur = (u128)t[0] + (u128)m * C.mod[0];
    carry = (u64)(cur >> 64);
    for (int j = 1; j < 4; ++j) {
      cur = (u128)t[j] + (u128)m * C.mod[j] + carry;
      t[j - 1] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    cur = (u128)t[4] + carry;
    t[3] = (u64)cur;
    t[4] = t[5] + (u64)(cur >> 64);
  }
  cond_sub_mod(t, C);
  std::memcpy(out.v, t, 32);
}

inline void fp_sqr(Fp& out, const Fp& a, const FpCtx& C) { fp_mul(out, a, a, C); }

inline bool fp_is_zero(const Fp& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline bool fp_eq(const Fp& a, const Fp& b) {
  return std::memcmp(a.v, b.v, 32) == 0;
}

inline void to_mont(Fp& out, const Fp& a, const FpCtx& C) { fp_mul(out, a, C.r2, C); }
inline void from_mont(Fp& out, const Fp& a, const FpCtx& C) {
  Fp one = {{1, 0, 0, 0}};
  fp_mul(out, a, one, C);
}

// out = a^e (Montgomery in/out), e standard 4-limb little-endian
void fp_pow(Fp& out, const Fp& a, const u64* e, const FpCtx& C) {
  Fp result = C.one;
  Fp base = a;
  for (int limb = 0; limb < 4; ++limb) {
    u64 bits = e[limb];
    for (int i = 0; i < 64; ++i) {
      if (bits & 1) fp_mul(result, result, base, C);
      fp_sqr(base, base, C);
      bits >>= 1;
    }
  }
  out = result;
}

void fp_inv(Fp& out, const Fp& a, const FpCtx& C) {
  u64 e[4];
  std::memcpy(e, C.mod, 32);
  e[0] -= 2;  // p is odd, no borrow
  fp_pow(out, a, e, C);
}

void ctx_init(FpCtx& C, const u64* mod) {
  std::memcpy(C.mod, mod, 32);
  // n0inv = -mod^{-1} mod 2^64 via Newton iteration
  u64 inv = 1;
  for (int i = 0; i < 63; ++i) inv *= 2 - mod[0] * inv;
  C.n0inv = ~inv + 1;
  // R mod p by long division-free doubling: start at 1, double 256 times
  Fp r = {{1, 0, 0, 0}};
  for (int i = 0; i < 256; ++i) fp_add(r, r, r, C);  // fp_add reduces mod p
  C.one = r;
  // R^2 mod p: double one 256 more times
  Fp r2 = r;
  for (int i = 0; i < 256; ++i) fp_add(r2, r2, r2, C);
  C.r2 = r2;
}

// ---------------------------------------------------------------------------
// G1 jacobian arithmetic over Fq (a = 0, b = 3); Z == 0 means infinity.
// ---------------------------------------------------------------------------

struct G1 {
  Fp x, y, z;  // Montgomery form
};

inline void g1_set_inf(G1& p) { std::memset(&p, 0, sizeof(G1)); }
inline bool g1_is_inf(const G1& p) { return fp_is_zero(p.z); }

// dbl-2009-l
void g1_dbl(G1& out, const G1& p) {
  if (g1_is_inf(p)) {
    out = p;
    return;
  }
  const FpCtx& C = g_fq;
  Fp A, B, Cc, D, E, F, t0, t1;
  fp_sqr(A, p.x, C);
  fp_sqr(B, p.y, C);
  fp_sqr(Cc, B, C);
  fp_add(t0, p.x, B, C);
  fp_sqr(t0, t0, C);
  fp_sub(t0, t0, A, C);
  fp_sub(t0, t0, Cc, C);
  fp_add(D, t0, t0, C);
  fp_add(E, A, A, C);
  fp_add(E, E, A, C);
  fp_sqr(F, E, C);
  G1 r;
  fp_add(t0, D, D, C);
  fp_sub(r.x, F, t0, C);
  fp_sub(t0, D, r.x, C);
  fp_mul(t0, E, t0, C);
  fp_add(t1, Cc, Cc, C);
  fp_add(t1, t1, t1, C);
  fp_add(t1, t1, t1, C);
  fp_sub(r.y, t0, t1, C);
  fp_mul(r.z, p.y, p.z, C);
  fp_add(r.z, r.z, r.z, C);
  out = r;
}

// add-2007-bl (general jacobian add)
void g1_add(G1& out, const G1& p, const G1& q) {
  if (g1_is_inf(p)) {
    out = q;
    return;
  }
  if (g1_is_inf(q)) {
    out = p;
    return;
  }
  const FpCtx& C = g_fq;
  Fp z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t0, t1;
  fp_sqr(z1z1, p.z, C);
  fp_sqr(z2z2, q.z, C);
  fp_mul(u1, p.x, z2z2, C);
  fp_mul(u2, q.x, z1z1, C);
  fp_mul(t0, q.z, z2z2, C);
  fp_mul(s1, p.y, t0, C);
  fp_mul(t0, p.z, z1z1, C);
  fp_mul(s2, q.y, t0, C);
  fp_sub(h, u2, u1, C);
  fp_sub(rr, s2, s1, C);
  if (fp_is_zero(h)) {
    if (fp_is_zero(rr)) {
      g1_dbl(out, p);
      return;
    }
    g1_set_inf(out);
    return;
  }
  fp_add(rr, rr, rr, C);  // r = 2(S2-S1)
  fp_add(i, h, h, C);
  fp_sqr(i, i, C);  // I = (2H)^2
  fp_mul(j, h, i, C);
  fp_mul(v, u1, i, C);
  G1 r;
  fp_sqr(r.x, rr, C);
  fp_sub(r.x, r.x, j, C);
  fp_add(t0, v, v, C);
  fp_sub(r.x, r.x, t0, C);
  fp_sub(t0, v, r.x, C);
  fp_mul(t0, rr, t0, C);
  fp_mul(t1, s1, j, C);
  fp_add(t1, t1, t1, C);
  fp_sub(r.y, t0, t1, C);
  fp_add(t0, p.z, q.z, C);
  fp_sqr(t0, t0, C);
  fp_sub(t0, t0, z1z1, C);
  fp_sub(t0, t0, z2z2, C);
  fp_mul(r.z, t0, h, C);
  out = r;
}

// mixed add: q affine (Montgomery coords), q_inf flag; madd-2007-bl
void g1_madd(G1& out, const G1& p, const Fp& qx, const Fp& qy) {
  if (g1_is_inf(p)) {
    out.x = qx;
    out.y = qy;
    out.z = g_fq.one;
    return;
  }
  const FpCtx& C = g_fq;
  Fp z1z1, u2, s2, h, hh, i, j, rr, v, t0, t1;
  fp_sqr(z1z1, p.z, C);
  fp_mul(u2, qx, z1z1, C);
  fp_mul(t0, p.z, z1z1, C);
  fp_mul(s2, qy, t0, C);
  fp_sub(h, u2, p.x, C);
  fp_sub(rr, s2, p.y, C);
  if (fp_is_zero(h)) {
    if (fp_is_zero(rr)) {
      g1_dbl(out, p);
      return;
    }
    g1_set_inf(out);
    return;
  }
  fp_add(rr, rr, rr, C);  // r = 2(S2-Y1)
  fp_sqr(hh, h, C);
  fp_add(i, hh, hh, C);
  fp_add(i, i, i, C);  // I = 4 HH
  fp_mul(j, h, i, C);
  fp_mul(v, p.x, i, C);
  G1 r;
  fp_sqr(r.x, rr, C);
  fp_sub(r.x, r.x, j, C);
  fp_add(t0, v, v, C);
  fp_sub(r.x, r.x, t0, C);
  fp_sub(t0, v, r.x, C);
  fp_mul(t0, rr, t0, C);
  fp_mul(t1, p.y, j, C);
  fp_add(t1, t1, t1, C);
  fp_sub(r.y, t0, t1, C);
  fp_add(t0, p.z, h, C);
  fp_sqr(t0, t0, C);
  fp_sub(t0, t0, z1z1, C);
  fp_sub(r.z, t0, hh, C);
  out = r;
}

void g1_to_affine_inner(Fp& ox, Fp& oy, const G1& p) {
  const FpCtx& C = g_fq;
  Fp zinv, zinv2, zinv3;
  fp_inv(zinv, p.z, C);
  fp_sqr(zinv2, zinv, C);
  fp_mul(zinv3, zinv2, zinv, C);
  fp_mul(ox, p.x, zinv2, C);
  fp_mul(oy, p.y, zinv3, C);
}

}  // namespace

// ---------------------------------------------------------------------------
// exported C ABI
// ---------------------------------------------------------------------------

extern "C" {

void spectre_init() {
  static bool done = false;
  if (!done) {
    ctx_init(g_fq, FQ_MOD);
    ctx_init(g_fr, FR_MOD);
    done = true;
  }
}

// ---- batched field ops (standard form at the boundary); field: 0=Fq, 1=Fr ----

static const FpCtx& pick(int field) {
  spectre_init();
  return field ? g_fr : g_fq;
}

void fp_mul_batch(int field, const u64* a, const u64* b, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  for (size_t i = 0; i < n; ++i) {
    Fp am, bm, r;
    std::memcpy(am.v, a + 4 * i, 32);
    std::memcpy(bm.v, b + 4 * i, 32);
    to_mont(am, am, C);
    to_mont(bm, bm, C);
    fp_mul(r, am, bm, C);
    from_mont(r, r, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

void fp_add_batch(int field, const u64* a, const u64* b, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  for (size_t i = 0; i < n; ++i) {
    Fp am, bm, r;
    std::memcpy(am.v, a + 4 * i, 32);
    std::memcpy(bm.v, b + 4 * i, 32);
    fp_add(r, am, bm, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

void fp_sub_batch(int field, const u64* a, const u64* b, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  for (size_t i = 0; i < n; ++i) {
    Fp am, bm, r;
    std::memcpy(am.v, a + 4 * i, 32);
    std::memcpy(bm.v, b + 4 * i, 32);
    fp_sub(r, am, bm, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

void fp_inv_batch(int field, const u64* a, u64* out, size_t n) {
  // Montgomery batch-inversion trick: one fp_inv for the whole batch.
  const FpCtx& C = pick(field);
  std::vector<Fp> vals(n), prefix(n);
  Fp acc = C.one;
  for (size_t i = 0; i < n; ++i) {
    std::memcpy(vals[i].v, a + 4 * i, 32);
    to_mont(vals[i], vals[i], C);
    prefix[i] = acc;
    if (!fp_is_zero(vals[i])) fp_mul(acc, acc, vals[i], C);
  }
  Fp inv_acc;
  fp_inv(inv_acc, acc, C);
  for (size_t i = n; i-- > 0;) {
    Fp r;
    if (fp_is_zero(vals[i])) {
      std::memset(out + 4 * i, 0, 32);  // inv(0) := 0 convention
      continue;
    }
    fp_mul(r, inv_acc, prefix[i], C);
    fp_mul(inv_acc, inv_acc, vals[i], C);
    from_mont(r, r, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

void fp_pow_single(int field, const u64* a, const u64* e, u64* out) {
  const FpCtx& C = pick(field);
  Fp am, r;
  std::memcpy(am.v, a, 32);
  to_mont(am, am, C);
  fp_pow(r, am, e, C);
  from_mont(r, r, C);
  std::memcpy(out, r.v, 32);
}

// ---- NTT over Fr (in place, standard form at the boundary) ----
// omega must be a primitive 2^logn-th root of unity.

// Twiddle plan: all stage twiddles in Montgomery form, stage with half-width
// h occupying entries [h-1, 2h-1) — total n-1 entries. A prove runs ~90
// same-(omega, size) NTTs over the extended domain (one per committed
// column, `prover.py::_quotient_host`), so the table is built once (~n muls)
// and every butterfly thereafter costs ONE mul instead of two (the serial
// `w *= wm` chain per block is gone). Same arithmetic, bit-identical output.
struct NttPlan {
  std::vector<Fp> tw;
};

std::mutex g_ntt_plan_mu;
std::map<std::array<u64, 5>, std::shared_ptr<NttPlan>> g_ntt_plans;

std::shared_ptr<NttPlan> ntt_plan(size_t logn, const Fp& omega_mont,
                                  const FpCtx& C) {
  std::array<u64, 5> key{omega_mont.v[0], omega_mont.v[1], omega_mont.v[2],
                         omega_mont.v[3], (u64)logn};
  {
    std::lock_guard<std::mutex> g(g_ntt_plan_mu);
    auto it = g_ntt_plans.find(key);
    if (it != g_ntt_plans.end()) return it->second;
  }
  const size_t n = (size_t)1 << logn;
  auto plan = std::make_shared<NttPlan>();
  plan->tw.resize(n - 1);
  for (size_t m = 2; m <= n; m <<= 1) {
    const size_t h = m >> 1;
    Fp wm = omega_mont;
    for (size_t k = m; k < n; k <<= 1) fp_sqr(wm, wm, C);  // omega^(n/m)
    Fp w = C.one;
    Fp* row = plan->tw.data() + (h - 1);
    for (size_t j = 0; j < h; ++j) {
      row[j] = w;
      fp_mul(w, w, wm, C);
    }
  }
  std::lock_guard<std::mutex> g(g_ntt_plan_mu);
  // the prover uses 4 (omega, size) pairs per circuit degree (fwd/inv x
  // base/extended); bound the cache, but evict ONE entry — clear() would
  // wipe the hot set whenever a service rotates through 3+ degrees and
  // re-pay the plan build ~90x per prove
  if (g_ntt_plans.size() > 12) g_ntt_plans.erase(g_ntt_plans.begin());
  g_ntt_plans[key] = plan;
  return plan;
}

void fr_ntt(u64* data, size_t logn, const u64* omega_std) {
  spectre_init();
  const FpCtx& C = g_fr;
  const size_t n = (size_t)1 << logn;
  // load to Montgomery
  std::vector<Fp> a(n);
  for (size_t i = 0; i < n; ++i) {
    std::memcpy(a[i].v, data + 4 * i, 32);
    to_mont(a[i], a[i], C);
  }
  // bit-reverse permutation
  for (size_t i = 1, j = 0; i < n; ++i) {
    size_t bit = n >> 1;
    for (; j & bit; bit >>= 1) j ^= bit;
    j ^= bit;
    if (i < j) std::swap(a[i], a[j]);
  }
  Fp omega;
  std::memcpy(omega.v, omega_std, 32);
  to_mont(omega, omega, C);
  auto plan = ntt_plan(logn, omega, C);
  const Fp* tw = plan->tw.data();
  for (size_t m = 2; m <= n; m <<= 1) {
    const size_t h = m >> 1;
    const Fp* wrow = tw + (h - 1);
    for (size_t start = 0; start < n; start += m) {
      Fp* lo = a.data() + start;
      Fp* hi = lo + h;
      for (size_t j = 0; j < h; ++j) {
        Fp t, u;
        fp_mul(t, hi[j], wrow[j], C);
        u = lo[j];
        fp_add(lo[j], u, t, C);
        fp_sub(hi[j], u, t, C);
      }
    }
  }
  for (size_t i = 0; i < n; ++i) {
    Fp r;
    from_mont(r, a[i], C);
    std::memcpy(data + 4 * i, r.v, 32);
  }
}

// ---- Pippenger MSM over G1 ----
// points: n * 8 limbs (x,y affine standard form; (0,0) = infinity, skipped)
// scalars: n * 4 limbs standard form
// out: 8 limbs affine + is_inf flag

static inline unsigned window_of(const u64* s, unsigned w, unsigned c) {
  unsigned bit = w * c;
  unsigned limb = bit >> 6, off = bit & 63;
  u64 v = s[limb] >> off;
  if (off + c > 64 && limb + 1 < 4) v |= s[limb + 1] << (64 - off);
  return (unsigned)(v & (((u64)1 << c) - 1));
}

void g1_msm(const u64* points, const u64* scalars, size_t n, int nthreads,
            u64* out_xy, int* out_inf) {
  spectre_init();
  const FpCtx& C = g_fq;
  unsigned c = 13;
  if (n < (1u << 12)) c = 8;
  if (n < (1u << 6)) c = 4;
  const unsigned nwin = (254 + c - 1) / c;
  const size_t nbuckets = ((size_t)1 << c) - 1;

  // pre-convert points to Montgomery affine
  std::vector<Fp> px(n), py(n);
  std::vector<char> pinf(n);
  for (size_t i = 0; i < n; ++i) {
    Fp x, y;
    std::memcpy(x.v, points + 8 * i, 32);
    std::memcpy(y.v, points + 8 * i + 4, 32);
    pinf[i] = fp_is_zero(x) && fp_is_zero(y);
    to_mont(px[i], x, C);
    to_mont(py[i], y, C);
  }

  std::vector<G1> win_res(nwin);
  auto do_window = [&](unsigned w) {
    std::vector<G1> buckets(nbuckets);
    for (auto& b : buckets) g1_set_inf(b);
    for (size_t i = 0; i < n; ++i) {
      if (pinf[i]) continue;
      unsigned idx = window_of(scalars + 4 * i, w, c);
      if (idx) g1_madd(buckets[idx - 1], buckets[idx - 1], px[i], py[i]);
    }
    G1 sum, acc;
    g1_set_inf(sum);
    g1_set_inf(acc);
    for (size_t b = nbuckets; b-- > 0;) {
      g1_add(sum, sum, buckets[b]);
      g1_add(acc, acc, sum);
    }
    win_res[w] = acc;
  };

  if (nthreads > 1) {
    std::vector<std::thread> pool;
    for (int t = 0; t < nthreads; ++t) {
      pool.emplace_back([&, t]() {
        for (unsigned w = t; w < nwin; w += nthreads) do_window(w);
      });
    }
    for (auto& th : pool) th.join();
  } else {
    for (unsigned w = 0; w < nwin; ++w) do_window(w);
  }

  G1 res;
  g1_set_inf(res);
  for (unsigned w = nwin; w-- > 0;) {
    for (unsigned d = 0; d < c && !g1_is_inf(res); ++d) g1_dbl(res, res);
    g1_add(res, res, win_res[w]);
  }
  if (g1_is_inf(res)) {
    *out_inf = 1;
    std::memset(out_xy, 0, 64);
    return;
  }
  *out_inf = 0;
  Fp ax, ay;
  g1_to_affine_inner(ax, ay, res);
  from_mont(ax, ax, C);
  from_mont(ay, ay, C);
  std::memcpy(out_xy, ax.v, 32);
  std::memcpy(out_xy + 4, ay.v, 32);
}

// ---- batched G1 ops for testing device EC kernels ----

// out = a + b where a, b, out are affine standard-form; (0,0) = infinity
void g1_add_affine_batch(const u64* a, const u64* b, u64* out, size_t n) {
  spectre_init();
  const FpCtx& C = g_fq;
  for (size_t i = 0; i < n; ++i) {
    Fp ax, ay, bx, by;
    std::memcpy(ax.v, a + 8 * i, 32);
    std::memcpy(ay.v, a + 8 * i + 4, 32);
    std::memcpy(bx.v, b + 8 * i, 32);
    std::memcpy(by.v, b + 8 * i + 4, 32);
    bool ainf = fp_is_zero(ax) && fp_is_zero(ay);
    bool binf = fp_is_zero(bx) && fp_is_zero(by);
    G1 pa;
    if (ainf) {
      g1_set_inf(pa);
    } else {
      to_mont(pa.x, ax, C);
      to_mont(pa.y, ay, C);
      pa.z = C.one;
    }
    if (!binf) {
      Fp bxm, bym;
      to_mont(bxm, bx, C);
      to_mont(bym, by, C);
      g1_madd(pa, pa, bxm, bym);
    }
    if (g1_is_inf(pa)) {
      std::memset(out + 8 * i, 0, 64);
    } else {
      Fp ox, oy;
      g1_to_affine_inner(ox, oy, pa);
      from_mont(ox, ox, C);
      from_mont(oy, oy, C);
      std::memcpy(out + 8 * i, ox.v, 32);
      std::memcpy(out + 8 * i + 4, oy.v, 32);
    }
  }
}

// Horner evaluation: out = sum a[i] x^i (a standard form, length n)
void fp_horner(int field, const u64* a, const u64* x, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp xm, acc;
  std::memcpy(xm.v, x, 32);
  to_mont(xm, xm, C);
  std::memset(acc.v, 0, 32);
  for (size_t i = n; i-- > 0;) {
    Fp ai;
    std::memcpy(ai.v, a + 4 * i, 32);
    to_mont(ai, ai, C);
    fp_mul(acc, acc, xm, C);
    fp_add(acc, acc, ai, C);
  }
  from_mont(acc, acc, C);
  std::memcpy(out, acc.v, 32);
}

// sum of all elements
void fp_sum(int field, const u64* a, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp acc;
  std::memset(acc.v, 0, 32);
  for (size_t i = 0; i < n; ++i) {
    Fp ai;
    std::memcpy(ai.v, a + 4 * i, 32);
    fp_add(acc, acc, ai, C);
  }
  std::memcpy(out, acc.v, 32);
}

}  // extern "C"

extern "C" {

// SRS generation: out[i] = tau^i * G, affine standard form [n, 8] limbs.
// Sequential chain P_{i+1} = tau * P_i with jacobian double-and-add.
void g1_scalar_powers(const u64* g_xy, const u64* tau, size_t n, u64* out) {
  // P_i = tau^i * g via FIXED-BASE windowed multiplication: the scalars
  // tau^i are cheap field muls, and one shared table of g-multiples
  // (16 windows x 2^16 entries) turns every point into <= 16 additions —
  // the previous per-power double-and-add was O(256) EC ops per point,
  // which made 2^22+ SRS generation dominate setup wall-clock.
  spectre_init();
  const FpCtx& C = g_fq;
  const FpCtx& Cr = g_fr;
  Fp gx, gy;
  std::memcpy(gx.v, g_xy, 32);
  std::memcpy(gy.v, g_xy + 4, 32);
  G1 base;
  to_mont(base.x, gx, C);
  to_mont(base.y, gy, C);
  base.z = C.one;

  // Window width from n (W must divide 64 so digits never straddle limbs).
  // Total adds ~ (256/W) * (2^W + n): the pure-add break-evens are n=224
  // (4->8) and n=65024 (8->16), but W=16 also means a 16x65536-entry table
  // (~100 MB) and ~1M precompute adds before any output — on a small-RAM
  // host that spike only pays off for multi-million-point SRS sizes, so the
  // 8->16 switch is held back to n >= 2^20.
  const int W = n <= 224 ? 4 : n < (1u << 20) ? 8 : 16;
  const int NW = 256 / W;
  const size_t TSZ = (size_t)1 << W;
  // table[j][d] = (d << (W*j)) * g ; entry 0 = infinity
  std::vector<G1> table((size_t)NW * TSZ);
  G1 wbase = base;                    // g * 2^(W*j)
  for (int j = 0; j < NW; ++j) {
    G1* row = table.data() + (size_t)j * TSZ;
    g1_set_inf(row[0]);
    row[1] = wbase;
    for (size_t d = 2; d < TSZ; ++d) g1_add(row[d], row[d - 1], wbase);
    if (j + 1 < NW) {
      wbase = row[TSZ - 1];
      g1_add(wbase, wbase, row[1]);   // g * 2^(W*(j+1))
    }
  }

  // scalar powers tau^i in Montgomery Fr, emitted in standard form
  Fp tau_m;
  std::memcpy(tau_m.v, tau, 32);
  to_mont(tau_m, tau_m, Cr);
  Fp cur_s = Cr.one;                  // tau^0 (Montgomery)
  std::vector<G1> jac(n);
  for (size_t i = 0; i < n; ++i) {
    Fp s;
    from_mont(s, cur_s, Cr);          // standard-form scalar
    G1 acc;
    g1_set_inf(acc);
    for (int j = 0; j < NW; ++j) {
      u64 d = (s.v[(j * W) / 64] >> ((j * W) % 64)) & (TSZ - 1);
      if (d) g1_add(acc, acc, table[(size_t)j * TSZ + d]);
    }
    jac[i] = acc;
    fp_mul(cur_s, cur_s, tau_m, Cr);
  }
  // batch-normalize to affine: montgomery batch inversion of z, skipping
  // infinity points (z == 0 would otherwise poison the whole product)
  std::vector<Fp> zs(n), prefix(n);
  Fp accp = C.one;
  for (size_t i = 0; i < n; ++i) {
    zs[i] = jac[i].z;
    prefix[i] = accp;
    if (!fp_is_zero(zs[i])) fp_mul(accp, accp, zs[i], C);
  }
  Fp inv_acc;
  fp_inv(inv_acc, accp, C);
  for (size_t i = n; i-- > 0;) {
    if (fp_is_zero(zs[i])) {
      std::memset(out + 8 * i, 0, 64);  // infinity -> (0, 0)
      continue;
    }
    Fp zinv, zinv2, zinv3, ax, ay;
    fp_mul(zinv, inv_acc, prefix[i], C);
    fp_mul(inv_acc, inv_acc, zs[i], C);
    fp_sqr(zinv2, zinv, C);
    fp_mul(zinv3, zinv2, zinv, C);
    fp_mul(ax, jac[i].x, zinv2, C);
    fp_mul(ay, jac[i].y, zinv3, C);
    from_mont(ax, ax, C);
    from_mont(ay, ay, C);
    std::memcpy(out + 8 * i, ax.v, 32);
    std::memcpy(out + 8 * i + 4, ay.v, 32);
  }
}

// pointwise ops used by the prover's quotient evaluation (standard form)

// out[i] = a[i] + s mod p. Representation-agnostic (add needs no Montgomery),
// one pass — replaces building an n-row constant array host-side just to
// call fp_add_batch (the expression contexts' add_const was doing exactly
// that, ~2s of Python marshalling per call at the k=21 extended domain).
void fp_add_scalar_batch(int field, const u64* a, const u64* s /*4 limbs*/,
                         u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp sv;
  std::memcpy(sv.v, s, 32);
  for (size_t i = 0; i < n; ++i) {
    Fp am, r;
    std::memcpy(am.v, a + 4 * i, 32);
    fp_add(r, am, sv, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

// out[i] = a[i]*s + b[i] mod p: the quotient's y-combination
// (acc = acc*y + e) as ONE pass instead of scale-then-add two-pass.
void fp_axpy_batch(int field, const u64* a, const u64* s /*4 limbs*/,
                   const u64* b, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp sm;
  std::memcpy(sm.v, s, 32);
  to_mont(sm, sm, C);
  for (size_t i = 0; i < n; ++i) {
    Fp am, bm, r;
    std::memcpy(am.v, a + 4 * i, 32);
    std::memcpy(bm.v, b + 4 * i, 32);
    to_mont(am, am, C);
    fp_mul(r, am, sm, C);
    from_mont(r, r, C);
    fp_add(r, r, bm, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

void fp_scale_batch(int field, const u64* a, const u64* s /*4 limbs*/, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp sm;
  std::memcpy(sm.v, s, 32);
  to_mont(sm, sm, C);
  for (size_t i = 0; i < n; ++i) {
    Fp am, r;
    std::memcpy(am.v, a + 4 * i, 32);
    to_mont(am, am, C);
    fp_mul(r, am, sm, C);
    from_mont(r, r, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

// out[i] = x^i for i in [0, n)
void fp_powers(int field, const u64* x, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp xm, cur;
  std::memcpy(xm.v, x, 32);
  to_mont(xm, xm, C);
  cur = C.one;
  for (size_t i = 0; i < n; ++i) {
    Fp r;
    from_mont(r, cur, C);
    std::memcpy(out + 4 * i, r.v, 32);
    fp_mul(cur, cur, xm, C);
  }
}

// prefix products: out[i] = prod_{j<=i} a[j]
void fp_prefix_prod(int field, const u64* a, u64* out, size_t n) {
  const FpCtx& C = pick(field);
  Fp acc = C.one;
  for (size_t i = 0; i < n; ++i) {
    Fp am, r;
    std::memcpy(am.v, a + 4 * i, 32);
    to_mont(am, am, C);
    fp_mul(acc, acc, am, C);
    from_mont(r, acc, C);
    std::memcpy(out + 4 * i, r.v, 32);
  }
}

}  // extern "C"

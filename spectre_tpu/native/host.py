"""ctypes wrapper over libspectre_host.so with numpy limb interop.

Boundary convention (matches spectre_host.cc): field elements are 4 little-
endian uint64 limbs, standard (non-Montgomery) form; affine points are 8 limbs
(x||y) with (0,0) = infinity.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libspectre_host.so")

FQ = 0
FR = 1


def _build_if_needed() -> bool:
    src = os.path.join(_DIR, "src", "spectre_host.cc")
    if os.path.exists(_SO):
        if not os.path.exists(src) or os.path.getmtime(_SO) >= os.path.getmtime(src):
            return True  # prebuilt .so without sources is fine
    try:
        subprocess.run(["make", "-C", _DIR], check=True, capture_output=True)
        return True
    except Exception:
        return False


class HostLib:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            if not _build_if_needed():
                raise RuntimeError("libspectre_host.so missing and build failed")
            lib = ctypes.CDLL(_SO)
            u64p = ctypes.POINTER(ctypes.c_uint64)
            lib.spectre_init.restype = None
            for name in ("fp_mul_batch", "fp_add_batch", "fp_sub_batch"):
                fn = getattr(lib, name)
                fn.argtypes = [ctypes.c_int, u64p, u64p, u64p, ctypes.c_size_t]
                fn.restype = None
            lib.fp_inv_batch.argtypes = [ctypes.c_int, u64p, u64p, ctypes.c_size_t]
            lib.fp_inv_batch.restype = None
            lib.fp_pow_single.argtypes = [ctypes.c_int, u64p, u64p, u64p]
            lib.fp_pow_single.restype = None
            lib.fr_ntt.argtypes = [u64p, ctypes.c_size_t, u64p]
            lib.fr_ntt.restype = None
            lib.g1_msm.argtypes = [u64p, u64p, ctypes.c_size_t, ctypes.c_int,
                                   u64p, ctypes.POINTER(ctypes.c_int)]
            lib.g1_msm.restype = None
            lib.g1_add_affine_batch.argtypes = [u64p, u64p, u64p, ctypes.c_size_t]
            lib.g1_add_affine_batch.restype = None
            lib.g1_scalar_powers.argtypes = [u64p, u64p, ctypes.c_size_t, u64p]
            lib.g1_scalar_powers.restype = None
            lib.fp_horner.argtypes = [ctypes.c_int, u64p, u64p, u64p, ctypes.c_size_t]
            lib.fp_horner.restype = None
            lib.fp_sum.argtypes = [ctypes.c_int, u64p, u64p, ctypes.c_size_t]
            lib.fp_sum.restype = None
            lib.fp_scale_batch.argtypes = [ctypes.c_int, u64p, u64p, u64p, ctypes.c_size_t]
            lib.fp_scale_batch.restype = None
            lib.fp_add_scalar_batch.argtypes = [ctypes.c_int, u64p, u64p, u64p, ctypes.c_size_t]
            lib.fp_add_scalar_batch.restype = None
            lib.fp_axpy_batch.argtypes = [ctypes.c_int, u64p, u64p, u64p, u64p, ctypes.c_size_t]
            lib.fp_axpy_batch.restype = None
            lib.fp_powers.argtypes = [ctypes.c_int, u64p, u64p, ctypes.c_size_t]
            lib.fp_powers.restype = None
            lib.fp_prefix_prod.argtypes = [ctypes.c_int, u64p, u64p, ctypes.c_size_t]
            lib.fp_prefix_prod.restype = None
            lib.spectre_init()
            inst = super().__new__(cls)
            inst.lib = lib
            cls._instance = inst
        return cls._instance


def available() -> bool:
    try:
        HostLib()
        return True
    except Exception:  # missing sources, corrupt .so, failed build, ...
        return False


def _u64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


# ---------------------------------------------------------------------------
# int <-> limb conversion
# ---------------------------------------------------------------------------

def ints_to_limbs(vals, nlimbs: int = 4) -> np.ndarray:
    """list[int] -> [n, nlimbs] uint64 little-endian limb array (bulk bytes
    round-trip: int.to_bytes is C-speed, the per-limb shift loop was not)."""
    nbytes = 8 * nlimbs
    buf = b"".join(int(v).to_bytes(nbytes, "little") for v in vals)
    return np.frombuffer(buf, dtype="<u8").reshape(len(vals), nlimbs).astype(
        np.uint64, copy=True)


def limbs_to_ints(arr: np.ndarray) -> list:
    arr = np.ascontiguousarray(arr, dtype=np.uint64)
    n, nl = arr.shape
    buf = arr.astype("<u8", copy=False).tobytes()
    w = 8 * nl
    return [int.from_bytes(buf[i * w:(i + 1) * w], "little") for i in range(n)]


def points_to_limbs(points) -> np.ndarray:
    """list of affine (x, y) field-elem tuples or None -> [n, 8] uint64."""
    flat = []
    for pt in points:
        if pt is None:
            flat.extend([0, 0])
        else:
            flat.extend([int(pt[0]), int(pt[1])])
    xs = ints_to_limbs(flat)
    return xs.reshape(len(points), 8)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def _binop(name: str, field: int, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    b = np.ascontiguousarray(b, dtype=np.uint64)
    assert a.shape == b.shape and a.shape[1] == 4
    out = np.empty_like(a)
    getattr(lib, name)(field, _u64p(a), _u64p(b), _u64p(out), a.shape[0])
    return out


def fp_mul_batch(field: int, a, b):
    return _binop("fp_mul_batch", field, a, b)


def fp_add_batch(field: int, a, b):
    return _binop("fp_add_batch", field, a, b)


def fp_sub_batch(field: int, a, b):
    return _binop("fp_sub_batch", field, a, b)


def fp_inv_batch(field: int, a) -> np.ndarray:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    assert a.ndim == 2 and a.shape[1] == 4
    out = np.empty_like(a)
    lib.fp_inv_batch(field, _u64p(a), _u64p(out), a.shape[0])
    return out


def fr_ntt(data: np.ndarray, omega: int) -> np.ndarray:
    """NTT of a C-contiguous uint64 [n, 4] limb array (n a power of 2).

    Transforms in place and returns the SAME array. Rejects inputs that would
    silently be copied (non-contiguous / wrong dtype), since the caller would
    otherwise keep an untransformed buffer."""
    lib = HostLib().lib
    assert isinstance(data, np.ndarray) and data.dtype == np.uint64 \
        and data.flags["C_CONTIGUOUS"], "fr_ntt requires a C-contiguous uint64 array"
    assert data.ndim == 2 and data.shape[1] == 4
    n = data.shape[0]
    logn = n.bit_length() - 1
    assert 1 << logn == n
    om = ints_to_limbs([omega])
    lib.fr_ntt(_u64p(data), logn, _u64p(om))
    return data


def g1_msm(points: np.ndarray, scalars: np.ndarray, nthreads: int = 1):
    """points [n,8], scalars [n,4] -> affine (x:int, y:int) or None."""
    lib = HostLib().lib
    points = np.ascontiguousarray(points, dtype=np.uint64)
    scalars = np.ascontiguousarray(scalars, dtype=np.uint64)
    n = points.shape[0]
    assert scalars.shape == (n, 4) and points.shape == (n, 8)
    out = np.zeros(8, dtype=np.uint64)
    inf = ctypes.c_int(0)
    lib.g1_msm(_u64p(points), _u64p(scalars), n, nthreads, _u64p(out),
               ctypes.byref(inf))
    if inf.value:
        return None
    x = sum(int(out[j]) << (64 * j) for j in range(4))
    y = sum(int(out[4 + j]) << (64 * j) for j in range(4))
    return (x, y)


def g1_add_affine_batch(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    b = np.ascontiguousarray(b, dtype=np.uint64)
    assert a.shape == b.shape and a.shape[1] == 8
    out = np.empty_like(a)
    lib.g1_add_affine_batch(_u64p(a), _u64p(b), _u64p(out), a.shape[0])
    return out


def g1_scalar_powers(g, tau: int, n: int) -> np.ndarray:
    """[n, 8] limbs: tau^i * g for i in [0, n). g = affine (x, y) ints."""
    lib = HostLib().lib
    gl = ints_to_limbs([int(g[0]), int(g[1])]).reshape(8)
    tl = ints_to_limbs([tau]).reshape(4)
    out = np.zeros((n, 8), dtype=np.uint64)
    lib.g1_scalar_powers(_u64p(gl), _u64p(tl), n, _u64p(out))
    return out


def fp_scale_batch(field: int, a: np.ndarray, s: int) -> np.ndarray:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    assert a.ndim == 2 and a.shape[1] == 4
    sl = ints_to_limbs([s]).reshape(4)
    out = np.empty_like(a)
    lib.fp_scale_batch(field, _u64p(a), _u64p(sl), _u64p(out), a.shape[0])
    return out


def fp_add_scalar_batch(field: int, a: np.ndarray, s: int) -> np.ndarray:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    assert a.ndim == 2 and a.shape[1] == 4
    sl = ints_to_limbs([s]).reshape(4)
    out = np.empty_like(a)
    lib.fp_add_scalar_batch(field, _u64p(a), _u64p(sl), _u64p(out), a.shape[0])
    return out


def fp_axpy_batch(field: int, a: np.ndarray, s: int, b: np.ndarray) -> np.ndarray:
    """out = a*s + b elementwise (one pass)."""
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    b = np.ascontiguousarray(b, dtype=np.uint64)
    assert a.shape == b.shape and a.ndim == 2 and a.shape[1] == 4
    sl = ints_to_limbs([s]).reshape(4)
    out = np.empty_like(a)
    lib.fp_axpy_batch(field, _u64p(a), _u64p(sl), _u64p(b), _u64p(out), a.shape[0])
    return out


def fp_powers(field: int, x: int, n: int) -> np.ndarray:
    lib = HostLib().lib
    xl = ints_to_limbs([x]).reshape(4)
    out = np.zeros((n, 4), dtype=np.uint64)
    lib.fp_powers(field, _u64p(xl), _u64p(out), n)
    return out


def fp_prefix_prod(field: int, a: np.ndarray) -> np.ndarray:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    assert a.ndim == 2 and a.shape[1] == 4
    out = np.empty_like(a)
    lib.fp_prefix_prod(field, _u64p(a), _u64p(out), a.shape[0])
    return out


def fp_horner(field: int, a: np.ndarray, x: int) -> int:
    """Evaluate sum a[i] x^i (coefficients little-index-first)."""
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    assert a.ndim == 2 and a.shape[1] == 4
    xl = ints_to_limbs([x]).reshape(4)
    out = np.zeros(4, dtype=np.uint64)
    lib.fp_horner(field, _u64p(a), _u64p(xl), _u64p(out), a.shape[0])
    return sum(int(out[j]) << (64 * j) for j in range(4))


def fp_sum(field: int, a: np.ndarray) -> int:
    lib = HostLib().lib
    a = np.ascontiguousarray(a, dtype=np.uint64)
    assert a.ndim == 2 and a.shape[1] == 4
    out = np.zeros(4, dtype=np.uint64)
    lib.fp_sum(field, _u64p(a), _u64p(out), a.shape[0])
    return sum(int(out[j]) << (64 * j) for j in range(4))

"""ctypes bindings for the C++ host library (libspectre_host.so).

Build with `make -C spectre_tpu/native`. These are the CPU-baseline / oracle
entry points: batched BN254 field ops, Fr NTT, Pippenger G1 MSM.
"""

from .host import (  # noqa: F401
    HostLib,
    available,
    fp_add_batch,
    fp_inv_batch,
    fp_mul_batch,
    fp_sub_batch,
    fr_ntt,
    g1_add_affine_batch,
    g1_msm,
    limbs_to_ints,
    ints_to_limbs,
)
